"""Jitted leaf-wise tree growth.

The TPU re-design of SerialTreeLearner::Train (src/treelearner/
serial_tree_learner.cpp:169-233): the whole best-first growth loop runs as a
single compiled `lax.while_loop` on device — no host↔device ping-pong per
split.  Differences from the reference dictated by XLA:

- the row partition is a `row→leaf` label vector relabelled in place, not a
  reordered index array (DataPartition, data_partition.hpp:17-222);
- per-leaf histograms live in a fixed `[max_leaves, F, B, 3]` cache instead
  of the LRU HistogramPool (feature_histogram.hpp:646-818) — the smaller
  child is histogrammed by a masked pass, the sibling by subtraction
  (serial_tree_learner.cpp:506-591's smaller/larger choreography);
- per-leaf best splits are cached as stacked SplitResult arrays, so each
  iteration is argmax → split → 1 histogram pass → 2 split scans.

Tree node layout matches the reference Tree (include/LightGBM/tree.h:20-391):
internal nodes indexed by split order, leaves referenced as `~leaf`.
"""
from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from ..parallel import collective as coll
from . import histogram as hist_ops
from .split import (K_MIN_SCORE, SplitParams, SplitResult,
                    best_split_for_leaf, best_split_per_feature,
                    best_split_per_feature_mixed, select_best_feature)

MISSING_NONE = 0
MISSING_ZERO = 1
MISSING_NAN = 2


class BundleMaps(NamedTuple):
    """Device-side EFB layout (io/efb.py BundleInfo): the bin matrix holds
    [n, G] bundled group columns; scans and splits address original
    features through these maps (FeatureGroup::SubFeatureIterator +
    Dataset::FixHistogram, feature_group.h:146-152, dataset.cpp:928-949)."""
    unbundle_idx: jnp.ndarray   # [F, B] int32 into flat [G*B] (+1 sentinel)
    feat_col: jnp.ndarray       # [F] int32 group column of each feature
    feat_lo: jnp.ndarray        # [F] int32 group-bin range of the feature's
    feat_hi: jnp.ndarray        #          mapped (non-default) bins
    feat_shift: jnp.ndarray     # [F] int32 group_bin = feature_bin + shift
    needs_fix: jnp.ndarray      # [F] bool default bin reconstructed at scan


def build_forced_candidate(hist, cnt, f_feat, f_thr, f_dl, unbundle,
                           num_bins, default_bins, missing_types, params,
                           cat_width: int = 0):
    """One forced-split plan entry -> the SplitResult to inject into the
    split cache (shared by the label and partition engines so the
    candidate semantics cannot drift; ForceSplits,
    serial_tree_learner.cpp:593-751)."""
    from .split import forced_split_result
    f_g = jnp.sum(hist[0, :, 0])
    f_h = jnp.sum(hist[0, :, 1])
    fsp = forced_split_result(
        unbundle(hist, f_g, f_h, cnt),
        jnp.int32(f_feat), jnp.int32(f_thr), f_g, f_h, cnt,
        num_bins, default_bins, missing_types, params,
        jnp.asarray(bool(f_dl)))
    if cat_width:
        fsp = fsp._replace(cat_mask=jnp.zeros(cat_width, bool))
    return fsp


def unbundle_hist(hist, sum_g, sum_h, cnt, bundle: Optional[BundleMaps],
                  default_bins):
    """[G, B, 3] group histogram -> [F, B, 3] per-feature view.

    Each feature's non-default bins are a gather from its group's bins;
    bundled features' default-bin entries are reconstructed as leaf
    totals minus the gathered sums (Dataset::FixHistogram,
    dataset.cpp:928-949).  Identity without EFB.  Shared by the label
    and partition engines — the two must stay math-identical."""
    if bundle is None:
        return hist
    F = bundle.feat_col.shape[0]
    flat = jnp.concatenate(
        [hist.reshape(-1, 3), jnp.zeros((1, 3), hist.dtype)], axis=0)
    hf = flat[bundle.unbundle_idx]                      # [F, B, 3]
    tot = jnp.stack([jnp.asarray(sum_g, hist.dtype),
                     jnp.asarray(sum_h, hist.dtype),
                     jnp.asarray(cnt, hist.dtype)])
    fix = tot[None, :] - jnp.sum(hf, axis=1)            # [F, 3]
    upd = jnp.where(bundle.needs_fix[:, None], fix, 0.0)
    return hf.at[jnp.arange(F), default_bins].add(upd)


def feature_bin_of(bins, feat, default_bins, bundle: Optional[BundleMaps]):
    """[n] feature-bin values of `feat` from the (possibly bundled) bin
    matrix: identity without EFB; otherwise the group column decoded back
    to feature bins, rows outside the feature's range -> its default bin."""
    if bundle is None:
        return jax.lax.dynamic_index_in_dim(
            bins, feat, axis=1, keepdims=False).astype(jnp.int32)
    col = jax.lax.dynamic_index_in_dim(
        bins, bundle.feat_col[feat], axis=1, keepdims=False).astype(jnp.int32)
    inside = (col >= bundle.feat_lo[feat]) & (col < bundle.feat_hi[feat])
    return jnp.where(inside, col - bundle.feat_shift[feat],
                     default_bins[feat])


class TreeArrays(NamedTuple):
    """SoA tree storage (tree.h:318-374).  Node arrays sized [max_leaves-1],
    leaf arrays [max_leaves]; children encode leaves as ~leaf_index."""
    split_feature: jnp.ndarray    # int32 [N] inner feature index
    threshold_bin: jnp.ndarray    # int32 [N]
    default_left: jnp.ndarray     # bool  [N]
    missing_type: jnp.ndarray     # int32 [N]
    left_child: jnp.ndarray       # int32 [N]
    right_child: jnp.ndarray      # int32 [N]
    split_gain: jnp.ndarray       # f     [N]
    internal_value: jnp.ndarray   # f     [N] output the node would have as leaf
    internal_count: jnp.ndarray   # int32 [N]
    leaf_value: jnp.ndarray       # f     [L]
    leaf_count: jnp.ndarray       # int32 [L]
    leaf_parent: jnp.ndarray      # int32 [L]
    leaf_depth: jnp.ndarray       # int32 [L]
    num_leaves: jnp.ndarray       # int32 scalar
    is_cat: jnp.ndarray           # bool  [N] categorical decision node
    cat_mask: jnp.ndarray         # bool  [N, W] left-going bins; W=0 when
    #                               the dataset has no categorical features

    @property
    def max_leaves(self) -> int:
        return self.leaf_value.shape[0]


def empty_tree(max_leaves: int, dtype=jnp.float32, cat_bins: int = 0
               ) -> TreeArrays:
    n = max(max_leaves - 1, 1)
    zf = jnp.zeros(n, dtype)
    zi = jnp.zeros(n, jnp.int32)
    return TreeArrays(
        split_feature=zi, threshold_bin=zi, default_left=jnp.zeros(n, bool),
        missing_type=zi, left_child=zi, right_child=zi, split_gain=zf,
        internal_value=zf, internal_count=zi,
        leaf_value=jnp.zeros(max_leaves, dtype),
        leaf_count=jnp.zeros(max_leaves, jnp.int32),
        leaf_parent=jnp.full(max_leaves, -1, jnp.int32),
        leaf_depth=jnp.zeros(max_leaves, jnp.int32),
        num_leaves=jnp.asarray(1, jnp.int32),
        is_cat=jnp.zeros(n, bool),
        cat_mask=jnp.zeros((n, cat_bins), bool),
    )


class GrowState(NamedTuple):
    tree: TreeArrays
    leaf_ids: jnp.ndarray          # [n] int32, -1 = not in this tree (bagging)
    hist_cache: jnp.ndarray        # [L, F, B, 3]
    split_cache: SplitResult       # stacked [L]
    done: jnp.ndarray              # bool scalar
    cegb_used: jnp.ndarray         # [F] bool — features used so far (CEGB
    #                                coupled penalty, feature_used in
    #                                serial_tree_learner.cpp:534-536)
    leaf_min: jnp.ndarray          # [L] per-leaf output lower bound (monotone
    #                                mid-constraint propagation, serial_tree_
    #                                learner.cpp:837-846 + leaf_splits.hpp)
    leaf_max: jnp.ndarray          # [L] per-leaf output upper bound


def _stack_split(res: SplitResult, cache: SplitResult, idx) -> SplitResult:
    return SplitResult(*[None if c is None else c.at[idx].set(v)
                         for c, v in zip(cache, res)])


def _index_split(cache: SplitResult, idx) -> SplitResult:
    return SplitResult(*[None if c is None else c[idx] for c in cache])


def grow_tree_impl(bins: jnp.ndarray,       # [n, F] uint8/16
              grad: jnp.ndarray,            # [n]
              hess: jnp.ndarray,            # [n]
              row_leaf_init: jnp.ndarray,   # [n] int32: 0 in-bag, -1 out
              feature_mask: jnp.ndarray,    # [F] bool
              num_bins: jnp.ndarray,        # [F] int32
              default_bins: jnp.ndarray,    # [F] int32
              missing_types: jnp.ndarray,   # [F] int32
              params: SplitParams,
              monotone: Optional[jnp.ndarray] = None,   # [F] int8 or None
              penalty: Optional[jnp.ndarray] = None,    # [F] or None
              is_categorical: Optional[jnp.ndarray] = None,  # [F] bool or None
              cegb_coupled: Optional[jnp.ndarray] = None,    # [F] or None:
              #   tradeoff * cegb_penalty_feature_coupled, charged while the
              #   feature is unused
              cegb_used_init: Optional[jnp.ndarray] = None,  # [F] bool
              bundle: Optional[BundleMaps] = None,  # EFB layout; bins is
              #   then [n, G] group columns (io/efb.py)
              *,
              forced_splits: tuple = (),   # static BFS list of
              #   (leaf_id, inner_feature, threshold_bin, default_left) from
              #   forcedsplits_filename (ForceSplits,
              #   serial_tree_learner.cpp:593-751); applied before the
              #   best-first loop by injecting +inf-gain cache entries
              max_leaves: int,
              max_depth: int = -1,
              max_bin: int,
              hist_impl: str = "auto",
              rows_per_chunk: int = 16384,
              learner: str = "serial",
              axis_name: Optional[str] = None,
              num_machines: int = 1,
              top_k: int = 20,
              max_cat_threshold: int = 32):
    """Grow one leaf-wise tree; returns (TreeArrays, leaf_ids).

    learner/axis_name select the distributed mode when called inside
    shard_map over a Mesh axis (the TPU re-design of the {serial, feature,
    data, voting} learner family, src/treelearner/tree_learner.cpp:9-33):

    - "serial": single shard, no collectives.
    - "data"  (DataParallelTreeLearner, data_parallel_tree_learner.cpp):
      rows sharded over axis_name; histograms reduce-scattered so each
      device aggregates + scans only its feature shard (full psum fallback
      for EFB/forced splits), winner synced like feature-parallel; rows
      are relabelled locally.
    - "feature" (FeatureParallelTreeLearner, feature_parallel_tree_learner
      .cpp): full data replicated; each shard builds histograms and scans
      only its contiguous F/num_machines feature slice; best split synced by
      all_gather + argmax (SyncUpGlobalBestSplit, parallel_tree_learner
      .h:186-209); splits applied locally everywhere.
    - "voting" (VotingParallelTreeLearner, voting_parallel_tree_learner
      .cpp): rows sharded; local top-k feature vote → global top-2k elected
      features → psum of elected histograms only → global best split.
    """
    n = bins.shape[0]
    F = num_bins.shape[0]        # scan features (== bins columns sans EFB)
    dtype = grad.dtype
    distributed = axis_name is not None and learner != "serial"
    if bundle is not None and learner == "feature":
        raise ValueError("EFB-bundled datasets do not support the "
                         "feature-parallel learner (bundling is disabled "
                         "at dataset construction for it)")
    # DP histogram exchange: reduce-scatter the [F,B,3] histogram so each
    # device aggregates and scans only its own contiguous feature shard,
    # then sync the winner — the reference's ReduceScatter + per-machine
    # FindBestSplitsFromHistograms + SyncUpGlobalBestSplit schedule
    # (data_parallel_tree_learner.cpp:146-245).  d× less collective
    # volume and d× less scan work than a full psum at pod scale.
    # Falls back to the full psum when any consumer needs non-local
    # features: EFB unbundling gathers across group boundaries, forced
    # splits read arbitrary features from the cached histogram, and the
    # coupled-CEGB penalty is a full-width per-feature vector.
    scatter_dp = (distributed and learner == "data"
                  and bundle is None and not forced_splits
                  and cegb_coupled is None
                  and num_machines > 1)
    scatter_pad = 0
    if scatter_dp:
        scatter_pad = -(-F // num_machines) * num_machines - F

    def _pad_feat(a, fill):
        """Pad per-feature statics so F divides the mesh; padded slots are
        inert in the scan (num_bins=1 -> no threshold exists)."""
        if a is None or not scatter_pad:
            return a
        return jnp.concatenate(
            [jnp.asarray(a),
             jnp.full((scatter_pad,), fill, jnp.asarray(a).dtype)])

    if distributed and (learner == "feature" or scatter_dp):
        # contiguous per-shard feature slice (deterministic sharding, the
        # analogue of the bin-count-balanced shuffle at
        # feature_parallel_tree_learner.cpp:30-49).  Feature-parallel
        # slices the BIN MATRIX (each shard histograms only its columns);
        # scatter-DP keeps full local histograms and shards post-reduce.
        if learner == "feature" and F % num_machines:
            raise ValueError(
                "feature-parallel requires num_features (%d) divisible by "
                "num_machines (%d); pad features first (ParallelGrower does)"
                % (F, num_machines))
        f_local = (F + scatter_pad) // num_machines
        f_off = coll.axis_index(axis_name).astype(jnp.int32) * f_local

        p_num_bins = _pad_feat(num_bins, 1)
        p_default_bins = _pad_feat(default_bins, 0)
        p_missing = _pad_feat(missing_types, 0)
        p_feature_mask = feature_mask
        if scatter_pad and p_feature_mask is None:
            p_feature_mask = jnp.ones((F,), jnp.float32)
        p_feature_mask = _pad_feat(p_feature_mask, 0)
        p_monotone = _pad_feat(monotone, 0)
        p_penalty = _pad_feat(penalty, 1)
        p_is_categorical = _pad_feat(is_categorical, False)

        def _slice(a):
            return (None if a is None
                    else jax.lax.dynamic_slice_in_dim(a, f_off, f_local))
        if learner == "feature":
            hist_bins = jax.lax.dynamic_slice_in_dim(bins, f_off, f_local,
                                                     axis=1)
        else:
            hist_bins = bins
        l_num_bins, l_default_bins, l_missing = map(
            _slice, (p_num_bins, p_default_bins, p_missing))
        l_monotone, l_penalty, l_feature_mask = map(
            _slice, (p_monotone, p_penalty, p_feature_mask))
        l_is_categorical = _slice(p_is_categorical)
        l_feature_index = f_off + jnp.arange(f_local, dtype=jnp.int32)
    else:
        hist_bins = bins
        l_num_bins, l_default_bins, l_missing = num_bins, default_bins, missing_types
        l_monotone, l_penalty, l_feature_mask = monotone, penalty, feature_mask
        l_is_categorical = is_categorical
        l_feature_index = None

    def reduce_hist(h):
        # DP: one collective per histogrammed leaf — psum_scatter when
        # each device can scan its own shard (see scatter_dp above),
        # full psum for the EFB/forced-split fallbacks (§3.4.2)
        if distributed and learner == "data":
            if scatter_dp:
                if scatter_pad:
                    h = jnp.concatenate(
                        [h, jnp.zeros((scatter_pad,) + h.shape[1:],
                                      h.dtype)], axis=0)
                return coll.psum_scatter(h, axis_name,
                                            scatter_dimension=0, tiled=True)
            return coll.psum(h, axis_name)
        return h

    def unbundle(hist, sum_g, sum_h, cnt):
        return unbundle_hist(hist, sum_g, sum_h, cnt, bundle, default_bins)

    def _bounds(minc, maxc, nf):
        """Per-leaf scalar output bounds -> per-feature arrays for the
        scans, or None when no monotone constraints exist (zero cost)."""
        if monotone is None or minc is None:
            return None, None
        return (jnp.broadcast_to(jnp.asarray(minc, dtype), (nf,)),
                jnp.broadcast_to(jnp.asarray(maxc, dtype), (nf,)))

    # feature statics for the Pallas scan, hoisted out of the while loop
    # (only the CEGB column is leaf-dependent and is patched per call)
    from . import split_pallas as sp_pl
    # n < 2^24 bound: the kernel's counts ride f32 prefix sums, which
    # are integer-exact only below 2^24 rows per leaf — the XLA path
    # keeps integer cumsums precisely for the billion-row regime
    use_scan_kernel = (is_categorical is None and dtype == jnp.float32
                       and n < (1 << 24))
    _shard_scan = distributed and (learner == "feature" or scatter_dp)
    if use_scan_kernel:
        _fvec_full = sp_pl.build_feature_statics(
            num_bins, default_bins, missing_types, monotone=monotone,
            penalty=penalty, feature_mask=feature_mask, children=1)
        _fvec_local = (_fvec_full if not _shard_scan
                       else sp_pl.build_feature_statics(
                           l_num_bins, l_default_bins, l_missing,
                           monotone=l_monotone, penalty=l_penalty,
                           feature_mask=l_feature_mask, children=1))
    else:
        _fvec_full = _fvec_local = None

    def local_scan(hist, sum_g, sum_h, cnt, nb, db, mt, mono, pen, fmask,
                   icat, findex=None, used=None, minc=None, maxc=None,
                   fvec_pre=None):
        """Per-feature scan (numerical or bin-type-dispatched) + argmax."""
        cegb_pen = None
        if cegb_coupled is not None and used is not None:
            cegb_pen = jnp.where(used, 0.0, cegb_coupled)
        mn, mx = _bounds(minc, maxc, hist.shape[0])
        if use_scan_kernel and icat is None and hist.dtype == jnp.float32:
            # single-launch Pallas scan (ops/split_pallas.py) — the XLA
            # op chain is ~0.45 ms of dispatch latency per call; the
            # kernel matches it up to f32 prefix-sum association, and
            # BOTH engines route here so their trees stay identical
            pf = sp_pl.scan_single(
                hist, sum_g, sum_h, cnt, params, fvec_pre=fvec_pre,
                num_bins=nb, default_bins=db, missing_types=mt,
                monotone=mono, penalty=pen, feature_mask=fmask,
                cegb_pen=cegb_pen, mn=mn, mx=mx)
        elif icat is None:
            pf = best_split_per_feature(hist, sum_g, sum_h, cnt, nb, db, mt,
                                        params, monotone=mono, penalty=pen,
                                        min_constraints=mn, max_constraints=mx,
                                        feature_mask=fmask,
                                        cegb_feature_penalty=cegb_pen)
        else:
            pf = best_split_per_feature_mixed(
                hist, sum_g, sum_h, cnt, nb, db, mt, icat, params,
                monotone=mono, penalty=pen, feature_mask=fmask,
                min_constraints=mn, max_constraints=mx,
                cegb_feature_penalty=cegb_pen,
                max_cat_threshold=max_cat_threshold)
        return select_best_feature(pf, feature_index=findex)

    def leaf_best_split(hist, sum_g, sum_h, cnt, depth, used=None,
                        minc=None, maxc=None):
        if _shard_scan:
            # used (CEGB) stays None here: scatter_dp is disabled when
            # cegb_coupled is set, and feature mode never wired it
            local = local_scan(
                hist, sum_g, sum_h, cnt,
                l_num_bins, l_default_bins, l_missing,
                l_monotone, l_penalty, l_feature_mask, l_is_categorical,
                used=None, minc=minc, maxc=maxc, fvec_pre=_fvec_local)
            # map the local winner to its global feature id
            local = local._replace(feature=jnp.where(
                local.feature >= 0, l_feature_index[local.feature],
                local.feature))
            # SyncUpGlobalBestSplit: pack the candidate into one float + one
            # int vector (the reference packs SplitInfo into a single wire
            # buffer, parallel_tree_learner.h:186-209), gather both in two
            # collectives, argmax on gain; first-hit tie-break = lowest
            # shard = lowest feature id
            fdt = local.gain.dtype
            fvec = jnp.stack([
                local.gain, local.default_left.astype(fdt),
                local.left_sum_gradient, local.left_sum_hessian,
                local.left_output, local.right_sum_gradient,
                local.right_sum_hessian, local.right_output])
            ivec = jnp.stack([local.feature, local.threshold,
                              local.left_count, local.right_count])
            if local.cat_mask is not None:
                ivec = jnp.concatenate(
                    [ivec, local.cat_mask.astype(jnp.int32)])
            fall = coll.all_gather(fvec, axis_name)             # [d, 8]
            iall = coll.all_gather(ivec, axis_name)             # [d, 4+W]
            winner = jnp.argmax(fall[:, 0]).astype(jnp.int32)
            fw, iw = fall[winner], iall[winner]
            res = SplitResult(
                feature=iw[0], threshold=iw[1], gain=fw[0],
                default_left=fw[1] > 0.5,
                left_sum_gradient=fw[2], left_sum_hessian=fw[3],
                left_count=iw[2], left_output=fw[4],
                right_sum_gradient=fw[5], right_sum_hessian=fw[6],
                right_count=iw[3], right_output=fw[7],
                cat_mask=(None if local.cat_mask is None
                          else iw[4:] > 0))
        elif distributed and learner == "voting":
            # voting scans LOCAL histograms first: the unbundle fix needs
            # local leaf totals, recovered from group 0's bins (each
            # in-leaf local row lands in exactly one of them)
            if bundle is not None:
                loc = jnp.sum(hist[0], axis=0)
                hist = unbundle(hist, loc[0], loc[1], loc[2])
            mn, mx = _bounds(minc, maxc, F)
            res = _voting_best_split(
                hist, sum_g, sum_h, cnt,
                num_bins, default_bins, missing_types, params,
                monotone, penalty, feature_mask, is_categorical,
                axis_name=axis_name, num_machines=num_machines,
                top_k=top_k, max_cat_threshold=max_cat_threshold,
                min_constraints=mn, max_constraints=mx,
                fvec_local=_fvec_full, use_kernel=use_scan_kernel)
        else:
            res = local_scan(unbundle(hist, sum_g, sum_h, cnt),
                             sum_g, sum_h, cnt,
                             num_bins, default_bins, missing_types,
                             monotone, penalty, feature_mask, is_categorical,
                             used=used, minc=minc, maxc=maxc,
                             fvec_pre=_fvec_full)
        depth_ok = (max_depth <= 0) | (depth < max_depth)
        blocked = (res.feature < 0) | ~depth_ok
        return res._replace(gain=jnp.where(blocked, K_MIN_SCORE, res.gain),
                            feature=jnp.where(depth_ok, res.feature, -1))

    # ---- root ----------------------------------------------------------
    tree = empty_tree(max_leaves, dtype,
                      cat_bins=(max_bin if is_categorical is not None else 0))
    root_hist = hist_ops.leaf_histogram(hist_bins, grad, hess, row_leaf_init, 0,
                                        max_bin, hist_impl, rows_per_chunk)
    root_hist = reduce_hist(root_hist)
    in_bag = row_leaf_init == 0
    root_g = jnp.sum(grad * in_bag)
    root_h = jnp.sum(hess * in_bag)
    root_c = jnp.sum(in_bag).astype(jnp.int32)
    if distributed and learner in ("data", "voting"):
        # root (cnt, Σg, Σh) Allreduce (data_parallel_tree_learner.cpp:116-142)
        root_g = coll.psum(root_g, axis_name)
        root_h = coll.psum(root_h, axis_name)
        root_c = coll.psum(root_c, axis_name)
    tree = tree._replace(leaf_count=tree.leaf_count.at[0].set(root_c))

    cegb_used0 = (cegb_used_init if cegb_used_init is not None
                  else jnp.zeros(F, bool))
    ninf = jnp.asarray(-jnp.inf, dtype)
    pinf = jnp.asarray(jnp.inf, dtype)
    root_split = leaf_best_split(root_hist, root_g, root_h, root_c,
                                 jnp.asarray(0, jnp.int32), used=cegb_used0,
                                 minc=ninf, maxc=pinf)

    L = max_leaves
    hist_cache = jnp.zeros((L,) + root_hist.shape, dtype).at[0].set(root_hist)
    split_cache = SplitResult(*[
        None if v is None else
        jnp.zeros((L,) + jnp.shape(jnp.asarray(v)), jnp.asarray(v).dtype)
        for v in root_split])
    split_cache = _stack_split(root_split, split_cache, 0)
    # non-existent leaves must never win the argmax
    split_cache = split_cache._replace(
        gain=split_cache.gain.at[1:].set(K_MIN_SCORE))

    state = GrowState(tree=tree, leaf_ids=row_leaf_init, hist_cache=hist_cache,
                      split_cache=split_cache, done=jnp.asarray(False),
                      cegb_used=cegb_used0,
                      leaf_min=jnp.full(L, ninf, dtype),
                      leaf_max=jnp.full(L, pinf, dtype))

    def cond(state: GrowState):
        return (~state.done) & (state.tree.num_leaves < max_leaves)

    def body(state: GrowState) -> GrowState:
        tree = state.tree
        nl = tree.num_leaves                      # current leaf count
        node = nl - 1                             # new internal node index

        best_leaf = jnp.argmax(state.split_cache.gain).astype(jnp.int32)
        sp = _index_split(state.split_cache, best_leaf)
        no_split = sp.gain <= K_MIN_SCORE  # includes min_gain (already masked)

        def do_split(state: GrowState) -> GrowState:
            tree = state.tree
            new_leaf = nl                          # right child leaf id
            feat = sp.feature
            thr = sp.threshold
            # -- relabel rows (DataPartition::Split, data_partition.hpp:108) --
            col = feature_bin_of(bins, feat, default_bins, bundle)
            mt = missing_types[feat]
            db = default_bins[feat]
            mb = num_bins[feat] - 1
            is_missing = ((mt == MISSING_ZERO) & (col == db)) | \
                         ((mt == MISSING_NAN) & (col == mb))
            go_left = jnp.where(is_missing, sp.default_left, col <= thr)
            if is_categorical is not None:
                # categorical: bitset membership decides; bins outside the
                # mask (incl. the NaN bin) go right (CategoricalDecision,
                # tree.h:259-273)
                go_left = jnp.where(is_categorical[feat],
                                    sp.cat_mask[col], go_left)
            in_leaf = state.leaf_ids == best_leaf
            leaf_ids = jnp.where(in_leaf & ~go_left, new_leaf, state.leaf_ids)

            # -- histograms: smaller child by masked pass, sibling by
            #    subtraction (the reference's core scheduling trick) --------
            left_smaller = sp.left_count <= sp.right_count
            small_leaf = jnp.where(left_smaller, best_leaf, new_leaf)
            parent_hist = state.hist_cache[best_leaf]
            small_hist = hist_ops.leaf_histogram(hist_bins, grad, hess, leaf_ids,
                                                 small_leaf, max_bin,
                                                 hist_impl, rows_per_chunk)
            small_hist = reduce_hist(small_hist)
            large_hist = parent_hist - small_hist
            left_hist = jnp.where(left_smaller, small_hist, large_hist)
            right_hist = jnp.where(left_smaller, large_hist, small_hist)
            hist_cache = state.hist_cache.at[best_leaf].set(left_hist)
            hist_cache = hist_cache.at[new_leaf].set(right_hist)

            # -- tree bookkeeping (Tree::Split, tree.h:393-423) -------------
            parent_of = tree.leaf_parent[best_leaf]
            # fix the parent's child pointer that referenced ~best_leaf
            was_left = jnp.where(parent_of >= 0,
                                 tree.left_child[parent_of] == ~best_leaf, False)
            left_child = jnp.where(
                (parent_of >= 0) & was_left,
                tree.left_child.at[parent_of].set(node), tree.left_child)
            right_child = jnp.where(
                (parent_of >= 0) & ~was_left,
                tree.right_child.at[parent_of].set(node), tree.right_child)

            depth = tree.leaf_depth[best_leaf]
            new_is_cat = tree.is_cat
            new_cat_mask = tree.cat_mask
            if is_categorical is not None:
                new_is_cat = new_is_cat.at[node].set(is_categorical[feat])
                new_cat_mask = new_cat_mask.at[node].set(sp.cat_mask)
            tree = tree._replace(
                is_cat=new_is_cat,
                cat_mask=new_cat_mask,
                split_feature=tree.split_feature.at[node].set(feat),
                threshold_bin=tree.threshold_bin.at[node].set(thr),
                default_left=tree.default_left.at[node].set(sp.default_left),
                missing_type=tree.missing_type.at[node].set(missing_types[feat]),
                left_child=left_child.at[node].set(~best_leaf),
                right_child=right_child.at[node].set(~new_leaf),
                split_gain=tree.split_gain.at[node].set(sp.gain.astype(dtype)),
                internal_value=tree.internal_value.at[node].set(
                    tree.leaf_value[best_leaf]),
                internal_count=tree.internal_count.at[node].set(
                    sp.left_count + sp.right_count),
                leaf_value=tree.leaf_value.at[best_leaf].set(
                    sp.left_output.astype(dtype)).at[new_leaf].set(
                    sp.right_output.astype(dtype)),
                leaf_count=tree.leaf_count.at[best_leaf].set(
                    sp.left_count).at[new_leaf].set(sp.right_count),
                leaf_parent=tree.leaf_parent.at[best_leaf].set(node)
                    .at[new_leaf].set(node),
                leaf_depth=tree.leaf_depth.at[best_leaf].set(depth + 1)
                    .at[new_leaf].set(depth + 1),
                num_leaves=nl + 1,
            )

            # -- monotone mid-constraint propagation ------------------------
            # (serial_tree_learner.cpp:837-846): children inherit the
            # parent's [min, max] output bounds; a NUMERICAL split on a
            # monotone feature pins the shared boundary at the mid of the
            # two child outputs so every descendant respects the ancestor
            minP = state.leaf_min[best_leaf]
            maxP = state.leaf_max[best_leaf]
            minL, maxL, minR, maxR = minP, maxP, minP, maxP
            leaf_min, leaf_max = state.leaf_min, state.leaf_max
            if monotone is not None:
                mono_t = monotone[feat].astype(jnp.int32)
                if is_categorical is not None:
                    mono_t = jnp.where(is_categorical[feat], 0, mono_t)
                mid = ((sp.left_output + sp.right_output) / 2).astype(dtype)
                maxL = jnp.where(mono_t > 0, mid, maxP)
                minR = jnp.where(mono_t > 0, mid, minP)
                minL = jnp.where(mono_t < 0, mid, minP)
                maxR = jnp.where(mono_t < 0, mid, maxP)
                leaf_min = leaf_min.at[best_leaf].set(minL).at[new_leaf].set(minR)
                leaf_max = leaf_max.at[best_leaf].set(maxL).at[new_leaf].set(maxR)

            # -- children best splits ---------------------------------------
            used2 = state.cegb_used.at[feat].set(True)
            lsp = leaf_best_split(left_hist, sp.left_sum_gradient,
                                  sp.left_sum_hessian, sp.left_count,
                                  depth + 1, used=used2, minc=minL, maxc=maxL)
            rsp = leaf_best_split(right_hist, sp.right_sum_gradient,
                                  sp.right_sum_hessian, sp.right_count,
                                  depth + 1, used=used2, minc=minR, maxc=maxR)
            split_cache = _stack_split(lsp, state.split_cache, best_leaf)
            split_cache = _stack_split(rsp, split_cache, new_leaf)

            return GrowState(tree=tree, leaf_ids=leaf_ids,
                             hist_cache=hist_cache, split_cache=split_cache,
                             done=jnp.asarray(False), cegb_used=used2,
                             leaf_min=leaf_min, leaf_max=leaf_max)

        return jax.lax.cond(no_split,
                            lambda s: s._replace(done=jnp.asarray(True)),
                            do_split, state)

    # Forced splits first (trace-time unrolled: the BFS plan is static):
    # overwrite the target leaf's cache entry with a +inf-gain forced
    # result and run one standard body step to apply it.  The plan's
    # static leaf numbering assumes every entry applies (entry i targets
    # static leaf plan[i][0] and creates static leaf i+1), but an entry
    # can be invalid at runtime (empty child, leaf budget).  A traced
    # static->dynamic leaf map keeps later entries addressed correctly
    # regardless: an invalid entry leaves its created leaf mapped to -1,
    # so its whole forced subtree is abandoned (ForceSplits,
    # serial_tree_learner.cpp:593-751) while siblings from other branches
    # still resolve to the right dynamic leaf ids.
    leafmap = jnp.full((len(forced_splits) + 1,), -1, jnp.int32).at[0].set(0)
    for i, (f_leaf, f_feat, f_thr, f_dl) in enumerate(forced_splits):
        if i >= max_leaves - 1:
            break      # each applied split adds one leaf; bound the count
        dyn_leaf = leafmap[f_leaf]
        safe_leaf = jnp.maximum(dyn_leaf, 0)
        fsp = build_forced_candidate(
            state.hist_cache[safe_leaf], state.tree.leaf_count[safe_leaf],
            f_feat, f_thr, f_dl, unbundle,
            num_bins, default_bins, missing_types, params,
            cat_width=(state.split_cache.cat_mask.shape[1]
                       if state.split_cache.cat_mask is not None else 0))
        valid = (dyn_leaf >= 0) & (fsp.gain > K_MIN_SCORE) & \
                (state.tree.num_leaves < max_leaves)
        injected = state._replace(
            split_cache=_stack_split(fsp, state.split_cache, safe_leaf))
        dyn_new = state.tree.num_leaves    # right-child leaf id body assigns
        stepped = body(injected)._replace(done=jnp.asarray(False))

        def _sel(a, b):
            if a is None:
                return None
            return jnp.where(valid, a, b)

        state = jax.tree_util.tree_map(
            _sel, stepped, state,
            is_leaf=lambda x: x is None)
        leafmap = leafmap.at[i + 1].set(jnp.where(valid, dyn_new, -1))
        # on failure also unmap the target: the only later entry that
        # references static id f_leaf is this entry's LEFT-child entry
        # (each static leaf is split at most once), which must be
        # abandoned along with the right subtree
        leafmap = leafmap.at[f_leaf].set(jnp.where(valid, dyn_leaf, -1))

    state = jax.lax.while_loop(cond, body, state)
    return state.tree, state.leaf_ids


_TREE_FLOAT_FIELDS = ("split_gain", "internal_value", "leaf_value")


def _tree_field_spec(max_leaves: int, cat_bins: int):
    import numpy as np

    n = max(max_leaves - 1, 1)
    L = max_leaves
    return [("split_feature", (n,), np.int32),
            ("threshold_bin", (n,), np.int32),
            ("default_left", (n,), bool),
            ("missing_type", (n,), np.int32),
            ("left_child", (n,), np.int32),
            ("right_child", (n,), np.int32),
            ("split_gain", (n,), None),
            ("internal_value", (n,), None),
            ("internal_count", (n,), np.int32),
            ("leaf_value", (L,), None),
            ("leaf_count", (L,), np.int32),
            ("leaf_parent", (L,), np.int32),
            ("leaf_depth", (L,), np.int32),
            ("num_leaves", (), np.int32),
            ("is_cat", (n,), bool),
            ("cat_mask", (n, cat_bins), bool)]


@jax.jit
def pack_tree_arrays(t: TreeArrays):
    """Flatten a TreeArrays into TWO device vectors (ints exactly as int32,
    floats in their own dtype).  One host fetch of this pair replaces ~17
    per-field transfers, each of which pays a full round-trip on
    remote-attached TPUs."""
    ints, floats = [], []
    for name, x in zip(TreeArrays._fields, t):
        if name in _TREE_FLOAT_FIELDS:
            floats.append(jnp.ravel(x))
        else:
            ints.append(jnp.ravel(x).astype(jnp.int32))
    return jnp.concatenate(ints), jnp.concatenate(floats)


def unpack_tree_vectors(ivec, fvec, max_leaves: int,
                        cat_bins: int) -> TreeArrays:
    """Host-side inverse of pack_tree_arrays (numpy in, numpy out)."""
    import numpy as np

    out, ioff, foff = {}, 0, 0
    for name, shape, dtype in _tree_field_spec(max_leaves, cat_bins):
        size = int(np.prod(shape)) if shape else 1
        if name in _TREE_FLOAT_FIELDS:
            out[name] = fvec[foff:foff + size].reshape(shape)
            foff += size
        else:
            out[name] = (ivec[ioff:ioff + size].reshape(shape)
                         .astype(dtype))
            ioff += size
    return TreeArrays(**out)


def fetch_tree_arrays(t: TreeArrays) -> TreeArrays:
    """Device TreeArrays -> host (numpy) TreeArrays in one bulk transfer."""
    ivec, fvec = jax.device_get(pack_tree_arrays(t))
    return unpack_tree_vectors(ivec, fvec, t.max_leaves, t.cat_mask.shape[1])


grow_tree = partial(jax.jit, static_argnames=(
    "max_leaves", "max_depth", "max_bin", "hist_impl", "rows_per_chunk",
    "learner", "axis_name", "num_machines", "top_k",
    "max_cat_threshold", "forced_splits"))(grow_tree_impl)


def _voting_best_split(local_hist, sum_g, sum_h, cnt,
                       num_bins, default_bins, missing_types,
                       params: SplitParams,
                       monotone, penalty, feature_mask, is_categorical,
                       *, axis_name: str, num_machines: int, top_k: int,
                       max_cat_threshold: int = 32,
                       min_constraints=None,
                       max_constraints=None,
                       fvec_local=None,
                       use_kernel: bool = True) -> SplitResult:
    """PV-tree best split (voting_parallel_tree_learner.cpp:257-460).

    local_hist [F, B, 3] holds *local-shard* rows only.  Protocol:
    1. local per-feature scan against 1/num_machines-rescaled min-data
       thresholds (the locally-rescaled config, voting...cpp:50-57);
    2. local top-k features by gain → Allgather (the LightSplitInfo
       allgather, voting...cpp:322-356);
    3. GlobalVoting: vote count per feature, elect top-2k
       (voting...cpp:166-195), smaller feature id on ties;
    4. psum of the elected features' histograms only (CopyLocalHistogram +
       ReduceScatter, voting...cpp:198-254) — O(2k·B) bytes instead of
       O(F·B);
    5. full-threshold scan on the global histograms, winner selected among
       the elected features.
    """
    F = local_hist.shape[0]
    k = min(top_k, F)
    # local parent sums: every in-leaf row lands in exactly one bin of
    # feature 0, so its bin-sum recovers the local leaf totals
    loc_g = jnp.sum(local_hist[0, :, 0])
    loc_h = jnp.sum(local_hist[0, :, 1])
    loc_c = jnp.round(jnp.sum(local_hist[0, :, 2])).astype(jnp.int32)

    def scan(hist, sg, sh, sc, nb, db, mt, mono, pen, fmask, icat, p,
             mn=None, mx=None, fvec_pre=None):
        if (icat is None and hist.dtype == jnp.float32
                and use_kernel):
            # same Pallas kernel as the serial scan — voting must elect
            # and score with bit-identical gains or its trees drift from
            # the serial learner on prefix-sum association ties
            from . import split_pallas as sp_pl
            return sp_pl.scan_single(
                hist, sg, sh, sc, p, fvec_pre=fvec_pre,
                num_bins=nb, default_bins=db, missing_types=mt,
                monotone=mono, penalty=pen, feature_mask=fmask,
                mn=mn, mx=mx)
        if icat is None:
            return best_split_per_feature(hist, sg, sh, sc, nb, db, mt, p,
                                          monotone=mono, penalty=pen,
                                          min_constraints=mn,
                                          max_constraints=mx,
                                          feature_mask=fmask)
        return best_split_per_feature_mixed(
            hist, sg, sh, sc, nb, db, mt, icat, p,
            monotone=mono, penalty=pen, feature_mask=fmask,
            min_constraints=mn, max_constraints=mx,
            max_cat_threshold=max_cat_threshold)

    # params leaves may be tracers (SplitParams rides the jit pytree)
    local_params = params._replace(
        min_data_in_leaf=jnp.maximum(params.min_data_in_leaf // num_machines, 1),
        min_sum_hessian_in_leaf=params.min_sum_hessian_in_leaf / num_machines)
    pf_local = scan(local_hist, loc_g, loc_h, loc_c,
                    num_bins, default_bins, missing_types,
                    monotone, penalty, feature_mask, is_categorical,
                    local_params, min_constraints, max_constraints,
                    fvec_pre=fvec_local)

    _, top_idx = jax.lax.top_k(pf_local.gain, k)                # [k]
    top_valid = jnp.take(pf_local.gain, top_idx) > K_MIN_SCORE
    all_top = coll.all_gather(top_idx, axis_name)            # [d, k]
    all_valid = coll.all_gather(top_valid, axis_name)        # [d, k]

    votes = jnp.zeros(F, jnp.int32).at[all_top.reshape(-1)].add(
        all_valid.reshape(-1).astype(jnp.int32))                # [F]
    n_elect = min(2 * k, F)
    # lax.top_k is stable (lower index first on ties) → equal-vote ties
    # break toward the smaller feature id (stable sort in GlobalVoting)
    _, elected = jax.lax.top_k(votes, n_elect)                  # [n_elect]
    elected = elected.astype(jnp.int32)

    glob_hist = coll.psum(jnp.take(local_hist, elected, axis=0), axis_name)

    def take(a):
        return None if a is None else jnp.take(a, elected, axis=0)

    pf_glob = scan(glob_hist, sum_g, sum_h, cnt,
                   take(num_bins), take(default_bins), take(missing_types),
                   take(monotone), take(penalty), take(feature_mask),
                   take(is_categorical), params,
                   take(min_constraints), take(max_constraints))
    return select_best_feature(pf_glob, feature_index=elected)


@jax.jit
def predict_leaf_inner(bins: jnp.ndarray, tree: TreeArrays,
                       num_bins: jnp.ndarray, default_bins: jnp.ndarray,
                       bundle: Optional[BundleMaps] = None) -> jnp.ndarray:
    """Leaf index per row by walking the tree over *inner* bin values
    (Tree::GetLeafAt + DecisionInner, tree.h:233-248, 289-296).

    Vectorized node walk: every row holds a current node (>=0 internal,
    negative = ~leaf); iterate until all rows rest at leaves.  With EFB
    `bins` holds group columns decoded per node through `bundle`.
    """
    n = bins.shape[0]
    start = jnp.where(tree.num_leaves > 1, 0, ~0)
    node = jnp.full((n,), start, jnp.int32)

    def cond(node):
        return jnp.any(node >= 0)

    def body(node):
        nd = jnp.maximum(node, 0)
        feat = tree.split_feature[nd]
        if bundle is None:
            gcol = feat
        else:
            gcol = bundle.feat_col[feat]
        col = jnp.take_along_axis(bins, gcol[:, None].astype(jnp.int32),
                                  axis=1)[:, 0].astype(jnp.int32)
        if bundle is not None:
            inside = (col >= bundle.feat_lo[feat]) & \
                     (col < bundle.feat_hi[feat])
            col = jnp.where(inside, col - bundle.feat_shift[feat],
                            default_bins[feat])
        mt = tree.missing_type[nd]
        db = default_bins[tree.split_feature[nd]]
        mb = num_bins[tree.split_feature[nd]] - 1
        is_missing = ((mt == MISSING_ZERO) & (col == db)) | \
                     ((mt == MISSING_NAN) & (col == mb))
        go_left = jnp.where(is_missing, tree.default_left[nd],
                            col <= tree.threshold_bin[nd])
        if tree.cat_mask.shape[1] > 0:
            go_left = jnp.where(tree.is_cat[nd], tree.cat_mask[nd, col],
                                go_left)
        nxt = jnp.where(go_left, tree.left_child[nd], tree.right_child[nd])
        return jnp.where(node >= 0, nxt, node)

    node = jax.lax.while_loop(cond, body, node)
    return ~node  # leaf index


def predict_value_inner(bins: jnp.ndarray, tree: TreeArrays,
                        num_bins: jnp.ndarray, default_bins: jnp.ndarray,
                        bundle: Optional[BundleMaps] = None) -> jnp.ndarray:
    leaf = predict_leaf_inner(bins, tree, num_bins, default_bins, bundle)
    return tree.leaf_value[leaf]
