"""Partition-engine leaf-wise tree growth (serial learner, TPU fast path).

The arena re-design of SerialTreeLearner::Train (reference
src/treelearner/serial_tree_learner.cpp:169-233): instead of the label
engine's per-split masked pass over all n rows (ops/grow.py), rows live
physically grouped by leaf in the feature-major bf16-plane arena of
ops/partition_pallas.py, so each split costs O(parent) to partition and
O(smaller_child) to histogram — the reference's asymptotics
(DataPartition::Split data_partition.hpp:108-160 + the smaller/larger
histogram choreography serial_tree_learner.cpp:360-437, with the sibling
recovered by subtraction, feature_histogram.hpp:67-73).

Segment allocation is a device-side bump allocator in 256-column units:
the larger child overwrites the parent segment in place, the smaller
child is appended at the cursor.  On overflow the tree simply stops
growing (a debug print fires; raise tpu_arena_factor) — the default
arena budget covers a balanced 255-leaf tree, and the GBDT driver falls
back to the label engine for configs that need full generality.

Supports categorical bitset splits, EFB-bundled datasets (both via the
go-left mask decision), forced splits (the same cache-injection scheme
as the label engine) and data-parallel sharding (axis_name: psum'd
histograms, local arenas).  Remaining restrictions vs the label engine
(the GBDT driver auto-selects): f32 only, max_bin <= 256, n < 2^24
(rowids ride three byte planes exactly), serial or data-parallel only
(feature-/voting-parallel use the label engine).
"""
from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from . import partition_pallas as pp
from . import split_pallas as sp_pl
from .grow import (MISSING_NAN, MISSING_ZERO, BundleMaps, TreeArrays,
                   _index_split, _stack_split, empty_tree)
from .split import (K_MIN_SCORE, SplitParams, SplitResult,
                    best_split_per_feature, best_split_per_feature_mixed,
                    select_best_feature)

ALLOC = pp.FLUSH_W         # allocation granularity (columns)


def _align(x, unit):
    return (x + unit - 1) // unit * unit


class PartState(NamedTuple):
    tree: TreeArrays
    arena: jnp.ndarray             # [C, cap] f32
    leaf_start: jnp.ndarray        # [L] int32 segment starts
    leaf_local: jnp.ndarray        # [L] int32 LOCAL segment lengths (==
    #   tree.leaf_count when serial; differs under data-parallel sharding)
    cursor: jnp.ndarray            # int32 bump cursor (256-aligned)
    hist_cache: jnp.ndarray        # [K, G, B, 3] slot cache (HistogramPool,
    #   feature_histogram.hpp:646-818: K < L spills by LRU; a missed
    #   parent is recomputed from its still-intact segment)
    slot_leaf: jnp.ndarray         # [K] int32 leaf whose hist each slot holds
    slot_tick: jnp.ndarray         # [K] int32 write-recency for eviction
    tick: jnp.ndarray              # int32 monotone write counter
    split_cache: SplitResult
    done: jnp.ndarray
    cegb_used: jnp.ndarray         # [F] bool (CEGB coupled feature_used)
    truncated: jnp.ndarray         # bool: growth stopped by arena overflow
    leaf_min: jnp.ndarray          # [L] monotone output bounds per leaf
    leaf_max: jnp.ndarray          # (serial_tree_learner.cpp:837-846)


def grow_tree_partition_impl(
        arena_buf: jnp.ndarray,       # [C, cap] bf16 scratch (donated)
        bins_t: jnp.ndarray,          # [F, n] bf16/f32 feature-major bins
        grad: jnp.ndarray,            # [n] f32
        hess: jnp.ndarray,            # [n] f32
        row_leaf_init: jnp.ndarray,   # [n] int32: 0 in-bag, -1 out
        feature_mask: jnp.ndarray,    # [F] bool
        num_bins: jnp.ndarray,        # [F] int32
        default_bins: jnp.ndarray,    # [F] int32
        missing_types: jnp.ndarray,   # [F] int32
        params: SplitParams,
        monotone: Optional[jnp.ndarray] = None,
        penalty: Optional[jnp.ndarray] = None,
        cegb_coupled: Optional[jnp.ndarray] = None,
        cegb_used_init: Optional[jnp.ndarray] = None,
        is_categorical: Optional[jnp.ndarray] = None,
        bundle: Optional[BundleMaps] = None,
        *,
        max_leaves: int,
        max_depth: int = -1,
        max_bin: int,
        emit: str = "leaf_ids",
        full_bag: bool = False,
        max_cat_threshold: int = 32,
        axis_name: Optional[str] = None,
        hist_slots: int = 0,
        forced_splits: tuple = (),
        interpret: bool = False):
    """Grow one leaf-wise tree.

    bins_t holds the (possibly EFB-bundled) GROUP columns [G, n]; the
    per-feature arrays (feature_mask/num_bins/...) address ORIGINAL
    features and scans go through the bundle unbundling, exactly like the
    label engine (Dataset::FixHistogram, dataset.cpp:928-949).

    With axis_name (inside shard_map), rows are sharded per device: each
    device runs its own arena over local rows while histograms are
    psum'd, so split decisions are globally identical — the reference's
    DataParallelTreeLearner schedule (data_parallel_tree_learner.cpp:
    116-245) with the ReduceScatter/Allreduce pair collapsed into psum.

    Returns (TreeArrays, leaf_ids [n] int32, arena, truncated) — the arena
    scratch is returned so the caller can thread (and donate) it across
    trees instead of re-materializing a multi-GB zero buffer per
    iteration; `truncated` (bool scalar) reports growth stopped early by
    arena overflow so the driver can warn (raise tpu_arena_factor).
    """
    G, n = bins_t.shape               # group (arena) columns
    F = num_bins.shape[0]             # original features
    C, cap = arena_buf.shape
    if n >= (1 << 24):
        raise ValueError("partition engine supports n < 2^24 rows")
    if C != pp.arena_channels(G):
        raise ValueError("arena_buf channel dim mismatch")
    dtype = jnp.float32
    Fp = pp.feature_channels(G)
    L = max_leaves
    seg = partial(pp.segment_histogram, num_features=G, max_bin=max_bin,
                  interpret=interpret)
    part = partial(pp.partition_segment, interpret=interpret)

    # ---- arena assembly (into the reused scratch; stale columns beyond n
    # are never read: every kernel masks by segment counts).  Payloads are
    # split into bf16 planes (exact, see partition_pallas docstring) ------
    adt = pp.ARENA_DT
    chans = [bins_t.astype(adt)]
    if Fp > G:
        chans.append(jnp.zeros((Fp - G, n), adt))
    chans += [c[None] for c in pp.split_f32(grad)]
    chans += [c[None] for c in pp.split_f32(hess)]
    chans += [c[None] for c in pp.split_rowid(jnp.arange(n, dtype=jnp.int32))]
    if C > Fp + pp.N_AUX:
        chans.append(jnp.zeros((C - Fp - pp.N_AUX, n), adt))
    arena = jax.lax.dynamic_update_slice(
        arena_buf, jnp.concatenate(chans, axis=0), (0, 0))

    # ---- root: in-bag rows compacted to the segment at 0 -----------------
    # decision-mode partition calls never read the pred stream; they get
    # a tile-sized dummy (a [1, cap] buffer would be constant-sunk into
    # the while loop and re-materialized every split)
    pred_dummy = jnp.zeros((1, pp.TILE), dtype)
    if full_bag:
        # no bagging: every row is in-bag, the root segment IS the
        # assembled arena prefix — skip the O(n) compaction pass and the
        # OOB dump region entirely
        root_c = jnp.int32(n)
        cursor0 = jnp.int32(_align(n, pp.TILE) + pp.TILE)
    else:
        in_bag = (row_leaf_init == 0)
        pred0 = jnp.pad(in_bag.astype(dtype), (0, cap - n))[None, :]
        oob_dst = _align(n, pp.TILE)
        # fused compaction + in-bag (stream A) histogram: the root
        # histogram covers every row the pass reads anyway, so here the
        # fusion is pure saving (one full-n re-read + a launch)
        arena, counts0, root_hist_b = part(
            arena, pred0, jnp.int32(0), jnp.int32(n),
            jnp.int32(0), jnp.int32(oob_dst), hist_stream=0,
            num_features=G, max_bin=max_bin)
        root_c = counts0[0]
        cursor0 = jnp.int32(oob_dst + _align(n, pp.TILE))  # oob dump space

    if full_bag:
        root_hist = seg(arena, jnp.int32(0), root_c)
    else:
        root_hist = root_hist_b.astype(dtype)
    root_c_local = root_c
    if axis_name is not None:
        # DP: one histogram allreduce; global sums/counts fall out of it
        root_hist = jax.lax.psum(root_hist, axis_name)
        root_c = jax.lax.psum(root_c, axis_name)
    root_g = jnp.sum(root_hist[0, :, 0])
    root_h = jnp.sum(root_hist[0, :, 1])

    def unbundle(hist, sum_g, sum_h, cnt):
        from .grow import unbundle_hist
        return unbundle_hist(hist, sum_g, sum_h, cnt, bundle, default_bins)

    # The numerical best-split scan runs as ONE Pallas launch for both
    # children (ops/split_pallas.py) — the XLA op chain was ~0.45 ms of
    # pure dispatch latency per split, the largest single line item in
    # the round-4 profile.  Categorical datasets keep the XLA path.
    use_scan_kernel = is_categorical is None
    fvec_base = sp_pl.build_feature_statics(
        num_bins, default_bins, missing_types,
        monotone=monotone, penalty=penalty, feature_mask=feature_mask,
        children=2) if use_scan_kernel else None

    def pair_best_split(hist2, sg2, sh2, cnt2_, depth, used, mn2, mx2):
        """Best split of BOTH children: [2, ...] stacked inputs ->
        (left SplitResult, right SplitResult)."""
        cegb_pen = None
        if cegb_coupled is not None and used is not None:
            cegb_pen = jnp.where(used, 0.0, cegb_coupled)
        if use_scan_kernel:
            h2 = jax.vmap(lambda hh, gg, hs, cc: unbundle(hh, gg, hs, cc))(
                hist2, sg2, sh2, cnt2_)
            fvec = fvec_base
            if cegb_pen is not None:
                fvec = fvec.at[:, sp_pl._CEGBF].set(
                    jnp.concatenate([cegb_pen, cegb_pen]).astype(jnp.float32))
            pf2 = sp_pl.best_splits_pallas(
                h2, sg2, sh2, cnt2_, fvec, params,
                min_constraints=(mn2 if monotone is not None else None),
                max_constraints=(mx2 if monotone is not None else None),
                interpret=interpret)
            depth_ok = (max_depth <= 0) | (depth < max_depth)

            def finish(i):
                pf = sp_pl.index_per_feature(pf2, i)
                res = select_best_feature(pf)
                blocked = (res.feature < 0) | ~depth_ok
                return res._replace(
                    gain=jnp.where(blocked, K_MIN_SCORE, res.gain),
                    feature=jnp.where(depth_ok, res.feature, -1))
            return finish(0), finish(1)
        both = jax.vmap(lambda hh, gg, hs2, cc, mn, mx: leaf_best_split(
            hh, gg, hs2, cc, depth, used=used, minc=mn, maxc=mx))(
            hist2, sg2, sh2, cnt2_, mn2, mx2)
        return _index_split(both, 0), _index_split(both, 1)

    def leaf_best_split(hist, sum_g, sum_h, cnt, depth, used=None,
                        minc=None, maxc=None):
        cegb_pen = None
        if cegb_coupled is not None and used is not None:
            cegb_pen = jnp.where(used, 0.0, cegb_coupled)
        mn = mx = None
        if monotone is not None and minc is not None:
            mn = jnp.broadcast_to(jnp.asarray(minc, dtype), (F,))
            mx = jnp.broadcast_to(jnp.asarray(maxc, dtype), (F,))
        hist = unbundle(hist, sum_g, sum_h, cnt)
        if use_scan_kernel:
            # same single-launch scan as the body splits: the ROOT split
            # must come from the identical kernel or last-ulp prefix-sum
            # association diffs could pick a different first split than
            # the label engine
            fvec = sp_pl.build_feature_statics(
                num_bins, default_bins, missing_types, monotone=monotone,
                penalty=penalty, feature_mask=feature_mask,
                cegb_feature_penalty=cegb_pen, children=1)
            pf1 = sp_pl.best_splits_pallas(
                hist[None], jnp.reshape(sum_g, (1,)),
                jnp.reshape(sum_h, (1,)), jnp.reshape(cnt, (1,)), fvec,
                params,
                min_constraints=None if mn is None else mn[:1],
                max_constraints=None if mx is None else mx[:1],
                interpret=interpret)
            pf = sp_pl.index_per_feature(pf1, 0)
        elif is_categorical is None:
            pf = best_split_per_feature(hist, sum_g, sum_h, cnt, num_bins,
                                        default_bins, missing_types, params,
                                        monotone=monotone, penalty=penalty,
                                        min_constraints=mn,
                                        max_constraints=mx,
                                        feature_mask=feature_mask,
                                        cegb_feature_penalty=cegb_pen)
        else:
            pf = best_split_per_feature_mixed(
                hist, sum_g, sum_h, cnt, num_bins, default_bins,
                missing_types, is_categorical, params,
                monotone=monotone, penalty=penalty,
                feature_mask=feature_mask,
                min_constraints=mn, max_constraints=mx,
                cegb_feature_penalty=cegb_pen,
                max_cat_threshold=max_cat_threshold)
        res = select_best_feature(pf)
        depth_ok = (max_depth <= 0) | (depth < max_depth)
        blocked = (res.feature < 0) | ~depth_ok
        return res._replace(gain=jnp.where(blocked, K_MIN_SCORE, res.gain),
                            feature=jnp.where(depth_ok, res.feature, -1))

    tree = empty_tree(L, dtype,
                      cat_bins=(max_bin if is_categorical is not None else 0))
    tree = tree._replace(leaf_count=tree.leaf_count.at[0].set(root_c))
    cegb_used0 = (cegb_used_init if cegb_used_init is not None
                  else jnp.zeros(F, bool))
    ninf = jnp.asarray(-jnp.inf, dtype)
    pinf = jnp.asarray(jnp.inf, dtype)
    root_split = leaf_best_split(root_hist, root_g, root_h, root_c,
                                 jnp.asarray(0, jnp.int32), used=cegb_used0,
                                 minc=ninf, maxc=pinf)

    # histogram slot cache: K < L spills by LRU (hist_slots; 0 = one slot
    # per leaf, never spills — leaf-indexed, no lookup machinery traced)
    K = max(min(hist_slots, L), 4) if hist_slots and hist_slots > 0 else L
    pooled = K < L
    if forced_splits and pooled:
        raise ValueError("forced_splits require the dense histogram cache "
                         "(hist_slots=0): the injection indexes it by leaf")
    hist_cache = jnp.zeros((K,) + root_hist.shape, dtype).at[0].set(root_hist)
    if pooled:
        slot_leaf0 = jnp.full(K, -1, jnp.int32).at[0].set(0)
        slot_tick0 = jnp.zeros(K, jnp.int32).at[0].set(1)
    else:
        slot_leaf0 = jnp.zeros(1, jnp.int32)    # placeholders (untraced)
        slot_tick0 = jnp.zeros(1, jnp.int32)
    split_cache = SplitResult(*[
        None if v is None else
        jnp.zeros((L,) + jnp.shape(jnp.asarray(v)), jnp.asarray(v).dtype)
        for v in root_split])
    split_cache = _stack_split(root_split, split_cache, 0)
    split_cache = split_cache._replace(
        gain=split_cache.gain.at[1:].set(K_MIN_SCORE))

    state = PartState(
        tree=tree, arena=arena,
        leaf_start=jnp.zeros(L, jnp.int32),
        leaf_local=jnp.zeros(L, jnp.int32).at[0].set(root_c_local),
        cursor=cursor0,
        hist_cache=hist_cache, slot_leaf=slot_leaf0, slot_tick=slot_tick0,
        tick=jnp.asarray(2, jnp.int32),
        split_cache=split_cache,
        done=jnp.asarray(False), cegb_used=cegb_used0,
        truncated=jnp.asarray(False),
        leaf_min=jnp.full(L, ninf, dtype),
        leaf_max=jnp.full(L, pinf, dtype))

    def cond(state: PartState):
        return (~state.done) & (state.tree.num_leaves < L)

    def body(state: PartState) -> PartState:
        # The arena flows UNCONDITIONALLY through the (aliased) partition
        # kernel: a lax.cond keeping the old arena value live on the
        # not-taken path would force XLA to copy the multi-GB buffer every
        # split.  When no split applies (done, or the bump allocator is
        # full) the partition degenerates to cnt=0 — a no-op pass — and the
        # small state is masked instead.
        best_leaf = jnp.argmax(state.split_cache.gain).astype(jnp.int32)
        sp = _index_split(state.split_cache, best_leaf)
        no_split = sp.gain <= K_MIN_SCORE

        tree = state.tree
        nl = tree.num_leaves
        node = nl - 1
        new_leaf = nl
        feat = jnp.maximum(sp.feature, 0)
        thr = sp.threshold

        left_smaller = sp.left_count <= sp.right_count
        small_cnt = jnp.minimum(sp.left_count, sp.right_count)

        s0 = state.leaf_start[best_leaf]
        cntP_local = state.leaf_local[best_leaf]
        # bump-allocator overflow: stop growing this tree (the arena
        # budget covers balanced trees; pathological shapes truncate —
        # the flag is surfaced so the driver can warn the user to raise
        # tpu_arena_factor).  Serial: the smaller-child count is exact.
        # Data-parallel: the LOCAL smaller-child size is only known after
        # the kernel runs, so the bound is the local parent size; the
        # flag is all-reduced so every shard truncates together.
        if axis_name is None:
            need_bound = _align(small_cnt, ALLOC)
        else:
            need_bound = _align(cntP_local, ALLOC)
        overflow = (~no_split) & (state.cursor + need_bound + pp.TILE > cap)
        if axis_name is not None:
            overflow = jax.lax.psum(overflow.astype(jnp.int32),
                                    axis_name) > 0
        no_split = no_split | overflow

        cntP = jnp.where(no_split, 0, cntP_local)
        dstB = state.cursor

        if pooled:
            # parent histogram: slot-cache lookup (HistogramPool::Get),
            # with a recompute from the parent's STILL-INTACT segment on
            # miss — this must run before the partition overwrites the
            # segment.  The recompute kernel degenerates to cnt=0 (free)
            # on a hit.
            in_slot = state.slot_leaf == best_leaf
            found = jnp.any(in_slot)
            pslot = jnp.argmax(in_slot).astype(jnp.int32)
            recomputed = seg(state.arena, s0,
                             jnp.where(found | no_split, 0, cntP_local))
            # under DP the recompute's allreduce is BATCHED with the
            # smaller-child histogram's below (one collective per split
            # even in pooled mode); only the kernel must run pre-split
        else:
            # dense cache (one slot per leaf): direct index, no extra
            # kernel or collective on the split critical path
            parent_hist = state.hist_cache[best_leaf]

        # the go-left decision is evaluated INSIDE the kernel via a
        # [1, B] mask vector over arena bin values — built here to encode
        # numerical threshold + missing direction (NumericalDecision,
        # tree.h:429-465), categorical bitsets (CategoricalDecision,
        # tree.h:259-273) and EFB bundle-local ranges uniformly.  An
        # XLA-side per-row predicate would cost an O(cap) pass per split.
        # Stream A (in place over the parent) takes the LARGER child:
        # go_left XOR left_smaller == "row goes to the larger side".
        bv = jnp.arange(256, dtype=jnp.int32)
        if bundle is None:
            chan = feat
            fbin = bv
        else:
            chan = bundle.feat_col[feat]
            inside = (bv >= bundle.feat_lo[feat]) & (bv < bundle.feat_hi[feat])
            fbin = jnp.where(inside, bv - bundle.feat_shift[feat],
                             default_bins[feat])
        mt = missing_types[feat]
        db = default_bins[feat]
        mb = num_bins[feat] - 1
        is_missing = ((mt == MISSING_ZERO) & (fbin == db)) | \
                     ((mt == MISSING_NAN) & (fbin == mb))
        go_left = jnp.where(is_missing, sp.default_left,
                            fbin <= thr)
        if is_categorical is not None:
            cm = jnp.pad(sp.cat_mask.astype(bool),
                         (0, 256 - sp.cat_mask.shape[0]))
            go_left = jnp.where(is_categorical[feat],
                                cm[jnp.clip(fbin, 0, 255)], go_left)
        decision = (chan, go_left.astype(jnp.float32),
                    left_smaller.astype(jnp.int32))
        # FUSED with the smaller-child histogram: the round-4 bandwidth
        # profile (tools/kernel_ablate.py) showed both kernels are
        # HBM-bound on this chip (~40 GB/s practical ceiling, far below
        # the MXU's appetite), so the fused pass's extra radix FLOPs
        # over the whole parent stream are hidden under the DMA time
        # while the separate kernel's re-read of the compacted child
        # (O(small) bytes) is pure added traffic.  Stream B is always
        # the smaller child (the xr choreography routes the larger side
        # in place), so hist_stream=1.
        arena, counts, small_hist = part(
            state.arena, pred_dummy, s0, cntP, s0, dstB,
            decision=decision, hist_stream=1,
            num_features=G, max_bin=max_bin)
        small_hist = jnp.where(no_split, jnp.zeros_like(small_hist),
                               small_hist).astype(dtype)
        if axis_name is not None:
            # DP: ONE collective per split — the smaller child's histogram
            # allreduce (the sibling still comes from subtraction, §3.4.2);
            # in pooled mode the parent recompute rides the same allreduce
            if pooled:
                both_h = jax.lax.psum(jnp.stack([small_hist, recomputed]),
                                      axis_name)
                small_hist, recomputed = both_h[0], both_h[1]
            else:
                small_hist = jax.lax.psum(small_hist, axis_name)
        if pooled:
            parent_hist = jnp.where(found, state.hist_cache[pslot],
                                    recomputed.astype(dtype))
        large_hist = parent_hist - small_hist
        left_hist = jnp.where(left_smaller, small_hist, large_hist)
        right_hist = jnp.where(left_smaller, large_hist, small_hist)
        if pooled:
            # store both children: the parent's slot (if cached) is
            # reused for the left child, the right child evicts the
            # least-recently-written slot (HistogramPool::Move + LRU)
            slotL = jnp.where(found, pslot,
                              jnp.argmin(state.slot_tick).astype(jnp.int32))
            tickL = state.slot_tick.at[slotL].set(state.tick)
            slotR = jnp.argmin(tickL).astype(jnp.int32)
            hist_cache = state.hist_cache.at[slotL].set(left_hist)
            hist_cache = hist_cache.at[slotR].set(right_hist)
            slot_leaf = state.slot_leaf.at[slotL].set(best_leaf)
            slot_leaf = slot_leaf.at[slotR].set(new_leaf)
            slot_tick = tickL.at[slotR].set(state.tick + 1)
            tick = state.tick + 2
        else:
            hist_cache = state.hist_cache.at[best_leaf].set(left_hist)
            hist_cache = hist_cache.at[new_leaf].set(right_hist)
            slot_leaf, slot_tick, tick = (state.slot_leaf, state.slot_tick,
                                          state.tick)

        leaf_start = state.leaf_start.at[best_leaf].set(
            jnp.where(left_smaller, dstB, s0))
        leaf_start = leaf_start.at[new_leaf].set(
            jnp.where(left_smaller, s0, dstB))
        leaf_local = state.leaf_local.at[best_leaf].set(
            jnp.where(left_smaller, counts[1], counts[0]))
        leaf_local = leaf_local.at[new_leaf].set(
            jnp.where(left_smaller, counts[0], counts[1]))
        cursor = dstB + _align(counts[1], ALLOC)

        # -- tree bookkeeping (Tree::Split, tree.h:393-423) -------------
        parent_of = tree.leaf_parent[best_leaf]
        was_left = jnp.where(parent_of >= 0,
                             tree.left_child[parent_of] == ~best_leaf,
                             False)
        left_child = jnp.where(
            (parent_of >= 0) & was_left,
            tree.left_child.at[parent_of].set(node), tree.left_child)
        right_child = jnp.where(
            (parent_of >= 0) & ~was_left,
            tree.right_child.at[parent_of].set(node), tree.right_child)
        depth = tree.leaf_depth[best_leaf]
        new_is_cat = tree.is_cat
        new_cat_mask = tree.cat_mask
        if is_categorical is not None:
            new_is_cat = new_is_cat.at[node].set(is_categorical[feat])
            new_cat_mask = new_cat_mask.at[node].set(sp.cat_mask)
        tree = tree._replace(
            is_cat=new_is_cat,
            cat_mask=new_cat_mask,
            split_feature=tree.split_feature.at[node].set(feat),
            threshold_bin=tree.threshold_bin.at[node].set(thr),
            default_left=tree.default_left.at[node].set(sp.default_left),
            missing_type=tree.missing_type.at[node].set(
                missing_types[feat]),
            left_child=left_child.at[node].set(~best_leaf),
            right_child=right_child.at[node].set(~new_leaf),
            split_gain=tree.split_gain.at[node].set(sp.gain.astype(dtype)),
            internal_value=tree.internal_value.at[node].set(
                tree.leaf_value[best_leaf]),
            internal_count=tree.internal_count.at[node].set(
                sp.left_count + sp.right_count),
            leaf_value=tree.leaf_value.at[best_leaf].set(
                sp.left_output.astype(dtype)).at[new_leaf].set(
                sp.right_output.astype(dtype)),
            leaf_count=tree.leaf_count.at[best_leaf].set(
                sp.left_count).at[new_leaf].set(sp.right_count),
            leaf_parent=tree.leaf_parent.at[best_leaf].set(node)
                .at[new_leaf].set(node),
            leaf_depth=tree.leaf_depth.at[best_leaf].set(depth + 1)
                .at[new_leaf].set(depth + 1),
            num_leaves=nl + 1,
        )

        # monotone mid-constraint propagation (serial_tree_learner.cpp:
        # 837-846); categorical splits never carry monotone constraints
        minP, maxP = state.leaf_min[best_leaf], state.leaf_max[best_leaf]
        minL, maxL, minR, maxR = minP, maxP, minP, maxP
        leaf_min, leaf_max = state.leaf_min, state.leaf_max
        if monotone is not None:
            mono_t = monotone[feat].astype(jnp.int32)
            if is_categorical is not None:
                mono_t = jnp.where(is_categorical[feat], 0, mono_t)
            mid = ((sp.left_output + sp.right_output) / 2).astype(dtype)
            maxL = jnp.where(mono_t > 0, mid, maxP)
            minR = jnp.where(mono_t > 0, mid, minP)
            minL = jnp.where(mono_t < 0, mid, minP)
            maxR = jnp.where(mono_t < 0, mid, maxP)
            leaf_min = leaf_min.at[best_leaf].set(minL).at[new_leaf].set(minR)
            leaf_max = leaf_max.at[best_leaf].set(maxL).at[new_leaf].set(maxR)

        used2 = state.cegb_used.at[feat].set(True)
        # ONE scan over both children (single Pallas launch on the
        # numerical path, vmapped XLA chain otherwise)
        lsp, rsp = pair_best_split(
            jnp.stack([left_hist, right_hist]),
            jnp.stack([sp.left_sum_gradient, sp.right_sum_gradient]),
            jnp.stack([sp.left_sum_hessian, sp.right_sum_hessian]),
            jnp.stack([sp.left_count, sp.right_count]),
            depth + 1, used2,
            jnp.stack([jnp.asarray(minL, dtype), jnp.asarray(minR, dtype)]),
            jnp.stack([jnp.asarray(maxL, dtype), jnp.asarray(maxR, dtype)]))
        split_cache = _stack_split(lsp, state.split_cache, best_leaf)
        split_cache = _stack_split(rsp, split_cache, new_leaf)

        # merge: arena is already unchanged when no_split (cnt=0 pass);
        # mask every small field back to its previous value
        keep = no_split

        def sel(old_v, new_v):
            if old_v is None:
                return None
            return jnp.where(keep, old_v, new_v)

        tree = TreeArrays(*[sel(o, nn) for o, nn in
                            zip(state.tree, tree)])
        split_cache = SplitResult(*[sel(o, nn) for o, nn in
                                    zip(state.split_cache, split_cache)])
        return PartState(
            tree=tree, arena=arena,
            leaf_start=sel(state.leaf_start, leaf_start),
            leaf_local=sel(state.leaf_local, leaf_local),
            cursor=sel(state.cursor, cursor),
            hist_cache=sel(state.hist_cache, hist_cache),
            slot_leaf=sel(state.slot_leaf, slot_leaf),
            slot_tick=sel(state.slot_tick, slot_tick),
            tick=sel(state.tick, tick),
            split_cache=split_cache,
            done=keep, cegb_used=sel(state.cegb_used, used2),
            truncated=state.truncated | overflow,
            leaf_min=sel(state.leaf_min, leaf_min),
            leaf_max=sel(state.leaf_max, leaf_max))

    # Forced splits first (trace-time unrolled, same scheme as the label
    # engine: inject a +inf-gain forced result into the split cache and
    # run one standard body step; a static->dynamic leaf map abandons
    # invalid subtrees — ForceSplits, serial_tree_learner.cpp:593-751).
    # NOTE: the dense-cache path indexes hist_cache by leaf id; forced
    # splits require hist_slots == 0 (the driver only offers them there).
    if forced_splits:
        from .grow import build_forced_candidate
        leafmap = jnp.full((len(forced_splits) + 1,), -1,
                           jnp.int32).at[0].set(0)
        for i, (f_leaf, f_feat, f_thr, f_dl) in enumerate(forced_splits):
            if i >= L - 1:
                break
            dyn_leaf = leafmap[f_leaf]
            safe_leaf = jnp.maximum(dyn_leaf, 0)
            fsp = build_forced_candidate(
                state.hist_cache[safe_leaf],
                state.tree.leaf_count[safe_leaf],
                f_feat, f_thr, f_dl, unbundle,
                num_bins, default_bins, missing_types, params,
                cat_width=(state.split_cache.cat_mask.shape[1]
                           if state.split_cache.cat_mask is not None else 0))
            pre_valid = (dyn_leaf >= 0) & (fsp.gain > K_MIN_SCORE) & \
                        (state.tree.num_leaves < L)
            # Unlike the label engine, the merge must NOT select over the
            # arena (a [C, cap] where would force a copy alongside the
            # aliased kernel).  Instead an INVALID entry masks every gain
            # in the injected cache to K_MIN so body() itself no-ops
            # (cnt=0 kernel pass, arena genuinely untouched, small state
            # kept) and stepped flows through unconditionally; only the
            # split cache must be restored afterwards (the no-op path
            # would otherwise keep the masked gains and end growth).
            inj = _stack_split(fsp, state.split_cache, safe_leaf)
            inj = inj._replace(gain=jnp.where(
                pre_valid, inj.gain,
                jnp.full_like(inj.gain, K_MIN_SCORE)))
            saved_cache = state.split_cache
            prev_leaves = state.tree.num_leaves
            dyn_new = prev_leaves
            stepped = body(state._replace(split_cache=inj))
            # the split may ALSO no-op on arena overflow inside body —
            # gate the leaf map on whether it actually applied, so an
            # abandoned entry's forced subtree is dropped
            applied = stepped.tree.num_leaves == prev_leaves + 1

            def _selc(new_v, old_v):
                if new_v is None:
                    return None
                return jnp.where(applied, new_v, old_v)

            state = stepped._replace(
                done=jnp.asarray(False),
                split_cache=SplitResult(*[
                    _selc(nn, oo) for nn, oo in
                    zip(stepped.split_cache, saved_cache)]))
            leafmap = leafmap.at[i + 1].set(jnp.where(applied, dyn_new, -1))
            # on failure also unmap the target: the only later entry that
            # references static id f_leaf is this entry's LEFT-child
            # entry, which must be abandoned with the right subtree
            leafmap = leafmap.at[f_leaf].set(
                jnp.where(applied, dyn_leaf, -1))

    state = jax.lax.while_loop(cond, body, state)

    # ---- recover per-row outputs from the final segments -----------------
    # The compact kernel streams ONLY the live segments (O(n) work,
    # independent of cap — the old step-function recovery paid three
    # cumsums plus a scatter over the whole ~6n-column arena) and emits a
    # dense (rowid, value) stream; one n-sized scatter finishes the job.
    tree = state.tree
    capn = -(-n // pp.TILE) * pp.TILE + L * pp.TILE
    vals = (tree.leaf_value.astype(jnp.float32) if emit == "score"
            else jnp.arange(L, dtype=jnp.int32).astype(jnp.float32))
    stream, used = pp.compact_segments(
        state.arena, state.leaf_start, state.leaf_local, vals,
        tree.num_leaves, n, G, capn, interpret=interpret)
    # positions >= used are never written by the kernel (garbage, not
    # dummy) — mask them to the dummy rowid before the scatter
    written = jnp.arange(capn, dtype=jnp.int32) < used[0]
    rid = jnp.where(written, stream[0].astype(jnp.int32), n)
    if emit == "score":
        # scatter each row's LEAF VALUE directly — the driver's separate
        # 255-table leaf_value[leaf_ids] gather is a pure serial-gather
        # cost on TPU and is skipped entirely
        delta = jnp.zeros(n + 1, dtype).at[rid].set(
            stream[1].astype(dtype), mode="drop")[:n]
        return tree, delta, state.arena, state.truncated
    leaf_ids = jnp.full(n + 1, -1, jnp.int32).at[rid].set(
        stream[1].astype(jnp.int32), mode="drop")[:n]
    return tree, leaf_ids, state.arena, state.truncated


grow_tree_partition = partial(jax.jit, static_argnames=(
    "max_leaves", "max_depth", "max_bin", "emit", "full_bag",
    "max_cat_threshold", "axis_name", "hist_slots", "forced_splits",
    "interpret"),
    donate_argnums=(0,))(grow_tree_partition_impl)
