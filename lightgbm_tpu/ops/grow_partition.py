"""Partition-engine leaf-wise tree growth (serial learner, TPU fast path).

The arena re-design of SerialTreeLearner::Train (reference
src/treelearner/serial_tree_learner.cpp:169-233): instead of the label
engine's per-split masked pass over all n rows (ops/grow.py), rows live
physically grouped by leaf in the feature-major bf16-plane arena of
ops/partition_pallas.py, so each split costs O(parent) to partition and
O(smaller_child) to histogram — the reference's asymptotics
(DataPartition::Split data_partition.hpp:108-160 + the smaller/larger
histogram choreography serial_tree_learner.cpp:360-437, with the sibling
recovered by subtraction, feature_histogram.hpp:67-73).

Segment allocation is a device-side bump allocator in 256-column units:
the larger child overwrites the parent segment in place, the smaller
child is appended at the cursor.  On overflow the tree simply stops
growing (a debug print fires; raise tpu_arena_factor) — the default
arena budget covers a balanced 255-leaf tree, and the GBDT driver falls
back to the label engine for configs that need full generality.

Supports categorical bitset splits, EFB-bundled datasets (both via the
go-left mask decision), forced splits (the same cache-injection scheme
as the label engine) and all three distributed learners (axis_name +
learner):

- "data":    rows sharded, local arenas, psum'd histograms — the
  DataParallelTreeLearner schedule (data_parallel_tree_learner.cpp:
  116-245) with ReduceScatter/Allreduce collapsed into psum;
- "feature": data replicated (every device has the full arena — the
  reference's FP learner replicates data too, feature_parallel_tree_
  learner.cpp:30-74), the best-split SEARCH sharded by features, winner
  synced with an all_gather of packed split rows (SyncUpGlobalBestSplit,
  parallel_tree_learner.h:186-209); the partition itself is local
  because every device holds all feature channels;
- "voting":  rows sharded + per-leaf top-k election so only the ~2k
  elected features' histograms ride the psum (PV-tree,
  voting_parallel_tree_learner.cpp:166-460).

Remaining restrictions vs the label engine (the GBDT driver
auto-selects): f32 only, max_bin <= 256, n < 2^24 (rowids ride three
byte planes exactly).
"""
from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from ..parallel import collective as coll
from . import partition_pallas as pp
from . import quantize as qz
from . import split_pallas as sp_pl
from .grow import MISSING_NAN, MISSING_ZERO, BundleMaps, TreeArrays
from .split import (K_MIN_SCORE, SplitParams,
                    best_split_per_feature_mixed, select_best_feature)

ALLOC = pp.FLUSH_W         # allocation granularity (columns)


def _align(x, unit):
    return (x + unit - 1) // unit * unit


class PartState(NamedTuple):
    """Packed grow-loop state: matrices instead of per-field arrays so
    each split is a handful of row scatters (see the packed-rows note in
    grow_tree_partition_impl)."""
    node_mat: jnp.ndarray          # [N, 16] f32 node table: feat, thr,
    #   default_left, missing_type, left_child, right_child, gain,
    #   internal_value, internal_count, is_cat, pad...
    leaf_mat: jnp.ndarray          # [L, 8] f32 leaf table: value, count,
    #   parent, depth, min, max, seg_start, seg_local (LOCAL lengths —
    #   differ from count under data-parallel sharding)
    node_cat: jnp.ndarray          # [N, cat_w] f32 0/1 left-going bins
    nl: jnp.ndarray                # int32 num_leaves
    arena: jnp.ndarray             # [C, cap] bf16
    cursor: jnp.ndarray            # int32 bump cursor (256-aligned)
    hist_cache: jnp.ndarray        # [K, G, B, 3] slot cache (HistogramPool,
    #   feature_histogram.hpp:646-818: K < L spills by LRU; a missed
    #   parent is recomputed from its still-intact segment)
    slot_leaf: jnp.ndarray         # [K] int32 leaf whose hist each slot holds
    slot_tick: jnp.ndarray         # [K] int32 write-recency for eviction
    tick: jnp.ndarray              # int32 monotone write counter
    split_cache: jnp.ndarray       # [L, ROW_W + cat_w] f32 packed rows
    done: jnp.ndarray
    cegb_used: jnp.ndarray         # [F] bool (CEGB coupled feature_used)
    truncated: jnp.ndarray         # bool: growth stopped by arena overflow


def grow_tree_partition_impl(
        arena_buf: jnp.ndarray,       # [C, cap] bf16 scratch (donated)
        bins_t: jnp.ndarray,          # [F, n] bf16/f32 feature-major bins
        grad: jnp.ndarray,            # [n] f32
        hess: jnp.ndarray,            # [n] f32
        row_leaf_init: jnp.ndarray,   # [n] int32: 0 in-bag, -1 out
        feature_mask: jnp.ndarray,    # [F] bool
        num_bins: jnp.ndarray,        # [F] int32
        default_bins: jnp.ndarray,    # [F] int32
        missing_types: jnp.ndarray,   # [F] int32
        params: SplitParams,
        monotone: Optional[jnp.ndarray] = None,
        penalty: Optional[jnp.ndarray] = None,
        cegb_coupled: Optional[jnp.ndarray] = None,
        cegb_used_init: Optional[jnp.ndarray] = None,
        is_categorical: Optional[jnp.ndarray] = None,
        bundle: Optional[BundleMaps] = None,
        *,
        max_leaves: int,
        max_depth: int = -1,
        max_bin: int,
        emit: str = "leaf_ids",
        full_bag: bool = False,
        max_cat_threshold: int = 32,
        axis_name: Optional[str] = None,
        learner: str = "data",
        num_machines: int = 1,
        top_k: int = 20,
        hist_slots: int = 0,
        forced_splits: tuple = (),
        pristine: bool = False,
        carried_root=None,            # traced col offset of an ALREADY-
        #   assembled root segment (carried-arena mode): bins/rowids AND
        #   score/label planes live at [carried_root, carried_root+n);
        #   assembly only refreshes the g/h planes there.  Requires
        #   full_bag; emit="carry" compacts the finished tree's segments
        #   to carry_dst for the next iteration's root.
        carry_dst=None,               # traced col offset for emit="carry"
        carried_bump0: int = 0,       # static first bump column (past
        #                               both root slots) in carried mode
        quantized: bool = False,      # static: grad/hess arrive as int8
        #   CODES (ops/quantize) riding TWO payload planes instead of six
        #   residue planes; histogram kernels run the 3-component radix
        #   and results are dequantized per-kernel via quant_scales
        quant_scales=None,            # traced (g_scale, h_scale) f32
        interpret: bool = False):
    """Grow one leaf-wise tree.

    bins_t holds the (possibly EFB-bundled) GROUP columns [G, n]; the
    per-feature arrays (feature_mask/num_bins/...) address ORIGINAL
    features and scans go through the bundle unbundling, exactly like the
    label engine (Dataset::FixHistogram, dataset.cpp:928-949).

    With axis_name (inside shard_map), rows are sharded per device: each
    device runs its own arena over local rows while histograms are
    psum'd, so split decisions are globally identical — the reference's
    DataParallelTreeLearner schedule (data_parallel_tree_learner.cpp:
    116-245) with the ReduceScatter/Allreduce pair collapsed into psum.

    Returns (TreeArrays, leaf_ids [n] int32, arena, truncated) — the arena
    scratch is returned so the caller can thread (and donate) it across
    trees instead of re-materializing a multi-GB zero buffer per
    iteration; `truncated` (bool scalar) reports growth stopped early by
    arena overflow so the driver can warn (raise tpu_arena_factor).
    """
    G, n = bins_t.shape               # group (arena) columns
    F = num_bins.shape[0]             # original features
    C, cap = arena_buf.shape
    if n >= (1 << 24):
        raise ValueError("partition engine supports n < 2^24 rows")
    if C != pp.arena_channels(G):
        raise ValueError("arena_buf channel dim mismatch")
    dist = axis_name is not None
    dp = dist and learner == "data"
    fp = dist and learner == "feature"
    vp = dist and learner == "voting"
    if fp and bundle is not None:
        raise ValueError("EFB-bundled datasets do not support the "
                         "feature-parallel learner (bundling is disabled "
                         "at dataset construction for it)")
    if fp and F % num_machines:
        raise ValueError(
            "feature-parallel requires num_features (%d) divisible by "
            "num_machines (%d); pad features first (ParallelGrower does)"
            % (F, num_machines))
    # quantized + distributed is legal since the Collective refactor:
    # callers agree code scales globally first (qz.global_scales — one
    # allreduce-max of the two per-tree maxima), after which the psum'd
    # integer histograms are exactly a single encoder's sums
    if quantized and quant_scales is None:
        raise ValueError("quantized=True requires quant_scales")
    dtype = jnp.float32
    Fp = pp.feature_channels(G)
    L = max_leaves
    seg = partial(pp.segment_histogram, num_features=G, max_bin=max_bin,
                  quantized=quantized, interpret=interpret)
    part = partial(pp.partition_segment, interpret=interpret)
    if quantized:
        _gs, _hs = quant_scales

        def deq(h):
            # integer code sums -> f32 (g, h, count); exact within the
            # qz.exact_rows() envelope (docs/Quantized.md)
            return qz.dequantize_hist(h, _gs, _hs)
    else:
        def deq(h):
            return h.astype(dtype)

    # ---- arena assembly --------------------------------------------------
    # Pristine layout (the driver's path): feature bins + rowid planes
    # were written ONCE per dataset by pp.init_pristine and pristine rows
    # are never overwritten (the first split's stream A is redirected to
    # the work region), so per-tree assembly only refreshes the six g/h
    # payload planes — 6/48 channels instead of a full rebuild.  Legacy
    # layout (pristine=False) rebuilds everything into the scratch; stale
    # columns beyond n are never read (kernels mask by segment counts).
    adt = pp.ARENA_DT
    n_al = _align(n, pp.TILE)
    carried = carried_root is not None
    if carried and (not full_bag or dist):
        raise ValueError("carried-arena mode requires full_bag serial")
    work0 = pp.pristine_work0(n) if pristine else 0
    if quantized:
        # TWO code planes at [Fp, Fp+2) (g_code, h_code as exact small
        # integers in bf16); planes Fp+2..Fp+5 go stale and are never
        # read — the 3-component radix stops at the count plane
        gh = pp.pack_code_planes(grad, hess)
    else:
        gh = jnp.concatenate(
            [c[None] for c in pp.split_f32(grad)]
            + [c[None] for c in pp.split_f32(hess)], axis=0)
    # full_bag quantized roots skip the XLA plane write entirely: the
    # fused root kernel below DMAs the fresh codes into the arena while
    # it streams the feature rows for the root histogram — one pass pays
    # for both (the per-iteration byte saving iteration_budget reports)
    fuse_root = quantized and full_bag
    if carried:
        # bins/rowids AND the score/label planes already sit at the
        # carried root (compacted there by the previous tree's
        # emit="carry"); only the g/h planes need this tree's gradients
        arena = (arena_buf if fuse_root else
                 jax.lax.dynamic_update_slice(
                     arena_buf, gh, (jnp.int32(Fp),
                                     jnp.asarray(carried_root, jnp.int32))))
    elif pristine:
        arena = (arena_buf if fuse_root else
                 jax.lax.dynamic_update_slice(arena_buf, gh, (Fp, 0)))
    else:
        chans = [bins_t.astype(adt)]
        if Fp > G:
            chans.append(jnp.zeros((Fp - G, n), adt))
        chans += [gh]
        if quantized:
            # keep the rowid planes at their fixed rows Fp+6..Fp+8
            chans.append(jnp.zeros((pp.N_AUX - 3 - gh.shape[0], n), adt))
        chans += [c[None] for c in
                  pp.split_rowid(jnp.arange(n, dtype=jnp.int32))]
        if C > Fp + pp.N_AUX:
            chans.append(jnp.zeros((C - Fp - pp.N_AUX, n), adt))
        arena = jax.lax.dynamic_update_slice(
            arena_buf, jnp.concatenate(chans, axis=0), (0, 0))

    # ---- root: in-bag rows compacted into one segment --------------------
    # decision-mode partition calls never read the pred stream; they get
    # a tile-sized dummy (a [1, cap] buffer would be constant-sunk into
    # the while loop and re-materialized every split)
    pred_dummy = jnp.zeros((1, pp.TILE), dtype)
    if full_bag:
        # no bagging: every row is in-bag, the root segment IS the
        # assembled prefix — skip the O(n) compaction pass and the
        # OOB dump region entirely
        root_c = jnp.int32(n)
        if carried:
            root_s0 = jnp.asarray(carried_root, jnp.int32)
            cursor0 = jnp.int32(carried_bump0)
        else:
            root_s0 = jnp.int32(0)
            cursor0 = jnp.int32(work0 + n_al if pristine else n_al + pp.TILE)
    else:
        in_bag = (row_leaf_init == 0)
        pred0 = jnp.pad(in_bag.astype(dtype), (0, cap - n))[None, :]
        # pristine: in-bag rows copied to the work region (pristine rows
        # intact for the next tree); legacy: compacted in place
        bag_dst = work0 if pristine else 0
        oob_dst = bag_dst + n_al
        # fused compaction + in-bag (stream A) histogram: the root
        # histogram covers every row the pass reads anyway, so here the
        # fusion is pure saving (one full-n re-read + a launch)
        arena, counts0, root_hist_b = part(
            arena, pred0, jnp.int32(0), jnp.int32(n),
            jnp.int32(bag_dst), jnp.int32(oob_dst), hist_stream=0,
            num_features=G, max_bin=max_bin, quantized=quantized)
        root_c = counts0[0]
        root_s0 = jnp.int32(bag_dst)
        cursor0 = jnp.int32(oob_dst + n_al)  # past the oob dump space

    if full_bag:
        if quantized:
            # fused mega-kernel (ISSUE 8 tentpole): ONE double-buffered
            # pass over the root segment writes the fresh code planes
            # AND accumulates the root histogram — replacing the XLA
            # plane update plus a separate full-read seg() launch.
            # Unlike the per-child fusion dead end below (the fh gate),
            # the root histogram covers every row the refresh touches
            # anyway, so this fusion is pure byte saving (the same
            # argument as the bagging hist_stream above).
            arena, root_hist = pp.fused_refresh_histogram(
                arena, gh, root_s0, root_c, num_features=G,
                max_bin=max_bin, interpret=interpret)
        else:
            root_hist = seg(arena, root_s0, root_c)
    else:
        root_hist = root_hist_b.astype(dtype)
    root_c_local = root_c
    if dp:
        # DP: one histogram allreduce; global sums/counts fall out of it.
        # The psum runs BEFORE dequantization: integer code sums reduce
        # exactly in f32, so the global quantized histogram is bitwise a
        # single encoder's sums (the module docstring's contract); the
        # unquantized histogram is f32 either way.
        root_hist = coll.psum(root_hist, axis_name)
        root_c = coll.psum(root_c, axis_name)
    root_hist = deq(root_hist)
    root_g = jnp.sum(root_hist[0, :, 0])
    root_h = jnp.sum(root_hist[0, :, 1])
    if vp:
        # voting keeps histograms LOCAL; only the scalar root stats ride
        # an allreduce (data_parallel_tree_learner.cpp:116-142)
        root_g = coll.psum(root_g, axis_name)
        root_h = coll.psum(root_h, axis_name)
        root_c = coll.psum(root_c, axis_name)

    def unbundle(hist, sum_g, sum_h, cnt):
        from .grow import unbundle_hist
        return unbundle_hist(hist, sum_g, sum_h, cnt, bundle, default_bins)

    # ---- packed split rows & tree state ---------------------------------
    # The while-loop body ran ~900 XLA ops per iteration when every
    # SplitResult / TreeArrays field was its own array (round-4 jaxpr
    # audit: 159 select_n, 50 scatter, 49 dynamic_slice, ...) — per-op
    # dispatch latency made that the biggest cost after the kernels.
    # Inside the loop a leaf's best split is ONE [ROW_W(+cat)] f32 row
    # (lane layout split_pallas._O*, produced in-kernel by the scan's
    # select stage), the node table and the leaf table are ONE matrix
    # each, so applying a split is a handful of row scatters instead of
    # ~45 per-field ones.  TreeArrays materializes once after the loop.
    RW = sp_pl.ROW_W
    cat_w = max_bin if is_categorical is not None else 0
    RWC = RW + cat_w
    NEGF = jnp.float32(sp_pl.NEG)
    NEG_GATE = jnp.float32(sp_pl.NEG_GATE)
    N = max(L - 1, 1)
    use_scan_kernel = is_categorical is None
    if fp:
        # contiguous per-shard feature slice (the analogue of the
        # bin-count-balanced shuffle, feature_parallel_tree_learner.cpp:
        # 30-49): each device SCANS only its own features; data (and so
        # histograms and partitions) are replicated
        f_local = F // num_machines
        _dev = coll.axis_index(axis_name).astype(jnp.int32)
        scan_feature_mask = feature_mask & (
            (jnp.arange(F, dtype=jnp.int32) // f_local) == _dev)
    else:
        scan_feature_mask = feature_mask
    fvec1 = fvec2 = None
    if use_scan_kernel:
        fvec1 = sp_pl.build_feature_statics(
            num_bins, default_bins, missing_types, monotone=monotone,
            penalty=penalty, feature_mask=scan_feature_mask, children=1)
        fvec2 = jnp.concatenate([fvec1, fvec1], axis=0)

    def _patch_cegb(fvec, used, children):
        if cegb_coupled is None or used is None:
            return fvec
        pen = jnp.where(used, 0.0, cegb_coupled).astype(jnp.float32)
        return fvec.at[:, sp_pl._CEGBF].set(
            jnp.concatenate([pen] * children) if children > 1 else pen)

    def _gate(rows, depth_ok):
        """Mask rows that can never apply (depth limit): gain -> NEG,
        feature -> -1 (the old leaf_best_split's blocked semantics)."""
        lane = jnp.arange(RWC, dtype=jnp.int32)[None, :]
        rows = jnp.where((lane == sp_pl._OG) & ~depth_ok, NEGF, rows)
        return jnp.where((lane == sp_pl._OF) & ~depth_ok, -1.0, rows)

    def _fp_sync(rows):
        """SyncUpGlobalBestSplit (parallel_tree_learner.h:186-209): each
        device scanned only its feature shard; all_gather the packed
        rows and keep the max-gain winner per child.  argmax first-hit =
        lowest shard = lowest feature id, the reference's tie-break."""
        allr = coll.all_gather(rows, axis_name)       # [d, CH, RWC]
        win = jnp.argmax(allr[:, :, sp_pl._OG], axis=0)  # [CH]
        return jnp.take_along_axis(allr, win[None, :, None], axis=0)[0]

    k_top = min(top_k, F)
    n_elect = min(2 * k_top, F)

    def _vote_rows(hist_l, sg, sh, cn, mn, mx):
        """PV-tree election (voting_parallel_tree_learner.cpp:166-460)
        over CH children in ONE all_gather + ONE psum: local scans with
        1/num_machines-rescaled min-data thresholds -> local top-k ->
        all_gather -> vote -> psum of the <=2k elected features'
        histograms -> global scan -> packed [CH, RWC] winner rows.

        hist_l [CH, G, B, 3] holds LOCAL-shard rows; sg/sh/cn [CH] are
        the GLOBAL child stats (they ride the packed split rows)."""
        CH = hist_l.shape[0]

        def _unb1(h):
            lg = jnp.sum(h[0, :, 0])
            lh = jnp.sum(h[0, :, 1])
            lc = jnp.sum(h[0, :, 2])
            return unbundle(h, lg, lh, lc), jnp.stack([lg, lh, lc])

        hu, locs = jax.vmap(_unb1)(hist_l)     # [CH, F, B, 3], [CH, 3]
        loc_cnt = jnp.round(locs[:, 2]).astype(jnp.int32)
        # locally-rescaled config (voting...cpp:50-57)
        lparams = params._replace(
            min_data_in_leaf=jnp.maximum(
                params.min_data_in_leaf // num_machines, 1),
            min_sum_hessian_in_leaf=(params.min_sum_hessian_in_leaf
                                     / num_machines))
        mn_a = None if monotone is None else mn
        mx_a = None if monotone is None else mx
        if use_scan_kernel:
            fvecCH = fvec1 if CH == 1 else fvec2
            pf_loc = sp_pl.best_splits_pallas(
                hu, locs[:, 0], locs[:, 1], loc_cnt, fvecCH, lparams,
                min_constraints=mn_a, max_constraints=mx_a,
                interpret=interpret)
            gains = pf_loc.gain                            # [CH, F]
        else:
            gains = jnp.stack([
                best_split_per_feature_mixed(
                    hu[i], locs[i, 0], locs[i, 1], loc_cnt[i],
                    num_bins, default_bins, missing_types,
                    is_categorical, lparams,
                    monotone=monotone, penalty=penalty,
                    feature_mask=scan_feature_mask,
                    min_constraints=(None if mn_a is None else
                                     jnp.broadcast_to(mn_a[i], (F,))),
                    max_constraints=(None if mx_a is None else
                                     jnp.broadcast_to(mx_a[i], (F,))),
                    max_cat_threshold=max_cat_threshold).gain
                for i in range(CH)])

        # local top-k -> Allgather (the LightSplitInfo allgather) ->
        # GlobalVoting; lax.top_k is stable so equal-vote ties break
        # toward the smaller feature id (voting...cpp:166-195)
        _, top_idx = jax.lax.top_k(gains, k_top)           # [CH, k]
        top_ok = jnp.take_along_axis(gains, top_idx, axis=1) > K_MIN_SCORE
        allt = coll.all_gather(top_idx, axis_name)      # [d, CH, k]
        allv = coll.all_gather(top_ok, axis_name)

        def _tally(t, v):
            return jnp.zeros(F, jnp.int32).at[t.reshape(-1)].add(
                v.reshape(-1).astype(jnp.int32))

        votes = jax.vmap(_tally, in_axes=(1, 1))(allt, allv)   # [CH, F]
        _, elected = jax.lax.top_k(votes, n_elect)
        elected = elected.astype(jnp.int32)                # [CH, n_elect]
        # psum of the elected features' histograms only — O(2k*B) bytes
        # instead of O(F*B) (CopyLocalHistogram + ReduceScatter)
        sel = jax.vmap(lambda h, e: jnp.take(h, e, axis=0))(hu, elected)
        glob = coll.psum(sel, axis_name)        # [CH, n_elect, B, 3]

        rows = []
        if use_scan_kernel:
            fv = jax.vmap(lambda e: fvec1[e])(elected).reshape(
                CH * n_elect, fvec1.shape[1])
            pf_g = sp_pl.best_splits_pallas(
                glob, sg, sh, cn, fv, params,
                min_constraints=mn_a, max_constraints=mx_a,
                interpret=interpret)
            for i in range(CH):
                res = select_best_feature(
                    sp_pl.index_per_feature(pf_g, i),
                    feature_index=elected[i])
                rows.append(sp_pl.pack_split_row(res, cat_width=cat_w))
        else:
            for i in range(CH):
                def _tk(a):
                    return None if a is None else jnp.take(a, elected[i],
                                                           axis=0)
                pf = best_split_per_feature_mixed(
                    glob[i], sg[i], sh[i], cn[i], _tk(num_bins),
                    _tk(default_bins), _tk(missing_types),
                    _tk(is_categorical), params,
                    monotone=_tk(monotone), penalty=_tk(penalty),
                    feature_mask=_tk(scan_feature_mask),
                    min_constraints=(None if mn_a is None else
                                     jnp.broadcast_to(mn_a[i], (n_elect,))),
                    max_constraints=(None if mx_a is None else
                                     jnp.broadcast_to(mx_a[i], (n_elect,))),
                    max_cat_threshold=max_cat_threshold)
                res = select_best_feature(pf, feature_index=elected[i])
                rows.append(sp_pl.pack_split_row(res, cat_width=cat_w))
        return jnp.stack(rows)

    def leaf_best_result(hist, sum_g, sum_h, cnt, used=None,
                         minc=None, maxc=None):
        """XLA SplitResult scan — categorical/mixed datasets only."""
        cegb_pen = None
        if cegb_coupled is not None and used is not None:
            cegb_pen = jnp.where(used, 0.0, cegb_coupled)
        mn = mx = None
        if monotone is not None and minc is not None:
            mn = jnp.broadcast_to(jnp.asarray(minc, dtype), (F,))
            mx = jnp.broadcast_to(jnp.asarray(maxc, dtype), (F,))
        hist = unbundle(hist, sum_g, sum_h, cnt)
        pf = best_split_per_feature_mixed(
            hist, sum_g, sum_h, cnt, num_bins, default_bins,
            missing_types, is_categorical, params,
            monotone=monotone, penalty=penalty,
            feature_mask=scan_feature_mask,
            min_constraints=mn, max_constraints=mx,
            cegb_feature_penalty=cegb_pen,
            max_cat_threshold=max_cat_threshold)
        return select_best_feature(pf)

    def single_best_row(hist, sum_g, sum_h, cnt, depth, used=None,
                        minc=None, maxc=None):
        depth_ok = (max_depth <= 0) | (depth < max_depth)
        if vp:
            rows = _vote_rows(
                hist[None], jnp.reshape(sum_g, (1,)),
                jnp.reshape(sum_h, (1,)),
                jnp.reshape(jnp.asarray(cnt, dtype), (1,)),
                None if minc is None else jnp.reshape(
                    jnp.asarray(minc, dtype), (1,)),
                None if maxc is None else jnp.reshape(
                    jnp.asarray(maxc, dtype), (1,)))
        elif use_scan_kernel:
            h1 = unbundle(hist, sum_g, sum_h, cnt)[None]
            mn1 = mx1 = None
            if monotone is not None and minc is not None:
                mn1 = jnp.reshape(jnp.asarray(minc, dtype), (1,))
                mx1 = jnp.reshape(jnp.asarray(maxc, dtype), (1,))
            rows = sp_pl.best_split_rows_pallas(
                h1, jnp.reshape(sum_g, (1,)), jnp.reshape(sum_h, (1,)),
                jnp.reshape(cnt, (1,)), _patch_cegb(fvec1, used, 1), params,
                min_constraints=mn1, max_constraints=mx1,
                interpret=interpret)
        else:
            res = leaf_best_result(hist, sum_g, sum_h, cnt, used=used,
                                   minc=minc, maxc=maxc)
            rows = sp_pl.pack_split_row(res, cat_width=cat_w)[None]
        if fp:
            rows = _fp_sync(rows)
        return _gate(rows, depth_ok)[0]

    def pair_best_rows(hist2, sg2, sh2, cnt2_, depth, used, mn2, mx2):
        """[2, RWC] packed best rows of both children — one kernel
        launch on the numerical path."""
        depth_ok = (max_depth <= 0) | (depth < max_depth)
        if vp:
            rows = _vote_rows(hist2, sg2, sh2, cnt2_,
                              mn2 if monotone is not None else None,
                              mx2 if monotone is not None else None)
        elif use_scan_kernel:
            h2 = jax.vmap(lambda hh, gg, hs, cc: unbundle(hh, gg, hs, cc))(
                hist2, sg2, sh2, cnt2_)
            rows = sp_pl.best_split_rows_pallas(
                h2, sg2, sh2, cnt2_, _patch_cegb(fvec2, used, 2), params,
                min_constraints=(mn2 if monotone is not None else None),
                max_constraints=(mx2 if monotone is not None else None),
                interpret=interpret)
        else:
            rows = jnp.stack([
                sp_pl.pack_split_row(
                    leaf_best_result(hist2[i], sg2[i], sh2[i], cnt2_[i],
                                     used=used, minc=mn2[i], maxc=mx2[i]),
                    cat_width=cat_w)
                for i in range(2)])
        if fp:
            rows = _fp_sync(rows)
        return _gate(rows, depth_ok)

    cegb_used0 = (cegb_used_init if cegb_used_init is not None
                  else jnp.zeros(F, bool))
    ninf = jnp.asarray(-jnp.inf, dtype)
    pinf = jnp.asarray(jnp.inf, dtype)
    root_row = single_best_row(root_hist, root_g, root_h, root_c,
                               jnp.asarray(0, jnp.int32), used=cegb_used0,
                               minc=ninf, maxc=pinf)

    # histogram slot cache: K < L spills by LRU (hist_slots; 0 = one slot
    # per leaf, never spills — leaf-indexed, no lookup machinery traced)
    K = max(min(hist_slots, L), 4) if hist_slots and hist_slots > 0 else L
    pooled = K < L
    if forced_splits and pooled:
        raise ValueError("forced_splits require the dense histogram cache "
                         "(hist_slots=0): the injection indexes it by leaf")
    hist_cache = jnp.zeros((K,) + root_hist.shape, dtype).at[0].set(root_hist)
    if pooled:
        slot_leaf0 = jnp.full(K, -1, jnp.int32).at[0].set(0)
        slot_tick0 = jnp.zeros(K, jnp.int32).at[0].set(1)
    else:
        slot_leaf0 = jnp.zeros(1, jnp.int32)    # placeholders (untraced)
        slot_tick0 = jnp.zeros(1, jnp.int32)
    split_cache0 = (jnp.zeros((L, RWC), dtype)
                    .at[:, sp_pl._OG].set(NEGF)
                    .at[:, sp_pl._OF].set(-1.0)
                    .at[0].set(root_row))
    # leaf_mat lanes: value, count, parent, depth, min, max, start, local
    leaf_mat0 = (jnp.zeros((L, 8), dtype)
                 .at[:, 2].set(-1.0)
                 .at[:, 4].set(-jnp.inf)
                 .at[:, 5].set(jnp.inf)
                 .at[0].set(jnp.stack([
                     jnp.asarray(0.0, dtype), root_c.astype(dtype),
                     jnp.asarray(-1.0, dtype), jnp.asarray(0.0, dtype),
                     ninf, pinf, root_s0.astype(dtype),
                     root_c_local.astype(dtype)])))

    state = PartState(
        node_mat=jnp.zeros((N, 16), dtype),
        leaf_mat=leaf_mat0,
        node_cat=jnp.zeros((N, cat_w), dtype),
        nl=jnp.asarray(1, jnp.int32),
        arena=arena, cursor=cursor0,
        hist_cache=hist_cache, slot_leaf=slot_leaf0, slot_tick=slot_tick0,
        tick=jnp.asarray(2, jnp.int32),
        split_cache=split_cache0,
        done=jnp.asarray(False), cegb_used=cegb_used0,
        truncated=jnp.asarray(False))

    def cond(state: PartState):
        return (~state.done) & (state.nl < L)

    def body(state: PartState) -> PartState:
        # The arena flows UNCONDITIONALLY through the (aliased) partition
        # kernel: a lax.cond keeping the old arena value live on the
        # not-taken path would force XLA to copy the multi-GB buffer every
        # split.  When no split applies (done, or the bump allocator is
        # full) the partition degenerates to cnt=0 — a no-op pass — and the
        # small state is masked instead.
        best_leaf = jnp.argmax(
            state.split_cache[:, sp_pl._OG]).astype(jnp.int32)
        row = state.split_cache[best_leaf]                     # [RWC]
        gain = row[sp_pl._OG]
        no_split = gain <= NEG_GATE

        nl = state.nl
        node = nl - 1
        new_leaf = nl
        feat = jnp.maximum(row[sp_pl._OF].astype(jnp.int32), 0)
        thr = row[sp_pl._OT].astype(jnp.int32)
        dl = row[sp_pl._ODL] > 0.5
        lg, lh = row[sp_pl._OLG], row[sp_pl._OLH]
        lc_f, lo = row[sp_pl._OLC], row[sp_pl._OLO]
        rg, rh = row[sp_pl._ORG], row[sp_pl._ORH]
        rc_f, ro = row[sp_pl._ORC], row[sp_pl._ORO]
        lc_i = lc_f.astype(jnp.int32)
        rc_i = rc_f.astype(jnp.int32)

        lrow = state.leaf_mat[best_leaf]                       # [8]
        old_value = lrow[0]
        parent_of = lrow[2].astype(jnp.int32)
        depth = lrow[3]
        minP, maxP = lrow[4], lrow[5]
        s0 = lrow[6].astype(jnp.int32)
        cntP_local = lrow[7].astype(jnp.int32)

        left_smaller = lc_i <= rc_i
        small_cnt = jnp.minimum(lc_i, rc_i)
        # bump-allocator overflow: stop growing this tree (the arena
        # budget covers balanced trees; pathological shapes truncate —
        # the flag is surfaced so the driver can warn the user to raise
        # tpu_arena_factor).  Serial: the smaller-child count is exact.
        # Data-parallel/voting: the LOCAL smaller-child size is only
        # known after the kernel runs, so the bound is the local parent
        # size; the flag is all-reduced so every shard truncates
        # together.  Feature-parallel replicates data, so counts (and
        # the overflow decision) are identical on every device.
        if axis_name is None or fp:
            need_bound = _align(small_cnt, ALLOC)
        else:
            need_bound = _align(cntP_local, ALLOC)
        overflow = (~no_split) & (state.cursor + need_bound + pp.TILE > cap)
        if dp or vp:
            overflow = coll.psum(overflow.astype(jnp.int32),
                                    axis_name) > 0
        no_split = no_split | overflow

        cntP = jnp.where(no_split, 0, cntP_local)
        dstB = state.cursor
        if pristine:
            # the pristine row block is read-only: the first split of the
            # root (s0 inside pristine) writes its larger child to the
            # start of the work region instead of in place
            dstA = jnp.where(s0 < work0, jnp.int32(work0), s0)
        else:
            dstA = s0

        if pooled:
            # parent histogram: slot-cache lookup (HistogramPool::Get),
            # with a recompute from the parent's STILL-INTACT segment on
            # miss — this must run before the partition overwrites the
            # segment.  The recompute kernel degenerates to cnt=0 (free)
            # on a hit.
            in_slot = state.slot_leaf == best_leaf
            found = jnp.any(in_slot)
            pslot = jnp.argmax(in_slot).astype(jnp.int32)
            recomputed = seg(state.arena, s0,
                             jnp.where(found | no_split, 0,
                                       cntP_local))
            # under DP the recompute's allreduce is BATCHED with the
            # smaller-child histogram's below (one collective per split
            # even in pooled mode); only the kernel must run pre-split
        else:
            # dense cache (one slot per leaf): direct index, no extra
            # kernel or collective on the split critical path
            parent_hist = state.hist_cache[best_leaf]

        # the go-left decision is evaluated INSIDE the kernel via a
        # [1, B] mask vector over arena bin values — built here to encode
        # numerical threshold + missing direction (NumericalDecision,
        # tree.h:429-465), categorical bitsets (CategoricalDecision,
        # tree.h:259-273) and EFB bundle-local ranges uniformly.  An
        # XLA-side per-row predicate would cost an O(cap) pass per split.
        # Stream A (in place over the parent) takes the LARGER child:
        # go_left XOR left_smaller == "row goes to the larger side".
        bv = jnp.arange(256, dtype=jnp.int32)
        if bundle is None:
            chan = feat
            fbin = bv
        else:
            chan = bundle.feat_col[feat]
            inside = (bv >= bundle.feat_lo[feat]) & (bv < bundle.feat_hi[feat])
            fbin = jnp.where(inside, bv - bundle.feat_shift[feat],
                             default_bins[feat])
        mt = missing_types[feat]
        db = default_bins[feat]
        mb = num_bins[feat] - 1
        is_missing = ((mt == MISSING_ZERO) & (fbin == db)) | \
                     ((mt == MISSING_NAN) & (fbin == mb))
        go_left = jnp.where(is_missing, dl, fbin <= thr)
        if is_categorical is not None:
            cm = jnp.pad(row[RW:] > 0.5, (0, 256 - cat_w))
            go_left = jnp.where(is_categorical[feat],
                                cm[jnp.clip(fbin, 0, 255)], go_left)
        decision = (chan, go_left.astype(jnp.float32),
                    left_smaller.astype(jnp.int32))
        # NOT fused with the histogram: slope-corrected round-4 profiling
        # (tools/kernel_slope.py — the earlier "fusion is free" reading
        # came from tunnel-fetch-biased microbenches) confirms the fused
        # pass pays the radix contraction over the WHOLE parent stream
        # (+6.9 ms/4M rows) while the separate kernel touches only the
        # compacted smaller child — O(small) beats O(parent) here.
        # Round 5 re-tested a PARENT-SIZE-GATED fusion (in-kernel fh
        # gate + small-parent fused path, partition_pallas fused_gate/
        # raw_hist): ~10% WORSE end-to-end — requesting the hist output
        # on every partition launch adds its buffer setup/writeback to
        # all ~254 splits, which costs more than the separate kernel's
        # fixed cost ever did.  Two launches stay the right shape here.
        arena, counts = part(state.arena, pred_dummy, s0, cntP, dstA, dstB,
                             decision=decision)
        small_hist = seg(arena, dstB, jnp.where(no_split, 0, counts[1]))
        if dp:
            # DP: ONE collective per split — the smaller child's histogram
            # allreduce (the sibling still comes from subtraction, §3.4.2);
            # in pooled mode the parent recompute rides the same allreduce.
            # Voting and feature-parallel skip this: voting keeps local
            # histograms (the election psums only elected features),
            # feature-parallel's histograms are replicated already.
            # As with the root, the psum reduces the raw (code-sum)
            # histograms so quantized DP stays bitwise-serial.
            if pooled:
                both_h = coll.psum(jnp.stack([small_hist, recomputed]),
                                      axis_name)
                small_hist, recomputed = both_h[0], both_h[1]
            else:
                small_hist = coll.psum(small_hist, axis_name)
        small_hist = deq(small_hist)
        if pooled:
            parent_hist = jnp.where(found, state.hist_cache[pslot],
                                    deq(recomputed).astype(dtype))
        large_hist = parent_hist - small_hist
        left_hist = jnp.where(left_smaller, small_hist, large_hist)
        right_hist = jnp.where(left_smaller, large_hist, small_hist)
        if pooled:
            # store both children: the parent's slot (if cached) is
            # reused for the left child, the right child evicts the
            # least-recently-written slot (HistogramPool::Move + LRU)
            slotL = jnp.where(found, pslot,
                              jnp.argmin(state.slot_tick).astype(jnp.int32))
            tickL = state.slot_tick.at[slotL].set(state.tick)
            slotR = jnp.argmin(tickL).astype(jnp.int32)
            hist_cache = state.hist_cache.at[slotL].set(left_hist)
            hist_cache = hist_cache.at[slotR].set(right_hist)
            slot_leaf = state.slot_leaf.at[slotL].set(best_leaf)
            slot_leaf = slot_leaf.at[slotR].set(new_leaf)
            slot_tick = tickL.at[slotR].set(state.tick + 1)
            tick = state.tick + 2
        else:
            hist_cache = state.hist_cache.at[best_leaf].set(left_hist)
            hist_cache = hist_cache.at[new_leaf].set(right_hist)
            slot_leaf, slot_tick, tick = (state.slot_leaf, state.slot_tick,
                                          state.tick)

        startL = jnp.where(left_smaller, dstB, dstA).astype(dtype)
        startR = jnp.where(left_smaller, dstA, dstB).astype(dtype)
        localL = jnp.where(left_smaller, counts[1], counts[0]).astype(dtype)
        localR = jnp.where(left_smaller, counts[0], counts[1]).astype(dtype)
        cursor = dstB + _align(counts[1], ALLOC)

        # monotone mid-constraint propagation (serial_tree_learner.cpp:
        # 837-846); categorical splits never carry monotone constraints
        minL, maxL, minR, maxR = minP, maxP, minP, maxP
        if monotone is not None:
            mono_t = monotone[feat].astype(jnp.int32)
            if is_categorical is not None:
                mono_t = jnp.where(is_categorical[feat], 0, mono_t)
            mid = ((lo + ro) / 2).astype(dtype)
            maxL = jnp.where(mono_t > 0, mid, maxP)
            minR = jnp.where(mono_t > 0, mid, minP)
            minL = jnp.where(mono_t < 0, mid, minP)
            maxR = jnp.where(mono_t < 0, mid, maxP)

        # -- tree bookkeeping (Tree::Split, tree.h:393-423): one node row
        # + two leaf rows + the parent's child-pointer fix-up ------------
        node_f = node.astype(dtype)
        safe_p = jnp.maximum(parent_of, 0)
        prow = state.node_mat[safe_p]
        was_left = prow[4] == -(best_leaf + 1).astype(dtype)
        node_mat = state.node_mat.at[safe_p, 4].set(
            jnp.where((parent_of >= 0) & was_left, node_f, prow[4]))
        node_mat = node_mat.at[safe_p, 5].set(
            jnp.where((parent_of >= 0) & ~was_left, node_f, prow[5]))
        is_cat_f = (is_categorical[feat].astype(dtype)
                    if is_categorical is not None
                    else jnp.asarray(0.0, dtype))
        nrow = jnp.concatenate([jnp.stack([
            feat.astype(dtype), thr.astype(dtype), dl.astype(dtype),
            missing_types[feat].astype(dtype),
            -(best_leaf + 1).astype(dtype), -(new_leaf + 1).astype(dtype),
            gain, old_value, lc_f + rc_f, is_cat_f]),
            jnp.zeros(6, dtype)])
        node_mat = node_mat.at[node].set(nrow)
        node_cat = state.node_cat
        if cat_w:
            node_cat = node_cat.at[node].set(row[RW:])

        lrow_l = jnp.stack([lo, lc_f, node_f, depth + 1, minL, maxL,
                            startL, localL])
        lrow_r = jnp.stack([ro, rc_f, node_f, depth + 1, minR, maxR,
                            startR, localR])
        leaf_mat = state.leaf_mat.at[best_leaf].set(lrow_l) \
                                 .at[new_leaf].set(lrow_r)

        used2 = state.cegb_used.at[feat].set(True)
        # ONE scan over both children (single Pallas launch incl. the
        # cross-feature select on the numerical path)
        rows2 = pair_best_rows(
            jnp.stack([left_hist, right_hist]),
            jnp.stack([lg, rg]), jnp.stack([lh, rh]),
            jnp.stack([lc_f, rc_f]), depth + 1, used2,
            jnp.stack([minL, minR]), jnp.stack([maxL, maxR]))
        split_cache = state.split_cache.at[best_leaf].set(rows2[0]) \
                                       .at[new_leaf].set(rows2[1])

        # merge: arena is already unchanged when no_split (cnt=0 pass);
        # mask every small field back to its previous value
        keep = no_split

        def sel(old_v, new_v):
            return jnp.where(keep, old_v, new_v)

        return PartState(
            node_mat=sel(state.node_mat, node_mat),
            leaf_mat=sel(state.leaf_mat, leaf_mat),
            node_cat=(sel(state.node_cat, node_cat) if cat_w
                      else state.node_cat),
            nl=sel(nl, nl + 1),
            arena=arena, cursor=sel(state.cursor, cursor),
            hist_cache=sel(state.hist_cache, hist_cache),
            slot_leaf=sel(state.slot_leaf, slot_leaf),
            slot_tick=sel(state.slot_tick, slot_tick),
            tick=sel(state.tick, tick),
            split_cache=sel(state.split_cache, split_cache),
            done=keep, cegb_used=sel(state.cegb_used, used2),
            truncated=state.truncated | overflow)

    # Forced splits first (trace-time unrolled, same scheme as the label
    # engine: inject a +inf-gain forced row into the split cache and
    # run one standard body step; a static->dynamic leaf map abandons
    # invalid subtrees — ForceSplits, serial_tree_learner.cpp:593-751).
    # NOTE: the dense-cache path indexes hist_cache by leaf id; forced
    # splits require hist_slots == 0 (the driver only offers them there).
    if forced_splits:
        from .grow import build_forced_candidate
        lane1 = jnp.arange(RWC, dtype=jnp.int32)
        leafmap = jnp.full((len(forced_splits) + 1,), -1,
                           jnp.int32).at[0].set(0)
        for i, (f_leaf, f_feat, f_thr, f_dl) in enumerate(forced_splits):
            if i >= L - 1:
                break
            dyn_leaf = leafmap[f_leaf]
            safe_leaf = jnp.maximum(dyn_leaf, 0)
            fsp = build_forced_candidate(
                state.hist_cache[safe_leaf],
                state.leaf_mat[safe_leaf, 1].astype(jnp.int32),
                f_feat, f_thr, f_dl, unbundle,
                num_bins, default_bins, missing_types, params,
                cat_width=cat_w)
            frow = sp_pl.pack_split_row(fsp, cat_width=cat_w)
            pre_valid = (dyn_leaf >= 0) & (fsp.gain > K_MIN_SCORE) & \
                        (state.nl < L)
            # An INVALID entry masks every gain in the injected cache to
            # NEG so body() itself no-ops (cnt=0 kernel pass, arena
            # genuinely untouched, small state kept); only the split
            # cache must be restored afterwards (the no-op path would
            # otherwise keep the masked gains and end growth).
            inj = state.split_cache.at[safe_leaf].set(frow)
            inj = jnp.where((lane1[None, :] == sp_pl._OG) & ~pre_valid,
                            NEGF, inj)
            saved_cache = state.split_cache
            prev_leaves = state.nl
            dyn_new = prev_leaves
            stepped = body(state._replace(split_cache=inj))
            # the split may ALSO no-op on arena overflow inside body —
            # gate the leaf map on whether it actually applied, so an
            # abandoned entry's forced subtree is dropped
            applied = stepped.nl == prev_leaves + 1
            state = stepped._replace(
                done=jnp.asarray(False),
                split_cache=jnp.where(applied, stepped.split_cache,
                                      saved_cache))
            leafmap = leafmap.at[i + 1].set(jnp.where(applied, dyn_new, -1))
            # on failure also unmap the target: the only later entry that
            # references static id f_leaf is this entry's LEFT-child
            # entry, which must be abandoned with the right subtree
            leafmap = leafmap.at[f_leaf].set(
                jnp.where(applied, dyn_leaf, -1))

    state = jax.lax.while_loop(cond, body, state)

    # ---- materialize TreeArrays from the packed tables -------------------
    nm, lm = state.node_mat, state.leaf_mat
    tree = TreeArrays(
        split_feature=nm[:, 0].astype(jnp.int32),
        threshold_bin=nm[:, 1].astype(jnp.int32),
        default_left=nm[:, 2] > 0.5,
        missing_type=nm[:, 3].astype(jnp.int32),
        left_child=nm[:, 4].astype(jnp.int32),
        right_child=nm[:, 5].astype(jnp.int32),
        split_gain=nm[:, 6].astype(dtype),
        internal_value=nm[:, 7].astype(dtype),
        internal_count=nm[:, 8].astype(jnp.int32),
        leaf_value=lm[:, 0].astype(dtype),
        leaf_count=lm[:, 1].astype(jnp.int32),
        leaf_parent=lm[:, 2].astype(jnp.int32),
        leaf_depth=lm[:, 3].astype(jnp.int32),
        num_leaves=state.nl,
        is_cat=nm[:, 9] > 0.5,
        cat_mask=state.node_cat > 0.5)

    if emit == "carry":
        # carried-arena boundary: compact the live segments (leaf-index
        # order, full channels incl. score/label planes) into the other
        # root slot — NO row-order recovery, NO sort; the caller updates
        # the score planes from leaf_value/leaf_count and roots the next
        # tree at carry_dst (per-row leaf values derive from
        # cumsum(leaf_count) over the same leaf order)
        arena2, used = pp.compact_carry(
            state.arena, lm[:, 6].astype(jnp.int32),
            lm[:, 7].astype(jnp.int32), state.nl,
            jnp.asarray(carry_dst, jnp.int32), interpret=interpret)
        return tree, used, arena2, state.truncated

    # ---- recover per-row outputs from the final segments -----------------
    # The compact kernel streams ONLY the live segments (O(n) work,
    # independent of cap — the old step-function recovery paid three
    # cumsums plus a scatter over the whole ~6n-column arena) and emits a
    # dense (rowid, value) stream; one n-sized scatter finishes the job.
    capn = -(-n // pp.TILE) * pp.TILE + L * pp.TILE
    vals = (lm[:, 0].astype(jnp.float32) if emit == "score"
            else jnp.arange(L, dtype=jnp.int32).astype(jnp.float32))
    stream, used = pp.compact_segments(
        state.arena, lm[:, 6].astype(jnp.int32), lm[:, 7].astype(jnp.int32),
        vals, state.nl, n, G, capn, interpret=interpret)
    # positions >= used are never written by the kernel (garbage, not
    # dummy) — mask them to the dummy rowid before the reorder
    written = jnp.arange(capn, dtype=jnp.int32) < used[0]
    rid = jnp.where(written, stream[0].astype(jnp.int32), n)
    if full_bag:
        # every rowid in [0, n) appears exactly once (segments partition
        # the full root segment), so a key/value sort puts the values in
        # row order directly — measured ~2x faster than the XLA scatter
        # (TPU scatters serialize; sort is a fast bitonic primitive)
        _, sv = jax.lax.sort((rid, stream[1]), num_keys=1)
        if emit == "score":
            return tree, sv[:n].astype(dtype), state.arena, state.truncated
        return (tree, jnp.round(sv[:n]).astype(jnp.int32), state.arena,
                state.truncated)
    if emit == "score":
        # scatter each row's LEAF VALUE directly — the driver's separate
        # 255-table leaf_value[leaf_ids] gather is a pure serial-gather
        # cost on TPU and is skipped entirely
        delta = jnp.zeros(n + 1, dtype).at[rid].set(
            stream[1].astype(dtype), mode="drop")[:n]
        return tree, delta, state.arena, state.truncated
    leaf_ids = jnp.full(n + 1, -1, jnp.int32).at[rid].set(
        stream[1].astype(jnp.int32), mode="drop")[:n]
    return tree, leaf_ids, state.arena, state.truncated


# donate_argnums=(0,): the arena is the only donatable input — every
# other large operand (bins_t, g/h, row_leaf_init) is resident by the
# driver's degrade contract: a failed partition call falls back to the
# label engine REUSING those same buffers (models/gbdt._run_partition),
# so donating them would hand the fallback deleted arrays on TPU.  The
# donation audit (obs/device.donation_audit) marks them resident rather
# than un-donated; lgbm_xla_undonated_bytes stays at the committed floor
# of zero for this executable.
grow_tree_partition = partial(jax.jit, static_argnames=(
    "max_leaves", "max_depth", "max_bin", "emit", "full_bag",
    "max_cat_threshold", "axis_name", "learner", "num_machines", "top_k",
    "hist_slots", "forced_splits", "pristine", "carried_bump0",
    "quantized", "interpret"),
    donate_argnums=(0,))(grow_tree_partition_impl)


# -- roofline cost model (obs/perf) -------------------------------------- #
from ..obs.perf import KernelCost, cost_model  # noqa: E402


@cost_model("tree/iteration")
def _cost_tree_iteration(rows: int, features: int, max_bin: int,
                         num_leaves: int,
                         engine: str = "partition",
                         quantized: bool = False) -> KernelCost:
    """One full boosting iteration (grow one tree): the aggregate of
    the phase floors in obs/perf.iteration_budget — root histogram,
    per-split partition + smaller-child histogram + split scans, g/h
    refresh and carry compaction.  Balanced-tree lower bound: the sum
    of parent segments across the L-1 splits is modeled as n*log2(L)
    rows."""
    from ..obs import perf
    b = perf.iteration_budget(rows, features, max_bin, num_leaves,
                              engine=engine, quantized=quantized)
    return KernelCost("tree/iteration", b["total_bytes"], b["total_flops"],
                      "sum of phase floors, n*log2(L) partition bound")
