"""Gradient/hessian/count histograms over the binned feature matrix.

The TPU replacement for the reference's histogram construction hot loop
(src/io/dense_bin.hpp:105-185, dataset.cpp:760-949 ConstructHistograms and
the OpenCL kernels in src/treelearner/ocl/): per-leaf histograms are built by
one pass over the row-sharded bin matrix.  Rows are selected by a leaf-label
vector (`row→leaf`), not by the reference's reordered index array — masking
keeps shapes static for XLA.

Implementations (select via Config.tpu_histogram_impl):
- "onehot": chunked one-hot × (g,h,1) matmul — rides the MXU, the TPU-native
  choice (mirrors what the OpenCL kernels do with local-memory atomics).
- "scatter": jnp scatter-add — best on CPU backends / small data; also the
  all-leaves variant used for root and level-batched growth.
- "auto": scatter on CPU, onehot on TPU.

All accumulate in f32 by default; pass f64 arrays for the gpu_use_dp
analogue (Config.tpu_double_precision).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..utils import log


def _gh1(grad, hess, mask, dtype):
    m = mask.astype(dtype)
    return jnp.stack([grad.astype(dtype) * m, hess.astype(dtype) * m, m], axis=-1)


def leaf_histogram_scatter(bins, grad, hess, leaf_ids, leaf,
                           max_bin: int) -> jnp.ndarray:
    """[F, B, 3] histogram of rows with leaf_ids == leaf via scatter-add."""
    n, F = bins.shape
    dtype = grad.dtype
    mask = leaf_ids == leaf
    gh1 = _gh1(grad, hess, mask, dtype)                       # [n, 3]
    flat_idx = bins.astype(jnp.int32) + (jnp.arange(F, dtype=jnp.int32) * max_bin)[None, :]
    out = jnp.zeros((F * max_bin, 3), dtype=dtype)
    # one scatter per row-feature pair; values broadcast over features
    out = out.at[flat_idx.reshape(-1)].add(
        jnp.repeat(gh1, F, axis=0).reshape(n * F, 3))
    return out.reshape(F, max_bin, 3)


def leaf_histogram_onehot(bins, grad, hess, leaf_ids, leaf,
                          max_bin: int, rows_per_chunk: int = 16384) -> jnp.ndarray:
    """[F, B, 3] histogram via chunked one-hot contraction on the MXU.

    Per chunk: onehot[n_c, F, B] contracted with gh1[n_c, 3] over rows —
    a [F*B, n_c] x [n_c, 3] matmul after reshape.
    """
    n, F = bins.shape
    dtype = grad.dtype
    mask = (leaf_ids == leaf)
    gh1 = _gh1(grad, hess, mask, dtype)                       # [n, 3]

    pad = (-n) % rows_per_chunk
    if pad:
        bins = jnp.pad(bins, ((0, pad), (0, 0)))
        gh1 = jnp.pad(gh1, ((0, pad), (0, 0)))
    n_chunks = (n + pad) // rows_per_chunk
    bins_c = bins.reshape(n_chunks, rows_per_chunk, F)
    gh1_c = gh1.reshape(n_chunks, rows_per_chunk, 3)

    def body(acc, chunk):
        b, g = chunk
        onehot = jax.nn.one_hot(b, max_bin, dtype=dtype)      # [rows, F, B]
        # HIGHEST: TPU einsum otherwise rounds the f32 payloads to bf16
        # MXU passes (~0.5% histogram error -> wrong recorded gains)
        acc = acc + jnp.einsum("rfb,rc->fbc", onehot, g,
                               preferred_element_type=dtype,
                               precision=jax.lax.Precision.HIGHEST)
        return acc, None

    init = jnp.zeros((F, max_bin, 3), dtype=dtype)
    acc, _ = jax.lax.scan(body, init, (bins_c, gh1_c))
    return acc


def all_leaves_histogram(bins, grad, hess, leaf_ids, num_leaves: int,
                         max_bin: int) -> jnp.ndarray:
    """[L, F, B, 3] histograms for every leaf in one scatter pass (root /
    level-batched growth; rows with leaf_ids outside [0, L) are dropped)."""
    n, F = bins.shape
    dtype = grad.dtype
    in_range = (leaf_ids >= 0) & (leaf_ids < num_leaves)
    gh1 = _gh1(grad, hess, in_range, dtype)
    leaf_c = jnp.clip(leaf_ids, 0, num_leaves - 1).astype(jnp.int32)
    flat_idx = (leaf_c[:, None] * (F * max_bin)
                + jnp.arange(F, dtype=jnp.int32)[None, :] * max_bin
                + bins.astype(jnp.int32))
    out = jnp.zeros((num_leaves * F * max_bin, 3), dtype=dtype)
    out = out.at[flat_idx.reshape(-1)].add(
        jnp.repeat(gh1, F, axis=0).reshape(n * F, 3))
    return out.reshape(num_leaves, F, max_bin, 3)


def leaf_histogram_compact(bins, grad, hess, leaf_ids, leaf,
                           max_bin: int, tile: int = 16384) -> jnp.ndarray:
    """[F, B, 3] histogram touching only the leaf's rows.

    The TPU answer to the reference's ordered-index partition
    (data_partition.hpp:17-222 + dense_bin.hpp:105-185): the leaf's row
    indices are compacted into a prefix of an index buffer (cumsum +
    scatter, O(n) vector work), then a lax.while_loop with a *data-dependent
    trip count* of ceil(leaf_rows/tile) iterations gathers each tile and
    accumulates its histogram.  Per-tree work drops from
    O(num_leaves * n * F) to O(sum of smaller-child sizes * F) ~=
    O(n * depth * F) — the same asymptotics as the reference's
    smaller-leaf scheduling.
    """
    n, F = bins.shape
    dtype = grad.dtype
    mask = leaf_ids == leaf
    gh1 = _gh1(grad, hess, mask, dtype)                       # [n, 3]

    pos = jnp.cumsum(mask.astype(jnp.int32))
    count = pos[-1]
    # idx[0:count] = member rows; the rest point at the zero dummy row n
    idx = jnp.full(n + tile, n, jnp.int32)
    idx = idx.at[jnp.where(mask, pos - 1, n + tile)].set(
        jnp.arange(n, dtype=jnp.int32), mode="drop")
    bins_p = jnp.pad(bins, ((0, 1), (0, 0)))                  # dummy row -> bin 0
    gh1_p = jnp.pad(gh1, ((0, 1), (0, 0)))                    # dummy row -> 0

    def body(carry):
        i, acc = carry
        sl = jax.lax.dynamic_slice(idx, (i * tile,), (tile,))
        bb = jnp.take(bins_p, sl, axis=0)                     # [T, F]
        gg = jnp.take(gh1_p, sl, axis=0)                      # [T, 3]
        onehot = jax.nn.one_hot(bb, max_bin, dtype=dtype)     # [T, F, B]
        acc = acc + jnp.einsum("rfb,rc->fbc", onehot, gg,
                               preferred_element_type=dtype,
                               precision=jax.lax.Precision.HIGHEST)
        return i + 1, acc

    init = (jnp.asarray(0, jnp.int32), jnp.zeros((F, max_bin, 3), dtype))
    _, acc = jax.lax.while_loop(lambda c: c[0] * tile < count, body, init)
    return acc


def leaf_histogram(bins, grad, hess, leaf_ids, leaf,
                   max_bin: int, impl: str = "auto",
                   rows_per_chunk: int = 16384) -> jnp.ndarray:
    if impl == "pallas":
        if max_bin <= 256 and bins.dtype == jnp.uint8:
            from . import histogram_pallas
            return histogram_pallas.leaf_histogram(
                bins, grad, hess, leaf_ids, leaf, max_bin,
                interpret=jax.default_backend() != "tpu")
        log.warning("Pallas histogram kernel needs uint8 bins and "
                    "max_bin <= 256; falling back to onehot")
        impl = "onehot"
    if impl == "auto":
        impl = "compact" if jax.default_backend() == "tpu" else "scatter"
    if impl == "scatter":
        return leaf_histogram_scatter(bins, grad, hess, leaf_ids, leaf, max_bin)
    if impl == "onehot":
        return leaf_histogram_onehot(bins, grad, hess, leaf_ids, leaf,
                                     max_bin, rows_per_chunk)
    if impl == "compact":
        return leaf_histogram_compact(bins, grad, hess, leaf_ids, leaf,
                                      max_bin, rows_per_chunk)
    raise ValueError("unknown histogram impl: %s" % impl)


def subtract(parent_hist: jnp.ndarray, child_hist: jnp.ndarray) -> jnp.ndarray:
    """Sibling histogram by subtraction (FeatureHistogram::Subtract,
    feature_histogram.hpp:67-73) — the communication/work saver."""
    return parent_hist - child_hist


# -- roofline cost model (obs/perf) -------------------------------------- #
from ..obs.perf import KernelCost, cost_model  # noqa: E402


@cost_model("hist/xla")
def _cost_hist_xla(rows: int, features: int, max_bin: int,
                   dtype_bytes: int = 4) -> KernelCost:
    """XLA histogram (scatter/onehot/compact): compulsory traffic is one
    pass over bins (u8), g/h/leaf_ids, plus the [F, B, 3] f32 output;
    the FLOP floor is 3 accumulates per (row, feature) — the onehot
    impl executes B times that on the MXU, which is exactly the
    bandwidth-for-lanes trade the Pallas kernel exists to undo."""
    n, F, B = int(rows), int(features), int(max_bin)
    nbytes = n * F + n * (2 * dtype_bytes + 4) + F * B * 3 * 4
    return KernelCost("hist/xla", nbytes, 3 * n * F,
                      "one pass over bins+gh; 3 adds/(row,feat) floor")
