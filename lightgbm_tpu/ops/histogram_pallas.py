"""Pallas TPU kernel for per-leaf gradient/hessian/count histograms.

The TPU-native re-design of the reference's OpenCL histogram kernels
(src/treelearner/ocl/histogram{16,64,256}.cl) and of the CPU hot loop
(src/io/dense_bin.hpp:105-185).  Those kernels scatter into per-workgroup
local-memory sub-histograms with hand-rolled float atomics; a TPU has no
fast scatter, so this kernel factorizes the bin one-hot over a radix pair
and rides the MXU:

    bin = hi * lo_n + lo
    hist[f, c, hi, lo] = sum_t (hi_t == hi) * (lo_t == lo) * gh[c, t]

Per row tile the kernel builds `lhs[(f, c, hi), t] = gh[c,t] * (hi_t==hi)`
and `rhs[(f', lo), t] = (lo_t==lo)` in VMEM and contracts them with ONE
MXU matmul covering a group of `m` features.  The (f, f') off-diagonal
blocks are wasted work, but they fill lanes that would otherwise idle —
radix/group sizes are chosen per max_bin so M<=128 and N==128, i.e. one
full 128x128 MXU tile per feature group (the analogue of the reference
GPU learner's 16/64/256-bin kernel specialization, gpu_tree_learner
.cpp:689-751).  VPU work is hi_n + lo_n comparisons per (row, feature)
instead of B, and the [T, F, 3*hi_n] intermediate never touches HBM (the
reason this is a Pallas kernel and not an XLA einsum).

Grid: (feature_groups, row_tiles), row tiles innermost; each feature
group's output block is revisited across row tiles and accumulated in
place, relying on the TPU's sequential grid iteration order.

The row→leaf label mask (leaf_ids == leaf) is fused into gh inside the
kernel, so per-leaf histogramming is one pass with no host-side compaction.
Accumulation is f32 (single-precision like the reference GPU default,
GPUHistogramBinEntry gpu_tree_learner.h:74-78; the gpu_use_dp analogue is
the XLA f64 fallback path in ops/histogram.py).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _radix_plan(max_bin: int):
    """(lo_n, hi_n, m): bin radix split and features-per-matmul group so
    that N = m*lo_n == 128 and M = 3*hi_n*m <= 128."""
    if max_bin <= 16:
        lo_n, hi_n = 16, 1
    elif max_bin <= 64:
        lo_n, hi_n = 16, -(-max_bin // 16)
    elif max_bin <= 128:
        lo_n, hi_n = 32, -(-max_bin // 32)
    elif max_bin <= 256:
        lo_n, hi_n = 32, -(-max_bin // 32)
    else:
        raise ValueError("pallas histogram kernel supports max_bin <= 256, "
                         "got %d" % max_bin)
    m = 128 // lo_n
    assert 3 * hi_n * m <= 128
    return lo_n, hi_n, m


def _radix_matmul(gh, bins, out_ref, i, *, lo_n: int, hi_n: int, m: int,
                  k: int, tile: int):
    """Shared radix-pair MXU contraction + in-place grid accumulation:
    gh [3, tile] payload planes, bins [k*m, tile] int32 bin codes."""
    hi = bins // lo_n
    lo = bins - hi * lo_n
    hi_iota = jax.lax.broadcasted_iota(jnp.int32, (1, hi_n, 1), 1)
    lo_iota = jax.lax.broadcasted_iota(jnp.int32, (1, lo_n, 1), 1)
    hihot = (hi[:, None, :] == hi_iota).astype(jnp.float32)   # [k*m, hi_n, T]
    lohot = (lo[:, None, :] == lo_iota).astype(jnp.float32)   # [k*m, lo_n, T]

    # lhs[g, (f, c, hi), t] = gh[c, t] * hihot[g*m + f, hi, t]
    lhs = (gh[None, :, None, :] * hihot[:, None, :, :]).reshape(
        k, m * 3 * hi_n, tile)
    rhs = lohot.reshape(k, m * lo_n, tile)
    part = jax.lax.dot_general(
        lhs, rhs, dimension_numbers=(((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST)                  # [k, M, N]

    @pl.when(i == 0)
    def _():
        out_ref[:] = part

    @pl.when(i != 0)
    def _():
        out_ref[:] = out_ref[:] + part


def _hist_kernel(leaf_ref, bins_ref, lid_ref, grad_ref, hess_ref, out_ref,
                 *, lo_n: int, hi_n: int, m: int, k: int, tile: int):
    """One (feature_block, row_tile) step; a feature block is k groups of m
    features, one MXU-tile matmul each (batched).

    bins_ref: [k * m, tile] uint8 (feature-major block slice)
    lid_ref:  [1, tile] int32 row→leaf labels
    grad/hess_ref: [1, tile] f32
    out_ref:  [k, 3 * hi_n * m, lo_n * m] f32 — rows (f, c, hi), cols (f', lo)
    """
    i = pl.program_id(1)
    bins = bins_ref[:].astype(jnp.int32)                      # [k*m, T]
    msk = (lid_ref[:] == leaf_ref[0]).astype(jnp.float32)     # [1, T]
    g = grad_ref[:] * msk
    h = hess_ref[:] * msk
    gh = jnp.concatenate([g, h, msk], axis=0)                 # [3, T]
    _radix_matmul(gh, bins, out_ref, i, lo_n=lo_n, hi_n=hi_n, m=m, k=k,
                  tile=tile)


def _hist_kernel_q(leaf_ref, bins_ref, lid_ref, code_ref, out_ref,
                   *, lo_n: int, hi_n: int, m: int, k: int, tile: int):
    """Quantized variant: g/h arrive as ONE [2, tile] int8 code block and
    leaf labels as uint8, so the per-row HBM read is F+3 bytes instead of
    F+12.  The MXU contraction is identical — the accumulator holds exact
    integer code sums (f32-exact below 2^24, ops/quantize.exact_rows)."""
    i = pl.program_id(1)
    bins = bins_ref[:].astype(jnp.int32)                      # [k*m, T]
    msk = (lid_ref[:].astype(jnp.int32) == leaf_ref[0]).astype(jnp.float32)
    gh = jnp.concatenate([code_ref[:].astype(jnp.float32) * msk, msk],
                         axis=0)                              # [3, T]
    _radix_matmul(gh, bins, out_ref, i, lo_n=lo_n, hi_n=hi_n, m=m, k=k,
                  tile=tile)


@functools.partial(jax.jit, static_argnames=("max_bin", "tile", "interpret"))
def leaf_histogram(bins, grad, hess, leaf_ids, leaf, max_bin: int,
                   tile: int = 2048, interpret: bool = False) -> jnp.ndarray:
    """[F, max_bin, 3] f32 histogram of rows with leaf_ids == leaf.

    bins [n, F] uint8; grad/hess [n] float; leaf_ids [n] int32; leaf scalar.
    Requires max_bin <= 256 (uint8 bin storage — the same cap the reference
    GPU learner has, gpu_tree_learner.cpp:233-251).
    """
    n, F = bins.shape
    lo_n, hi_n, m = _radix_plan(max_bin)
    M, N = 3 * hi_n * m, lo_n * m
    f_blk = max(m, 8)          # bins block sublane dim must be a multiple of 8
    k = f_blk // m             # matmul groups per block (batched in-kernel)

    f_pad = -F % f_blk
    n_pad = -n % tile
    bins_t = jnp.pad(bins.astype(jnp.uint8), ((0, n_pad), (0, f_pad))).T
    lid = jnp.pad(leaf_ids.astype(jnp.int32), (0, n_pad),
                  constant_values=-2)[None, :]                # never a leaf id
    g32 = jnp.pad(grad.astype(jnp.float32), (0, n_pad))[None, :]
    h32 = jnp.pad(hess.astype(jnp.float32), (0, n_pad))[None, :]
    Fp = F + f_pad
    n_blocks = Fp // f_blk
    n_tiles = (n + n_pad) // tile
    leaf_arr = jnp.asarray(leaf, jnp.int32).reshape(1)

    kernel = functools.partial(_hist_kernel, lo_n=lo_n, hi_n=hi_n, m=m, k=k,
                               tile=tile)
    out = pl.pallas_call(
        kernel,
        grid=(n_blocks, n_tiles),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),             # leaf scalar
            pl.BlockSpec((f_blk, tile), lambda f, i: (f, i)),  # bins
            pl.BlockSpec((1, tile), lambda f, i: (0, i)),      # leaf_ids
            pl.BlockSpec((1, tile), lambda f, i: (0, i)),      # grad
            pl.BlockSpec((1, tile), lambda f, i: (0, i)),      # hess
        ],
        out_specs=pl.BlockSpec((k, M, N), lambda f, i: (f, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((n_blocks * k, M, N), jnp.float32),
        interpret=interpret,
    )(leaf_arr, bins_t, lid, g32, h32)

    hist = radix_epilogue(out, n_blocks * k, m, hi_n, lo_n)
    return hist[:F, :max_bin, :].astype(grad.dtype)


@functools.partial(jax.jit, static_argnames=("max_bin", "tile", "interpret"))
def leaf_histogram_quantized(bins, g_code, h_code, leaf_ids, leaf,
                             max_bin: int, tile: int = 2048,
                             interpret: bool = False) -> jnp.ndarray:
    """[F, max_bin, 3] f32 INTEGER-CODE histogram of rows with
    leaf_ids == leaf: (sum g_code, sum h_code, count).

    bins [n, F] uint8; g_code/h_code [n] int8-valued (any real dtype —
    packed to int8 on the wire); leaf_ids [n] with values < 255 (uint8 on
    the wire; pass zeros with leaf=0 for a whole-dataset/root histogram,
    where order-invariance lets this kernel read the row-order packed
    bins instead of streaming the bf16 arena).  Recover real g/h sums
    with ops.quantize.dequantize_hist.
    """
    n, F = bins.shape
    lo_n, hi_n, m = _radix_plan(max_bin)
    M, N = 3 * hi_n * m, lo_n * m
    f_blk = max(m, 8)
    k = f_blk // m

    f_pad = -F % f_blk
    n_pad = -n % tile
    bins_t = jnp.pad(bins.astype(jnp.uint8), ((0, n_pad), (0, f_pad))).T
    # pad value 255 is never a leaf id (leaf < 255 enforced by callers)
    lid = jnp.pad(leaf_ids.astype(jnp.uint8), (0, n_pad),
                  constant_values=255)[None, :]
    codes = jnp.stack([
        jnp.pad(g_code.astype(jnp.int8), (0, n_pad)),
        jnp.pad(h_code.astype(jnp.int8), (0, n_pad))])        # [2, n+pad]
    Fp = F + f_pad
    n_blocks = Fp // f_blk
    n_tiles = (n + n_pad) // tile
    leaf_arr = jnp.asarray(leaf, jnp.int32).reshape(1)

    kernel = functools.partial(_hist_kernel_q, lo_n=lo_n, hi_n=hi_n, m=m,
                               k=k, tile=tile)
    out = pl.pallas_call(
        kernel,
        grid=(n_blocks, n_tiles),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),             # leaf scalar
            pl.BlockSpec((f_blk, tile), lambda f, i: (f, i)),  # bins
            pl.BlockSpec((1, tile), lambda f, i: (0, i)),      # leaf_ids u8
            pl.BlockSpec((2, tile), lambda f, i: (0, i)),      # g/h codes i8
        ],
        out_specs=pl.BlockSpec((k, M, N), lambda f, i: (f, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((n_blocks * k, M, N), jnp.float32),
        interpret=interpret,
    )(leaf_arr, bins_t, lid, codes)

    hist = radix_epilogue(out, n_blocks * k, m, hi_n, lo_n)
    return hist[:F, :max_bin, :]


def radix_epilogue(out, G: int, m: int, hi_n: int, lo_n: int):
    """Unscramble the [G*M, N] radix-matmul accumulator into [G*m, B, 3]
    histograms: [G, f, 3, hi_n, f', lo_n] -> diagonal f == f' -> transpose.
    Shared by the masked (leaf_histogram) and the segment
    (partition_pallas.segment_histogram) kernels — the two must stay layout
    identical."""
    out = out.reshape(G, m, 3, hi_n, m, lo_n)
    diag = jnp.moveaxis(jnp.diagonal(out, axis1=1, axis2=4), -1, 1)
    return diag.reshape(G * m, 3, hi_n * lo_n).transpose(0, 2, 1)


# -- roofline cost model (obs/perf) -------------------------------------- #
from ..obs.perf import KernelCost, cost_model  # noqa: E402


@cost_model("hist/pallas")
def _cost_hist_pallas(rows: int, features: int, max_bin: int,
                      dtype_bytes: int = 4) -> KernelCost:
    """Radix-pair MXU histogram: HBM floor is one pass over bins (u8)
    and g/h/leaf_ids plus the pre-epilogue [G, M, N] f32 accumulator;
    FLOPs are what the MXU actually executes — 2*M*N MACs per row tile
    per feature group, off-diagonal (f, f') blocks included."""
    n, F, B = int(rows), int(features), int(max_bin)
    lo_n, hi_n, m = _radix_plan(B)
    G = -(-F // m)
    M, N = 3 * hi_n * m, m * lo_n
    nbytes = n * F + n * (2 * dtype_bytes + 4) + G * M * N * 4
    return KernelCost("hist/pallas", nbytes, 2 * n * G * M * N,
                      "MXU %dx%d tile per %d-feature group" % (M, N, m))


@cost_model("hist/quantized")
def _cost_hist_quantized(rows: int, features: int, max_bin: int,
                         dtype_bytes: int = 4) -> KernelCost:
    """Quantized radix histogram: per-row HBM floor is F bin bytes plus
    THREE payload bytes (int8 g code, int8 h code, uint8 leaf id) where
    the f32 kernel reads 2*dtype_bytes+4 — and where the f32 PARTITION
    engine streams the full bf16 arena row (partition/hist).  FLOPs are
    identical: this chip's MXU runs every dtype at the same rate, so the
    quantized win is purely bytes."""
    n, F, B = int(rows), int(features), int(max_bin)
    lo_n, hi_n, m = _radix_plan(B)
    G = -(-F // m)
    M, N = 3 * hi_n * m, m * lo_n
    nbytes = n * (F + 3) + G * M * N * 4
    return KernelCost("hist/quantized", nbytes, 2 * n * G * M * N,
                      "int8 codes: %d B/row vs %d B/row f32"
                      % (F + 3, F + 2 * dtype_bytes + 4))
