"""Pallas TPU kernels for the partitioned (arena) tree-growth engine.

The TPU re-design of the reference's ordered row partition
(`DataPartition`, src/treelearner/data_partition.hpp:17-222) plus the
per-leaf histogram construction it feeds (src/io/dense_bin.hpp:105-185):
rows live physically grouped by leaf in a feature-major f32 "arena"
`[C, cap]` whose channels are the F binned features followed by
(grad, hess, rowid).  Leaf segments are contiguous column ranges, so

- `partition_segment` splits a parent segment into its two children with
  one sequential pass: per 256-lane sub-block it builds a compaction
  permutation (prefix-scan of the go-left predicate -> position one-hot)
  and applies it as an MXU matmul — a TPU has no fast scatter, so row
  movement is expressed as dense matrix products.  Stream A may be
  written back in place over the parent (writes provably lag reads); the
  other child goes to the bump-allocator cursor.  This mirrors the
  reference's smaller/larger split choreography where only the smaller
  leaf is rebuilt (serial_tree_learner.cpp:360-437).
- `segment_histogram` builds the [F, B, 3] grad/hess/count histogram of
  one leaf by streaming its contiguous segment tiles through the same
  radix-factorized MXU contraction as ops/histogram_pallas.py — per-leaf
  cost is O(leaf_rows), the reference's asymptotics, with sequential HBM
  reads instead of gathers.

All arena payloads ride bf16 with EXACT semantics: bin channels hold
integers <= 256 (bf16-exact), and each f32 payload (grad, hess) rides as
THREE bf16 channels (hi/mid/lo residue split — 8 mantissa bits each
reconstruct the f32 exactly); rowid rides as three 8-bit byte planes
(2^24-row cap checked by the caller).  The permutation and histogram
matmuls then run as single bf16 MXU passes instead of f32
Precision.HIGHEST multi-pass emulation, and arena HBM traffic halves.
Histogram accumulation stays f32 (MXU accumulators), matching the
reference GPU learner's single-precision default.

Pipeline invariant in both kernels: tile j's read is complete when its
loop iteration starts; iteration j issues read j+1, computes j (overlapped
with that read), then waits read j+1.  In `partition_segment` the output
writes are issued only after that wait, which makes the in-place stream
safe: writes span at most (j+1)*tile + SUB columns past the segment start
while reads through (j+2)*tile have completed.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .histogram_pallas import _radix_plan

SUB = 256          # compaction sub-block width (lanes per permutation matmul)
TILE = 2048        # rows per streamed tile
N_AUX = 9          # g_hi,g_mid,g_lo, h_hi,h_mid,h_lo, r_hi,r_mid,r_lo
ARENA_DT = jnp.bfloat16
# sublane tiling granularity for the arena dtype (bf16 memrefs tile at 16)
_SUBL = 16


def feature_channels(num_features: int) -> int:
    """Feature channels padded to the histogram kernel's block width; the
    padding rows hold zeros and their (garbage) histograms are sliced off."""
    return num_features + (-num_features % 8)


def arena_channels(num_features: int) -> int:
    """Total arena channels: padded features, then the split payload
    planes, padded for sublane tiling."""
    c = feature_channels(num_features) + N_AUX
    return c + (-c % _SUBL)


def arena_geometry(num_data: int, num_features: int,
                   factor: int = 3) -> tuple:
    """(C, cap) of the arena for a dataset — the SINGLE sizing formula
    shared by GBDT._setup_tree_engine and the driver compile check
    (__graft_entry__.entry), so the compile check always exercises the
    same shapes real training uses.  `factor` multiples of the row
    footprint cover root + OOB dump + bump-allocated child segments
    (pristine layout: pristine bins + root copy + dump + bump -> pass
    factor >= 4); the 16-tile tail is kernel read-overrun headroom."""
    base = -(-max(num_data, 1) // TILE) * TILE
    cap = max(factor, 3) * base + 16 * TILE
    return arena_channels(max(num_features, 1)), cap


def pristine_work0(num_data: int) -> int:
    """First work-region column in the pristine arena layout: the
    pristine row block [0, align(n)) plus one guard tile (kernel reads
    overrun segments by < TILE)."""
    return -(-max(num_data, 1) // TILE) * TILE + TILE


@functools.partial(jax.jit, donate_argnums=(0,))
def init_pristine(arena, bins_t):
    """Write the PER-DATASET arena channels (feature bins + rowid byte
    planes + padding) into the pristine region [0, n) once.  Per-tree
    assembly then touches only the six g/h payload planes — the other
    42-of-48 channels of the old full re-assembly were identical every
    tree (the bins never change and pristine rows stay in row order).
    g/h plane rows are left untouched (overwritten per tree)."""
    C, cap = arena.shape
    G, n = bins_t.shape
    Fp = feature_channels(G)
    adt = ARENA_DT
    chans = [bins_t.astype(adt)]
    if Fp > G:
        chans.append(jnp.zeros((Fp - G, n), adt))
    arena = jax.lax.dynamic_update_slice(
        arena, jnp.concatenate(chans, axis=0), (0, 0))
    rid = jnp.stack(split_rowid(jnp.arange(n, dtype=jnp.int32)))
    arena = jax.lax.dynamic_update_slice(arena, rid, (Fp + 6, 0))
    if C > Fp + N_AUX:
        arena = jax.lax.dynamic_update_slice(
            arena, jnp.zeros((C - Fp - N_AUX, n), adt), (Fp + N_AUX, 0))
    return arena


def split_f32(x):
    """f32 [n] -> three bf16 planes whose f32 sum reconstructs x exactly
    (8 mantissa bits each; 24 total covers the f32 significand).

    The residue split MUST round through reduce_precision, not
    astype(bf16).astype(f32): under --xla_allow_excess_precision (set in
    this environment) XLA elides the cast round-trip inside jit, which
    zeroes the mid/lo planes and silently degrades payloads to single
    bf16 (~0.5% histogram error).  reduce_precision is semantically a
    rounding op XLA must honor."""
    x = x.astype(jnp.float32)
    hi = jax.lax.reduce_precision(x, 8, 7)
    r1 = x - hi
    mid = jax.lax.reduce_precision(r1, 8, 7)
    lo = r1 - mid
    return (hi.astype(jnp.bfloat16), mid.astype(jnp.bfloat16),
            lo.astype(jnp.bfloat16))


def pack_code_planes(g_code, h_code):
    """int8-valued g/h codes (f32 arrays from ops.quantize) -> [2, n]
    bf16 payload planes for arena rows Fp+0/Fp+1.  bf16 represents every
    integer in [-256, 256] exactly, so the cast is lossless — quantized
    mode replaces the SIX f32-residue planes with these TWO."""
    return jnp.stack([g_code, h_code]).astype(ARENA_DT)


def _align8(rows: int) -> int:
    """Round an arena row count up to the 8-sublane DMA granule."""
    return -(-rows // 8) * 8


def _side_effect_params():
    """pltpu.CompilerParams(has_side_effects=True) where available.
    CPU-only jax builds lack the attribute; interpret-mode tests of the
    side-effecting kernels then run without compiler params (interpret
    mode ignores them anyway)."""
    cp = getattr(pltpu, "CompilerParams", None)
    return cp(has_side_effects=True) if cp is not None else None


def split_rowid(r):
    """int32 [n] (< 2^24) -> three byte planes as bf16 (values <= 255)."""
    r = r.astype(jnp.int32)
    return ((r // 65536).astype(ARENA_DT),
            ((r // 256) % 256).astype(ARENA_DT),
            (r % 256).astype(ARENA_DT))


def merge_rowid(hi, mid, lo):
    return (hi.astype(jnp.int32) * 65536 + mid.astype(jnp.int32) * 256
            + lo.astype(jnp.int32))


def _prefix_scan_lanes(x):
    """Inclusive prefix sum along the last (lane) axis via log-step rolls."""
    n = x.shape[-1]
    lane = jax.lax.broadcasted_iota(jnp.int32, x.shape, x.ndim - 1)
    sh = 1
    while sh < n:
        x = x + jnp.where(lane >= sh, pltpu.roll(x, sh, axis=x.ndim - 1), 0.0)
        sh *= 2
    return x


FLUSH_W = SUB          # flush chunk width; all HBM write offsets are
#                        multiples of FLUSH_W (tiled-memref alignment).
#                        128 RE-TESTED with the sort-P kernel (round 5):
#                        21.8 vs 22.9 Mrows*iter/s — narrower carries
#                        don't pay for the doubled flush DMAs here either
CARRY_W = FLUSH_W + SUB    # per-stream carry width (append window)


def _sort_P(pref2, pred2, K: int):
    """Stable-partition permutation one-hots for ALL subblocks of a tile
    in one build: P_all [K, SUB, SUB] bf16 — subblock k's stream-A rows
    map to columns [0, ca_k) (compacted, in order) and its stream-B rows
    to columns [ca_k, ca_k + cb_k), i.e. ONE [C, S] @ [S, S] MXU matmul
    per subblock SORTS the block into an A-prefix and a B-suffix.  Half
    the MACs of the previous dual-stream [S, 2*SUB] product: the two
    halves of that output were disjoint by construction, so the split
    point ca_k (known before any matmul from the prefix scans) lets both
    streams share one SUB-wide product; the appends separate them again
    with cheap lane masks + the usual VPU carry roll.

    pref2/pred2: [2K, SUB] f32 — A-rows then B-rows (inclusive prefix
    sums and 0/1 predicates).  Invalid rows (neither stream) map
    nowhere (all-zero P row)."""
    pA = pred2[:K]                                     # [K, S] f32 0/1
    vAB = pred2[:K] + pred2[K:]                        # valid (0/1)
    ca = pref2[:K, SUB - 1].reshape(K, 1)              # [K, 1] f32
    pos = (pA * (pref2[:K] - 1.0)
           + (1.0 - pA) * (pref2[K:] - 1.0 + ca))      # [K, S] f32
    t3 = jax.lax.broadcasted_iota(jnp.int32, (K, SUB, SUB), 2)
    # build the one-hot in f32 then cast: an i1 mask from 32-bit compares
    # can't relayout onto 16-bit vector selects in Mosaic
    return jnp.where(
        (pos.astype(jnp.int32)[:, :, None] == t3)
        & (vAB[:, :, None] > 0.5),
        jnp.float32(1.0), jnp.float32(0.0)).astype(jnp.bfloat16)


def _partition_kernel(sc_ref, feat_onehot_ref, mask_ref, arena_any, pred_any,
                      out_any, cnt_ref, *rest,
                      C: int, tile: int, hist_plan=None):
    """sc_ref (SMEM [7] i32): start, cnt, dstA, dstB, mode, xr, hs —
    start, dstA and dstB must be multiples of `tile` resp. FLUSH_W (the
    bump allocator aligns).
    arena_any/out_any: [C, cap] bf16 in HBM, aliased (same buffer).
    Routing: mode=0 reads pred_any ([1, cap] f32, 1.0 -> stream A);
    mode=1 computes the split decision in-kernel — the feature row is
    extracted with a one-hot matvec (feat_onehot_ref [1, C], bins < 256
    are bf16-exact) and routed through mask_ref ([1, 256] bf16 0/1:
    mask[v] == 1 -> arena value v goes left), XOR'd with xr (1 when the
    left child is the smaller/bump-allocated stream-B side).  The caller
    bakes ALL decision semantics (numerical threshold, missing
    direction, categorical bitsets, EFB ranges) into the mask.
    cnt_ref (SMEM out [2] i32): rows written to A and B.

    Each SUB-lane sub-block is compacted with an MXU permutation matmul
    and appended into a narrow per-stream VMEM carry via dynamic-shift
    roll + add (appends are disjoint); whenever a carry holds FLUSH_W
    rows, that chunk is DMA'd to the stream's next FLUSH_W-aligned arena
    columns.  Stream A may write over the parent segment in place: flushed
    columns [dstA + wA, +FLUSH_W) always lie within the rows already read,
    because wA + FLUSH_W <= rows consumed so far <= (j+1)*tile and tile j
    is fully read before its sub-blocks are appended.
    """
    if hist_plan is None:
        hist_ref = None
        (in_buf, pred_buf, carryA, carryB, flush_buf,
         read_sems, pred_sems, write_sems) = rest
    else:
        # fused smaller-child histogram: one extra VMEM output, stream-B
        # rows accumulated with the radix contraction while they are
        # already in VMEM for compaction — saves the separate
        # segment_histogram kernel launch AND its re-read of the child
        (hist_ref, in_buf, pred_buf, carryA, carryB, flush_buf,
         read_sems, pred_sems, write_sems) = rest
        hist_ref[:] = jnp.zeros_like(hist_ref)
    s, cnt = sc_ref[0], sc_ref[1]
    dstA, dstB = sc_ref[2], sc_ref[3]
    mode = sc_ref[4]
    xr = sc_ref[5]    # XOR'd into the decision: 1 when the left child is
    #                   the smaller (stream-B) side
    hs = sc_ref[6]    # fused-histogram stream: 1 -> B, 0 -> A
    n_tiles = jax.lax.div(cnt + jnp.int32(tile - 1), jnp.int32(tile))
    K = tile // SUB
    lane_w = jax.lax.broadcasted_iota(jnp.int32, (C, CARRY_W), 1)

    def read_dmas(j, slot):
        src = pl.multiple_of(s + j * tile, 128)
        # the pred stream is only consumed in mode 0; in decision mode the
        # caller passes a [1, tile] dummy (a full [1, cap] zeros buffer
        # gets constant-sunk into the grow while-loop by XLA and
        # re-materialized EVERY split — measured 75 ms/iter) and the DMA
        # pins its read to offset 0
        psrc = jnp.where(mode == 0, src, 0)
        return (pltpu.make_async_copy(
                    arena_any.at[:, pl.ds(src, tile)],
                    in_buf.at[slot], read_sems.at[slot]),
                pltpu.make_async_copy(
                    pred_any.at[:, pl.ds(pl.multiple_of(psrc, 128), tile)],
                    pred_buf.at[slot], pred_sems.at[slot]))

    def flush_dma(stream, slot, dst_col):
        return pltpu.make_async_copy(
            flush_buf.at[stream, slot],
            out_any.at[:, pl.ds(pl.multiple_of(dst_col, 128), FLUSH_W)],
            write_sems.at[stream, slot])

    @pl.when(n_tiles > 0)
    def _():
        for d in read_dmas(0, 0):
            d.start()
        for d in read_dmas(0, 0):
            d.wait()
    carryA[:] = jnp.zeros((C, CARRY_W), jnp.float32)
    carryB[:] = jnp.zeros((C, CARRY_W), jnp.float32)

    def append_and_flush(carry, chunk, lo, ck, fill, written, dst, stream,
                         fslot):
        """chunk ([C, SUB] f32) holds this stream's rows at lanes
        [lo, lo+ck), zeros elsewhere (masked OFF the serial chain, in
        the parallel region after the sort matmuls); circular-roll them
        onto carry lanes [fill, fill+ck) (fill + ck <= CARRY_W by the
        flush invariant, so the rotation never wraps values).  Then
        flush filled FLUSH_W chunks (up to ceil(SUB/FLUSH_W) per append
        when FLUSH_W < SUB).  The carry is f32 precisely so the
        positioning can be a dynamic pltpu.roll (32-bit-only op)
        instead of MXU MACs; values are exact bf16 payloads so the
        f32->bf16 cast at flush is lossless.
        Returns (fill', written', fslot')."""
        padded = jnp.concatenate(
            [chunk, jnp.zeros((C, CARRY_W - SUB), jnp.float32)], axis=1)
        shift = jax.lax.rem(fill - lo + jnp.int32(CARRY_W),
                            jnp.int32(CARRY_W))
        carry[:] = carry[:] + pltpu.roll(padded, shift, axis=1)
        fill = fill + ck

        for _ in range(-(-SUB // FLUSH_W)):
            @pl.when(fill >= FLUSH_W)
            def _(fill=fill, written=written, fslot=fslot):
                # previous flush of this slot (2 flushes ago) must have landed
                @pl.when(written >= 2 * FLUSH_W)
                def _():
                    flush_dma(stream, fslot, 0).wait()
                flush_buf[stream, fslot] = carry[:, 0:FLUSH_W].astype(ARENA_DT)
                flush_dma(stream, fslot, dst + written).start()
                shifted = jnp.concatenate(
                    [carry[:, FLUSH_W:CARRY_W],
                     jnp.zeros((C, FLUSH_W), jnp.float32)], axis=1)
                carry[:] = jnp.where(lane_w < fill - FLUSH_W, shifted,
                                     jnp.float32(0.0))

            flushed = fill >= FLUSH_W
            fill = jnp.where(flushed, fill - FLUSH_W, fill)
            written = jnp.where(flushed, written + FLUSH_W, written)
            fslot = jnp.where(flushed, 1 - fslot, fslot)
        return fill, written, fslot

    def loop(j, carry_state):
        fillA, wA, fsA, fillB, wB, fsB = carry_state
        slot = jax.lax.rem(j, jnp.int32(2))
        nslot = jax.lax.rem(j + jnp.int32(1), jnp.int32(2))

        @pl.when(j + 1 < n_tiles)
        def _():
            for d in read_dmas(j + 1, nslot):
                d.start()

        valid = jax.lax.broadcasted_iota(
            jnp.int32, (1, tile), 1) < (cnt - j * tile)
        block = in_buf[slot]
        # in-kernel split decision (mode 1): the arena column is read with
        # a one-hot matvec over channels, then routed through the go-left
        # MASK VECTOR (mask_ref [1, MB]: mask[v] == 1 -> bin value v goes
        # left).  The mask is built in XLA per split and encodes ALL
        # decision semantics — numerical threshold + missing direction
        # (NumericalDecision, tree.h:429-465), categorical bitsets
        # (CategoricalDecision, tree.h:259-273) and EFB bundle-local bin
        # ranges — so the kernel needs no per-kind logic.
        col = jnp.round(jax.lax.dot(feat_onehot_ref[:], block,
                                    preferred_element_type=jnp.float32)
                        ).astype(jnp.int32)                   # [1, T]
        MB = mask_ref.shape[1]
        col_onehot = jnp.where(
            jax.lax.broadcasted_iota(jnp.int32, (MB, tile), 0)
            == col.reshape(1, tile),
            jnp.float32(1.0), jnp.float32(0.0)).astype(jnp.bfloat16)
        go_left_f = jax.lax.dot(mask_ref[:], col_onehot,
                                preferred_element_type=jnp.float32)
        xr_f = jnp.float32(xr)
        decide_f = go_left_f + xr_f - 2.0 * go_left_f * xr_f   # xor
        mode_f = jnp.float32(mode)
        on_f = mode_f * decide_f + (1.0 - mode_f) * pred_buf[slot]
        on = on_f > 0.5
        predA = jnp.where(valid & on, jnp.float32(1.0), jnp.float32(0.0))
        predB = jnp.where(valid & ~on, jnp.float32(1.0), jnp.float32(0.0))

        if hist_plan is not None:
            hs_f = hs.astype(jnp.float32)
            hmask = (hs_f * predB + (1.0 - hs_f) * predA).astype(jnp.bfloat16)
            nb_h, k_h, m_h, lo_h, hi_h, pay_h = hist_plan
            _radix_accumulate(hist_ref, block, hmask, n_blocks=nb_h, k=k_h,
                              m=m_h, lo_n=lo_h, hi_n=hi_h, tile=tile,
                              payload=pay_h)

        # ONE batched prefix scan for all subblocks of both streams — the
        # per-subblock scans were 2*K*log2(SUB) serial roll steps, the
        # kernel's dominant latency.  Then ONE batched P build and K
        # dependency-free SORT matmuls ([C,S]@[S,S]: A-prefix + B-suffix
        # in a single product — half the MACs of the dual-stream [S,2S]
        # build): nothing on the MXU path waits on the serial carry/fill
        # chain (that chain is cheap VPU mask/roll/add work), so the
        # systolic array stays fed.
        pred2 = jnp.concatenate(
            [predA.reshape(K, SUB), predB.reshape(K, SUB)], axis=0)
        pref2 = _prefix_scan_lanes(pred2)                  # [2K, SUB]
        cnt2 = pref2[:, SUB - 1].astype(jnp.int32)         # [2K]
        P_all = _sort_P(pref2, pred2, K)                   # [K, S, S]
        comps = [jax.lax.dot(block[:, k * SUB:(k + 1) * SUB], P_all[k],
                             preferred_element_type=jnp.float32)
                 for k in range(K)]                        # [C, S] f32
        # split each sorted block into its A-prefix / B-suffix OFF the
        # serial carry chain (depends only on cnt2, not on fill); the
        # B chunk is a subtraction, not a second select
        lane_s = jax.lax.broadcasted_iota(jnp.int32, (1, SUB), 1)
        chunksA = [jnp.where(lane_s < cnt2[k], comps[k], jnp.float32(0.0))
                   for k in range(K)]
        chunksB = [comps[k] - chunksA[k] for k in range(K)]
        for k in range(K):
            ca, cb = cnt2[k], cnt2[K + k]
            fillA, wA, fsA = append_and_flush(
                carryA, chunksA[k], jnp.int32(0), ca, fillA, wA, dstA, 0,
                fsA)
            fillB, wB, fsB = append_and_flush(
                carryB, chunksB[k], ca, cb, fillB, wB, dstB, 1, fsB)

        @pl.when(j + 1 < n_tiles)
        def _():
            for d in read_dmas(j + 1, nslot):
                d.wait()
        return fillA, wA, fsA, fillB, wB, fsB

    z = jnp.int32(0)
    fillA, wA, fsA, fillB, wB, fsB = jax.lax.fori_loop(
        0, n_tiles, loop, (z, z, z, z, z, z))

    # Final partial flush, then drain every in-flight DMA.  With c = w /
    # FLUSH_W loop flushes, the in-loop waits consumed the signals of
    # flushes 0..c-3; flushes c-2 (slot fslot) and c-1 (slot 1-fslot) are
    # still outstanding and every one must be waited before kernel exit.
    for stream, carry, fill, w, dst, fslot in (
            (0, carryA, fillA, wA, dstA, fsA),
            (1, carryB, fillB, wB, dstB, fsB)):
        @pl.when(fill > 0)
        def _(stream=stream, carry=carry, fill=fill, w=w, dst=dst,
              fslot=fslot):
            @pl.when(w >= 2 * FLUSH_W)
            def _():
                flush_dma(stream, fslot, 0).wait()     # flush c-2
            flush_buf[stream, fslot] = carry[:, 0:FLUSH_W].astype(ARENA_DT)
            flush_dma(stream, fslot, dst + w).start()
            flush_dma(stream, fslot, 0).wait()         # the final flush

        @pl.when((fill == 0) & (w >= 2 * FLUSH_W))
        def _(stream=stream, fslot=fslot):
            flush_dma(stream, fslot, 0).wait()         # flush c-2

        @pl.when(w >= FLUSH_W)
        def _(stream=stream, fslot=fslot):
            flush_dma(stream, 1 - fslot, 0).wait()     # flush c-1

    cnt_ref[0] = wA + fillA
    cnt_ref[1] = wB + fillB


@functools.partial(jax.jit, static_argnames=("tile", "interpret",
                                             "num_features", "max_bin",
                                             "quantized"))
def partition_segment(arena, pred, start, cnt, dstA, dstB,
                      decision=None, hist_stream=None,
                      num_features: int = 0, max_bin: int = 0,
                      tile: int = TILE, interpret: bool = False,
                      quantized: bool = False):
    """Partition arena columns [start, start+cnt) into stream A at dstA
    (dstA == start allowed: in-place with lagging writes) and stream B at
    dstB (must not overlap [start, start+cnt+tile)).

    Routing: by `pred` ([1, cap] f32, 1.0 -> A) when decision is None,
    else by the in-kernel split decision — decision = (feat_channel,
    goleft_mask [MB] 0/1, xor_flag): a row whose arena value on the
    feature channel is v follows goleft_mask[v] (XOR xor_flag); the mask
    encodes numerical/missing/categorical/EFB semantics uniformly.  pred
    is then ignored (pass a [1, tile] dummy).

    When hist_stream is given (0 -> stream A, 1 -> stream B; requires
    num_features/max_bin), the kernel also accumulates that stream's
    [F, max_bin, 3] histogram in the same pass and returns it third —
    the partition + histogram fusion (used for the bagging root pass;
    a parent-size-gated fusion on the split path was measured ~10%
    WORSE end-to-end in round 5 — the hist output's per-launch setup
    outweighs the separate O(child) kernel's fixed cost).

    Returns (new_arena, counts[2] int32[, hist]).  Writes stay within
    align(count, FLUSH_W) columns of each stream's dst; reads overrun the
    segment by < tile columns, so callers keep cap >= last segment + tile.
    """
    C, cap = arena.shape
    z = jnp.int32(0)
    MB = 256   # mask lane width (any bin value < 256 fits)
    if decision is None:
        tail = [z, z]
        feat_onehot = jnp.zeros((1, C), ARENA_DT)
        goleft = jnp.zeros((1, MB), ARENA_DT)
    else:
        feat, mask_vec, xr = decision
        feat = jnp.asarray(feat, jnp.int32)
        tail = [jnp.int32(1), jnp.asarray(xr, jnp.int32)]
        feat_onehot = (jnp.arange(C, dtype=jnp.int32)[None, :]
                       == feat).astype(ARENA_DT)
        mv = jnp.asarray(mask_vec, jnp.float32).reshape(1, -1)
        goleft = jnp.pad(mv, ((0, 0), (0, MB - mv.shape[1]))
                         ).astype(ARENA_DT)
    with_hist = hist_stream is not None
    tail.append(jnp.asarray(hist_stream if with_hist else 0, jnp.int32))
    sc = jnp.stack([jnp.asarray(start), jnp.asarray(cnt),
                    jnp.asarray(dstA), jnp.asarray(dstB)]
                   + tail).astype(jnp.int32)
    hist_plan = None
    out_specs = (pl.BlockSpec(memory_space=pl.ANY),
                 pl.BlockSpec(memory_space=pltpu.SMEM))
    out_shape = [jax.ShapeDtypeStruct((C, cap), ARENA_DT),
                 jax.ShapeDtypeStruct((2,), jnp.int32)]
    payload = 3 if quantized else 7
    if with_hist:
        lo_n, hi_n, m = _radix_plan(max_bin)
        f_blk = max(m, 8)
        k = f_blk // m
        n_blocks = feature_channels(num_features) // f_blk
        hist_plan = (n_blocks, k, m, lo_n, hi_n, payload)
        Mc, N = payload * hi_n * m, lo_n * m
        out_specs = out_specs + (pl.BlockSpec(memory_space=pltpu.VMEM),)
        out_shape.append(
            jax.ShapeDtypeStruct((n_blocks * k * Mc, N), jnp.float32))
    kernel = functools.partial(_partition_kernel, C=C, tile=tile,
                               hist_plan=hist_plan)
    outs = pl.pallas_call(
        kernel,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=out_specs,
        out_shape=tuple(out_shape),
        scratch_shapes=[
            pltpu.VMEM((2, C, tile), ARENA_DT),
            pltpu.VMEM((2, 1, tile), jnp.float32),
            pltpu.VMEM((C, CARRY_W), jnp.float32),
            pltpu.VMEM((C, CARRY_W), jnp.float32),
            pltpu.VMEM((2, 2, C, FLUSH_W), ARENA_DT),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2, 2)),
        ],
        input_output_aliases={3: 0},
        compiler_params=_side_effect_params(),
        interpret=interpret,
    )(sc, feat_onehot, goleft, arena, pred)
    if not with_hist:
        return outs[0], outs[1]
    hist = split_radix_epilogue(outs[2], n_blocks * k, m, hi_n=hi_n,
                                lo_n=lo_n,
                                payload=payload)[:num_features, :max_bin, :]
    return outs[0], outs[1], hist


def _compact_carry_kernel(sc_ref, starts_ref, cnts_ref, arena_any, out_any,
                          used_ref, in_buf, carry, flush_buf,
                          read_sems, write_sems, *, C: int, tile: int):
    """Compact the live leaf segments' FULL channel rows into one dense
    contiguous block at dst0 — the carried-arena tree boundary: instead
    of extracting (rowid, value) pairs and sorting scores back to row
    order (O(n log^2 n) bitonic, ~64 ms at 10.5M rows), the next tree
    simply roots at the compacted block, and score/label planes ride
    along as channels.  Valid rows are a PREFIX of every segment tile,
    so appends need no permutation matmul: static SUB-wide slices roll
    into the carry window exactly like the partition kernel's append
    (same FLUSH_W-aligned write discipline; dst0 must be FLUSH_W-aligned
    and the destination block must not overlap any live segment).

    sc_ref (SMEM [2] i32): num_live_leaves, dst0.
    starts/cnts (SMEM [L] i32): per-leaf segment start and count; the
    output packs segments in LEAF-INDEX order (callers derive per-row
    leaf values from cumsum(cnts)).
    used_ref (SMEM [1] i32): rows written (= sum of cnts).
    """
    nseg, dst0 = sc_ref[0], sc_ref[1]
    K = tile // SUB
    lane_w = jax.lax.broadcasted_iota(jnp.int32, (C, CARRY_W), 1)
    lane_s = jax.lax.broadcasted_iota(jnp.int32, (1, SUB), 1)

    def read_dma(start, j, slot):
        src = pl.multiple_of(start + j * tile, 128)
        return pltpu.make_async_copy(
            arena_any.at[:, pl.ds(src, tile)],
            in_buf.at[slot], read_sems.at[slot])

    def flush_dma(slot, dst_col):
        return pltpu.make_async_copy(
            flush_buf.at[slot],
            out_any.at[:, pl.ds(pl.multiple_of(dst_col, 128), FLUSH_W)],
            write_sems.at[slot])

    carry[:] = jnp.zeros((C, CARRY_W), jnp.float32)

    def append(chunk, ck, fill, written, fslot):
        """The partition kernel's append/flush, single-stream, lo=0."""
        padded = jnp.concatenate(
            [chunk, jnp.zeros((C, CARRY_W - SUB), jnp.float32)], axis=1)
        carry[:] = carry[:] + pltpu.roll(padded, fill, axis=1)
        fill = fill + ck
        for _ in range(-(-SUB // FLUSH_W)):
            @pl.when(fill >= FLUSH_W)
            def _(fill=fill, written=written, fslot=fslot):
                @pl.when(written >= 2 * FLUSH_W)
                def _():
                    flush_dma(fslot, 0).wait()
                flush_buf[fslot] = carry[:, 0:FLUSH_W].astype(ARENA_DT)
                flush_dma(fslot, dst0 + written).start()
                shifted = jnp.concatenate(
                    [carry[:, FLUSH_W:CARRY_W],
                     jnp.zeros((C, FLUSH_W), jnp.float32)], axis=1)
                carry[:] = jnp.where(lane_w < fill - FLUSH_W, shifted,
                                     jnp.float32(0.0))
            flushed = fill >= FLUSH_W
            fill = jnp.where(flushed, fill - FLUSH_W, fill)
            written = jnp.where(flushed, written + FLUSH_W, written)
            fslot = jnp.where(flushed, 1 - fslot, fslot)
        return fill, written, fslot

    def seg_body(s, st):
        fill, written, fslot, rd = st
        start, cnt = starts_ref[s], cnts_ref[s]
        n_t = jax.lax.div(cnt + jnp.int32(tile - 1), jnp.int32(tile))

        @pl.when(n_t > 0)
        def _():
            read_dma(start, 0, jax.lax.rem(rd, jnp.int32(2))).start()

        def tile_body(j, st2):
            fill, written, fslot, rd = st2
            rslot = jax.lax.rem(rd, jnp.int32(2))
            read_dma(start, j, rslot).wait()

            @pl.when(j + 1 < n_t)
            def _():
                read_dma(start, j + 1, 1 - rslot).start()
            vt = cnt - j * tile          # valid prefix of this tile
            block = in_buf[rslot]
            for k2 in range(K):
                ck = jnp.clip(vt - k2 * SUB, 0, SUB)
                chunk = jnp.where(
                    lane_s < ck,
                    block[:, k2 * SUB:(k2 + 1) * SUB].astype(jnp.float32),
                    jnp.float32(0.0))
                fill, written, fslot = append(chunk, ck, fill, written,
                                              fslot)
            return fill, written, fslot, rd + 1

        return jax.lax.fori_loop(0, n_t, tile_body,
                                 (fill, written, fslot, rd))

    z = jnp.int32(0)
    fill, written, fslot, _rd = jax.lax.fori_loop(
        0, nseg, seg_body, (z, z, z, z))

    @pl.when(fill > 0)
    def _():
        @pl.when(written >= 2 * FLUSH_W)
        def _():
            flush_dma(fslot, 0).wait()
        flush_buf[fslot] = carry[:, 0:FLUSH_W].astype(ARENA_DT)
        flush_dma(fslot, dst0 + written).start()
        flush_dma(fslot, 0).wait()

    @pl.when((fill == 0) & (written >= 2 * FLUSH_W))
    def _():
        flush_dma(fslot, 0).wait()

    @pl.when(written >= FLUSH_W)
    def _():
        flush_dma(1 - fslot, 0).wait()

    used_ref[0] = written + fill


@functools.partial(jax.jit, static_argnames=("tile", "interpret"))
def compact_carry(arena, starts, cnts, num_live, dst0,
                  tile: int = TILE, interpret: bool = False):
    """Compact live segments (leaf-index order) into a dense full-channel
    block at dst0; returns (arena', rows_written).  dst0 must be
    FLUSH_W-aligned and its block disjoint from every live segment."""
    C, cap = arena.shape
    sc = jnp.stack([jnp.asarray(num_live),
                    jnp.asarray(dst0)]).astype(jnp.int32)
    kernel = functools.partial(_compact_carry_kernel, C=C, tile=tile)
    out, used = pl.pallas_call(
        kernel,
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=(pl.BlockSpec(memory_space=pl.ANY),
                   pl.BlockSpec(memory_space=pltpu.SMEM)),
        out_shape=(jax.ShapeDtypeStruct((C, cap), ARENA_DT),
                   jax.ShapeDtypeStruct((1,), jnp.int32)),
        scratch_shapes=[
            pltpu.VMEM((2, C, tile), ARENA_DT),
            pltpu.VMEM((C, CARRY_W), jnp.float32),
            pltpu.VMEM((2, C, FLUSH_W), ARENA_DT),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        input_output_aliases={3: 0},
        compiler_params=_side_effect_params(),
        interpret=interpret,
    )(sc, jnp.asarray(starts, jnp.int32), jnp.asarray(cnts, jnp.int32),
      arena)
    return out, used[0]


def _compact_rows_kernel(sc_ref, starts_ref, cnts_ref, vals_ref, arena_any,
                         out_any, used_ref, in_buf, out_buf,
                         read_sems, write_sems, *, fp: int, tile: int):
    """Compact the live leaf segments' (rowid, value) pairs into one
    dense stream — the cap-independent replacement for the old
    step-function label recovery (three O(cap) cumsums + an O(cap)
    scatter; cap is ~6x rows, so recovery dominated the fixed per-tree
    cost).  Only segment tiles are streamed: O(rows) work total.

    sc_ref (SMEM [2] i32): num_live_leaves, dummy_rowid.
    starts/cnts (SMEM [L] i32), vals (SMEM [L] f32): per-leaf segment
    start, count and emitted value (leaf value or leaf index).
    arena_any: [C, cap] bf16; rowid byte planes at rows fp+6..fp+8.
    out_any: [2, capn] f32 — row 0 rowid (exact: n < 2^24), row 1 value.
    used_ref (SMEM [1] i32): columns written (= Σ ceil(cnt/tile)*tile).

    Each segment writes ceil(cnt/tile) full tiles at a tile-aligned
    output cursor; slots beyond the segment count carry dummy_rowid and
    are dropped by the consumer's scatter.  Double-buffered on both the
    read and write sides.
    """
    nseg, dummy = sc_ref[0], sc_ref[1]
    dummy_f = dummy.astype(jnp.float32)

    def read_dma(start, j, slot):
        # full channel block: a 3-row sublane slice at fp+6 may violate
        # the (16, 128) bf16 memref tiling; the extra bandwidth is ~2 ms
        # at 4M rows, well under what this kernel replaces
        src = pl.multiple_of(start + j * tile, 128)
        return pltpu.make_async_copy(
            arena_any.at[:, pl.ds(src, tile)],
            in_buf.at[slot], read_sems.at[slot])

    def write_dma(dst_col, slot):
        dst = pl.multiple_of(dst_col, 128)
        return pltpu.make_async_copy(
            out_buf.at[slot], out_any.at[:, pl.ds(dst, tile)],
            write_sems.at[slot])

    def seg_body(s, carry):
        ocur, w_total = carry
        start, cnt = starts_ref[s], cnts_ref[s]
        val = vals_ref[s]
        n_t = jax.lax.div(cnt + jnp.int32(tile - 1), jnp.int32(tile))

        @pl.when(n_t > 0)
        def _():
            read_dma(start, 0, 0).start()

        def tile_body(j, wt):
            rslot = jax.lax.rem(j, jnp.int32(2))
            read_dma(start, j, rslot).wait()

            @pl.when(j + 1 < n_t)
            def _():
                read_dma(start, j + 1, 1 - rslot).start()

            rid = (in_buf[rslot][fp + 6:fp + 7].astype(jnp.float32) * 65536.0
                   + in_buf[rslot][fp + 7:fp + 8].astype(jnp.float32) * 256.0
                   + in_buf[rslot][fp + 8:fp + 9].astype(jnp.float32))
            lane = jax.lax.broadcasted_iota(jnp.int32, (1, tile), 1)
            live = (lane < (cnt - j * tile)).astype(jnp.float32)
            # write slots cycle on the GLOBAL write counter (segments
            # restart j at 0, so per-tile parity would double-book a
            # semaphore); wait the write that used this slot 2 writes ago
            wslot = jax.lax.rem(wt, jnp.int32(2))
            @pl.when(wt >= 2)
            def _():
                write_dma(0, wslot).wait()
            out_buf[wslot, 0:1] = rid * live + dummy_f * (1.0 - live)
            out_buf[wslot, 1:2] = val * live
            write_dma(ocur + j * tile, wslot).start()
            return wt + 1

        w_total = jax.lax.fori_loop(0, n_t, tile_body, w_total)
        return ocur + n_t * tile, w_total

    ocur, w_total = jax.lax.fori_loop(0, nseg, seg_body,
                                      (jnp.int32(0), jnp.int32(0)))
    # drain outstanding writes: the last two used parities (w-1)%2, w%2
    @pl.when(w_total >= 1)
    def _():
        write_dma(0, jax.lax.rem(w_total + jnp.int32(1), jnp.int32(2))).wait()

    @pl.when(w_total >= 2)
    def _():
        write_dma(0, jax.lax.rem(w_total, jnp.int32(2))).wait()
    used_ref[0] = ocur


@functools.partial(jax.jit, static_argnames=("num_features", "capn", "tile",
                                             "interpret"))
def compact_segments(arena, starts, cnts, vals, num_live, dummy_rowid,
                     num_features: int, capn: int,
                     tile: int = TILE, interpret: bool = False):
    """[2, capn] f32 (rowid, value) compact stream over the live leaf
    segments + used-columns count.  Slots with rowid == dummy_rowid are
    padding.  capn must be >= align(total_rows, tile) + num_leaves*tile."""
    C, cap = arena.shape
    fp = feature_channels(num_features)
    L = starts.shape[0]
    sc = jnp.stack([jnp.asarray(num_live), jnp.asarray(dummy_rowid)]
                   ).astype(jnp.int32)
    kernel = functools.partial(_compact_rows_kernel, fp=fp, tile=tile)
    out, used = pl.pallas_call(
        kernel,
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=(pl.BlockSpec(memory_space=pl.ANY),
                   pl.BlockSpec(memory_space=pltpu.SMEM)),
        out_shape=(jax.ShapeDtypeStruct((2, capn), jnp.float32),
                   jax.ShapeDtypeStruct((1,), jnp.int32)),
        scratch_shapes=[
            pltpu.VMEM((2, C, tile), ARENA_DT),
            pltpu.VMEM((2, 2, tile), jnp.float32),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        compiler_params=_side_effect_params(),
        interpret=interpret,
    )(sc, jnp.asarray(starts, jnp.int32), jnp.asarray(cnts, jnp.int32),
      jnp.asarray(vals, jnp.float32), arena)
    return out, used


def _comp_chunks(hi_n: int, m: int, payload: int = 7):
    """Split the payload components (f32: g_hi,g_mid,g_lo, h_hi,h_mid,h_lo,
    cnt; quantized: g_code, h_code, cnt) into dot chunks with
    chunk*hi_n*m <= 128 rows each."""
    per = max(1, 128 // (hi_n * m))
    chunks = []
    i = 0
    while i < payload:
        chunks.append(min(per, payload - i))
        i += chunks[-1]
    return chunks


def _radix_accumulate(out_ref, block, mask, *, n_blocks: int, k: int,
                      m: int, lo_n: int, hi_n: int, tile: int,
                      payload: int = 7):
    """Accumulate the radix-factorized split-payload histogram of `block`
    [C, tile] bf16 rows selected by `mask` [1, tile] bf16 (0/1) into
    out_ref [n_blocks*k*payload*hi_n*m, lo_n*m] f32 — the shared inner
    loop of the segment-histogram kernel and the fused
    partition/refresh+histogram passes.  payload=7 is the f32-exact mode
    (6 residue planes + count); payload=3 is the quantized mode (int8
    g/h codes + count — the accumulator then holds exact integer code
    sums, see ops/quantize)."""
    N = lo_n * m
    Mc = payload * hi_n * m
    f_blk = k * m
    chunks = _comp_chunks(hi_n, m, payload)
    Fp = n_blocks * f_blk
    # payload planes after the feature rows; masking by 0/1 keeps every
    # entry a bf16-exact plane value (residue planes or int8 codes)
    comps = [block[Fp + i:Fp + i + 1, :] * mask for i in range(payload - 1)]
    comps.append(mask)
    gh = jnp.concatenate(comps, axis=0)               # [payload, T] bf16

    for b in range(n_blocks):
        bins = block[b * f_blk:(b + 1) * f_blk, :].astype(jnp.float32)
        hi = jnp.floor(bins * (1.0 / lo_n))
        lo = bins - hi * lo_n
        hih = jnp.where(
            hi.astype(jnp.int32)[:, None, :]
            == jax.lax.broadcasted_iota(jnp.int32, (1, hi_n, 1), 1),
            jnp.float32(1.0),
            jnp.float32(0.0)).astype(jnp.bfloat16)    # [f_blk,hi_n,T]
        loh = jnp.where(
            lo.astype(jnp.int32)[:, None, :]
            == jax.lax.broadcasted_iota(jnp.int32, (1, lo_n, 1), 1),
            jnp.float32(1.0),
            jnp.float32(0.0)).astype(jnp.bfloat16)    # [f_blk,lo_n,T]
        rhs = loh.reshape(k, N, tile)
        c0 = 0
        for csz in chunks:
            # lhs[g, (f, c, hi), t] = gh[c, t] * hihot[g*m + f, hi, t]
            # NB: slice-then-reshape, never `[None, c0:c0+csz, None]`
            # indexing — a partial slice mixed with newaxes lowers via
            # lax.gather, which Mosaic rejects in this shape
            ghc = gh[c0:c0 + csz, :].reshape(1, csz, 1, tile)
            lhs = (ghc * hih.reshape(f_blk, 1, hi_n, tile)
                   ).reshape(k, m * csz * hi_n, tile)
            part = jax.lax.dot_general(
                lhs, rhs,
                dimension_numbers=(((2,), (2,)), ((0,), (0,))),
                preferred_element_type=jnp.float32)   # [k, m*csz*hi_n, N]
            r0 = b * k * Mc
            # part rows are (f, c_local, hi); the accumulator layout is
            # (f, c, hi) with the FULL payload-component c axis — each
            # feature's chunk block lands at its own strided offset
            for kk in range(k):
                for f in range(m):
                    src = (f * csz) * hi_n
                    dst = r0 + kk * Mc + (f * payload + c0) * hi_n
                    sz = csz * hi_n
                    out_ref[dst:dst + sz, :] = (
                        out_ref[dst:dst + sz, :]
                        + part[kk, src:src + sz, :])
            c0 += csz


def _seg_hist_kernel(sc_ref, arena_any, out_ref, in_buf, read_sems,
                     *, C: int, F: int,
                     n_blocks: int, k: int, m: int, lo_n: int, hi_n: int,
                     tile: int, payload: int = 7, read_rows: int = 0):
    """sc_ref (SMEM [2] i32): start, cnt.  out_ref VMEM
    [n_blocks*k*payload*hi_n*m, N]: payload split components per feature —
    every lhs entry is a bf16-exact payload plane value times a one-hot,
    so the dots run as single bf16 MXU passes and the f32 values are
    reconstructed exactly in the epilogue.  read_rows < C (quantized
    mode) restricts the per-tile DMA to the leading arena rows that the
    3-component payload actually consumes — the row stripe is the
    kernel's whole byte bill, so this IS the quantized bandwidth win."""
    s, cnt = sc_ref[0], sc_ref[1]
    n_tiles = jax.lax.div(cnt + jnp.int32(tile - 1), jnp.int32(tile))
    rows = read_rows or C

    def read_dma(j, slot):
        src = pl.multiple_of(s + j * tile, 128)
        return pltpu.make_async_copy(
            arena_any.at[pl.ds(0, rows), pl.ds(src, tile)],
            in_buf.at[slot], read_sems.at[slot])

    out_ref[:] = jnp.zeros_like(out_ref)

    @pl.when(n_tiles > 0)
    def _():
        read_dma(0, 0).start()
        read_dma(0, 0).wait()

    def loop(j, _):
        slot = jax.lax.rem(j, jnp.int32(2))

        @pl.when(j + 1 < n_tiles)
        def _():
            read_dma(j + 1, jax.lax.rem(j + jnp.int32(1), jnp.int32(2))).start()

        block = in_buf[slot]                              # [rows, T] bf16
        valid = (jax.lax.broadcasted_iota(jnp.int32, (1, tile), 1)
                 < (cnt - j * tile)).astype(jnp.bfloat16)
        _radix_accumulate(out_ref, block, valid, n_blocks=n_blocks, k=k,
                          m=m, lo_n=lo_n, hi_n=hi_n, tile=tile,
                          payload=payload)

        @pl.when(j + 1 < n_tiles)
        def _():
            read_dma(j + 1, jax.lax.rem(j + jnp.int32(1), jnp.int32(2))).wait()
        return 0

    jax.lax.fori_loop(0, n_tiles, loop, 0)


def split_radix_epilogue(out, G: int, m: int, hi_n: int, lo_n: int,
                         payload: int = 7):
    """[G*payload*hi_n*m, N] split-component accumulator -> [G*m, B, 3]:
    payload=7 sums each f32 value's three split-plane partials; payload=3
    (quantized) passes the integer code sums through unchanged."""
    out = out.reshape(G, m, payload, hi_n, m, lo_n)
    diag = jnp.moveaxis(jnp.diagonal(out, axis1=1, axis2=4), -1, 1)
    comp = diag.reshape(G * m, payload, hi_n * lo_n)
    if payload == 3:
        return jnp.stack([comp[:, 0], comp[:, 1], comp[:, 2]], axis=-1)
    g = comp[:, 0] + comp[:, 1] + comp[:, 2]
    h = comp[:, 3] + comp[:, 4] + comp[:, 5]
    return jnp.stack([g, h, comp[:, 6]], axis=-1)         # [G*m, B, 3]


@functools.partial(jax.jit,
                   static_argnames=("num_features", "max_bin", "tile",
                                    "interpret", "quantized"))
def segment_histogram(arena, start, cnt, num_features: int, max_bin: int,
                      tile: int = TILE, interpret: bool = False,
                      quantized: bool = False):
    """[F, max_bin, 3] f32 histogram of arena columns [start, start+cnt).

    quantized=True reads the two int8-code payload planes (arena rows
    Fp+0/Fp+1, see pack_code_planes) instead of the six f32-residue
    planes AND restricts the per-tile DMA to the leading Fp+2 arena rows
    — the returned planes are then exact integer (g_code, h_code, count)
    sums to recover with ops.quantize.dequantize_hist."""
    C, cap = arena.shape
    F = num_features
    lo_n, hi_n, m = _radix_plan(max_bin)
    f_blk = max(m, 8)
    k = f_blk // m
    n_blocks = feature_channels(F) // f_blk
    if n_blocks * f_blk + N_AUX > C:
        raise ValueError("arena channels too small for feature layout")
    payload = 3 if quantized else 7
    # quantized rows: features + the two code planes, DMA-aligned to the
    # 8-sublane granule; everything past that row never leaves HBM
    read_rows = min(C, _align8(n_blocks * f_blk + 2)) if quantized else C
    Mc, N = payload * hi_n * m, lo_n * m
    sc = jnp.stack([jnp.asarray(start), jnp.asarray(cnt)]).astype(jnp.int32)
    kernel = functools.partial(
        _seg_hist_kernel, C=C, F=F, n_blocks=n_blocks, k=k, m=m,
        lo_n=lo_n, hi_n=hi_n, tile=tile, payload=payload,
        read_rows=read_rows)
    out = pl.pallas_call(
        kernel,
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n_blocks * k * Mc, N), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((2, read_rows, tile), ARENA_DT),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        interpret=interpret,
    )(sc, arena)
    hist = split_radix_epilogue(out, n_blocks * k, m, hi_n=hi_n, lo_n=lo_n,
                                payload=payload)
    return hist[:F, :max_bin, :]


def _fused_root_kernel(sc_ref, codes_any, arena_any, out_any, hist_ref,
                       in_buf, code_buf, read_sems, code_sems, write_sems,
                       *, n_blocks: int, k: int, m: int, lo_n: int,
                       hi_n: int, tile: int):
    """Fused per-tree g/h-plane refresh + root histogram over ONE arena
    pass (quantized mode): per tile, DMA in the feature rows and the
    fresh code tile, DMA the codes OUT to the arena's payload planes
    (dynamic-destination HBM DMA — legal, unlike dynamic-offset VMEM
    stores in a fori_loop), and accumulate the 3-component radix
    histogram from the values already in VMEM.

    This replaces the XLA plane update + separate segment_histogram
    launch of the separate-pass schedule: the root segment's rows are
    read ONCE (features only — the stale payload planes never leave
    HBM), and the fresh codes are touched once on the way in instead of
    write-then-re-read.  Naive per-CHILD fusion was measured ~10% worse
    (see grow_partition's dead-end note); the root is different — its
    histogram covers every row of a segment the refresh must stream
    anyway, so the fusion is pure saving, exactly like the bagging root
    partition's hist_stream.

    sc_ref (SMEM [2] i32): start, cnt.  codes_any [2, n_al] bf16 code
    planes in segment order; arena_any/out_any [C, cap] bf16 aliased;
    hist_ref VMEM [n_blocks*k*3*hi_n*m, lo_n*m] f32.

    Write-DMA discipline: write j uses sem slot j%2; it is waited at
    iteration j+1 (before the slot's buffer is refilled for tile j+2),
    and the final two writes are drained after the loop — strict per-slot
    alternation, no global counters.
    """
    s, cnt = sc_ref[0], sc_ref[1]
    n_tiles = jax.lax.div(cnt + jnp.int32(tile - 1), jnp.int32(tile))
    Fp = n_blocks * k * m

    def feat_dma(j, slot):
        src = pl.multiple_of(s + j * tile, 128)
        return pltpu.make_async_copy(
            arena_any.at[pl.ds(0, Fp), pl.ds(src, tile)],
            in_buf.at[slot], read_sems.at[slot])

    def code_read_dma(j, slot):
        src = pl.multiple_of(j * tile, 128)
        return pltpu.make_async_copy(
            codes_any.at[:, pl.ds(src, tile)],
            code_buf.at[slot], code_sems.at[slot])

    def code_write_dma(j, slot):
        dst = pl.multiple_of(s + j * tile, 128)
        return pltpu.make_async_copy(
            code_buf.at[slot],
            out_any.at[pl.ds(Fp, 2), pl.ds(dst, tile)],
            write_sems.at[slot])

    hist_ref[:] = jnp.zeros_like(hist_ref)

    @pl.when(n_tiles > 0)
    def _():
        feat_dma(0, 0).start()
        code_read_dma(0, 0).start()
        feat_dma(0, 0).wait()
        code_read_dma(0, 0).wait()

    def loop(j, _):
        slot = jax.lax.rem(j, jnp.int32(2))
        nslot = jax.lax.rem(j + jnp.int32(1), jnp.int32(2))

        @pl.when(j + 1 < n_tiles)
        def _():
            # nslot's outbound write (issued at j-1) must land before the
            # slot's code buffer is refilled
            @pl.when(j >= 1)
            def _():
                code_write_dma(0, nslot).wait()
            feat_dma(j + 1, nslot).start()
            code_read_dma(j + 1, nslot).start()

        code_write_dma(j, slot).start()

        block = jnp.concatenate([in_buf[slot], code_buf[slot]], axis=0)
        valid = (jax.lax.broadcasted_iota(jnp.int32, (1, tile), 1)
                 < (cnt - j * tile)).astype(jnp.bfloat16)
        _radix_accumulate(hist_ref, block, valid, n_blocks=n_blocks, k=k,
                          m=m, lo_n=lo_n, hi_n=hi_n, tile=tile, payload=3)

        @pl.when(j + 1 < n_tiles)
        def _():
            feat_dma(j + 1, nslot).wait()
            code_read_dma(j + 1, nslot).wait()
        return 0

    jax.lax.fori_loop(0, n_tiles, loop, 0)

    # drain: writes n_tiles-1 and n_tiles-2 are still outstanding (the
    # in-loop wait is skipped on the last iteration)
    @pl.when(n_tiles >= 2)
    def _():
        code_write_dma(0, jax.lax.rem(n_tiles - 2, jnp.int32(2))).wait()

    @pl.when(n_tiles >= 1)
    def _():
        code_write_dma(0, jax.lax.rem(n_tiles - 1, jnp.int32(2))).wait()


@functools.partial(jax.jit,
                   static_argnames=("num_features", "max_bin", "tile",
                                    "interpret"))
def fused_refresh_histogram(arena, codes, start, cnt, num_features: int,
                            max_bin: int, tile: int = TILE,
                            interpret: bool = False):
    """(arena', hist): write the quantized code planes for arena columns
    [start, start+cnt) AND build the segment's integer-code histogram in
    one pass.  codes [2, n] bf16-castable int8-valued planes in segment
    order (pack_code_planes); hist is [F, max_bin, 3] exact integer
    (g_code, h_code, count) sums — recover with quantize.dequantize_hist.
    """
    C, cap = arena.shape
    F = num_features
    lo_n, hi_n, m = _radix_plan(max_bin)
    f_blk = max(m, 8)
    k = f_blk // m
    n_blocks = feature_channels(F) // f_blk
    if n_blocks * f_blk + N_AUX > C:
        raise ValueError("arena channels too small for feature layout")
    Fp = n_blocks * f_blk
    Mc, N = 3 * hi_n * m, lo_n * m
    n = codes.shape[1]
    n_al = -(-n // tile) * tile
    codes = jnp.pad(codes.astype(ARENA_DT), ((0, 0), (0, n_al - n)))
    sc = jnp.stack([jnp.asarray(start), jnp.asarray(cnt)]).astype(jnp.int32)
    kernel = functools.partial(
        _fused_root_kernel, n_blocks=n_blocks, k=k, m=m, lo_n=lo_n,
        hi_n=hi_n, tile=tile)
    outs = pl.pallas_call(
        kernel,
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec(memory_space=pl.ANY),
                  pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=(pl.BlockSpec(memory_space=pl.ANY),
                   pl.BlockSpec(memory_space=pltpu.VMEM)),
        out_shape=(jax.ShapeDtypeStruct((C, cap), ARENA_DT),
                   jax.ShapeDtypeStruct((n_blocks * k * Mc, N),
                                        jnp.float32)),
        scratch_shapes=[
            pltpu.VMEM((2, Fp, tile), ARENA_DT),
            pltpu.VMEM((2, 2, tile), ARENA_DT),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        input_output_aliases={2: 0},
        compiler_params=_side_effect_params(),
        interpret=interpret,
    )(sc, codes, arena)
    hist = split_radix_epilogue(outs[1], n_blocks * k, m, hi_n=hi_n,
                                lo_n=lo_n, payload=3)
    return outs[0], hist[:F, :max_bin, :]


# -- roofline cost models (obs/perf) ------------------------------------- #
from ..obs.perf import KernelCost, cost_model  # noqa: E402

_ARENA_B = 2  # bf16 arena element


@cost_model("partition/segment")
def _cost_partition(rows: int, features: int) -> KernelCost:
    """Stream a parent segment once and write both children (same total
    rows): 2x the segment's arena footprint plus the pred plane slice.
    The per-sub-block permutation matmuls are DMA-overlapped, so FLOPs
    count only the 2*SUB MACs per row that fill otherwise-idle lanes —
    this kernel lives on the bandwidth roof by design."""
    n = int(rows)
    row_b = _ARENA_B * arena_channels(int(features))
    return KernelCost("partition/segment", 2 * n * row_b + n * 4,
                      2 * n * SUB,
                      "parent read + children write, %dB/row" % row_b)


@cost_model("partition/hist")
def _cost_seg_hist(rows: int, features: int, max_bin: int) -> KernelCost:
    """Segment histogram: one pass over the segment's arena rows (bin
    planes AND residue planes ride the same row stripe) plus the
    [F, B, 3] f32 output; 3 accumulates per (row, feature) floor."""
    n, F, B = int(rows), int(features), int(max_bin)
    row_b = _ARENA_B * arena_channels(F)
    return KernelCost("partition/hist", n * row_b + F * B * 3 * 4,
                      3 * n * F, "one arena pass, %dB/row" % row_b)


@cost_model("partition/hist_quantized")
def _cost_seg_hist_q(rows: int, features: int, max_bin: int) -> KernelCost:
    """Quantized segment histogram: the per-tile DMA stops after the
    feature rows + TWO code planes (8-sublane aligned), so the stale
    residue/rowid rows never leave HBM — the row stripe is the whole
    byte bill, so this IS the quantized win over partition/hist."""
    n, F, B = int(rows), int(features), int(max_bin)
    read_rows = min(arena_channels(F), _align8(feature_channels(F) + 2))
    row_b = _ARENA_B * read_rows
    return KernelCost("partition/hist_quantized",
                      n * row_b + F * B * 3 * 4, 3 * n * F,
                      "partial arena pass, %dB/row (f32: %dB)"
                      % (row_b, _ARENA_B * arena_channels(F)))


@cost_model("partition/fused_root")
def _cost_fused_root(rows: int, features: int, max_bin: int) -> KernelCost:
    """Fused refresh+histogram: read the feature rows once plus the
    fresh code planes, write the code planes — replaces the separate
    schedule's plane update (read codes + write planes) AND the full
    arena row stripe of the f32 root segment_histogram."""
    n, F, B = int(rows), int(features), int(max_bin)
    row_b = _ARENA_B * (feature_channels(F) + 2 + 2)   # feats + code r/w
    return KernelCost("partition/fused_root",
                      n * row_b + F * B * 3 * 4, 3 * n * F,
                      "one fused pass, %dB/row vs %dB separate"
                      % (row_b, _ARENA_B * (arena_channels(F) + 2 + 6)))


@cost_model("partition/compact")
def _cost_compact(rows: int, features: int) -> KernelCost:
    """Carry compaction: read every live row once, write it once at its
    packed destination — pure data movement, zero useful FLOPs."""
    n = int(rows)
    row_b = _ARENA_B * arena_channels(int(features))
    return KernelCost("partition/compact", 2 * n * row_b, 0,
                      "pure copy, %dB/row" % row_b)
