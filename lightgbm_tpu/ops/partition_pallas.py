"""Pallas TPU kernels for the partitioned (arena) tree-growth engine.

The TPU re-design of the reference's ordered row partition
(`DataPartition`, src/treelearner/data_partition.hpp:17-222) plus the
per-leaf histogram construction it feeds (src/io/dense_bin.hpp:105-185):
rows live physically grouped by leaf in a feature-major f32 "arena"
`[C, cap]` whose channels are the F binned features followed by
(grad, hess, rowid).  Leaf segments are contiguous column ranges, so

- `partition_segment` splits a parent segment into its two children with
  one sequential pass: per 256-lane sub-block it builds a compaction
  permutation (prefix-scan of the go-left predicate -> position one-hot)
  and applies it as an MXU matmul — a TPU has no fast scatter, so row
  movement is expressed as dense matrix products.  Stream A may be
  written back in place over the parent (writes provably lag reads); the
  other child goes to the bump-allocator cursor.  This mirrors the
  reference's smaller/larger split choreography where only the smaller
  leaf is rebuilt (serial_tree_learner.cpp:360-437).
- `segment_histogram` builds the [F, B, 3] grad/hess/count histogram of
  one leaf by streaming its contiguous segment tiles through the same
  radix-factorized MXU contraction as ops/histogram_pallas.py — per-leaf
  cost is O(leaf_rows), the reference's asymptotics, with sequential HBM
  reads instead of gathers.

All payloads ride f32 (bins are small integers, exact; rowid is exact to
2^24 rows — the 16.7M-row cap is checked by the caller).  Accumulation is
f32, matching the reference GPU learner's single-precision default.

Pipeline invariant in both kernels: tile j's read is complete when its
loop iteration starts; iteration j issues read j+1, computes j (overlapped
with that read), then waits read j+1.  In `partition_segment` the output
writes are issued only after that wait, which makes the in-place stream
safe: writes span at most (j+1)*tile + SUB columns past the segment start
while reads through (j+2)*tile have completed.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .histogram_pallas import _radix_plan, radix_epilogue

SUB = 256          # compaction sub-block width (lanes per permutation matmul)
TILE = 2048        # rows per streamed tile
N_AUX = 3          # grad, hess, rowid channels appended after features


def feature_channels(num_features: int) -> int:
    """Feature channels padded to the histogram kernel's block width; the
    padding rows hold zeros and their (garbage) histograms are sliced off."""
    return num_features + (-num_features % 8)


def arena_channels(num_features: int) -> int:
    """Total arena channels: padded features, then grad/hess/rowid, padded
    for sublane tiling."""
    c = feature_channels(num_features) + N_AUX
    return c + (-c % 8)


def _prefix_scan_lanes(x):
    """Inclusive prefix sum along the last (lane) axis via log-step rolls."""
    n = x.shape[-1]
    lane = jax.lax.broadcasted_iota(jnp.int32, x.shape, x.ndim - 1)
    sh = 1
    while sh < n:
        x = x + jnp.where(lane >= sh, pltpu.roll(x, sh, axis=x.ndim - 1), 0.0)
        sh *= 2
    return x


FLUSH_W = SUB          # flush chunk width; all HBM write offsets are
#                        multiples of FLUSH_W (tiled-memref alignment)
CARRY_W = FLUSH_W + SUB    # per-stream carry width (append window)


def _compact_subblock(block_k, pred_k, fill):
    """Place the columns of `block_k` [C, S] selected by `pred_k` [1, S]
    (0/1 f32) contiguously starting at carry position `fill` (< FLUSH_W):
    prefix-scan -> destination one-hot P[u, fill + pos_u] [S, CARRY_W] ->
    one [C, S] @ [S, CARRY_W] MXU matmul.  Positioning is baked into P so
    no dynamic roll/shift of the carry is ever needed.  Returns
    (comp [C, CARRY_W], count); columns outside [fill, fill+count) are 0."""
    prefix = _prefix_scan_lanes(pred_k)                       # [1, S]
    cnt_k = prefix[0, SUB - 1].astype(jnp.int32)
    pos_col = (prefix - 1.0).astype(jnp.int32).reshape(SUB, 1) + fill
    sel_col = pred_k.reshape(SUB, 1) > 0.5
    t_iota = jax.lax.broadcasted_iota(jnp.int32, (SUB, CARRY_W), 1)
    P = jnp.where((pos_col == t_iota) & sel_col,
                  jnp.float32(1.0), jnp.float32(0.0))
    comp = jax.lax.dot(block_k, P, preferred_element_type=jnp.float32,
                       precision=jax.lax.Precision.HIGHEST)
    return comp, cnt_k


def _partition_kernel(sc_ref, feat_onehot_ref, arena_any, pred_any,
                      out_any, cnt_ref,
                      in_buf, pred_buf, carryA, carryB, flush_buf,
                      read_sems, pred_sems, write_sems,
                      *, C: int, tile: int):
    """sc_ref (SMEM [11] i32): start, cnt, dstA, dstB, mode, thr, dl, mt,
    db, mb, xr — start, dstA and dstB must be multiples of `tile` resp.
    FLUSH_W (the bump allocator aligns).
    arena_any/out_any: [C, cap] f32 in HBM, aliased (same buffer).
    Routing: mode=0 reads pred_any ([1, cap] f32, 1.0 -> stream A); mode=1
    computes the split decision in-kernel — the feature row is extracted
    with a one-hot matvec (feat_onehot_ref [1, C], bins < 256 are
    bf16-exact) and a row goes to stream A when the reference's
    NumericalDecision (tree.h:429-465) XOR'd with dl says "larger child":
    dl is the node's default_left, xr is XOR'd in (1 when the left child
    is the smaller/bump-allocated side), and missing bins are identified
    via mt (missing type), db (default bin), mb (last bin).
    cnt_ref (SMEM out [2] i32): rows written to A and B.

    Each SUB-lane sub-block is compacted with an MXU permutation matmul
    and appended into a narrow per-stream VMEM carry via dynamic-shift
    roll + add (appends are disjoint); whenever a carry holds FLUSH_W
    rows, that chunk is DMA'd to the stream's next FLUSH_W-aligned arena
    columns.  Stream A may write over the parent segment in place: flushed
    columns [dstA + wA, +FLUSH_W) always lie within the rows already read,
    because wA + FLUSH_W <= rows consumed so far <= (j+1)*tile and tile j
    is fully read before its sub-blocks are appended.
    """
    s, cnt = sc_ref[0], sc_ref[1]
    dstA, dstB = sc_ref[2], sc_ref[3]
    mode, thr = sc_ref[4], sc_ref[5]
    dl, mt, db, mb = sc_ref[6], sc_ref[7], sc_ref[8], sc_ref[9]
    xr = sc_ref[10]   # XOR'd into the decision: 1 when the left child is
    #                   the smaller (stream-B) side
    n_tiles = jax.lax.div(cnt + jnp.int32(tile - 1), jnp.int32(tile))
    K = tile // SUB
    lane_w = jax.lax.broadcasted_iota(jnp.int32, (C, CARRY_W), 1)

    def read_dmas(j, slot):
        src = pl.multiple_of(s + j * tile, 128)
        # the pred stream is only consumed in mode 0 but always read —
        # [1, tile] is ~3% of the arena tile and keeps the DMA plumbing
        # uniform
        return (pltpu.make_async_copy(
                    arena_any.at[:, pl.ds(src, tile)],
                    in_buf.at[slot], read_sems.at[slot]),
                pltpu.make_async_copy(
                    pred_any.at[:, pl.ds(src, tile)],
                    pred_buf.at[slot], pred_sems.at[slot]))

    def flush_dma(stream, slot, dst_col):
        return pltpu.make_async_copy(
            flush_buf.at[stream, slot],
            out_any.at[:, pl.ds(pl.multiple_of(dst_col, 128), FLUSH_W)],
            write_sems.at[stream, slot])

    @pl.when(n_tiles > 0)
    def _():
        for d in read_dmas(0, 0):
            d.start()
        for d in read_dmas(0, 0):
            d.wait()
    carryA[:] = jnp.zeros((C, CARRY_W), jnp.float32)
    carryB[:] = jnp.zeros((C, CARRY_W), jnp.float32)

    def append_and_flush(carry, comp, ck, fill, written, dst, stream, fslot):
        """Add comp (already positioned at `fill`) into the carry; flush one
        FLUSH_W chunk if filled.  Returns (fill', written', fslot')."""
        carry[:] = carry[:] + comp
        fill = fill + ck

        @pl.when(fill >= FLUSH_W)
        def _():
            # previous flush of this slot (two flushes ago) must have landed
            @pl.when(written >= 2 * FLUSH_W)
            def _():
                flush_dma(stream, fslot, 0).wait()
            flush_buf[stream, fslot] = carry[:, 0:FLUSH_W]
            flush_dma(stream, fslot, dst + written).start()
            shifted = pltpu.roll(carry[:], CARRY_W - FLUSH_W, axis=1)
            carry[:] = jnp.where(lane_w < fill - FLUSH_W, shifted, 0.0)

        flushed = fill >= FLUSH_W
        fill = jnp.where(flushed, fill - FLUSH_W, fill)
        written = jnp.where(flushed, written + FLUSH_W, written)
        fslot = jnp.where(flushed, 1 - fslot, fslot)
        return fill, written, fslot

    def loop(j, carry_state):
        fillA, wA, fsA, fillB, wB, fsB = carry_state
        slot = jax.lax.rem(j, jnp.int32(2))
        nslot = jax.lax.rem(j + jnp.int32(1), jnp.int32(2))

        @pl.when(j + 1 < n_tiles)
        def _():
            for d in read_dmas(j + 1, nslot):
                d.start()

        valid = jax.lax.broadcasted_iota(
            jnp.int32, (1, tile), 1) < (cnt - j * tile)
        block = in_buf[slot]
        # in-kernel split decision (mode 1): feature row via one-hot
        # matvec, then pure f32 arithmetic (scalar-broadcast bool selects
        # crash the Mosaic compiler)
        col = jnp.round(jax.lax.dot(feat_onehot_ref[:], block,
                                    preferred_element_type=jnp.float32)
                        ).astype(jnp.int32)                   # [1, T]
        f = lambda c: jnp.where(c, jnp.float32(1.0), jnp.float32(0.0))
        missing_f = f(((mt == 1) & (col == db)) | ((mt == 2) & (col == mb)))
        dl_f = jnp.float32(dl)
        go_left_f = missing_f * dl_f + (1.0 - missing_f) * f(col <= thr)
        xr_f = jnp.float32(xr)
        decide_f = go_left_f + xr_f - 2.0 * go_left_f * xr_f   # xor
        mode_f = jnp.float32(mode)
        on_f = mode_f * decide_f + (1.0 - mode_f) * pred_buf[slot]
        on = on_f > 0.5
        predA = jnp.where(valid & on, jnp.float32(1.0), jnp.float32(0.0))
        predB = jnp.where(valid & ~on, jnp.float32(1.0), jnp.float32(0.0))

        for k in range(K):
            blk = block[:, k * SUB:(k + 1) * SUB]
            compA, ca = _compact_subblock(
                blk, predA[:, k * SUB:(k + 1) * SUB], fillA)
            compB, cb = _compact_subblock(
                blk, predB[:, k * SUB:(k + 1) * SUB], fillB)
            fillA, wA, fsA = append_and_flush(
                carryA, compA, ca, fillA, wA, dstA, 0, fsA)
            fillB, wB, fsB = append_and_flush(
                carryB, compB, cb, fillB, wB, dstB, 1, fsB)

        @pl.when(j + 1 < n_tiles)
        def _():
            for d in read_dmas(j + 1, nslot):
                d.wait()
        return fillA, wA, fsA, fillB, wB, fsB

    z = jnp.int32(0)
    fillA, wA, fsA, fillB, wB, fsB = jax.lax.fori_loop(
        0, n_tiles, loop, (z, z, z, z, z, z))

    # Final partial flush, then drain every in-flight DMA.  With c = w /
    # FLUSH_W loop flushes, the in-loop waits consumed the signals of
    # flushes 0..c-3; flushes c-2 (slot fslot) and c-1 (slot 1-fslot) are
    # still outstanding and every one must be waited before kernel exit.
    for stream, carry, fill, w, dst, fslot in (
            (0, carryA, fillA, wA, dstA, fsA),
            (1, carryB, fillB, wB, dstB, fsB)):
        @pl.when(fill > 0)
        def _(stream=stream, carry=carry, fill=fill, w=w, dst=dst,
              fslot=fslot):
            @pl.when(w >= 2 * FLUSH_W)
            def _():
                flush_dma(stream, fslot, 0).wait()     # flush c-2
            flush_buf[stream, fslot] = carry[:, 0:FLUSH_W]
            flush_dma(stream, fslot, dst + w).start()
            flush_dma(stream, fslot, 0).wait()         # the final flush

        @pl.when((fill == 0) & (w >= 2 * FLUSH_W))
        def _(stream=stream, fslot=fslot):
            flush_dma(stream, fslot, 0).wait()         # flush c-2

        @pl.when(w >= FLUSH_W)
        def _(stream=stream, fslot=fslot):
            flush_dma(stream, 1 - fslot, 0).wait()     # flush c-1

    cnt_ref[0] = wA + fillA
    cnt_ref[1] = wB + fillB


@functools.partial(jax.jit, static_argnames=("tile", "interpret"))
def partition_segment(arena, pred, start, cnt, dstA, dstB,
                      decision=None,
                      tile: int = TILE, interpret: bool = False):
    """Partition arena columns [start, start+cnt) into stream A at dstA
    (dstA == start allowed: in-place with lagging writes) and stream B at
    dstB (must not overlap [start, start+cnt+tile)).

    Routing: by `pred` ([1, cap] f32, 1.0 -> A) when decision is None,
    else by the in-kernel split decision — decision = (feat_channel, thr,
    default_left, missing_type, default_bin, max_bin_idx, xor_flag)
    scalars; pred is then ignored (pass any [1, cap] array).

    Returns (new_arena, counts[2] int32).  Writes stay within
    align(count, FLUSH_W) columns of each stream's dst; reads overrun the
    segment by < tile columns, so callers keep cap >= last segment + tile.
    """
    C, cap = arena.shape
    z = jnp.int32(0)
    if decision is None:
        tail = [z] * 7
        feat_onehot = jnp.zeros((1, C), jnp.float32)
    else:
        feat, thr, dlft, mt, db, mb, xr = [
            jnp.asarray(v, jnp.int32) for v in decision]
        tail = [jnp.int32(1), thr, dlft, mt, db, mb, xr]
        feat_onehot = (jnp.arange(C, dtype=jnp.int32)[None, :]
                       == feat).astype(jnp.float32)
    sc = jnp.stack([jnp.asarray(start), jnp.asarray(cnt),
                    jnp.asarray(dstA), jnp.asarray(dstB)]
                   + tail).astype(jnp.int32)
    kernel = functools.partial(_partition_kernel, C=C, tile=tile)
    arena_out, counts = pl.pallas_call(
        kernel,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=(pl.BlockSpec(memory_space=pl.ANY),
                   pl.BlockSpec(memory_space=pltpu.SMEM)),
        out_shape=(jax.ShapeDtypeStruct((C, cap), jnp.float32),
                   jax.ShapeDtypeStruct((2,), jnp.int32)),
        scratch_shapes=[
            pltpu.VMEM((2, C, tile), jnp.float32),
            pltpu.VMEM((2, 1, tile), jnp.float32),
            pltpu.VMEM((C, CARRY_W), jnp.float32),
            pltpu.VMEM((C, CARRY_W), jnp.float32),
            pltpu.VMEM((2, 2, C, FLUSH_W), jnp.float32),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2, 2)),
        ],
        input_output_aliases={2: 0},
        compiler_params=pltpu.CompilerParams(has_side_effects=True),
        interpret=interpret,
    )(sc, feat_onehot, arena, pred)
    return arena_out, counts


def _seg_hist_kernel(sc_ref, arena_any, out_ref, in_buf, read_sems,
                     *, C: int, F: int,
                     n_blocks: int, k: int, m: int, lo_n: int, hi_n: int,
                     tile: int):
    """sc_ref (SMEM [2] i32): start, cnt.  out_ref VMEM [n_blocks*k*M, N]."""
    s, cnt = sc_ref[0], sc_ref[1]
    n_tiles = jax.lax.div(cnt + jnp.int32(tile - 1), jnp.int32(tile))
    M, N = 3 * hi_n * m, lo_n * m
    f_blk = k * m

    def read_dma(j, slot):
        src = pl.multiple_of(s + j * tile, 128)
        return pltpu.make_async_copy(
            arena_any.at[:, pl.ds(src, tile)],
            in_buf.at[slot], read_sems.at[slot])

    out_ref[:] = jnp.zeros_like(out_ref)

    @pl.when(n_tiles > 0)
    def _():
        read_dma(0, 0).start()
        read_dma(0, 0).wait()

    def loop(j, _):
        slot = jax.lax.rem(j, jnp.int32(2))

        @pl.when(j + 1 < n_tiles)
        def _():
            read_dma(j + 1, jax.lax.rem(j + jnp.int32(1), jnp.int32(2))).start()

        block = in_buf[slot]                              # [C, T]
        valid = (jax.lax.broadcasted_iota(jnp.int32, (1, tile), 1)
                 < (cnt - j * tile)).astype(jnp.float32)
        Fp = n_blocks * f_blk
        g = block[Fp:Fp + 1, :] * valid
        h = block[Fp + 1:Fp + 2, :] * valid
        gh = jnp.concatenate([g, h, valid], axis=0)       # [3, T]

        for b in range(n_blocks):
            bins = block[b * f_blk:(b + 1) * f_blk, :]    # [f_blk, T]
            hi = jnp.floor(bins * (1.0 / lo_n))
            lo = bins - hi * lo_n
            hih = jnp.where(
                hi.astype(jnp.int32)[:, None, :]
                == jax.lax.broadcasted_iota(jnp.int32, (1, hi_n, 1), 1),
                jnp.float32(1.0), jnp.float32(0.0))                                 # [f_blk,hi_n,T]
            loh = jnp.where(
                lo.astype(jnp.int32)[:, None, :]
                == jax.lax.broadcasted_iota(jnp.int32, (1, lo_n, 1), 1),
                jnp.float32(1.0), jnp.float32(0.0))                                 # [f_blk,lo_n,T]
            lhs = (gh[None, :, None, :] * hih[:, None, :, :]).reshape(
                k, M, tile)
            rhs = loh.reshape(k, N, tile)
            part = jax.lax.dot_general(
                lhs, rhs, dimension_numbers=(((2,), (2,)), ((0,), (0,))),
                preferred_element_type=jnp.float32,
                precision=jax.lax.Precision.HIGHEST)      # [k, M, N]
            out_ref[b * k * M:(b + 1) * k * M, :] = (
                out_ref[b * k * M:(b + 1) * k * M, :]
                + part.reshape(k * M, N))

        @pl.when(j + 1 < n_tiles)
        def _():
            read_dma(j + 1, jax.lax.rem(j + jnp.int32(1), jnp.int32(2))).wait()
        return 0

    jax.lax.fori_loop(0, n_tiles, loop, 0)



@functools.partial(jax.jit,
                   static_argnames=("num_features", "max_bin", "tile",
                                    "interpret"))
def segment_histogram(arena, start, cnt, num_features: int, max_bin: int,
                      tile: int = TILE, interpret: bool = False):
    """[F, max_bin, 3] f32 histogram of arena columns [start, start+cnt)."""
    C, cap = arena.shape
    F = num_features
    lo_n, hi_n, m = _radix_plan(max_bin)
    f_blk = max(m, 8)
    k = f_blk // m
    n_blocks = feature_channels(F) // f_blk
    if n_blocks * f_blk + N_AUX > C:
        raise ValueError("arena channels too small for feature layout")
    M, N = 3 * hi_n * m, lo_n * m
    sc = jnp.stack([jnp.asarray(start), jnp.asarray(cnt)]).astype(jnp.int32)
    kernel = functools.partial(
        _seg_hist_kernel, C=C, F=F, n_blocks=n_blocks, k=k, m=m,
        lo_n=lo_n, hi_n=hi_n, tile=tile)
    out = pl.pallas_call(
        kernel,
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n_blocks * k * M, N), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((2, C, tile), jnp.float32),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        interpret=interpret,
    )(sc, arena)
    hist = radix_epilogue(out, n_blocks * k, m, lo_n=lo_n, hi_n=hi_n)
    return hist[:F, :max_bin, :]
