"""Batched device prediction over raw features — signature-matmul design.

The reference predicts tree-by-tree, row-by-row on the host
(gbdt_prediction.cpp + Tree::Predict, tree.h:429-512).  A literal
vectorized node WALK on TPU is gather-bound (per-(tree,row) table reads
lower to scalar gathers).  Instead, prediction is restructured to ride
the MXU:

1. decisions for ALL nodes of ALL trees are computed densely:
   D[row, t*n] = +-1 from one contiguous column-take of X + elementwise
   missing/categorical handling;
2. each leaf's root-to-leaf path is a signature row A[t, leaf, node] in
   {+1 (expects left), -1 (expects right), 0 (off path)}; a row reaches
   the leaf iff  sum_n A[l,n] * D[n] == path_len[l] — ONE batched bf16
   matmul per chunk (inputs are +-1/0 so bf16 is exact, sums <= depth);
3. leaf values dot the 0/1 match indicator (f32, exact).

500 trees x 1M rows is then a few TFLOP of bf16 matmul instead of 1e9
serial gathers.  Shapes are quantized (trees padded to a power of two,
rows chunked) so repeated predicts reuse the compiled executable.
Prediction early stop stays on the host path (inherently row-dependent
pruning, predict_raw in models/gbdt.py).
"""
from __future__ import annotations

from functools import partial
from typing import List

import numpy as np
import jax
import jax.numpy as jnp

MISSING_NONE = 0
MISSING_ZERO = 1
MISSING_NAN = 2
K_ZERO_THRESHOLD = 1e-35
_MAX_CAT_W = 4096
_MAX_SIG_ELEMS = 1 << 30   # cap on the [T, L, N] signature tensor

# device-path threshold: below this many (tree x row) pairs the host walk
# is cheaper than a compile + dispatch
MIN_DEVICE_WORK = 1 << 22
# bound D ([rows, T*N]) to ~2^27 elements per chunk
_CHUNK_BUDGET = 1 << 27


def _next_pow2(x: int) -> int:
    p = 1
    while p < x:
        p *= 2
    return p


def bucket_rows(n: int, max_bucket: int = 1 << 20) -> int:
    """Row-count bucket for executable reuse: the next power of two,
    capped so giant requests chunk through predict_sum instead of
    compiling a bespoke one-off executable."""
    return min(_next_pow2(max(n, 1)), _next_pow2(max_bucket))


def pow2_buckets(max_batch: int) -> List[int]:
    """All power-of-two bucket sizes up to (and including) max_batch —
    the default warmup set for serving."""
    out, b = [], 1
    top = _next_pow2(max(max_batch, 1))
    while b <= top:
        out.append(b)
        b *= 2
    return out


def ensemble_layout(trees: List, num_classes: int) -> dict:
    """The padded device-array shapes DeviceEnsemble will build for
    these trees, computed WITHOUT touching the device.  Trees are padded
    to k * pow2(iterations) — keeps the per-class reshape exact and
    quantizes shapes for executable reuse.  ``ok`` False means the
    ensemble cannot run on device (giant signature tensor / category
    ids) and the host walk keeps prediction duty.

    The serving residency manager (serving/fleet.py) sizes ensembles
    from this layout BEFORE building them, so eviction happens ahead of
    allocation instead of after an OOM."""
    k = max(num_classes, 1)
    T = k * _next_pow2(max(-(-len(trees) // k), 1))
    N = max(max((t.num_leaves - 1 for t in trees), default=1), 1)
    L = _next_pow2(N + 1)
    any_cat = any(t.num_cat > 0 for t in trees)
    # O(trees * leaves^2) signature tensor must fit; the categorical
    # bitset tensor [T*N, W] has its own budget
    ok = T * L * N <= _MAX_SIG_ELEMS
    W = 0
    if ok and any_cat:
        if T * N * _MAX_CAT_W > _MAX_SIG_ELEMS:
            ok = False
        else:
            mx = 31
            for t in trees:
                if t.num_cat > 0:
                    bits = np.asarray(t.cat_threshold, np.uint32)
                    nz = np.flatnonzero(bits)
                    if len(nz):
                        mx = max(mx, 32 * int(nz[-1]) + 31)
            W = _next_pow2(mx + 1)
            if W > _MAX_CAT_W:
                ok = False          # enormous category ids: host path
    return {"k": k, "T": T, "N": N, "L": L, "W": W,
            "any_cat": any_cat, "ok": ok}


def estimate_device_bytes(trees: List, num_classes: int,
                          x64: bool = None) -> int:
    """HBM bytes the DeviceEnsemble for `trees` will hold, from the
    layout alone — exact (matches device_bytes() of the built ensemble),
    so byte-budget reservations made before the build never drift from
    the accounting after it.  None when the ensemble is host-only."""
    lay = ensemble_layout(trees, num_classes)
    if not lay["ok"]:
        return None
    if x64 is None:
        x64 = bool(jax.config.jax_enable_x64)
    T, N, L, W = lay["T"], lay["N"], lay["L"], lay["W"]
    fb = 8 if x64 else 4
    total = T * N * 4                       # sf_flat  int32
    total += T * N * fb                     # thr_flat f64/f32
    if not x64:
        total += T * N * 4                  # thr_lo   f32 (double-single)
    total += T * N * 1                      # dl_flat  bool
    total += T * N * 4                      # mt_flat  int32
    if lay["any_cat"]:
        total += T * N * 1                  # ic_flat  bool
        total += T * N * max(W, 1) * 1      # cat bitset bool
    total += T * L * N * 2                  # sig      bf16
    total += T * L * 4                      # path_len f32
    total += T * L * fb                     # lv       f64/f32
    return int(total)


class DeviceEnsemble:
    """Stacked ensemble for device prediction; built once per model state
    (callers cache on len(models)).

    `device`: commit the ensemble's arrays to that jax device
    (``jax.device_put``).  Committed constants force every jit dispatch
    onto that device (uncommitted row inputs follow), which is how the
    serving replica sets (serving/replicas.py) pin one copy per fault
    domain.  None keeps the historical uncommitted ``jnp.asarray``
    placement — the default-device path, byte-identical to pre-replica
    behavior."""

    def __init__(self, trees: List, num_classes: int, device=None):
        lay = ensemble_layout(trees, num_classes)
        self.k = lay["k"]
        self.num_trees = len(trees)
        self.ok = lay["ok"]
        self.device = device
        T, N, L, W = lay["T"], lay["N"], lay["L"], lay["W"]
        self.T, self.N, self.L, self.W = T, N, L, W
        if not self.ok:
            return

        sf = np.zeros((T, N), np.int64)
        thr = np.zeros((T, N), np.float64)
        dl = np.zeros((T, N), bool)
        mt = np.zeros((T, N), np.int8)
        ic = np.zeros((T, N), bool)
        sig = np.zeros((T, L, N), np.int8)
        path_len = np.full((T, L), -1, np.int32)  # -1: no such leaf
        lv = np.zeros((T, L), np.float64)

        any_cat = lay["any_cat"]
        cat = np.zeros((T * N, max(W, 1)), bool) if any_cat else None

        for ti, t in enumerate(trees):
            n_nodes = t.num_leaves - 1
            lv[ti, :max(t.num_leaves, 1)] = t.leaf_value[:max(t.num_leaves, 1)]
            if n_nodes <= 0:
                path_len[ti, 0] = 0      # constant tree: leaf 0, empty path
                continue
            sf[ti, :n_nodes] = t.split_feature[:n_nodes]
            thr[ti, :n_nodes] = t.threshold[:n_nodes]
            d = np.asarray(t.decision_type[:n_nodes], np.int64)
            ic[ti, :n_nodes] = (d & 1) > 0         # K_CATEGORICAL_MASK
            dl[ti, :n_nodes] = (d & 2) > 0         # K_DEFAULT_LEFT_MASK
            mt[ti, :n_nodes] = (d >> 2) & 3
            # root-to-leaf signatures (iterative DFS)
            stack = [(0, [], [])]
            while stack:
                node, nodes, dirs = stack.pop()
                if node < 0:
                    leaf = ~node
                    sig[ti, leaf, nodes] = dirs
                    path_len[ti, leaf] = len(nodes)
                    continue
                stack.append((int(t.left_child[node]),
                              nodes + [node], dirs + [1]))
                stack.append((int(t.right_child[node]),
                              nodes + [node], dirs + [-1]))
            if t.num_cat > 0:
                for nd in np.flatnonzero(ic[ti, :n_nodes]):
                    ci = int(t.threshold[nd])
                    lo = t.cat_boundaries[ci]
                    hi = t.cat_boundaries[ci + 1]
                    bits = np.asarray(t.cat_threshold[lo:hi], np.uint32)
                    vals = np.arange(min(len(bits) * 32, W))
                    member = (bits[vals // 32] >> (vals % 32)) & 1
                    cat[ti * N + nd, :len(vals)] = member.astype(bool)

        self.x64 = bool(jax.config.jax_enable_x64)
        fdt = jnp.float64 if self.x64 else jnp.float32

        def _dev(a, dtype=None):
            arr = jnp.asarray(a) if dtype is None else jnp.asarray(a, dtype)
            return arr if device is None else jax.device_put(arr, device)

        self.sf_flat = _dev(sf.reshape(-1).astype(np.int32))
        self.thr_flat = _dev(thr.reshape(-1), fdt)
        if self.x64:
            self.thr_lo = None
        else:
            # double-single threshold split: comparisons against the f64
            # thresholds stay ~2^-48-exact in f32 (the host walk compares
            # in f64; a plain f32 downcast would flip boundary rows)
            t_hi = thr.reshape(-1).astype(np.float32)
            self.thr_lo = _dev(
                (thr.reshape(-1) - t_hi.astype(np.float64))
                .astype(np.float32))
        self.dl_flat = _dev(dl.reshape(-1))
        self.mt_flat = _dev(mt.reshape(-1).astype(np.int32))
        self.ic_flat = _dev(ic.reshape(-1)) if any_cat else None
        self.cat = _dev(cat) if any_cat else None
        self.sig = _dev(sig, jnp.bfloat16)                 # +-1/0 exact
        self.path_len = _dev(path_len.astype(np.float32))
        self.lv = _dev(lv, fdt)

    def predict_sum(self, X: np.ndarray, num_iteration: int) -> np.ndarray:
        """[k, n] summed raw scores over the first num_iteration*k trees."""
        n = X.shape[0]
        k = self.k
        use_T = num_iteration * k
        tmask = (np.arange(self.T) < use_T)
        lv = self.lv * jnp.asarray(tmask[:, None], self.lv.dtype)
        chunk = max(256, _CHUNK_BUDGET // max(self.T * self.N, 1))
        X64 = np.asarray(X, np.float64)
        if self.x64:
            Xd = jnp.asarray(X64)
            Xlo = None
        else:
            hi = X64.astype(np.float32)
            Xd = jnp.asarray(hi)
            Xlo = jnp.asarray((X64 - hi.astype(np.float64))
                              .astype(np.float32))
        parts = []
        for a in range(0, n, chunk):
            b = min(n, a + chunk)
            xc = Xd[a:b]
            xl = None if Xlo is None else Xlo[a:b]
            if b - a < chunk and n > chunk:
                xc = jnp.pad(xc, ((0, chunk - (b - a)), (0, 0)))
                if xl is not None:
                    xl = jnp.pad(xl, ((0, chunk - (b - a)), (0, 0)))
            parts.append(_chunk_scores(
                xc, xl, self.sf_flat, self.thr_flat, self.thr_lo,
                self.dl_flat, self.mt_flat, self.ic_flat,
                self.cat, self.sig, self.path_len, lv,
                k=k, T=self.T, N=self.N))
        # ONE host transfer at the end — a per-chunk np.asarray would pay
        # a blocking device sync per chunk (remote-attached TPUs)
        out = np.array(jnp.concatenate(parts, axis=1), np.float64)
        return out[:, :n]

    # -- serving hooks ----------------------------------------------- #
    def device_bytes(self) -> int:
        """HBM bytes held by this ensemble's device arrays (0 when the
        ensemble is host-only) — the residency manager's accounting
        unit; equals estimate_device_bytes() for the same trees."""
        if not self.ok:
            return 0
        arrs = (self.sf_flat, self.thr_flat, self.thr_lo, self.dl_flat,
                self.mt_flat, self.ic_flat, self.cat, self.sig,
                self.path_len, self.lv)
        return int(sum(a.nbytes for a in arrs if a is not None))

    def shape_signature(self, num_features: int) -> tuple:
        """Executable identity for the fleet compile cache: two
        ensembles with equal signatures hit the SAME `_chunk_scores`
        executables per row bucket — the jit statics (k, T, N) and every
        traced array shape/dtype are functions of these values, so equal
        signatures cannot false-share and unequal ones cannot collide."""
        return (self.k, self.T, self.N, self.L, self.W,
                int(num_features), self.x64)

    def predict_bucketed(self, X: np.ndarray, num_iteration: int,
                         max_bucket: int = 1 << 20) -> np.ndarray:
        """predict_sum with rows padded to the power-of-two bucket, so
        every request size between buckets reuses ONE compiled
        executable (the serving hot path; per-row results are unchanged
        by padding — reductions are row-independent).  Returns [k, n]."""
        n = X.shape[0]
        B = bucket_rows(n, max_bucket)
        if B > n:
            Xp = np.zeros((B, X.shape[1]), X.dtype)
            Xp[:n] = X
        else:
            Xp = X
        return self.predict_sum(Xp, num_iteration)[:, :n]

    def warmup_buckets(self, num_features: int, buckets,
                       num_iteration: int) -> List[int]:
        """Pre-compile the per-bucket executables a server will hit, so
        the first real request never waits on XLA.  Returns the bucket
        sizes actually compiled."""
        done = []
        for b in sorted(set(int(x) for x in buckets)):
            if b <= 0:
                continue
            self.predict_sum(np.zeros((b, num_features), np.float64),
                             num_iteration)
            done.append(b)
        return done


@partial(jax.jit, static_argnames=("k", "T", "N"))
def _chunk_scores(X, X_lo, sf_flat, thr_flat, thr_lo, dl_flat, mt_flat,
                  ic_flat, cat, sig, path_len, lv, *, k: int, T: int, N: int):
    """[k, rows] summed scores for one row chunk."""
    rows = X.shape[0]
    # dense decisions for every node: contiguous column take, elementwise
    # missing handling (NumericalDecision, tree.h:429-465)
    fv = jnp.take(X, sf_flat, axis=1)                    # [rows, T*N]
    nan_mask = jnp.isnan(fv)
    zero_nan = nan_mask & (mt_flat != MISSING_NAN)[None, :]
    fv_num = jnp.where(zero_nan, 0.0, fv)
    is_zero = jnp.abs(fv_num) <= K_ZERO_THRESHOLD
    missing = ((mt_flat == MISSING_ZERO)[None, :] & is_zero) | \
              ((mt_flat == MISSING_NAN)[None, :] & jnp.isnan(fv_num))
    if X_lo is None:
        le = fv_num <= thr_flat[None, :]
    else:
        # double-single comparison: lexicographic on (hi, lo) pairs keeps
        # the f64 threshold semantics without x64
        fv_lo = jnp.where(zero_nan, 0.0, jnp.take(X_lo, sf_flat, axis=1))
        th = thr_flat[None, :]
        le = (fv_num < th) | ((fv_num == th) & (fv_lo <= thr_lo[None, :]))
    go_left = jnp.where(missing, dl_flat[None, :], le)
    if ic_flat is not None:
        # categorical membership: per-(row, cat-node) bitset lookup
        # (CategoricalDecision, tree.h:249-267).  int truncation like
        # static_cast<int> (so -0.5 tests category 0); ids beyond the
        # bitset width are non-members, not clipped
        nan_fv = jnp.isnan(fv)
        iv_raw = jnp.where(nan_fv, 0.0, fv).astype(jnp.int32)
        in_range = (~nan_fv) & (iv_raw >= 0) & (iv_raw < cat.shape[1])
        iv = jnp.clip(iv_raw, 0, cat.shape[1] - 1)
        member = _cat_member(cat, iv) & in_range
        go_left = jnp.where(ic_flat[None, :], member, go_left)
    D = jnp.where(go_left, 1.0, -1.0).astype(jnp.bfloat16)
    D3 = D.reshape(rows, T, N)
    # per-tree signature match: s[t, l, r] = sum_n sig[t,l,n] * D[r,t,n]
    s = jnp.einsum("tln,rtn->tlr", sig, D3,
                   preferred_element_type=jnp.float32)
    ind = (s == path_len[:, :, None]).astype(lv.dtype)   # exactly one per t
    vals = jnp.einsum("tlr,tl->tr", ind, lv,
                      precision=jax.lax.Precision.HIGHEST)
    return jnp.sum(vals.reshape(T // k, k, rows), axis=0)


def _cat_member(cat, iv):
    """cat: [T*N, W] bool; iv: [rows, T*N] -> [rows, T*N] membership."""
    # gather per (node, value): transpose so the node axis aligns
    return jnp.take_along_axis(cat[None, :, :],
                               iv.astype(jnp.int32)[:, :, None],
                               axis=2)[:, :, 0]


# -- roofline cost model (obs/perf) -------------------------------------- #
from ..obs.perf import KernelCost, cost_model  # noqa: E402


@cost_model("predict/ensemble")
def _cost_predict(rows: int, features: int, trees: int, leaves: int,
                  nodes: int, classes: int = 1) -> KernelCost:
    """Signature-matmul prediction: stream X once (hi+lo planes when
    split-f32 is active — modeled as the f32 plane only, the floor),
    read the ensemble constants (sig dominates at [T, L, N] bf16), and
    write [rows, k] scores.  FLOPs are the two einsums the MXU
    executes: the [T,L,N]x[rows,T,N] signature match plus the [T,L]
    leaf-value contraction, on top of T*N threshold compares."""
    r, F = int(rows), int(features)
    T, L, N, k = int(trees), int(leaves), int(nodes), max(int(classes), 1)
    nbytes = r * F * 4 + T * L * N * 2 + T * L * 4 + r * k * 4
    flops = 2 * r * T * L * N + 2 * r * T * L + 3 * r * T * N
    return KernelCost("predict/ensemble", nbytes, flops,
                      "sig einsum dominates: 2*rows*T*L*N MACs")
