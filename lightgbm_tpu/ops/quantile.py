"""Device per-leaf percentile renewal for L1-family objectives.

RenewTreeOutput for regression_l1 / quantile / MAPE re-fits every leaf
output to a (weighted) percentile of the leaf's residuals (reference
regression_objective.hpp:17-69 PercentileFun/WeightedPercentileFun +
serial_tree_learner.cpp:850-928).  The reference scans rows per leaf on
the host; here ALL leaves are renewed in one device pass: rows are
grouped by (leaf, residual) with two stable argsorts, per-leaf offsets
come from a bincount, and the percentile interpolation is a handful of
[num_leaves]-sized gathers — no per-leaf host loop, no score transfer.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

K_EPSILON = 1e-15


@partial(jax.jit, static_argnames=("L",))
def renew_leaf_percentiles(residual, lids, alpha, *, L: int, weights=None):
    """[L] percentile of residuals per leaf (leaves without rows -> 0).

    residual: [n]; lids: [n] int32 row->leaf (-1 = out of bag); alpha:
    scalar; weights: [n] or None.  Follows PercentileFun's descending
    interpolation and WeightedPercentileFun's CDF interpolation exactly.
    """
    n = residual.shape[0]
    lid = jnp.where(lids >= 0, lids, L).astype(jnp.int32)
    # ascending residual within each leaf: stable two-pass argsort
    o1 = jnp.argsort(residual, stable=True)
    o2 = jnp.argsort(lid[o1], stable=True)
    order = o1[o2]
    v = residual[order]
    counts = jnp.bincount(lid, length=L + 1)[:L]
    ends = jnp.cumsum(counts)
    starts = ends - counts
    c = counts

    def at(i):
        return v[jnp.clip(i, 0, n - 1)]

    if weights is None:
        # PercentileFun on the descending view d[i] = v[c-1-i]
        float_pos = (1.0 - alpha) * c
        pos = jnp.floor(float_pos).astype(jnp.int32)
        bias = (float_pos - pos).astype(v.dtype)
        v1 = at(starts + c - pos)         # d[pos-1]
        v2 = at(starts + c - 1 - pos)     # d[pos]
        interp = v1 - (v1 - v2) * bias
        out = jnp.where(pos < 1, at(starts + c - 1),
                        jnp.where(pos >= c, at(starts), interp))
        out = jnp.where(c <= 1, jnp.where(c == 1, at(starts), 0.0), out)
        return out

    w = weights[order]
    cum = jnp.cumsum(w)
    seg_off = jnp.where(starts > 0, cum[jnp.clip(starts - 1, 0, n - 1)], 0.0)
    lid_sorted = lid[order]
    # per-row CDF inside its leaf
    row_off = jnp.concatenate([seg_off, jnp.zeros(1, w.dtype)])[
        jnp.clip(lid_sorted, 0, L)]
    cdf = cum - row_off
    totals = jnp.where(c > 0, cum[jnp.clip(ends - 1, 0, n - 1)] - seg_off, 0.0)
    thr = totals * alpha
    below = (cdf <= thr[jnp.clip(lid_sorted, 0, L - 1)]) \
        & (lid_sorted < L)
    pos = jnp.zeros(L, jnp.int32).at[jnp.clip(lid_sorted, 0, L - 1)].add(
        jnp.where(lid_sorted < L, below.astype(jnp.int32), 0))
    pos = jnp.minimum(pos, c - 1)

    def cdf_at(i):
        return cdf[jnp.clip(i, 0, n - 1)]

    v_pos = at(starts + pos)
    v_prev = at(starts + pos - 1)
    d = cdf_at(starts + pos + 1) - cdf_at(starts + pos)
    interp = (thr - cdf_at(starts + pos)) / jnp.where(
        jnp.abs(d) > K_EPSILON, d, 1.0) * (v_pos - v_prev) + v_prev
    inner = jnp.where((pos + 1 < c) & (d > K_EPSILON), interp, v_pos)
    out = jnp.where((pos == 0) | (pos == c - 1), v_pos, inner)
    out = jnp.where(c <= 1, jnp.where(c == 1, at(starts), 0.0), out)
    return out
