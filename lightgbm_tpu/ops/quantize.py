"""Gradient/hessian quantization for histogram training.

LightGBM's quantized-training mode ("Quantized Training of Gradient
Boosting Decision Trees", NeurIPS 2022) observes that histogram
construction is bandwidth-bound and that low-bit gradient codes keep
split quality when gradients are STOCHASTICALLY rounded (the rounding
noise stays zero-mean, so bin sums are unbiased estimates).  On this
chip the observation is sharper than on CPU/GPU: NOTES.md measures the
same ~24 TFLOP/s in every dtype, so int8 buys BYTES, not FLOPs — and
HBM bytes (~161 GB/s) are the binding resource for every histogram
kernel (see docs/Quantized.md and obs/perf.iteration_budget).

Codes here are int8 in [-127, 127] with ONE scale per (tree, g|h):

    g_code = stochastic_round(g / g_scale),   g_scale = max|g| / 127
    h_code = nearest_round(h / h_scale),      h_scale = max h  / 127

Histogram kernels accumulate the integer codes (plus a count plane) in
f32, which is EXACT while every partial sum stays below 2^24 — the
bin-count-aware envelope `exact_rows()` reports.  Within that envelope
recovered bin sums `code_sum * scale` are float64-exact functions of
the integer sums, so sibling subtraction and leaf-output recovery lose
nothing beyond the initial rounding itself.

Stochastic rounding uses `jax.random` (threefry) with a key folded from
(tpu_quantized_seed or seed, iteration) — a pure function of restored
trainer state, so checkpoint kill-and-resume is bitwise identical.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

# int8 code range is symmetric [-127, 127]: reserving -128 keeps the
# negation of every code representable (sibling subtraction in code
# space) and matches LightGBM's grad_quant convention.
CODE_MAX = 127

# f32 accumulates integers exactly below 2^24; a single bin's |code sum|
# is bounded by CODE_MAX * rows_in_bin, so this many rows in ONE bin is
# the worst-case exactness envelope.
_F32_EXACT = 1 << 24


def exact_rows(bits: int = 8) -> int:
    """Max rows a single histogram bin may hold with the integer code
    sums still exactly representable in the f32 accumulator (the
    bin-count-aware overflow guard: occupancy of the fullest bin, not
    the bin count, is what bounds exactness)."""
    code_max = (1 << (bits - 1)) - 1
    return _F32_EXACT // code_max


def overflow_safe(segment_rows: int, bits: int = 8) -> bool:
    """True when a segment of `segment_rows` rows cannot overflow the
    integer-exactness envelope even if every row lands in one bin."""
    return int(segment_rows) <= exact_rows(bits)


def quantize_gradients(grad, hess, key):
    """(g_code, h_code, g_scale, h_scale): int8-valued f32 codes plus the
    per-call scales.

    Gradients are stochastically rounded (unbiased — split gains stay
    unbiased estimates of the f32 gains); hessians are deterministically
    rounded to nearest (they sit in denominators, where zero-mean noise
    does NOT cancel).  Codes are returned as f32 arrays holding exact
    small integers so they can be cast losslessly to the bf16 arena
    payload planes (bf16 represents every integer up to 256 exactly).
    """
    g = jnp.asarray(grad, jnp.float32)
    h = jnp.asarray(hess, jnp.float32)
    g_scale = jnp.maximum(jnp.max(jnp.abs(g)), 1e-30) / CODE_MAX
    h_scale = jnp.maximum(jnp.max(jnp.abs(h)), 1e-30) / CODE_MAX
    u = jax.random.uniform(key, g.shape, jnp.float32)
    g_code = jnp.clip(jnp.floor(g / g_scale + u), -CODE_MAX, CODE_MAX)
    h_code = jnp.clip(jnp.round(h / h_scale), -CODE_MAX, CODE_MAX)
    return g_code, h_code, g_scale, h_scale


def quantize_key(seed: int, iteration) -> jax.Array:
    """Stochastic-rounding key for one boosting iteration — a pure
    function of (config seed, iteration index) so a resumed run draws
    the identical rounding noise."""
    return jax.random.fold_in(jax.random.PRNGKey(seed & 0x7FFFFFFF),
                              jnp.asarray(iteration, jnp.int32))


def dequantize_hist(hist_code, g_scale, h_scale):
    """Recover f32 (g, h, count) histograms from integer code sums.

    hist_code [..., 3] carries (sum g_code, sum h_code, count); the
    count plane is already exact.  Within the exact_rows() envelope the
    code sums are exact integers, so this multiply IS the float64-exact
    recovery (one rounding per bin, from the scale multiply itself).
    """
    scale = jnp.stack([jnp.asarray(g_scale, jnp.float32),
                       jnp.asarray(h_scale, jnp.float32),
                       jnp.float32(1.0)])
    return hist_code.astype(jnp.float32) * scale


def global_scales(grad, hess, collective):
    """(g_scale, h_scale) agreed across the collective's world.

    The distributed hazard this solves: integer histograms only psum
    correctly when every rank encodes with the SAME scale, but each
    rank sees only its shard's maxima.  One extra allreduce-max of the
    two per-tree maxima (ISSUE's "one extra psum" — any symmetric
    combine agrees across ranks; max keeps the code range tight)
    before encoding makes the scales global, after which the summed
    codes are exactly what a single encoder would have produced.

    Under the single-controller mesh backend host values are already
    global, so this degenerates to the serial computation — which is
    exactly why mesh quantized training is bitwise-identical to serial.
    """
    g = jnp.asarray(grad, jnp.float32)
    h = jnp.asarray(hess, jnp.float32)
    local = jnp.stack([jnp.max(jnp.abs(g)), jnp.max(jnp.abs(h))])
    agreed = collective.allreduce(local, "max") if collective is not None \
        else local
    agreed = jnp.asarray(agreed, jnp.float32)
    g_scale = jnp.maximum(agreed[0], 1e-30) / CODE_MAX
    h_scale = jnp.maximum(agreed[1], 1e-30) / CODE_MAX
    return g_scale, h_scale


def encode_with_scales(grad, hess, key, g_scale, h_scale,
                       global_rows=None, row_start=0, row_ids=None):
    """(g_code, h_code) encoded with GIVEN (globally-agreed) scales.

    When this rank holds rows [row_start, row_start+n) of a
    `global_rows`-row dataset, the stochastic-rounding noise is drawn
    from the GLOBAL uniform stream and sliced — so the union of every
    rank's codes is bitwise what a single encoder drawing
    uniform(key, (global_rows,)) would produce, and distributed
    quantized training matches serial bit-for-bit (the
    kill-and-resume invariant extends across world sizes).

    `row_ids` covers NON-contiguous partitions (pre_partition_rows'
    random per-row draw): the noise is gathered at this rank's global
    row indices instead of a contiguous slice.
    """
    g = jnp.asarray(grad, jnp.float32)
    h = jnp.asarray(hess, jnp.float32)
    if row_ids is not None:
        u = jax.random.uniform(key, (int(global_rows),),
                               jnp.float32)[jnp.asarray(row_ids, jnp.int32)]
    elif global_rows is None:
        u = jax.random.uniform(key, g.shape, jnp.float32)
    else:
        u = jax.lax.dynamic_slice_in_dim(
            jax.random.uniform(key, (int(global_rows),), jnp.float32),
            int(row_start), g.shape[0])
    g_code = jnp.clip(jnp.floor(g / g_scale + u), -CODE_MAX, CODE_MAX)
    h_code = jnp.clip(jnp.round(h / h_scale), -CODE_MAX, CODE_MAX)
    return g_code, h_code
