"""Device-side ranking ops: padded per-query segment batching.

The reference computes lambdarank gradients and NDCG with per-query host
loops (rank_objective.hpp:80-167 GetGradientsForOneQuery, rank_metric.hpp
NDCGMetric::Eval).  On TPU a per-query Python loop costs a host dispatch
per query, so queries are grouped by size class into padded [Q, S] blocks
(bucketed by the next power-of-two size) and each block runs as one
jitted kernel: stable descending sort, dense [S, S] pair matrices for the
lambda sums, masked positions for the padding.  Wall-clock per iteration
is then a handful of device dispatches regardless of query count.

All statics (index maps, sorted label gains, inverse max DCG) are
computed once at init; only scores stream through per iteration.
"""
from __future__ import annotations

from functools import partial
from typing import List, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

_BUCKET_MIN = 8
# pair matrices are [chunk, S, S]; keep each chunk under ~2^22 floats.
# Measured on the v5e-lite tunnel at the MSLR shape (18.9k queries of
# 120 docs -> S=128): 2^25 (chunk 2048) = 418 ms/call — the fused
# elementwise pair chain spills to HBM; 2^23 = 286 ms; **2^22 (chunk
# 256) = 204 ms**; 2^21/2^20/2^18 = 207-217 ms.  Chunk 256 keeps each
# [chunk, S, S] f32 stage at 16 MiB — small enough for XLA to tile the
# fused chain without HBM round-trips — and the ~74 sequential lax.map
# steps cost less than the spill they avoid.
_CHUNK_BUDGET = 1 << 22


def _bucket_size(sz: int) -> int:
    b = _BUCKET_MIN
    while b < sz:
        b *= 2
    return b


class QueryBuckets:
    """Static padded layout of queries grouped by size class.

    For each bucket: `idx` [Q, S] int32 row indices into the data arrays
    (padding = n, a sentinel one past the end), plus the query ids [Q]
    for per-query scalars.
    """

    def __init__(self, query_boundaries: np.ndarray, num_data: int):
        qb = np.asarray(query_boundaries, np.int64)
        sizes = np.diff(qb)
        self.num_data = int(num_data)
        self.num_queries = len(sizes)
        by_bucket = {}
        for q, sz in enumerate(sizes):
            if sz <= 0:
                continue
            by_bucket.setdefault(_bucket_size(int(sz)), []).append(q)
        self.buckets = []           # list of (idx [Q,S] i32, qids [Q] i32)
        for S in sorted(by_bucket):
            qids = np.asarray(by_bucket[S], np.int32)
            idx = np.full((len(qids), S), self.num_data, np.int64)
            for r, q in enumerate(qids):
                a, b = qb[q], qb[q + 1]
                idx[r, :b - a] = np.arange(a, b)
            self.buckets.append((idx.astype(np.int32), qids))


def _chunk(Q: int, S: int) -> int:
    c = max(1, _CHUNK_BUDGET // max(S * S, 1))
    return int(min(c, Q))


@partial(jax.jit, static_argnames=("chunk",))
def _lambda_bucket(score_pad, lab, gains, real, inv_mdcg, disc, sigmoid,
                   *, chunk: int):
    """Lambdarank sums for one padded bucket.

    score_pad/lab/gains/real: [Q, S]; inv_mdcg: [Q]; disc: [S].
    Returns (lam, hes) [Q, S] in the UNSORTED (original slot) order.
    """
    Q, S = score_pad.shape
    pad_q = (-Q) % chunk
    if pad_q:
        def p2(a):
            return jnp.pad(a, ((0, pad_q), (0, 0)))
        score_pad, lab, gains = p2(score_pad), p2(lab), p2(gains)
        real = jnp.pad(real, ((0, pad_q), (0, 0)))
        inv_mdcg = jnp.pad(inv_mdcg, (0, pad_q))
    nc = score_pad.shape[0] // chunk

    def shape(a):
        return a.reshape((nc, chunk) + a.shape[1:])

    def one(args):
        s0, l0, g0, r0, inv = args
        neg = jnp.where(r0, s0, -jnp.inf)
        order = jnp.argsort(-neg, axis=1, stable=True)
        s = jnp.take_along_axis(s0, order, axis=1)
        l = jnp.take_along_axis(l0, order, axis=1)
        g = jnp.take_along_axis(g0, order, axis=1)
        r = jnp.take_along_axis(r0, order, axis=1)
        best = jnp.max(jnp.where(r, s, -jnp.inf), axis=1)
        worst = jnp.min(jnp.where(r, s, jnp.inf), axis=1)
        delta = s[:, :, None] - s[:, None, :]
        valid = (l[:, :, None] > l[:, None, :]) \
            & r[:, :, None] & r[:, None, :]
        dcg_gap = g[:, :, None] - g[:, None, :]
        paired = jnp.abs(disc[:, None] - disc[None, :])
        dndcg = dcg_gap * paired[None] * inv[:, None, None]
        # regularize by score distance when scores differ (hpp:139-142)
        norm = (best != worst)[:, None, None]
        dndcg = jnp.where(norm, dndcg / (0.01 + jnp.abs(delta)), dndcg)
        sig = 2.0 / (1.0 + jnp.exp(
            jnp.clip(2.0 * sigmoid * delta, -80.0, 80.0)))
        p_lambda = jnp.where(valid, sig * -dndcg, 0.0)
        p_hess = jnp.where(valid, sig * (2.0 - sig) * 2.0 * dndcg, 0.0)
        lam_s = p_lambda.sum(axis=2) - p_lambda.sum(axis=1)
        hes_s = p_hess.sum(axis=2) + p_hess.sum(axis=1)
        # back to the original (unsorted) slots
        inv_order = jnp.argsort(order, axis=1)
        lam = jnp.take_along_axis(lam_s, inv_order, axis=1)
        hes = jnp.take_along_axis(hes_s, inv_order, axis=1)
        return lam, hes

    lam, hes = jax.lax.map(one, (shape(score_pad), shape(lab), shape(gains),
                                 shape(real), shape(inv_mdcg)))
    lam = lam.reshape(-1, S)[:Q]
    hes = hes.reshape(-1, S)[:Q]
    return lam, hes


class DeviceLambdarank:
    """Per-iteration lambdarank gradients fully on device."""

    def __init__(self, query_boundaries, labels, label_gain,
                 inverse_max_dcgs, sigmoid: float, dtype=jnp.float32):
        labels = np.asarray(labels)
        n = len(labels)
        self.n = n
        self.dtype = dtype
        self.sigmoid = float(sigmoid)
        self.qb = QueryBuckets(query_boundaries, n)
        gain_tab = np.asarray(label_gain, np.float64)
        inv = np.asarray(inverse_max_dcgs, np.float64)
        self._buckets = []
        for idx, qids in self.qb.buckets:
            lab_pad = np.full(idx.shape, -1, np.int32)
            real = idx < n
            lab_pad[real] = labels[idx[real]].astype(np.int32)
            self._buckets.append(dict(
                idx=jnp.asarray(idx),
                lab=jnp.asarray(lab_pad.astype(np.float64), dtype),
                gains=jnp.asarray(
                    np.where(real, gain_tab[np.clip(lab_pad, 0, None)], 0.0),
                    dtype),
                real=jnp.asarray(real),
                inv=jnp.asarray(inv[qids], dtype),
                disc=jnp.asarray(
                    1.0 / np.log2(2.0 + np.arange(idx.shape[1])), dtype),
                chunk=_chunk(*idx.shape)))

    def __call__(self, score) -> tuple:
        score = jnp.asarray(score, self.dtype).reshape(-1)
        ext = jnp.concatenate(
            [score, jnp.asarray([-jnp.inf], self.dtype)])
        grad = jnp.zeros(self.n + 1, self.dtype)
        hess = jnp.zeros(self.n + 1, self.dtype)
        for b in self._buckets:
            sp = ext[b["idx"]]
            lam, hes = _lambda_bucket(sp, b["lab"], b["gains"], b["real"],
                                      b["inv"], b["disc"],
                                      jnp.asarray(self.sigmoid, self.dtype),
                                      chunk=b["chunk"])
            flat = jnp.where(b["real"], b["idx"], self.n).reshape(-1)
            grad = grad.at[flat].add(lam.reshape(-1), mode="drop")
            hess = hess.at[flat].add(hes.reshape(-1), mode="drop")
        return grad[:self.n], hess[:self.n]


@partial(jax.jit, static_argnames=("ks",))
def _ndcg_bucket(score_pad, gains, real, inv_mdcg_k, wq, disc, *, ks: tuple):
    """Weighted NDCG sums at each k for one bucket -> [len(ks)]."""
    neg = jnp.where(real, score_pad, -jnp.inf)
    order = jnp.argsort(-neg, axis=1, stable=True)
    g = jnp.take_along_axis(gains, order, axis=1)          # [Q, S]
    S = score_pad.shape[1]
    pos = jnp.arange(S)
    out = []
    for j, k in enumerate(ks):
        dcg = jnp.sum(g * disc * (pos < k)[None, :], axis=1)    # [Q]
        # all-negative queries (inv <= 0) count as NDCG = 1
        ndcg = jnp.where(inv_mdcg_k[:, j] > 0.0,
                         dcg * inv_mdcg_k[:, j], 1.0)
        out.append(jnp.sum(ndcg * wq))
    return jnp.stack(out)


class DeviceNDCG:
    """Vectorized NDCG@k over all queries (rank_metric.hpp:15-171)."""

    def __init__(self, query_boundaries, labels, label_gain, eval_at,
                 inverse_max_dcgs, query_weights=None):
        labels = np.asarray(labels)
        n = len(labels)
        self.n = n
        self.ks = tuple(int(k) for k in eval_at)
        self.qb = QueryBuckets(query_boundaries, n)
        # zero-row queries are in no bucket but still count as NDCG = 1
        # (maxDCG <= 0 rule, rank_metric.hpp NDCGMetric::Eval)
        sizes = np.diff(np.asarray(query_boundaries, np.int64))
        gain_tab = np.asarray(label_gain, np.float64)
        inv = np.asarray(inverse_max_dcgs, np.float64)   # [num_q, K]
        qw = (np.asarray(query_weights, np.float64)
              if query_weights is not None
              else np.ones(self.qb.num_queries))
        self.sum_weights = float(qw.sum())
        self.base = float(qw[sizes <= 0].sum())
        self._buckets = []
        for idx, qids in self.qb.buckets:
            real = idx < n
            lab_pad = np.where(real, np.clip(labels, 0, None)[
                np.clip(idx, 0, n - 1)].astype(np.int64), 0)
            self._buckets.append(dict(
                idx=jnp.asarray(idx),
                gains=jnp.asarray(np.where(real, gain_tab[lab_pad], 0.0)),
                real=jnp.asarray(real),
                inv=jnp.asarray(inv[qids]),
                wq=jnp.asarray(qw[qids]),
                disc=jnp.asarray(
                    1.0 / np.log2(2.0 + np.arange(idx.shape[1])))))

    def __call__(self, score) -> List[float]:
        score = jnp.asarray(score, jnp.float64
                            if jax.config.jax_enable_x64 else jnp.float32)
        ext = jnp.concatenate([score.reshape(-1),
                               jnp.asarray([-jnp.inf], score.dtype)])
        total = jnp.zeros(len(self.ks), jnp.float64
                          if jax.config.jax_enable_x64 else jnp.float32)
        for b in self._buckets:
            total = total + _ndcg_bucket(
                ext[b["idx"]].astype(total.dtype), b["gains"].astype(total.dtype),
                b["real"], b["inv"].astype(total.dtype),
                b["wq"].astype(total.dtype), b["disc"].astype(total.dtype),
                ks=self.ks)
        return [(float(x) + self.base) / self.sum_weights
                for x in np.asarray(total)]
