"""Best-split search over histograms — fully vectorized XLA scans.

TPU-native re-design of FeatureHistogram::FindBestThreshold*
(src/treelearner/feature_histogram.hpp:29-645): instead of the reference's
per-feature sequential two-direction loops, all features × all thresholds ×
both default-directions are evaluated at once as cumulative sums along the
bin axis of a `[F, B, 3]` histogram tensor, followed by a masked argmax.
Semantics preserved exactly:

- gain math with L1 thresholding, L2, max_delta_step clamps
  (feature_histogram.hpp:437-498);
- missing handling: MissingType None/Zero/NaN with the default bin (zeros) or
  the NaN bin riding the chosen default direction, both directions scanned
  when the feature has missing values (feature_histogram.hpp:84-110, 500-636);
- min_data_in_leaf / min_sum_hessian_in_leaf / min_gain_to_split masks;
- tie-breaking: descending scan beats ascending at equal gain, higher
  threshold wins inside the descending scan, lower inside the ascending one,
  lower feature index wins across features (split_info.hpp:131-158).
"""
from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

K_EPSILON = 1e-15  # meta.h:38
K_MIN_SCORE = -jnp.inf

MISSING_NONE = 0
MISSING_ZERO = 1
MISSING_NAN = 2


class SplitParams(NamedTuple):
    """Split hyper-parameters (subset of Config used by the scans).  Leaves
    ride the jit pytree, so every field may be a tracer at scan time —
    except max_cat_threshold, which bounds a scan and must stay static."""
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    max_delta_step: float = 0.0
    min_data_in_leaf: int = 20
    min_sum_hessian_in_leaf: float = 1e-3
    min_gain_to_split: float = 0.0
    # categorical optimal-split knobs (config.h:394-437)
    max_cat_to_onehot: int = 4
    cat_smooth: float = 10.0
    cat_l2: float = 10.0
    min_data_per_group: int = 100
    # CEGB (cost-effective gradient boosting): gain -= cegb_split_penalty *
    # num_data_in_leaf, applied after the per-feature threshold search like
    # the reference (serial_tree_learner.cpp:533)
    cegb_split_penalty: float = 0.0


class SplitResult(NamedTuple):
    """Per-leaf best split (all scalars / [()] arrays); the jax analogue of
    SplitInfo (src/treelearner/split_info.hpp:17-130)."""
    feature: jnp.ndarray        # int32, -1 = no valid split
    threshold: jnp.ndarray      # int32 bin threshold (inner, <= goes left)
    gain: jnp.ndarray           # f32/f64
    default_left: jnp.ndarray   # bool
    left_sum_gradient: jnp.ndarray
    left_sum_hessian: jnp.ndarray
    left_count: jnp.ndarray     # int32
    left_output: jnp.ndarray
    right_sum_gradient: jnp.ndarray
    right_sum_hessian: jnp.ndarray
    right_count: jnp.ndarray    # int32
    right_output: jnp.ndarray
    # categorical split payload: [B] bool membership mask over bins (goes
    # left), all-False for numerical splits.  The array analogue of
    # SplitInfo::cat_threshold (split_info.hpp:36-39); packed to the
    # reference's uint32 bitset on the host (Tree::ConstructBitset).
    # None only in cat-free contexts (never mixed inside one jit trace).
    cat_mask: Optional[jnp.ndarray] = None


class PerFeatureSplit(NamedTuple):
    """Best split of every feature of one leaf — all fields [F].  The array
    analogue of the per-feature SplitInfo vector the reference reduces over
    (serial_tree_learner.cpp:506-591) and the payload voting-parallel gathers
    (LightSplitInfo, split_info.hpp:203-285)."""
    gain: jnp.ndarray           # [F], K_MIN_SCORE = no valid split
    threshold: jnp.ndarray      # [F] int32
    default_left: jnp.ndarray   # [F] bool
    left_sum_gradient: jnp.ndarray
    left_sum_hessian: jnp.ndarray   # includes the +eps directional bias
    left_count: jnp.ndarray
    left_output: jnp.ndarray
    right_sum_gradient: jnp.ndarray
    right_sum_hessian: jnp.ndarray
    right_count: jnp.ndarray
    right_output: jnp.ndarray
    cat_mask: Optional[jnp.ndarray] = None   # [F, B]


def threshold_l1(s, l1):
    """sign(s) * max(0, |s| - l1) (feature_histogram.hpp:437-440)."""
    reg = jnp.maximum(0.0, jnp.abs(s) - l1)
    return jnp.sign(s) * reg


def calculate_splitted_leaf_output(sum_grad, sum_hess, l1, l2, max_delta_step):
    """feature_histogram.hpp:442-449."""
    ret = -threshold_l1(sum_grad, l1) / (sum_hess + l2)
    clipped = jnp.sign(ret) * max_delta_step
    use_clip = (max_delta_step > 0.0) & (jnp.abs(ret) > max_delta_step)
    return jnp.where(use_clip, clipped, ret)


def leaf_split_gain_given_output(sum_grad, sum_hess, l1, l2, output):
    """-(2*T_l1(g)*w + (h+l2)*w^2) (feature_histogram.hpp:494-497)."""
    sg_l1 = threshold_l1(sum_grad, l1)
    return -(2.0 * sg_l1 * output + (sum_hess + l2) * output * output)


def leaf_split_gain(sum_grad, sum_hess, l1, l2, max_delta_step):
    out = calculate_splitted_leaf_output(sum_grad, sum_hess, l1, l2, max_delta_step)
    return leaf_split_gain_given_output(sum_grad, sum_hess, l1, l2, out)


def split_gains(lg, lh, rg, rh, l1, l2, max_delta_step,
                min_constraint=-jnp.inf, max_constraint=jnp.inf, monotone=0):
    """Gain of a (left,right) pair with monotone zeroing
    (feature_histogram.hpp:452-463)."""
    lo = jnp.clip(calculate_splitted_leaf_output(lg, lh, l1, l2, max_delta_step),
                  min_constraint, max_constraint)
    ro = jnp.clip(calculate_splitted_leaf_output(rg, rh, l1, l2, max_delta_step),
                  min_constraint, max_constraint)
    gain = (leaf_split_gain_given_output(lg, lh, l1, l2, lo)
            + leaf_split_gain_given_output(rg, rh, l1, l2, ro))
    violates = ((monotone > 0) & (lo > ro)) | ((monotone < 0) & (lo < ro))
    return jnp.where(violates, 0.0, gain), lo, ro


def best_split_per_feature(hist: jnp.ndarray,
                           sum_gradient, sum_hessian, num_data,
                           num_bins: jnp.ndarray,
                           default_bins: jnp.ndarray,
                           missing_types: jnp.ndarray,
                           params: SplitParams,
                           monotone: Optional[jnp.ndarray] = None,
                           penalty: Optional[jnp.ndarray] = None,
                           min_constraints: Optional[jnp.ndarray] = None,
                           max_constraints: Optional[jnp.ndarray] = None,
                           feature_mask: Optional[jnp.ndarray] = None,
                           cegb_feature_penalty: Optional[jnp.ndarray] = None
                           ) -> PerFeatureSplit:
    """Best numerical split of *every* feature of one leaf (fields [F]).

    hist: [F, B, 3] (grad, hess, count) including every bin (the default bin
    is stored explicitly — no FixHistogram reconstruction step is needed in
    this design, unlike dataset.cpp:928-949).
    num_bins/default_bins/missing_types: [F] int32 per-feature statics.
    feature_mask: [F] bool — feature_fraction sampling (col_sampler).
    """
    F, B, _ = hist.shape
    dtype = hist.dtype
    l1 = jnp.asarray(params.lambda_l1, dtype)
    l2 = jnp.asarray(params.lambda_l2, dtype)
    mds = jnp.asarray(params.max_delta_step, dtype)

    sum_gradient = jnp.asarray(sum_gradient, dtype)
    # FindBestThreshold adds 2*eps to the parent hessian (hpp:79)
    sum_hessian = jnp.asarray(sum_hessian, dtype) + 2 * K_EPSILON
    num_data = jnp.asarray(num_data, jnp.int32)

    bins = jnp.arange(B, dtype=jnp.int32)                       # [B]
    in_range = bins[None, :] < num_bins[:, None]                # [F, B]
    # bins riding the default direction (excluded from directional sums)
    excl = ((missing_types[:, None] == MISSING_ZERO) &
            (bins[None, :] == default_bins[:, None])) | \
           ((missing_types[:, None] == MISSING_NAN) &
            (bins[None, :] == num_bins[:, None] - 1))
    # with <=2 bins the reference falls into the single plain scan with no
    # default-direction bin (feature_histogram.hpp:89,97-103)
    excl = excl & in_range & (num_bins[:, None] > 2)

    g = jnp.where(in_range & ~excl, hist[..., 0], 0.0)
    h = jnp.where(in_range & ~excl, hist[..., 1], 0.0)
    # counts stay integral: f32 loses exactness above 2^24 rows per leaf,
    # which would flip min_data_in_leaf masks on billion-row data
    c = jnp.where(in_range & ~excl, hist[..., 2], 0.0)
    c_int = jnp.round(c).astype(jnp.int64 if c.dtype == jnp.float64 else jnp.int32)

    # ascending: left(θ) = Σ_{b<=θ, not excl};  descending: right(θ) = Σ_{b>θ}
    cg = jnp.cumsum(g, axis=1)
    ch = jnp.cumsum(h, axis=1)
    cc = jnp.cumsum(c_int, axis=1)
    tg, th, tc = cg[:, -1:], ch[:, -1:], cc[:, -1:]

    def eval_dir(left_g, left_h, left_c):
        right_g = sum_gradient - left_g
        right_h = sum_hessian - left_h
        right_c = num_data - left_c
        gain, lo, ro = split_gains(left_g, left_h, right_g, right_h, l1, l2, mds,
                                   (-jnp.inf if min_constraints is None
                                    else min_constraints[:, None]),
                                   (jnp.inf if max_constraints is None
                                    else max_constraints[:, None]),
                                   0 if monotone is None else monotone[:, None])
        min_cnt = jnp.maximum(params.min_data_in_leaf, 1)
        valid = ((left_c >= min_cnt)
                 & (right_c >= min_cnt)
                 & (left_h >= params.min_sum_hessian_in_leaf)
                 & (right_h >= params.min_sum_hessian_in_leaf))
        return gain, lo, ro, valid, (left_g, left_h, left_c, right_g, right_h, right_c)

    # dir == +1 (default right): left accumulates from the low end, +eps
    asc_lg, asc_lh, asc_lc = cg, ch + K_EPSILON, cc
    asc = eval_dir(asc_lg, asc_lh, asc_lc)
    # dir == -1 (default left): right accumulates from the high end, +eps;
    # right(θ) = total_directional - cum(θ); left = parent - right
    desc_rg, desc_rh, desc_rc = tg - cg, th - ch + K_EPSILON, tc - cc
    desc = eval_dir(sum_gradient - desc_rg, sum_hessian - desc_rh,
                    num_data - desc_rc)

    # threshold validity: θ in [0, num_bin-2]
    thr_ok = bins[None, :] <= num_bins[:, None] - 2
    # ascending scan only runs for features with missing values and >2 bins
    # (feature_histogram.hpp:89-96); descending always runs
    asc_ok = thr_ok & (missing_types[:, None] != MISSING_NONE) & (num_bins[:, None] > 2)
    desc_ok = thr_ok

    # no-split gain threshold (strict >)
    gain_shift = leaf_split_gain(sum_gradient, sum_hessian, l1, l2, mds)
    min_gain_shift = gain_shift + params.min_gain_to_split

    def masked_gain(d, ok):
        gain, lo, ro, valid, _ = d
        return jnp.where(ok & valid & (gain > min_gain_shift), gain, K_MIN_SCORE)

    asc_gain = masked_gain(asc, asc_ok)
    desc_gain = masked_gain(desc, desc_ok)

    # scan-order tie-breaking: desc scans high→low θ then asc scans low→high,
    # strict-greater updates.  Build candidates in that order per feature.
    cand_gain = jnp.concatenate([desc_gain[:, ::-1], asc_gain], axis=1)  # [F, 2B]
    best_idx = jnp.argmax(cand_gain, axis=1)                             # [F]
    best_gain = jnp.take_along_axis(cand_gain, best_idx[:, None], 1)[:, 0]
    is_desc = best_idx < B
    best_thr = jnp.where(is_desc, B - 1 - best_idx, best_idx - B).astype(jnp.int32)

    def pick(d, which):
        return jnp.take_along_axis(d, jnp.where(which, best_thr, 0)[:, None], 1)[:, 0]

    (asc_gain_, asc_lo, asc_ro, _, asc_sums) = asc
    (desc_gain_, desc_lo, desc_ro, _, desc_sums) = desc

    def sel(asc_v, desc_v):
        return jnp.where(is_desc, pick(desc_v, is_desc), pick(asc_v, ~is_desc))

    lg = sel(asc_sums[0], desc_sums[0])
    lh = sel(asc_sums[1], desc_sums[1])
    lc = sel(asc_sums[2], desc_sums[2])
    rg = sel(asc_sums[3], desc_sums[3])
    rh = sel(asc_sums[4], desc_sums[4])
    rc = sel(asc_sums[5], desc_sums[5])
    lo = sel(asc_lo, desc_lo)
    ro = sel(asc_ro, desc_ro)

    # per-feature reported gain relative to no-split, times feature penalty
    rel_gain = best_gain - min_gain_shift
    if penalty is not None:
        rel_gain = rel_gain * penalty
    # CEGB penalties are subtracted AFTER the threshold search
    # (serial_tree_learner.cpp:533-539): they shift whole features/leaves,
    # not individual thresholds
    rel_gain = rel_gain - jnp.asarray(params.cegb_split_penalty,
                                      dtype) * num_data
    if cegb_feature_penalty is not None:
        rel_gain = rel_gain - cegb_feature_penalty
    # penalties can push the gain non-positive: such splits never apply
    # (the reference's gain <= 0 stop, serial_tree_learner.cpp:220-223)
    feat_gain = jnp.where((best_gain > K_MIN_SCORE) & (rel_gain > 0),
                          rel_gain, K_MIN_SCORE)
    if feature_mask is not None:
        feat_gain = jnp.where(feature_mask, feat_gain, K_MIN_SCORE)

    # 2-bin NaN features report default_right even from the single descending
    # scan (feature_histogram.hpp:99-102)
    two_bin_nan = (missing_types == MISSING_NAN) & (num_bins <= 2)
    default_left_f = is_desc & ~two_bin_nan

    return PerFeatureSplit(
        gain=feat_gain,
        threshold=best_thr,
        default_left=default_left_f,
        left_sum_gradient=lg,
        left_sum_hessian=lh,
        left_count=lc.astype(jnp.int32),
        left_output=lo,
        right_sum_gradient=rg,
        right_sum_hessian=rh,
        right_count=rc.astype(jnp.int32),
        right_output=ro,
    )


def select_best_feature(pf: PerFeatureSplit,
                        feature_index: Optional[jnp.ndarray] = None
                        ) -> SplitResult:
    """Cross-feature argmax of a PerFeatureSplit → SplitResult.

    feature_index: optional [F] int32 mapping row → global feature id (used
    by the feature-parallel shard offset and the voting-parallel gather);
    defaults to arange.  Ties -> smaller array position (argmax first-hit),
    matching the reference's ascending-feature update loop
    (serial_tree_learner.cpp:575-587).
    """
    best_f = jnp.argmax(pf.gain, axis=0).astype(jnp.int32)
    has_split = pf.gain[best_f] > K_MIN_SCORE
    if feature_index is None:
        out_f = best_f
    else:
        out_f = feature_index[best_f].astype(jnp.int32)
    best_f_out = jnp.where(has_split, out_f, -1)

    def at(v):
        return v[best_f]

    return SplitResult(
        feature=best_f_out,
        threshold=at(pf.threshold),
        gain=at(pf.gain),
        default_left=at(pf.default_left),
        left_sum_gradient=at(pf.left_sum_gradient),
        left_sum_hessian=at(pf.left_sum_hessian) - K_EPSILON,
        left_count=at(pf.left_count),
        left_output=at(pf.left_output),
        right_sum_gradient=at(pf.right_sum_gradient),
        right_sum_hessian=at(pf.right_sum_hessian) - K_EPSILON,
        right_count=at(pf.right_count),
        right_output=at(pf.right_output),
        cat_mask=None if pf.cat_mask is None else pf.cat_mask[best_f],
    )


def best_split_per_feature_mixed(hist: jnp.ndarray,
                                 sum_gradient, sum_hessian, num_data,
                                 num_bins: jnp.ndarray,
                                 default_bins: jnp.ndarray,
                                 missing_types: jnp.ndarray,
                                 is_categorical: jnp.ndarray,   # [F] bool
                                 params: SplitParams,
                                 monotone: Optional[jnp.ndarray] = None,
                                 penalty: Optional[jnp.ndarray] = None,
                                 min_constraints=None, max_constraints=None,
                                 feature_mask: Optional[jnp.ndarray] = None,
                                 cegb_feature_penalty=None,
                                 *, max_cat_threshold: int = 32
                                 ) -> PerFeatureSplit:
    """Per-feature best split with the numerical/categorical scan selected
    per feature by bin type (the find_best_threshold_fun_ dispatch,
    feature_histogram.hpp:49-58)."""
    pf_num = best_split_per_feature(
        hist, sum_gradient, sum_hessian, num_data,
        num_bins, default_bins, missing_types, params,
        monotone=monotone, penalty=penalty,
        min_constraints=min_constraints, max_constraints=max_constraints,
        feature_mask=feature_mask, cegb_feature_penalty=cegb_feature_penalty)
    pf_cat = best_split_categorical_per_feature(
        hist, sum_gradient, sum_hessian, num_data,
        num_bins, missing_types, params,
        penalty=penalty,
        min_constraints=min_constraints, max_constraints=max_constraints,
        feature_mask=feature_mask, cegb_feature_penalty=cegb_feature_penalty,
        max_cat_threshold=max_cat_threshold)

    def sel(num_v, cat_v):
        ic = is_categorical
        if cat_v.ndim == 2:
            ic = is_categorical[:, None]
        return jnp.where(ic, cat_v, num_v)

    merged = PerFeatureSplit(*[
        sel(n, c) for n, c in
        zip(pf_num._replace(cat_mask=jnp.zeros_like(pf_cat.cat_mask)),
            pf_cat)])
    return merged


def best_split_categorical_per_feature(hist: jnp.ndarray,
                                       sum_gradient, sum_hessian, num_data,
                                       num_bins: jnp.ndarray,
                                       missing_types: jnp.ndarray,
                                       params: SplitParams,
                                       penalty: Optional[jnp.ndarray] = None,
                                       min_constraints=None,
                                       max_constraints=None,
                                       feature_mask: Optional[jnp.ndarray] = None,
                                       cegb_feature_penalty=None,
                                       *, max_cat_threshold: int = 32
                                       ) -> PerFeatureSplit:
    """Categorical optimal split of every feature (FindBestThresholdCategorical,
    feature_histogram.hpp:110-271), vectorized over features:

    - one-hot mode when num_bin <= max_cat_to_onehot: each category vs rest,
      evaluated for every bin at once;
    - sorted mode: bins with cnt >= cat_smooth sorted by g/(h+cat_smooth),
      prefixes from both directions scanned up to
      min(max_cat_threshold, (used_bin+1)/2) with the min_data_per_group
      group-accumulation walk (a lax.scan over <= max_cat_threshold steps,
      vectorized over F).

    Returns PerFeatureSplit whose threshold is unused (-1) and whose
    cat_mask [F, B] holds the left-going category set.
    """
    F, B, _ = hist.shape
    dtype = hist.dtype
    l1 = jnp.asarray(params.lambda_l1, dtype)
    l2n = jnp.asarray(params.lambda_l2, dtype)
    l2 = l2n + jnp.asarray(params.cat_l2, dtype)   # hpp:172
    mds = jnp.asarray(params.max_delta_step, dtype)
    sum_gradient = jnp.asarray(sum_gradient, dtype)
    sum_hessian = jnp.asarray(sum_hessian, dtype) + 2 * K_EPSILON  # hpp:79
    num_data = jnp.asarray(num_data, jnp.int32)
    minc1 = -jnp.inf if min_constraints is None else min_constraints   # [F]
    maxc1 = jnp.inf if max_constraints is None else max_constraints
    minc = minc1 if min_constraints is None else minc1[:, None]        # [F,1]
    maxc = maxc1 if max_constraints is None else maxc1[:, None]

    bins = jnp.arange(B, dtype=jnp.int32)
    # used_bin = num_bin - 1 + (missing_type == None) (hpp:121-122)
    used_bin = num_bins - 1 + (missing_types == MISSING_NONE).astype(jnp.int32)
    in_used = bins[None, :] < used_bin[:, None]                  # [F, B]

    g = jnp.where(in_used, hist[..., 0], 0.0)
    h = jnp.where(in_used, hist[..., 1], 0.0)
    c = jnp.round(jnp.where(in_used, hist[..., 2], 0.0)).astype(jnp.int32)

    # min_gain_shift against the PLAIN-l2 no-split gain (hpp:119-120)
    gain_shift = leaf_split_gain(sum_gradient, sum_hessian, l1, l2n, mds)
    min_gain_shift = gain_shift + params.min_gain_to_split

    min_cnt = jnp.maximum(params.min_data_in_leaf, 1)
    min_hess = params.min_sum_hessian_in_leaf

    # ---------------- one-hot mode (hpp:129-160) ----------------------- #
    other_g = sum_gradient - g
    other_h = sum_hessian - h - K_EPSILON
    other_c = num_data - c
    oh_gain, oh_lo, oh_ro = split_gains(other_g, other_h, g, h + K_EPSILON,
                                        l1, l2, mds, minc, maxc, 0)
    oh_valid = (in_used
                & (c >= min_cnt) & (h >= min_hess)
                & (other_c >= min_cnt) & (other_h >= min_hess))
    oh_gain = jnp.where(oh_valid & (oh_gain > min_gain_shift),
                        oh_gain, K_MIN_SCORE)
    oh_best = jnp.argmax(oh_gain, axis=1)                         # [F]
    oh_bgain = jnp.take_along_axis(oh_gain, oh_best[:, None], 1)[:, 0]
    oh_mask = jax.nn.one_hot(oh_best, B, dtype=jnp.int32).astype(bool)

    def at_b(v):
        return jnp.take_along_axis(v, oh_best[:, None], 1)[:, 0]

    onehot = dict(
        gain=oh_bgain,
        lg=at_b(g), lh=at_b(h) + K_EPSILON, lc=at_b(c),
        mask=oh_mask)

    # ---------------- sorted mode (hpp:161-238) ------------------------ #
    eligible = in_used & (c.astype(dtype) >= params.cat_smooth)   # hpp:163
    n_elig = jnp.sum(eligible, axis=1).astype(jnp.int32)          # [F]
    ratio = jnp.where(eligible, g / (h + params.cat_smooth), jnp.inf)
    order = jnp.argsort(ratio, axis=1).astype(jnp.int32)          # [F, B]
    # per-direction prefix walk with group accumulation; dir 0 = ascending
    # (+1), dir 1 = descending (-1: walk from the high end of the order)
    max_steps = min(max_cat_threshold, B)
    # max_num_cat = min(max_cat_threshold, (used_bin+1)/2) (hpp:185)
    max_num_cat = jnp.minimum(max_cat_threshold, (n_elig + 1) // 2)

    og = jnp.take_along_axis(g, order, axis=1)                    # [F, B]
    oh_ = jnp.take_along_axis(h, order, axis=1)
    oc = jnp.take_along_axis(c, order, axis=1)

    def scan_dir(descending: bool):
        if descending:
            sg, sh, sc = og[:, ::-1], oh_[:, ::-1], oc[:, ::-1]
            # descending starts at position n_elig-1: shift the reversed
            # arrays so step 0 reads the last *eligible* bin
            shift = B - n_elig                                    # [F]
            idx = (jnp.arange(B)[None, :] + shift[:, None]) % B
            sg = jnp.take_along_axis(sg, idx, axis=1)
            sh = jnp.take_along_axis(sh, idx, axis=1)
            sc = jnp.take_along_axis(sc, idx, axis=1)
        else:
            sg, sh, sc = og, oh_, oc

        def step(carry, i):
            cnt_grp, lg, lh, lc = carry
            lg = lg + sg[:, i]
            lh = lh + sh[:, i]
            lc = lc + sc[:, i]
            cnt_grp = cnt_grp + sc[:, i]
            in_range = (i < n_elig) & (i < max_num_cat)
            rc = num_data - lc
            rh = sum_hessian - lh
            # break conditions poison all later steps (hpp:207-212)
            brk = (rc < min_cnt) | (rc < params.min_data_per_group) | \
                  (rh < min_hess)
            cont = (lc < min_cnt) | (lh < min_hess)
            # the group resets whenever the walk reaches an evaluation,
            # before the gain test (hpp:216-218)
            evalable = in_range & ~brk & ~cont & \
                (cnt_grp >= params.min_data_per_group)
            gain, _lo, _ro = split_gains(lg, lh, sum_gradient - lg, rh,
                                         l1, l2, mds, minc1, maxc1, 0)
            gain = jnp.where(evalable & (gain > min_gain_shift),
                             gain, K_MIN_SCORE)
            cnt_grp = jnp.where(evalable, 0, cnt_grp)
            new_dead = brk & in_range
            return ((cnt_grp, lg, lh, lc), (gain, lg, lh, lc, new_dead))

        init = (jnp.zeros(F, jnp.int32), jnp.zeros(F, dtype) ,
                jnp.full(F, K_EPSILON, dtype), jnp.zeros(F, jnp.int32))
        _, (gains, lgs, lhs, lcs, dead) = jax.lax.scan(
            step, init, jnp.arange(max_steps))
        # poison every step after the first break
        dead_before = jnp.cumsum(dead.astype(jnp.int32), axis=0) \
            - dead.astype(jnp.int32)
        gains = jnp.where(dead_before > 0, K_MIN_SCORE, gains)   # [S, F]
        best_i = jnp.argmax(gains, axis=0)                        # [F]
        bg = jnp.take_along_axis(gains, best_i[None, :], 0)[0]

        def at_i(v):
            return jnp.take_along_axis(v, best_i[None, :], 0)[0]

        # membership mask: first (best_i+1) positions of the walk
        rank = jnp.argsort(order, axis=1)                         # bin -> pos
        if descending:
            pos_from_end = n_elig[:, None] - 1 - rank
            member = (pos_from_end >= 0) & (pos_from_end <= best_i[:, None])
        else:
            member = rank <= best_i[:, None]
        member = member & eligible
        return dict(gain=bg, lg=at_i(lgs), lh=at_i(lhs), lc=at_i(lcs),
                    mask=member)

    asc = scan_dir(False)
    desc = scan_dir(True)
    # strict-greater update: ascending wins ties (it is scanned first,
    # hpp:186-238 out_i order)
    use_desc = desc["gain"] > asc["gain"]

    def sel(a, d):
        if a.ndim == 2:
            return jnp.where(use_desc[:, None], d, a)
        return jnp.where(use_desc, d, a)

    sorted_res = {k: sel(asc[k], desc[k]) for k in asc}

    # ---------------- mode select + outputs ---------------------------- #
    use_onehot = num_bins <= params.max_cat_to_onehot             # [F]

    def pick(o, s):
        if o.ndim == 2:
            return jnp.where(use_onehot[:, None], o, s)
        return jnp.where(use_onehot, o, s)

    res = {k: pick(onehot[k], sorted_res[k]) for k in onehot}
    gain, lg, lh, lc = res["gain"], res["lg"], res["lh"], res["lc"]
    rg = sum_gradient - lg
    rh = sum_hessian - lh
    rc = num_data - lc
    lo = jnp.clip(calculate_splitted_leaf_output(lg, lh, l1, l2, mds),
                  minc1, maxc1)
    ro = jnp.clip(calculate_splitted_leaf_output(rg, rh, l1, l2, mds),
                  minc1, maxc1)

    rel_gain = gain - min_gain_shift
    if penalty is not None:
        rel_gain = rel_gain * penalty
    rel_gain = rel_gain - jnp.asarray(params.cegb_split_penalty,
                                      dtype) * num_data
    if cegb_feature_penalty is not None:
        rel_gain = rel_gain - cegb_feature_penalty
    feat_gain = jnp.where((gain > K_MIN_SCORE) & (rel_gain > 0),
                          rel_gain, K_MIN_SCORE)
    if feature_mask is not None:
        feat_gain = jnp.where(feature_mask, feat_gain, K_MIN_SCORE)
    cat_mask = res["mask"] & (feat_gain > K_MIN_SCORE)[:, None]

    return PerFeatureSplit(
        gain=feat_gain,
        threshold=jnp.full(F, -1, jnp.int32),
        default_left=jnp.zeros(F, bool),      # hpp:113 default_left=false
        left_sum_gradient=lg,
        left_sum_hessian=lh,
        left_count=lc,
        left_output=lo,
        right_sum_gradient=rg,
        right_sum_hessian=rh,
        right_count=rc,
        right_output=ro,
        cat_mask=cat_mask,
    )


def forced_split_result(hist, feat, thr_bin, sum_gradient, sum_hessian,
                        num_data, num_bins, default_bins, missing_types,
                        params: SplitParams, default_left) -> SplitResult:
    """Stats of the numerical split (feat, thr_bin) on this leaf — the
    forced-split analogue of FeatureHistogram::GatherInfoForThreshold
    (feature_histogram.hpp:273-411).  Returns a SplitResult whose gain is
    +inf when both children are nonempty (forced splits apply regardless
    of gain) and K_MIN_SCORE otherwise."""
    dtype = hist.dtype
    B = hist.shape[1]
    l1 = jnp.asarray(params.lambda_l1, dtype)
    l2 = jnp.asarray(params.lambda_l2, dtype)
    mds = jnp.asarray(params.max_delta_step, dtype)
    sum_gradient = jnp.asarray(sum_gradient, dtype)
    sum_hessian = jnp.asarray(sum_hessian, dtype) + 2 * K_EPSILON
    num_data = jnp.asarray(num_data, jnp.int32)

    h_f = hist[feat]                                           # [B, 3]
    bins = jnp.arange(B, dtype=jnp.int32)
    nb = num_bins[feat]
    in_range = bins < nb
    mt = missing_types[feat]
    excl = (((mt == MISSING_ZERO) & (bins == default_bins[feat])) |
            ((mt == MISSING_NAN) & (bins == nb - 1))) & in_range & (nb > 2)
    take_left = in_range & ~excl & (bins <= thr_bin)
    lg = jnp.sum(jnp.where(take_left, h_f[:, 0], 0.0))
    lh = jnp.sum(jnp.where(take_left, h_f[:, 1], 0.0))
    lc = jnp.sum(jnp.where(take_left, h_f[:, 2], 0.0))
    excl_g = jnp.sum(jnp.where(excl, h_f[:, 0], 0.0))
    excl_h = jnp.sum(jnp.where(excl, h_f[:, 1], 0.0))
    excl_c = jnp.sum(jnp.where(excl, h_f[:, 2], 0.0))
    dl = jnp.asarray(default_left, bool)
    lg = lg + jnp.where(dl, excl_g, 0.0)
    lh = lh + jnp.where(dl, excl_h, 0.0)
    lc = lc + jnp.where(dl, excl_c, 0.0)
    rg = sum_gradient - lg
    rh = sum_hessian - lh
    rc = num_data - jnp.round(lc).astype(jnp.int32)
    lc_i = jnp.round(lc).astype(jnp.int32)
    lo = calculate_splitted_leaf_output(lg, lh, l1, l2, mds)
    ro = calculate_splitted_leaf_output(rg, rh, l1, l2, mds)
    valid = (lc_i > 0) & (rc > 0)
    return SplitResult(
        feature=jnp.where(valid, feat, -1).astype(jnp.int32),
        threshold=jnp.asarray(thr_bin, jnp.int32),
        gain=jnp.where(valid, jnp.asarray(jnp.inf, dtype),
                       jnp.asarray(K_MIN_SCORE, dtype)),
        default_left=dl,
        left_sum_gradient=lg, left_sum_hessian=lh - K_EPSILON,
        left_count=lc_i, left_output=lo,
        right_sum_gradient=rg, right_sum_hessian=rh - K_EPSILON,
        right_count=rc, right_output=ro,
        cat_mask=None)


def best_split_for_leaf(hist: jnp.ndarray,
                        sum_gradient, sum_hessian, num_data,
                        num_bins: jnp.ndarray,
                        default_bins: jnp.ndarray,
                        missing_types: jnp.ndarray,
                        params: SplitParams,
                        monotone: Optional[jnp.ndarray] = None,
                        penalty: Optional[jnp.ndarray] = None,
                        min_constraints: Optional[jnp.ndarray] = None,
                        max_constraints: Optional[jnp.ndarray] = None,
                        feature_mask: Optional[jnp.ndarray] = None) -> SplitResult:
    """Best numerical split across all features of one leaf (see
    best_split_per_feature for the argument contract)."""
    pf = best_split_per_feature(hist, sum_gradient, sum_hessian, num_data,
                                num_bins, default_bins, missing_types, params,
                                monotone=monotone, penalty=penalty,
                                min_constraints=min_constraints,
                                max_constraints=max_constraints,
                                feature_mask=feature_mask)
    return select_best_feature(pf)


# -- roofline cost model (obs/perf) -------------------------------------- #
from ..obs.perf import KernelCost, cost_model  # noqa: E402


@cost_model("split/xla")
def _cost_split_xla(features: int, max_bin: int) -> KernelCost:
    """Best-split scan over one leaf's [F, B, 3] histogram: read the
    histogram once, write one packed split row per feature; ~32 FLOPs
    per bin cover the L/R prefix sums, both missing directions and the
    regularized gain formula."""
    F, B = int(features), int(max_bin)
    return KernelCost("split/xla", F * B * 3 * 4 + F * 64, 32 * F * B,
                      "hist read + per-feature split row out")
