"""Single-kernel best-split scan (numerical features) for the grow loops.

The XLA formulation in ops/split.py is ~200 small [F, B] ops per call;
inside the tree-growth while-loop that chain is pure per-op dispatch
latency (~0.45 ms per split pair measured on the round-4 chip — more
than the partition kernel itself).  This kernel computes the SAME
numerical two-direction scan semantics (FindBestThresholdSequentially,
reference src/treelearner/feature_histogram.hpp:437-636) for BOTH
children of a split in ONE Pallas launch:

- children are sublane-stacked: rows = CH*F, lanes = bins;
- inclusive prefix sums via log-step rolls;
- missing-direction enumeration (asc scan only for features with
  missing values, desc always), L1/L2/max_delta_step gain math,
  min_data/min_hessian/min_gain masks, monotone clamp+veto, feature
  penalty, CEGB penalties — bit-for-bit the formulas of ops/split.py;
- tie-breaking preserved: desc beats asc at equal gain, higher
  threshold wins inside desc, lower inside asc (split_info.hpp:131-158).

The categorical path stays in XLA (ops/split.py) — the engines dispatch
here only for all-numerical datasets, which is also the only case the
reference's GPU learner accelerates (gpu_tree_learner.cpp:xxx dense
numerical feature groups).

Outputs ride a [CH*F, 128] f32 block whose first 11 lanes are the
PerFeatureSplit fields; masked gains use a -1e38 sentinel that the
wrapper maps back to K_MIN_SCORE (-inf survives no kernel arithmetic).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .split import K_EPSILON, K_MIN_SCORE, PerFeatureSplit, SplitParams

NEG = -1e38        # in-kernel "no split" sentinel (python float: a
NEG_GATE = -1e37   # module-level jnp scalar would be a captured const)

# fvec column layout (per-feature statics, [R, 8] f32)
_NB, _DB, _MT, _MONO, _PEN, _FMASK, _CEGBF = range(7)
# svec column layout (per-child scalars, [CH, 8] f32)
_SG, _SH, _ND, _MINC, _MAXC = range(5)
# pvec layout (params, [8] f32 SMEM)
_L1, _L2, _MDS, _MINCNT, _MINH, _MINGAIN, _CEGBS = range(7)
# output lane layout (shared by the per-feature block and the selected
# best-rows: lane 1 holds the feature id so a best-row is a complete,
# directly-scatterable SplitResult record)
(_OG, _OF, _OT, _ODL, _OLG, _OLH, _OLC, _OLO,
 _ORG, _ORH, _ORC, _ORO) = range(12)
ROW_W = 128        # lane width of one packed split row


def _prefix_lanes(x):
    """Inclusive prefix sum along lanes (Hillis-Steele log rolls)."""
    n = x.shape[-1]
    lane = jax.lax.broadcasted_iota(jnp.int32, x.shape, x.ndim - 1)
    sh = 1
    while sh < n:
        x = x + jnp.where(lane >= sh, pltpu.roll(x, sh, axis=x.ndim - 1), 0.0)
        sh *= 2
    return x


def _split_scan_kernel(pvec_ref, svec_ref, fvec_ref, hist_ref, out_ref,
                       best_ref, *, CH: int, F: int, B: int):
    R = CH * F
    l1 = pvec_ref[_L1]
    l2 = pvec_ref[_L2]
    mds = pvec_ref[_MDS]
    min_cnt = jnp.maximum(pvec_ref[_MINCNT], 1.0)
    min_hess = pvec_ref[_MINH]
    min_gain = pvec_ref[_MINGAIN]
    cegb_split = pvec_ref[_CEGBS]

    fv = fvec_ref[:]                                    # [R, 8]
    nb = fv[:, _NB:_NB + 1]
    db = fv[:, _DB:_DB + 1]
    mt = fv[:, _MT:_MT + 1]
    mono = fv[:, _MONO:_MONO + 1]
    pen = fv[:, _PEN:_PEN + 1]
    fmask = fv[:, _FMASK:_FMASK + 1]
    cegb_f = fv[:, _CEGBF:_CEGBF + 1]

    # per-row child scalars: rows [ch*F, (ch+1)*F) take svec[ch] —
    # SMEM permits scalar loads only, so read element-wise and select
    row = jax.lax.broadcasted_iota(jnp.int32, (R, 1), 0)

    def per_child(col):
        v = jnp.full((R, 1), 0.0, jnp.float32) + svec_ref[0, col]
        for ch in range(1, CH):
            v = jnp.where(row >= ch * F, svec_ref[ch, col], v)
        return v

    sum_g = per_child(_SG)
    sum_h = per_child(_SH) + 2 * K_EPSILON              # hpp:79
    num_data = per_child(_ND)
    minc = per_child(_MINC)
    maxc = per_child(_MAXC)

    bins = jax.lax.broadcasted_iota(jnp.int32, (R, B), 1)
    bins_f = bins.astype(jnp.float32)
    in_range = bins_f < nb
    excl = (((mt == 1.0) & (bins_f == db))
            | ((mt == 2.0) & (bins_f == nb - 1.0))) & in_range & (nb > 2.0)
    live = in_range & ~excl

    G = jnp.where(live, hist_ref[0], 0.0)               # [R, B]
    H = jnp.where(live, hist_ref[1], 0.0)
    Cc = jnp.where(live, hist_ref[2], 0.0)

    pref = _prefix_lanes(jnp.concatenate([G, H, Cc], axis=0))
    cg, ch_, cc = pref[:R], pref[R:2 * R], pref[2 * R:]
    tg, th, tc = cg[:, B - 1:B], ch_[:, B - 1:B], cc[:, B - 1:B]

    def thr_l1(s):
        return jnp.sign(s) * jnp.maximum(0.0, jnp.abs(s) - l1)

    def leaf_out(g, h):
        ret = -thr_l1(g) / (h + l2)
        clipped = jnp.sign(ret) * mds
        use_clip = (mds > 0.0) & (jnp.abs(ret) > mds)
        return jnp.where(use_clip, clipped, ret)

    def gain_given(g, h, out):
        return -(2.0 * thr_l1(g) * out + (h + l2) * out * out)

    # no-split shift from the parent (scalar per row)
    parent_out = leaf_out(sum_g, sum_h)
    min_gain_shift = gain_given(sum_g, sum_h, parent_out) + min_gain

    def eval_dir(lg, lh, lc):
        rg = sum_g - lg
        rh = sum_h - lh
        rc = num_data - lc
        lo = jnp.clip(leaf_out(lg, lh), minc, maxc)
        ro = jnp.clip(leaf_out(rg, rh), minc, maxc)
        gain = gain_given(lg, lh, lo) + gain_given(rg, rh, ro)
        violates = ((mono > 0.0) & (lo > ro)) | ((mono < 0.0) & (lo < ro))
        gain = jnp.where(violates, 0.0, gain)
        valid = ((lc >= min_cnt) & (rc >= min_cnt)
                 & (lh >= min_hess) & (rh >= min_hess))
        return gain, lo, ro, valid, (lg, lh, lc, rg, rh, rc)

    asc = eval_dir(cg, ch_ + K_EPSILON, cc)
    d_rg, d_rh, d_rc = tg - cg, th - ch_ + K_EPSILON, tc - cc
    desc = eval_dir(sum_g - d_rg, sum_h - d_rh, num_data - d_rc)

    thr_ok = bins_f <= nb - 2.0
    asc_ok = thr_ok & (mt != 0.0) & (nb > 2.0)
    desc_ok = thr_ok

    def masked(d, ok):
        gain = d[0]
        valid = d[3]
        return jnp.where(ok & valid & (gain > min_gain_shift), gain, jnp.float32(NEG))

    asc_m = masked(asc, asc_ok)
    desc_m = masked(desc, desc_ok)

    BIG = 1e9
    asc_best = jnp.max(asc_m, axis=1, keepdims=True)
    asc_thr = jnp.min(jnp.where(asc_m == asc_best, bins_f, BIG),
                      axis=1, keepdims=True)             # low θ wins ties
    desc_best = jnp.max(desc_m, axis=1, keepdims=True)
    desc_thr = jnp.max(jnp.where(desc_m == desc_best, bins_f, -BIG),
                       axis=1, keepdims=True)            # high θ wins ties
    use_desc = desc_best >= asc_best                     # desc wins ties
    best_gain = jnp.maximum(desc_best, asc_best)
    best_thr = jnp.where(use_desc, desc_thr, asc_thr)

    oh = jnp.where(bins_f == best_thr, 1.0, 0.0)

    def pick(asc_v, desc_v):
        v = jnp.where(use_desc, desc_v, asc_v)
        # select, don't multiply: unselected lanes may hold inf/NaN from
        # degenerate-bin divisions and NaN*0 would poison the reduction
        return jnp.sum(jnp.where(oh > 0.5, v, 0.0), axis=1, keepdims=True)

    lo_p = pick(asc[1], desc[1])
    ro_p = pick(asc[2], desc[2])
    stats = [pick(a, d) for a, d in zip(asc[4], desc[4])]

    rel = best_gain - min_gain_shift
    rel = rel * pen - cegb_split * num_data - cegb_f
    has = best_gain > NEG_GATE
    feat_gain = jnp.where(has & (rel > 0.0) & (fmask > 0.5), rel, NEG)

    two_bin_nan = (mt == 2.0) & (nb <= 2.0)
    dl = jnp.where(use_desc & ~two_bin_nan, 1.0, 0.0)

    feat_id = (row - (row // F) * F).astype(jnp.float32)
    cols = [feat_gain, feat_id, best_thr, dl, stats[0], stats[1], stats[2],
            lo_p, stats[3], stats[4], stats[5], ro_p]
    block = jnp.concatenate(
        cols + [jnp.zeros((R, ROW_W - len(cols)), jnp.float32)], axis=1)
    out_ref[:] = block

    # in-kernel cross-feature selection (select_best_feature): per child,
    # max gain over its F rows, lowest feature id on ties — emitted as a
    # ready-to-scatter [CH, ROW_W] result row for the packed grow state.
    # The gain lane keeps the NEG sentinel when no feature has a valid
    # split (feature lane -1), and the +eps directional hessian bias is
    # removed exactly like select_best_feature.
    best_rows = []
    row_f = row.astype(jnp.float32)
    lane = jax.lax.broadcasted_iota(jnp.int32, (1, ROW_W), 1)
    for ch in range(CH):
        in_ch = (row >= ch * F) & (row < (ch + 1) * F)
        mgain = jnp.where(in_ch, feat_gain, jnp.float32(NEG))
        bg = jnp.max(mgain)
        brow = jnp.min(jnp.where(mgain == bg, row_f, jnp.float32(BIG)))
        sel = row_f == brow
        picked = jnp.sum(jnp.where(sel, block, 0.0), axis=0, keepdims=True)
        has = bg > jnp.float32(NEG_GATE)
        # no-valid-split guard: with bg == NEG the tie-break row may be
        # ANOTHER child's (out-of-child rows are also NEG), leaking the
        # sibling's gain/stats into this child's row — mask the whole
        # row back to the no-split sentinel (gain NEG, feature -1)
        picked = jnp.where(has, picked, 0.0)
        picked = jnp.where(lane == _OG,
                           jnp.where(has, picked, jnp.float32(NEG)), picked)
        feat_lane = jnp.where(has, picked[:, _OF:_OF + 1], -1.0)
        picked = jnp.where(lane == _OF, feat_lane, picked)
        picked = jnp.where((lane == _OLH) | (lane == _ORH),
                           picked - jnp.float32(K_EPSILON), picked)
        best_rows.append(picked)
    best_ref[:] = jnp.concatenate(best_rows, axis=0)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _run_scan(pvec, svec, fvec, hist3, *, interpret: bool):
    CH_F, _ = fvec.shape
    _, R, B = hist3.shape
    CH = svec.shape[0]
    F = R // CH
    kernel = functools.partial(_split_scan_kernel, CH=CH, F=F, B=B)
    return pl.pallas_call(
        kernel,
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec(memory_space=pltpu.VMEM),
                  pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=(pl.BlockSpec(memory_space=pltpu.VMEM),
                   pl.BlockSpec(memory_space=pltpu.VMEM)),
        out_shape=(jax.ShapeDtypeStruct((R, ROW_W), jnp.float32),
                   jax.ShapeDtypeStruct((CH, ROW_W), jnp.float32)),
        interpret=interpret,
    )(pvec, svec, fvec, hist3)


def index_per_feature(pf: PerFeatureSplit, i: int) -> PerFeatureSplit:
    """[CH, F]-batched PerFeatureSplit -> child i's [F] view."""
    return PerFeatureSplit(*[None if v is None else v[i] for v in pf])


def build_feature_statics(num_bins, default_bins, missing_types,
                          monotone=None, penalty=None, feature_mask=None,
                          cegb_feature_penalty=None, children: int = 2):
    """[CH*F, 8] f32 per-feature static matrix for best_splits_pallas —
    build ONCE per tree (outside the grow while-loop) and thread through;
    only feature_mask changes between trees."""
    F = num_bins.shape[0]
    z = jnp.zeros(F, jnp.float32)
    cols = [num_bins.astype(jnp.float32),
            default_bins.astype(jnp.float32),
            missing_types.astype(jnp.float32),
            z if monotone is None else monotone.astype(jnp.float32),
            jnp.ones(F, jnp.float32) if penalty is None
            else penalty.astype(jnp.float32),
            jnp.ones(F, jnp.float32) if feature_mask is None
            else feature_mask.astype(jnp.float32),
            z if cegb_feature_penalty is None
            else cegb_feature_penalty.astype(jnp.float32),
            z]
    one = jnp.stack(cols, axis=1)                       # [F, 8]
    return jnp.concatenate([one] * children, axis=0)


def _pack_inputs(hist, sum_g, sum_h, num_data, min_constraints,
                 max_constraints, params: SplitParams,
                 quant_scales=None):
    """(pvec, svec, hist3) shared by both kernel entry points — ONE place
    owns the lane layouts (_SG.._MAXC / _L1.._CEGBS).

    quant_scales=(g_scale, h_scale) accepts CODE-domain histograms and
    sums (integer code sums from ops/quantize) and folds the dequantize
    multiply into this pack pass, so the scan itself always runs on real
    g/h values: leaf outputs recover as -(Σg_code·gs) / (Σh_code·hs + λ)
    — float64-exact functions of the integer sums within the
    qz.exact_rows() envelope, one rounding per scale multiply.  The
    partition grow loop instead dequantizes each histogram as it leaves
    its kernel (grow_partition `deq`): cached, psum'd and
    sibling-subtracted histograms there mix with REAL-domain sums read
    back from earlier scan outputs, so a single domain everywhere beats
    saving one [F, B, 3] multiply."""
    CH, F, B, _ = hist.shape
    f32 = jnp.float32
    hist3 = jnp.moveaxis(hist.astype(f32), 3, 0).reshape(3, CH * F, B)
    if quant_scales is not None:
        gs = jnp.asarray(quant_scales[0], f32)
        hs = jnp.asarray(quant_scales[1], f32)
        hist3 = hist3 * jnp.stack([gs, hs, jnp.float32(1.0)])[:, None, None]
        sum_g = jnp.asarray(sum_g, f32) * gs
        sum_h = jnp.asarray(sum_h, f32) * hs
    ninf = jnp.full((CH,), -jnp.inf, f32)
    pinf = jnp.full((CH,), jnp.inf, f32)
    svec = jnp.stack([
        jnp.asarray(sum_g, f32).reshape(CH),
        jnp.asarray(sum_h, f32).reshape(CH),
        jnp.asarray(num_data, f32).reshape(CH),
        (ninf if min_constraints is None
         else jnp.asarray(min_constraints, f32).reshape(CH)),
        (pinf if max_constraints is None
         else jnp.asarray(max_constraints, f32).reshape(CH)),
        jnp.zeros(CH, f32), jnp.zeros(CH, f32), jnp.zeros(CH, f32)],
        axis=1)                                         # [CH, 8]
    pvec = jnp.stack([
        jnp.asarray(params.lambda_l1, f32),
        jnp.asarray(params.lambda_l2, f32),
        jnp.asarray(params.max_delta_step, f32),
        jnp.asarray(params.min_data_in_leaf, f32),
        jnp.asarray(params.min_sum_hessian_in_leaf, f32),
        jnp.asarray(params.min_gain_to_split, f32),
        jnp.asarray(params.cegb_split_penalty, f32)] + [jnp.float32(0.0)])
    return pvec, svec, hist3


def best_splits_pallas(hist,            # [CH, F, B, 3]
                       sum_g, sum_h, num_data,          # [CH] each
                       fvec,            # [CH*F, 8] from build_feature_statics
                       params: SplitParams,
                       min_constraints=None, max_constraints=None,  # [CH]
                       quant_scales=None,
                       interpret: bool = False) -> PerFeatureSplit:
    """Numerical best split per feature for CH children in one kernel
    launch.  Returns a PerFeatureSplit with [CH, F] fields (cat_mask
    None) matching ops/split.py best_split_per_feature vmapped over
    children, up to f32 prefix-sum association order.

    NOTE: counts ride f32 prefix sums in-kernel — exact only for
    num_data < 2^24; callers gate on that (the same bound as the
    partition engine's rowid planes)."""
    CH, F, B, _ = hist.shape
    pvec, svec, hist3 = _pack_inputs(hist, sum_g, sum_h, num_data,
                                     min_constraints, max_constraints,
                                     params, quant_scales=quant_scales)
    out, _ = _run_scan(pvec, svec, fvec, hist3, interpret=interpret)
    out = out.reshape(CH, F, ROW_W)
    gain = out[..., _OG]
    gain = jnp.where(gain <= NEG_GATE, K_MIN_SCORE, gain)
    return PerFeatureSplit(
        gain=gain,
        threshold=out[..., _OT].astype(jnp.int32),
        default_left=out[..., _ODL] > 0.5,
        left_sum_gradient=out[..., _OLG],
        left_sum_hessian=out[..., _OLH],
        left_count=jnp.round(out[..., _OLC]).astype(jnp.int32),
        left_output=out[..., _OLO],
        right_sum_gradient=out[..., _ORG],
        right_sum_hessian=out[..., _ORH],
        right_count=jnp.round(out[..., _ORC]).astype(jnp.int32),
        right_output=out[..., _ORO],
    )


def best_split_rows_pallas(hist, sum_g, sum_h, num_data, fvec,
                           params: SplitParams,
                           min_constraints=None, max_constraints=None,
                           quant_scales=None,
                           interpret: bool = False):
    """[CH, ROW_W] packed best-split rows (lane layout _O*): the kernel's
    in-kernel select_best_feature output, ready to scatter into the
    packed split cache of the grow loop.  gain lane uses the NEG
    sentinel (compare against NEG_GATE), feature lane is -1 when no
    valid split."""
    pvec, svec, hist3 = _pack_inputs(hist, sum_g, sum_h, num_data,
                                     min_constraints, max_constraints,
                                     params, quant_scales=quant_scales)
    _, best = _run_scan(pvec, svec, fvec, hist3, interpret=interpret)
    return best


def pack_split_row(res, cat_width: int = 0):
    """SplitResult -> [ROW_W (+cat_width)] packed row (XLA fallback used
    by the categorical/mixed path and forced splits; keeps K_MIN_SCORE
    gains as-is — any gain <= NEG_GATE means no split)."""
    f32 = jnp.float32
    vals = [jnp.asarray(res.gain, f32), jnp.asarray(res.feature, f32),
            jnp.asarray(res.threshold, f32),
            jnp.asarray(res.default_left, f32),
            jnp.asarray(res.left_sum_gradient, f32),
            jnp.asarray(res.left_sum_hessian, f32),
            jnp.asarray(res.left_count, f32),
            jnp.asarray(res.left_output, f32),
            jnp.asarray(res.right_sum_gradient, f32),
            jnp.asarray(res.right_sum_hessian, f32),
            jnp.asarray(res.right_count, f32),
            jnp.asarray(res.right_output, f32)]
    row = jnp.zeros(ROW_W + cat_width, f32)
    row = row.at[:12].set(jnp.stack(vals))
    if cat_width:
        row = row.at[ROW_W:].set(jnp.asarray(res.cat_mask, f32))
    return row

def scan_single(hist, sum_g, sum_h, cnt, params: SplitParams,
                fvec_pre=None, num_bins=None, default_bins=None,
                missing_types=None, monotone=None, penalty=None,
                feature_mask=None, cegb_pen=None, mn=None, mx=None,
                interpret=None) -> PerFeatureSplit:
    """One-child kernel dispatch shared by the serial/feature-parallel
    and voting scans in ops/grow.py — the two call sites must stay
    bit-identical (voting elects against serial gains) so the argument
    massaging lives HERE once."""
    import jax as _jax
    if interpret is None:
        interpret = _jax.default_backend() != "tpu"
    if fvec_pre is not None:
        fvec = fvec_pre
    else:
        fvec = build_feature_statics(
            num_bins, default_bins, missing_types, monotone=monotone,
            penalty=penalty, feature_mask=feature_mask, children=1)
    if cegb_pen is not None:
        fvec = fvec.at[:, _CEGBF].set(cegb_pen.astype(jnp.float32))
    pf = best_splits_pallas(
        hist[None], jnp.reshape(sum_g, (1,)), jnp.reshape(sum_h, (1,)),
        jnp.reshape(cnt, (1,)), fvec, params,
        min_constraints=None if mn is None else mn[:1],
        max_constraints=None if mx is None else mx[:1],
        interpret=interpret)
    return index_per_feature(pf, 0)


# -- roofline cost model (obs/perf) -------------------------------------- #
from ..obs.perf import KernelCost, cost_model  # noqa: E402


@cost_model("split/pallas")
def _cost_split_pallas(features: int, max_bin: int) -> KernelCost:
    """Fused Pallas split scan: same compulsory traffic as the XLA scan
    (one histogram read, one packed result row) — the kernel's win is
    dispatch count and VMEM reuse, not bytes, so the model is shared."""
    F, B = int(features), int(max_bin)
    return KernelCost("split/pallas", F * B * 3 * 4 + F * 64, 32 * F * B,
                      "fused scan; same byte floor as split/xla")
