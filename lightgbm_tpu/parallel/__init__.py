"""lightgbm_tpu.parallel — distributed data loading, tree learners and
the cross-host comm layer.

- ``distributed``: SocketComm (hub-and-spoke JSON allgather),
  ElasticComm (generation-fenced membership + liveness control plane),
  machine-list parsing and jax.distributed bring-up.
- ``dist_data``: rank-sharded ingest with distributed find-bin.
- ``learners``: shard_map'd parallel tree growers over a device mesh.
- ``collective``: the Collective interface over both backends — the
  in-process mesh (shard_map/psum) and the socket wire.
"""
from .collective import (Collective, MeshCollective,  # noqa: F401
                         SocketCollective, make_collective,
                         set_process_comm)
from .distributed import (ElasticComm, SocketComm,  # noqa: F401
                          WorldChangedError, initialize_from_config,
                          parse_machines, resolve_rank)

__all__ = [
    "Collective", "MeshCollective", "SocketCollective",
    "make_collective", "set_process_comm",
    "ElasticComm", "SocketComm", "WorldChangedError",
    "initialize_from_config", "parse_machines", "resolve_rank",
]
