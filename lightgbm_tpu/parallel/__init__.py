"""lightgbm_tpu.parallel — distributed data loading, tree learners and
the cross-host comm layer.

- ``distributed``: SocketComm (hub-and-spoke JSON allgather),
  ElasticComm (generation-fenced membership + liveness control plane),
  machine-list parsing and jax.distributed bring-up.
- ``dist_data``: rank-sharded ingest with distributed find-bin.
- ``learners``: shard_map'd parallel tree growers over a device mesh.
"""
from .distributed import (ElasticComm, SocketComm,  # noqa: F401
                          WorldChangedError, initialize_from_config,
                          parse_machines, resolve_rank)

__all__ = [
    "ElasticComm", "SocketComm", "WorldChangedError",
    "initialize_from_config", "parse_machines", "resolve_rank",
]
