"""Single ``Collective`` interface over the two comm backends.

PAPER.md's blueprint maps the reference's Network layer (src/network/:
Bruck / recursive-halving collectives over TCP sockets) onto *XLA
collectives over ICI*.  This module is that seam made explicit: one
interface for allreduce / allgather / scatter-reduce over histogram and
scalar payloads plus rank/world/fence queries, with two backends:

- ``MeshCollective`` — single-controller, in-process: the grow loop runs
  ``shard_map``'d over a ``jax.sharding.Mesh`` of the local devices and
  exchanges histograms with ``psum``/``all_gather`` that never leave HBM
  (no pickle, no socket hop, no per-collective host sync).  The host
  side of the interface is therefore trivial — host values are already
  global — while the traced side (the primitives below) carries
  trace-time byte attribution so comm counters and ``comm/mesh_psum``
  spans stay populated even though the collectives execute inside one
  fused XLA program.
- ``SocketCollective`` — cross-host: wraps the existing ``SocketComm``/
  ``ElasticComm`` hub-and-spoke wire (parallel/distributed.py) behind
  the same interface, preserving its retry policy, heartbeat liveness
  and generation fencing.  Traced collectives route through an ordered
  host callback (``SocketAxis``), so the SAME grow program serves both
  backends: ``axis_name`` is either a mesh axis string or a
  ``SocketAxis`` handle.

A third backend composes the two: ``HybridCollective``
(parallel/hybrid.py) psums within the host's local mesh and rides the
socket wire between per-host leaders — the topology docs/Distributed.md
names, with whole-host fault domains.

Backend selection rides ``Config.tpu_comm_backend``
(auto|mesh|socket|hybrid); ``make_collective`` resolves it, emits one
``comm_backend`` recorder event per (requested, resolved-topology)
change and falls back socket-ward when the mesh is unavailable (fewer
than two local devices, or the ``mesh_unavailable`` chaos drill) — see
docs/Distributed.md.
"""
from __future__ import annotations

import os
import threading
from contextlib import contextmanager
from functools import partial
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import log

#: the 1-D model-parallel mesh axis every learner shard_maps over
AXIS = "mp"

# jax moved shard_map out of experimental (and renamed check_rep to
# check_vma) across the versions this repo meets; resolve once here so
# every build site works on either spelling
try:
    from jax import shard_map as _shard_map
    _SHARD_CHECK_KW = "check_vma"
except ImportError:
    from jax.experimental.shard_map import shard_map as _shard_map
    _SHARD_CHECK_KW = "check_rep"


def shard_mapped(fn, mesh, in_specs, out_specs):
    """shard_map under either jax spelling (see _SHARD_CHECK_KW above)."""
    return _shard_map(fn, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **{_SHARD_CHECK_KW: False})


# --------------------------------------------------------------------- #
# Traced collective primitives.
#
# Every collective inside the grow programs (ops/grow.py,
# ops/grow_partition.py) goes through these instead of bare jax.lax so
# that (a) the mesh backend can attribute collective bytes at TRACE time
# (the ops execute inside one fused jit program — there is no host
# boundary to measure at), and (b) a SocketAxis handle swaps the XLA
# collective for an ordered host callback into the socket wire without
# touching the grow code.
# --------------------------------------------------------------------- #

_TLS = threading.local()


def _np_dtype(name: str) -> np.dtype:
    """np.dtype by name, resolving the accelerator dtypes (bfloat16 &
    friends) that plain numpy doesn't know through ml_dtypes."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, name))


def _leaf_bytes(x) -> int:
    try:
        shape = getattr(x, "shape", ())
        dtype = getattr(x, "dtype", None)
        item = np.dtype(dtype).itemsize if dtype is not None else 4
        return int(np.prod(shape)) * item if shape else item
    except Exception:  # noqa: BLE001 — accounting must never break tracing
        return 0


def _account(kind: str, tree) -> None:
    prof = getattr(_TLS, "profile", None)
    if prof is None:
        return
    nbytes = sum(_leaf_bytes(leaf) for leaf in jax.tree_util.tree_leaves(tree))
    cnt, tot = prof.get(kind, (0, 0))
    prof[kind] = (cnt + 1, tot + nbytes)


@contextmanager
def capture_traced(profile: Dict[str, Tuple[int, int]]):
    """Collect {collective kind: (call count, payload bytes)} for every
    traced primitive executed on this thread while the context is live —
    i.e. during the first (tracing) call of a jitted grow program."""
    prev = getattr(_TLS, "profile", None)
    _TLS.profile = profile
    try:
        yield profile
    finally:
        _TLS.profile = prev


def psum(x, axis):
    """Allreduce-sum over the collective axis (mesh string or SocketAxis)."""
    if isinstance(axis, SocketAxis):
        return axis.allreduce(x, "sum")
    _account("psum", x)
    return jax.lax.psum(x, axis)


def pmax(x, axis):
    """Allreduce-max over the collective axis."""
    if isinstance(axis, SocketAxis):
        return axis.allreduce(x, "max")
    _account("pmax", x)
    return jax.lax.pmax(x, axis)


def all_gather(x, axis, **kwargs):
    """Allgather over the collective axis (new leading world dim)."""
    if isinstance(axis, SocketAxis):
        return axis.gather(x)
    _account("all_gather", x)
    return jax.lax.all_gather(x, axis, **kwargs)


def psum_scatter(x, axis, **kwargs):
    """Scatter-reduce over the collective axis: each rank keeps its own
    shard of the summed payload (ReduceScatter)."""
    if isinstance(axis, SocketAxis):
        return axis.scatter_reduce(x, **kwargs)
    _account("psum_scatter", x)
    return jax.lax.psum_scatter(x, axis, **kwargs)


def axis_index(axis):
    """This shard's rank along the collective axis."""
    if isinstance(axis, SocketAxis):
        # the hybrid axis nests a mesh inside the wire: its shard index
        # is host-major * local-mesh-minor (HybridAxis.global_index)
        gi = getattr(axis, "global_index", None)
        if gi is not None:
            return gi()
        return jnp.int32(axis.rank)
    return jax.lax.axis_index(axis)


# --------------------------------------------------------------------- #
# The interface
# --------------------------------------------------------------------- #

class Collective:
    """Rank/world/fence queries plus host-payload collectives.

    Concrete backends add the traced side: ``MeshCollective`` hands the
    learners its mesh + axis string; ``SocketCollective`` hands them a
    ``SocketAxis`` whose traced ops call back into the wire."""

    backend = "none"

    @property
    def rank(self) -> int:
        raise NotImplementedError

    @property
    def world(self) -> int:
        raise NotImplementedError

    # host-payload collectives (scalars / small numpy arrays)
    def allreduce(self, value, op: str = "sum"):
        raise NotImplementedError

    def allgather(self, payload) -> List:
        raise NotImplementedError

    def scatter_reduce(self, value):
        """Allreduce then keep this rank's equal slice of dim 0."""
        total = self.allreduce(value, "sum")
        arr = np.asarray(total)
        per = arr.shape[0] // max(self.world, 1)
        return arr[self.rank * per:(self.rank + 1) * per]

    # membership / fencing
    def fence(self) -> int:
        """Barrier; returns the generation the world agreed on."""
        raise NotImplementedError

    def generation(self) -> int:
        return 0

    def world_changed(self):
        return None

    def fenced_ranks(self) -> Tuple[int, ...]:
        return ()

    def close(self) -> None:
        pass


class MeshCollective(Collective):
    """In-process shard_map/psum backend over the local devices.

    Single controller: the host process IS every rank, so host-payload
    collectives are identities ([payload] * world for allgather) and
    ``fence`` is free.  The real collectives are the traced primitives
    above, executed inside the jitted grow programs; ``bind`` wraps each
    jitted callable so its traced collective profile (captured once, at
    trace time) is re-emitted as backend-tagged comm counters and one
    ``comm/mesh_psum`` span per dispatch.
    """

    backend = "mesh"

    def __init__(self, num_machines: int, devices=None, axis: str = AXIS,
                 registry=None):
        self.axis = axis
        self._d = int(num_machines)
        devices = (jax.devices() if devices is None
                   else list(devices))[:num_machines]
        if len(devices) < num_machines:
            raise ValueError(
                "mesh backend needs %d devices, found %d"
                % (num_machines, len(devices)))
        self.mesh = jax.sharding.Mesh(np.asarray(devices), (axis,))
        self._profiles: Dict = {}
        if registry is None:
            from ..obs import default_registry
            registry = default_registry()
        from ..obs import adapters as obs_adapters
        m = obs_adapters.ensure_comm_metrics(registry, 0, self._d,
                                             backend="mesh")
        self._m_sent = m["lgbm_comm_bytes_sent_total"]
        self._m_recv = m["lgbm_comm_bytes_received_total"]
        self._m_rounds = m["lgbm_comm_allgather_total"]

    @property
    def rank(self) -> int:
        return 0

    @property
    def world(self) -> int:
        return self._d

    def allreduce(self, value, op: str = "sum"):
        return value          # host values are already global

    def allgather(self, payload) -> List:
        return [payload] * self._d

    def fence(self) -> int:
        return 0

    def shard_map(self, fn, in_specs, out_specs):
        return shard_mapped(fn, self.mesh, in_specs, out_specs)

    def bind(self, key, fn):
        """Wrap a jitted shard_mapped callable: the first call runs under
        ``capture_traced`` (tracing happens inside it, so the collective
        profile lands here exactly once per compilation); every call
        re-emits that profile as counters + a comm/mesh_psum span."""
        def wrapped(*args):
            prof = self._profiles.get(key)
            if prof is None:
                prof = {}
                with capture_traced(prof):
                    out = fn(*args)
                self._profiles[key] = prof
            else:
                out = fn(*args)
            self._emit(prof)
            return out
        return wrapped

    def _emit(self, prof: Dict[str, Tuple[int, int]]) -> None:
        if not prof:
            return
        ops = sum(c for c, _ in prof.values())
        nbytes = sum(b for _, b in prof.values())
        # logical payload bytes: what one shard contributes to (and
        # receives from) the reduction — the mesh moves them over ICI,
        # never through the host
        self._m_sent.inc(nbytes)
        self._m_recv.inc(nbytes)
        self._m_rounds.inc(ops)
        from ..obs import tracing
        if tracing.get_tracer().enabled:
            tracing.complete(
                "comm/mesh_psum", 0.0, cat="comm", nbytes=nbytes, ops=ops,
                world=self._d,
                **{k: dict(count=c, bytes=b) for k, (c, b) in prof.items()})


class SocketAxis:
    """Traced-collective handle for the socket backend.

    Grow-loop collectives become ORDERED host callbacks into the wrapped
    comm, so the same grow program that psums over a mesh axis string
    rendezvouses over TCP when handed this instead.  Every rank runs the
    identical program, so callbacks fire in the same order on every rank
    (the symmetry the tpulint ``collectives`` family enforces); each op
    carries a sequence tag and the combine verifies all ranks sent the
    same one, so a desync fails loudly instead of summing mismatched
    payloads.

    Exceptions inside an XLA host callback cannot propagate cleanly, so
    wire failures (CommFailure / WorldChangedError — the elastic fence)
    are parked on ``failure`` and re-raised by ``check_failure`` once the
    program returns; the payload degrades to zeros in the meantime.
    """

    def __init__(self, collective: "SocketCollective"):
        self._coll = collective
        self.rank = collective.rank
        self.world = collective.world
        self._seq = 0
        self.failure: Optional[BaseException] = None

    # static-arg hashability: jitted growers close over this handle
    def __hash__(self):
        return id(self)

    def __eq__(self, other):
        return self is other

    def _next_tag(self, kind: str) -> str:
        self._seq += 1
        return "%s:%d" % (kind, self._seq)

    def _call(self, fn, x, out_shape):
        from jax.experimental import io_callback
        return io_callback(fn, out_shape, x, ordered=True)

    def _host(self, kind: str, op: str, arr: np.ndarray,
              stack: bool) -> np.ndarray:
        tag = self._next_tag(kind)
        try:
            parts = self._coll.exchange_arrays(tag, np.asarray(arr))
            if stack:
                return np.stack(parts)
            out = parts[0].copy()
            for p in parts[1:]:
                out = np.maximum(out, p) if op == "max" else out + p
            return out.astype(arr.dtype, copy=False)
        except BaseException as exc:  # noqa: BLE001 — park, don't crash XLA
            if self.failure is None:
                self.failure = exc
            shape = ((self.world,) + arr.shape) if stack else arr.shape
            return np.zeros(shape, arr.dtype)

    def allreduce(self, x, op: str):
        x = jnp.asarray(x)
        out = jax.ShapeDtypeStruct(x.shape, x.dtype)
        return self._call(partial(self._host, "allreduce", op, stack=False),
                          x, out)

    def gather(self, x):
        x = jnp.asarray(x)
        out = jax.ShapeDtypeStruct((self.world,) + x.shape, x.dtype)
        return self._call(partial(self._host, "gather", "sum", stack=True),
                          x, out)

    def scatter_reduce(self, x, **kwargs):
        total = self.allreduce(x, "sum")
        per = total.shape[0] // self.world
        return jax.lax.dynamic_slice_in_dim(total, self.rank * per, per)

    def check_failure(self) -> None:
        if self.failure is not None:
            failure, self.failure = self.failure, None
            raise failure


class SocketCollective(Collective):
    """The SocketComm/ElasticComm wire behind the Collective interface.

    Delegation preserves the wrapped comm's whole resilience surface:
    ``_with_retry`` retry budgets, heartbeat liveness, poison frames and
    generation fencing all fire exactly as they do for the find-bin and
    elastic-sync allgathers that already ride this wire."""

    backend = "socket"

    def __init__(self, comm):
        self.comm = comm
        self._axis: Optional[SocketAxis] = None
        self._row_layout: Optional[Tuple[int, int]] = None

    @property
    def rank(self) -> int:
        return self.comm.rank

    @property
    def world(self) -> int:
        return self.comm.world

    def axis(self) -> SocketAxis:
        """The traced-collective handle for this comm (one per booster
        generation: a re-formed world gets a fresh axis + sequence)."""
        if self._axis is None:
            self._axis = SocketAxis(self)
        return self._axis

    # -- host payloads --------------------------------------------------
    def allgather(self, payload) -> List:
        return [p.get("v") if isinstance(p, dict) else None
                for p in self.comm.allgather({"v": payload})]

    def allreduce(self, value, op: str = "sum"):
        arr = np.asarray(value)
        parts = self.exchange_arrays("host:%s" % op, arr)
        out = parts[0].copy()
        for p in parts[1:]:
            out = np.maximum(out, p) if op == "max" else out + p
        return out.astype(arr.dtype, copy=False)

    def exchange_arrays(self, tag: str, arr: np.ndarray) -> List[np.ndarray]:
        """Allgather one ndarray (rank order), verifying every rank is in
        the same collective (same tag) — the wire-level symmetry check."""
        payload = {"tag": tag, "dtype": str(arr.dtype),
                   "shape": list(arr.shape), "v": arr.tolist()}
        replies = self.comm.allgather(payload)
        parts: List[np.ndarray] = []
        for r, p in enumerate(replies):
            if p is None or p.get("tag") != tag:
                raise RuntimeError(
                    "collective desync: rank %d sent %r during %r"
                    % (r, None if p is None else p.get("tag"), tag))
            parts.append(np.asarray(p["v"], _np_dtype(p["dtype"]))
                         .reshape(p["shape"]))
        return parts

    def row_layout(self, local_rows: int) -> Tuple[int, int]:
        """(global_rows, this rank's row offset) for the contiguous
        pre-partitioned shard layout — agreed once per booster via one
        tiny allgather (the quantized global-noise slice needs it)."""
        if self._row_layout is None:
            counts = [int(c[0]) for c in self.exchange_arrays(
                "row_layout", np.asarray([local_rows], np.int64))]
            start = int(sum(counts[:self.rank]))
            self._row_layout = (int(sum(counts)), start)
        return self._row_layout

    # -- membership / fencing -------------------------------------------
    def fence(self) -> int:
        self.exchange_arrays("fence", np.asarray([self.generation()],
                                                 np.int64))
        return self.generation()

    def generation(self) -> int:
        return int(getattr(self.comm, "generation", 0))

    def world_changed(self):
        wc = getattr(self.comm, "world_changed", None)
        return wc() if callable(wc) else None

    def fenced_ranks(self) -> Tuple[int, ...]:
        fr = getattr(self.comm, "fenced_ranks", None)
        return tuple(fr()) if callable(fr) else ()

    def close(self) -> None:
        self.comm.close()


# --------------------------------------------------------------------- #
# Backend selection
# --------------------------------------------------------------------- #

_process_comm = None
_process_comm_lock = threading.Lock()


def set_process_comm(comm) -> None:
    """Attach (or clear, with None) this process's cross-host comm so
    ``make_collective`` can wrap it.  The elastic supervisor attaches its
    generation's ElasticComm here before building each booster."""
    global _process_comm
    with _process_comm_lock:
        _process_comm = comm


def get_process_comm():
    with _process_comm_lock:
        return _process_comm


def _mesh_devices_available() -> int:
    # the mesh_unavailable chaos drill (tools/chaos_run.py) forces the
    # mesh path down to exercise the socket fallback
    chaos = os.environ.get("LGBM_TPU_CHAOS", "")
    if chaos.split(":")[0] == "mesh_unavailable":
        return 0
    try:
        return jax.device_count()
    except Exception:  # noqa: BLE001 — no backend at all
        return 0


def resolve_backend(config) -> str:
    """tpu_comm_backend -> concrete backend
    ('hybrid'|'mesh'|'socket'|'none'), given what is actually available
    in this process."""
    want = getattr(config, "tpu_comm_backend", "auto")
    comm = get_process_comm()
    have_socket = comm is not None and comm.world > 1
    have_mesh = _mesh_devices_available() > 1
    if want == "hybrid":
        if have_socket and have_mesh:
            return "hybrid"
        if have_socket:
            log.warning("tpu_comm_backend=hybrid but fewer than two local "
                        "devices are visible; falling back to the socket "
                        "backend")
            return "socket"
        if have_mesh:
            log.warning("tpu_comm_backend=hybrid but no cross-host comm is "
                        "attached to this process; using the mesh backend")
            return "mesh"
        return "none"
    if want == "socket":
        if have_socket:
            return "socket"
        log.warning("tpu_comm_backend=socket but no cross-host comm is "
                    "attached to this process; %s",
                    "using the mesh backend" if have_mesh
                    else "using the serial learner")
        return "mesh" if have_mesh else "none"
    if want == "mesh":
        if have_mesh:
            return "mesh"
        if have_socket:
            log.warning("tpu_comm_backend=mesh but fewer than two local "
                        "devices are visible; falling back to the socket "
                        "backend")
            return "socket"
        return "none"
    # auto: in-process mesh when the local devices allow it; a
    # multi-process world keeps its existing per-rank behavior unless
    # the socket backend is requested explicitly (docs/Distributed.md)
    return "mesh" if have_mesh else "none"


# one comm_backend recorder event per backend RESOLUTION, not per
# train() call: re-training on an unchanged topology says nothing new,
# while an actual change (fallback, re-formation shrinking the world)
# must stay observable for the chaos drills to assert on
_comm_event_lock = threading.Lock()
_last_comm_event: Optional[Tuple[str, str]] = None


def _reset_comm_backend_event() -> None:
    """Test hook: forget the last emitted (requested, topology) key."""
    global _last_comm_event
    with _comm_event_lock:
        _last_comm_event = None


def make_collective(config, num_machines: Optional[int] = None,
                    devices=None) -> Optional[Collective]:
    """Resolve tpu_comm_backend and build the backend, emitting a
    ``comm_backend`` recorder event tagged requested-vs-resolved on
    every topology change (the chaos drill's observable).  Returns None
    when no collective backend is available (serial)."""
    requested = getattr(config, "tpu_comm_backend", "auto")
    backend = resolve_backend(config)
    coll: Optional[Collective] = None
    if backend == "hybrid":
        from .hybrid import HybridCollective, resolve_local_devices
        local = resolve_local_devices(config, _mesh_devices_available())
        if local > 1:
            coll = HybridCollective(get_process_comm(), local,
                                    devices=devices)
        else:
            backend = "socket"
            coll = SocketCollective(get_process_comm())
    elif backend == "socket":
        coll = SocketCollective(get_process_comm())
    elif backend == "mesh":
        if num_machines is None:
            from .learners import resolve_num_machines
            num_machines = resolve_num_machines(config)
        if num_machines > 1:
            coll = MeshCollective(num_machines, devices=devices)
        else:
            backend = "none"
    if coll is None:
        topology = "none"
    elif backend == "hybrid":
        topology = "hybrid[%dx%d]" % (coll.world, coll.local_world)
    else:
        topology = "%s[%d]" % (backend, coll.world)
    global _last_comm_event
    with _comm_event_lock:
        emit = (requested, topology) != _last_comm_event
        if emit:
            _last_comm_event = (requested, topology)
    if emit:
        from ..obs.recorder import comm_backend_event
        comm_backend_event(config, backend, requested=requested,
                           topology=topology,
                           world=coll.world if coll is not None else 1)
    return coll
