"""Distributed data loading: rank-sharded ingest + distributed find-bin.

The host-side half of the reference's multi-machine loading
(src/io/dataset_loader.cpp):

- distributed find-bin (:873-955): every rank computes bin mappers only
  for its contiguous feature shard, then the serialized mappers are
  allgathered — compute sharding with single-rank-identical results
  (io/dataset.py BinnedDataset.construct(find_bin_comm=...)).
- query-granular row pre-partition (:694-740): rows assigned to ranks
  whole-query-at-a-time so ranking groups never straddle machines
  (io/loader.py load_data_file(pre_partition=True) and
  pre_partition_rows below).

The collective here is a host-side exchange of small serialized mapper
dicts — setup, not hot path — so the transport is INJECTED (the
precedent is the reference's LGBM_NetworkInitWithFunctions external
collective hook, c_api.cpp:1373): in one process use LocalComm; across
hosts pass a callable that moves bytes however the launcher likes (TCP,
files on shared storage, jax.experimental multihost utils).
"""
from __future__ import annotations

from typing import Callable, List, Optional, Sequence

import numpy as np

from ..io.dataset import BinnedDataset, _issparse
from ..io.metadata import Metadata
from ..utils import log


class LocalComm:
    """In-process allgather for N simulated ranks (one thread per rank,
    the single-process multi-rank emulation of SURVEY §4.5): each rank
    deposits its contribution and blocks on a barrier until every rank
    has, then all see the full list in rank order."""

    def __init__(self, world: int):
        import threading
        self.world = world
        self._slots: List[Optional[dict]] = [None] * world
        self._barrier = threading.Barrier(world)

    def allgather_fn(self, rank: int) -> Callable[[dict], List[dict]]:
        def allgather(payload: dict) -> List[dict]:
            self._slots[rank] = payload
            self._barrier.wait(timeout=300)
            out = list(self._slots)
            # second barrier: no rank may start the NEXT round (and
            # overwrite its slot) until every rank has read this one
            self._barrier.wait(timeout=300)
            return out
        return allgather


def slice_class_major(init_score, n: int, rows: np.ndarray) -> np.ndarray:
    """Slice a class-major [k*n] init-score vector by row indices —
    the single home of the multiclass layout slice (shared by
    construct_rank_shard and the two_round pre-partition loader).
    Fails loudly on a length that is not a multiple of n (stale side
    file)."""
    s = np.asarray(init_score, np.float64).reshape(-1)
    if n <= 0 or s.size % n != 0:
        log.fatal("init_score length %d is not a multiple of num_data %d"
                  % (s.size, n))
    k = max(1, s.size // n)
    return s.reshape(k, n)[:, rows].reshape(-1)


def pre_partition_rows(n: int, rank: int, num_machines: int,
                       query_boundaries: Optional[np.ndarray] = None,
                       seed: int = 0):
    """(row_indices, q_rank) assigned to `rank` (dataset_loader.cpp:
    694-740): uniform random per row, or whole-query-at-a-time when
    query boundaries are given so ranking groups never straddle ranks.
    q_rank ([num_queries] or None) is returned so callers can derive the
    per-rank group sizes from the SAME draw."""
    rng = np.random.RandomState(seed)
    if query_boundaries is None:
        return np.flatnonzero(rng.randint(0, num_machines, n) == rank), None
    nq = len(query_boundaries) - 1
    q_rank = rng.randint(0, num_machines, nq)
    q_of_row = np.repeat(np.arange(nq),
                         np.diff(np.asarray(query_boundaries)))
    return np.flatnonzero(q_rank[q_of_row] == rank), q_rank


def exchange_sample_rows(X: np.ndarray, config, keep: np.ndarray,
                         rank: int, world: int, allgather):
    """Distributed find-bin sample assembly: each rank contributes only
    the sample rows that live on ITS shard, one allgather reassembles
    the full sample in global-row order.

    Every rank replicates the global sample DRAW (a cheap index
    computation seeded by data_random_seed — no data touched), then
    slices X only at the drawn indices it owns.  The pre-partition is
    exact — each global row lives on exactly one rank — so the
    reassembled (rows, values) block equals the single-rank extraction
    ``X[sample_indices]`` bitwise (JSON round-trips float64 exactly),
    and every mapper derived from it is bitwise-identical to a
    single-rank load.  Returns (sample_indices, Xs) for
    ``BinnedDataset.construct(sample_override=...)``.
    """
    n, num_raw = X.shape
    sample_cnt = min(config.bin_construct_sample_cnt, n)
    rng = np.random.RandomState(config.data_random_seed)
    sample_indices = (np.arange(n) if sample_cnt >= n else
                      np.sort(rng.choice(n, sample_cnt, replace=False)))
    mine = sample_indices[np.isin(sample_indices, keep)]
    vals = np.asarray(X[mine], np.float64)
    parts = allgather({"rows": mine.tolist(), "vals": vals.tolist()})
    rows = np.concatenate(
        [np.asarray(p["rows"], np.int64) for p in parts]) \
        if parts else np.empty(0, np.int64)
    blocks = [np.asarray(p["vals"], np.float64).reshape(len(p["rows"]),
                                                        num_raw)
              for p in parts]
    xs = np.concatenate(blocks) if blocks else np.empty((0, num_raw))
    order = np.argsort(rows, kind="stable")
    rows, xs = rows[order], xs[order]
    if not np.array_equal(rows, sample_indices):
        log.fatal("distributed find-bin sample reassembly does not cover "
                  "the global draw (%d of %d rows) — the row partition "
                  "and the sample draw disagree on seed or world"
                  % (len(rows), len(sample_indices)))
    return sample_indices, xs


def construct_rank_shard(X: np.ndarray, config, rank: int, world: int,
                         comm: LocalComm,
                         label: Optional[np.ndarray] = None,
                         group: Optional[Sequence[int]] = None,
                         weight: Optional[np.ndarray] = None,
                         init_score: Optional[np.ndarray] = None,
                         categorical_features: Sequence[int] = (),
                         pre_partition: bool = True) -> BinnedDataset:
    """One rank's view of a distributed load: (optionally) keep only this
    rank's row partition, but find bins feature-sharded over the FULL
    local sample and allgather — the mappers come out identical on every
    rank (and identical to a single-rank load of the same data).

    Returns the rank-local BinnedDataset ready for the data-parallel
    learners (rows of this rank only when pre_partition).
    """
    X = np.asarray(X)
    n = len(X)
    qb = None
    if group is not None:
        qb = np.concatenate([[0], np.cumsum(np.asarray(group))])
    if pre_partition:
        keep, q_rank = pre_partition_rows(n, rank, world, qb,
                                          seed=config.data_random_seed)
    else:
        keep, q_rank = np.arange(n), None

    def fill_meta(meta, rows):
        if label is not None:
            meta.set_label(np.asarray(label)[rows])
        if weight is not None:
            meta.set_weights(np.asarray(weight)[rows])
        if init_score is not None:
            meta.set_init_score(slice_class_major(init_score, n, rows))

    # find-bin runs BEFORE the row partition, on the full data, so every
    # rank derives identical mappers (the reference's !pre_partition
    # find-bin semantics; with pre_partition the reference accepts
    # shard-local mappers — we keep the exact variant, which is stronger)
    allgather = comm.allgather_fn(rank)
    # distributed find-bin sampling: assemble the bin-construction
    # sample from per-rank row shards instead of every rank slicing the
    # full matrix (dense + pre-partitioned only: sparse find-bin works
    # on stored entries per column, and without a row partition there
    # is no shard to sample from)
    sample_override = None
    if (pre_partition and world > 1 and not _issparse(X)
            and bool(getattr(config, "tpu_dist_find_bin", True))):
        # symmetric: world, pre_partition, sparsity and config are
        # identical on every rank, so all ranks take the same branch
        # tpulint: disable-next-line=collective-rank-branch
        sample_override = exchange_sample_rows(X, config, keep, rank,
                                               world, allgather)
    mapper_ds = BinnedDataset.construct(
        X, config, metadata=Metadata(n),
        categorical_features=categorical_features,
        find_bin_comm=(rank, world, allgather),
        sample_override=sample_override,
        bin_rows=not pre_partition)   # mapper-only when re-binning a shard
    if not pre_partition:
        fill_meta(mapper_ds.metadata, keep)
        if group is not None:
            mapper_ds.metadata.set_query(np.asarray(group))
        mapper_ds.dist_row_ids = keep
        mapper_ds.dist_global_rows = n
        return mapper_ds

    # bin ONLY this rank's rows against the agreed mappers
    meta = Metadata(len(keep))
    fill_meta(meta, keep)
    if group is not None and q_rank is not None:
        meta.set_query(np.asarray(group)[q_rank == rank])
    shard = BinnedDataset.construct(
        X[keep], config, metadata=meta,
        categorical_features=categorical_features,
        reference=mapper_ds)
    # the partition draw is random per row, so downstream global-stream
    # consumers (quantized stochastic rounding) need the actual indices
    shard.dist_row_ids = keep
    shard.dist_global_rows = n
    return shard


def load_rank_shard_file(config, filename: str, rank: int, world: int,
                         comm: LocalComm) -> BinnedDataset:
    """File-based rank shard: parse the shared input file, pre-partition
    rows (query-granular when groups exist), distributed find-bin."""
    from ..io import loader as loader_mod
    d = loader_mod.load_data_file(config, filename)
    log.debug("rank %d/%d loaded %s: %d rows", rank, world, filename,
              len(d.X))
    return construct_rank_shard(
        d.X, config, rank, world, comm, label=d.label, group=d.group,
        weight=d.weight, init_score=d.init_score,
        categorical_features=d.categorical or ())
