"""Multi-host wiring: the machine-list entry point + cross-host comm.

The reference trains across machines out of the box: Application reads
`machines` / `machine_list_filename`, Network::Init builds a TCP
connect mesh and rank is found by matching local interface addresses
(src/network/linkers_socket.cpp:77-162, application.cpp:96-98).  The
TPU-native equivalent has two halves:

1. **Device-side collectives** — `jax.distributed.initialize` attaches
   this process to the JAX coordination service; afterwards
   `jax.devices()` spans every host and the SAME shard_map'd learners
   (parallel/learners.py) emit ICI/DCN collectives with no code change.
   `initialize_from_config` maps the reference's machine-list config
   onto (coordinator_address, num_processes, process_id).

2. **Host-side setup exchange** — distributed find-bin allgathers small
   serialized bin mappers BEFORE any device array exists
   (dist_data.construct_rank_shard).  `SocketComm` is the cross-host
   transport for that seam (LocalComm covers single-process testing):
   a hub-and-spoke TCP allgather on `local_listen_port + 1` (the
   machine-list port itself belongs to the JAX coordination service;
   open BOTH in the firewall), the moral equivalent of the reference's
   one-shot mapper Allgather
   (dataset_loader.cpp:873-955) without the O(n^2) pairwise mesh the
   reference builds for its hot-path collectives (ours ride XLA).

Launch recipe (every host runs the same command):

    # host0 is the coordinator; rank resolved from local addresses
    python -m lightgbm_tpu config=train.conf \
        machines=host0:12400,host1:12400 num_machines=2

or from Python:

    cfg = Config(machines="host0:12400,host1:12400", num_machines=2)
    rank, world = initialize_from_config(cfg)     # jax.distributed up
    comm = SocketComm(rank, world, parse_machines(cfg))
    shard = dist_data.construct_rank_shard(X, cfg, rank, world, comm)
    ... ParallelGrower("data", jax.device_count()) ...
"""
from __future__ import annotations

import errno
import hashlib
import json
import math
import os
import select
import socket
import struct
import threading
import time
import uuid
from contextlib import nullcontext
from typing import Dict, List, Optional, Tuple

from ..obs import tracing as obs_tracing
from ..resilience.comm import (CommFailure, FaultInjector, Heartbeat,
                               RetryPolicy, WorldChangedError)
from ..utils import log

# sentinel returned by _with_retry when the fault injector swallowed the
# frame (drop): callers treat the operation as "done" and the PEER's
# op-timeout machinery is what notices the loss
_DROPPED = object()

RANK_ENV = "LIGHTGBM_TPU_RANK"   # explicit override, highest priority


def parse_machines(config) -> List[str]:
    """machine list as ["host:port", ...] from `machines` or
    `machine_list_filename` (config.h:748-755); ports default to
    local_listen_port + rank-position like the reference's
    machine-file parser (linkers_socket.cpp:77-121)."""
    entries: List[str] = []
    if getattr(config, "machines", ""):
        entries = [m.strip() for m in config.machines.split(",") if m.strip()]
    elif getattr(config, "machine_list_filename", ""):
        with open(config.machine_list_filename) as f:
            entries = [ln.strip() for ln in f
                       if ln.strip() and not ln.startswith("#")]
    out = []
    for e in entries:
        # the reference's machine files separate host and port with
        # spaces or tabs (linkers_socket.cpp:77-121); normalize first
        e = e.replace("\t", " ").strip()
        if " " in e:
            host, port = e.split()[:2]
            e = "%s:%s" % (host, port)
        if ":" not in e:
            e = "%s:%d" % (e, config.local_listen_port)
        out.append(e)
    return out


def _local_addresses() -> set:
    """Hostnames/IPs that mean 'this machine' (the address-matching rank
    discovery of linkers_socket.cpp:123-160)."""
    names = {"localhost", "127.0.0.1", "::1"}
    try:
        host = socket.gethostname()
        names.add(host)
        names.add(socket.getfqdn())
        for info in socket.getaddrinfo(host, None):
            names.add(info[4][0])
    except OSError:
        pass
    return names


def rank_from_env() -> Optional[int]:
    """LIGHTGBM_TPU_RANK as an int, None when unset — the single home
    of the env-override parsing (resolve_rank and the CLI pre-partition
    guard both consult it)."""
    env = os.environ.get(RANK_ENV)
    if env is None:
        return None
    try:
        return int(env)
    except ValueError:
        log.fatal("%s must be an integer rank, got %r" % (RANK_ENV, env))
        return None


def resolve_rank(machines: List[str],
                 explicit: Optional[int] = None) -> int:
    """This process's rank: explicit argument > LIGHTGBM_TPU_RANK env >
    local-address match against the machine list."""
    if explicit is not None:
        return int(explicit)
    env = rank_from_env()
    if env is not None:
        return env
    local = _local_addresses()
    matches = [i for i, m in enumerate(machines)
               if m.rsplit(":", 1)[0] in local]
    if len(matches) == 1:
        return matches[0]
    if len(matches) > 1:
        # several list entries name this machine (multi-process per
        # host): address matching cannot disambiguate — silently taking
        # the first would give every local process the same rank
        log.fatal("Machine list has %d entries matching this host "
                  "(%s); set %s or machine_rank per process"
                  % (len(matches), machines, RANK_ENV))
    log.fatal("Could not find local machine in the machine list %s; "
              "set %s or machine_rank explicitly" % (machines, RANK_ENV))
    return -1


def initialize_from_config(config, rank: Optional[int] = None
                           ) -> Tuple[int, int]:
    """Attach this process to the multi-host JAX runtime from the
    reference's machine-list config (the Network::Init analogue,
    application.cpp:96-98).  Returns (rank, num_machines); a no-op
    (0, 1) for single-machine configs.

    After this call jax.devices() spans all hosts, so
    ParallelGrower/resolve_num_machines build a GLOBAL mesh and the
    shard_map'd learners' psum/all_gather ride ICI/DCN across hosts.
    """
    if getattr(config, "num_machines", 1) <= 1:
        return 0, 1
    machines = parse_machines(config)
    if not machines:
        log.warning("num_machines=%d but no machine list configured; "
                    "staying single-machine", config.num_machines)
        return 0, 1
    if len(machines) < config.num_machines:
        # a silently clamped world means some expected machines can
        # never join — fail loudly like the reference's Network::Init
        # does on a short machine file; a LONGER shared list is fine
        # (the reference uses the first num_machines entries)
        log.fatal("machine list has %d entries but num_machines=%d; "
                  "the list is short" % (len(machines), config.num_machines))
    world = config.num_machines
    machines = machines[:world]
    cfg_rank = getattr(config, "machine_rank", -1)
    r = resolve_rank(machines,
                     rank if rank is not None
                     else (cfg_rank if cfg_rank >= 0 else None))
    if not 0 <= r < world:
        # catch it here with a named error rather than letting
        # jax.distributed.initialize fail with an opaque
        # coordination-service timeout
        log.fatal("resolved rank %d is outside [0, %d); check %s / "
                  "machine_rank against the machine list" % (r, world, RANK_ENV))
    import jax
    jax.distributed.initialize(coordinator_address=machines[0],
                               num_processes=world, process_id=r)
    # every JSON-mode log line from here on carries this process's
    # cluster coordinates (utils/log.bind_context)
    log.bind_context(rank=r, world=world)
    log.info("Connected to %d-machine cluster as rank %d (%d devices "
             "visible)", world, r, jax.device_count())
    return r, world


class SocketComm:
    """Cross-host allgather for the find-bin seam: hub-and-spoke TCP
    with length-prefixed JSON payloads.

    JSON, deliberately: the payloads are plain bin-mapper state dicts
    (numbers, strings, lists), and a non-executable wire format means a
    hostile peer that reaches the port can at worst corrupt mapper
    state — never run code, matching the reference's numeric-buffer-only
    socket mesh (linkers_socket.cpp).  Dict keys round-trip as strings;
    the find-bin merge re-ints them (io/dataset.py).

    Rank 0 binds machine-list port + 1 (port_offset; the list port is
    the JAX coordinator's) and accepts world-1 spokes; each
    allgather round every spoke sends its payload, the hub replies with
    the full rank-ordered list.  Setup-phase traffic only (a few KB of
    serialized BinMapper state) — hot-path collectives are XLA's job.

    Wire format (v3, span-trace + generation aware): the spoke
    handshake is ``!iqd`` (rank, generation, local wall clock) and the
    hub replies ``!16sqdd`` (comm session id, generation, recv time,
    send time) — an NTP-style exchange whose midpoint estimates each
    spoke's clock offset against the hub for tools/trace_merge.py.
    Every frame is then an 8-byte ``!q`` length + 16-byte trace-id +
    8-byte ``!q`` span-id + 8-byte ``!q`` generation + 1-byte frame
    kind header + JSON blob.  The trace fields carry the sender's
    collective trace-id and live span so per-rank trace files correlate
    (all zeros when tracing is off).  The generation is the elasticity
    fence: a plain SocketComm lives its whole life at generation 0, an
    ElasticComm bumps it on every world re-formation, and a receiver
    REJECTS any data frame stamped with a different generation
    (``WorldChangedError``) so a fenced rank's stale traffic can never
    corrupt a re-formed world.  Kind ``FRAME_POISON`` aborts the
    receiver's collective immediately (bounded-time failure
    propagation).  The header is always present, keeping the protocol
    uniform; every rank runs the same code, so there is no version
    skew.
    """

    def __init__(self, rank: int, world: int, machines: List[str],
                 timeout_s: float = 120.0, port_offset: int = 1,
                 retry: Optional[RetryPolicy] = None,
                 op_timeout_s: float = 0.0,
                 heartbeat_s: float = 0.0,
                 injector: Optional[FaultInjector] = None,
                 generation: int = 0):
        """port_offset: the machine-list port belongs to the JAX
        coordination service (initialize_from_config) — binding the hub
        there would EADDRINUSE against it, so the find-bin comm uses
        port + 1 by default (pass 0 when jax.distributed is not in
        play).

        retry: RetryPolicy wrapping every post-setup wire operation
        (default RetryPolicy()); op_timeout_s > 0 caps each individual
        send/recv (default: inherit timeout_s); heartbeat_s > 0 starts
        the rank-liveness probe thread; injector is the test-only
        FaultInjector hook consulted before each wire op.
        """
        self._init_state(rank, world, timeout_s, retry, op_timeout_s,
                         injector, generation)
        host, port = machines[0].rsplit(":", 1)
        port = int(port) + port_offset
        if world == 1:
            self._publish_trace_identity()
            return
        if rank == 0:
            srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)  # tpulint: ok=socket-no-with
            srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            # bind the interface the machine list names for rank 0.  If
            # that address is not locally bindable (NAT / port-forward
            # deployments list the externally-reachable name), fall back
            # to all interfaces — but LOUDLY, since that widens exposure
            try:
                srv.bind((host, int(port)))
            except OSError as e:
                # only a genuinely non-local / non-resolvable address
                # falls back (NAT / port-forward lists the external
                # name, which may not even resolve from inside);
                # EADDRINUSE etc. must surface as the port conflict it is
                if not (e.errno == errno.EADDRNOTAVAIL
                        or isinstance(e, socket.gaierror)):
                    srv.close()
                    raise
                log.warning("SocketComm hub cannot bind %s:%d (%s) — "
                            "assuming NAT/port-forwarding and binding "
                            "all interfaces; firewall port %d to the "
                            "training cluster", host, int(port), e,
                            int(port))
                srv.bind(("", int(port)))
            srv.listen(world - 1)
            srv.settimeout(timeout_s)
            by_rank = {}
            t0 = time.monotonic()
            for _ in range(world - 1):
                conn, _addr = srv.accept()
                conn.settimeout(timeout_s)
                # 20-byte spoke handshake: rank + generation + the
                # spoke's wall clock at send time (t0 of the NTP-style
                # offset exchange)
                r, _peer_gen, _peer_t0 = struct.unpack(
                    "!iqd", _recv_exact(conn, 20))
                by_rank[r] = (conn, time.time())
            # waiting for world-1 spokes to dial in is the hub's share
            # of cluster-formation skew; the 20-byte rank handshakes are
            # the first wire traffic
            self._m_wait.inc(time.monotonic() - t0)
            self._m_recv.inc(20 * (world - 1))
            srv.close()
            # reply to every spoke: session id + the hub's generation +
            # (t1 recv time, t2 send time) so each spoke closes its own
            # offset estimate
            for r in range(1, world):
                conn, t1 = by_rank[r]
                conn.sendall(struct.pack("!16sqdd", self._session,
                                         self.generation, t1, time.time()))
            self._m_sent.inc(40 * (world - 1))
            self._peers = [by_rank[r][0] for r in range(1, world)]
            self._peer_ranks = list(range(1, world))
        else:
            # retry-connect until the hub binds (every host launches the
            # same command, so spokes may start before rank 0 listens —
            # the reference's linkers retry the same way)
            deadline = time.monotonic() + timeout_s
            t0 = time.monotonic()
            while True:
                s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)  # tpulint: ok=socket-no-with
                s.settimeout(min(5.0, timeout_s))
                try:
                    s.connect((host, int(port)))
                    break
                except OSError:
                    s.close()
                    if time.monotonic() >= deadline:
                        raise
                    time.sleep(0.25)
            self._m_wait.inc(time.monotonic() - t0)
            s.settimeout(timeout_s)
            wall_t0 = time.time()
            s.sendall(struct.pack("!iqd", rank, self.generation, wall_t0))
            self._m_sent.inc(20)
            self._session, hub_gen, t1, t2 = struct.unpack(
                "!16sqdd", _recv_exact(s, 40))
            # the hub's generation is authoritative (a restarted spoke
            # rejoining an elastic world adopts the current one)
            self.generation = hub_gen
            wall_t3 = time.time()
            self._m_recv.inc(40)
            # NTP midpoint: hub clock minus this rank's clock; add it to
            # local wall timestamps to express them in hub time
            self._clock_offset_s = ((t1 - wall_t0) + (t2 - wall_t3)) / 2.0
            self._clock_rtt_s = (wall_t3 - wall_t0) - (t2 - t1)
            self._peers = [s]
            self._peer_ranks = [0]
        self._publish_trace_identity()
        # setup handshakes above ran under the generous timeout_s; from
        # here every individual send/recv is capped at op_timeout so a
        # hung peer surfaces as a retryable timeout, not a 2-minute stall
        for s in self._peers:
            s.settimeout(self.op_timeout)
        if heartbeat_s > 0:
            self.start_heartbeat(heartbeat_s)

    def _init_state(self, rank: int, world: int, timeout_s: float,
                    retry: Optional[RetryPolicy], op_timeout_s: float,
                    injector: Optional[FaultInjector],
                    generation: int = 0) -> None:
        """Per-instance comm state shared by SocketComm and ElasticComm
        (which forms its topology first and only then knows its rank and
        world, so this cannot live inline in __init__)."""
        self.rank, self.world = rank, world
        self.timeout = timeout_s
        self.retry = retry if retry is not None else RetryPolicy()
        self.op_timeout = op_timeout_s if op_timeout_s > 0 else timeout_s
        self._injector = injector
        self._heartbeat: Optional[Heartbeat] = None
        self.generation = int(generation)
        # set by the control plane (poison / liveness conviction / hub
        # loss): _with_retry raises it instead of retrying, so a blocked
        # or failing collective surfaces the topology change in bounded
        # time rather than burning the whole retry budget
        self._world_changed: Optional[WorldChangedError] = None
        self._peers: List[socket.socket] = []
        # hub peers arrive rank-ordered 1..world-1; a spoke's single
        # peer is the hub (rank 0) — CommFailure names ranks from this
        self._peer_ranks: List[int] = []
        # comm counters (bytes in/out, allgather rounds, sync-wait
        # seconds, retries/aborts) tagged rank/world in the process-wide
        # registry — the comm quarter of the unified telemetry layer
        from ..obs import adapters as obs_adapters
        from ..obs import default_registry
        m = obs_adapters.ensure_comm_metrics(default_registry(), rank, world)
        self._m_sent = m["lgbm_comm_bytes_sent_total"]
        self._m_recv = m["lgbm_comm_bytes_received_total"]
        self._m_allgather = m["lgbm_comm_allgather_total"]
        self._m_wait = m["lgbm_comm_sync_wait_seconds_total"]
        self._m_retries = m["lgbm_comm_retries_total"]
        self._m_failures = m["lgbm_comm_failures_total"]
        # span-trace correlation state: the comm session id (minted by
        # the hub, learned by spokes in the handshake) + a per-instance
        # collective sequence number derive cluster-unique collective
        # trace ids; clock offset is this rank's wall clock vs the hub's
        self._session = uuid.uuid4().bytes
        self._seq = 0
        self._clock_offset_s = 0.0
        self._clock_rtt_s = 0.0
        # hub-side straggler signal: per-peer blocking-recv seconds from
        # the most recent allgather (slow_hosts reads it), plus the
        # per-peer MAX since take_peer_waits last drained it — a round
        # runs many allgathers and the straggler shows in the worst one,
        # which last-wins _peer_waits would overwrite
        self._peer_waits: Dict[int, float] = {}
        self._peer_waits_max: Dict[int, float] = {}

    @classmethod
    def from_config(cls, rank: int, world: int, machines: List[str],
                    config, **kwargs) -> "SocketComm":
        """Construct with the resilience knobs resolved from a Config
        (tpu_comm_retries / tpu_comm_backoff_ms / tpu_comm_backoff_max_ms /
        tpu_comm_op_timeout_s / tpu_comm_heartbeat_s)."""
        kwargs.setdefault("retry", RetryPolicy.from_config(config))
        kwargs.setdefault("op_timeout_s",
                          float(getattr(config, "tpu_comm_op_timeout_s", 0.0)))
        kwargs.setdefault("heartbeat_s",
                          float(getattr(config, "tpu_comm_heartbeat_s", 0.0)))
        return cls(rank, world, machines, **kwargs)

    # -- retry / liveness ----------------------------------------------
    def _with_retry(self, op: str, peer_rank: int, fn):
        """Run one whole-frame wire operation under the retry policy.

        The injector (when armed) fires BEFORE the wire is touched, so
        injected faults retry protocol-cleanly; a real failure after
        partial frame traffic means the peer is gone and the remaining
        attempts fail fast until CommFailure names it.  Returns fn()'s
        value, or the _DROPPED sentinel for an injected drop.
        """
        attempts = self.retry.retries + 1
        last: Optional[BaseException] = None
        for attempt in range(1, attempts + 1):
            wc = self._world_changed
            if wc is not None:
                # the control plane already knows the membership is
                # wrong — retrying the wire op would just burn the
                # budget against sockets the fence deliberately killed
                raise wc
            try:
                if self._injector is not None:
                    if self._injector.check(op) == FaultInjector.DROP:
                        return _DROPPED
                return fn()
            except (CommFailure, WorldChangedError):
                raise
            except (OSError, ConnectionError) as exc:
                wc = self._world_changed
                if wc is not None:
                    raise wc
                last = exc
                if attempt >= attempts:
                    break
                self._m_retries.inc()
                delay = self.retry.backoff_s(attempt)
                log.warning("comm %s to rank %d failed (%s); retry %d/%d "
                            "in %.0f ms", op, peer_rank, exc, attempt,
                            self.retry.retries, delay * 1e3)
                time.sleep(delay)
        self._m_failures.inc()
        raise CommFailure(op, peer_rank, attempts, last)

    def start_heartbeat(self, interval_s: float) -> Optional[Heartbeat]:
        """Start (or return the running) rank-liveness probe thread."""
        if self.world == 1:
            return None
        if self._heartbeat is None:
            from ..obs import default_registry
            self._heartbeat = Heartbeat(
                self._peer_liveness, interval_s, rank=self.rank,
                world=self.world, registry=default_registry()).start()
        return self._heartbeat

    def _peer_liveness(self) -> List[int]:
        """Passive socket health probe: a peer whose socket is readable
        with zero bytes (EOF) or errored is reported dead.  Pending
        legitimate frame data reads as alive (MSG_PEEK does not consume
        it)."""
        dead: List[int] = []
        for idx, s in enumerate(self._peers):
            r = self._peer_ranks[idx] if idx < len(self._peer_ranks) else idx
            try:
                readable, _, errored = select.select([s], [], [s], 0)
                if errored:
                    dead.append(r)
                elif readable and s.recv(1, socket.MSG_PEEK) == b"":
                    dead.append(r)
            except (OSError, ValueError):
                dead.append(r)
        return dead

    def dead_ranks(self) -> List[int]:
        hb = self._heartbeat
        return hb.dead_ranks() if hb is not None else []

    def slow_hosts(self, threshold_s: float) -> List[int]:
        """Ranks whose last hub-side allgather blocking-recv exceeded
        ``threshold_s`` — the leader-phase straggler signal for the
        hybrid backend, where a wire rank IS a whole host.  Original
        numbering when this comm knows its membership (ElasticComm),
        else current ranks.  Hub only (spokes see no per-peer waits);
        attribution is head-of-line: the hub drains peers in rank
        order, so a slow early peer can mask a slow later one for a
        round — conviction needs tpu_hybrid_slow_rounds consecutive
        marks anyway."""
        if self.rank != 0 or threshold_s <= 0:
            return []
        membership = getattr(self, "membership", None)
        out = []
        for i, dt in self._peer_waits.items():
            if dt > threshold_s:
                out.append(int(membership[i]) if membership else i)
        return sorted(out)

    def take_peer_waits(self) -> Dict[int, float]:
        """Per-peer MAX blocking-recv seconds since the last call, keyed
        by ORIGINAL rank when membership is known (ElasticComm), else by
        current rank — then reset.  The federation hub reads this once
        per round to charge straggler wait in the round ledger; unlike
        slow_hosts it reports the worst wait of the whole round, not
        just the last allgather's.  Hub only (spokes see no waits)."""
        waits, self._peer_waits_max = self._peer_waits_max, {}
        membership = getattr(self, "membership", None)
        return {(int(membership[i]) if membership else i): dt
                for i, dt in waits.items()}

    # -- span-trace correlation ----------------------------------------
    def _publish_trace_identity(self) -> None:
        """Hand the process tracer this rank's comm coordinates: session
        id for collective-id derivation, clock offset for trace_merge's
        cross-rank time alignment.  No-op when tracing is off."""
        tr = obs_tracing.get_tracer()
        if not tr.enabled:
            return
        tr.set_metadata(comm_session=self._session.hex(),
                        comm_rank=self.rank, comm_world=self.world)
        tr.set_clock_offset(self._clock_offset_s, self._clock_rtt_s)

    def _collective_id(self) -> str:
        """Deterministic 32-hex id for the NEXT collective: every rank
        hashes (session, seq) and all ranks issue collectives in the
        same order, so matching allgather spans across ranks share it."""
        self._seq += 1
        return hashlib.md5(
            self._session + struct.pack("!q", self._seq)).hexdigest()

    # LocalComm-compatible surface -------------------------------------
    def allgather_fn(self, rank: int):
        assert rank == self.rank
        return self.allgather

    def allgather(self, payload: dict) -> List[dict]:
        self._m_allgather.inc()
        tr = obs_tracing.get_tracer()
        if not tr.enabled:
            if self.world == 1:
                return [payload]
            return self._allgather_impl(payload, None, _ZERO_TRACE, 0, "")
        cid = self._collective_id()
        with tr.span("comm/allgather", "comm",
                     {"trace_id": cid, "seq": self._seq,
                      "world": self.world}) as sp:
            if self.world == 1:
                return [payload]
            return self._allgather_impl(payload, tr, bytes.fromhex(cid),
                                        sp.span_id, cid)

    def _allgather_impl(self, payload: dict, tr, trace_id: bytes,
                        span_id: int, cid: str) -> List[dict]:
        if self.rank == 0:
            out: List[Optional[dict]] = [None] * self.world
            out[0] = payload
            waits: Dict[int, float] = {}
            for i, conn in enumerate(self._peers, start=1):
                with _maybe_span(tr, "comm/wait", peer=i, trace_id=cid):
                    t0 = time.monotonic()
                    got = self._with_retry(
                        "allgather", i, lambda c=conn: self._recv_counted(c))
                    waits[i] = time.monotonic() - t0
                out[i] = None if got is _DROPPED else got
            self._peer_waits = waits
            for i, dt in waits.items():
                if dt > self._peer_waits_max.get(i, 0.0):
                    self._peer_waits_max[i] = dt
            blob = _encode(out)
            for i, conn in enumerate(self._peers, start=1):
                with _maybe_span(tr, "comm/send", peer=i, trace_id=cid,
                                 nbytes=len(blob)):
                    sent = self._with_retry(
                        "send", i,
                        lambda c=conn: _send_blob(c, blob, trace_id, span_id,
                                                  self.generation))
                if sent is not _DROPPED:
                    self._m_sent.inc(len(blob) + _FRAME_OVERHEAD)
            return out  # type: ignore[return-value]
        with _maybe_span(tr, "comm/send", peer=0, trace_id=cid):
            self._with_retry(
                "send", 0, lambda: self._send_counted(
                    self._peers[0], payload, trace_id, span_id))
        with _maybe_span(tr, "comm/wait", peer=0, trace_id=cid):
            got = self._with_retry(
                "allgather", 0, lambda: self._recv_counted(self._peers[0]))
        return None if got is _DROPPED else got

    # counted wire helpers: every frame is 8-byte length prefix +
    # 33-byte trace/generation header + blob, and blocking-recv time IS
    # the rank-skew sync wait at this seam
    def _send_counted(self, sock: socket.socket, obj,
                      trace_id: bytes = None, span_id: int = 0) -> None:
        blob = _encode(obj)
        _send_blob(sock, blob, trace_id if trace_id is not None
                   else _ZERO_TRACE, span_id, self.generation)
        self._m_sent.inc(len(blob) + _FRAME_OVERHEAD)

    def _recv_counted(self, sock: socket.socket):
        t0 = time.monotonic()
        blob, peer_trace, peer_span, peer_gen, kind = _recv_frame(sock)
        self._m_wait.inc(time.monotonic() - t0)
        self._m_recv.inc(len(blob) + _FRAME_OVERHEAD)
        if kind == FRAME_POISON:
            # bounded-time failure propagation: a peer's control plane
            # says the membership changed — abort this collective NOW
            # instead of waiting out op timeouts against dead sockets
            info = json.loads(blob.decode("utf-8"))
            dead = info.get("dead", [])
            me = getattr(self, "orig_rank", self.rank)
            raise WorldChangedError(
                "poison frame received", dead_ranks=dead,
                generation=info.get("generation", peer_gen),
                fenced=me in dead)
        if peer_gen != self.generation:
            # generation fencing: traffic from a rank still living in a
            # previous (or future) incarnation of the world must never
            # be mistaken for this one's payloads
            raise WorldChangedError(
                "frame from generation %d rejected" % peer_gen,
                generation=self.generation)
        if peer_span:
            # mark the arrival with the SENDER's ids so the merged
            # timeline can connect this rank's wait to the peer's send
            obs_tracing.instant("comm/recv", "comm",
                                trace_id=peer_trace.hex(),
                                peer_span=peer_span, nbytes=len(blob))
        return json.loads(blob.decode("utf-8"))

    def close(self) -> None:
        if self._heartbeat is not None:
            self._heartbeat.stop()
            self._heartbeat = None
        for s in self._peers:
            try:
                s.close()
            except OSError:
                pass
        self._peers = []
        self._peer_ranks = []


class FormationPending(ConnectionError):
    """A JOIN knocked on a hub that is MID-INCARNATION (scale-up mode):
    the hub recorded the petition and will admit the knocker at the
    next formation epoch.  Deliberately a ConnectionError subclass so
    callers that don't know about scale-up still treat it as a retryable
    formation failure — but the elastic supervisor catches it FIRST and
    retries without convicting anyone (the hub is alive and answered).

    ``woken=True`` means the petitioner was parked on the hub's
    formation socket and the hub pushed the epoch announcement down the
    parked connection: the join window is opening NOW, so the
    supervisor should re-knock immediately instead of sleeping out its
    poll cadence."""

    def __init__(self, msg: str, woken: bool = False):
        super().__init__(msg)
        self.woken = bool(woken)


class ElasticComm(SocketComm):
    """A SocketComm that survives rank death: generation-fenced world
    formation, an active ping/pong control channel, and poison-frame
    failure propagation.  resilience.elastic.ElasticSupervisor re-forms
    one of these per world incarnation.

    Under the hybrid collective backend (parallel/hybrid.py) a wire
    rank is a whole HOST: conviction of a host's leader fences every
    device behind it (the local mesh has no other path to the world),
    quorum (``min_world``) therefore counts hosts, and ``slow_hosts``
    surfaces the leader-phase straggler signal rounds before the
    heartbeat would convict — see docs/Elasticity.md (host fencing).

    Formation runs on ONE port per original rank (its machine-list
    entry + port_offset).  The hub is the lowest rank this process
    believes alive; spokes dial every lower-ranked candidate in a
    round-robin sweep until one accepts (a dead candidate refuses or
    times out, so the sweep converges on the real hub).  Each spoke
    sends a JSON JOIN on the connection that then becomes its data
    plane, the hub answers with ASSIGN carrying the membership (original
    ranks, hub first — the hub anchors rank 0 of every incarnation),
    the generation, the comm session and the NTP-style clock pair; a
    second connection per spoke becomes the control channel.  Initial
    formation (generation 0) demands the full expected world; a
    re-formation waits ``rejoin_window_s`` for restarted ranks to come
    back (they adopt the hub's generation), then proceeds with whoever
    joined — so a killed rank costs one rejoin window, never a hang.

    After formation the hub's liveness monitor (resilience.comm
    Heartbeat with consecutive-miss suspicion) PINGs every control
    channel each ``heartbeat_s``; a control-channel EOF (process death)
    or ``suspect_s`` of silence (hang, partition) convicts the rank.
    Conviction FENCES it: ``_world_changed`` is set so in-flight
    collectives abort with WorldChangedError instead of retrying, a
    POISON frame goes to every surviving spoke, and the fenced rank's
    sockets are shut down so no thread blocked in recv waits past the
    suspicion timeout.  Spokes mirror the hub: their control thread
    answers PINGs, treats POISON as world change and control-channel
    EOF as hub death.  Fencing is one-way — a convicted rank that
    wakes up finds its generation rejected and must rejoin at the next
    re-formation window.

    Scale-UP (``scale_up=True`` / ``tpu_elastic_scale_up``): the hub
    keeps the formation socket LISTENING for the whole incarnation; a
    fenced or fresh rank that knocks mid-run gets ``wait`` (its
    petition is recorded in ``pending_joiners()``) instead of a
    rejection, and ``announce_epoch(readmit)`` — POISON's deliberate
    twin, generation-stamped the same way — tears the world down with
    ``WorldChangedError(epoch=True)`` so the supervisor re-forms one
    generation up with the knockers admitted through the normal JOIN
    window.  Today's shrink-only elasticity becomes shrink-and-grow.

    Split-brain caveat (documented, not solved — CAP is undefeated): a
    spoke whose alive-view is stale keeps sweeping candidates until
    ``timeout_s`` and then fails formation rather than electing a
    second hub; a restarted rank that believes it is the hub will wait
    out its own formation window and abort rather than hijack a world
    it cannot see.
    """

    def __init__(self, orig_rank: int, machines: List[str],
                 generation: int = 0, alive=None,
                 timeout_s: float = 30.0, port_offset: int = 1,
                 rejoin_window_s: float = 3.0, min_world: int = 1,
                 heartbeat_s: float = 0.2, suspect_s: float = 1.0,
                 retry: Optional[RetryPolicy] = None,
                 op_timeout_s: float = 0.0,
                 injector: Optional[FaultInjector] = None,
                 scale_up: bool = False,
                 petition_poll_s: float = 2.0):
        self.orig_rank = int(orig_rank)
        self.machines = list(machines)
        self.rejoin_window_s = max(float(rejoin_window_s), 0.05)
        self.min_world = max(int(min_world), 1)
        self.scale_up = bool(scale_up)
        self.petition_poll_s = max(float(petition_poll_s), 0.0)
        self._hb_interval = max(float(heartbeat_s), 1e-3)
        self._suspect_s = max(float(suspect_s), self._hb_interval)
        # scale-up: the hub keeps its formation socket listening for the
        # whole incarnation so fenced/fresh hosts can KNOCK mid-run; the
        # heartbeat probe drains the knocks into _pending_joins
        self._join_srv: Optional[socket.socket] = None
        self._pending_joins: Dict[int, float] = {}
        # scale-up hub: petition connections PARKED open (orig rank ->
        # socket) so announce_epoch can wake the petitioner the moment
        # the join window opens instead of waiting out its poll cadence
        self._parked_petitions: Dict[int, socket.socket] = {}
        self._ctrl: Dict[int, dict] = {}      # hub: orig -> conn state
        self._ctrl_sock: Optional[socket.socket] = None   # spoke: to hub
        self._ctrl_thread: Optional[threading.Thread] = None
        self._ctrl_stop = threading.Event()
        self._fence_lock = threading.Lock()
        self._fenced_origs: set = set()
        alive_set = {int(a) for a in (alive if alive is not None
                                      else range(len(self.machines)))}
        alive_set.add(self.orig_rank)
        self._alive = sorted(alive_set)
        if self.orig_rank == self._alive[0]:
            formed = self._form_hub(int(generation), timeout_s, port_offset)
        else:
            formed = self._form_spoke(int(generation), timeout_s, port_offset)
        membership: List[int] = formed["membership"]
        new_rank = membership.index(self.orig_rank)
        self._init_state(new_rank, len(membership), timeout_s, retry,
                         op_timeout_s, injector, formed["generation"])
        self._session = formed["session"]
        self._clock_offset_s, self._clock_rtt_s = formed.get("clock",
                                                             (0.0, 0.0))
        self.membership = list(membership)
        self._publish_trace_identity()
        if self.world > 1:
            if new_rank == 0:
                self._peers = [formed["data"][membership[i]]
                               for i in range(1, self.world)]
                self._peer_ranks = list(range(1, self.world))
                now = time.monotonic()
                self._ctrl = {o: {"sock": formed["ctrl"][o], "last": now,
                                  "eof": False}
                              for o in membership[1:]}
            else:
                self._peers = [formed["data"]]
                self._peer_ranks = [0]
                self._ctrl_sock = formed["ctrl"]
            for s in self._peers:
                s.settimeout(self.op_timeout)
            self._start_control_plane()
        log.info("elastic world formed: generation=%d membership=%s "
                 "(orig rank %d -> %d/%d)", self.generation,
                 self.membership, self.orig_rank, self.rank, self.world)

    @classmethod
    def from_config(cls, orig_rank: int, machines: List[str], config,
                    generation: int = 0, alive=None,
                    **kwargs) -> "ElasticComm":
        """Construct with the elasticity knobs resolved from a Config
        (tpu_elastic_heartbeat_ms / tpu_elastic_suspect_ms /
        tpu_elastic_rejoin_s / tpu_elastic_min_world on top of the
        tpu_comm_* resilience set)."""
        kwargs.setdefault("retry", RetryPolicy.from_config(config))
        kwargs.setdefault("op_timeout_s",
                          float(getattr(config, "tpu_comm_op_timeout_s", 0.0)))
        kwargs.setdefault("heartbeat_s", float(
            getattr(config, "tpu_elastic_heartbeat_ms", 200.0)) / 1e3)
        kwargs.setdefault("suspect_s", float(
            getattr(config, "tpu_elastic_suspect_ms", 1000.0)) / 1e3)
        kwargs.setdefault("rejoin_window_s",
                          float(getattr(config, "tpu_elastic_rejoin_s", 3.0)))
        kwargs.setdefault("min_world",
                          int(getattr(config, "tpu_elastic_min_world", 1)))
        kwargs.setdefault("scale_up", bool(
            getattr(config, "tpu_elastic_scale_up", False)))
        kwargs.setdefault("petition_poll_s", float(
            getattr(config, "tpu_elastic_petition_poll_s", 2.0)))
        return cls(orig_rank, machines, generation=generation, alive=alive,
                   **kwargs)

    # -- formation ------------------------------------------------------
    def _addr(self, orig: int, port_offset: int) -> Tuple[str, int]:
        host, port = self.machines[orig].rsplit(":", 1)
        return host, int(port) + port_offset

    def _form_hub(self, gen: int, timeout_s: float,
                  port_offset: int) -> dict:
        host, port = self._addr(self.orig_rank, port_offset)
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)  # tpulint: ok=socket-no-with
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        try:
            srv.bind((host, port))
        except OSError as e:
            if not (e.errno == errno.EADDRNOTAVAIL
                    or isinstance(e, socket.gaierror)):
                srv.close()
                raise
            log.warning("elastic hub cannot bind %s:%d (%s) — binding "
                        "all interfaces", host, port, e)
            srv.bind(("", port))
        srv.listen(max(len(self.machines) * 2, 2))
        expected = set(self._alive) - {self.orig_rank}
        everyone = set(range(len(self.machines))) - {self.orig_rank}
        # initial formation demands the full expected world and may wait
        # the whole timeout; a re-formation waits only the rejoin window,
        # leaving early when every original rank is back
        window = timeout_s if gen == 0 else self.rejoin_window_s
        deadline = time.monotonic() + window
        # under scale-up, world GROWTH is serialized at formation epoch
        # boundaries: a re-formation admits only the ranks the supervisor
        # already believes alive, and any other knocker (convicted,
        # restarted, fresh) is parked as a rejoin petition for the next
        # epoch.  Without scale-up a re-formation window welcomes every
        # original rank back (the restart-rejoin path).
        want = expected if (gen == 0 or self.scale_up) else everyone
        joins: Dict[int, tuple] = {}
        try:
            while True:
                have = set(joins)
                if have >= want:
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                srv.settimeout(min(remaining, 0.25))
                try:
                    conn, _addr_ = srv.accept()
                except socket.timeout:
                    continue
                conn.settimeout(timeout_s)
                try:
                    hello, _hg = _recv_formation_msg(conn)
                except (OSError, ConnectionError, ValueError):
                    conn.close()
                    continue
                r = int(hello.get("orig_rank", -1))
                if (hello.get("type") != "join"
                        or not 0 <= r < len(self.machines)):
                    conn.close()
                    continue
                if self.scale_up and gen > 0 and r not in expected:
                    # epoch-serialized growth: park the petition and keep
                    # forming the expected world (see `want` above)
                    with self._fence_lock:
                        self._pending_joins[r] = time.monotonic()
                    try:
                        _send_msg(conn, {"type": "wait",
                                         "generation": gen}, gen)
                    except OSError:
                        pass
                    conn.close()
                    continue
                if r in joins:
                    # a restarted process supersedes its stale connection
                    joins[r][0].close()
                joins[r] = (conn, time.time())
            if gen == 0 and not set(joins) >= expected:
                missing = sorted(expected - set(joins))
                for conn, _t1 in joins.values():
                    conn.close()
                srv.close()
                raise ConnectionError(
                    "elastic formation timed out after %.1fs: rank(s) %s "
                    "never joined" % (timeout_s, missing))
            # hub first: the hub anchors rank 0 of every incarnation, so
            # the hub-and-spoke data plane never needs re-wiring
            membership = [self.orig_rank] + sorted(joins)
            if len(membership) < self.min_world:
                # under-join is a TRANSIENT verdict — the absentees may
                # just be late (still draining their own failed
                # collectives).  ConnectionError, not WorldChangedError:
                # nobody gets convicted, the supervisor burns one reform
                # and retries, and the late ranks join the next attempt
                for conn, _t1 in joins.values():
                    conn.close()
                srv.close()
                raise ConnectionError(
                    "cannot re-form generation %d: %d rank(s) joined "
                    "within the %.1fs rejoin window but min_world=%d"
                    % (gen, len(membership), window, self.min_world))
            session = uuid.uuid4().bytes
            for r, (conn, t1) in joins.items():
                _send_msg(conn, {"type": "assign", "membership": membership,
                                 "generation": gen,
                                 "session": session.hex(),
                                 "t1": t1, "t2": time.time()}, gen)
            # second connection per member: the control channel
            ctrl: Dict[int, socket.socket] = {}
            cdl = time.monotonic() + timeout_s
            while set(ctrl) < set(joins):
                remaining = cdl - time.monotonic()
                if remaining <= 0:
                    for c in ctrl.values():
                        c.close()
                    for conn, _t1 in joins.values():
                        conn.close()
                    srv.close()
                    raise ConnectionError(
                        "control channel(s) missing from rank(s) %s"
                        % sorted(set(joins) - set(ctrl)))
                srv.settimeout(min(remaining, 0.25))
                try:
                    conn, _addr_ = srv.accept()
                except socket.timeout:
                    continue
                conn.settimeout(timeout_s)
                try:
                    hello, _hg = _recv_formation_msg(conn)
                except (OSError, ConnectionError, ValueError):
                    conn.close()
                    continue
                if hello.get("type") == "join":
                    # a rank that missed the rejoin window: under
                    # scale-up it becomes a petition for the next
                    # formation epoch; otherwise reject it explicitly
                    # so it fails fast instead of timing out
                    jr = int(hello.get("orig_rank", -1))
                    if self.scale_up and 0 <= jr < len(self.machines):
                        with self._fence_lock:
                            self._pending_joins[jr] = time.monotonic()
                        reply = {"type": "wait", "generation": gen}
                    else:
                        reply = {"type": "reject", "generation": gen}
                    try:
                        _send_msg(conn, reply, gen)
                    except OSError:
                        pass
                    conn.close()
                    continue
                r = int(hello.get("orig_rank", -1))
                if hello.get("type") != "ctrl" or r not in joins:
                    conn.close()
                    continue
                ctrl[r] = conn
            if self.scale_up:
                # keep listening for the whole incarnation: late JOINs
                # become rejoin petitions (_drain_join_knocks) instead
                # of rejections, and the next formation epoch admits
                # them.  close() owns the socket from here.
                self._join_srv = srv
        finally:
            if self._join_srv is not srv:
                srv.close()
        return {"membership": membership, "generation": gen,
                "session": session,
                "data": {r: conn for r, (conn, _t1) in joins.items()},
                "ctrl": ctrl}

    def _form_spoke(self, gen: int, timeout_s: float,
                    port_offset: int) -> dict:
        candidates = [c for c in self._alive if c < self.orig_rank]
        deadline = time.monotonic() + timeout_s
        while True:
            conn = hub = None
            # round-robin sweep: a dead candidate refuses instantly (or
            # times out in 1 s); the real hub is the first that accepts
            while conn is None:
                for c in candidates:
                    if time.monotonic() >= deadline:
                        break
                    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)  # tpulint: ok=socket-no-with
                    s.settimeout(1.0)
                    try:
                        s.connect(self._addr(c, port_offset))
                        conn, hub = s, c
                        break
                    except OSError:
                        s.close()
                if conn is None:
                    if time.monotonic() >= deadline:
                        raise ConnectionError(
                            "no elastic hub among candidate rank(s) %s "
                            "within %.1fs" % (candidates, timeout_s))
                    time.sleep(0.1)
            conn.settimeout(timeout_s + self.rejoin_window_s)
            wall_t0 = time.time()
            try:
                _send_msg(conn, {"type": "join",
                                 "orig_rank": self.orig_rank,
                                 "generation": gen, "wall": wall_t0}, gen)
                # the generation is still being negotiated here; the
                # hub's JSON assign payload carries it, formation
                # adopts it (stray control frames are dropped by kind)
                assign, _ag = _recv_formation_msg(conn)
                break
            except (OSError, ConnectionError, ValueError) as e:
                # a drop mid-exchange is usually the hub's PREVIOUS
                # incarnation tearing down its listener right as we
                # knocked (the new window rebinds the same port
                # moments later) — a transient, not a conviction:
                # keep sweeping until the deadline says otherwise
                conn.close()
                if time.monotonic() >= deadline:
                    raise ConnectionError(
                        "hub candidate %d dropped the formation "
                        "exchange: %s" % (hub, e))
                time.sleep(0.1)
        wall_t3 = time.time()
        if assign.get("type") == "reject":
            conn.close()
            raise WorldChangedError(
                "rejoin window missed: the world re-formed without "
                "this rank", dead_ranks=[self.orig_rank],
                generation=int(assign.get("generation", gen)), fenced=True)
        if assign.get("type") == "wait":
            # the hub is mid-incarnation with scale-up on: our petition
            # is recorded and the hub PARKS this connection.  Block in
            # recv (up to petition_poll_s) for the epoch wake the hub
            # pushes from announce_epoch — when it lands, the join
            # window is opening and the supervisor should re-knock
            # immediately (woken=True) instead of sleeping first.
            woken = False
            poll_s = getattr(self, "petition_poll_s", 2.0)
            if poll_s > 0:
                try:
                    conn.settimeout(poll_s)
                    wake, _wg = _recv_formation_msg(conn)
                    woken = wake.get("type") == "epoch"
                except (OSError, ConnectionError, ValueError):
                    pass
            conn.close()
            raise FormationPending(
                "hub %d is mid-incarnation at generation %s; rejoin "
                "petition recorded, %s"
                % (hub, assign.get("generation", "?"),
                   "formation epoch announced — re-knocking now" if woken
                   else "awaiting a formation epoch"), woken=woken)
        if assign.get("type") != "assign":
            conn.close()
            raise ConnectionError("unexpected formation reply %r"
                                  % assign.get("type"))
        hub_gen = int(assign["generation"])
        if hub_gen < gen:
            # a fenced ex-hub that woke up mid-re-formation still
            # answers on its old port at its old generation; adopting
            # its stale world would fork the membership.  Refuse and
            # keep sweeping at the next supervisor attempt — the hub's
            # ASSIGN is only authoritative FORWARD in time.
            conn.close()
            raise ConnectionError(
                "stale hub: assign at generation %d but this rank is "
                "forming generation %d" % (hub_gen, gen))
        membership = [int(r) for r in assign["membership"]]
        gen = hub_gen
        t1, t2 = float(assign["t1"]), float(assign["t2"])
        clock = (((t1 - wall_t0) + (t2 - wall_t3)) / 2.0,
                 (wall_t3 - wall_t0) - (t2 - t1))
        ctrl = socket.socket(socket.AF_INET, socket.SOCK_STREAM)  # tpulint: ok=socket-no-with
        ctrl.settimeout(timeout_s)
        try:
            ctrl.connect(self._addr(hub, port_offset))
            _send_msg(ctrl, {"type": "ctrl",
                             "orig_rank": self.orig_rank}, gen)
        except OSError:
            ctrl.close()
            conn.close()
            raise
        return {"membership": membership, "generation": gen,
                "session": bytes.fromhex(assign["session"]),
                "data": conn, "ctrl": ctrl, "clock": clock}

    # -- control plane --------------------------------------------------
    def _start_control_plane(self) -> None:
        if self.rank == 0:
            from ..obs import default_registry
            suspect_after = max(
                1, int(math.ceil(self._suspect_s / self._hb_interval)))
            self._heartbeat = Heartbeat(
                self._ctrl_probe, self._hb_interval, rank=self.rank,
                world=self.world, registry=default_registry(),
                suspect_after=suspect_after,
                on_change=self._fence).start()
        else:
            self._ctrl_thread = threading.Thread(
                target=self._ctrl_loop, name="lgbm-elastic-ctrl",
                daemon=True)
            self._ctrl_thread.start()

    def _drain_join_knocks(self) -> None:
        """Scale-up only (hub): accept any connection waiting on the
        formation socket, record a JOIN hello as a rejoin petition and
        answer ``wait`` — then PARK the connection open (keyed by
        original rank, a re-knock supersedes its predecessor) so
        ``announce_epoch`` can push the epoch announcement straight to
        the petitioner, which is blocked in recv waiting for exactly
        that wake.  Non-JOIN garbage is dropped; nothing here blocks
        the probe for more than the 1 s hello timeout per knock."""
        srv = self._join_srv
        if srv is None:
            return
        while True:
            try:
                readable, _, _ = select.select([srv], [], [], 0)
            except (OSError, ValueError):
                return
            if not readable:
                return
            try:
                conn, _addr_ = srv.accept()
            except OSError:
                return
            parked = False
            try:
                conn.settimeout(1.0)
                hello, _hg = _recv_formation_msg(conn)
                r = int(hello.get("orig_rank", -1))
                if (hello.get("type") == "join"
                        and 0 <= r < len(self.machines)):
                    first = r not in self._pending_joins
                    with self._fence_lock:
                        self._pending_joins[r] = time.monotonic()
                        stale = self._parked_petitions.pop(r, None)
                    if stale is not None:
                        stale.close()
                    if first:
                        log.info("elastic: rank %d is knocking to rejoin "
                                 "(generation %d); pending a formation "
                                 "epoch", r, self.generation)
                    _send_msg(conn, {"type": "wait",
                                     "generation": self.generation},
                              self.generation)
                    with self._fence_lock:
                        self._parked_petitions[r] = conn
                    parked = True
            except (OSError, ConnectionError, ValueError):
                pass
            finally:
                if not parked:
                    conn.close()

    def _ctrl_probe(self) -> List[int]:
        """Hub liveness probe (one Heartbeat round): PING every control
        channel, drain PONGs, report ranks (ORIGINAL numbering) that are
        closed or silent past the staleness bound.  Under scale-up the
        same cadence also drains rejoin knocks off the formation
        socket."""
        self._drain_join_knocks()
        now = time.monotonic()
        for orig, st in self._ctrl.items():
            if st["eof"]:
                continue
            try:
                _send_msg(st["sock"], {}, self.generation, FRAME_PING)
            except OSError:
                st["eof"] = True
        socks = {st["sock"]: st for st in self._ctrl.values()
                 if not st["eof"]}
        while socks:
            try:
                readable, _, _ = select.select(list(socks), [], [], 0)
            except (OSError, ValueError):
                break
            if not readable:
                break
            for s in readable:
                st = socks.pop(s)
                try:
                    s.settimeout(1.0)
                    _blob, _tr, _sp, g, kind = _recv_frame(s)
                except (OSError, ConnectionError, ValueError):
                    st["eof"] = True
                    continue
                if kind == FRAME_PONG and g == self.generation:
                    st["last"] = now
        stale_after = max(1.5 * self._hb_interval, 0.05)
        unresponsive = []
        for orig, st in self._ctrl.items():
            if orig in self._fenced_origs:
                continue
            if st["eof"] or (now - st["last"]) > stale_after:
                unresponsive.append(orig)
        return unresponsive

    def _fence(self, dead_origs: set) -> None:
        """Heartbeat conviction-set transition: fence newly dead ranks.
        One-way — a convicted rank that wakes up later finds its
        generation rejected and must rejoin at the next re-formation."""
        with self._fence_lock:
            fresh = {int(r) for r in dead_origs} - self._fenced_origs
            if not fresh:
                return
            self._fenced_origs |= fresh
            all_dead = sorted(self._fenced_origs)
        log.warning("elastic: fencing rank(s) %s at generation %d",
                    sorted(fresh), self.generation)
        # 1. our own collectives must stop retrying against the fence
        with self._fence_lock:
            self._world_changed = WorldChangedError(
                "peer rank(s) fenced by liveness monitor",
                dead_ranks=all_dead, generation=self.generation)
        # 2. poison every spoke so nobody blocks past this — INCLUDING
        # the freshly fenced ranks: the verdict frame is how a demoted-
        # but-alive host learns it was fenced (fenced=True in its
        # WorldChangedError) rather than mistaking the closed control
        # channel for hub death and convicting the hub right back.  A
        # genuinely dead rank just fails the send.
        poison = _encode({"dead": all_dead, "generation": self.generation})
        for orig, st in self._ctrl.items():
            if st["eof"] or (orig in all_dead and orig not in fresh):
                continue
            try:
                _send_blob(st["sock"], poison,
                           generation=self.generation, kind=FRAME_POISON)
            except OSError:
                st["eof"] = True
        # 3. shut the fenced ranks' sockets so any thread blocked in
        # recv on them wakes immediately
        for orig in fresh:
            st = self._ctrl.get(orig)
            if st is not None:
                _shutdown(st["sock"])
            if orig in self.membership:
                idx = self.membership.index(orig)
                if 1 <= idx <= len(self._peers):
                    _shutdown(self._peers[idx - 1])

    def announce_epoch(self, readmit=()) -> None:
        """Hub only: declare a FORMATION EPOCH — the deliberate,
        scale-UP twin of ``_fence``.  Nobody is convicted; the world
        tears down so the supervisor can re-form it one generation up
        with the ``readmit`` ranks back in the alive view (they are
        knocking on the formation socket and will join the new window).
        Generation-stamped like POISON: an EPOCH frame from a stale
        incarnation is ignored by the formation transport's kind/
        generation fencing."""
        readmit = sorted({int(r) for r in readmit})
        with self._fence_lock:
            if self._world_changed is not None:
                return
            self._world_changed = WorldChangedError(
                "formation epoch: re-forming to admit rank(s) %s"
                % readmit, dead_ranks=[], generation=self.generation,
                epoch=True, readmit=readmit)
        log.info("elastic: formation epoch at generation %d "
                 "(readmit=%s)", self.generation, readmit)
        payload = _encode({"readmit": readmit,
                           "generation": self.generation})
        for orig, st in self._ctrl.items():
            if st["eof"]:
                continue
            try:
                _send_blob(st["sock"], payload,
                           generation=self.generation, kind=FRAME_EPOCH)
            except OSError:
                st["eof"] = True
        # wake the parked petitioners: each is blocked in recv on its
        # petition connection (petition_poll_s) and will re-knock the
        # moment this lands — the rejoin latency is bounded by the
        # epoch, not the petitioner's poll cadence.  A petitioner whose
        # poll already expired just fails the send; it re-knocks on its
        # own schedule and the next window admits it anyway.
        with self._fence_lock:
            parked = dict(self._parked_petitions)
            self._parked_petitions.clear()
        for r, conn in parked.items():
            try:
                _send_msg(conn, {"type": "epoch", "readmit": readmit,
                                 "generation": self.generation},
                          self.generation)
            except OSError:
                pass
            conn.close()

    def pending_joiners(self) -> List[int]:
        """Original ranks whose rejoin petitions the hub has recorded
        this incarnation (scale-up) and that are not already members."""
        with self._fence_lock:
            return sorted(r for r in self._pending_joins
                          if r not in self.membership)

    def _ctrl_loop(self) -> None:
        """Spoke control thread: answer hub PINGs, treat POISON as a
        world change and control-channel EOF as hub death; either way
        shut our own data socket so the main thread never blocks past
        the event."""
        sock = self._ctrl_sock
        hub_orig = self.membership[0]
        while not self._ctrl_stop.is_set():
            try:
                readable, _, _ = select.select([sock], [], [], 0.25)
            except (OSError, ValueError):
                break
            if not readable:
                continue
            try:
                sock.settimeout(5.0)
                # the control channel is generation-agnostic by
                # design: PONG echoes our generation for the prober to
                # judge, and a POISON verdict must land regardless of
                # the frame's age
                # tpulint: disable-next-line=wire-unfenced-recv
                blob, _tr, _sp, g, kind = _recv_frame(sock)
            except (OSError, ConnectionError, ValueError):
                if self._ctrl_stop.is_set():
                    break
                with self._fence_lock:
                    self._world_changed = WorldChangedError(
                        "control channel to hub lost",
                        dead_ranks=[hub_orig], generation=self.generation)
                for s in self._peers:
                    _shutdown(s)
                break
            if kind == FRAME_PING:
                try:
                    _send_msg(sock, {}, self.generation, FRAME_PONG)
                except OSError:
                    pass
            elif kind == FRAME_POISON:
                try:
                    info = json.loads(blob.decode("utf-8"))
                except ValueError:
                    info = {}
                dead = [int(r) for r in info.get("dead", [])]
                with self._fence_lock:
                    self._world_changed = WorldChangedError(
                        "world membership changed", dead_ranks=dead,
                        generation=int(info.get("generation", g)),
                        fenced=self.orig_rank in dead)
                for s in self._peers:
                    _shutdown(s)
                break
            elif kind == FRAME_EPOCH:
                # the POISON twin for scale-UP: nobody died — tear down
                # and let the supervisor rejoin the next formation
                try:
                    info = json.loads(blob.decode("utf-8"))
                except ValueError:
                    info = {}
                with self._fence_lock:
                    self._world_changed = WorldChangedError(
                        "formation epoch announced by hub",
                        dead_ranks=[],
                        generation=int(info.get("generation", g)),
                        epoch=True,
                        readmit=[int(r) for r in info.get("readmit", [])])
                for s in self._peers:
                    _shutdown(s)
                break

    # -- supervisor surface ---------------------------------------------
    def world_changed(self) -> Optional[WorldChangedError]:
        return self._world_changed

    def fenced_ranks(self) -> List[int]:
        """Original ranks this incarnation has fenced (hub) or been told
        are dead (spoke)."""
        wc = self._world_changed
        dead = set(self._fenced_origs)
        if wc is not None:
            dead |= set(wc.dead_ranks)
        return sorted(dead)

    def close(self) -> None:
        self._ctrl_stop.set()
        if self._join_srv is not None:
            try:
                self._join_srv.close()
            except OSError:
                pass
            self._join_srv = None  # tpulint: ok=lock-shared-write
        with self._fence_lock:
            parked = dict(self._parked_petitions)
            self._parked_petitions.clear()
        for conn in parked.values():
            try:
                conn.close()
            except OSError:
                pass
        if self._heartbeat is not None:
            self._heartbeat.stop()
            # close() runs after the heartbeat/control threads are
            # stopped+joined; teardown writes are single-threaded.
            # tpulint: disable-next-line=lock-shared-write
            self._heartbeat = None
        if self._ctrl_sock is not None:
            _shutdown(self._ctrl_sock)
        if self._ctrl_thread is not None:
            self._ctrl_thread.join(timeout=2.0)
            self._ctrl_thread = None  # tpulint: ok=lock-shared-write
        for st in self._ctrl.values():
            try:
                st["sock"].close()
            except OSError:
                pass
        self._ctrl = {}  # tpulint: ok=lock-shared-write — teardown
        if self._ctrl_sock is not None:
            try:
                self._ctrl_sock.close()
            except OSError:
                pass
            self._ctrl_sock = None  # tpulint: ok=lock-shared-write
        super().close()


def _shutdown(sock: socket.socket) -> None:
    try:
        sock.shutdown(socket.SHUT_RDWR)
    except OSError:
        pass


def _json_default(o):
    # mapper state can carry numpy scalars/arrays (min/max, bounds)
    if hasattr(o, "item") and not hasattr(o, "__len__"):
        return o.item()
    if hasattr(o, "tolist"):
        return o.tolist()
    raise TypeError("SocketComm payloads must be JSON-serializable, "
                    "got %r" % type(o))


def _encode(obj) -> bytes:
    # allow_nan stays on: bin-mapper min/max can legitimately be +-inf,
    # and Python's json round-trips Infinity/NaN literals
    return json.dumps(obj, default=_json_default).encode("utf-8")


def _maybe_span(tr, name: str, **args):
    """A comm-leg span when the tracer rode in, else a free nullcontext."""
    if tr is None:
        return nullcontext()
    return tr.span(name, "comm", args)


def _send_blob(sock: socket.socket, blob: bytes,
               trace_id: bytes = None, span_id: int = 0,
               generation: int = 0, kind: int = 0) -> None:
    sock.sendall(struct.pack("!q", len(blob))
                 + (trace_id if trace_id is not None else _ZERO_TRACE)
                 + struct.pack("!qqB", span_id, generation, kind) + blob)


def _send_msg(sock: socket.socket, obj, generation: int = 0,
              kind: int = 0) -> None:
    _send_blob(sock, _encode(obj), generation=generation, kind=kind)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    # chunked reads: allocation grows with data actually received, so a
    # garbage/hostile length prefix cannot force an up-front multi-GB
    # buffer; bytearray keeps the append O(n)
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(n - len(buf), 1 << 20))
        if not chunk:
            raise ConnectionError("peer closed during receive")
        buf += chunk
    return bytes(buf)


def _recv_frame(sock: socket.socket):
    """-> (blob, sender trace-id bytes, sender span id, generation,
    frame kind)."""
    (n,) = struct.unpack("!q", _recv_exact(sock, 8))
    if n < 0 or n > _MAX_MSG:
        raise ConnectionError(
            "refusing %d-byte frame (cap %d): either a corrupt/hostile "
            "length prefix, or a dataset so wide its mapper exchange "
            "exceeds the cap — raise distributed._MAX_MSG if the latter"
            % (n, _MAX_MSG))
    hdr = _recv_exact(sock, _FRAME_OVERHEAD - 8)
    span_id, generation, kind = struct.unpack("!qqB", hdr[16:33])
    return _recv_exact(sock, n), hdr[:16], span_id, generation, kind


def _recv_msg(sock: socket.socket):
    # pre-formation JSON transport; generations are fenced in the
    # payloads by the callers
    # tpulint: disable-next-line=wire-unfenced-recv
    return json.loads(_recv_frame(sock)[0].decode("utf-8"))


_FRAME_NAMES = {0: "data", 1: "poison", 2: "ping", 3: "pong", 4: "epoch"}


def _recv_formation_msg(sock: socket.socket,
                        max_skip: int = 8) -> Tuple[dict, int]:
    """Formation-window transport: the next DATA frame as JSON, DROPPING
    stray control frames.  A fenced host's control plane can still be
    firing at its old generation while the survivors re-form — a stale
    POISON (or a late PING/PONG) landing on a socket that is about to
    carry a JOIN or ASSIGN must be skipped, not misparsed as the
    formation message nor allowed to kill the connection a legitimate
    frame follows on.  Returns (msg, frame generation) so the caller
    can fence the payload's generation itself."""
    for _ in range(max_skip):
        # generation negotiation happens in the formation payloads; the
        # kind filter here is what keeps stale control frames out, and
        # every caller settimeout()s the socket before handing it here
        # tpulint: disable-next-line=wire-unfenced-recv,wire-blocking-handler
        blob, _tr, _sp, gen, kind = _recv_frame(sock)
        if kind != FRAME_DATA:
            log.warning("formation: dropping stray %s frame from "
                        "generation %d",
                        _FRAME_NAMES.get(kind, str(kind)), gen)
            continue
        return json.loads(blob.decode("utf-8")), gen
    raise ConnectionError(
        "formation: %d consecutive non-data frames" % max_skip)


# mapper payloads are a few KB/feature and the hub broadcast carries
# every rank's shard, so size the cap for very wide datasets (~1M
# features) while still bounding what a garbage length prefix can make
# us allocate
_MAX_MSG = 8 << 30
# per-frame wire overhead (v3): 8-byte length + 16-byte trace-id +
# 8-byte span-id + 8-byte generation + 1-byte frame kind
_FRAME_OVERHEAD = 41
_ZERO_TRACE = b"\x00" * 16

# frame kinds: DATA carries an allgather payload; POISON tells the
# receiver the world membership changed (blob = {"dead": [...],
# "generation": g}); PING/PONG are the ElasticComm control-channel
# liveness probes (empty blobs); EPOCH is the scale-UP twin of POISON —
# a DELIBERATE formation boundary (blob = {"readmit": [...],
# "generation": g}): nobody died, the world tears down to re-form one
# generation up with the readmitted ranks back in
FRAME_DATA = 0
FRAME_POISON = 1
FRAME_PING = 2
FRAME_PONG = 3
FRAME_EPOCH = 4
