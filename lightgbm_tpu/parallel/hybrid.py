"""Hybrid multi-host collective: ICI mesh within a host, socket stage
between per-host leaders.

PAPER.md layer 3 describes a machine-level Network topology over
per-machine parallel learners; real TPU fleets fail at exactly that
granularity — a host and its ICI-attached devices live and die
together.  This backend composes the two existing collectives to match:

- INNER: the grow loop runs ``shard_map``'d over the host's local
  device mesh (``MeshCollective``), so per-level histograms are first
  reduced over ICI with ``jax.lax.psum`` — after which every local
  shard holds the identical host-local partial sum.
- OUTER: one ordered host callback per collective op hands that
  partial to the ``ElasticComm``/``SocketComm`` wire, where the
  per-host LEADERS allreduce across hosts; the result is returned to
  every local shard — the "broadcast back into the mesh" is the
  callback's return value, replicated because every shard receives the
  same array.

Determinism: the reduce happens in two stages (ICI sum, then wire
sum), but both stages add the SAME integer code sums the quantized
path psums (ops/quantize: integer-code/psum-before-dequantize), and
the f32 parity tests ride dyadic gradients — so hybrid training is
bitwise identical to serial exactly like the mesh and socket backends
(tests/test_hybrid_collective.py).

Leader election rides the callback stream: under ``shard_map`` the
ordered callback fires once per LOCAL shard with identical post-psum
payloads, so the FIRST arrival of each (op, epoch) is the leader and
performs the wire exchange; followers wait on the condition variable
and return the leader's cached result.  The ordering invariant this
relies on: each device issues its callbacks in program order
(``ordered=True``), and a follower can only reach op B after ITS op A
returned — which requires op A's wire exchange to have completed — so
wire exchanges are issued in program order on every host and the
``exchange_arrays`` tag rendezvous stays symmetric.

Fault domain: the wire is the per-host leader plane, so heartbeat
conviction of a leader (ElasticComm's liveness monitor) fences the
WHOLE host — its local mesh has no other connection to the world.
Re-formation quorum is counted in hosts (the ElasticComm world IS the
host set), rows re-shard host-first (``pre_partition_rows`` over the
surviving hosts) then device-second (the grower's local padding /
shard_map split), and recovery resumes from the newest checkpoint via
``resume_mode="reshard"`` — see docs/Distributed.md (hybrid topology)
and docs/Elasticity.md (host fencing).

The same holds in reverse for elastic scale-UP
(``tpu_elastic_scale_up``): a formation epoch re-forms the host set
one host LARGER, and because this collective is built fresh from
``get_process_comm()`` each incarnation — the world size is never
baked into the mesh stage — the readmitted host simply appears as one
more leader on the wire at the next generation.
"""
from __future__ import annotations

import threading
import time
from functools import partial
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import log
from .collective import (AXIS, Collective, MeshCollective, SocketAxis,
                         SocketCollective, _account, capture_traced)


class HybridAxis(SocketAxis):
    """Traced-collective handle composing mesh psum with the leader wire.

    Subclasses ``SocketAxis`` so the primitive dispatch in
    parallel/collective.py routes here unchanged; every op performs the
    ICI stage inline (``jax.lax.psum`` over the local mesh axis) before
    the ordered callback performs the cross-host stage once per host.

    ``rank``/``world`` are the HOST coordinates (the wire's view); the
    local mesh size rides ``local_world``.
    """

    def __init__(self, collective: "HybridCollective"):
        super().__init__(collective.socket)
        self.local_world = int(collective.local_world)
        self.mesh_axis = collective.mesh_axis
        self._oid = 0                  # trace-time op id (program order)
        self._cv = threading.Condition()
        self._counts: Dict[int, int] = {}   # oid -> host-callback arrivals
        self._epochs: Dict[int, int] = {}   # oid -> last published epoch
        self._results: Dict[int, np.ndarray] = {}
        self._wire_wait_s = 0.0        # cumulative leader-phase wire time

    # -- trace-time op identity -----------------------------------------
    def _next_oid(self) -> int:
        """Unique id per traced op, assigned in program order at TRACE
        time (jit traces once, so executions reuse the same ids — the
        epoch counter below distinguishes successive executions)."""
        self._oid += 1  # tpulint: ok=lock-shared-write — trace time only
        return self._oid

    # -- the deduped host callback --------------------------------------
    def _host_hybrid(self, oid: int, kind: str, op: str, arr, stack: bool):
        arr = np.asarray(arr)
        with self._cv:
            n = self._counts[oid] = self._counts.get(oid, 0) + 1
            epoch, slot = divmod(n - 1, self.local_world)
            is_leader = slot == 0
        if is_leader:
            out = self._leader_exchange(oid, epoch, kind, op, arr, stack)
        else:
            out = self._await_leader(oid, epoch, arr, stack)
        return out

    def _leader_exchange(self, oid: int, epoch: int, kind: str, op: str,
                         arr: np.ndarray, stack: bool) -> np.ndarray:
        """The leader phase: one wire collective per (op, epoch) across
        the per-host leader ranks.  Failures park on ``failure`` (XLA
        callbacks cannot raise) and degrade the payload to zeros, for
        followers too — ``check_failure`` re-raises after the program."""
        tag = "hybrid:%s:%d:%d" % (kind, oid, epoch)
        t0 = time.monotonic()
        try:
            parts = self._coll.exchange_arrays(tag, arr)
            if stack:
                out = np.stack(parts)
            else:
                out = parts[0].copy()
                for p in parts[1:]:
                    out = np.maximum(out, p) if op == "max" else out + p
                out = out.astype(arr.dtype, copy=False)
        except BaseException as exc:  # noqa: BLE001 — park, don't crash XLA
            with self._cv:
                if self.failure is None:
                    self.failure = exc
            shape = ((self.world,) + arr.shape) if stack else arr.shape
            out = np.zeros(shape, arr.dtype)
        dt = time.monotonic() - t0
        from ..obs import tracing
        if tracing.get_tracer().enabled:
            tracing.complete("comm/hybrid_%s" % kind, dt, cat="comm",
                             tag=tag, nbytes=int(arr.nbytes),
                             hosts=self.world, local=self.local_world)
        with self._cv:
            self._wire_wait_s += dt
            self._results[oid] = out
            self._epochs[oid] = epoch
            self._cv.notify_all()
        return out

    def _await_leader(self, oid: int, epoch: int, arr: np.ndarray,
                      stack: bool) -> np.ndarray:
        """Follower shards block until the leader publishes this epoch's
        result; a leader that never publishes (wire death mid-exchange)
        bounds the wait at the comm timeout and degrades to zeros."""
        deadline = time.monotonic() + max(
            float(getattr(self._coll.comm, "timeout", 30.0)), 1.0) + 5.0
        with self._cv:
            while self._epochs.get(oid, -1) < epoch:
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not self._cv.wait(
                        timeout=min(remaining, 0.25)):
                    if time.monotonic() >= deadline:
                        if self.failure is None:
                            self.failure = RuntimeError(
                                "hybrid leader callback never published "
                                "op %d epoch %d" % (oid, epoch))
                        shape = ((self.world,) + arr.shape) if stack \
                            else arr.shape
                        return np.zeros(shape, arr.dtype)
            return self._results[oid]

    def _wire(self, kind: str, op: str, x, out_shape, stack: bool):
        oid = self._next_oid()
        return self._call(partial(self._host_hybrid, oid, kind, op,
                                  stack=stack), x, out_shape)

    # -- the traced primitives ------------------------------------------
    def allreduce(self, x, op: str):
        x = (jax.lax.psum(x, self.mesh_axis) if op == "sum"
             else jax.lax.pmax(x, self.mesh_axis))
        _account("hybrid_" + op, x)
        out = jax.ShapeDtypeStruct(x.shape, x.dtype)
        return self._wire("allreduce", op, x, out, stack=False)

    def gather(self, x):
        # local concat over the mesh, then one stacked wire gather: the
        # leading dim is HOSTS, each carrying its mesh-tiled block, so
        # flattening yields global host-major/device-minor shard order —
        # the same order the rows were pre-partitioned in
        g = jax.lax.all_gather(x, self.mesh_axis, tiled=True)
        _account("hybrid_gather", g)
        out = jax.ShapeDtypeStruct((self.world,) + g.shape, g.dtype)
        return self._wire("gather", "sum", g, out, stack=True)

    def scatter_reduce(self, x, **kwargs):
        total = self.allreduce(x, "sum")
        gw = self.world * self.local_world
        per = total.shape[0] // gw
        idx = (jnp.int32(self.rank * self.local_world)
               + jax.lax.axis_index(self.mesh_axis)) * per
        return jax.lax.dynamic_slice_in_dim(total, idx, per)

    def global_index(self):
        """This shard's GLOBAL index: host-major over the wire world,
        device-minor over the local mesh."""
        return (jnp.int32(self.rank * self.local_world)
                + jax.lax.axis_index(self.mesh_axis))


class HybridCollective(Collective):
    """``Collective`` over H hosts x D local devices.

    Host-payload semantics match ``SocketCollective`` exactly — the
    interface's rank/world are the HOST coordinates, so the quantized
    global-scale agreement, ``row_layout`` and the supervisor's
    re-shard all work unchanged — while the traced side hands the
    learners the local mesh plus a ``HybridAxis``.  ``local_world``
    (D) and ``global_world`` (H*D) expose the two nesting levels.
    """

    backend = "hybrid"

    def __init__(self, comm, local_devices: int, devices=None):
        if comm is None or comm.world < 1:
            raise ValueError("hybrid backend needs an attached cross-host "
                             "comm (parallel.collective.set_process_comm)")
        if local_devices < 2:
            raise ValueError("hybrid backend needs >= 2 local devices for "
                             "the inner mesh; got %d" % local_devices)
        self.socket = SocketCollective(comm)
        self._mesh_coll = MeshCollective(local_devices, devices=devices)
        self.mesh = self._mesh_coll.mesh
        self.mesh_axis = AXIS
        self.local_world = int(local_devices)
        self._axis: Optional[HybridAxis] = None
        self._profiles: Dict = {}

    # -- topology --------------------------------------------------------
    @property
    def rank(self) -> int:
        return self.socket.rank          # host rank on the leader wire

    @property
    def world(self) -> int:
        return self.socket.world         # number of hosts

    @property
    def hosts(self) -> int:
        return self.socket.world

    @property
    def global_world(self) -> int:
        return self.socket.world * self.local_world

    @property
    def comm(self):
        return self.socket.comm

    def axis(self) -> HybridAxis:
        if self._axis is None:
            self._axis = HybridAxis(self)
        return self._axis

    # -- host payloads ride the leader wire ------------------------------
    def allreduce(self, value, op: str = "sum"):
        return self.socket.allreduce(value, op)

    def allgather(self, payload) -> List:
        return self.socket.allgather(payload)

    def exchange_arrays(self, tag: str, arr: np.ndarray) -> List[np.ndarray]:
        return self.socket.exchange_arrays(tag, arr)

    def row_layout(self, local_rows: int) -> Tuple[int, int]:
        return self.socket.row_layout(local_rows)

    # -- membership / fencing --------------------------------------------
    def fence(self) -> int:
        return self.socket.fence()

    def generation(self) -> int:
        return self.socket.generation()

    def world_changed(self):
        return self.socket.world_changed()

    def fenced_ranks(self) -> Tuple[int, ...]:
        return self.socket.fenced_ranks()

    def close(self) -> None:
        self.socket.close()

    # -- grower binding ---------------------------------------------------
    def bind(self, key, fn):
        """Wrap a jitted shard_mapped grow callable: capture the traced
        collective profile once (trace time), then on every dispatch
        block for the program, surface parked wire failures
        (WorldChangedError keeps the fence intact) and emit the
        ``comm/hybrid_dispatch`` span + counters."""
        axis = self.axis()

        def wrapped(*args):
            prof = self._profiles.get(key)
            if prof is None:
                prof = {}
                with capture_traced(prof):
                    out = fn(*args)
                self._profiles[key] = prof
            else:
                out = fn(*args)
            out = jax.block_until_ready(out)
            axis.check_failure()
            self._emit(prof, axis)
            return out
        return wrapped

    def _emit(self, prof, axis: HybridAxis) -> None:
        if not prof:
            return
        ops = sum(c for c, _ in prof.values())
        nbytes = sum(b for _, b in prof.values())
        self._mesh_coll._m_sent.inc(nbytes)
        self._mesh_coll._m_recv.inc(nbytes)
        self._mesh_coll._m_rounds.inc(ops)
        from ..obs import tracing
        if tracing.get_tracer().enabled:
            tracing.complete(
                "comm/hybrid_dispatch", 0.0, cat="comm", nbytes=nbytes,
                ops=ops, hosts=self.world, local=self.local_world,
                wire_wait_s=round(axis._wire_wait_s, 6),
                **{k: dict(count=c, bytes=b) for k, (c, b) in prof.items()})


def resolve_local_devices(config, available: Optional[int] = None) -> int:
    """Inner-mesh size for the hybrid backend: ``tpu_hybrid_local_devices``
    when positive, else every local device — clamped to what is visible."""
    if available is None:
        try:
            available = jax.device_count()
        except Exception:  # noqa: BLE001 — no backend at all
            available = 0
    want = int(getattr(config, "tpu_hybrid_local_devices", 0))
    if want <= 0:
        return available
    if want > available:
        log.warning("tpu_hybrid_local_devices=%d > visible devices=%d; "
                    "clamping", want, available)
    return min(want, available)
