"""Distributed tree learners over a JAX device mesh.

The TPU-native replacement for the reference's parallel learner family +
socket/MPI network stack (src/treelearner/{feature,data,voting}_parallel_
tree_learner.cpp, src/network/): instead of hand-rolled Bruck/recursive-
halving collectives over TCP (network.cpp:64-243), the grow loop runs inside
`jax.shard_map` over a 1-D mesh axis and exchanges histograms/splits with
XLA collectives (psum / all_gather) that ride ICI on a pod.

Modes (Config.tree_learner):
- "data":    rows sharded across devices (the primary TPU mode);
- "feature": data replicated, the split *search* sharded by features;
- "voting":  rows sharded + top-k vote to cap collective volume.

The reference requires a machine file and a port handshake
(linkers_socket.cpp:77-121); here the "machines" are the mesh devices and
rank = `jax.lax.axis_index`.  Multi-host pods work transparently: the same
shard_map over a mesh spanning hosts emits DCN/ICI collectives via XLA.
"""
from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..ops import grow as grow_ops
from ..utils import log
from . import collective as coll_mod
from .collective import AXIS  # noqa: F401 — canonical home moved there
from .collective import shard_mapped as _shard_mapped


def resolve_num_machines(config, available: Optional[int] = None) -> int:
    """Device count for the parallel learners: min(num_machines, devices),
    defaulting to every local device (a pod slice is the natural 'cluster';
    there is no machine-list file, cf. config.h:748-755 machine_list_filename)."""
    if available is None:
        available = jax.device_count()
    want = config.num_machines if config.num_machines > 1 else available
    if want > available:
        log.warning("num_machines=%d > available devices=%d; clamping",
                    want, available)
    return max(1, min(want, available))


class ParallelGrower:
    """Callable matching grow_ops.grow_tree's contract, running the grow
    loop shard_map'd over a device mesh.

    Pads rows (data/voting) or features (feature) to a multiple of the
    device count; padded rows enter with leaf id -1 (never in-bag), padded
    features get num_bins=0 + feature_mask=False so no scan can pick them.
    """

    def __init__(self, mode: str, num_machines: int, top_k: int = 20,
                 devices=None, collective=None):
        assert mode in ("data", "feature", "voting"), mode
        self.mode = mode
        self.d = num_machines
        self.top_k = top_k
        if collective is None:
            collective = coll_mod.MeshCollective(num_machines,
                                                 devices=devices)
        self.collective = collective
        if collective.backend == "mesh":
            self.mesh = collective.mesh
            self._axis = AXIS
        elif collective.backend == "hybrid":
            # host-first then device-second: this process holds its
            # host's row shard, shard_map splits it over the local mesh,
            # and the HybridAxis composes psum-over-ICI with the leader
            # wire — rows are pre-partitioned across hosts, so only the
            # data learner is meaningful (parallel/hybrid.py)
            if mode != "data":
                raise ValueError(
                    "tpu_comm_backend=hybrid supports tree_learner=data "
                    "only (rows are pre-partitioned across hosts); got %r"
                    % mode)
            self.mesh = collective.mesh
            self._axis = collective.axis()
        else:
            # cross-host: every rank runs the SAME grow program over its
            # local shard, collectives rendezvous on the wire through the
            # SocketAxis handle — rows are already pre-partitioned, so
            # only the data learner is meaningful here
            if mode != "data":
                raise ValueError(
                    "tpu_comm_backend=socket supports tree_learner=data "
                    "only (rows are pre-partitioned across hosts); got %r"
                    % mode)
            self.mesh = None
            self._axis = collective.axis()
        self._cache = {}
        # partition (arena) engine fast path — opted in by the GBDT
        # driver when the dataset is eligible (f32, max_bin<=256, n<2^24,
        # no forced splits); all three modes run on it, the label engine
        # stays as the fully-general fallback
        self._partition = None
        self._pcache = {}
        self._arena = None
        self._bins_t = None
        self._bins_key = None
        self.last_truncated = None
        # donation forensics (obs/device.donation_audit): the GBDT driver
        # flips audit_donation on when telemetry is armed; each partition
        # executable is walked once per build, against the raw jitted fn
        # kept in _praw (the bind/reshard wrappers cannot .lower())
        self.audit_donation = False
        self._praw = {}
        self._audited = set()

    # ------------------------------------------------------------------ #
    def enable_partition(self, hist_slots: int = 0):
        self._partition = dict(hist_slots=hist_slots)

    def disable_partition(self):
        self._partition = None
        self._pcache = {}
        self._arena = None
        self._bins_t = None
        self._bins_key = None

    # ------------------------------------------------------------------ #
    def _build(self, statics: tuple):
        fn = self._cache.get(statics)
        if fn is not None:
            return fn
        if self.mesh is None or self.collective.backend == "hybrid":
            raise RuntimeError(
                "the %s collective backend requires the partition "
                "engine (label-engine collectives are mesh-only)"
                % self.collective.backend)
        (max_leaves, max_depth, max_bin, hist_impl, rows_per_chunk,
         max_cat_threshold) = statics
        inner = partial(grow_ops.grow_tree_impl,
                        max_leaves=max_leaves, max_depth=max_depth,
                        max_bin=max_bin, hist_impl=hist_impl,
                        rows_per_chunk=rows_per_chunk,
                        learner=self.mode, axis_name=AXIS,
                        num_machines=self.d, top_k=self.top_k,
                        max_cat_threshold=max_cat_threshold)
        if self.mode in ("data", "voting"):
            row = P(AXIS)
            in_specs = (P(AXIS, None), row, row, row,
                        P(), P(), P(), P(), P(), P(), P(), P(),
                        P(), P(), P())
            out_specs = (P(), P(AXIS))
        else:  # feature: everything replicated, search sharded internally
            in_specs = tuple(P() for _ in range(15))
            out_specs = (P(), P())
        fn = jax.jit(_shard_mapped(inner, self.mesh, in_specs, out_specs))
        fn = self.collective.bind(("label",) + statics, fn) \
            if isinstance(self.collective, coll_mod.MeshCollective) else fn
        self._cache[statics] = fn
        return fn

    # ------------------------------------------------------------------ #
    def __call__(self, bins, grad, hess, row_leaf_init, feature_mask,
                 num_bins, default_bins, missing_types, params,
                 monotone=None, penalty=None, is_categorical=None,
                 bundle=None, *,
                 max_leaves: int, max_depth: int = -1, max_bin: int,
                 hist_impl: str = "auto", rows_per_chunk: int = 16384,
                 max_cat_threshold: int = 32,
                 quantized: bool = False, quant_scales=None):
        n, F = bins.shape
        if bundle is not None and self.mode == "feature":
            raise ValueError("feature-parallel learner does not support "
                             "EFB-bundled datasets")
        d = self.d
        if self._partition is not None:
            try:
                return self._call_partition(
                    bins, grad, hess, row_leaf_init, feature_mask,
                    num_bins, default_bins, missing_types, params,
                    monotone, penalty, is_categorical, bundle,
                    max_leaves=max_leaves, max_depth=max_depth,
                    max_bin=max_bin, max_cat_threshold=max_cat_threshold,
                    quantized=quantized, quant_scales=quant_scales)
            except Exception as exc:
                from ..resilience.comm import WorldChangedError
                if isinstance(exc, WorldChangedError):
                    raise          # elastic fence — never degrade past it
                if (self.mesh is None or quantized
                        or self.collective.backend == "hybrid"):
                    # socket/hybrid worlds and quantized codes have no
                    # label-engine equivalent; the driver owns the
                    # fallback
                    raise
                log.warning(
                    "partition engine failed under %s-parallel (%s: %s); "
                    "falling back to the label engine for this grower",
                    self.mode, type(exc).__name__,
                    str(exc).split("\n")[0][:200])
                self.disable_partition()
        if quantized:
            raise RuntimeError("quantized codes require the partition "
                               "engine; it is not enabled on this grower")
        self.last_truncated = None      # label engine never truncates
        if self.mode in ("data", "voting"):
            pad = (-n) % d
            if pad:
                bins = jnp.pad(bins, ((0, pad), (0, 0)))
                grad = jnp.pad(grad, (0, pad))
                hess = jnp.pad(hess, (0, pad))
                row_leaf_init = jnp.pad(row_leaf_init, (0, pad),
                                        constant_values=-1)
        else:  # feature
            pad = (-F) % d
            if pad:
                bins = jnp.pad(bins, ((0, 0), (0, pad)))
                feature_mask = jnp.pad(feature_mask, (0, pad))
                num_bins = jnp.pad(num_bins, (0, pad))
                default_bins = jnp.pad(default_bins, (0, pad))
                missing_types = jnp.pad(missing_types, (0, pad))
                if monotone is not None:
                    monotone = jnp.pad(monotone, (0, pad))
                if penalty is not None:
                    penalty = jnp.pad(penalty, (0, pad),
                                      constant_values=1.0)
                if is_categorical is not None:
                    is_categorical = jnp.pad(is_categorical, (0, pad))

        fn = self._build((max_leaves, max_depth, max_bin, hist_impl,
                          rows_per_chunk, max_cat_threshold))
        tree, leaf_ids = fn(bins, grad, hess, row_leaf_init, feature_mask,
                            num_bins, default_bins, missing_types, params,
                            monotone, penalty, is_categorical,
                            None, None, bundle)
        if self.mode in ("data", "voting") and leaf_ids.shape[0] != n:
            leaf_ids = leaf_ids[:n]
        return tree, leaf_ids


    # ------------------------------------------------------------------ #
    # Partition (arena) engine under shard_map: the flagship kernels run
    # per device over local arenas — data/voting shard rows, feature
    # replicates them — so the distributed modes keep the serial fast
    # path's asymptotics instead of dropping to the label engine's
    # masked full-n passes (VERDICT r3 weak #3).
    # ------------------------------------------------------------------ #
    def _build_partition(self, statics: tuple):
        fn = self._pcache.get(statics)
        if fn is not None:
            return fn
        from ..ops import grow_partition as gp
        (max_leaves, max_depth, max_bin, max_cat_threshold, C, cap,
         hist_slots, interpret, quantized) = statics
        d, mode, top_k = self.d, self.mode, self.top_k
        axis = self._axis      # AXIS for mesh, the HybridAxis for hybrid
        row_shard = mode in ("data", "voting")

        def shard_fn(arena, bins_t, g, h, r0, fmask, nb, db, mt, sparams,
                     mono, pen, icat, bnd, qsc):
            t, l, arena_out, trunc = gp.grow_tree_partition_impl(
                arena[0], bins_t, g, h, r0, fmask, nb, db, mt, sparams,
                mono, pen, None, None, icat, bnd,
                max_leaves=max_leaves, max_depth=max_depth,
                max_bin=max_bin, emit="leaf_ids", full_bag=False,
                max_cat_threshold=max_cat_threshold, axis_name=axis,
                learner=mode, num_machines=d, top_k=top_k,
                hist_slots=hist_slots, interpret=interpret,
                quantized=quantized,
                quant_scales=(qsc[0], qsc[1]) if quantized else None)
            return t, l, arena_out[None], trunc

        rp = P(AXIS) if row_shard else P()
        in_specs = (P(AXIS, None, None),
                    P(None, AXIS) if row_shard else P(),
                    rp, rp, rp,
                    P(), P(), P(), P(), P(), P(), P(), P(), P(), P())
        out_specs = (P(), rp, P(AXIS, None, None), P())
        jit_kw = {}
        if not isinstance(axis, str):
            # hybrid: the ordered io_callbacks inside thread an XLA token
            # through the entry computation, adding a hidden parameter;
            # with inferred shardings XLA's spmd-propagation-to-parameters
            # vector is sized to the USER parameters only and the
            # mismatch is a fatal CHECK (sharding_propagation.cc) that
            # aborts the process.  Explicit shardings sidestep the
            # propagation pass entirely.
            def _ns(spec):
                return jax.sharding.NamedSharding(self.mesh, spec)
            jit_kw = dict(in_shardings=tuple(_ns(s) for s in in_specs),
                          out_shardings=tuple(_ns(s) for s in out_specs))
        # donate_argnums=(0,): the arena is the ONLY donatable input.
        # bins_t / grad / hess / row_leaf_init look like candidates but
        # are semantically resident: bins_t and the bag mask persist
        # across rounds, and grad/hess are re-used by BOTH degrade paths
        # after a failed call (the quantized retry in gbdt._grow_tree and
        # the label-engine fallback in grow()) — donating them would
        # hand those paths deleted buffers on a real TPU.  The donation
        # audit marks them resident instead of un-donated.
        fn = jax.jit(_shard_mapped(shard_fn, self.mesh, in_specs,
                                   out_specs),
                     donate_argnums=(0,), **jit_kw)
        self._praw[statics] = fn
        if jit_kw:
            # explicit in_shardings REFUSE already-committed args whose
            # sharding differs (e.g. a replicated grad plane rebuilt by
            # an elastic restore); device_put reshards them and is a
            # no-op when the sharding already matches — the donated
            # arena passes through untouched on the steady-state path
            shardings = jit_kw["in_shardings"]
            jitted = fn

            def fn(*args):
                args = tuple(a if a is None else jax.device_put(a, s)
                             for a, s in zip(args, shardings))
                return jitted(*args)
        fn = self.collective.bind(("partition",) + statics, fn)
        self._pcache[statics] = fn
        return fn

    def _build_partition_socket(self, statics: tuple):
        """Socket twin of _build_partition: no shard_map — each rank jits
        the grow program over its LOCAL arena with the SocketAxis handle
        as axis_name, so every collective inside rendezvouses on the
        wire.  Programs are identical across ranks (same statics), which
        is what keeps the ordered callbacks symmetric."""
        fn = self._pcache.get(statics)
        if fn is not None:
            return fn
        from ..ops import grow_partition as gp
        (max_leaves, max_depth, max_bin, max_cat_threshold, C, cap,
         hist_slots, interpret, quantized) = statics
        d, mode, top_k, axis = self.d, self.mode, self.top_k, self._axis

        def local_fn(arena, bins_t, g, h, r0, fmask, nb, db, mt, sparams,
                     mono, pen, icat, bnd, qsc):
            t, l, arena_out, trunc = gp.grow_tree_partition_impl(
                arena[0], bins_t, g, h, r0, fmask, nb, db, mt, sparams,
                mono, pen, None, None, icat, bnd,
                max_leaves=max_leaves, max_depth=max_depth,
                max_bin=max_bin, emit="leaf_ids", full_bag=False,
                max_cat_threshold=max_cat_threshold, axis_name=axis,
                learner=mode, num_machines=d, top_k=top_k,
                hist_slots=hist_slots, interpret=interpret,
                quantized=quantized,
                quant_scales=(qsc[0], qsc[1]) if quantized else None)
            return t, l, arena_out[None], trunc

        # arena-only donation, same residency argument as _build_partition
        jitted = jax.jit(local_fn, donate_argnums=(0,))
        self._praw[statics] = jitted

        def wrapped(*args):
            out = jitted(*args)
            # surface wire failures parked by the host callbacks —
            # WorldChangedError re-raises here with the fence intact
            jax.block_until_ready(out[3])
            axis.check_failure()
            return out

        self._pcache[statics] = wrapped
        return wrapped

    def _call_partition(self, bins, grad, hess, row_leaf_init, feature_mask,
                        num_bins, default_bins, missing_types, params,
                        monotone, penalty, is_categorical, bundle, *,
                        max_leaves: int, max_depth: int, max_bin: int,
                        max_cat_threshold: int,
                        quantized: bool = False, quant_scales=None):
        import jax.numpy as jnp

        from ..ops import partition_pallas as pp
        n, G = bins.shape
        F = num_bins.shape[0]
        socket = self.mesh is None
        # socket ranks hold only their local shard: one local arena, no
        # cross-rank padding (the wire doesn't care about row counts)
        d = 1 if socket else self.d
        row_shard = self.mode in ("data", "voting")
        if socket:
            pad_r, pad_f = 0, 0
        elif row_shard:
            pad_r, pad_f = (-n) % d, 0
        else:
            # FP shards the SEARCH by features: pad features to d; data
            # (and the arena channel set) is replicated
            pad_r, pad_f = 0, (-F) % d
        n_pad, F_pad = n + pad_r, F + pad_f
        n_loc = n_pad // d if row_shard else n_pad
        G_pad = G + pad_f                  # G == F for FP (no EFB)
        C, cap = pp.arena_geometry(n_loc, G_pad)

        # the key holds a STRONG reference to the bins array: a bare
        # id() could be recycled after a dataset swap + GC, silently
        # reusing the previous dataset's transposed bins
        key = (bins, n, G, self.mode)
        if not (self._bins_key is not None
                and self._bins_key[0] is key[0]
                and self._bins_key[1:] == key[1:]):
            bt = jnp.asarray(bins, pp.ARENA_DT)
            if pad_r or pad_f:
                bt = jnp.pad(bt, ((0, pad_r), (0, pad_f)))
            self._bins_t = bt.T
            self._bins_key = key
            self._arena = None
        if self._arena is None or self._arena.shape != (d, C, cap):
            self._arena = jnp.zeros((d, C, cap), pp.ARENA_DT)
        if pad_r:
            grad = jnp.pad(grad, (0, pad_r))
            hess = jnp.pad(hess, (0, pad_r))
            row_leaf_init = jnp.pad(row_leaf_init, (0, pad_r),
                                    constant_values=-1)
        if pad_f:
            feature_mask = jnp.pad(feature_mask, (0, pad_f))
            num_bins = jnp.pad(num_bins, (0, pad_f))
            default_bins = jnp.pad(default_bins, (0, pad_f))
            missing_types = jnp.pad(missing_types, (0, pad_f))
            if monotone is not None:
                monotone = jnp.pad(monotone, (0, pad_f))
            if penalty is not None:
                penalty = jnp.pad(penalty, (0, pad_f), constant_values=1.0)
            if is_categorical is not None:
                is_categorical = jnp.pad(is_categorical, (0, pad_f))

        interpret = jax.default_backend() != "tpu"
        statics = (max_leaves, max_depth, max_bin, max_cat_threshold, C,
                   cap, self._partition["hist_slots"], interpret,
                   bool(quantized))
        # the builder returns a donating jit but does NOT donate
        # `statics` (a hashable int tuple, the cache key); bind the
        # audit key up front so nothing re-reads `statics` past the
        # build, which the donation-use-after checker cannot tell apart
        # from a donated-buffer read
        audit_key = statics if self.audit_donation else None
        fn = (self._build_partition_socket(statics) if socket
              else self._build_partition(statics))
        if quantized:
            qsc = jnp.stack([jnp.asarray(quant_scales[0], jnp.float32),
                             jnp.asarray(quant_scales[1], jnp.float32)])
        else:
            qsc = jnp.zeros((2,), jnp.float32)
        call_args = (self._arena, self._bins_t, grad, hess, row_leaf_init,
                     feature_mask, num_bins, default_bins, missing_types,
                     params, monotone, penalty, is_categorical, bundle, qsc)
        audit_raw = None
        if audit_key is not None and audit_key not in self._audited:
            self._audited.add(audit_key)
            audit_raw = self._praw.get(audit_key)
        tree, leaf_ids, self._arena, self.last_truncated = fn(*call_args)
        if audit_raw is not None:
            # AFTER the call: .lower() before the first execution would
            # populate the jaxpr cache outside capture_traced and starve
            # the collective byte accounting; post-call it is a cache hit
            from ..obs import device as obs_device
            # resident leaves 1-4: bins_t (dataset plane), grad/hess
            # (reused by the quantized-retry and label-fallback degrade
            # paths after a failed call), row_leaf_init (the bag mask,
            # reused until the next bagging round) — donation is
            # semantically impossible for all four.  call_args[0] was
            # donated into the call just made; lower with the
            # (identically-shaped) output arena instead
            obs_device.donation_audit(
                audit_raw, (self._arena,) + call_args[1:],
                label="partition/%s_w%d%s" % (
                    self.mode, self.d, "_q" if quantized else ""),
                resident=(1, 2, 3, 4))
        if leaf_ids.shape[0] != n:
            leaf_ids = leaf_ids[:n]
        return tree, leaf_ids


def make_grower(config, dataset_num_features: int):
    """GBDT-facing factory (TreeLearner::CreateTreeLearner,
    src/treelearner/tree_learner.cpp:9-33): returns None for the serial
    learner, else a ParallelGrower over the resolved Collective backend
    (mesh when the local devices allow it, socket when a cross-host comm
    is attached and tpu_comm_backend selects it — see
    parallel/collective.py and docs/Distributed.md)."""
    mode = config.tree_learner
    if mode == "serial":
        return None
    collective = coll_mod.make_collective(config)
    if collective is None:
        log.warning("tree_learner=%s requested but no collective backend "
                    "is available (one device, no attached comm); using "
                    "serial learner", mode)
        return None
    # the grower's machine count is the SHARD_MAP width: the local mesh
    # for hybrid (host payloads ride the leader wire at host rank/world),
    # the full world otherwise
    d = (collective.local_world if collective.backend == "hybrid"
         else collective.world)
    if mode == "feature" and dataset_num_features < d:
        log.warning("feature-parallel with fewer features (%d) than devices "
                    "(%d); padded features will idle some devices",
                    dataset_num_features, d)
    return ParallelGrower(mode, d, top_k=config.top_k,
                          collective=collective)
