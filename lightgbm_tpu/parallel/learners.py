"""Distributed tree learners over a JAX device mesh.

The TPU-native replacement for the reference's parallel learner family +
socket/MPI network stack (src/treelearner/{feature,data,voting}_parallel_
tree_learner.cpp, src/network/): instead of hand-rolled Bruck/recursive-
halving collectives over TCP (network.cpp:64-243), the grow loop runs inside
`jax.shard_map` over a 1-D mesh axis and exchanges histograms/splits with
XLA collectives (psum / all_gather) that ride ICI on a pod.

Modes (Config.tree_learner):
- "data":    rows sharded across devices (the primary TPU mode);
- "feature": data replicated, the split *search* sharded by features;
- "voting":  rows sharded + top-k vote to cap collective volume.

The reference requires a machine file and a port handshake
(linkers_socket.cpp:77-121); here the "machines" are the mesh devices and
rank = `jax.lax.axis_index`.  Multi-host pods work transparently: the same
shard_map over a mesh spanning hosts emits DCN/ICI collectives via XLA.
"""
from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..ops import grow as grow_ops
from ..utils import log

AXIS = "mp"


def resolve_num_machines(config, available: Optional[int] = None) -> int:
    """Device count for the parallel learners: min(num_machines, devices),
    defaulting to every local device (a pod slice is the natural 'cluster';
    there is no machine-list file, cf. config.h:748-755 machine_list_filename)."""
    if available is None:
        available = jax.device_count()
    want = config.num_machines if config.num_machines > 1 else available
    if want > available:
        log.warning("num_machines=%d > available devices=%d; clamping",
                    want, available)
    return max(1, min(want, available))


class ParallelGrower:
    """Callable matching grow_ops.grow_tree's contract, running the grow
    loop shard_map'd over a device mesh.

    Pads rows (data/voting) or features (feature) to a multiple of the
    device count; padded rows enter with leaf id -1 (never in-bag), padded
    features get num_bins=0 + feature_mask=False so no scan can pick them.
    """

    def __init__(self, mode: str, num_machines: int, top_k: int = 20,
                 devices=None):
        assert mode in ("data", "feature", "voting"), mode
        self.mode = mode
        self.d = num_machines
        self.top_k = top_k
        devices = (jax.devices() if devices is None else devices)[:num_machines]
        self.mesh = jax.sharding.Mesh(np.asarray(devices), (AXIS,))
        self._cache = {}

    # ------------------------------------------------------------------ #
    def _build(self, statics: tuple):
        fn = self._cache.get(statics)
        if fn is not None:
            return fn
        (max_leaves, max_depth, max_bin, hist_impl, rows_per_chunk,
         max_cat_threshold) = statics
        inner = partial(grow_ops.grow_tree_impl,
                        max_leaves=max_leaves, max_depth=max_depth,
                        max_bin=max_bin, hist_impl=hist_impl,
                        rows_per_chunk=rows_per_chunk,
                        learner=self.mode, axis_name=AXIS,
                        num_machines=self.d, top_k=self.top_k,
                        max_cat_threshold=max_cat_threshold)
        if self.mode in ("data", "voting"):
            row = P(AXIS)
            in_specs = (P(AXIS, None), row, row, row,
                        P(), P(), P(), P(), P(), P(), P(), P(),
                        P(), P(), P())
            out_specs = (P(), P(AXIS))
        else:  # feature: everything replicated, search sharded internally
            in_specs = tuple(P() for _ in range(15))
            out_specs = (P(), P())
        fn = jax.jit(jax.shard_map(inner, mesh=self.mesh,
                                   in_specs=in_specs, out_specs=out_specs,
                                   check_vma=False))
        self._cache[statics] = fn
        return fn

    # ------------------------------------------------------------------ #
    def __call__(self, bins, grad, hess, row_leaf_init, feature_mask,
                 num_bins, default_bins, missing_types, params,
                 monotone=None, penalty=None, is_categorical=None,
                 bundle=None, *,
                 max_leaves: int, max_depth: int = -1, max_bin: int,
                 hist_impl: str = "auto", rows_per_chunk: int = 16384,
                 max_cat_threshold: int = 32):
        n, F = bins.shape
        if bundle is not None and self.mode == "feature":
            raise ValueError("feature-parallel learner does not support "
                             "EFB-bundled datasets")
        d = self.d
        if self.mode in ("data", "voting"):
            pad = (-n) % d
            if pad:
                bins = jnp.pad(bins, ((0, pad), (0, 0)))
                grad = jnp.pad(grad, (0, pad))
                hess = jnp.pad(hess, (0, pad))
                row_leaf_init = jnp.pad(row_leaf_init, (0, pad),
                                        constant_values=-1)
        else:  # feature
            pad = (-F) % d
            if pad:
                bins = jnp.pad(bins, ((0, 0), (0, pad)))
                feature_mask = jnp.pad(feature_mask, (0, pad))
                num_bins = jnp.pad(num_bins, (0, pad))
                default_bins = jnp.pad(default_bins, (0, pad))
                missing_types = jnp.pad(missing_types, (0, pad))
                if monotone is not None:
                    monotone = jnp.pad(monotone, (0, pad))
                if penalty is not None:
                    penalty = jnp.pad(penalty, (0, pad),
                                      constant_values=1.0)
                if is_categorical is not None:
                    is_categorical = jnp.pad(is_categorical, (0, pad))

        fn = self._build((max_leaves, max_depth, max_bin, hist_impl,
                          rows_per_chunk, max_cat_threshold))
        tree, leaf_ids = fn(bins, grad, hess, row_leaf_init, feature_mask,
                            num_bins, default_bins, missing_types, params,
                            monotone, penalty, is_categorical,
                            None, None, bundle)
        if self.mode in ("data", "voting") and leaf_ids.shape[0] != n:
            leaf_ids = leaf_ids[:n]
        return tree, leaf_ids


def make_grower(config, dataset_num_features: int):
    """GBDT-facing factory (TreeLearner::CreateTreeLearner,
    src/treelearner/tree_learner.cpp:9-33): returns None for the serial
    learner, else a ParallelGrower over the local mesh."""
    mode = config.tree_learner
    if mode == "serial":
        return None
    d = resolve_num_machines(config)
    if d <= 1:
        log.warning("tree_learner=%s requested but only one device is "
                    "visible; using serial learner", mode)
        return None
    if mode == "feature" and dataset_num_features < d:
        log.warning("feature-parallel with fewer features (%d) than devices "
                    "(%d); padded features will idle some devices",
                    dataset_num_features, d)
    return ParallelGrower(mode, d, top_k=config.top_k)
