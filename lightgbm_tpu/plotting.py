"""Plotting utilities (reference python-package/lightgbm/plotting.py:1-456):
plot_importance, plot_metric, plot_tree / create_tree_digraph.  matplotlib
and graphviz are imported lazily so the core package has no hard
dependency on them.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from .basic import Booster
from .utils import log


def _check_not_tuple_of_2_elements(obj, obj_name):
    if not isinstance(obj, tuple) or len(obj) != 2:
        raise TypeError("%s must be a tuple of 2 elements." % obj_name)


def plot_importance(booster, ax=None, height=0.2, xlim=None, ylim=None,
                    title="Feature importance", xlabel="Feature importance",
                    ylabel="Features", importance_type="split",
                    max_num_features=None, ignore_zero=True, figsize=None,
                    grid=True, precision=3, **kwargs):
    """Horizontal bar chart of feature importances
    (plotting.py:20-143)."""
    try:
        import matplotlib.pyplot as plt
    except ImportError:
        raise ImportError("You must install matplotlib to plot importance")

    if isinstance(booster, Booster):
        importance = booster.feature_importance(importance_type)
        feature_name = booster.feature_name()
    elif hasattr(booster, "booster_"):          # sklearn estimator
        importance = booster.booster_.feature_importance(importance_type)
        feature_name = booster.booster_.feature_name()
    else:
        raise TypeError("booster must be Booster or LGBMModel")

    tuples = sorted(zip(feature_name, importance), key=lambda x: x[1])
    if ignore_zero:
        tuples = [t for t in tuples if t[1] > 0]
    if not tuples:
        raise ValueError("Booster's feature_importance is empty")
    if max_num_features is not None and max_num_features > 0:
        tuples = tuples[-max_num_features:]
    labels, values = zip(*tuples)

    if ax is None:
        if figsize is not None:
            _check_not_tuple_of_2_elements(figsize, "figsize")
        _, ax = plt.subplots(1, 1, figsize=figsize)
    ylocs = np.arange(len(values))
    ax.barh(ylocs, values, align="center", height=height, **kwargs)
    for x, y in zip(values, ylocs):
        ax.text(x + 1, y,
                ("%." + str(precision) + "f") % x if importance_type == "gain"
                else str(int(x)), va="center")
    ax.set_yticks(ylocs)
    ax.set_yticklabels(labels)
    if xlim is not None:
        _check_not_tuple_of_2_elements(xlim, "xlim")
    else:
        xlim = (0, max(values) * 1.1)
    ax.set_xlim(xlim)
    if ylim is not None:
        _check_not_tuple_of_2_elements(ylim, "ylim")
    else:
        ylim = (-1, len(values))
    ax.set_ylim(ylim)
    if title is not None:
        ax.set_title(title)
    if xlabel is not None:
        ax.set_xlabel(xlabel)
    if ylabel is not None:
        ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def plot_metric(booster, metric=None, dataset_names=None, ax=None,
                xlim=None, ylim=None, title="Metric during training",
                xlabel="Iterations", ylabel="auto", figsize=None, grid=True):
    """Plot one metric's history recorded by the record_evaluation callback
    (plotting.py:146-255).  `booster` is the eval-result dict or a Booster
    trained with evals_result."""
    try:
        import matplotlib.pyplot as plt
    except ImportError:
        raise ImportError("You must install matplotlib to plot metric")

    if isinstance(booster, dict):
        eval_results = booster
    elif hasattr(booster, "evals_result_"):
        eval_results = booster.evals_result_
    else:
        raise TypeError("booster must be dict or LGBMModel with "
                        "evals_result_")
    if not eval_results:
        raise ValueError("eval results cannot be empty")

    if ax is None:
        if figsize is not None:
            _check_not_tuple_of_2_elements(figsize, "figsize")
        _, ax = plt.subplots(1, 1, figsize=figsize)

    names = dataset_names or list(eval_results.keys())
    msg = None
    for name in names:
        metrics = eval_results[name]
        if metric is None:
            metric = next(iter(metrics))
        if metric not in metrics:
            raise ValueError("Specified metric %s not found" % metric)
        results = metrics[metric]
        ax.plot(range(len(results)), results, label=name)
        msg = metric
    ax.legend(loc="best")
    if xlim is not None:
        _check_not_tuple_of_2_elements(xlim, "xlim")
        ax.set_xlim(xlim)
    if ylim is not None:
        _check_not_tuple_of_2_elements(ylim, "ylim")
        ax.set_ylim(ylim)
    if title is not None:
        ax.set_title(title)
    if xlabel is not None:
        ax.set_xlabel(xlabel)
    ax.set_ylabel(msg if ylabel == "auto" else ylabel)
    ax.grid(grid)
    return ax


def create_tree_digraph(booster, tree_index=0, show_info=None,
                        precision=3, **kwargs):
    """Graphviz Digraph of one tree (plotting.py:258-378)."""
    try:
        from graphviz import Digraph
    except ImportError:
        raise ImportError("You must install graphviz to plot tree")

    if hasattr(booster, "booster_"):
        booster = booster.booster_
    if not isinstance(booster, Booster):
        raise TypeError("booster must be Booster or LGBMModel")
    model = booster.dump_model()
    tree_infos = model["tree_info"]
    feature_names = model.get("feature_names")
    if tree_index >= len(tree_infos):
        raise IndexError("tree_index is out of range")
    tree_info = tree_infos[tree_index]
    show_info = show_info or []

    graph = Digraph(**kwargs)

    def add(node, parent=None, decision=None):
        if "split_index" in node:
            name = "split%d" % node["split_index"]
            feat = node["split_feature"]
            if feature_names:
                feat = feature_names[feat]
            label = "split_feature_name: %s" % feat
            label += r"\nthreshold: %s" % round(node["threshold"], precision) \
                if not isinstance(node["threshold"], int) \
                else r"\nthreshold: %s" % node["threshold"]
            for info in ("split_gain", "internal_value", "internal_count"):
                if info in show_info:
                    label += r"\n%s: %s" % (info,
                                            round(node[info], precision))
            graph.node(name, label=label)
            add(node["left_child"], name, "yes")
            add(node["right_child"], name, "no")
        else:
            name = "leaf%d" % node.get("leaf_index", 0)
            label = "leaf_index: %d" % node.get("leaf_index", 0)
            label += r"\nleaf_value: %s" % round(node["leaf_value"], precision)
            if "leaf_count" in show_info and "leaf_count" in node:
                label += r"\nleaf_count: %d" % node["leaf_count"]
            graph.node(name, label=label)
        if parent is not None:
            graph.edge(parent, name, decision)

    add(tree_info["tree_structure"])
    return graph


def plot_tree(booster, ax=None, tree_index=0, figsize=None,
              show_info=None, precision=3, **kwargs):
    """Render one tree via graphviz into a matplotlib axis
    (plotting.py:381-456)."""
    try:
        import matplotlib.image as mpimg
        import matplotlib.pyplot as plt
    except ImportError:
        raise ImportError("You must install matplotlib to plot tree")
    from io import BytesIO

    graph = create_tree_digraph(booster, tree_index=tree_index,
                                show_info=show_info, precision=precision,
                                **kwargs)
    if ax is None:
        if figsize is not None:
            _check_not_tuple_of_2_elements(figsize, "figsize")
        _, ax = plt.subplots(1, 1, figsize=figsize)
    s = BytesIO(graph.pipe(format="png"))
    ax.imshow(mpimg.imread(s))
    ax.axis("off")
    return ax
