"""lightgbm_tpu.resilience — survive process kills and flaky sockets.

Two halves:

- ``checkpoint``: atomic round-level snapshots (model string + trainer
  aux state + exact score planes) with manifest hashes, retention and
  deterministic ``engine.train(..., resume_from=...)`` restore — the
  resumed model file is byte-identical to the uninterrupted run.
- ``comm``: retry policy / fault injector / typed ``CommFailure`` /
  rank-liveness heartbeat that ``parallel.distributed.SocketComm``
  wraps around its wire operations.
- ``elastic``: the degraded-world training supervisor — re-forms the
  comm world at a smaller size when a rank dies, re-shards the row
  partition and resumes from the newest checkpoint
  (docs/Elasticity.md).
- ``supervisor``: the continuous-learning loop — streaming ingest ->
  candidate refit -> shadow eval -> gated hot-swap -> automatic
  rollback, against a serving.Server (docs/ContinuousLearning.md).

See docs/Resilience.md for the checkpoint format and failure modes.
"""
from .checkpoint import (CheckpointData, CheckpointError, CheckpointManager,
                         CheckpointMismatchError, config_hash,
                         dataset_fingerprint, list_checkpoints, verify)
from .comm import CommFailure, FaultInjector, Heartbeat, RetryPolicy
from .elastic import (ElasticAborted, ElasticFenced, ElasticResult,
                      ElasticSupervisor)
from .supervisor import ContinuousLearningSupervisor, IngestBuffer

__all__ = [
    "CheckpointData", "CheckpointError", "CheckpointManager",
    "CheckpointMismatchError", "CommFailure",
    "ContinuousLearningSupervisor", "ElasticAborted",
    "ElasticFenced", "ElasticResult", "ElasticSupervisor", "FaultInjector",
    "Heartbeat", "IngestBuffer", "RetryPolicy", "config_hash",
    "dataset_fingerprint", "list_checkpoints", "verify",
]
