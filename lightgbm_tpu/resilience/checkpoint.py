"""Round-level training checkpoints with deterministic resume.

A checkpoint is one directory under the manager root:

    <root>/ckpt_00000012/
        MANIFEST.json    round, schema, config hash, dataset fingerprint,
                         per-file sha256 + byte sizes
        model.txt        the model string (save_model_to_string)
        state.json       trainer auxiliary state: round index, bagging /
                         feature / GOSS / DART RNG state, DART tree
                         weights, shrinkage (GBDT.capture_aux_state)
        scores.npz       raw training (and valid) score planes, exact
                         dtype — restored directly so resumed gradients
                         are bitwise-identical to the uninterrupted run

Writes are atomic: everything lands in a dot-tmp sibling directory,
every file is fsync'd, the directory is renamed into place and the
parent fsync'd — a crash mid-save leaves either the previous checkpoint
set or a ``.tmp`` directory the next save sweeps away, never a
half-written checkpoint.  ``keep_last_n`` retention prunes old rounds
after each successful save.

Resume contract (the guarantee the obs PR established for telemetry,
extended to restarts): ``engine.train(..., resume_from=...)`` restores
the booster from the newest valid checkpoint and continues training so
the final model file is byte-identical to the uninterrupted run — for
gbdt, dart and goss (tests/test_resilience.py asserts this).  Resume is
REFUSED with ``CheckpointMismatchError`` when the config hash or the
dataset bin-mapper fingerprint differs: silently continuing against
different binning or different training parameters would produce a
model that looks resumed but is neither run.

Early stopping and learning-rate schedules are evaluated from absolute
round indices, so schedules continue correctly; early-stopping METRIC
HISTORY restarts at resume (trackers are in-callback state), so the
byte-identity guarantee applies to fixed-round runs.
"""
from __future__ import annotations

import hashlib
import io
import json
import os
import shutil
import time
from typing import Dict, List, Optional

import numpy as np

from ..obs import tracing
from ..utils import log

SCHEMA_VERSION = 1
_CKPT_PREFIX = "ckpt_"
_TMP_PREFIX = ".tmp_"
MANIFEST = "MANIFEST.json"
MODEL_FILE = "model.txt"
STATE_FILE = "state.json"
SCORES_FILE = "scores.npz"

# Params that do not change what the booster computes per round: run
# control, IO paths, telemetry/serving/resilience knobs, predict-only
# settings.  Everything else is part of the config hash, so a resumed
# run with (say) a different num_leaves or lambda_l2 is refused.
CONFIG_HASH_EXCLUDE = frozenset({
    "config", "task", "data", "valid", "num_iterations",
    "early_stopping_round", "snapshot_freq", "verbosity",
    "output_model", "input_model", "output_result",
    "initscore_filename", "valid_data_initscores",
    "convert_model", "convert_model_language",
    "num_iteration_predict", "predict_raw_score", "predict_leaf_index",
    "predict_contrib", "pred_early_stop", "pred_early_stop_freq",
    "pred_early_stop_margin",
    "machine_rank", "machines", "machine_list_filename",
    "local_listen_port", "time_out",
    "tpu_profile", "tpu_profile_trace_dir", "tpu_log_json",
    "tpu_telemetry_path", "tpu_telemetry_device_stats",
    "tpu_trace_path", "tpu_trace_max_events", "tpu_trace_xla_analysis",
    "tpu_checkpoint_path", "tpu_checkpoint_interval", "tpu_checkpoint_keep",
    "tpu_comm_retries", "tpu_comm_backoff_ms", "tpu_comm_backoff_max_ms",
    "tpu_comm_op_timeout_s", "tpu_comm_heartbeat_s",
    "tpu_elastic", "tpu_elastic_heartbeat_ms", "tpu_elastic_suspect_ms",
    "tpu_elastic_rejoin_s", "tpu_elastic_min_world",
    "tpu_elastic_max_reforms", "tpu_elastic_sync_every",
    "tpu_elastic_scale_up", "tpu_elastic_scale_up_wait_s",
    "tpu_policy", "tpu_policy_rules", "tpu_policy_dry_run",
    "tpu_policy_rate_limit", "tpu_policy_rate_window_s",
    "tpu_policy_cooldown_rounds",
    "tpu_serve_shed_queue_rows", "tpu_serve_shed_retry_after_s",
    "tpu_serve_breaker_failures", "tpu_serve_breaker_reset_s",
    "tpu_serve_drain_timeout_s",
    "tpu_replica_count", "tpu_replica_min", "tpu_replica_max",
    "tpu_replica_probe_interval_s", "tpu_replica_probe_deadline_ms",
    "tpu_replica_breaker_failures", "tpu_replica_breaker_reset_s",
    "tpu_continuous_learning", "tpu_refit_interval_s", "tpu_refit_min_rows",
    "tpu_refit_mode", "tpu_refit_rounds", "tpu_refit_buffer_rows",
    "tpu_refit_holdout_fraction", "tpu_promote_min_delta",
    "tpu_promote_min_samples", "tpu_promote_watch_s",
    "tpu_promote_rollback_delta",
})

# Additionally excluded for DEGRADED-WORLD (elastic) resume: topology
# params legitimately change when the world re-forms at a different
# size, and the per-rank row partition they drive is rebuilt anyway.
ELASTIC_HASH_EXCLUDE = CONFIG_HASH_EXCLUDE | frozenset({
    "num_machines", "pre_partition",
})


class CheckpointError(RuntimeError):
    """A checkpoint could not be written, read or verified."""


class CheckpointMismatchError(CheckpointError):
    """Resume refused: the checkpoint was taken under a different config
    or against a differently-binned dataset."""


def config_hash(config, exclude: frozenset = CONFIG_HASH_EXCLUDE) -> str:
    """Stable hash over the training-relevant half of the config."""
    from ..config import PARAMETER_SET
    payload = {name: getattr(config, name) for name in sorted(PARAMETER_SET)
               if name not in exclude}
    blob = json.dumps(payload, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()


def dataset_fingerprint(binned) -> str:
    """Hash of the binned dataset identity: row/feature counts plus the
    full serialized bin-mapper state.  Two datasets with the same
    fingerprint bin every value identically, which is exactly what the
    restored score planes and parsed trees assume."""
    payload = {
        "num_data": int(binned.num_data),
        "num_features": int(binned.num_features),
        "mappers": [m.to_state() for m in binned.bin_mappers],
    }
    blob = json.dumps(payload, sort_keys=True)
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return  # not all filesystems allow O_RDONLY on dirs
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _write_fsync(path: str, data: bytes) -> None:
    with open(path, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())


class CheckpointData:
    """One loaded checkpoint: manifest + model text + aux state + score
    arrays, hash-verified at load time."""

    def __init__(self, path: str, manifest: Dict, model_str: str,
                 state: Dict, scores: Dict[str, np.ndarray]):
        self.path = path
        self.manifest = manifest
        self.model_str = model_str
        self.state = state
        self.scores = scores

    @property
    def round(self) -> int:
        return int(self.manifest["round"])


class CheckpointManager:
    """Atomic periodic snapshots + deterministic restore.

    Instantiate with the checkpoint root for the save side (the
    ``checkpoint`` callback calls ``maybe_save`` each round); the load
    side is classmethod-only (``latest`` / ``load`` / ``restore``) so
    resume never needs a manager instance.
    """

    def __init__(self, path: str, interval: int = 10, keep_last_n: int = 3,
                 registry=None, rank: int = 0):
        if not path:
            raise CheckpointError("CheckpointManager needs a directory path")
        self.path = str(path)
        self.interval = int(interval)
        self.keep_last_n = max(int(keep_last_n), 1)
        # when several ranks share one tpu_checkpoint_path, only rank 0
        # writes and sweeps — concurrent retention from multiple ranks
        # would race rmtree against a sibling's in-flight rename
        self.rank = max(int(rank), 0)
        if registry is None:
            from ..obs import default_registry
            registry = default_registry()
        self._m_saves = registry.counter(
            "lgbm_checkpoint_saves_total", help="Checkpoints written")
        self._m_seconds = registry.counter(
            "lgbm_checkpoint_seconds_total",
            help="Wall seconds spent writing checkpoints")
        self._m_last_round = registry.gauge(
            "lgbm_checkpoint_last_round",
            help="Round index of the newest checkpoint written")

    # -- save side ------------------------------------------------------ #
    def maybe_save(self, booster, iteration: int) -> Optional[str]:
        """Checkpoint after round ``iteration`` (0-based) when it closes
        an interval; the checkpoint callback routes here every round."""
        if self.interval <= 0 or (iteration + 1) % self.interval:
            return None
        return self.save(booster)

    def save(self, booster) -> Optional[str]:
        """Write one atomic checkpoint of the booster's CURRENT state
        (model + trainer aux + scores), then apply retention.  A no-op
        (None) on ranks > 0: every rank holds the same model, so one
        writer suffices and shared-directory sweeps cannot race."""
        if self.rank > 0:
            return None
        with tracing.span("ckpt/save", "ckpt"):
            return self._save_impl(booster)

    def _save_impl(self, booster) -> str:
        t0 = time.monotonic()
        gbdt = getattr(booster, "_gbdt", booster)
        # _sync_model first (inside capture_aux_state): deferred pipeline
        # trees must be materialized before the model text is cut
        state = gbdt.capture_aux_state()
        model_str = gbdt.save_model_to_string()
        scores = gbdt.capture_score_arrays()
        round_idx = int(state["round"])

        os.makedirs(self.path, exist_ok=True)
        self._sweep_tmp()
        name = "%s%08d" % (_CKPT_PREFIX, round_idx)
        tmp = os.path.join(self.path, _TMP_PREFIX + name)
        final = os.path.join(self.path, name)
        if os.path.isdir(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        try:
            _write_fsync(os.path.join(tmp, MODEL_FILE),
                         model_str.encode("utf-8"))
            _write_fsync(os.path.join(tmp, STATE_FILE),
                         json.dumps(state, sort_keys=True).encode("utf-8"))
            buf = io.BytesIO()
            np.savez(buf, **scores)
            _write_fsync(os.path.join(tmp, SCORES_FILE), buf.getvalue())
            manifest = {
                "schema": SCHEMA_VERSION,
                "round": round_idx,
                "boosting": state.get("boosting", ""),
                "num_trees": model_str.count("\nTree="),
                "config_hash": config_hash(gbdt.config),
                "config_hash_elastic": config_hash(gbdt.config,
                                                   ELASTIC_HASH_EXCLUDE),
                "dataset_fingerprint": dataset_fingerprint(gbdt.train_set),
                "created_at": time.time(),
                "files": {
                    fn: {"sha256": _sha256_file(os.path.join(tmp, fn)),
                         "bytes": os.path.getsize(os.path.join(tmp, fn))}
                    for fn in (MODEL_FILE, STATE_FILE, SCORES_FILE)
                },
            }
            _write_fsync(os.path.join(tmp, MANIFEST),
                         json.dumps(manifest, sort_keys=True,
                                    indent=1).encode("utf-8"))
            _fsync_dir(tmp)
            if os.path.isdir(final):
                # re-checkpointing the same round (resume overlap):
                # replace wholesale
                shutil.rmtree(final)
            os.rename(tmp, final)
            _fsync_dir(self.path)
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        self._retain()
        wall = time.monotonic() - t0
        self._m_saves.inc()
        self._m_seconds.inc(wall)
        self._m_last_round.set(round_idx)
        recorder = getattr(gbdt, "recorder", None)
        if recorder is not None:
            try:
                recorder.record_checkpoint(round_idx, final, wall)
            except Exception as exc:  # noqa: BLE001 — telemetry never raises
                log.warning("checkpoint telemetry failed: %s", exc)
        log.info("Checkpoint round %d written to %s (%.0f ms)",
                 round_idx, final, wall * 1e3)
        return final

    def _retain(self) -> None:
        ckpts = list_checkpoints(self.path)
        for path, _round in ckpts[:-self.keep_last_n]:
            shutil.rmtree(path, ignore_errors=True)
            log.debug("checkpoint retention: removed %s", path)

    def _sweep_tmp(self) -> None:
        for entry in os.listdir(self.path):
            if entry.startswith(_TMP_PREFIX):
                shutil.rmtree(os.path.join(self.path, entry),
                              ignore_errors=True)

    # -- load side ------------------------------------------------------ #
    @staticmethod
    def latest(path: str) -> Optional[str]:
        """Newest checkpoint directory under ``path`` that passes hash
        verification, or None.  A corrupt newest checkpoint (crash
        mid-rename races are impossible, but disk rot is not) falls back
        to the next older one with a warning."""
        for ckpt, _round in reversed(list_checkpoints(path)):
            try:
                verify(ckpt)
                return ckpt
            except CheckpointError as exc:
                log.warning("skipping corrupt checkpoint %s: %s", ckpt, exc)
        return None

    @staticmethod
    def latest_model_file(path: str) -> str:
        """Model file inside the newest valid checkpoint (the serving
        registry's load-from-checkpoint seam)."""
        ckpt = CheckpointManager.latest(path)
        if ckpt is None:
            raise CheckpointError("no valid checkpoint under %s" % path)
        return os.path.join(ckpt, MODEL_FILE)

    @staticmethod
    def load(path: str) -> CheckpointData:
        """Load a checkpoint: ``path`` is either one checkpoint directory
        or a manager root (then the newest valid checkpoint is used)."""
        with tracing.span("ckpt/load", "ckpt", path=str(path)):
            if os.path.isfile(os.path.join(path, MANIFEST)):
                ckpt = path
            else:
                ckpt = CheckpointManager.latest(path)
                if ckpt is None:
                    raise CheckpointError(
                        "no valid checkpoint found under %s" % path)
            manifest = verify(ckpt)
            with open(os.path.join(ckpt, MODEL_FILE)) as f:
                model_str = f.read()
            with open(os.path.join(ckpt, STATE_FILE)) as f:
                state = json.load(f)
            with np.load(os.path.join(ckpt, SCORES_FILE)) as z:
                scores = {k: z[k] for k in z.files}
            return CheckpointData(ckpt, manifest, model_str, state, scores)

    @staticmethod
    def restore(booster, ckpt: CheckpointData) -> int:
        """Restore a freshly constructed booster (same params, same
        dataset) to the checkpointed round.  Returns the round index to
        resume the boosting loop from.  Refuses on config-hash or
        dataset-fingerprint mismatch."""
        with tracing.span("ckpt/restore", "ckpt", round=ckpt.round):
            return CheckpointManager._restore_impl(booster, ckpt)

    @staticmethod
    def _restore_impl(booster, ckpt: CheckpointData) -> int:
        gbdt = getattr(booster, "_gbdt", booster)
        want, have = ckpt.manifest["config_hash"], config_hash(gbdt.config)
        if want != have:
            raise CheckpointMismatchError(
                "config mismatch: checkpoint %s was taken with config hash "
                "%s but this run resolves to %s — resume needs identical "
                "training parameters (run-control params like "
                "num_iterations/paths may differ)"
                % (ckpt.path, want[:12], have[:12]))
        want = ckpt.manifest["dataset_fingerprint"]
        have = dataset_fingerprint(gbdt.train_set)
        if want != have:
            raise CheckpointMismatchError(
                "dataset mismatch: checkpoint %s was taken against a "
                "dataset with bin-mapper fingerprint %s but this run's "
                "train set fingerprints to %s — resume needs the same "
                "data binned the same way" % (ckpt.path, want[:12], have[:12]))
        boosting = ckpt.state.get("boosting", "")
        if boosting and boosting != type(gbdt).__name__.lower():
            raise CheckpointMismatchError(
                "boosting mismatch: checkpoint is %r, booster is %r"
                % (boosting, type(gbdt).__name__.lower()))
        gbdt.load_model_from_string(ckpt.model_str)
        if gbdt.iter != ckpt.round:
            raise CheckpointError(
                "checkpoint %s claims round %d but its model holds %d "
                "iterations" % (ckpt.path, ckpt.round, gbdt.iter))
        gbdt.restore_aux_state(ckpt.state)
        gbdt.restore_score_arrays(ckpt.scores)
        log.info("Restored checkpoint %s: round %d, %d trees",
                 ckpt.path, ckpt.round, len(gbdt.models))
        return ckpt.round

    @staticmethod
    def restore_elastic(booster, ckpt: CheckpointData,
                        raw_X: np.ndarray) -> int:
        """Degraded-world restore: same training params, DIFFERENT row
        shard (the elastic supervisor re-partitions after a world
        re-formation, so strict ``restore`` would refuse on the dataset
        fingerprint).  The config hash is checked with topology params
        additionally excluded; the saved train score plane — which
        indexes the OLD shard's rows — is discarded and rebuilt from
        ``raw_X`` (this rank's current raw shard) via
        ``rebuild_score_from_raw``.  Shard-independent score entries
        (valid-set planes, DART's exact per-tree arrays) restore
        verbatim.
        """
        with tracing.span("ckpt/restore_elastic", "ckpt", round=ckpt.round):
            gbdt = getattr(booster, "_gbdt", booster)
            want = ckpt.manifest.get("config_hash_elastic")
            have = config_hash(gbdt.config, ELASTIC_HASH_EXCLUDE)
            if want is None:
                log.warning("checkpoint %s predates elastic config "
                            "hashing; resuming without the config check",
                            ckpt.path)
            elif want != have:
                raise CheckpointMismatchError(
                    "config mismatch: checkpoint %s was taken with "
                    "elastic config hash %s but this run resolves to %s "
                    "— degraded-world resume allows topology changes, "
                    "not training-parameter changes"
                    % (ckpt.path, want[:12], have[:12]))
            boosting = ckpt.state.get("boosting", "")
            if boosting and boosting != type(gbdt).__name__.lower():
                raise CheckpointMismatchError(
                    "boosting mismatch: checkpoint is %r, booster is %r"
                    % (boosting, type(gbdt).__name__.lower()))
            gbdt.load_model_from_string(ckpt.model_str)
            if gbdt.iter != ckpt.round:
                raise CheckpointError(
                    "checkpoint %s claims round %d but its model holds "
                    "%d iterations" % (ckpt.path, ckpt.round, gbdt.iter))
            gbdt.restore_aux_state(ckpt.state)
            gbdt.restore_score_arrays(
                {k: v for k, v in ckpt.scores.items() if k != "train"})
            gbdt.rebuild_score_from_raw(raw_X)
            log.info("Elastic-restored checkpoint %s: round %d, %d "
                     "trees, train plane rebuilt for a %d-row shard",
                     ckpt.path, ckpt.round, len(gbdt.models), len(raw_X))
            return ckpt.round


def list_checkpoints(path: str) -> List:
    """[(dir, round)] under ``path``, oldest first."""
    out = []
    if not os.path.isdir(path):
        return out
    for entry in os.listdir(path):
        if not entry.startswith(_CKPT_PREFIX):
            continue
        try:
            rnd = int(entry[len(_CKPT_PREFIX):])
        except ValueError:
            continue
        full = os.path.join(path, entry)
        if os.path.isdir(full):
            out.append((full, rnd))
    out.sort(key=lambda pr: pr[1])
    return out


def verify(ckpt_dir: str) -> Dict:
    """Check a checkpoint's manifest against its files (existence, size,
    sha256).  Returns the manifest; raises CheckpointError on any
    mismatch.  tools/ckpt_inspect.py is the CLI face of this."""
    mpath = os.path.join(ckpt_dir, MANIFEST)
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except (OSError, ValueError) as exc:
        raise CheckpointError("unreadable manifest %s: %s" % (mpath, exc))
    files = manifest.get("files", {})
    if not files:
        raise CheckpointError("manifest %s lists no files" % mpath)
    for fn, meta in files.items():
        full = os.path.join(ckpt_dir, fn)
        if not os.path.isfile(full):
            raise CheckpointError("checkpoint file missing: %s" % full)
        size = os.path.getsize(full)
        if size != meta.get("bytes"):
            raise CheckpointError(
                "size mismatch for %s: manifest says %s bytes, file has %d"
                % (full, meta.get("bytes"), size))
        digest = _sha256_file(full)
        if digest != meta.get("sha256"):
            raise CheckpointError(
                "content hash mismatch for %s: manifest %s, file %s"
                % (full, str(meta.get("sha256"))[:12], digest[:12]))
    return manifest
