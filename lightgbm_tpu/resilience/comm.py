"""Comm robustness primitives: retry policy, fault injection, liveness.

The reference aborts all ranks when one socket operation fails
(src/network/linkers_socket.cpp has no retry beyond the initial connect
loop).  For a fleet-scale TPU deployment that is the wrong trade: a
transient RST during the find-bin exchange kills a run that would have
retraced hours of XLA compiles on restart.  This module supplies the
pieces `parallel/distributed.SocketComm` wraps around its wire ops:

- ``RetryPolicy``       exponential backoff + jitter with a bounded budget
- ``FaultInjector``     deterministic chaos hook (fail/delay/drop/partition/
                        kill), used by tests and tools/chaos_run.py
- ``CommFailure``       typed abort naming the dead peer rank
- ``WorldChangedError`` typed abort meaning "the MEMBERSHIP is wrong, not
                        the wire" — re-form the world instead of retrying
- ``Heartbeat``         background rank-liveness probe thread with
                        consecutive-miss suspicion (flap suppression)

Retry semantics are whole-frame: an operation that fails before its
frame hits the wire (connection refused, peer reset, injected fault)
retries cleanly; a peer that stays dead exhausts the budget and raises
``CommFailure`` carrying the peer rank, the operation name and the last
underlying error.  Retries and aborts are counted in the process-wide
obs registry (``lgbm_comm_retries_total`` / ``lgbm_comm_failures_total``)
so they surface in /metrics scrapes and TrainingRecorder events.
"""
from __future__ import annotations

import os
import random
import signal
import threading
import time
from typing import Callable, Dict, Iterable, List, Optional

from ..utils import log


class WorldChangedError(ConnectionError):
    """The comm world's MEMBERSHIP changed: a peer was fenced (poison
    frame / suspicion timeout), this rank itself was fenced by the
    survivors, or a frame arrived stamped with a stale generation.

    Retrying the wire op is pointless — the fix is topology-level:
    tear the ring down and re-form it (resilience.elastic does exactly
    that).  ``dead_ranks`` names the ranks believed gone, ``generation``
    the generation the error was observed under, and ``fenced`` is True
    when THIS rank is the one the survivors cut off.

    The same exception also carries the scale-UP boundary: ``epoch`` is
    True for a DELIBERATE formation epoch (ElasticComm.announce_epoch —
    nobody died, the world is re-forming to ADMIT hosts) and
    ``readmit`` names the ranks the supervisor should put back in its
    alive view before re-forming.
    """

    def __init__(self, message: str, dead_ranks: Iterable[int] = (),
                 generation: int = 0, fenced: bool = False,
                 epoch: bool = False, readmit: Iterable[int] = ()):
        self.dead_ranks = sorted(int(r) for r in dead_ranks)
        self.generation = int(generation)
        self.fenced = bool(fenced)
        self.epoch = bool(epoch)
        self.readmit = sorted(int(r) for r in readmit)
        super().__init__("%s (dead=%s, generation=%d%s%s)"
                         % (message, self.dead_ranks, self.generation,
                            ", self-fenced" if fenced else "",
                            ", epoch readmit=%s" % self.readmit
                            if epoch else ""))


class CommFailure(ConnectionError):
    """A comm operation exhausted its retry budget against one peer.

    Carries enough to act on: ``rank`` (the peer observed dead), ``op``
    (send/recv/allgather), ``attempts`` and the last underlying error.
    """

    def __init__(self, op: str, rank: int, attempts: int,
                 cause: Optional[BaseException] = None):
        self.op = op
        self.rank = int(rank)
        self.attempts = int(attempts)
        self.cause = cause
        super().__init__(
            "comm %s failed against rank %d after %d attempt(s): %s"
            % (op, rank, attempts, cause))


class RetryPolicy:
    """Bounded exponential backoff with jitter.

    ``retries`` is the number of RE-tries after the first attempt, so a
    policy with retries=4 makes at most 5 attempts.  Delay for attempt
    ``n`` (1-based) is ``base_ms * 2**(n-1)`` capped at ``max_ms``, then
    scaled by a uniform jitter in [0.5, 1.0] so a whole fleet retrying
    the same dead hub does not thundering-herd in lockstep.  Jitter
    affects timing only — never training output — so the seeded RNG here
    has no bearing on model determinism.
    """

    def __init__(self, retries: int = 4, base_ms: float = 50.0,
                 max_ms: float = 2000.0, jitter: float = 0.5,
                 seed: Optional[int] = None):
        self.retries = max(int(retries), 0)
        self.base_ms = max(float(base_ms), 0.0)
        self.max_ms = max(float(max_ms), self.base_ms)
        self.jitter = min(max(float(jitter), 0.0), 1.0)
        self._rng = random.Random(seed)

    def backoff_s(self, attempt: int) -> float:
        """Sleep before retry `attempt` (1-based), in seconds."""
        raw = min(self.base_ms * (2.0 ** max(attempt - 1, 0)), self.max_ms)
        scale = 1.0 - self.jitter * self._rng.random()
        return raw * scale / 1e3

    @classmethod
    def from_config(cls, config) -> "RetryPolicy":
        return cls(retries=getattr(config, "tpu_comm_retries", 4),
                   base_ms=getattr(config, "tpu_comm_backoff_ms", 50.0),
                   max_ms=getattr(config, "tpu_comm_backoff_max_ms", 2000.0))


class FaultInjector:
    """Deterministic chaos hook for the comm layer, used by tests and
    tools/chaos_run.py.

    Armed per (operation name); ``check(op)`` is called by SocketComm
    immediately before the real wire operation and either raises (fail),
    sleeps (delay), tells the caller to silently lose the frame (drop),
    or terminates the process outright (kill — SIGKILL, so no cleanup
    handler can soften the failure the survivors must ride out).
    ``count=-1`` arms a fault forever: ``partition`` is sugar for an
    infinite drop, the network-partition model where every frame to/from
    this rank vanishes but the process stays up.  Unarmed operations
    cost one dict lookup.

        inj = FaultInjector()
        inj.fail("allgather", count=2)        # next 2 allgathers raise
        inj.delay("send", count=1, seconds=0.2)
        inj.drop("send", count=1)             # frame silently lost
        inj.partition("send")                 # every frame lost, forever
        inj.kill("allgather", after=3)        # 4th allgather: SIGKILL
        comm = SocketComm(..., injector=inj)
    """

    OK, DROP = "ok", "drop"

    def __init__(self):
        self._lock = threading.Lock()
        self._faults: Dict[str, List[dict]] = {}
        self.injected = 0

    def fail(self, op: str, count: int = 1,
             exc_factory: Optional[Callable[[], BaseException]] = None) -> None:
        self._arm(op, {"kind": "fail", "count": int(count),
                       "exc": exc_factory})

    def delay(self, op: str, count: int = 1, seconds: float = 0.05) -> None:
        self._arm(op, {"kind": "delay", "count": int(count),
                       "seconds": float(seconds)})

    def drop(self, op: str, count: int = 1) -> None:
        self._arm(op, {"kind": "drop", "count": int(count)})

    def partition(self, op: str) -> None:
        """Permanent silent frame loss on `op` — the process stays alive
        but is unreachable through this operation (network partition)."""
        self._arm(op, {"kind": "drop", "count": -1})

    def kill(self, op: str, after: int = 0) -> None:
        """SIGKILL this process on the (after+1)-th `op`.  The real
        rank-death fault: no exception propagates, no socket is closed
        gracefully — peers see RST/EOF, exactly like an OOM-kill or a
        preempted VM."""
        if after > 0:
            self._arm(op, {"kind": "noop", "count": int(after)})
        self._arm(op, {"kind": "kill", "count": 1})

    def reset(self) -> None:
        with self._lock:
            self._faults.clear()

    def armed(self, op: Optional[str] = None) -> bool:
        with self._lock:
            if op is None:
                return any(self._faults.values())
            return bool(self._faults.get(op))

    def _arm(self, op: str, fault: dict) -> None:
        with self._lock:
            self._faults.setdefault(op, []).append(fault)

    def check(self, op: str) -> str:
        """Consume one armed fault for `op`.  Returns OK or DROP; raises
        for fail faults (a ConnectionError by default, so the retry loop
        treats it exactly like a real transient wire error).  A count of
        -1 never depletes (partition)."""
        with self._lock:
            queue = self._faults.get(op)
            if not queue:
                return self.OK
            fault = queue[0]
            if fault["count"] > 0:
                fault["count"] -= 1
                if fault["count"] <= 0:
                    queue.pop(0)
            self.injected += 1
        kind = fault["kind"]
        if kind == "noop":
            return self.OK
        if kind == "delay":
            time.sleep(fault["seconds"])
            return self.OK
        if kind == "drop":
            return self.DROP
        if kind == "kill":
            log.warning("fault injector: SIGKILL on %s", op)
            os.kill(os.getpid(), signal.SIGKILL)
            time.sleep(60)  # unreachable; keep the op blocked while dying
        exc_factory = fault.get("exc")
        raise (exc_factory() if exc_factory is not None
               else ConnectionError("injected fault: %s" % op))


class Heartbeat:
    """Rank-liveness monitor: a daemon thread calling ``probe()`` every
    ``interval_s`` seconds.  ``probe`` returns the list of peer ranks
    currently UNRESPONSIVE this round (SocketComm supplies a passive
    socket health check; ElasticComm an active ping/pong age check).

    Suspicion, not reflex: a rank is only declared dead after
    ``suspect_after`` CONSECUTIVE unresponsive rounds, so a single
    missed probe — GC pause, packet loss, a briefly saturated NIC —
    never flaps the world (detection latency is therefore bounded by
    ``interval_s * suspect_after`` plus one probe).  A suspect that
    answers again before conviction has its miss count reset, and a
    CONVICTED rank that comes back (transient stall, partition healed)
    is un-declared: the ``lgbm_comm_alive_ranks`` gauge recovers.

    ``on_change(dead_set)`` fires on every conviction-set transition —
    ElasticComm fences + poisons from it; tests observe it.
    """

    def __init__(self, probe: Callable[[], List[int]], interval_s: float,
                 rank: int = 0, world: int = 1, registry=None,
                 suspect_after: int = 1,
                 on_change: Optional[Callable[[set], None]] = None):
        self.probe = probe
        self.interval_s = max(float(interval_s), 1e-3)
        self.rank, self.world = int(rank), int(world)
        self.suspect_after = max(int(suspect_after), 1)
        self.on_change = on_change
        self._dead: set = set()
        self._misses: Dict[int, int] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._gauge = None
        self._miss_gauge = None
        if registry is not None:
            self._gauge = registry.gauge(
                "lgbm_comm_alive_ranks",
                help="Ranks the heartbeat currently considers alive",
                rank=str(rank), world=str(world))
            self._gauge.set(world)
            # worst consecutive-miss streak across peers: the alert
            # engine's heartbeat_miss rule watches this — it climbs
            # BEFORE conviction flips alive_ranks
            self._miss_gauge = registry.gauge(
                "lgbm_comm_heartbeat_miss_streak",
                help="Max consecutive missed heartbeat probes over peers",
                rank=str(rank), world=str(world))
            self._miss_gauge.set(0)

    def start(self) -> "Heartbeat":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="lgbm-heartbeat", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2 * self.interval_s + 1.0)
            self._thread = None

    def dead_ranks(self) -> List[int]:
        return sorted(self._dead)

    def suspect_ranks(self) -> List[int]:
        """Ranks with at least one miss but not yet convicted."""
        return sorted(r for r, m in self._misses.items()
                      if 0 < m < self.suspect_after and r not in self._dead)

    def alive(self) -> bool:
        return not self._dead

    def poll_once(self) -> List[int]:
        """One probe round (also what the thread loop runs)."""
        try:
            missing = set(self.probe())
        except Exception as exc:  # noqa: BLE001 — liveness must not raise
            log.debug("heartbeat probe failed: %s", exc)
            return self.dead_ranks()
        for r in missing:
            self._misses[r] = self._misses.get(r, 0) + 1
        for r in list(self._misses):
            if r not in missing:
                self._misses[r] = 0
        dead = {r for r, m in self._misses.items()
                if m >= self.suspect_after}
        for r in sorted(dead - self._dead):
            log.warning("heartbeat: rank %d declared dead after %d "
                        "consecutive missed probe(s)", r, self._misses[r])
        for r in sorted(self._dead - dead):
            log.warning("heartbeat: rank %d responded again — liveness "
                        "restored", r)
        changed = dead != self._dead
        self._dead = dead
        if self._gauge is not None:
            self._gauge.set(self.world - len(dead))
        if self._miss_gauge is not None:
            self._miss_gauge.set(max(self._misses.values(), default=0))
        if changed and self.on_change is not None:
            try:
                self.on_change(set(dead))
            except Exception as exc:  # noqa: BLE001 — liveness must not raise
                log.warning("heartbeat on_change callback failed: %s", exc)
        return self.dead_ranks()

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.poll_once()
