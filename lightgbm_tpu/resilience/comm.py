"""Comm robustness primitives: retry policy, fault injection, liveness.

The reference aborts all ranks when one socket operation fails
(src/network/linkers_socket.cpp has no retry beyond the initial connect
loop).  For a fleet-scale TPU deployment that is the wrong trade: a
transient RST during the find-bin exchange kills a run that would have
retraced hours of XLA compiles on restart.  This module supplies the
pieces `parallel/distributed.SocketComm` wraps around its wire ops:

- ``RetryPolicy``     exponential backoff + jitter with a bounded budget
- ``FaultInjector``   deterministic test hook (fail-next-N, delay, drop)
- ``CommFailure``     typed abort naming the dead peer rank
- ``Heartbeat``       background rank-liveness probe thread

Retry semantics are whole-frame: an operation that fails before its
frame hits the wire (connection refused, peer reset, injected fault)
retries cleanly; a peer that stays dead exhausts the budget and raises
``CommFailure`` carrying the peer rank, the operation name and the last
underlying error.  Retries and aborts are counted in the process-wide
obs registry (``lgbm_comm_retries_total`` / ``lgbm_comm_failures_total``)
so they surface in /metrics scrapes and TrainingRecorder events.
"""
from __future__ import annotations

import random
import threading
import time
from typing import Callable, Dict, List, Optional

from ..utils import log


class CommFailure(ConnectionError):
    """A comm operation exhausted its retry budget against one peer.

    Carries enough to act on: ``rank`` (the peer observed dead), ``op``
    (send/recv/allgather), ``attempts`` and the last underlying error.
    """

    def __init__(self, op: str, rank: int, attempts: int,
                 cause: Optional[BaseException] = None):
        self.op = op
        self.rank = int(rank)
        self.attempts = int(attempts)
        self.cause = cause
        super().__init__(
            "comm %s failed against rank %d after %d attempt(s): %s"
            % (op, rank, attempts, cause))


class RetryPolicy:
    """Bounded exponential backoff with jitter.

    ``retries`` is the number of RE-tries after the first attempt, so a
    policy with retries=4 makes at most 5 attempts.  Delay for attempt
    ``n`` (1-based) is ``base_ms * 2**(n-1)`` capped at ``max_ms``, then
    scaled by a uniform jitter in [0.5, 1.0] so a whole fleet retrying
    the same dead hub does not thundering-herd in lockstep.  Jitter
    affects timing only — never training output — so the seeded RNG here
    has no bearing on model determinism.
    """

    def __init__(self, retries: int = 4, base_ms: float = 50.0,
                 max_ms: float = 2000.0, jitter: float = 0.5,
                 seed: Optional[int] = None):
        self.retries = max(int(retries), 0)
        self.base_ms = max(float(base_ms), 0.0)
        self.max_ms = max(float(max_ms), self.base_ms)
        self.jitter = min(max(float(jitter), 0.0), 1.0)
        self._rng = random.Random(seed)

    def backoff_s(self, attempt: int) -> float:
        """Sleep before retry `attempt` (1-based), in seconds."""
        raw = min(self.base_ms * (2.0 ** max(attempt - 1, 0)), self.max_ms)
        scale = 1.0 - self.jitter * self._rng.random()
        return raw * scale / 1e3

    @classmethod
    def from_config(cls, config) -> "RetryPolicy":
        return cls(retries=getattr(config, "tpu_comm_retries", 4),
                   base_ms=getattr(config, "tpu_comm_backoff_ms", 50.0),
                   max_ms=getattr(config, "tpu_comm_backoff_max_ms", 2000.0))


class FaultInjector:
    """Deterministic fault hook for the comm layer, used by tests.

    Armed per (operation name); ``check(op)`` is called by SocketComm
    immediately before the real wire operation and either raises (fail),
    sleeps (delay), or tells the caller to silently lose the frame
    (drop).  Unarmed operations cost one dict lookup.

        inj = FaultInjector()
        inj.fail("allgather", count=2)        # next 2 allgathers raise
        inj.delay("send", count=1, seconds=0.2)
        inj.drop("send", count=1)             # frame silently lost
        comm = SocketComm(..., injector=inj)
    """

    OK, DROP = "ok", "drop"

    def __init__(self):
        self._lock = threading.Lock()
        self._faults: Dict[str, List[dict]] = {}
        self.injected = 0

    def fail(self, op: str, count: int = 1,
             exc_factory: Optional[Callable[[], BaseException]] = None) -> None:
        self._arm(op, {"kind": "fail", "count": int(count),
                       "exc": exc_factory})

    def delay(self, op: str, count: int = 1, seconds: float = 0.05) -> None:
        self._arm(op, {"kind": "delay", "count": int(count),
                       "seconds": float(seconds)})

    def drop(self, op: str, count: int = 1) -> None:
        self._arm(op, {"kind": "drop", "count": int(count)})

    def reset(self) -> None:
        with self._lock:
            self._faults.clear()

    def armed(self, op: Optional[str] = None) -> bool:
        with self._lock:
            if op is None:
                return any(self._faults.values())
            return bool(self._faults.get(op))

    def _arm(self, op: str, fault: dict) -> None:
        with self._lock:
            self._faults.setdefault(op, []).append(fault)

    def check(self, op: str) -> str:
        """Consume one armed fault for `op`.  Returns OK or DROP; raises
        for fail faults (a ConnectionError by default, so the retry loop
        treats it exactly like a real transient wire error)."""
        with self._lock:
            queue = self._faults.get(op)
            if not queue:
                return self.OK
            fault = queue[0]
            fault["count"] -= 1
            if fault["count"] <= 0:
                queue.pop(0)
            self.injected += 1
        kind = fault["kind"]
        if kind == "delay":
            time.sleep(fault["seconds"])
            return self.OK
        if kind == "drop":
            return self.DROP
        exc_factory = fault.get("exc")
        raise (exc_factory() if exc_factory is not None
               else ConnectionError("injected fault: %s" % op))


class Heartbeat:
    """Rank-liveness monitor: a daemon thread calling ``probe()`` every
    ``interval_s`` seconds.  ``probe`` returns the list of peer ranks
    currently considered dead (SocketComm supplies a passive socket
    health check); newly dead ranks are logged once and published as the
    ``lgbm_comm_alive_ranks`` gauge, giving operators a liveness signal
    BEFORE the next collective blocks on the dead peer."""

    def __init__(self, probe: Callable[[], List[int]], interval_s: float,
                 rank: int = 0, world: int = 1, registry=None):
        self.probe = probe
        self.interval_s = max(float(interval_s), 1e-3)
        self.rank, self.world = int(rank), int(world)
        self._dead: set = set()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._gauge = None
        if registry is not None:
            self._gauge = registry.gauge(
                "lgbm_comm_alive_ranks",
                help="Ranks the heartbeat currently considers alive",
                rank=str(rank), world=str(world))
            self._gauge.set(world)

    def start(self) -> "Heartbeat":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="lgbm-heartbeat", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2 * self.interval_s + 1.0)
            self._thread = None

    def dead_ranks(self) -> List[int]:
        return sorted(self._dead)

    def alive(self) -> bool:
        return not self._dead

    def poll_once(self) -> List[int]:
        """One probe round (also what the thread loop runs)."""
        try:
            dead = set(self.probe())
        except Exception as exc:  # noqa: BLE001 — liveness must not raise
            log.debug("heartbeat probe failed: %s", exc)
            return self.dead_ranks()
        for r in sorted(dead - self._dead):
            log.warning("heartbeat: rank %d looks dead (peer socket "
                        "closed/errored)", r)
        self._dead = dead
        if self._gauge is not None:
            self._gauge.set(self.world - len(dead))
        return self.dead_ranks()

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.poll_once()
