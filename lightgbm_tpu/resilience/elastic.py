"""Elastic distributed training: degraded-world recovery supervisor.

The reference's network stack treats any rank death as fatal — every
surviving machine blocks in Allreduce until its socket times out and
the job is lost (network.cpp:64-243 has no membership protocol at
all).  Here the world is allowed to SHRINK: the supervisor wraps
``engine.train`` in a re-formation loop so a killed, hung or
partitioned rank costs one rejoin window and the rounds since the
last checkpoint, never the job.

One incarnation of the world = one ``parallel.distributed.ElasticComm``
generation:

1. form the world among the ranks still believed alive (the hub —
   lowest surviving original rank — anchors rank 0 of every
   incarnation);
2. re-shard the data-parallel row partition for the NEW (rank, world)
   with the same ``pre_partition_rows`` draw a fresh launch would use
   — deterministic given the topology — and run distributed find-bin
   so bin mappers stay identical across ranks;
3. resume from the newest checkpoint under ``tpu_checkpoint_path``
   via ``engine.train(resume_mode="reshard")``, which waives the
   dataset fingerprint (the shard changed with the world) and rebuilds
   the score plane from this rank's raw rows;
4. train; a per-round sync collective is the failure-propagation seam:
   when the liveness monitor fences a rank, every survivor's next
   collective raises WorldChangedError, the supervisor tears the comm
   down, marks the fenced ranks dead, and re-forms at generation+1.

The recovered run is deterministic given the new topology: same
checkpoint, same re-shard draw, same mappers.  It is NOT byte-identical
to an undisturbed run — the row partition changed — which is the
documented degraded-world promise (docs/Elasticity.md).

Scale-UP (``tpu_elastic_scale_up``): the world can also GROW back.  A
fenced rank does not exit — it petitions the live hub's formation
listener, which records the knock and answers ``wait`` (the
``FormationPending`` path: caught BEFORE the generic comm-failure
handler so a petitioner never convicts the live hub).  The hub's
policy engine — or ``ElasticComm.announce_epoch`` directly — declares
a formation epoch: every survivor raises ``WorldChangedError`` with
``epoch=True``, the supervisor shrinks ``known_dead`` by the readmit
set and re-forms at generation+1 WITHOUT burning a reform budget slot,
rows re-shard host-first back up to the full world, and training
resumes from the newest checkpoint via ``resume_mode="reshard"`` —
the same bitwise-deterministic recovery path as shrink, run in
reverse.  A petition that outlives ``tpu_elastic_scale_up_wait_s``
gives up with ``ElasticFenced``.

When ``tpu_policy`` is on, the hub incarnation additionally binds the
control-plane levers (``demote_host``, ``expand_world``) on the
process actuator for the policy engine (control/engine.py) — see
docs/ControlPlane.md for the action catalog.

Under the hybrid collective backend (parallel/hybrid.py) a wire rank
is a whole HOST, so everything above is host-granular: conviction
fences the host and every device behind it, ``min_world`` counts
hosts, and re-sharding is host-first (this loop) then device-second
(the grower's local shard_map).  The hub additionally watches the
per-round leader-phase waits (``ElasticComm.slow_hosts``) and marks a
host *slow* — gauge + ``hybrid_slow`` recorder event — rounds before
the heartbeat could convict it; ``tpu_hybrid_slow_policy=demote``
fences a host after ``tpu_hybrid_slow_rounds`` consecutive marks.

Chaos hooks: ``LGBM_TPU_CHAOS=kill:<orig_rank>:<round>`` (also
``exit:``/``slow:<orig>:<round>:<secs>``/``partition:<orig>:<round>``)
makes that rank injure itself at the start of that round of generation
0 — tools/chaos_run.py drives real multi-process scenarios with it.
``lag:<orig>:<round>:<secs>[:<until>]`` is the straggler drill: it
sleeps in the TRAIN thread every round from ``<round>`` on (stopping at
``<until>`` when given) while the control thread keeps answering pings,
so the host is marked slow but never convicted.
"""
from __future__ import annotations

import os
import signal
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..utils import log
from .checkpoint import CheckpointManager
from .comm import CommFailure, FaultInjector

CHAOS_ENV = "LGBM_TPU_CHAOS"


class ElasticAborted(RuntimeError):
    """Degraded-world recovery gave up: the world shrank below
    ``tpu_elastic_min_world``, re-formed more than
    ``tpu_elastic_max_reforms`` times, or failed to form at all."""


class ElasticFenced(ElasticAborted):
    """THIS rank was fenced by the survivors (missed the rejoin window
    or was convicted by the liveness monitor).  The process should exit
    quietly — the world has already moved on without it."""


@dataclass
class ElasticResult:
    """What one rank's supervisor run produced."""
    booster: Any                       # trained Booster (this rank's copy)
    orig_rank: int                     # machine-list rank of this process
    rank: int                          # rank in the FINAL incarnation
    world: int                         # final world size
    generation: int                    # final comm generation
    reforms: int                       # world re-formations survived
    dead_ranks: List[int] = field(default_factory=list)
    recovery_s: float = 0.0            # total failure->re-formed seconds


class ElasticSupervisor:
    """Degraded-world training supervisor for one rank.

    ``params`` is the ordinary train-parameter dict (must carry the
    topology: ``machines``/``machine_list_filename`` + ``num_machines``;
    ``tpu_checkpoint_path`` enables resume-on-re-form).  ``X``/``label``
    are the FULL dataset — every rank loads the same arrays and keeps
    only its partition, exactly like the fresh-launch pre-partition
    path, so a re-shard needs no data movement.

        sup = ElasticSupervisor(params, X, y, orig_rank=rank)
        result = sup.run()            # -> ElasticResult
    """

    def __init__(self, params: Dict[str, Any], X, label, *,
                 orig_rank: Optional[int] = None,
                 machines: Optional[List[str]] = None,
                 weight=None, group=None, init_score=None,
                 categorical_features: Sequence[int] = (),
                 num_boost_round: Optional[int] = None,
                 callbacks: Optional[list] = None,
                 port_offset: int = 1,
                 timeout_s: Optional[float] = None,
                 injector: Optional[FaultInjector] = None):
        from ..config import Config
        from ..parallel.distributed import parse_machines, resolve_rank
        self.params = dict(params)
        self.X = np.asarray(X)
        self.label = None if label is None else np.asarray(label)
        self.weight = weight
        self.group = group
        self.init_score = init_score
        self.categorical_features = tuple(categorical_features)
        self.callbacks = list(callbacks or [])
        self.port_offset = int(port_offset)
        self.injector = injector
        cfg = Config(self.params)
        self.cfg = cfg
        self.machines = (list(machines) if machines is not None
                         else parse_machines(cfg))
        if orig_rank is not None:
            self.orig_rank = int(orig_rank)
        elif cfg.machine_rank >= 0:
            self.orig_rank = int(cfg.machine_rank)
        else:
            self.orig_rank = resolve_rank(self.machines)
        self.num_boost_round = int(
            num_boost_round if num_boost_round is not None
            else cfg.num_iterations)
        self.timeout_s = float(
            timeout_s if timeout_s is not None
            else max(cfg.time_out, 1) * 1.0)
        self._chaos_fired = False
        self._metrics = None

    # -- public ---------------------------------------------------------
    def run(self) -> ElasticResult:
        """Train to ``num_boost_round`` rounds, surviving rank deaths.

        Raises ElasticFenced when THIS rank is voted out, ElasticAborted
        when the world cannot recover (too small / too many reforms /
        formation failure past the budget)."""
        from ..parallel.distributed import (ElasticComm, FormationPending,
                                            WorldChangedError)
        cfg = self.cfg
        max_reforms = max(0, int(getattr(cfg, "tpu_elastic_max_reforms", 3)))
        min_world = max(1, int(getattr(cfg, "tpu_elastic_min_world", 1)))
        scale_up = bool(getattr(cfg, "tpu_elastic_scale_up", False))
        petition_wait = float(
            getattr(cfg, "tpu_elastic_scale_up_wait_s", 60.0) or 60.0)
        petition_deadline: Optional[float] = None
        known_dead: set = set()
        generation = 0
        reforms = 0
        recovery_s = 0.0
        t_failure: Optional[float] = None
        while True:
            if self.orig_rank in known_dead:
                raise ElasticFenced(
                    "rank %d was fenced by the surviving world"
                    % self.orig_rank)
            alive = [r for r in range(len(self.machines))
                     if r not in known_dead]
            if len(alive) < min_world:
                raise ElasticAborted(
                    "world shrank to %d rank(s) < tpu_elastic_min_world=%d"
                    % (len(alive), min_world))
            comm = None
            try:
                comm = ElasticComm.from_config(
                    self.orig_rank, self.machines, cfg,
                    generation=generation, alive=alive,
                    timeout_s=self.timeout_s,
                    port_offset=self.port_offset,
                    injector=self.injector)
                generation = comm.generation
                petition_deadline = None
                if t_failure is not None:
                    dt = time.monotonic() - t_failure
                    recovery_s += dt
                    t_failure = None
                    log.warning("elastic: world re-formed at generation %d "
                                "(world %d) %.2fs after failure",
                                generation, comm.world, dt)
                    # recovery observable: the chaos drills bound the
                    # epoch -> rejoined gap with this event's timestamp
                    self._record(cfg, "rejoined", generation, comm.world,
                                 reforms, recovery_s)
                self._publish(generation, comm.world, reforms, recovery_s,
                              membership=getattr(comm, "membership", None))
                booster = self._train_once(comm)
                # final barrier: nobody tears the world down while a
                # peer is still inside its last sync collective
                comm.allgather({"type": "done", "orig": comm.orig_rank})
                result = ElasticResult(
                    booster=booster, orig_rank=self.orig_rank,
                    rank=comm.rank, world=comm.world,
                    generation=generation, reforms=reforms,
                    dead_ranks=sorted(known_dead), recovery_s=recovery_s)
                comm.close()
                self._record(cfg, "complete", generation, comm.world,
                             reforms, recovery_s)
                return result
            except WorldChangedError as exc:
                dead = set(int(r) for r in exc.dead_ranks)
                if getattr(exc, "epoch", False):
                    # deliberate scale-UP boundary (announce_epoch):
                    # nobody died — put the readmitted ranks back in the
                    # alive view and re-form one generation up.  Not a
                    # failure: no reform burned, no recovery clock.
                    readmit = set(int(r)
                                  for r in getattr(exc, "readmit", ()) or ())
                    known_dead -= readmit
                    if comm is not None:
                        try:
                            comm.close()
                        except OSError:
                            pass
                    log.warning("elastic: formation epoch at generation %d;"
                                " re-forming to admit rank(s) %s",
                                generation, sorted(readmit))
                    self._record(cfg, "epoch", generation,
                                 len(alive) + len(readmit - set(alive)),
                                 reforms, recovery_s,
                                 dead=sorted(known_dead))
                    generation += 1
                    continue
                if exc.fenced or self.orig_rank in dead:
                    if comm is not None:
                        comm.close()
                    if not scale_up:
                        raise ElasticFenced(
                            "rank %d fenced at generation %d: %s"
                            % (self.orig_rank, generation, exc)) from exc
                    # scale-up: instead of exiting, petition the
                    # surviving world to readmit us at the next
                    # formation epoch.  Drop our (stale) conviction
                    # view — the hub's ASSIGN is authoritative.
                    if petition_deadline is None:
                        petition_deadline = time.monotonic() + petition_wait
                    log.warning("elastic: rank %d fenced at generation %d; "
                                "petitioning to rejoin (scale-up)",
                                self.orig_rank, generation)
                    self._record(cfg, "petition", generation, 0,
                                 reforms, recovery_s)
                    known_dead = set()
                    if t_failure is None:
                        t_failure = time.monotonic()
                    generation += 1
                    continue
            except FormationPending as exc:
                # the hub is alive and mid-incarnation: our petition is
                # recorded.  No conviction, no reform burn — sleep and
                # re-knock until the next epoch's window (or the wait
                # budget) runs out.
                if petition_deadline is None:
                    petition_deadline = time.monotonic() + petition_wait
                if time.monotonic() >= petition_deadline:
                    raise ElasticFenced(
                        "rank %d rejoin petition expired after %.1fs "
                        "(tpu_elastic_scale_up_wait_s)"
                        % (self.orig_rank, petition_wait)) from exc
                log.debug("elastic: rejoin pending (%s); re-knocking",
                          str(exc).split("\n")[0][:120])
                if t_failure is None:
                    t_failure = time.monotonic()
                if getattr(exc, "woken", False):
                    # the hub pushed the epoch announcement down our
                    # parked petition connection: the join window is
                    # opening NOW — re-knock without sleeping.  The
                    # chaos drill asserts the epoch->wake gap this
                    # push keeps tight.
                    self._record(cfg, "petition_wake", generation, 0,
                                 reforms, recovery_s)
                else:
                    # no epoch wake within the petition poll — back off
                    # briefly before re-knocking
                    time.sleep(0.2)
                continue
            except (CommFailure, ConnectionError, OSError) as exc:
                # wire failure without a membership verdict.  For a spoke
                # that exhausted its hub sweep, the candidates it could
                # not reach are the dead set — marking them dead makes
                # this rank the hub of the next incarnation, so the
                # sweep converges instead of spinning.
                dead = set()
                if comm is None:
                    dead = {r for r in alive if r < self.orig_rank}
                log.warning("elastic: comm failure at generation %d (%s: "
                            "%s)", generation, type(exc).__name__,
                            str(exc).split("\n")[0][:200])
                if not dead and comm is not None:
                    # the wire failure raced the liveness verdict: give
                    # the heartbeat/poison one suspicion window to
                    # convict BEFORE tearing the world down, so every
                    # survivor re-forms with the same dead set instead
                    # of splitting on divergent alive views
                    dead = self._await_verdict(comm)
            if t_failure is None:
                t_failure = time.monotonic()
            if comm is not None:
                dead |= set(comm.fenced_ranks())
                try:
                    comm.close()
                except OSError:
                    pass
            if self.orig_rank in dead:
                raise ElasticFenced(
                    "rank %d fenced at generation %d (verdict arrived "
                    "after a wire failure)" % (self.orig_rank, generation))
            dead -= {self.orig_rank}
            if not dead:
                # a failure nobody was convicted for (e.g. hub formation
                # raced a dying spoke): burn one reform and retry with
                # the same alive view
                log.warning("elastic: no conviction for the failure; "
                            "retrying formation")
            known_dead |= dead
            reforms += 1
            self._record(cfg, "reform", generation, len(alive) - len(dead),
                         reforms, recovery_s, dead=sorted(known_dead))
            if reforms > max_reforms:
                raise ElasticAborted(
                    "gave up after %d re-formation(s) "
                    "(tpu_elastic_max_reforms=%d); dead ranks: %s"
                    % (reforms, max_reforms, sorted(known_dead)))
            generation += 1

    def _await_verdict(self, comm) -> set:
        """Poll the comm's membership verdict (heartbeat convictions on
        the hub, the hub's poison broadcast on spokes) for up to one
        suspicion window plus a few probes.  Returns the convicted set
        (possibly containing THIS rank — the caller turns that into
        ElasticFenced); empty when no verdict arrived in time."""
        wait = comm._suspect_s + 3.0 * comm._hb_interval
        deadline = time.monotonic() + wait
        while time.monotonic() < deadline:
            dead = set(comm.fenced_ranks())
            wc = comm.world_changed()
            if wc is not None:
                dead |= {int(r) for r in wc.dead_ranks}
                if wc.fenced:
                    dead.add(self.orig_rank)
            if dead:
                return dead
            time.sleep(min(comm._hb_interval, 0.05))
        return set()

    # -- one incarnation ------------------------------------------------
    def _train_once(self, comm):
        """Re-shard for the incarnation's (rank, world) and train, with
        the per-round sync collective wired in as a callback."""
        from ..basic import Dataset
        from ..config import Config
        from ..engine import train as engine_train
        from ..parallel.dist_data import construct_rank_shard, \
            pre_partition_rows
        params = dict(self.params)
        params["machine_rank"] = comm.rank
        params["num_machines"] = comm.world
        params.pop("machines", None)
        params.pop("machine_list_filename", None)
        cfg = Config(params)
        shard = construct_rank_shard(
            self.X, cfg, comm.rank, comm.world, comm,
            label=self.label, group=self.group, weight=self.weight,
            init_score=self.init_score,
            categorical_features=self.categorical_features,
            pre_partition=True)
        # the raw rows of the SAME draw ride on the Dataset: the elastic
        # restore rebuilds the score plane from them (restore_elastic)
        qb = None
        if self.group is not None:
            qb = np.concatenate([[0], np.cumsum(np.asarray(self.group))])
        keep, _ = pre_partition_rows(len(self.X), comm.rank, comm.world,
                                     qb, seed=cfg.data_random_seed)
        ds = Dataset(self.X[keep], params=params)
        ds._binned = shard
        resume = None
        if cfg.tpu_checkpoint_path:
            resume = CheckpointManager.latest(cfg.tpu_checkpoint_path)
            if resume is not None:
                log.info("elastic: rank %d/%d resuming from %s",
                         comm.rank, comm.world, resume)
        cbs = [self._sync_callback(comm, cfg)] + list(self.callbacks)
        # make this incarnation's fenced comm visible to the Collective
        # backend resolver: tpu_comm_backend=socket rides THIS comm (so
        # training collectives inherit its retry/heartbeat/generation
        # fencing), and a torn-down world never leaks into the next one
        from ..parallel import collective as coll_mod
        levers = self._bind_policy_levers(comm)
        coll_mod.set_process_comm(comm)
        try:
            return engine_train(params, ds,
                                num_boost_round=self.num_boost_round,
                                resume_from=resume,
                                resume_mode="reshard" if resume else "strict",
                                callbacks=cbs)
        finally:
            coll_mod.set_process_comm(None)
            if levers:
                from ..control import default_actuator
                act = default_actuator()
                for name, fn in levers:
                    act.unbind(name, fn)

    def _bind_policy_levers(self, comm):
        """Hub-side control-plane levers for THIS incarnation: the
        policy engine (ticked by the federation hub, obs/federation.py)
        dispatches by name through the process actuator; the comm
        object changes every re-formation, so the bindings are made
        here and dropped in ``_train_once``'s finally.  Returns the
        (name, fn) pairs to unbind, or None when policy is off or this
        rank is not the hub."""
        if not bool(getattr(self.cfg, "tpu_policy", False)) \
                or comm.rank != 0 or comm.world <= 1:
            return None
        from ..control import default_actuator
        min_world = max(1, int(getattr(self.cfg,
                                       "tpu_elastic_min_world", 1)))

        def demote_host(args):
            orig = int(args["orig"])
            if orig == comm.membership[0]:
                raise ValueError("refusing to demote the hub (orig %d)"
                                 % orig)
            if orig not in comm.membership:
                raise ValueError("orig %d is not in the current formation"
                                 % orig)
            if comm.world - 1 < min_world:
                raise ValueError(
                    "demote would shrink the world below "
                    "tpu_elastic_min_world=%d" % min_world)
            comm._fence({orig})
            return "fenced %d" % orig

        def expand_world(args):
            if not getattr(comm, "scale_up", False):
                raise ValueError("tpu_elastic_scale_up is off")
            pend = set(comm.pending_joiners())
            want = [int(r) for r in (args.get("readmit") or [])]
            readmit = sorted(set(want) & pend) or sorted(pend)
            if not readmit:
                raise ValueError("no pending joiners to admit")
            comm.announce_epoch(readmit)
            return "epoch admit %s" % readmit

        act = default_actuator()
        levers = [("demote_host", demote_host),
                  ("expand_world", expand_world)]
        for name, fn in levers:
            act.bind(name, fn)
        return levers

    def _sync_callback(self, comm, cfg):
        """The failure-propagation seam: a tiny allgather every
        ``tpu_elastic_sync_every`` rounds.  A fenced world turns the
        next sync into WorldChangedError on every survivor, bounding
        how far ranks can drift past a failure."""
        every = max(1, int(getattr(cfg, "tpu_elastic_sync_every", 1)))

        slow_ms = float(getattr(cfg, "tpu_hybrid_slow_ms", 0.0))
        slow_rounds = max(1, int(getattr(cfg, "tpu_hybrid_slow_rounds", 3)))
        slow_policy = str(getattr(cfg, "tpu_hybrid_slow_policy", "observe"))
        slow_counts: Dict[int, int] = {}

        def _callback(env) -> None:
            self._maybe_chaos(comm, env.iteration)
            wc = comm.world_changed()
            if wc is not None:
                raise wc
            if env.iteration % every:
                return
            comm.allgather({"type": "sync", "round": env.iteration,
                            "orig": comm.orig_rank,
                            "generation": comm.generation})
            if slow_ms > 0 and comm.rank == 0:
                self._check_stragglers(comm, cfg, env.iteration,
                                       slow_ms / 1e3, slow_rounds,
                                       slow_policy, slow_counts)

        _callback.before_iteration = True
        _callback.order = 1     # right after preemption (0)
        return _callback

    def _check_stragglers(self, comm, cfg, round_idx: int,
                          threshold_s: float, slow_rounds: int,
                          policy: str, counts: Dict[int, int]) -> None:
        """Hub-side straggler policy: a host whose leader-phase wait in
        the sync allgather exceeded the threshold is marked *slow*
        (per-host gauge + ``hybrid_slow`` recorder event) — observable
        rounds before heartbeat conviction could fire, since a straggler
        still answers pings.  After ``slow_rounds`` CONSECUTIVE marks
        the ``demote`` policy fences the host exactly like a liveness
        conviction (the survivors re-form without it); ``observe``
        keeps emitting telemetry only."""
        slow = set(comm.slow_hosts(threshold_s))
        for orig in [o for o in counts if o not in slow]:
            counts.pop(orig)
            self._publish_host(orig, up=1, slow=0)
        for orig in sorted(slow):
            counts[orig] = counts.get(orig, 0) + 1
            self._publish_host(orig, up=1, slow=counts[orig])
            log.warning("elastic: host %d slow at round %d (%d consecutive "
                        "round(s) over the %.0f ms leader-phase threshold)",
                        orig, round_idx, counts[orig], threshold_s * 1e3)
            try:
                from ..obs.recorder import elastic_event
                elastic_event(cfg, "hybrid_slow", orig_rank=self.orig_rank,
                              slow_host=orig, rounds=counts[orig],
                              round=round_idx, generation=comm.generation,
                              policy=policy)
            except Exception as exc:   # noqa: BLE001
                log.debug("hybrid_slow telemetry event failed: %s", exc)
            if counts[orig] >= slow_rounds and policy == "demote":
                log.warning("elastic: demoting straggler host %d after %d "
                            "consecutive slow round(s)", orig, counts[orig])
                comm._fence({orig})

    # -- chaos ----------------------------------------------------------
    def _maybe_chaos(self, comm, round_idx: int) -> None:
        """Self-inflicted failures for chaos testing, armed by the
        LGBM_TPU_CHAOS env var (generation 0 only, once per process)."""
        spec = os.environ.get(CHAOS_ENV)
        if not spec or self._chaos_fired or comm.generation != 0:
            return
        try:
            parts = spec.split(":")
            kind, target, at = parts[0], int(parts[1]), int(parts[2])
        except (ValueError, IndexError):
            log.warning("unparseable %s=%r (want kind:rank:round[:secs])",
                        CHAOS_ENV, spec)
            return
        if comm.orig_rank != target or round_idx < at:
            return
        if kind == "lag":
            # straggler injection: delay the TRAIN thread only — the
            # spoke's control thread keeps answering pings, so the host
            # is marked *slow* by the hub's leader-phase timer but never
            # convicted.  Fires every round from `at` on (no
            # _chaos_fired), unlike the one-shot kinds.  An optional 5th
            # field bounds it — lag:<orig>:<at>:<secs>:<until> stops at
            # round `until` so alert-clear drills can watch recovery.
            secs = float(parts[3]) if len(parts) > 3 else 0.5
            if len(parts) > 4 and round_idx >= int(parts[4]):
                return
            log.warning("chaos: lag %.2fs on rank %d at round %d",
                        secs, comm.orig_rank, round_idx)
            # yield as soon as the world moved on without us: a fenced
            # host has nothing left to be slow AT, and the scale-up
            # petition timing should be bounded by the heartbeat, not
            # by the injected lag
            deadline = time.monotonic() + secs
            while (time.monotonic() < deadline
                    and comm.world_changed() is None):
                time.sleep(0.05)
            return
        self._chaos_fired = True
        log.warning("chaos: %s on rank %d at round %d", kind,
                    comm.orig_rank, round_idx)
        if kind == "kill":
            os.kill(os.getpid(), signal.SIGKILL)
            time.sleep(60)      # pragma: no cover — SIGKILL landed
        elif kind == "exit":
            os._exit(17)
        elif kind in ("slow", "partition"):
            # a hang/partition from the world's point of view: stop
            # answering pings long enough for conviction (slow ranks
            # resume and find themselves fenced)
            secs = float(parts[3]) if len(parts) > 3 else 30.0
            if comm._ctrl_sock is not None and kind == "partition":
                from ..parallel.distributed import _shutdown
                _shutdown(comm._ctrl_sock)
            comm._ctrl_stop.set()       # stop answering hub pings
            time.sleep(secs)
        else:
            log.warning("unknown chaos kind %r", kind)

    # -- observability ---------------------------------------------------
    def _publish(self, generation: int, world: int, reforms: int,
                 recovery_s: float, membership=None) -> None:
        try:
            from ..obs.adapters import ensure_elastic_metrics
            from ..obs import default_registry
            m = ensure_elastic_metrics(default_registry(),
                                       rank=self.orig_rank)
            m["generation"].set(generation)
            m["world"].set(world)
            m["reforms"].set(reforms)
            m["recovery_s"].set(recovery_s)
        except Exception as exc:   # noqa: BLE001 — metrics never break
            log.debug("elastic metrics publish failed: %s", exc)
        if membership is not None:
            # per-host liveness: 1 while in the formation, 0 once
            # fenced out; a fresh formation also clears the straggler
            # counters (the slow host may have recovered or left)
            alive = set(membership)
            for orig in range(len(self.machines)):
                self._publish_host(orig, up=int(orig in alive), slow=0)

    def _publish_host(self, orig: int, up: int, slow: int) -> None:
        try:
            from ..obs.adapters import ensure_hybrid_metrics
            from ..obs import default_registry
            m = ensure_hybrid_metrics(default_registry(), host=orig)
            m["up"].set(up)
            m["slow"].set(slow)
        except Exception as exc:   # noqa: BLE001
            log.debug("hybrid host gauge publish failed: %s", exc)

    def _record(self, cfg, what: str, generation: int, world: int,
                reforms: int, recovery_s: float, dead=None) -> None:
        """One elastic lifecycle event into the telemetry JSONL (when
        tpu_telemetry_path is configured); best-effort."""
        try:
            from ..obs.recorder import elastic_event
            elastic_event(cfg, what, orig_rank=self.orig_rank,
                          generation=generation, world=world,
                          reforms=reforms, recovery_s=round(recovery_s, 4),
                          dead_ranks=dead or [])
        except Exception as exc:   # noqa: BLE001
            log.debug("elastic telemetry event failed: %s", exc)
