"""Continuous-learning supervisor: the loop that keeps a served model
fresh without ever serving a silently-worse one.

    ingest ──> bounded validated buffer (crash-safe spool)
                      │  tpu_refit_interval_s AND tpu_refit_min_rows
                      v
    REFIT:  candidate = Booster.refit(buffer)        (tpu_refit_mode=refit)
            or live trees + init_model continuation  (tpu_refit_mode=continue)
                      │  candidate persisted, spool trimmed
                      v
    SHADOW: mirror served traffic onto the candidate (serving/shadow.py)
            + paired loss on the held-out label window
                      │  delta >= tpu_promote_min_delta over
                      │  >= tpu_promote_min_samples held-out rows
                      v
    PROMOTE: registry hot-swap (version advances)       else: discard
                      │
                      v
    WATCH:  live loss on FRESH held-out rows for tpu_promote_watch_s
                      │  breach of baseline + tpu_promote_rollback_delta
                      v
    ROLLBACK: registry reinstalls the prior version, loop returns to idle

Crash consistency: every accepted ingest block is spooled to disk
(`supervisor_spool/seg_*.npz`) BEFORE it is acknowledged, and segments
are deleted only after a candidate built from them has been persisted —
so a SIGKILL anywhere in the loop (the `kill_refit` chaos drill lands
one mid-refit) loses zero ingested rows.  The supervisor's own state
rides `SUPERVISOR.json` next to the spool, written with the same
atomic temp+fsync+replace sequence as model files.  Serving is never
gated on any of this: the live model keeps answering through refit,
kill, resume, promote and rollback alike.

The tick() state machine is synchronous and single-threaded by
construction (one `_tick_lock` serializes tick and force_promote), so
the unit tests drive it without threads; start() merely runs tick on a
daemon loop.
"""
from __future__ import annotations

import glob
import json
import os
import signal
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from .. import engine
from ..basic import Booster, Dataset
from ..config import Config
from ..io.dataset import IngestError, validate_ingest_block
from ..io.file_io import atomic_write_text
from ..obs import default_registry
from ..obs.recorder import supervisor_event
from ..utils import log

SPOOL_DIR = "supervisor_spool"
STATE_FILE = "SUPERVISOR.json"
CANDIDATE_FILE = "candidate.txt"

IDLE, REFIT, SHADOW, WATCH = "idle", "refit", "shadow", "watch"


def _shed_overflow(rows: int) -> None:
    default_registry().counter(
        "lgbm_ingest_shed_total",
        help="ingest rows shed at the validation boundary",
        reason="overflow").inc(rows)


class IngestBuffer:
    """Bounded, validated, crash-safe buffer of fresh labeled rows.

    Accepted blocks are split row-wise into a TRAINING part and a
    HELD-OUT part (`holdout_fraction`, never trained on — the shadow
    metric window).  Each accepted block becomes one numbered spool
    segment on disk; `discard_upto(seq)` removes segments only after the
    caller has durably consumed them.  Over `capacity` training rows the
    OLDEST blocks are shed (with the overflow counter) — ingest pressure
    degrades freshness, never the process."""

    def __init__(self, num_features: int, capacity: int,
                 holdout_fraction: float, spool_dir: Optional[str] = None,
                 window_rows: int = 4096, seed: int = 0):
        self.num_features = int(num_features)
        self.capacity = max(1, int(capacity))
        self.holdout_fraction = float(holdout_fraction)
        self.window_rows = max(1, int(window_rows))
        self.spool_dir = spool_dir
        self._rng = np.random.RandomState(seed)
        self._lock = threading.Lock()
        self._seq = 0                      # next segment number
        self._blocks: List[Dict] = []      # pending TRAIN blocks
        self._window: List[Dict] = []      # held-out eval blocks
        self._shed_overflow_rows = 0
        if spool_dir:
            os.makedirs(spool_dir, exist_ok=True)

    # -- ingest --------------------------------------------------------- #
    def add(self, X, label=None, weight=None) -> int:
        """Validate, spool and buffer one block; rows with NaN/inf
        labels are shed (counted), block-level malformations raise
        IngestError.  Returns the number of ACCEPTED rows."""
        X, y, w = validate_ingest_block(
            X, label, weight, num_features=self.num_features, shed=True)
        n = int(X.shape[0])
        if n == 0:
            return 0
        hold = self._rng.random_sample(n) < self.holdout_fraction
        keep = ~hold
        with self._lock:
            seq = self._seq
            self._seq += 1
            if keep.any():
                blk = {"seq": seq, "X": X[keep],
                       "y": y[keep] if y is not None else None,
                       "w": w[keep] if w is not None else None}
                self._spool_write("seg", blk)
                self._blocks.append(blk)
            if hold.any() and y is not None:
                blk = {"seq": seq, "X": X[hold], "y": y[hold],
                       "w": w[hold] if w is not None else None}
                self._spool_write("win", blk)
                self._window.append(blk)
            self._trim_locked()
        return n

    def _trim_locked(self) -> None:
        # every caller holds self._lock (the _locked suffix contract)
        while (len(self._blocks) > 1
               and sum(b["X"].shape[0] for b in self._blocks)
               > self.capacity):
            dead = self._blocks.pop(0)  # tpulint: ok=lock-unguarded-write
            self._shed_overflow_rows += dead["X"].shape[0]  # tpulint: ok=lock-unguarded-write
            _shed_overflow(dead["X"].shape[0])
            self._spool_unlink("seg", dead["seq"])
        while (len(self._window) > 1
               and sum(b["X"].shape[0] for b in self._window)
               > self.window_rows):
            dead = self._window.pop(0)  # tpulint: ok=lock-unguarded-write
            self._spool_unlink("win", dead["seq"])

    # -- spool ---------------------------------------------------------- #
    # Two segment families: "seg" (training rows, deleted once a
    # candidate built from them is persisted) and "win" (held-out metric
    # rows, deleted when trimmed out of the window) — so a SIGKILL loses
    # neither the next refit's data nor the shadow verdict's window.
    def _seg_path(self, kind: str, seq: int) -> str:
        return os.path.join(self.spool_dir, "%s_%08d.npz" % (kind, seq))

    def _spool_write(self, kind: str, blk: Dict) -> None:
        if not self.spool_dir:
            return
        path = self._seg_path(kind, blk["seq"])
        tmp = path + ".tmp"
        y, w = blk["y"], blk["w"]
        with open(tmp, "wb") as f:
            np.savez(f, X=blk["X"],
                     y=y if y is not None else np.zeros(0),
                     has_y=np.array(y is not None),
                     w=w if w is not None else np.zeros(0),
                     has_w=np.array(w is not None))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def _spool_unlink(self, kind: str, seq: int) -> None:
        if not self.spool_dir:
            return
        try:
            os.unlink(self._seg_path(kind, seq))
        except OSError:
            pass

    def _spool_read(self, path: str) -> Optional[Dict]:
        try:
            with np.load(path) as z:
                return {
                    "seq": int(os.path.basename(path)[4:-4]),
                    "X": z["X"],
                    "y": z["y"] if bool(z["has_y"]) else None,
                    "w": z["w"] if bool(z["has_w"]) else None}
        except Exception as exc:  # noqa: BLE001 — torn tail segment
            log.warning("supervisor: dropping unreadable spool segment "
                        "%s (%s)", path, exc)
            return None

    def restore(self, consumed_upto: int = -1) -> int:
        """Rebuild the buffer from spool segments.  Training segments
        with seq <= `consumed_upto` were consumed by a persisted
        candidate and are deleted; window segments always reload (the
        shadow verdict must survive a kill too).  Returns restored
        training-row count."""
        if not self.spool_dir:
            return 0
        restored = 0
        with self._lock:
            for path in sorted(glob.glob(
                    os.path.join(self.spool_dir, "seg_*.npz"))):
                seq = int(os.path.basename(path)[4:-4])
                if seq <= consumed_upto:
                    os.unlink(path)
                    continue
                blk = self._spool_read(path)
                if blk is None:
                    continue
                self._blocks.append(blk)
                self._seq = max(self._seq, seq + 1)
                restored += int(blk["X"].shape[0])
            for path in sorted(glob.glob(
                    os.path.join(self.spool_dir, "win_*.npz"))):
                blk = self._spool_read(path)
                if blk is None or blk["y"] is None:
                    continue
                self._window.append(blk)
                self._seq = max(self._seq, blk["seq"] + 1)
            self._trim_locked()
        return restored

    # -- consumption ---------------------------------------------------- #
    def train_rows(self) -> int:
        with self._lock:
            return sum(b["X"].shape[0] for b in self._blocks)

    def window_rows_count(self, after_seq: int = -1) -> int:
        with self._lock:
            return sum(b["X"].shape[0] for b in self._window
                       if b["seq"] > after_seq)

    def current_seq(self) -> int:
        with self._lock:
            return self._seq - 1

    def take_training(self):
        """Snapshot every pending training block: (X, y, w, upto_seq).
        Blocks stay buffered (and spooled) until discard_upto — a kill
        between here and candidate persistence replays them."""
        with self._lock:
            blocks = list(self._blocks)
        if not blocks:
            return None
        X = np.vstack([b["X"] for b in blocks])
        n = X.shape[0]
        y = (np.concatenate([np.zeros(b["X"].shape[0])
                             if b["y"] is None else b["y"] for b in blocks])
             if any(b["y"] is not None for b in blocks) else None)
        w = (np.concatenate([np.ones(b["X"].shape[0])
                             if b["w"] is None else b["w"] for b in blocks])
             if any(b["w"] is not None for b in blocks) else None)
        return X, y, w, max(b["seq"] for b in blocks)

    def window(self, after_seq: int = -1):
        """The held-out metric window (optionally only rows newer than
        `after_seq` — the WATCH phase's freshness cut)."""
        with self._lock:
            blocks = [b for b in self._window if b["seq"] > after_seq]
        if not blocks:
            return None
        X = np.vstack([b["X"] for b in blocks])
        y = np.concatenate([b["y"] for b in blocks])
        w = (np.concatenate([np.ones(b["X"].shape[0])
                             if b["w"] is None else b["w"] for b in blocks])
             if any(b["w"] is not None for b in blocks) else None)
        return X, y, w

    def discard_upto(self, seq: int) -> None:
        """Drop consumed training blocks and their spool segments.
        Window blocks up to `seq` stay in memory (still useful for the
        shadow metric) but lose crash persistence — acceptable, the
        window is advisory."""
        with self._lock:
            self._blocks = [b for b in self._blocks if b["seq"] > seq]
            if self.spool_dir:
                for path in glob.glob(
                        os.path.join(self.spool_dir, "seg_*.npz")):
                    if int(os.path.basename(path)[4:-4]) <= seq:
                        try:
                            os.unlink(path)
                        except OSError:
                            pass

    def shed_overflow_rows(self) -> int:
        with self._lock:
            return self._shed_overflow_rows


def _loss(booster, X, y, w, objective: str) -> float:
    """Held-out quality metric: logloss on probabilities for binary and
    multiclass objectives, weighted MSE otherwise — enough signal to
    rank live vs candidate, cheap enough to run every tick."""
    pred = np.asarray(booster._gbdt.predict(X, device=False), np.float64)
    y = np.asarray(y, np.float64)
    wt = np.ones(len(y)) if w is None else np.asarray(w, np.float64)
    wsum = max(float(wt.sum()), 1e-12)
    if pred.ndim == 2:     # multiclass probabilities [n, k]
        k = pred.shape[1]
        p = np.clip(pred[np.arange(len(y)), y.astype(np.int64) % k],
                    1e-12, 1.0)
        return float(-(wt * np.log(p)).sum() / wsum)
    pred = pred.reshape(-1)
    if objective.startswith("binary"):
        p = np.clip(pred, 1e-12, 1 - 1e-12)
        return float(-(wt * (y * np.log(p)
                             + (1 - y) * np.log(1 - p))).sum() / wsum)
    d = pred - y
    return float((wt * d * d).sum() / wsum)


class ContinuousLearningSupervisor:
    """Drives one served model name through the refit -> shadow ->
    promote -> watch -> rollback loop against a `serving.Server`."""

    def __init__(self, server, config: Optional[Config] = None,
                 model_name: Optional[str] = None,
                 train_params: Optional[Dict] = None,
                 base_dataset: Optional[Dataset] = None, **overrides):
        if isinstance(config, Config) and not overrides:
            cfg = config
        elif isinstance(config, Config):
            cfg = Config(dict(config.raw_params, **overrides))
        else:
            cfg = Config(dict(config or {}, **overrides))
        self.config = cfg
        self.server = server
        self.name = model_name or cfg.serve_model_name
        self.base_dataset = base_dataset
        entry = server.registry.get(self.name)
        self.train_params = dict(train_params
                                 or getattr(entry.booster, "params", None)
                                 or {})
        # the candidate trains serially, in-process, and must not write
        # over the serving checkpoints or recurse into the supervisor
        for k in ("machines", "machine_list_filename", "num_machines",
                  "tpu_elastic", "tpu_continuous_learning",
                  "tpu_checkpoint_path", "tpu_telemetry_path", "task"):
            self.train_params.pop(k, None)
        self.train_params.setdefault("verbosity", -1)
        self.root = cfg.tpu_checkpoint_path or os.path.join(
            ".", "lgbm_supervisor")
        os.makedirs(self.root, exist_ok=True)
        self.buffer = IngestBuffer(
            num_features=entry.num_features,
            capacity=cfg.tpu_refit_buffer_rows,
            holdout_fraction=cfg.tpu_refit_holdout_fraction,
            spool_dir=os.path.join(self.root, SPOOL_DIR),
            window_rows=max(4 * cfg.tpu_promote_min_samples, 1024),
            seed=cfg.seed if cfg.seed else 0)
        # _tick_lock serializes the state machine (tick / force_promote);
        # _state_lock guards the fields snapshot() reads.  Heavy work
        # (training, loads) runs under _tick_lock only.
        self._tick_lock = threading.Lock()
        self._state_lock = threading.Lock()
        self.state = IDLE
        self._last_refit_t = time.monotonic()
        self._refits = 0
        self._promotes = 0
        self._rollbacks = 0
        self._candidate: Optional[Booster] = None
        self._cand_built_t: Optional[float] = None
        self._cand_consumed_upto = -1
        self._mirror = None
        self._shadow_deadline: Optional[float] = None
        self._last_shadow: Optional[Dict] = None
        self._baseline: Optional[float] = None
        self._watch_deadline: Optional[float] = None
        self._watch_from_seq = -1
        self._stop_event = threading.Event()
        self._thread: Optional[threading.Thread] = None
        obj = str(self.train_params.get("objective") or "")
        if not obj:
            g = getattr(entry.booster, "_gbdt", None)
            if g is not None and g.objective is not None:
                obj = g.objective.to_string()
        self.objective = obj or str(cfg.objective or "regression")
        reg = default_registry()
        reg.gauge("lgbm_supervisor_buffer_rows",
                  help="Ingested rows buffered for the next refit",
                  model=self.name).set_fn(self.buffer.train_rows)
        reg.gauge("lgbm_supervisor_candidate_age_s",
                  help="Age of the current shadow candidate",
                  model=self.name).set_fn(self._candidate_age)
        self._shadow_gauge = reg.gauge(
            "lgbm_supervisor_shadow_delta",
            help="Last shadow eval: live loss minus candidate loss",
            model=self.name)
        self._restore()
        server.attach_supervisor(self)
        self._policy_levers = self._bind_policy_levers()

    def _bind_policy_levers(self):
        """Control-plane lever: the policy engine reacts to a
        ``supervisor_rollbacks`` burn-rate alert by tightening the
        promote floor, so a regressing refit stream has to clear a
        higher quality bar before the next promote.  Mutates
        ``self.config.tpu_promote_min_delta``, which ``_tick_shadow``
        reads fresh every tick.  Returns the (name, fn) pairs so
        ``stop()`` can unbind them."""
        if not bool(getattr(self.config, "tpu_policy", False)):
            return None
        from ..control import default_actuator

        def tighten_promote_floor(args):
            factor = float(args.get("factor", 2.0))
            floor = float(args.get("min_delta", 0.0))
            old = float(self.config.tpu_promote_min_delta)
            new = max(old * factor, floor)
            self.config.tpu_promote_min_delta = new
            return "promote floor %.6g -> %.6g" % (old, new)

        act = default_actuator()
        levers = [("tighten_promote_floor", tighten_promote_floor)]
        for name, fn in levers:
            act.bind(name, fn)
        return levers

    # -- ingest (HTTP + in-process edge) -------------------------------- #
    def ingest(self, rows, labels=None, weights=None):
        """Feed fresh labeled rows.  Returns (accepted, shed); malformed
        blocks/rows are shed with the obs counter, never an exception —
        a poisoned producer cannot crash the loop."""
        try:
            X = np.asarray(rows, np.float64)
            n_in = int(X.shape[0]) if X.ndim == 2 else 1
            # IngestBuffer serializes internally; no supervisor lock here
            accepted = self.buffer.add(  # tpulint: ok=lock-unguarded-write
                X, labels, weights)
            return accepted, n_in - accepted
        except (IngestError, ValueError, TypeError) as exc:
            try:
                n_in = int(np.asarray(rows, np.float64).shape[0])
            except Exception:  # noqa: BLE001 — unparseable payload
                n_in = 0
            log.warning("supervisor: shed ingest block (%s)", exc)
            return 0, n_in

    # -- lifecycle ------------------------------------------------------ #
    def start(self, poll_s: Optional[float] = None) -> None:
        poll = poll_s if poll_s is not None else min(
            1.0, self.config.tpu_refit_interval_s / 4.0)

        def _loop():
            while not self._stop_event.wait(poll):
                try:
                    self.tick()
                except Exception as exc:  # noqa: BLE001 — loop must survive
                    log.warning("supervisor tick failed: %s", exc)
        with self._state_lock:
            if self._thread is not None:
                return
            self._stop_event.clear()
            self._thread = thread = threading.Thread(
                target=_loop, name="lgbm-supervisor", daemon=True)
        thread.start()

    def stop(self, timeout_s: float = 10.0) -> None:
        self._stop_event.set()
        with self._state_lock:
            thread, self._thread = self._thread, None
            mirror, self._mirror = self._mirror, None
            levers, self._policy_levers = self._policy_levers, None
        if thread is not None:
            thread.join(timeout=timeout_s)
        if mirror is not None:
            self.server.detach_shadow(self.name)
        if levers:
            from ..control import default_actuator
            act = default_actuator()
            for name, fn in levers:
                act.unbind(name, fn)

    # -- the state machine ---------------------------------------------- #
    def tick(self, now: Optional[float] = None) -> str:
        """One synchronous step; returns the state after the step."""
        with self._tick_lock:
            now = time.monotonic() if now is None else now
            state = self.state
            if state == IDLE:
                self._tick_idle(now)
            elif state == SHADOW:
                self._tick_shadow(now)
            elif state == WATCH:
                self._tick_watch(now)
            return self.state

    def _set_state(self, state: str) -> None:
        with self._state_lock:
            self.state = state

    def _tick_idle(self, now: float) -> None:
        cfg = self.config
        if now - self._last_refit_t < cfg.tpu_refit_interval_s:
            return
        if self.buffer.train_rows() < cfg.tpu_refit_min_rows:
            return
        self._build_candidate(now)

    def _build_candidate(self, now: float) -> None:
        cfg = self.config
        self._set_state(REFIT)
        self._persist()
        taken = self.buffer.take_training()
        if taken is None:
            self._set_state(IDLE)
            return
        X, y, w, upto = taken
        self._chaos_kill_refit()
        live = self.server.registry.get(self.name)
        t0 = time.monotonic()
        try:
            if cfg.tpu_refit_mode == "continue":
                cand = self._continue_candidate(live.booster, X, y, w)
            else:
                cand = live.booster.refit(
                    X, y, decay_rate=cfg.refit_decay_rate, weight=w)
        except Exception as exc:  # noqa: BLE001 — a bad refit sheds, not dies
            log.warning("supervisor: candidate build failed (%s); rows stay "
                        "buffered for the next interval", exc)
            with self._state_lock:
                self._last_refit_t = now
                self.state = IDLE
            self._persist()
            return
        cand._gbdt._sync_model()
        cand_str = cand.model_to_string()
        # durability order: candidate first, then the watermark, then the
        # spool trim — a kill between any two steps replays, never loses
        atomic_write_text(os.path.join(self.root, CANDIDATE_FILE), cand_str)
        with self._state_lock:
            self._candidate = cand
            self._cand_built_t = time.monotonic()
            self._cand_consumed_upto = upto
            self._refits += 1
            self._last_refit_t = now
            self.state = SHADOW
            self._shadow_deadline = now + 20.0 * cfg.tpu_refit_interval_s
            self._last_shadow = None
        self._persist()
        self.buffer.discard_upto(upto)
        self._attach_mirror(cand)
        default_registry().counter(
            "lgbm_supervisor_refits_total",
            help="Candidate models built by the supervisor",
            model=self.name).inc()
        supervisor_event(self.config, "refit", model=self.name,
                         mode=cfg.tpu_refit_mode, rows=int(X.shape[0]),
                         live_version=live.version,
                         num_trees=cand.num_trees(),
                         build_s=round(time.monotonic() - t0, 3))

    def _continue_candidate(self, live_booster: Booster, X, y, w) -> Booster:
        """Continued training: new trees fit on the buffer with the live
        model's raw predictions as init_score, then grafted onto a copy
        of the live ensemble (raw scores add exactly, so the merged model
        is servable standalone — engine.train's init_model output alone
        carries only the NEW trees)."""
        cfg = self.config
        params = dict(self.train_params)
        ref = self.base_dataset if (
            self.base_dataset is not None
            and getattr(self.base_dataset, "_binned", None) is not None) \
            else None
        ds = Dataset(X, label=y, weight=w, params=params, reference=ref)
        new = engine.train(params, ds,
                           num_boost_round=cfg.tpu_refit_rounds,
                           init_model=live_booster, verbose_eval=False)
        new._gbdt._sync_model()
        merged = Booster(model_str=live_booster.model_to_string(),
                         params=params)
        merged._gbdt.models.extend(new._gbdt.models)
        return merged

    def _attach_mirror(self, cand: Booster) -> None:
        from ..serving.shadow import ShadowMirror
        mirror = ShadowMirror(self.name, cand)
        with self._state_lock:
            self._mirror = mirror
        self.server.attach_shadow(self.name, mirror)

    def _tick_shadow(self, now: float) -> None:
        cfg = self.config
        win = self.buffer.window()
        samples = 0 if win is None else int(win[0].shape[0])
        if samples < cfg.tpu_promote_min_samples:
            if (self._shadow_deadline is not None
                    and now > self._shadow_deadline):
                self._reject("shadow_window_starved", samples)
            return
        X, y, w = win
        live = self.server.registry.get(self.name)
        live_loss = _loss(live.booster, X, y, w, self.objective)
        cand_loss = _loss(self._candidate, X, y, w, self.objective)
        delta = live_loss - cand_loss
        mirror_snap = self._mirror.snapshot() if self._mirror else None
        with self._state_lock:
            self._last_shadow = {
                "samples": samples, "live_loss": live_loss,
                "cand_loss": cand_loss, "delta": delta,
                "mirror": mirror_snap}
        self._shadow_gauge.set(delta)
        supervisor_event(self.config, "shadow", model=self.name,
                         samples=samples, live_loss=live_loss,
                         cand_loss=cand_loss, delta=delta,
                         mirror_rows=(mirror_snap or {}).get("rows", 0))
        if delta > cfg.tpu_promote_min_delta:
            self._promote(live, live_loss, now)
        else:
            self._reject("below_floor", samples, delta=delta)

    def _promote(self, live_entry, live_loss: float, now: float,
                 forced: bool = False) -> None:
        cfg = self.config
        cand = self._candidate
        entry = self.server.load_model(
            self.name, model_str=cand.model_to_string())
        self.server.detach_shadow(self.name)
        shadow = self._last_shadow or {}
        with self._state_lock:
            self._mirror = None
            self._candidate = None
            self._cand_built_t = None
            self._promotes += 1
            # rollback floor: what the DEMOTED model achieved — a
            # promotion that then does worse than the model it replaced
            # is exactly the breach the watch window exists to catch
            self._baseline = live_loss
            self._watch_deadline = now + cfg.tpu_promote_watch_s
            self._watch_from_seq = self.buffer.current_seq()
            self.state = WATCH
        self._persist()
        default_registry().counter(
            "lgbm_supervisor_promotes_total",
            help="Candidates promoted to live",
            model=self.name).inc()
        supervisor_event(self.config, "promote", model=self.name,
                         version=entry.version,
                         prior_version=live_entry.version,
                         delta=shadow.get("delta"),
                         samples=shadow.get("samples"),
                         baseline_loss=live_loss, forced=forced)
        log.info("supervisor: promoted %s v%d -> v%d (shadow delta %s)",
                 self.name, live_entry.version, entry.version,
                 shadow.get("delta"))

    def _reject(self, why: str, samples: int, **fields) -> None:
        self.server.detach_shadow(self.name)
        with self._state_lock:
            self._mirror = None
            self._candidate = None
            self._cand_built_t = None
            self.state = IDLE
        self._persist()
        supervisor_event(self.config, "reject", model=self.name,
                         why=why, samples=samples, **fields)
        log.info("supervisor: candidate for %s rejected (%s)", self.name,
                 why)

    def _tick_watch(self, now: float) -> None:
        cfg = self.config
        win = self.buffer.window(after_seq=self._watch_from_seq)
        samples = 0 if win is None else int(win[0].shape[0])
        breached = False
        live_loss = None
        if samples >= min(cfg.tpu_promote_min_samples, 32):
            X, y, w = win
            live = self.server.registry.get(self.name)
            live_loss = _loss(live.booster, X, y, w, self.objective)
            if self._baseline is None or not np.isfinite(self._baseline):
                # forced promote before any labeled window existed: the
                # demoted model is still warm in the registry — score it
                # on the same rows so the floor is what it WOULD achieve
                prior = self.server.registry.prior_entry(self.name)
                if prior is not None:
                    with self._state_lock:
                        self._baseline = _loss(prior.booster, X, y, w,
                                               self.objective)
            if self._baseline is not None and np.isfinite(self._baseline):
                breached = (live_loss > self._baseline
                            + cfg.tpu_promote_rollback_delta)
        if breached:
            self._rollback(live_loss, samples)
            return
        if now > (self._watch_deadline or now):
            with self._state_lock:
                self.state = IDLE
                self._baseline = None
                self._watch_deadline = None
            self._persist()
            supervisor_event(self.config, "watch", model=self.name,
                             outcome="pass", samples=samples,
                             live_loss=live_loss)

    def _rollback(self, live_loss: float, samples: int) -> None:
        entry = self.server.registry.rollback(self.name)
        baseline = self._baseline
        with self._state_lock:
            self._rollbacks += 1
            self.state = IDLE
            self._baseline = None
            self._watch_deadline = None
        self._persist()
        default_registry().counter(
            "lgbm_supervisor_rollbacks_total",
            help="Automatic post-promotion rollbacks",
            model=self.name).inc()
        supervisor_event(self.config, "rollback", model=self.name,
                         version=entry.version, live_loss=live_loss,
                         baseline_loss=baseline, samples=samples)
        log.warning("supervisor: rolled %s back to v%d (live loss %.6g "
                    "breached baseline %.6g)", self.name, entry.version,
                    live_loss, baseline)

    def force_promote(self, model_str: Optional[str] = None,
                      booster: Optional[Booster] = None) -> None:
        """Skip the quality gate and promote `booster`/`model_str` NOW —
        the bad_promote chaos drill's lever (and an operator override).
        The watch window still applies, so a degraded forced candidate
        is auto-rolled back like any other breach."""
        if (model_str is None) == (booster is None):
            raise ValueError("force_promote needs exactly one of "
                             "model_str / booster")
        if booster is None:
            booster = Booster(model_str=model_str,
                              params=dict(self.train_params))
        booster._gbdt._sync_model()
        with self._tick_lock:
            now = time.monotonic()
            live = self.server.registry.get(self.name)
            win = self.buffer.window()
            live_loss = (_loss(live.booster, win[0], win[1], win[2],
                               self.objective) if win is not None
                         else float("inf"))
            with self._state_lock:
                self._candidate = booster
                self._last_shadow = None
            self._promote(live, live_loss, now, forced=True)

    # -- chaos ----------------------------------------------------------- #
    def _chaos_kill_refit(self) -> None:
        """LGBM_TPU_CHAOS=kill_refit:<rank>:<n> — SIGKILL this process at
        the n-th refit, AFTER the buffer snapshot and BEFORE the
        candidate persists: the exact window where a naive loop would
        lose ingested rows."""
        spec = os.environ.get("LGBM_TPU_CHAOS", "")
        if not spec.startswith("kill_refit:"):
            return
        parts = spec.split(":")
        n = int(parts[2]) if len(parts) > 2 else 0
        if self._refits == n:
            log.warning("CHAOS: SIGKILL mid-refit (refit #%d)", n)
            os.kill(os.getpid(), signal.SIGKILL)

    # -- persistence ----------------------------------------------------- #
    def _state_path(self) -> str:
        return os.path.join(self.root, STATE_FILE)

    def _persist(self) -> None:
        with self._state_lock:
            doc = {
                "model": self.name,
                "state": self.state,
                "consumed_upto": self._cand_consumed_upto,
                "refits": self._refits,
                "promotes": self._promotes,
                "rollbacks": self._rollbacks,
                "baseline_loss": self._baseline,
                "watch_from_seq": self._watch_from_seq,
                "objective": self.objective,
                "updated_at": time.time(),
            }
        try:
            atomic_write_text(self._state_path(),
                              json.dumps(doc, indent=1, sort_keys=True))
        except OSError as exc:
            log.warning("supervisor: state persist failed: %s", exc)

    def _restore(self) -> None:
        doc = read_state(self.root)
        if doc is None:
            self.buffer.restore(-1)
            return
        consumed = int(doc.get("consumed_upto", -1))
        state = doc.get("state", IDLE)
        restored = self.buffer.restore(
            consumed if state in (SHADOW, WATCH) else -1)
        with self._state_lock:
            self._refits = int(doc.get("refits", 0))
            self._promotes = int(doc.get("promotes", 0))
            self._rollbacks = int(doc.get("rollbacks", 0))
            self._cand_consumed_upto = consumed
        resumed_as = IDLE
        if state == SHADOW:
            # the persisted candidate resumes its shadow audition
            cand_path = os.path.join(self.root, CANDIDATE_FILE)
            if os.path.exists(cand_path):
                try:
                    with open(cand_path) as f:
                        cand = Booster(model_str=f.read(),
                                       params=dict(self.train_params))
                    with self._state_lock:
                        self._candidate = cand
                        self._cand_built_t = time.monotonic()
                        self.state = SHADOW
                        self._shadow_deadline = (
                            time.monotonic()
                            + 20.0 * self.config.tpu_refit_interval_s)
                    self._attach_mirror(cand)
                    resumed_as = SHADOW
                except Exception as exc:  # noqa: BLE001 — stale candidate
                    log.warning("supervisor: candidate restore failed "
                                "(%s); back to idle", exc)
        elif state == WATCH and doc.get("baseline_loss") is not None:
            with self._state_lock:
                self.state = WATCH
                self._baseline = float(doc["baseline_loss"])
                self._watch_deadline = (time.monotonic()
                                        + self.config.tpu_promote_watch_s)
                self._watch_from_seq = int(doc.get("watch_from_seq", -1))
            resumed_as = WATCH
        # REFIT means we died mid-build: the spool replayed above, the
        # next interval rebuilds the candidate — zero ingest loss
        supervisor_event(self.config, "resume", model=self.name,
                         persisted_state=state, resumed_state=resumed_as,
                         restored_rows=restored, refits=self._refits)
        log.info("supervisor: restored state=%s -> %s (%d spooled rows)",
                 state, resumed_as, restored)

    # -- observability ---------------------------------------------------- #
    def _candidate_age(self) -> float:
        t = self._cand_built_t
        return time.monotonic() - t if t is not None else 0.0

    def snapshot(self) -> Dict:
        try:
            version = self.server.registry.get(self.name).version
        except KeyError:
            version = None
        with self._state_lock:
            return {
                "model": self.name,
                "state": self.state,
                "live_version": version,
                "buffer_rows": self.buffer.train_rows(),
                "window_rows": self.buffer.window_rows_count(),
                "shed_overflow_rows": self.buffer.shed_overflow_rows(),
                "refits": self._refits,
                "promotes": self._promotes,
                "rollbacks": self._rollbacks,
                "candidate_age_s": round(self._candidate_age(), 3),
                "last_shadow": self._last_shadow,
                "baseline_loss": self._baseline,
            }


def read_state(root: str) -> Optional[Dict]:
    """Parse `SUPERVISOR.json` under a checkpoint root (shared with
    tools/ckpt_inspect.py); None when absent/unreadable."""
    path = os.path.join(root, STATE_FILE)
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None
