"""lightgbm_tpu.serving — TPU-resident inference serving.

A model registry with versioned hot-swap (registry.py), an adaptive
micro-batcher amortizing the ~100 ms device dispatch floor across
concurrent requests (batcher.py), an in-process + stdlib-HTTP frontend
(server.py, CLI task=serve), a byte-accounted HBM residency manager for
multi-tenant fleets (fleet.py), per-device replica sets with
health-probed routing and loss-free failover (replicas.py),
request-path observability (metrics.py) and a small client (client.py).
See docs/Serving.md, docs/Fleet.md and docs/Replicas.md.
"""
from .admission import (CircuitBreaker, DrainingError,  # noqa: F401
                        ShedError, TenantQuota)
from .batcher import (BatcherStoppedError, MicroBatcher,  # noqa: F401
                      QueueFullError, RequestTimeoutError)
from .client import ServingClient, ServingError  # noqa: F401
from .fleet import (FleetFaultInjector,  # noqa: F401
                    HbmResidencyManager, ShapeBucketCache,
                    publish_fleet_metrics)
from .metrics import Histogram, ModelStats  # noqa: F401
from .registry import (ModelEntry, ModelNotFoundError,  # noqa: F401
                       ModelRegistry)
from .replicas import Replica, ReplicaRouter, ReplicaSet  # noqa: F401
from .server import Server  # noqa: F401
from .shadow import ShadowMirror  # noqa: F401

__all__ = [
    "Server", "ServingClient", "ServingError",
    "ModelRegistry", "ModelEntry", "ModelNotFoundError",
    "MicroBatcher", "QueueFullError", "RequestTimeoutError",
    "BatcherStoppedError", "ModelStats", "Histogram",
    "CircuitBreaker", "DrainingError", "ShedError", "ShadowMirror",
    "TenantQuota", "HbmResidencyManager", "ShapeBucketCache",
    "FleetFaultInjector", "publish_fleet_metrics",
    "Replica", "ReplicaSet", "ReplicaRouter",
]
