"""Admission control for the serving predict path.

Four protections sit in front of the micro-batcher so overload and
device trouble degrade predictably instead of cascading:

- **Load shedding** (ShedError -> HTTP 429 + Retry-After): requests are
  refused at the door once the queue holds more than
  ``tpu_serve_shed_queue_rows`` rows.  Shedding fires BEFORE enqueue —
  a shed request costs one counter bump, the queue never grows
  unboundedly, and the client learns exactly when to come back.
- **Circuit breaker** around device execution: after
  ``tpu_serve_breaker_failures`` consecutive dispatch failures the
  breaker OPENS and batches ride the host walk (always available — it
  is plain NumPy) until ``tpu_serve_breaker_reset_s`` passes; then one
  HALF-OPEN probe decides whether the device path is healthy again.
- **Draining** (DrainingError -> HTTP 503): after SIGTERM the server
  stops admitting work, finishes every queued and in-flight request
  within ``tpu_serve_drain_timeout_s``, then exits — no request is
  abandoned mid-predict.
- **Per-tenant quotas** (``TenantQuota`` -> HTTP 429 + Retry-After):
  with ``tpu_fleet_tenant_qps`` set, each model name gets its own token
  bucket, so one noisy tenant sheds against its OWN quota instead of
  starving every other tenant's batcher — the multi-tenant counterpart
  of the global queue-depth shed.
"""
from __future__ import annotations

import threading
import time
from typing import Dict


class ShedError(Exception):
    """Load shed at admission — HTTP 429 with a Retry-After hint."""

    def __init__(self, message: str, retry_after_s: float = 1.0):
        super().__init__(message)
        self.retry_after_s = float(retry_after_s)


class DrainingError(Exception):
    """The server is draining for shutdown — HTTP 503."""


class TenantQuota:
    """Per-tenant token-bucket admission quota.

    Each tenant (model name) refills at ``qps`` tokens/s up to a
    ``burst`` ceiling (default 2x qps, floor 1 — a tenant idle for a
    while may burst briefly, steady state is capped at qps).
    ``try_admit`` consumes one token and returns None, or returns the
    seconds until a token refills — the Retry-After hint for the 429.
    Sheds are counted per tenant so a quota-limited tenant is
    attributable in /metrics.  Thread-safe; clock injectable for tests.
    """

    def __init__(self, qps: float, burst: float = 0.0,
                 clock=time.monotonic):
        self.qps = max(float(qps), 1e-9)
        self.burst = float(burst) if burst > 0 else max(2.0 * self.qps, 1.0)
        self._clock = clock
        self._lock = threading.Lock()
        self._buckets: Dict[str, list] = {}      # name -> [tokens, last_t]
        self._sheds: Dict[str, int] = {}

    def try_admit(self, tenant: str):
        """None = admitted (one token consumed); otherwise the seconds
        until the tenant's next token — shed with 429 + Retry-After."""
        now = self._clock()
        with self._lock:
            bucket = self._buckets.get(tenant)
            if bucket is None:
                bucket = self._buckets[tenant] = [self.burst, now]
            tokens, last = bucket
            tokens = min(self.burst, tokens + (now - last) * self.qps)
            if tokens >= 1.0:
                bucket[0] = tokens - 1.0
                bucket[1] = now
                return None
            bucket[0] = tokens
            bucket[1] = now
            self._sheds[tenant] = self._sheds.get(tenant, 0) + 1
            return (1.0 - tokens) / self.qps

    def shed_count(self, tenant: str) -> int:
        with self._lock:
            return self._sheds.get(tenant, 0)

    def snapshot(self) -> Dict:
        with self._lock:
            return {"qps": self.qps, "burst": self.burst,
                    "sheds": dict(self._sheds)}


class CircuitBreaker:
    """Consecutive-failure circuit breaker (closed -> open -> half-open).

    ``allow()`` answers "may this dispatch use the guarded path?":
    CLOSED always, OPEN no until ``reset_s`` elapsed, then exactly ONE
    caller gets a HALF-OPEN probe; its ``record_success`` re-closes the
    breaker, its ``record_failure`` re-opens it for another full
    ``reset_s``.  Thread-safe; the clock is injectable for tests.
    """

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

    def __init__(self, failure_threshold: int = 5, reset_s: float = 30.0,
                 clock=time.monotonic):
        self.failure_threshold = max(int(failure_threshold), 1)
        self.reset_s = max(float(reset_s), 0.0)
        self._clock = clock
        self._lock = threading.Lock()
        self._state = self.CLOSED
        self._consecutive_failures = 0
        self._opened_at = 0.0
        self._probe_out = False
        self.open_count = 0          # times the breaker tripped

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def allow(self) -> bool:
        with self._lock:
            if self._state == self.CLOSED:
                return True
            if self._state == self.OPEN:
                if self._clock() - self._opened_at < self.reset_s:
                    return False
                self._state = self.HALF_OPEN
                self._probe_out = True
                return True
            # HALF_OPEN: one probe at a time
            if self._probe_out:
                return False
            self._probe_out = True
            return True

    def record_success(self) -> None:
        with self._lock:
            self._consecutive_failures = 0
            self._probe_out = False
            self._state = self.CLOSED

    def record_failure(self) -> None:
        with self._lock:
            self._consecutive_failures += 1
            self._probe_out = False
            if (self._state == self.HALF_OPEN
                    or self._consecutive_failures >= self.failure_threshold):
                if self._state != self.OPEN:
                    self.open_count += 1
                self._state = self.OPEN
                self._opened_at = self._clock()

    def snapshot(self) -> dict:
        with self._lock:
            return {"state": self._state,
                    "consecutive_failures": self._consecutive_failures,
                    "open_count": self.open_count}
