"""Adaptive micro-batching queue for the serving predict path.

NOTES.md measures ~100 ms per blocking device dispatch on this backend,
so naive per-request predicts cap near 10 QPS no matter how small the
model is.  The classic serving fix (Clipper-style adaptive batching):
concurrent requests are coalesced into ONE padded batch per dispatch —
the power-of-two row buckets of ops/predict.py mean every batch size
between buckets reuses the same compiled executable, so the dispatch
floor amortizes across every rider.

Policy knobs (Config serve_*):
- max_batch_rows: dispatch as soon as this many rows are waiting;
- max_wait_ms:    dispatch a partial batch once the OLDEST rider has
                  waited this long (latency deadline, not a fixed tick);
- max_queue_rows: bounded queue — submits beyond it raise QueueFullError
                  (the HTTP layer maps it to 429, or host-fallback);
- timeout_ms:     per-request deadline covering queue wait + predict;
                  expired riders are dropped before dispatch so one
                  slow compile can't cascade timeouts down the queue.

One worker thread per batcher (one batcher per served model name); the
predict function itself resolves the registry's CURRENT model version,
so hot-swaps never drain the queue.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional

import numpy as np

from ..obs import tracing
from ..utils import log
from .admission import DrainingError
from .metrics import ModelStats


class QueueFullError(Exception):
    """Bounded queue overflow — backpressure; map to HTTP 429."""


class RequestTimeoutError(Exception):
    """The request missed its deadline (queue wait + predict)."""


class BatcherStoppedError(Exception):
    """Submit after stop() — the server is shutting down."""


class _Request:
    __slots__ = ("rows", "n", "enqueue_t", "deadline_t", "event", "result",
                 "error", "cancelled")

    def __init__(self, rows: np.ndarray, timeout_s: float):
        self.rows = rows
        self.n = rows.shape[0]
        self.enqueue_t = time.perf_counter()
        self.deadline_t = self.enqueue_t + timeout_s
        self.event = threading.Event()
        self.result: Optional[np.ndarray] = None
        self.error: Optional[BaseException] = None
        self.cancelled = False


class MicroBatcher:
    """Coalesces concurrent predict requests into one dispatch.

    predict_fn: Callable[[np.ndarray], np.ndarray] taking the coalesced
    [rows, features] matrix and returning per-row outputs whose leading
    axis is rows (1-D scores or [rows, k] multiclass both work).
    """

    def __init__(self, predict_fn: Callable[[np.ndarray], np.ndarray],
                 *, max_batch_rows: int = 256, max_wait_ms: float = 2.0,
                 max_queue_rows: int = 4096, timeout_ms: float = 1000.0,
                 stats: Optional[ModelStats] = None, name: str = ""):
        self.predict_fn = predict_fn
        self.max_batch_rows = max(int(max_batch_rows), 1)
        self.max_wait_s = max(float(max_wait_ms), 0.0) / 1e3
        self.max_queue_rows = max(int(max_queue_rows), self.max_batch_rows)
        self.timeout_s = float(timeout_ms) / 1e3
        self.stats = stats or ModelStats()
        self.name = name
        self._queue: List[_Request] = []
        self._queued_rows = 0
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._stopped = False
        self._draining = False
        self._inflight = 0           # requests inside a dispatch right now
        self._worker = threading.Thread(
            target=self._run, name="lgbm-serve-batcher-%s" % (name or "?"),
            daemon=True)
        self._started = False

    # -- public API ---------------------------------------------------- #
    def start(self) -> "MicroBatcher":
        with self._lock:
            if self._started:
                return self
            self._started = True
        self._worker.start()
        return self

    def stop(self, join: bool = True) -> None:
        with self._lock:
            self._stopped = True
            pending = list(self._queue)
            self._queue.clear()
            self._queued_rows = 0
            self._not_empty.notify_all()
        for req in pending:
            req.error = BatcherStoppedError("batcher %s stopped" % self.name)
            req.event.set()
        if join and self._started and self._worker.is_alive() \
                and threading.current_thread() is not self._worker:
            self._worker.join(timeout=5.0)

    def queue_depth_rows(self) -> int:
        with self._lock:
            return self._queued_rows

    # -- graceful drain ------------------------------------------------- #
    def begin_drain(self) -> None:
        """Stop admitting new work; queued and in-flight requests still
        complete.  Irreversible for this batcher instance."""
        with self._lock:
            self._draining = True
            self._not_empty.notify_all()

    def drained(self) -> bool:
        with self._lock:
            return (self._draining and not self._queue
                    and self._inflight == 0)

    def drain(self, timeout_s: float = 10.0) -> bool:
        """begin_drain() and wait until every admitted request finished
        (or the timeout passes).  Returns True when fully drained."""
        self.begin_drain()
        deadline = time.perf_counter() + max(float(timeout_s), 0.0)
        while not self.drained():
            if time.perf_counter() >= deadline:
                return False
            time.sleep(0.005)
        return True

    def submit(self, rows: np.ndarray,
               timeout_ms: Optional[float] = None) -> np.ndarray:
        """Blocking predict through the coalescing queue.

        Raises QueueFullError on backpressure, RequestTimeoutError when
        the deadline passes, BatcherStoppedError after stop().
        """
        if not self._started:
            self.start()
        timeout_s = (self.timeout_s if timeout_ms is None
                     else float(timeout_ms) / 1e3)
        req = _Request(rows, timeout_s)
        with tracing.span("serve/enqueue", "serve", rows=req.n,
                          model=self.name):
            with self._lock:
                if self._stopped:
                    raise BatcherStoppedError(
                        "batcher %s stopped" % self.name)
                if self._draining:
                    raise DrainingError(
                        "batcher %s is draining for shutdown" % self.name)
                if self._queued_rows + req.n > self.max_queue_rows:
                    self.stats.record_reject()
                    raise QueueFullError(
                        "queue full: %d rows waiting, +%d over the %d cap"
                        % (self._queued_rows, req.n, self.max_queue_rows))
                self._queue.append(req)
                self._queued_rows += req.n
                self.stats.set_queue_depth(self._queued_rows)
                self._not_empty.notify()
        if not req.event.wait(timeout_s):
            # mark cancelled so the worker skips it if still queued; a
            # dispatch already in flight just discards the result
            req.cancelled = True
            self.stats.record_timeout()
            raise RequestTimeoutError(
                "request (%d rows) missed its %.0f ms deadline"
                % (req.n, timeout_s * 1e3))
        if req.error is not None:
            raise req.error
        return req.result

    # -- worker -------------------------------------------------------- #
    def _take_batch(self) -> List[_Request]:
        """Block until requests are waiting, then coalesce until the
        batch is full or the oldest rider's max-wait deadline passes."""
        with self._lock:
            while not self._queue and not self._stopped:
                self._not_empty.wait()
            if self._stopped:
                return []
            dispatch_at = self._queue[0].enqueue_t + self.max_wait_s
            while True:
                waiting = sum(r.n for r in self._queue)
                now = time.perf_counter()
                if waiting >= self.max_batch_rows or now >= dispatch_at:
                    break
                if not self._not_empty.wait(timeout=dispatch_at - now):
                    break       # deadline hit with no new arrivals
                if self._stopped:
                    return []
            batch: List[_Request] = []
            taken = 0
            while self._queue:
                nxt = self._queue[0]
                if batch and taken + nxt.n > self.max_batch_rows:
                    break       # keep oversize requests whole, alone
                batch.append(self._queue.pop(0))
                taken += nxt.n
            self._queued_rows -= taken
            self._inflight += len(batch)
            self.stats.set_queue_depth(self._queued_rows)
            return batch

    def _run(self) -> None:
        while True:
            batch = self._take_batch()
            if not batch:
                if self._stopped:
                    return
                continue
            try:
                self._dispatch(batch)
            finally:
                with self._lock:
                    self._inflight -= len(batch)

    def _dispatch(self, batch: List[_Request]) -> None:
        now = time.perf_counter()
        live = []
        for req in batch:
            if req.cancelled or now >= req.deadline_t:
                req.cancelled = True    # expired in queue: don't pay
                continue                # the dispatch for a dead rider
            live.append(req)
            self.stats.record_wait((now - req.enqueue_t) * 1e3)
        if not live:
            return
        try:
            X = (live[0].rows if len(live) == 1
                 else np.concatenate([r.rows for r in live], axis=0))
            with tracing.span("serve/micro_batch", "serve",
                              rows=X.shape[0], riders=len(live),
                              model=self.name):
                out = np.asarray(self.predict_fn(X))
            a = 0
            for req in live:
                req.result = out[a:a + req.n]
                a += req.n
                req.event.set()
        except BaseException as e:  # noqa: BLE001 — riders must wake
            log.warning("serving batch dispatch failed: %s", e)
            self.stats.record_error()
            for req in live:
                req.error = e
                req.event.set()
