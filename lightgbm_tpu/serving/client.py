"""Minimal stdlib HTTP client for the serving endpoint.

Usage:
    from lightgbm_tpu.serving import ServingClient
    c = ServingClient(port=9109)
    scores = c.predict([[5.1, 3.5, 1.4, 0.2]])
    print(c.stats()["models"]["default"]["latency_ms"]["p99"])
"""
from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import Dict, List, Optional

import numpy as np


class ServingError(Exception):
    """Non-2xx reply from the server; carries the HTTP status code."""

    def __init__(self, status: int, message: str):
        super().__init__("HTTP %d: %s" % (status, message))
        self.status = status


class ServingClient:
    def __init__(self, host: str = "127.0.0.1", port: int = 9109,
                 timeout: float = 30.0):
        self.base = "http://%s:%d" % (host, port)
        self.timeout = timeout

    def _call(self, path: str, payload: Optional[Dict] = None) -> Dict:
        url = self.base + path
        data = None if payload is None else json.dumps(payload).encode()
        req = urllib.request.Request(
            url, data=data,
            headers={"Content-Type": "application/json"} if data else {})
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                return json.loads(resp.read().decode())
        except urllib.error.HTTPError as e:
            try:
                message = json.loads(e.read().decode()).get("error", str(e))
            except Exception:  # noqa: BLE001 — error body is best-effort
                message = str(e)
            raise ServingError(e.code, message) from None

    # -- API ------------------------------------------------------------ #
    def predict(self, rows, model: Optional[str] = None,
                timeout_ms: Optional[float] = None) -> np.ndarray:
        payload: Dict = {"rows": np.asarray(rows, np.float64).tolist()}
        if model is not None:
            payload["model"] = model
        if timeout_ms is not None:
            payload["timeout_ms"] = timeout_ms
        return np.asarray(self._call("/predict", payload)["predictions"])

    def stats(self) -> Dict:
        return self._call("/stats")

    def models(self) -> Dict:
        return self._call("/models")["models"]

    def health(self) -> Dict:
        return self._call("/healthz")

    def load_model(self, name: str, model_file: Optional[str] = None,
                   model_str: Optional[str] = None) -> int:
        """Load or hot-swap a model; returns the new version."""
        payload: Dict = {"name": name}
        if model_file is not None:
            payload["model_file"] = model_file
        if model_str is not None:
            payload["model_str"] = model_str
        return int(self._call("/models/load", payload)["version"])

    def evict_model(self, name: str) -> None:
        self._call("/models/evict", {"name": name})
