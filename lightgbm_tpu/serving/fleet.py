"""HBM residency manager for multi-tenant model fleets.

Production GBDT serving is a per-segment/per-region *fleet*: thousands
of small boosters, a handful hot at any instant.  Keeping every loaded
ensemble device-resident forever (the pre-fleet registry behavior)
means the Nth tenant does not degrade capacity — it OOMs the process
and takes every tenant down.  This module turns device memory into an
explicitly byte-accounted, LRU-managed cache over the registry's
models:

- **Residency states**: each tenant is RESIDENT (device arrays built,
  compiled executables warm), SPILLED (host tier only: the booster's
  frozen node arrays plus a hashed model-text snapshot; device buffers
  dropped) or PROMOTING (a build is in flight).  A request hitting a
  SPILLED tenant is served IMMEDIATELY via the host tree-walk while an
  asynchronous promotion runs — cold tenants cost latency, never
  availability.
- **Byte budget before allocation**: ``tpu_fleet_hbm_budget_mb`` with
  high/low watermarks.  Ensembles are sized from
  ``ops.predict.estimate_device_bytes`` (exact, from the padded layout
  alone) and LRU tenants are spilled BEFORE the new arrays are built,
  so pressure resolves by eviction, not by an allocator OOM.  The
  accounting invariant — resident + reserved bytes never exceed the
  budget — holds at every instant; ``peak_resident_bytes`` records the
  high-water mark so drills can assert it.
- **Shape-bucketed compile cache**: executables are keyed on the
  ensemble shape signature (padded tree count, node/leaf widths,
  features, dtype) plus the row bucket.  Tenants with equal signatures
  share ONE compiled executable per bucket (the jit statics and traced
  shapes are functions of the signature), so fleet size does not
  multiply retraces; promotion skips warmups a sibling already paid
  for.
- **Faults**: ``FleetFaultInjector`` arms promotion failure, slow
  device and spill-read corruption (manifest sha256 mismatch).
  Promotions retry with the resilience ``RetryPolicy``'s exponential
  backoff; an exhausted budget DEGRADES the tenant to the host walk —
  counted (``promote_failures``), never raised to clients — and the
  tenant re-arms after a cool-down.  A corrupt spill snapshot is
  detected before use and healed from the authoritative in-memory
  trees.

Lock discipline (tpulint `locks` family): the manager lock guards only
dict/counter state; every expensive operation — ensemble build, bucket
warmup, model-text snapshot, backoff sleep — runs OUTSIDE the lock with
a generation re-check at commit time, the same pattern the registry's
load() uses.
"""
from __future__ import annotations

import hashlib
import queue
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..obs import default_registry
from ..obs import tracing as obs_tracing
from ..obs.recorder import fleet_event
from ..ops import predict as predict_ops
from ..resilience.comm import FaultInjector, RetryPolicy
from ..utils import log

RESIDENT, SPILLED, PROMOTING = "resident", "spilled", "promoting"


class FleetFaultInjector(FaultInjector):
    """Deterministic chaos hooks for the residency manager, extending
    the comm-layer verbs (fail/delay/drop/partition/kill) with spilled-
    tier corruption:

        inj = FleetFaultInjector()
        inj.fail("promote", count=2)      # next 2 promotions raise
        inj.delay("promote", seconds=0.2) # slow device: build stalls
        inj.corrupt("spill_read")         # next spill read: bad sha256
        fleet = HbmResidencyManager(..., injector=inj)

    ``corrupt`` faults are consumed by :meth:`corrupt_check` (NOT by the
    base ``check``, which treats unknown kinds as failures): the spilled
    model text comes back mutated, so the manifest hash recorded at
    spill time no longer matches and the manager must detect and heal.
    """

    CORRUPT = "corrupt"

    def corrupt(self, op: str = "spill_read", count: int = 1) -> None:
        self._arm(op, {"kind": self.CORRUPT, "count": int(count)})

    def corrupt_check(self, op: str, payload: str) -> str:
        """Consume one armed corrupt fault for `op`: returns `payload`
        with its first byte flipped (any hash-breaking mutation would
        do), or unchanged when no corrupt fault is armed."""
        with self._lock:
            q = self._faults.get(op)
            if not q or q[0]["kind"] != self.CORRUPT:
                return payload
            fault = q[0]
            if fault["count"] > 0:
                fault["count"] -= 1
                if fault["count"] <= 0:
                    q.pop(0)
            self.injected += 1
        if not payload:
            return "\x00"
        flipped = chr(ord(payload[0]) ^ 0x01)
        return flipped + payload[1:]


class ShapeBucketCache:
    """Fleet-wide (shape signature, row bucket) compile cache.

    jax's jit cache already deduplicates executables process-wide; what
    it cannot do is tell the fleet that tenant B's warmup is a no-op
    because tenant A compiled the identical executable a minute ago.
    This cache makes executable identity EXPLICIT: promotion consults it
    per (signature, bucket) and skips warmups whose executable is
    already live, so a 64-tenant fleet of same-shape models pays the
    trace/compile cost once, not 64 times.  Signatures come from
    ``DeviceEnsemble.shape_signature`` — equal signatures imply equal
    jit statics and traced shapes, so sharing can never change results;
    unequal signatures never collide.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._warm: set = set()
        self.hits = 0
        self.misses = 0

    def check(self, signature: tuple, bucket: int) -> bool:
        """True when this (signature, bucket) executable is already
        compiled fleet-wide (counted as a hit); False counts a miss —
        the caller compiles, then calls :meth:`mark`."""
        key = (tuple(signature), int(bucket))
        with self._lock:
            if key in self._warm:
                self.hits += 1
                return True
            self.misses += 1
            return False

    def mark(self, signature: tuple, bucket: int) -> None:
        with self._lock:
            self._warm.add((tuple(signature), int(bucket)))

    def __len__(self) -> int:
        with self._lock:
            return len(self._warm)

    def snapshot(self) -> Dict:
        with self._lock:
            return {"entries": len(self._warm), "hits": self.hits,
                    "misses": self.misses}


class _Record:
    """Per-tenant residency record; every field is guarded by the
    manager lock.  ``gen`` increments on each admit so an in-flight
    promotion for a superseded entry can detect the race at commit time
    and discard its work instead of installing a torn mix."""

    __slots__ = ("name", "entry", "state", "ens", "bytes", "est",
                 "last_access", "spill_text", "spill_sha", "host_only",
                 "degraded", "queued", "retry_at", "gen",
                 "promote_failures")

    def __init__(self, name: str, entry):
        self.name = name
        self.entry = entry
        self.state = SPILLED
        self.ens = None               # DeviceEnsemble while RESIDENT
        self.bytes = 0                # accounted HBM bytes while RESIDENT
        self.est = 0                  # layout-exact build estimate
        self.last_access = 0.0
        self.spill_text = None        # host-tier model snapshot + manifest
        self.spill_sha = None
        self.host_only = False        # device-incapable or over-budget
        self.degraded = False         # promotion budget exhausted
        self.queued = False           # promotion enqueued/in flight
        self.retry_at = 0.0           # degraded cool-down deadline
        self.gen = 0
        self.promote_failures = 0


class HbmResidencyManager:
    """Byte-accounted LRU residency over the serving registry's models.

    The registry calls :meth:`admit` at load/rollback time and
    :meth:`release` at evict time; the per-batch hot path calls
    :meth:`checkout`, which returns the tenant's live DeviceEnsemble
    (touching LRU recency) or None — in which case the caller rides the
    host walk and an asynchronous promotion has been scheduled.  A
    checkout that raced with an eviction still finishes on the buffers
    it was handed (plain references keep them alive, the same in-flight
    semantics hot-swap has); the accounting drops the bytes at evict
    time, so actual usage can only exceed the accounting transiently,
    never the other way around.
    """

    def __init__(self, budget_bytes: int, high_watermark: float = 0.9,
                 low_watermark: float = 0.7,
                 warmup_buckets: Optional[List[int]] = None,
                 retry: Optional[RetryPolicy] = None,
                 injector: Optional[FaultInjector] = None,
                 compile_cache: Optional[ShapeBucketCache] = None,
                 config=None, degrade_cooldown_s: float = 5.0,
                 clock=time.monotonic):
        self.budget_bytes = max(int(budget_bytes), 0)
        self.high_watermark = min(max(float(high_watermark), 1e-6), 1.0)
        self.low_watermark = min(max(float(low_watermark), 1e-6),
                                 self.high_watermark)
        self.warmup_buckets = list(warmup_buckets or [])
        self.retry = retry or RetryPolicy()
        self.injector = injector
        # explicit None test: an EMPTY cache is falsy (__len__ == 0) and
        # `or` would silently drop a caller-shared instance
        self.compile_cache = (ShapeBucketCache() if compile_cache is None
                              else compile_cache)
        self.config = config
        self.degrade_cooldown_s = max(float(degrade_cooldown_s), 0.0)
        self._clock = clock
        self._lock = threading.Lock()
        self._records: Dict[str, _Record] = {}
        self._queue: "queue.Queue[Optional[str]]" = queue.Queue()
        self._worker: Optional[threading.Thread] = None
        self._stopped = False
        # per-device replica ledger (serving/replicas.py): device ordinal
        # -> replica bytes parked there.  Device 0 is shared with the
        # classic residency ledger above, so _make_room_locked treats its
        # replica bytes as an immovable floor; devices 1..N-1 hold
        # replicas only and are budget-checked independently — the
        # admission invariant (resident + reserved <= budget) holds PER
        # DEVICE, not just globally.
        self._replica_bytes: Dict[Tuple[str, int], Tuple[int, int]] = {}
        self._device_used: Dict[int, int] = {}
        self._device_peak: Dict[int, int] = {}
        # counters (ints, bumped under the lock; scraped lock-free)
        self.resident_bytes = 0       # includes in-flight reservations
        self.peak_resident_bytes = 0
        self.replica_reserve_failures = 0
        self.promotions = 0
        self.promote_retries = 0
        self.promote_failures = 0
        self.evictions = 0
        self.spill_corruptions = 0
        self.device_hits = 0
        self.host_serves = 0
        self._policy_levers = self._bind_policy_levers()

    @classmethod
    def from_config(cls, config, **kwargs) -> "HbmResidencyManager":
        buckets = (list(config.serve_warmup_buckets)
                   if config.serve_warmup_buckets
                   else predict_ops.pow2_buckets(config.serve_max_batch_rows))
        return cls(
            budget_bytes=int(config.tpu_fleet_hbm_budget_mb * (1 << 20)),
            high_watermark=config.tpu_fleet_high_watermark,
            low_watermark=config.tpu_fleet_low_watermark,
            warmup_buckets=buckets,
            retry=RetryPolicy(
                retries=config.tpu_fleet_promote_retries,
                base_ms=config.tpu_fleet_promote_backoff_ms),
            config=config, **kwargs)

    # -- hot path ------------------------------------------------------ #
    def checkout(self, name: str, entry) -> Optional[object]:
        """The per-batch residency decision: the tenant's DeviceEnsemble
        when RESIDENT (LRU recency touched), else None — the caller
        serves on the host walk and, for a SPILLED tenant, promotion has
        been scheduled.  Never blocks on a build."""
        promote = False
        with self._lock:
            rec = self._records.get(name)
            if rec is None or rec.entry is not entry:
                # mid-swap stale entry: the host walk is always safe
                return None
            rec.last_access = self._clock()
            if rec.state == RESIDENT:
                self.device_hits += 1
                return rec.ens
            self.host_serves += 1
            if (rec.state == SPILLED and not rec.host_only
                    and not rec.queued
                    and self._clock() >= rec.retry_at):
                rec.queued = True
                promote = True
        if promote:
            self._enqueue(name)
        return None

    # -- lifecycle ----------------------------------------------------- #
    def admit(self, entry, promote: bool = True) -> bool:
        """Register `entry` as the current model for its name.  With
        ``promote=True`` (the load path) the ensemble is built and
        warmed synchronously — evicting LRU tenants first, exactly like
        any promotion; with ``promote=False`` (the rollback path) the
        entry is installed host-serving and promotion runs
        asynchronously, so the install itself stays O(dict assignment).
        Returns True when the entry ended up device-RESIDENT."""
        name = entry.name
        g = entry.booster._gbdt
        est = predict_ops.estimate_device_bytes(
            g.models, g.num_tree_per_iteration)
        demoted = None
        with obs_tracing.span("serving/fleet_admit", "fleet", model=name,
                              est_bytes=est or 0):
            with self._lock:
                rec = self._records.get(name)
                if rec is None:
                    rec = _Record(name, entry)
                    self._records[name] = rec
                else:
                    if rec.entry is not entry:
                        if (getattr(rec.entry, "version", 0)
                                >= getattr(entry, "version", 0)):
                            # a newer load admitted past this one while it
                            # was off-lock (registry stale-load race): the
                            # freshest version keeps the record
                            return rec.state == RESIDENT
                        demoted = (rec.entry, rec.state == RESIDENT)
                    if rec.state == RESIDENT:
                        # the replaced entry's bytes leave the budget NOW;
                        # in-flight batches on the old buffers finish on
                        # plain references (hot-swap semantics)
                        self.resident_bytes -= rec.bytes
                        self.evictions += 1
                    rec.entry = entry
                    rec.ens = None
                    rec.bytes = 0
                    rec.spill_text = None
                    rec.spill_sha = None
                    rec.gen += 1
                rec.state = SPILLED
                rec.est = int(est or 0)
                rec.host_only = est is None or (
                    self.budget_bytes > 0 and est > self.budget_bytes)
                rec.degraded = False
                rec.retry_at = 0.0
                rec.last_access = self._clock()
                oversize = (est is not None and self.budget_bytes > 0
                            and est > self.budget_bytes)
                host_only = rec.host_only
                rec.queued = not host_only
        if demoted is not None:
            # drop the demoted entry's device buffers: the prior tier is
            # host-RAM, and rollback() transparently re-promotes
            self._drop_device_state(demoted[0])
            self._event("demote", model=name, was_resident=demoted[1])
        if oversize:
            log.warning("fleet: %s needs %d bytes but the budget is %d; "
                        "serving host-only", name, est, self.budget_bytes)
            self._event("oversize", model=name, est_bytes=est,
                        budget_bytes=self.budget_bytes)
        self._event("admit", model=name, est_bytes=est or 0,
                    host_only=host_only)
        if host_only:
            return False
        if promote:
            return self._promote_with_retry(name)
        self._enqueue(name)
        return False

    def release(self, name: str) -> None:
        """Forget a tenant (registry eviction): its accounted bytes
        leave the budget and its record is dropped.  Stray replica
        reservations for the tenant (a ReplicaSet that was not stopped
        first) are dropped from the per-device ledger too."""
        with self._lock:
            for key in [k for k in self._replica_bytes if k[0] == name]:
                dev_ord, b = self._replica_bytes.pop(key)
                self._device_used[dev_ord] = max(
                    self._device_used.get(dev_ord, 0) - b, 0)
            rec = self._records.pop(name, None)
            if rec is None:
                return
            if rec.state == RESIDENT:
                self.resident_bytes -= rec.bytes
            rec.gen += 1          # in-flight promotions discard at commit
            entry = rec.entry
        self._drop_device_state(entry)
        self._event("release", model=name)

    # -- per-device replica ledger (serving/replicas.py) ---------------- #
    def reserve_replica(self, name: str, slot: int, dev_ord: int,
                        est: int) -> bool:
        """Reserve `est` bytes for replica `slot` of tenant `name` on
        device `dev_ord` (admission-before-allocation, same as
        promotion).  Device 0 shares the budget with the classic
        residency ledger — LRU residents are spilled to make room
        exactly like a promotion would; devices 1..N-1 hold replicas
        only, so the check is a plain per-device budget test.  Returns
        False (counted) when the replica does not fit: the ReplicaSet
        places fewer copies — capacity degrades, admission stays exact."""
        est = int(est or 0)
        dev_ord = int(dev_ord)
        key = (str(name), int(slot))
        victims: List[Tuple] = []
        with self._lock:
            if key in self._replica_bytes:
                return True       # idempotent double-reserve
            if self.budget_bytes <= 0:
                fits = True       # unbudgeted manager: track, never refuse
            elif dev_ord == 0:
                fits, victims = self._make_room_locked(est, exclude=name)
            else:
                fits = (self._device_used.get(dev_ord, 0) + est
                        <= self.budget_bytes)
            if fits:
                self._replica_bytes[key] = (dev_ord, est)
                used = self._device_used.get(dev_ord, 0) + est
                self._device_used[dev_ord] = used
                if used > self._device_peak.get(dev_ord, 0):
                    self._device_peak[dev_ord] = used
            else:
                self.replica_reserve_failures += 1
        self._finish_spills(victims)
        if not fits:
            log.warning("fleet: no room for replica %d of %s on device %d "
                        "(%d bytes)", slot, name, dev_ord, est)
            self._event("replica_reserve_failed", model=name, slot=slot,
                        device=dev_ord, est_bytes=est)
        return fits

    def commit_replica(self, name: str, slot: int, actual: int) -> None:
        """Adjust a reservation to the built ensemble's actual bytes
        (estimate -> exact, same as promotion's commit)."""
        key = (str(name), int(slot))
        actual = int(actual)
        with self._lock:
            rec = self._replica_bytes.get(key)
            if rec is None:
                return
            dev_ord, est = rec
            self._replica_bytes[key] = (dev_ord, actual)
            used = max(self._device_used.get(dev_ord, 0) + actual - est, 0)
            self._device_used[dev_ord] = used
            if used > self._device_peak.get(dev_ord, 0):
                self._device_peak[dev_ord] = used

    def release_replica(self, name: str, slot: int) -> None:
        """Return a replica's bytes to its device's budget (ReplicaSet
        stop/scale-down; in-flight dispatches finish on references)."""
        key = (str(name), int(slot))
        with self._lock:
            rec = self._replica_bytes.pop(key, None)
            if rec is None:
                return
            dev_ord, b = rec
            self._device_used[dev_ord] = max(
                self._device_used.get(dev_ord, 0) - b, 0)

    def stop(self) -> None:
        """Stop the promotion worker (idempotent)."""
        with self._lock:
            self._stopped = True
            worker, self._worker = self._worker, None
            levers, self._policy_levers = self._policy_levers, None
        self._queue.put(None)
        if worker is not None:
            worker.join(timeout=5.0)
        if levers:
            from ..control import default_actuator
            act = default_actuator()
            for name, fn in levers:
                act.unbind(name, fn)

    # -- control-plane levers ------------------------------------------- #
    def pre_spill(self, count: int = 1) -> List[str]:
        """Proactively spill the ``count`` coldest device-resident
        tenants to the host tier, returning their names.  This is the
        shed-burn-rate lever: when admission is 429ing, freeing HBM
        headroom BEFORE the next admit avoids the synchronous
        make-room eviction on the serving path.  Same spill mechanics
        as watermark eviction (accounting drops under the lock, the
        model-text snapshot is written outside it)."""
        count = max(1, int(count))
        victims: List[Tuple] = []
        with self._lock:
            cands = sorted(
                (r for r in self._records.values() if r.state == RESIDENT),
                key=lambda r: r.last_access)
            for r in cands[:count]:
                self.resident_bytes -= r.bytes  # tpulint: ok=lock-unguarded-write
                self.evictions += 1  # tpulint: ok=lock-unguarded-write
                victims.append((r, r.entry, r.ens))
                r.bytes = 0
                r.ens = None
                r.state = SPILLED
        self._finish_spills(victims)
        names = [rec.name for rec, _e, _s in victims]
        if names:
            self._event("pre_spill", models=names)
        return names

    def _bind_policy_levers(self):
        """Expose the residency levers to the policy engine
        (control/engine.py) through the process actuator; unbound again
        in :meth:`stop`.  Returns the (name, fn) pairs, or None when
        ``tpu_policy`` is off."""
        if not bool(getattr(self.config, "tpu_policy", False)):
            return None
        from ..control import default_actuator

        def fleet_pre_spill(args):
            names = self.pre_spill(int(args.get("count", 1)))
            if not names:
                raise ValueError("no device-resident tenants to pre-spill")
            return "spilled %s" % names

        act = default_actuator()
        levers = [("fleet_pre_spill", fleet_pre_spill)]
        for name, fn in levers:
            act.bind(name, fn)
        return levers

    # -- promotion ------------------------------------------------------ #
    def _enqueue(self, name: str) -> None:
        with self._lock:
            if self._stopped:
                return
            if self._worker is None:
                self._worker = threading.Thread(
                    target=self._worker_loop, name="lgbm-fleet-promoter",
                    daemon=True)
                self._worker.start()
        self._queue.put(name)

    def _worker_loop(self) -> None:
        while True:
            name = self._queue.get()
            if name is None:
                return
            try:
                self._promote_with_retry(name)
            except Exception as exc:  # noqa: BLE001 — worker never dies
                log.warning("fleet: promotion worker error for %s: %s",
                            name, exc)

    def _promote_with_retry(self, name: str) -> bool:
        """Promote with the RetryPolicy's exponential backoff.  An
        exhausted budget DEGRADES the tenant: it keeps serving on the
        host walk (counted, nothing raised to clients) and re-arms for
        promotion after a cool-down."""
        attempts = self.retry.retries + 1
        for attempt in range(1, attempts + 1):
            try:
                self._promote_once(name)
                return True
            except Exception as exc:  # noqa: BLE001 — degrade, never raise
                if attempt >= attempts:
                    self._degrade(name, exc)
                    return False
                with self._lock:
                    self.promote_retries += 1
                delay = self.retry.backoff_s(attempt)
                log.warning("fleet: promotion of %s failed (%s); retry "
                            "%d/%d in %.0f ms", name, exc, attempt,
                            attempts - 1, delay * 1e3)
                time.sleep(delay)
        return False

    def _degrade(self, name: str, exc: BaseException) -> None:
        with self._lock:
            self.promote_failures += 1
            rec = self._records.get(name)
            # a racing admit may have promoted a NEWER entry under this
            # name; never demote a resident record from a stale failure
            if rec is not None and rec.state != RESIDENT:
                rec.state = SPILLED
                rec.degraded = True
                rec.queued = False
                rec.promote_failures += 1
                rec.retry_at = self._clock() + self.degrade_cooldown_s
        log.warning("fleet: promotion of %s exhausted %d attempt(s) (%s); "
                    "tenant degraded to the host walk for %.1fs", name,
                    self.retry.retries + 1, exc, self.degrade_cooldown_s)
        self._event("degrade", model=name, error=str(exc))

    def _promote_once(self, name: str) -> None:
        """One promotion attempt: reserve bytes (evicting LRU tenants
        first), build + warm OUTSIDE the lock, commit under a generation
        re-check.  Raises on injected/real faults — the caller retries."""
        with obs_tracing.span("serving/fleet_promote", "fleet", model=name):
            with self._lock:
                rec = self._records.get(name)
                if rec is None or rec.host_only or rec.state == RESIDENT:
                    if rec is not None:
                        rec.queued = False
                    return
                entry, est, gen0 = rec.entry, rec.est, rec.gen
                spill_text, spill_sha = rec.spill_text, rec.spill_sha
                fits, victims = self._make_room_locked(est, exclude=name)
                if not fits:
                    rec.queued = False
                else:
                    rec.state = PROMOTING
                    self.resident_bytes += est     # reservation
                    self._touch_peak_locked()
            if not fits:
                # victims (if any) are already marked SPILLED — finish
                # their spill so no device bytes outlive the accounting
                self._finish_spills(victims)
                raise RuntimeError(
                    "fleet: no room for %s (%d bytes; %d of %d in use)"
                    % (name, est, self.resident_bytes, self.budget_bytes))
            try:
                self._finish_spills(victims)
                if self.injector is not None:
                    # promotion failure / slow device, armed by chaos
                    self.injector.check("promote")
                if spill_text is not None:
                    self._verify_spill(name, spill_text, spill_sha)
                g = entry.booster._gbdt
                ens = g._device_ensemble()
                warmed = ([] if ens is None
                          else self._warm(entry, ens))
            except BaseException:
                with self._lock:
                    self.resident_bytes -= est   # release the reservation
                    if self._records.get(name) is rec and rec.gen == gen0:
                        rec.state = SPILLED
                raise
            committed = stale = False
            with self._lock:
                self.resident_bytes -= est       # reservation ->
                rec2 = self._records.get(name)
                stale = (rec2 is not rec or rec.gen != gen0
                         or self._stopped)
                if stale:
                    pass
                elif ens is None:
                    rec.state = SPILLED
                    rec.host_only = True
                    rec.queued = False
                else:
                    actual = ens.device_bytes()
                    rec.ens = ens
                    rec.bytes = actual
                    self.resident_bytes += actual   # -> actual bytes
                    rec.state = RESIDENT
                    rec.degraded = False
                    rec.queued = False
                    self.promotions += 1
                    self._touch_peak_locked()
                    committed = True
        if stale:
            # a newer admit/release raced past this build: the ensemble
            # it cached on the old entry's booster must not outlive the
            # accounting
            self._drop_device_state(entry)
        if committed:
            entry.warmed_buckets = warmed
            self._event("promote", model=name, bytes=rec.bytes,
                        buckets=warmed)
            log.info("fleet: %s promoted (%d bytes resident, buckets %s)",
                     name, rec.bytes, warmed or "none")
        elif ens is None:
            self._event("host_only", model=name)

    def _verify_spill(self, name: str, text: str,
                      sha: Optional[str]) -> None:
        """Integrity-check the host-tier snapshot against the manifest
        hash recorded at spill time.  A mismatch (bit rot, injected
        corruption) is counted and HEALED: the in-memory booster's
        frozen node arrays are authoritative, so promotion proceeds from
        them and the bad snapshot is discarded — corrupt bytes are never
        promoted."""
        cc = getattr(self.injector, "corrupt_check", None)
        if cc is not None:
            text = cc("spill_read", text)
        if sha is not None and hashlib.sha256(
                text.encode()).hexdigest() == sha:
            return
        with self._lock:
            self.spill_corruptions += 1
            rec = self._records.get(name)
            if rec is not None:
                rec.spill_text = None
                rec.spill_sha = None
        log.warning("fleet: spilled snapshot of %s failed its manifest "
                    "hash; rebuilding from the in-memory trees", name)
        self._event("spill_corrupt", model=name)

    def _warm(self, entry, ens) -> List[int]:
        """Warm the bucket executables through the fleet-wide compile
        cache: (signature, bucket) pairs a sibling tenant already
        compiled are skipped — the executable is live in jax's jit cache
        — so fleet size does not multiply retraces."""
        g = entry.booster._gbdt
        iters = len(g.models) // max(g.num_tree_per_iteration, 1)
        sig = ens.shape_signature(entry.num_features)
        warmed: List[int] = []
        for b in sorted({int(x) for x in self.warmup_buckets}):
            if b <= 0 or not entry.use_device(b):
                continue
            if self.compile_cache.check(sig, b):
                warmed.append(b)      # shared executable already compiled
                continue
            ens.warmup_buckets(entry.num_features, [b], iters)
            self.compile_cache.mark(sig, b)
            warmed.append(b)
        return warmed

    # -- eviction ------------------------------------------------------- #
    def _make_room_locked(self, incoming: int,
                          exclude: str) -> Tuple[bool, List[Tuple]]:
        """Called UNDER the lock: spill LRU residents until `incoming`
        bytes fit.  Crossing the high watermark evicts down to the low
        watermark (hysteresis — one oversized admit does not thrash the
        whole fleet); the hard invariant is resident + incoming <=
        budget.  Returns (fits, victims); the caller ALWAYS finishes the
        victims' spill outside the lock — even on a failed fit — so no
        device bytes outlive the accounting."""
        # replica bytes parked on device 0 (per-device ledger) shrink the
        # classic ledger's room; they are pinned by their ReplicaSet, so
        # they act as an immovable floor, never as eviction candidates
        floor = self._device_used.get(0, 0)
        if self.budget_bytes <= 0 or incoming + floor > self.budget_bytes:
            return False, []
        victims: List[Tuple] = []
        trigger = self.high_watermark * self.budget_bytes
        target = min(self.low_watermark * self.budget_bytes,
                     self.budget_bytes - incoming) - floor
        if self.resident_bytes + floor + incoming > trigger:
            cands = sorted(
                (r for r in self._records.values()
                 if r.state == RESIDENT and r.name != exclude),
                key=lambda r: r.last_access)
            for r in cands:
                if self.resident_bytes <= target:
                    break
                # every caller holds self._lock (the _locked suffix
                # contract, same as supervisor.IngestBuffer)
                self.resident_bytes -= r.bytes  # tpulint: ok=lock-unguarded-write
                self.evictions += 1  # tpulint: ok=lock-unguarded-write
                victims.append((r, r.entry, r.ens))
                r.bytes = 0
                r.ens = None
                r.state = SPILLED
        # remaining overshoot means everything else is an in-flight
        # reservation: the caller backs off and retries
        return (self.resident_bytes + floor + incoming
                <= self.budget_bytes), victims

    def _finish_spills(self, victims: List[Tuple]) -> None:
        """OUTSIDE the lock: drop the victims' device caches and record
        their host-tier snapshot (model text + sha256 manifest).  The
        snapshot write is the expensive part — model_to_string — which
        is exactly why it cannot run under the lock."""
        for rec, entry, _ens in victims or ():
            with obs_tracing.span("serving/fleet_spill", "fleet",
                                  model=rec.name):
                self._drop_device_state(entry)
                try:
                    text = entry.booster.model_to_string()
                    sha = hashlib.sha256(text.encode()).hexdigest()
                except Exception as exc:  # noqa: BLE001 — trees stay valid
                    log.warning("fleet: spill snapshot of %s failed (%s); "
                                "host tier keeps the node arrays only",
                                rec.name, exc)
                    text = sha = None
                with self._lock:
                    if self._records.get(rec.name) is rec \
                            and rec.entry is entry:
                        rec.spill_text = text
                        rec.spill_sha = sha
            self._event("spill", model=rec.name)
            log.info("fleet: spilled %s to the host tier", rec.name)

    @staticmethod
    def _drop_device_state(entry) -> None:
        """Drop an entry's device buffers: clear the gbdt ensemble cache
        and the warmed-bucket list.  In-flight dispatches holding the
        old ensemble finish on plain references; the NEXT dispatch sees
        a host-only entry."""
        try:
            entry.booster._gbdt._dev_ens_cache = None
        except Exception as exc:  # noqa: BLE001 — cache drop is advisory
            log.debug("fleet: dev cache drop failed: %s", exc)
        entry.warmed_buckets = []

    def _touch_peak_locked(self) -> None:
        # every caller holds self._lock (the _locked suffix contract)
        if self.resident_bytes > self.peak_resident_bytes:
            self.peak_resident_bytes = self.resident_bytes  # tpulint: ok=lock-unguarded-write

    # -- observability -------------------------------------------------- #
    def _event(self, what: str, **fields) -> None:
        if self.config is not None:
            fleet_event(self.config, what, **fields)

    def state_counts(self) -> Dict[str, int]:
        with self._lock:
            out = {RESIDENT: 0, SPILLED: 0, PROMOTING: 0, "degraded": 0,
                   "host_only": 0}
            for r in self._records.values():
                out[r.state] += 1
                if r.degraded:
                    out["degraded"] += 1
                if r.host_only:
                    out["host_only"] += 1
        return out

    def residency(self, name: str) -> Optional[str]:
        with self._lock:
            rec = self._records.get(name)
            return None if rec is None else rec.state

    def snapshot(self) -> Dict:
        with self._lock:
            tenants = {
                r.name: {"state": r.state, "bytes": r.bytes,
                         "degraded": r.degraded, "host_only": r.host_only,
                         "promote_failures": r.promote_failures,
                         "spilled_snapshot": r.spill_sha is not None}
                for r in self._records.values()}
            devices = {
                str(d): {"replica_bytes": self._device_used.get(d, 0),
                         "peak_replica_bytes": self._device_peak.get(d, 0),
                         "replicas": sum(
                             1 for (dv, _b) in self._replica_bytes.values()
                             if dv == d)}
                for d in sorted(set(self._device_used)
                                | set(self._device_peak))}
            return {
                "budget_bytes": self.budget_bytes,
                "resident_bytes": self.resident_bytes,
                "peak_resident_bytes": self.peak_resident_bytes,
                "devices": devices,
                "replica_reserve_failures": self.replica_reserve_failures,
                "high_watermark": self.high_watermark,
                "low_watermark": self.low_watermark,
                "promotions": self.promotions,
                "promote_retries": self.promote_retries,
                "promote_failures": self.promote_failures,
                "evictions": self.evictions,
                "spill_corruptions": self.spill_corruptions,
                "device_hits": self.device_hits,
                "host_serves": self.host_serves,
                "compile_cache": self.compile_cache.snapshot(),
                "tenants": tenants,
            }


def publish_fleet_metrics(reg=None,
                          fleet: Optional[HbmResidencyManager] = None):
    """Expose a residency manager on the process-wide metrics registry
    (gauges pull live values at scrape time, obs/adapters idiom)."""
    reg = reg or default_registry()
    reg.gauge("lgbm_fleet_budget_bytes",
              help="HBM byte budget for resident ensembles").set_fn(
        lambda: fleet.budget_bytes)
    reg.gauge("lgbm_fleet_resident_bytes",
              help="Accounted resident + reserved ensemble bytes").set_fn(
        lambda: fleet.resident_bytes)
    reg.gauge("lgbm_fleet_peak_resident_bytes",
              help="High-water mark of the byte accounting").set_fn(
        lambda: fleet.peak_resident_bytes)
    reg.gauge("lgbm_fleet_resident_models",
              help="Tenants with device-resident ensembles").set_fn(
        lambda: fleet.state_counts()[RESIDENT])
    reg.gauge("lgbm_fleet_spilled_models",
              help="Tenants serving from the host tier").set_fn(
        lambda: fleet.state_counts()[SPILLED])
    reg.counter("lgbm_fleet_promotions_total",
                help="Spilled tenants promoted to device").set_fn(
        lambda: fleet.promotions)
    reg.counter("lgbm_fleet_promote_retries_total",
                help="Promotion attempts retried after a fault").set_fn(
        lambda: fleet.promote_retries)
    reg.counter("lgbm_fleet_promote_failures_total",
                help="Promotions that exhausted the retry budget "
                     "(tenant degraded to the host walk)").set_fn(
        lambda: fleet.promote_failures)
    reg.counter("lgbm_fleet_evictions_total",
                help="Resident ensembles spilled under pressure").set_fn(
        lambda: fleet.evictions)
    reg.counter("lgbm_fleet_spill_corruptions_total",
                help="Spilled snapshots failing their manifest hash "
                     "(healed from the in-memory trees)").set_fn(
        lambda: fleet.spill_corruptions)
    reg.counter("lgbm_fleet_host_serves_total",
                help="Batches served on the host walk because the "
                     "tenant was not resident").set_fn(
        lambda: fleet.host_serves)
    reg.counter("lgbm_fleet_compile_cache_hits_total",
                help="Warmups skipped: a sibling tenant already "
                     "compiled the (signature, bucket) executable").set_fn(
        lambda: fleet.compile_cache.hits)
    reg.counter("lgbm_fleet_compile_cache_misses_total",
                help="(signature, bucket) executables compiled "
                     "first-hand").set_fn(
        lambda: fleet.compile_cache.misses)
