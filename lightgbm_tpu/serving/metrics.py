"""Request-path observability: counters + histograms per served model.

The serving analogue of the training-side TIMETAG profiler
(utils/profiling.py): every request, batch dispatch, rejection and
fallback increments lock-guarded accumulators, and /stats renders one
JSON snapshot — request counts, batch-size distribution, latency
percentiles, live queue depth — cheap enough to leave on in production
(two dict updates per request; no locks on the predict dispatch itself).

The Histogram implementation moved to the shared telemetry layer
(lightgbm_tpu/obs/registry.py) so training and serving report through
one type; it is re-exported here for API compatibility.  ModelStats
stays the serving-local accumulator; obs/adapters.publish_model_stats
exposes it through the MetricsRegistry for `GET /metrics`.
"""
from __future__ import annotations

import threading
from typing import Dict, Sequence

from ..obs.registry import Histogram  # noqa: F401 — shared impl, re-exported

# Latency buckets (ms): roughly log-spaced around the ~100 ms blocking
# device-dispatch floor measured in NOTES.md, so the histogram resolves
# both the coalesced-fast-path and the compile-stall tail.
DEFAULT_LATENCY_BOUNDS_MS = (
    0.5, 1, 2, 5, 10, 20, 50, 100, 200, 500, 1000, 2000, 5000)
# Batch-size buckets: power-of-two edges matching the batcher's row
# buckets, so the histogram reads as "which executables are hot".
DEFAULT_BATCH_BOUNDS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024)


class ModelStats:
    """Per-model request-path accumulators; one per registry name."""

    def __init__(self,
                 latency_bounds_ms: Sequence[float] = DEFAULT_LATENCY_BOUNDS_MS,
                 batch_bounds: Sequence[float] = DEFAULT_BATCH_BOUNDS):
        self._lock = threading.Lock()
        self.requests = 0            # requests admitted
        self.rows = 0                # total rows predicted
        self.batches = 0             # coalesced dispatches
        self.device_batches = 0      # dispatches that rode the device path
        self.host_batches = 0        # dispatches on the host walk
        self.host_fallback = 0       # overload requests served host-side
        self.rejected_queue_full = 0  # 429-style rejections
        self.shed = 0                # admission-control sheds (429+Retry-After)
        self.breaker_batches = 0     # batches forced host-side (breaker open)
        self.timeouts = 0            # requests that missed their deadline
        self.errors = 0              # predict-path exceptions
        self.queue_depth = 0         # live gauge (rows waiting)
        self.latency_ms = Histogram(latency_bounds_ms)
        self.batch_size = Histogram(batch_bounds)
        self.wait_ms = Histogram(latency_bounds_ms)   # queue wait per rider

    def record_request(self, rows: int) -> None:
        with self._lock:
            self.requests += 1
            self.rows += rows

    def record_batch(self, rows: int, device: bool) -> None:
        with self._lock:
            self.batches += 1
            if device:
                self.device_batches += 1
            else:
                self.host_batches += 1
            self.batch_size.observe(rows)

    def record_latency(self, ms: float) -> None:
        with self._lock:
            self.latency_ms.observe(ms)

    def record_wait(self, ms: float) -> None:
        with self._lock:
            self.wait_ms.observe(ms)

    def record_reject(self) -> None:
        with self._lock:
            self.rejected_queue_full += 1

    def record_shed(self) -> None:
        with self._lock:
            self.shed += 1

    def record_breaker_batch(self) -> None:
        with self._lock:
            self.breaker_batches += 1

    def record_timeout(self) -> None:
        with self._lock:
            self.timeouts += 1

    def record_error(self) -> None:
        with self._lock:
            self.errors += 1

    def record_fallback(self) -> None:
        with self._lock:
            self.host_fallback += 1

    def set_queue_depth(self, rows: int) -> None:
        with self._lock:
            self.queue_depth = rows

    def snapshot(self) -> Dict:
        with self._lock:
            return {
                "requests": self.requests,
                "rows": self.rows,
                "batches": self.batches,
                "device_batches": self.device_batches,
                "host_batches": self.host_batches,
                "host_fallback": self.host_fallback,
                "rejected_queue_full": self.rejected_queue_full,
                "shed": self.shed,
                "breaker_batches": self.breaker_batches,
                "timeouts": self.timeouts,
                "errors": self.errors,
                "queue_depth": self.queue_depth,
                "rows_per_batch": round(self.rows / self.batches, 3)
                if self.batches else None,
                "latency_ms": self.latency_ms.snapshot(),
                "batch_size": self.batch_size.snapshot(),
                "wait_ms": self.wait_ms.snapshot(),
            }
