"""Request-path observability: counters + histograms per served model.

The serving analogue of the training-side TIMETAG profiler
(utils/profiling.py): every request, batch dispatch, rejection and
fallback increments lock-guarded accumulators, and /stats renders one
JSON snapshot — request counts, batch-size distribution, latency
percentiles, live queue depth — cheap enough to leave on in production
(two dict updates per request; no locks on the predict dispatch itself).
"""
from __future__ import annotations

import bisect
import threading
from typing import Dict, List, Optional, Sequence

# Latency buckets (ms): roughly log-spaced around the ~100 ms blocking
# device-dispatch floor measured in NOTES.md, so the histogram resolves
# both the coalesced-fast-path and the compile-stall tail.
DEFAULT_LATENCY_BOUNDS_MS = (
    0.5, 1, 2, 5, 10, 20, 50, 100, 200, 500, 1000, 2000, 5000)
# Batch-size buckets: power-of-two edges matching the batcher's row
# buckets, so the histogram reads as "which executables are hot".
DEFAULT_BATCH_BOUNDS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024)


class Histogram:
    """Fixed-boundary histogram with percentile estimation.

    observe() is O(log buckets); percentile() linearly interpolates
    inside the winning bucket (Prometheus histogram_quantile style), so
    p50/p99 come out of bounded memory without storing samples.
    """

    def __init__(self, bounds: Sequence[float]):
        self.bounds: List[float] = sorted(float(b) for b in bounds)
        self.counts = [0] * (len(self.bounds) + 1)
        self.n = 0
        self.total = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None

    def observe(self, value: float) -> None:
        v = float(value)
        self.counts[bisect.bisect_left(self.bounds, v)] += 1
        self.n += 1
        self.total += v
        self.min = v if self.min is None else min(self.min, v)
        self.max = v if self.max is None else max(self.max, v)

    def percentile(self, q: float) -> Optional[float]:
        """Estimated q-th percentile (q in [0, 100]); None when empty."""
        if self.n == 0:
            return None
        rank = q / 100.0 * self.n
        seen = 0
        for i, c in enumerate(self.counts):
            if seen + c >= rank and c > 0:
                lo = self.bounds[i - 1] if i > 0 else (self.min or 0.0)
                hi = self.bounds[i] if i < len(self.bounds) else \
                    (self.max if self.max is not None else lo)
                frac = (rank - seen) / c
                return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
            seen += c
        return self.max

    def snapshot(self) -> Dict:
        return {
            "count": self.n,
            "sum": round(self.total, 6),
            "mean": round(self.total / self.n, 6) if self.n else None,
            "min": self.min,
            "max": self.max,
            "p50": self.percentile(50),
            "p90": self.percentile(90),
            "p99": self.percentile(99),
            "buckets": {
                ("le_%g" % self.bounds[i]) if i < len(self.bounds)
                else "inf": c
                for i, c in enumerate(self.counts) if c
            },
        }


class ModelStats:
    """Per-model request-path accumulators; one per registry name."""

    def __init__(self,
                 latency_bounds_ms: Sequence[float] = DEFAULT_LATENCY_BOUNDS_MS,
                 batch_bounds: Sequence[float] = DEFAULT_BATCH_BOUNDS):
        self._lock = threading.Lock()
        self.requests = 0            # requests admitted
        self.rows = 0                # total rows predicted
        self.batches = 0             # coalesced dispatches
        self.device_batches = 0      # dispatches that rode the device path
        self.host_batches = 0        # dispatches on the host walk
        self.host_fallback = 0       # overload requests served host-side
        self.rejected_queue_full = 0  # 429-style rejections
        self.timeouts = 0            # requests that missed their deadline
        self.errors = 0              # predict-path exceptions
        self.queue_depth = 0         # live gauge (rows waiting)
        self.latency_ms = Histogram(latency_bounds_ms)
        self.batch_size = Histogram(batch_bounds)

    def record_request(self, rows: int) -> None:
        with self._lock:
            self.requests += 1
            self.rows += rows

    def record_batch(self, rows: int, device: bool) -> None:
        with self._lock:
            self.batches += 1
            if device:
                self.device_batches += 1
            else:
                self.host_batches += 1
            self.batch_size.observe(rows)

    def record_latency(self, ms: float) -> None:
        with self._lock:
            self.latency_ms.observe(ms)

    def record_reject(self) -> None:
        with self._lock:
            self.rejected_queue_full += 1

    def record_timeout(self) -> None:
        with self._lock:
            self.timeouts += 1

    def record_error(self) -> None:
        with self._lock:
            self.errors += 1

    def record_fallback(self) -> None:
        with self._lock:
            self.host_fallback += 1

    def set_queue_depth(self, rows: int) -> None:
        with self._lock:
            self.queue_depth = rows

    def snapshot(self) -> Dict:
        with self._lock:
            return {
                "requests": self.requests,
                "rows": self.rows,
                "batches": self.batches,
                "device_batches": self.device_batches,
                "host_batches": self.host_batches,
                "host_fallback": self.host_fallback,
                "rejected_queue_full": self.rejected_queue_full,
                "timeouts": self.timeouts,
                "errors": self.errors,
                "queue_depth": self.queue_depth,
                "rows_per_batch": round(self.rows / self.batches, 3)
                if self.batches else None,
                "latency_ms": self.latency_ms.snapshot(),
                "batch_size": self.batch_size.snapshot(),
            }
