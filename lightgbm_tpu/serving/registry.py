"""Versioned model registry for the serving subsystem.

TF-Serving-style model lifecycle on top of Booster: load a model from
text (file or string), warm up the compiled signature-matmul predictor
for every power-of-two batch bucket the batcher can emit (so the first
real request never waits on XLA), then install it atomically as the
CURRENT version of its name.  Re-loading the same name hot-swaps: the
version counter increments, in-flight batches finish on the old entry
(plain references keep it alive), and the next dispatch sees the new
one.  Bounded capacity with least-recently-used eviction keeps a
many-model box from accumulating dead ensembles in device memory.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

import numpy as np

from ..basic import Booster
from ..obs import default_registry
from ..ops import predict as predict_ops
from ..utils import log
from ..utils.profiling import Profiler


class ModelNotFoundError(KeyError):
    """No model registered under this name — map to HTTP 404."""


class ModelEntry:
    """One immutable (name, version) pair: a loaded Booster plus the
    per-batch device/host dispatch decision."""

    def __init__(self, name: str, version: int, booster: Booster,
                 min_device_work: int, max_bucket: int, fleet=None):
        self.name = name
        self.version = version
        self.booster = booster
        self.min_device_work = int(min_device_work)
        self.max_bucket = int(max_bucket)
        # HbmResidencyManager when the registry is fleet-managed: the
        # per-batch device/host decision then also asks "is this tenant
        # device-RESIDENT right now?" (serving/fleet.py)
        self.fleet = fleet
        # ReplicaSet (serving/replicas.py) when the tenant is replicated
        # across device fault domains; None keeps the single-device path
        # (tpu_replica_count=1 must stay byte-identical to pre-replica
        # serving, so the classic path below is untouched)
        self.replicas = None
        self.loaded_at = time.time()
        self.warmed_buckets: List[int] = []
        g = booster._gbdt
        self.num_features = g.max_feature_idx + 1
        self.num_trees = len(g.models)
        self.num_class = max(g.num_tree_per_iteration, 1)

    def use_device(self, n_rows: int) -> bool:
        """Per-BATCH dispatch decision: the device path only pays off
        once rows x trees clears the work floor (MIN_DEVICE_WORK
        rationale, ops/predict.py); below it the host walk is cheaper
        than a dispatch — and never waits on compilation."""
        return n_rows * max(self.num_trees, 1) >= self.min_device_work

    def predict(self, X: np.ndarray, raw_score: bool = False):
        """Batch predict with the per-batch device/host choice.  Device
        batches ride the bucket-padded compiled executable; host
        batches walk the trees exactly like Booster.predict on small
        inputs — both bitwise-identical to the corresponding
        Booster.predict path.

        Fleet-managed entries add a residency gate: a SPILLED tenant is
        served IMMEDIATELY on the host walk (checkout schedules an async
        promotion), and a resident dispatch rides the checked-out
        ensemble explicitly so a concurrent eviction can never trigger a
        silent unaccounted rebuild through the gbdt cache."""
        g = self.booster._gbdt
        if self.use_device(X.shape[0]):
            rset = self.replicas
            if rset is not None:
                # replicated tenant: least-outstanding routing across the
                # per-device copies, loss-free failover, host walk only
                # when zero replicas are healthy (serving/replicas.py)
                return rset.predict(X, raw_score=raw_score)
            if self.fleet is None:
                return self.predict_device(X, raw_score=raw_score), True
            ens = self.fleet.checkout(self.name, self)
            if ens is not None:
                return g.predict_bucketed(X, raw_score=raw_score,
                                          max_bucket=self.max_bucket,
                                          ensemble=ens), True
        return g.predict(X, raw_score=raw_score, device=False), False

    def predict_device(self, X: np.ndarray, raw_score: bool = False):
        return self.booster._gbdt.predict_bucketed(
            X, raw_score=raw_score, max_bucket=self.max_bucket)

    def warmup(self, buckets) -> List[int]:
        """Compile the bucket executables this entry can be dispatched
        at (only those clearing the device-work floor — host-walk
        buckets have nothing to compile)."""
        g = self.booster._gbdt
        ens = g._device_ensemble()
        if ens is None:
            return []
        device_buckets = [b for b in buckets if self.use_device(b)]
        if device_buckets:
            self.warmed_buckets = ens.warmup_buckets(
                self.num_features, device_buckets, len(g.models)
                // max(g.num_tree_per_iteration, 1))
        return self.warmed_buckets

    def info(self) -> Dict:
        g = self.booster._gbdt
        if self.fleet is not None:
            # layout-only eligibility: _device_ensemble() would BUILD
            # (and cache) device arrays outside the fleet's accounting
            # for every spilled tenant a /stats scrape touches
            eligible = predict_ops.estimate_device_bytes(
                g.models, g.num_tree_per_iteration) is not None
        else:
            eligible = g._device_ensemble() is not None
        out = {
            "name": self.name,
            "version": self.version,
            "num_trees": self.num_trees,
            "num_features": self.num_features,
            "num_class": self.num_class,
            "loaded_at": self.loaded_at,
            "warmed_buckets": list(self.warmed_buckets),
            "device_eligible": eligible,
        }
        if self.fleet is not None:
            out["residency"] = self.fleet.residency(self.name)
        rset = self.replicas
        if rset is not None:
            out["replicas"] = rset.snapshot()
        return out


class ModelRegistry:
    """name -> current ModelEntry, with versioned hot-swap and LRU
    eviction past `max_models` names."""

    def __init__(self, max_models: int = 4,
                 min_device_work: int = predict_ops.MIN_DEVICE_WORK,
                 max_batch_rows: int = 256,
                 warmup_buckets: Optional[List[int]] = None,
                 profiler: Optional[Profiler] = None,
                 fleet=None, replica_count: int = 1,
                 replica_opts: Optional[Dict] = None):
        self.max_models = max(int(max_models), 1)
        # HbmResidencyManager (serving/fleet.py) when device residency is
        # byte-budgeted; None keeps the pre-fleet always-resident behavior
        self.fleet = fleet
        # replica_count > 1: every loaded tenant gets a ReplicaSet
        # (serving/replicas.py) at that count; exactly 1 keeps the
        # classic single-device path (entry.replicas stays None)
        self.replica_count = max(int(replica_count), 1)
        self.replica_opts = dict(replica_opts or {})
        self.min_device_work = int(min_device_work)
        self.max_batch_rows = int(max_batch_rows)
        # [] / None -> every pow2 bucket the batcher can emit
        self.warmup_bucket_list = (list(warmup_buckets) if warmup_buckets
                                   else predict_ops.pow2_buckets(
                                       self.max_batch_rows))
        self.replica_opts.setdefault("warmup_buckets",
                                     self.warmup_bucket_list)
        self.profiler = profiler or Profiler(enabled=True)
        self._lock = threading.Lock()
        self._entries: Dict[str, ModelEntry] = {}
        self._versions: Dict[str, int] = {}
        self._last_used: Dict[str, float] = {}
        # the entry each hot-swap DEMOTED, kept warm for rollback()
        self._prior: Dict[str, ModelEntry] = {}

    # -- lifecycle ----------------------------------------------------- #
    def load(self, name: str, model_str: Optional[str] = None,
             model_file: Optional[str] = None,
             params: Optional[Dict] = None, warmup: bool = True,
             checkpoint_dir: Optional[str] = None) -> ModelEntry:
        """Load + warm a model and install it as the current version of
        `name` (hot-swap when the name exists).  The expensive parts —
        parse, ensemble build, bucket compiles — happen OUTSIDE the
        registry lock, so serving traffic on other models never stalls
        behind a load.

        checkpoint_dir: serve the newest hash-verified training
        checkpoint under that directory (resilience/checkpoint.py) —
        the crash-restart path when no exported model file exists yet.
        """
        if checkpoint_dir is not None:
            if model_str is not None or model_file is not None:
                raise ValueError("load() takes checkpoint_dir OR "
                                 "model_str/model_file, not both")
            from ..resilience import CheckpointManager
            model_file = CheckpointManager.latest_model_file(checkpoint_dir)
            log.info("registry: %s loading from checkpoint %s", name,
                     model_file)
        if (model_str is None) == (model_file is None):
            raise ValueError("load() needs exactly one of model_str / "
                             "model_file")
        with self.profiler.phase("serve/model_load"):
            booster = (Booster(model_file=model_file, params=params)
                       if model_file is not None
                       else Booster(model_str=model_str, params=params))
        with self._lock:
            version = self._versions.get(name, 0) + 1
            self._versions[name] = version
        entry = ModelEntry(name, version, booster,
                           self.min_device_work, self.max_batch_rows,
                           fleet=self.fleet)
        if warmup and self.fleet is None:
            # fleet-managed entries warm via admit() AFTER install, so
            # residency accounting only ever tracks the live version
            with self.profiler.phase("serve/model_warmup"):
                entry.warmup(self.warmup_bucket_list)
        evicted: List[ModelEntry] = []
        with self._lock:
            current = self._versions.get(name, 0)
            if version < current:
                # a newer load for the same name raced past us while we
                # compiled; the freshest version stays installed
                log.warning("stale load of %s v%d discarded (v%d is live)",
                            name, version, current)
                return self._entries[name]
            demoted = self._entries.get(name)
            if demoted is not None:
                self._prior[name] = demoted
            self._entries[name] = entry
            self._last_used[name] = time.time()
            while len(self._entries) > self.max_models:
                lru = min((n for n in self._entries if n != name),
                          key=lambda n: self._last_used.get(n, 0.0))
                evicted.append(self._entries.pop(lru))
                self._last_used.pop(lru, None)
                self._prior.pop(lru, None)
        # the demoted entry's replicas release their device bytes NOW
        # (rollback rebuilds a fresh set at the same count); in-flight
        # batches on the old set finish on references
        self._stop_replicas(demoted)
        for dropped in evicted:
            log.warning("registry over capacity (%d): evicted %s",
                        self.max_models, dropped.name)
            self._stop_replicas(dropped)
            if self.fleet is not None:
                self.fleet.release(dropped.name)
        if self.fleet is not None:
            with self.profiler.phase("serve/model_warmup"):
                self.fleet.admit(entry, promote=warmup)
        self._attach_replicas(entry, self.replica_count)
        log.info("registry: %s v%d live (%d trees, %d features, "
                 "buckets %s)", name, entry.version, entry.num_trees,
                 entry.num_features, entry.warmed_buckets or "host-only")
        default_registry().counter(
            "lgbm_serve_model_loads_total",
            help="Models loaded into the serving registry",
            model=name).inc()
        return entry

    def rollback(self, name: str) -> ModelEntry:
        """Reinstall the version the last hot-swap demoted, under a NEW
        monotonic version — versions never reuse, so clients watching
        `info()` observe v_n -> v_{n+1} rather than time running
        backwards.  When the demoted booster is still warm (bucket
        executables live on its device ensemble), rollback is
        install-only: no parse, no compile, and the swap itself is one
        dict assignment under the lock — concurrent predictions either
        see the whole old entry or the whole new one, never a torn mix.
        When the prior's device buffers were EVICTED in the meantime
        (fleet spill, cache invalidation), the new entry must not
        inherit the stale warmed-bucket list — that would advertise a
        torn entry whose "warm" executables are gone.  Instead it
        installs host-serving and is transparently re-promoted: the
        fleet admits it for asynchronous promotion, or (no fleet) it is
        re-warmed right after install, outside the lock.
        Current and prior swap places, so a bad rollback can itself be
        rolled back.  Raises ModelNotFoundError when there is no prior
        version to return to.

        Replica-aware: a replicated tenant rolls back AT ITS CURRENT
        replica count — the count is read and the new entry installed in
        ONE critical section, so a concurrent set_replica_count cannot
        interleave between "decide the count" and "install the entry"
        and silently drop the fleet back to one copy.  The demoted set's
        device bytes are released outside the lock and a fresh set is
        built for the reinstalled version (requests ride the host walk
        for the build's duration, exactly like the fleet re-promotion
        path)."""
        with self._lock:
            current = self._entries.get(name)
            prior = self._prior.get(name)
            if current is None or prior is None:
                raise ModelNotFoundError(name)
            version = self._versions.get(name, 0) + 1
            self._versions[name] = version
            entry = ModelEntry(name, version, prior.booster,
                               self.min_device_work, self.max_batch_rows,
                               fleet=self.fleet)
            g = prior.booster._gbdt
            cache = getattr(g, "_dev_ens_cache", None)
            cache_key = (len(g.models), getattr(g, "_model_gen", 0))
            still_warm = (self.fleet is None and cache is not None
                          and cache[0] == cache_key
                          and cache[1] is not None)
            entry.warmed_buckets = (list(prior.warmed_buckets)
                                    if still_warm else [])
            # ONE critical section: count decision + entry install —
            # the reinstalled version keeps the demoted one's replica
            # count even when set_replica_count races this rollback
            keep_count = (current.replicas.count
                          if current.replicas is not None else 1)
            self._entries[name] = entry
            self._prior[name] = current
            self._last_used[name] = time.time()
        self._stop_replicas(current)
        if self.fleet is not None:
            # async re-promotion: the rollback stays O(dict assignment),
            # requests ride the host walk until the build commits
            self.fleet.admit(entry, promote=False)
        elif not still_warm and prior.warmed_buckets:
            # the prior's device buffers were evicted while demoted:
            # re-promote now (outside the lock) instead of serving a
            # torn entry that claims warm buckets it does not have
            entry.warmup(self.warmup_bucket_list)
        self._attach_replicas(entry, keep_count)
        log.warning("registry: %s rolled back to v%d (the v%d booster)",
                    name, version, prior.version)
        default_registry().counter(
            "lgbm_serve_rollbacks_total",
            help="Registry rollbacks to the prior model version",
            model=name).inc()
        return entry

    def get(self, name: str) -> ModelEntry:
        with self._lock:
            entry = self._entries.get(name)
            if entry is None:
                raise ModelNotFoundError(name)
            self._last_used[name] = time.time()
            return entry

    def prior_entry(self, name: str) -> Optional[ModelEntry]:
        """The entry the last hot-swap demoted (rollback's target), or
        None — the supervisor scores it to establish a watch baseline."""
        with self._lock:
            return self._prior.get(name)

    def evict(self, name: str) -> bool:
        with self._lock:
            dropped = self._entries.pop(name, None)
            self._last_used.pop(name, None)
            self._prior.pop(name, None)
            # keep the version counter: a re-load of the same name must
            # not reuse a version clients may have already seen
        if dropped is not None:
            self._stop_replicas(dropped)
            if self.fleet is not None:
                self.fleet.release(name)
            log.info("registry: evicted %s", name)
        return dropped is not None

    # -- replicas ------------------------------------------------------- #
    def replica_set(self, name: str):
        """The tenant's live ReplicaSet, or None (no LRU touch — this is
        the metrics-scrape accessor)."""
        with self._lock:
            entry = self._entries.get(name)
            return None if entry is None else entry.replicas

    def set_replica_count(self, name: str, n: int) -> int:
        """The control plane's replica actuator: grow or shrink `name`
        to `n` per-device replicas.  ``n == 1`` tears the ReplicaSet
        down entirely — the tenant returns to the EXACT single-device
        path (entry.replicas is None), so scale-to-one is byte-identical
        to never having replicated.  Builds run outside the registry
        lock; installs re-check the entry is still current.  Returns the
        resulting count (growth may fall short of `n` when devices have
        no room)."""
        n = max(int(n), 1)
        with self._lock:
            entry = self._entries.get(name)
            if entry is None:
                raise ModelNotFoundError(name)
            rset = entry.replicas
            if n == 1:
                entry.replicas = None
        if n == 1:
            if rset is not None:
                rset.stop()
                log.info("registry: %s scaled down to the single-device "
                         "path", name)
            return 1
        if rset is not None:
            got = rset.resize(n)
            log.info("registry: %s resized to %d replica(s)", name, got)
            return got
        got = self._attach_replicas(entry, n)
        return got.count if got is not None else 1

    def _attach_replicas(self, entry: ModelEntry, count: int):
        """Build a ReplicaSet for `entry` OUTSIDE the lock and install
        it only if the entry is still current (the stale-load discipline
        every expensive registry operation follows).  Never raises — a
        replica build failure leaves the classic path serving."""
        if count <= 1:
            return None
        from .replicas import ReplicaSet
        try:
            rset = ReplicaSet(entry, count, fleet=self.fleet,
                              **self.replica_opts)
        except Exception as exc:  # noqa: BLE001 — replicas degrade, never fail a load
            log.warning("registry: replica set for %s failed (%s); "
                        "single-device path stays live", entry.name, exc)
            return None
        if rset.count == 0:
            # host-only model or zero placements: nothing to route to
            rset.stop()
            return None
        with self._lock:
            if (self._entries.get(entry.name) is entry
                    and entry.replicas is None):
                entry.replicas = rset
                log.info("registry: %s serving on %d replica(s)",
                         entry.name, rset.count)
                return rset
        rset.stop()          # the entry was swapped/evicted mid-build
        return None

    @staticmethod
    def _stop_replicas(entry: Optional[ModelEntry]) -> None:
        if entry is None or entry.replicas is None:
            return
        rset, entry.replicas = entry.replicas, None
        rset.stop()

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._entries)

    def info(self) -> Dict:
        with self._lock:
            entries = list(self._entries.values())
        return {e.name: e.info() for e in entries}
