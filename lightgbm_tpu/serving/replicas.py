"""Device-fault-domain replicated serving.

One device ensemble per tenant (the pre-replica serving path) makes
every local device a shared fate domain: a single sick device trips the
tenant's circuit breaker and drops ALL of its traffic onto the ~100x
slower NumPy host walk.  This module turns the local devices into
independent fault domains:

- **ReplicaSet**: N copies of the frozen ``DeviceEnsemble``, committed
  to distinct local devices round-robin (``jax.device_put`` pins the
  ensemble constants, so every jit dispatch against replica *i* executes
  on device *i*'s fault domain).  Admission stays exact: each replica is
  priced with ``estimate_device_bytes`` and reserved against the
  ``HbmResidencyManager``'s per-device byte ledger BEFORE its arrays are
  built, so ``resident + reserved <= budget`` holds per device, not just
  globally.  A replica that does not fit is simply not placed — capacity
  degrades, admission never lies.
- **ReplicaRouter**: least-outstanding-rows routing in front of the
  micro-batcher.  Every batch is dispatched to the healthy replica with
  the fewest in-flight rows; a dispatch failure marks the victim,
  reroutes the SAME rows to the next sibling (requeue-not-drop — the
  batch is never lost, never answered with an error while a sibling can
  serve it), and only when ZERO replicas are healthy does the batch ride
  the always-available host walk.
- **Per-device health**: each replica carries its own ``CircuitBreaker``
  plus an optional periodic liveness probe (a tiny one-row dispatch with
  a deadline).  An open breaker removes the replica from routing; after
  ``reset_s`` the breaker's half-open probe — taken by the router or the
  prober, whichever dispatches first — re-admits the device
  automatically and the router re-balances.  Recovery needs no operator
  action.

Scaling is a control-plane lever: ``ModelRegistry.set_replica_count``
resizes a live set (build outside the registry lock, install under it),
and the server binds it to the process actuator as the
``set_replica_count`` policy action (control/policy.py scales up on
sustained queue-depth alerts, down on residency pressure).

Lock discipline (tpulint `locks` family): ``_lock`` guards the replica
list, the outstanding-rows table and the counters; ensemble builds,
warmups, dispatches and probe predicts all run OUTSIDE it.  Breakers
carry their own internal lock.
"""
from __future__ import annotations

import collections
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from ..obs import tracing as obs_tracing
from ..obs.recorder import fleet_event
from ..ops import predict as predict_ops
from ..utils import log
from .admission import CircuitBreaker


def local_devices() -> list:
    """The process-local jax devices (import deferred so host-only
    tooling can import this module without initializing a backend)."""
    import jax
    return list(jax.local_devices())


class Replica:
    """One placed copy: a device-committed ensemble plus its own
    breaker and counters.  Mutable fields are guarded by the owning
    ReplicaSet's lock (outstanding/dispatches/failures/probes); the
    breaker is internally locked."""

    __slots__ = ("slot", "dev_ord", "device", "ens", "breaker",
                 "outstanding", "dispatches", "failures", "probes")

    def __init__(self, slot: int, dev_ord: int, device, ens,
                 breaker: CircuitBreaker):
        self.slot = slot
        self.dev_ord = dev_ord
        self.device = device
        self.ens = ens
        self.breaker = breaker
        self.outstanding = 0          # in-flight rows (router load signal)
        self.dispatches = 0
        self.failures = 0
        self.probes = 0

    def healthy(self) -> bool:
        return self.breaker.state == CircuitBreaker.CLOSED


class ReplicaSet:
    """N per-device replicas of one tenant's frozen ensemble, with
    least-outstanding-rows routing, per-replica breakers, loss-free
    failover and an optional liveness prober.

    ``predict`` is the hot path the micro-batcher's batches land on (via
    ``ModelEntry.predict``); it returns ``(scores, used_device)`` with
    the same output contract as the single-device path — replicas change
    WHERE a batch executes, never what it returns.
    """

    def __init__(self, entry, count: int, fleet=None,
                 breaker_failures: int = 3, breaker_reset_s: float = 5.0,
                 probe_interval_s: float = 0.0,
                 probe_deadline_ms: float = 1000.0,
                 warmup_buckets: Optional[List[int]] = None,
                 config=None, clock=time.monotonic):
        self.entry = entry
        self.fleet = fleet
        self.breaker_failures = max(int(breaker_failures), 1)
        self.breaker_reset_s = max(float(breaker_reset_s), 0.0)
        self.probe_interval_s = max(float(probe_interval_s), 0.0)
        self.probe_deadline_ms = max(float(probe_deadline_ms), 1e-3)
        self.warmup_buckets = list(warmup_buckets or [])
        self.config = config
        self._clock = clock
        self._devices = local_devices()
        self._lock = threading.Lock()
        self._resize_lock = threading.Lock()  # serializes resize/stop
        self._replicas: List[Replica] = []
        self._events: "collections.deque" = collections.deque(maxlen=64)
        self._injector = None
        self._stop_event = threading.Event()
        self._prober: Optional[threading.Thread] = None
        self._stopped = False
        self._rr = 0                  # rotating tie-break (see _pick)
        # counters (bumped under the lock; scraped lock-free)
        self.failovers = 0            # batches rerouted off a failed replica
        self.host_fallbacks = 0       # batches with zero healthy replicas
        self.reserve_failures = 0     # replicas skipped: no device room
        for slot in range(max(int(count), 0)):
            rep = self._build_replica(slot)
            if rep is not None:
                with self._lock:
                    self._replicas.append(rep)
        self._start_prober()

    # -- placement ----------------------------------------------------- #
    def _build_replica(self, slot: int) -> Optional[Replica]:
        """Reserve bytes on the slot's device, then build the committed
        ensemble OUTSIDE any lock and true-up the reservation.  Returns
        None (counted, evented) when the device has no room or the model
        is host-only — the set simply holds fewer replicas."""
        g = self.entry.booster._gbdt
        est = predict_ops.estimate_device_bytes(
            g.models, g.num_tree_per_iteration)
        if est is None:
            return None               # device-incapable model: host walk
        dev_ord = slot % max(len(self._devices), 1)
        name = self.entry.name
        if self.fleet is not None and not self.fleet.reserve_replica(
                name, slot, dev_ord, est):
            with self._lock:
                self.reserve_failures += 1
            self._record_event("reserve_failed", slot=slot, device=dev_ord,
                               est_bytes=est)
            return None
        try:
            ens = predict_ops.DeviceEnsemble(
                g.models, g.num_tree_per_iteration,
                device=self._devices[dev_ord])
            if not ens.ok:
                raise RuntimeError("ensemble layout not device-capable")
            self._warm_replica(ens, dev_ord)
        except Exception as exc:  # noqa: BLE001 — degrade, never raise
            if self.fleet is not None:
                self.fleet.release_replica(name, slot)
            log.warning("replicas: build of %s slot %d on device %d "
                        "failed: %s", name, slot, dev_ord, exc)
            self._record_event("build_failed", slot=slot, device=dev_ord,
                               error=str(exc))
            return None
        if self.fleet is not None:
            self.fleet.commit_replica(name, slot, ens.device_bytes())
        breaker = CircuitBreaker(failure_threshold=self.breaker_failures,
                                 reset_s=self.breaker_reset_s,
                                 clock=self._clock)
        return Replica(slot, dev_ord, self._devices[dev_ord], ens, breaker)

    def _warm_replica(self, ens, dev_ord: int) -> None:
        """Pre-compile the bucket executables on the replica's device.
        The fleet compile cache key is extended with the DEVICE ordinal:
        jit executables for committed arrays are device-specific, so a
        sibling's warmth on device 0 must not suppress device 1's warmup
        (shape signatures alone would false-share)."""
        entry = self.entry
        g = entry.booster._gbdt
        iters = len(g.models) // max(g.num_tree_per_iteration, 1)
        cache = self.fleet.compile_cache if self.fleet is not None else None
        sig = ens.shape_signature(entry.num_features) + ("dev", dev_ord)
        for b in sorted({int(x) for x in self.warmup_buckets}):
            if b <= 0 or not entry.use_device(b):
                continue
            if cache is not None and cache.check(sig, b):
                continue
            ens.warmup_buckets(entry.num_features, [b], iters)
            if cache is not None:
                cache.mark(sig, b)

    # -- routing / failover -------------------------------------------- #
    def predict(self, X: np.ndarray, raw_score: bool = False):
        """Route one batch: least-outstanding healthy replica first,
        loss-free failover to siblings on dispatch failure, host walk
        only when zero replicas are healthy.  Returns
        ``(scores, used_device)`` — dispatch exceptions never escape to
        the batcher (the per-model breaker stays closed; health is
        tracked per DEVICE here)."""
        g = self.entry.booster._gbdt
        rows = int(X.shape[0])
        tried: set = set()
        while True:
            rep = self._pick(tried)
            if rep is None:
                with self._lock:
                    self.host_fallbacks += 1
                self._record_event("host_fallback", rows=rows)
                return g.predict(X, raw_score=raw_score, device=False), False
            prev_state = rep.breaker.state
            with self._lock:
                rep.outstanding += rows
            try:
                if self._injector is not None:
                    self._injector.check("replica:%d" % rep.slot)
                out = g.predict_bucketed(
                    X, raw_score=raw_score, max_bucket=self.entry.max_bucket,
                    ensemble=rep.ens)
            except Exception as exc:  # noqa: BLE001 — reroute, never drop
                rep.breaker.record_failure()
                with self._lock:
                    rep.failures += 1
                    self.failovers += 1
                tried.add(rep.slot)
                with obs_tracing.span("serving/failover", "serve",
                                      model=self.entry.name,
                                      victim_slot=rep.slot,
                                      victim_device=rep.dev_ord, rows=rows):
                    self._record_event("failover", victim=rep.slot,
                                       device=rep.dev_ord, rows=rows,
                                       error=str(exc))
                if rep.breaker.state == CircuitBreaker.OPEN \
                        and prev_state != CircuitBreaker.OPEN:
                    self._record_event("breaker_open", victim=rep.slot,
                                       device=rep.dev_ord)
                log.warning("replicas: %s slot %d (device %d) dispatch "
                            "failed (%s); rerouting %d rows",
                            self.entry.name, rep.slot, rep.dev_ord, exc,
                            rows)
                continue
            finally:
                with self._lock:
                    rep.outstanding -= rows
            rep.breaker.record_success()
            with self._lock:
                rep.dispatches += 1
            if prev_state != CircuitBreaker.CLOSED:
                self._record_event("readmit", slot=rep.slot,
                                   device=rep.dev_ord)
            return out, True

    def _pick(self, tried: set) -> Optional[Replica]:
        """Least-outstanding-rows healthy candidate, ties broken by a
        rotating counter.  The micro-batcher dispatches serially, so at
        pick time every replica is usually idle — a fixed tie-break
        would pin ALL traffic to one slot, leaving the siblings as cold
        (and therefore untested) standbys; the rotation keeps every
        device's executables and health continuously exercised.
        ``allow()`` is consulted in sorted order: it consumes a
        half-open probe token ONLY when it returns True, and a True
        here always leads to a dispatch — so recovering replicas get
        exactly one organic probe batch, never a wasted token."""
        with self._lock:
            cands = [r for r in self._replicas if r.slot not in tried]
            if cands:
                self._rr = (self._rr + 1) % (1 << 30)
                off = self._rr % len(cands)
                cands = cands[off:] + cands[:off]
        cands.sort(key=lambda r: r.outstanding)  # stable: rotation = ties
        for rep in cands:
            if rep.breaker.allow():
                return rep
        return None

    # -- liveness probing ---------------------------------------------- #
    def _start_prober(self) -> None:
        if self.probe_interval_s <= 0 or self._prober is not None:
            return
        self._prober = threading.Thread(
            target=self._probe_loop, daemon=True,
            name="lgbm-replica-probe-%s" % self.entry.name)
        self._prober.start()

    def _probe_loop(self) -> None:
        Xp = np.zeros((1, self.entry.num_features), np.float64)
        g = self.entry.booster._gbdt
        while not self._stop_event.wait(self.probe_interval_s):
            with self._lock:
                reps = list(self._replicas)
            for rep in reps:
                if self._stop_event.is_set():
                    return
                if not rep.breaker.allow():
                    continue
                prev_state = rep.breaker.state
                t0 = time.monotonic()
                ok = True
                try:
                    if self._injector is not None:
                        self._injector.check("replica:%d" % rep.slot)
                    g.predict_bucketed(Xp, max_bucket=self.entry.max_bucket,
                                       ensemble=rep.ens)
                except Exception:  # noqa: BLE001 — a probe failure IS data
                    ok = False
                if (time.monotonic() - t0) * 1e3 > self.probe_deadline_ms:
                    ok = False    # a stuck device must not pass its probe
                with self._lock:
                    rep.probes += 1
                if ok:
                    rep.breaker.record_success()
                    if prev_state != CircuitBreaker.CLOSED:
                        self._record_event("readmit", slot=rep.slot,
                                           device=rep.dev_ord, probe=True)
                else:
                    rep.breaker.record_failure()
                    with self._lock:
                        rep.failures += 1
                    if rep.breaker.state == CircuitBreaker.OPEN \
                            and prev_state != CircuitBreaker.OPEN:
                        self._record_event("breaker_open", victim=rep.slot,
                                           device=rep.dev_ord, probe=True)

    # -- scaling ------------------------------------------------------- #
    @property
    def count(self) -> int:
        with self._lock:
            return len(self._replicas)

    def resize(self, n: int) -> int:
        """Grow or shrink to `n` replicas.  Builds run outside the lock;
        shrink pops the highest slots and returns their bytes to the
        per-device ledger (in-flight dispatches finish on references).
        Returns the resulting count (growth may fall short when devices
        have no room)."""
        n = max(int(n), 0)
        with self._resize_lock:
            with self._lock:
                if self._stopped:
                    return 0
                cur = len(self._replicas)
                next_slot = ((self._replicas[-1].slot + 1)
                             if self._replicas else 0)
                doomed = []
                if n < cur:
                    doomed = self._replicas[n:]
                    del self._replicas[n:]
            if n > cur:
                for slot in range(next_slot, next_slot + (n - cur)):
                    rep = self._build_replica(slot)
                    if rep is not None:
                        with self._lock:
                            self._replicas.append(rep)
                self._record_event("scale_up", requested=n, got=self.count)
            elif n < cur:
                for rep in doomed:
                    if self.fleet is not None:
                        self.fleet.release_replica(self.entry.name, rep.slot)
                    rep.ens = None
                self._record_event("scale_down", requested=n, got=self.count)
        return self.count

    def stop(self) -> None:
        """Halt the prober and return every replica's bytes (idempotent;
        in-flight dispatches finish on plain references — the hot-swap
        semantics every other serving teardown uses)."""
        with self._resize_lock:
            with self._lock:
                if self._stopped:
                    return
                self._stopped = True
                doomed, self._replicas = self._replicas, []
            self._stop_event.set()
            prober, self._prober = self._prober, None
            if prober is not None:
                prober.join(timeout=5.0)
            for rep in doomed:
                if self.fleet is not None:
                    self.fleet.release_replica(self.entry.name, rep.slot)
                rep.ens = None

    # -- chaos / observability ----------------------------------------- #
    def arm_injector(self, injector) -> None:
        """Chaos hook: dispatches for replica slot `i` consult the
        injector op ``"replica:<i>"`` — `inj.fail("replica:1", count=8)`
        kills slot 1's next 8 dispatches (router AND prober)."""
        with self._lock:
            self._injector = injector

    def _record_event(self, what: str, **fields) -> None:
        ev = dict(what=what, model=self.entry.name, **fields)
        with self._lock:
            self._events.append(ev)
        if self.config is not None:
            fleet_event(self.config, "replica_" + what,
                        model=self.entry.name, **fields)

    def events(self) -> List[Dict]:
        with self._lock:
            return list(self._events)

    def snapshot(self) -> Dict:
        with self._lock:
            reps = [{
                "slot": r.slot, "device": r.dev_ord,
                "state": r.breaker.state, "healthy": r.healthy(),
                "outstanding_rows": r.outstanding,
                "dispatches": r.dispatches, "failures": r.failures,
                "probes": r.probes,
                "breaker": r.breaker.snapshot(),
            } for r in self._replicas]
            return {
                "count": len(reps),
                "healthy": sum(1 for r in reps if r["healthy"]),
                "failovers": self.failovers,
                "host_fallbacks": self.host_fallbacks,
                "reserve_failures": self.reserve_failures,
                "replicas": reps,
                "events": list(self._events),
            }


class ReplicaRouter:
    """Thin façade over a ReplicaSet's routing for callers that want the
    router without the lifecycle (tests, benches): picks the
    least-outstanding healthy replica and dispatches with loss-free
    failover, exactly :meth:`ReplicaSet.predict`."""

    def __init__(self, rset: ReplicaSet):
        self.rset = rset

    def route(self, X: np.ndarray, raw_score: bool = False):
        return self.rset.predict(X, raw_score=raw_score)
