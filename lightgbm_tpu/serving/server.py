"""TPU-resident inference server: in-process API + stdlib HTTP frontend.

Composition of the serving subsystem (docs/Serving.md has the full
architecture):

    HTTP POST /predict ─┐
                        ├─> Server.predict() ─> MicroBatcher (per model)
    in-process callers ─┘           │                  │ coalesce
                                    │                  v
                                    │        ModelRegistry.get(name)
                                    │                  │
                                    │        ModelEntry.predict(batch)
                                    │          device bucket path OR
                                    │          host walk (small batch)
                                    └─ backpressure: queue full ->
                                       host fallback (small) / 429

Everything is stdlib (http.server + json) — the box serving the model
has no web framework, matching the repo's no-new-deps constraint.
"""
from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional

import numpy as np

from ..config import Config
from ..obs import adapters as obs_adapters
from ..obs import default_registry
from ..obs import tracing as obs_tracing
from ..utils import log
from ..utils.profiling import Profiler
from .admission import CircuitBreaker, DrainingError, ShedError, TenantQuota
from .batcher import (BatcherStoppedError, MicroBatcher, QueueFullError,
                      RequestTimeoutError)
from .fleet import HbmResidencyManager, publish_fleet_metrics
from .metrics import ModelStats
from .registry import ModelEntry, ModelNotFoundError, ModelRegistry
from .shadow import ShadowMirror


class Server:
    """In-process serving frontend; one MicroBatcher + ModelStats per
    registered model name, all models sharing one registry/profiler."""

    def __init__(self, config: Optional[Config] = None, **overrides):
        if isinstance(config, Config) and not overrides:
            cfg = config
        elif isinstance(config, Config):
            cfg = Config(dict(config.raw_params, **overrides))
        else:
            cfg = Config(dict(config or {}, **overrides))
        self.config = cfg
        self.profiler = Profiler(enabled=True)
        # fleet residency: with a byte budget set, device memory becomes
        # an LRU-managed cache over the registry's models (serving/fleet)
        self.fleet = (HbmResidencyManager.from_config(cfg)
                      if cfg.tpu_fleet_hbm_budget_mb > 0 else None)
        self._quota = (TenantQuota(cfg.tpu_fleet_tenant_qps,
                                   cfg.tpu_fleet_tenant_burst)
                       if cfg.tpu_fleet_tenant_qps > 0 else None)
        self.registry = ModelRegistry(
            max_models=cfg.serve_max_models,
            min_device_work=cfg.serve_min_device_work,
            max_batch_rows=cfg.serve_max_batch_rows,
            warmup_buckets=cfg.serve_warmup_buckets or None,
            profiler=self.profiler,
            fleet=self.fleet,
            # tpu_replica_count=1 keeps entry.replicas None — the exact
            # pre-replica single-device path (byte-identity is pinned by
            # test); >1 places per-device fault-domain replicas
            replica_count=cfg.tpu_replica_count,
            replica_opts=dict(
                breaker_failures=cfg.tpu_replica_breaker_failures,
                breaker_reset_s=cfg.tpu_replica_breaker_reset_s,
                probe_interval_s=cfg.tpu_replica_probe_interval_s,
                probe_deadline_ms=cfg.tpu_replica_probe_deadline_ms,
                config=cfg))
        self._batchers: Dict[str, MicroBatcher] = {}
        self._stats: Dict[str, ModelStats] = {}
        self._breakers: Dict[str, CircuitBreaker] = {}
        self._shadows: Dict[str, ShadowMirror] = {}
        self._supervisor = None   # ContinuousLearningSupervisor, if attached
        self._draining = False
        # GET /metrics renders the process-wide registry: per-model
        # request counters published below, plus the device gauges and
        # comm counter families (rank-0 defaults so the exposition
        # always covers all four groups even single-machine)
        self.metrics = default_registry()
        obs_adapters.ensure_device_metrics(self.metrics)
        obs_adapters.ensure_comm_metrics(self.metrics)
        if self.fleet is not None:
            publish_fleet_metrics(self.metrics, self.fleet)
        # SLO alerting (obs/alerts.py): the rule engine ticks on every
        # stats snapshot and serves GET /alerts; init failure degrades
        # to a warning, never a dead server
        self.alerts = None
        if getattr(cfg, "tpu_alert", False):
            try:
                from ..obs.alerts import AlertEngine
                self.alerts = AlertEngine.from_config(cfg, self.metrics)
            except Exception as exc:  # noqa: BLE001 — alerting is optional
                log.warning("serving alerts disabled: engine init "
                            "failed (%s)", exc)
        # trend observatory (obs/timeseries.py): each stats tick also
        # samples the registry into a bounded series store, so /trends
        # answers trajectory questions (is p99 drifting? shed growing?)
        self.series = None
        self._trend_tick = 0
        self._trend_window = max(4, int(getattr(cfg, "tpu_trend_window",
                                                64) or 64))
        if getattr(cfg, "tpu_trend", False):
            from ..obs.timeseries import SeriesStore
            self.series = SeriesStore(capacity=self._trend_window)
            pats = str(getattr(cfg, "tpu_trend_metrics", "") or "")
            self._trend_include = [p.strip() for p in pats.split(",")
                                   if p.strip()] or None
        # span timeline for the request lifecycle (enqueue -> micro-batch
        # -> device -> respond) when tpu_trace_path is set; flushed on
        # shutdown and harmless to leave armed
        self._tracing = obs_tracing.configure_from_config(cfg) is not None
        self._lock = threading.Lock()
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._http_thread: Optional[threading.Thread] = None
        self._start_t = time.time()
        # replica-count lever for the policy engine (control/policy.py
        # scales up on queue pressure, down on residency pressure);
        # unbound in shutdown(), same pattern as the fleet's levers
        self._policy_levers = self._bind_policy_levers()

    # -- control-plane levers ------------------------------------------- #
    def _bind_policy_levers(self):
        if not bool(getattr(self.config, "tpu_policy", False)):
            return None
        from ..control import default_actuator

        def set_replica_count(args):
            return self._set_replica_count_lever(args or {})

        act = default_actuator()
        levers = [("set_replica_count", set_replica_count)]
        for name, fn in levers:
            act.bind(name, fn)
        return levers

    def _set_replica_count_lever(self, args: Dict) -> str:
        """Actuator-facing replica scaling: absolute ``count`` or
        relative ``delta``; without an explicit ``tenant`` the busiest
        queue is scaled up / the most-replicated tenant down.  Clamped
        to [tpu_replica_min, tpu_replica_max]; a no-op target raises so
        the policy engine records it instead of silently 'succeeding'."""
        delta = int(args.get("delta", 0))
        count = args.get("count")
        tenant = args.get("tenant") or args.get("model")
        if tenant is None:
            tenant = self._pick_scale_tenant(delta)
        if tenant is None:
            raise ValueError("no tenant eligible for replica scaling")
        lo = max(int(self.config.tpu_replica_min), 1)
        hi = max(int(self.config.tpu_replica_max), lo)
        rset = self.registry.replica_set(tenant)
        cur = rset.count if rset is not None else 1
        target = int(count) if count is not None else cur + delta
        target = min(max(target, lo), hi)
        if target == cur:
            raise ValueError(
                "tenant %s already at %d replica(s) (bounds %d..%d)"
                % (tenant, cur, lo, hi))
        got = self.registry.set_replica_count(tenant, target)
        obs_adapters.publish_replica_metrics(
            self.metrics, tenant,
            lambda _n=tenant: self.registry.replica_set(_n))
        return "tenant %s replicas %d -> %d" % (tenant, cur, got)

    def _pick_scale_tenant(self, delta: int) -> Optional[str]:
        """Scale-up targets the deepest queue (the tenant the alert is
        about); scale-down the most-replicated tenant (the biggest
        residency refund)."""
        with self._lock:
            batchers = dict(self._batchers)
        if delta >= 0:
            best, depth = None, -1
            for name, b in batchers.items():
                d = b.queue_depth_rows()
                if d > depth:
                    best, depth = name, d
            return best
        best, count = None, 1
        for name in batchers:
            rset = self.registry.replica_set(name)
            if rset is not None and rset.count > count:
                best, count = name, rset.count
        return best

    # -- model lifecycle ---------------------------------------------- #
    def load_model(self, name: Optional[str] = None,
                   model_str: Optional[str] = None,
                   model_file: Optional[str] = None,
                   params: Optional[Dict] = None,
                   checkpoint_dir: Optional[str] = None) -> ModelEntry:
        """Load/hot-swap a model under `name` and make it servable."""
        name = name or self.config.serve_model_name
        entry = self.registry.load(name, model_str=model_str,
                                   model_file=model_file, params=params,
                                   checkpoint_dir=checkpoint_dir)
        with self._lock:
            if name not in self._batchers:
                stats = ModelStats()
                self._stats[name] = stats
                cfg = self.config
                self._batchers[name] = MicroBatcher(
                    lambda X, _n=name: self._batch_predict(_n, X),
                    max_batch_rows=cfg.serve_max_batch_rows,
                    max_wait_ms=cfg.serve_batch_wait_ms,
                    max_queue_rows=cfg.serve_queue_rows,
                    timeout_ms=cfg.serve_request_timeout_ms,
                    stats=stats, name=name).start()
                self._breakers[name] = CircuitBreaker(
                    failure_threshold=cfg.tpu_serve_breaker_failures,
                    reset_s=cfg.tpu_serve_breaker_reset_s)
                obs_adapters.publish_model_stats(
                    self.metrics, name, stats,
                    queue_depth_fn=self._batchers[name].queue_depth_rows)
                obs_adapters.publish_breaker_metrics(
                    self.metrics, name, self._breakers[name])
                if self._quota is not None:
                    obs_adapters.publish_quota_metrics(
                        self.metrics, name, self._quota)
        if entry.replicas is not None:
            obs_adapters.publish_replica_metrics(
                self.metrics, name,
                lambda _n=name: self.registry.replica_set(_n))
        return entry

    def evict_model(self, name: str) -> bool:
        existed = self.registry.evict(name)
        with self._lock:
            batcher = self._batchers.pop(name, None)
            self._stats.pop(name, None)
            self._breakers.pop(name, None)
        if batcher is not None:
            batcher.stop()
        self.detach_shadow(name)
        obs_adapters.unpublish_model_stats(self.metrics, name)
        return existed

    # -- continuous learning ------------------------------------------- #
    def attach_shadow(self, name: str, mirror: ShadowMirror) -> None:
        """Mirror `name`'s served batches onto a candidate (replacing
        any previous mirror).  The swap is one dict assignment — traffic
        already in `_batch_predict` finishes on whichever mirror it
        resolved."""
        with self._lock:
            old = self._shadows.get(name)
            self._shadows[name] = mirror
        if old is not None:
            old.stop()

    def detach_shadow(self, name: str):
        with self._lock:
            mirror = self._shadows.pop(name, None)
        if mirror is not None:
            mirror.stop()
        return mirror

    def attach_supervisor(self, supervisor) -> None:
        """Expose a ContinuousLearningSupervisor on the HTTP frontend
        (POST /ingest, GET /supervisor).  Duck-typed: anything with
        ingest(rows, labels, weights) and snapshot()."""
        with self._lock:
            self._supervisor = supervisor

    # -- predict path -------------------------------------------------- #
    def _batch_predict(self, name: str, X: np.ndarray) -> np.ndarray:
        """The batcher's dispatch fn: resolve the CURRENT version at
        batch time (hot-swaps apply to the very next batch) and record
        which path the batch rode.  The circuit breaker guards the
        dispatch: while OPEN, batches ride the host walk — plain NumPy,
        no compilation, always available — so a sick device path turns
        into slower answers instead of an error storm."""
        entry = self.registry.get(name)
        stats = self._stats.get(name)
        breaker = self._breakers.get(name)
        if breaker is not None and not breaker.allow():
            with self.profiler.phase("serve/breaker_host"):
                out = entry.booster._gbdt.predict(X, device=False)
            if stats is not None:
                stats.record_breaker_batch()
                stats.record_batch(X.shape[0], device=False)
            out = np.asarray(out)
            self._mirror(name, X, out)
            return out
        try:
            with self.profiler.phase("serve/batch_predict"):
                out, device = entry.predict(X)
        except Exception:
            if breaker is not None:
                breaker.record_failure()
                if breaker.state == CircuitBreaker.OPEN:
                    log.warning("serving: circuit breaker for %s OPENED "
                                "(%d consecutive failures); batches ride "
                                "the host path for %.1fs", name,
                                breaker.failure_threshold, breaker.reset_s)
            raise
        if breaker is not None:
            breaker.record_success()
        if stats is not None:
            stats.record_batch(X.shape[0], device)
        out = np.asarray(out)
        self._mirror(name, X, out)
        return out

    def _mirror(self, name: str, X: np.ndarray, out: np.ndarray) -> None:
        """Offer a finished batch to the shadow mirror.  The live `out`
        is already final — observe() copies, never blocks and never
        raises, so the served response is bitwise mirror-independent."""
        shadow = self._shadows.get(name)
        if shadow is None:
            return
        try:
            shadow.observe(X, out)
        except Exception as exc:  # noqa: BLE001 — shadow never hurts serving
            log.debug("shadow observe failed for %s: %s", name, exc)

    def predict(self, rows, model: Optional[str] = None,
                timeout_ms: Optional[float] = None) -> np.ndarray:
        """Blocking predict through the coalescing queue.  `rows` is
        [n, features] (a single 1-D row is auto-wrapped).  Returns the
        per-row outputs ([n] scores or [n, k] multiclass)."""
        name = model or self.config.serve_model_name
        X = np.ascontiguousarray(np.asarray(rows, np.float64))
        if X.ndim == 1:
            X = X[None, :]
        if X.ndim != 2 or X.shape[0] == 0:
            raise ValueError("rows must be [n, features] with n >= 1")
        if self._draining:
            # whole-server state, checked before the model lookup: a
            # drained server answers 503 even for evicted models
            raise DrainingError("server is draining for shutdown")
        with self._lock:
            batcher = self._batchers.get(name)
            stats = self._stats.get(name)
        if batcher is None:
            raise ModelNotFoundError(name)
        if self._quota is not None:
            # per-tenant quota BEFORE the global queue shed: a noisy
            # tenant sheds against its own token bucket instead of
            # filling the shared queue until everyone sheds
            retry_after = self._quota.try_admit(name)
            if retry_after is not None:
                stats.record_shed()
                raise ShedError(
                    "tenant %s over its %.1f qps admission quota" % (
                        name, self._quota.qps),
                    retry_after_s=retry_after)
        shed_rows = self.config.tpu_serve_shed_queue_rows
        if shed_rows > 0 and (batcher.queue_depth_rows() + X.shape[0]
                              > shed_rows):
            # shed at the door: the queue never grows past the watermark
            # and the client gets an explicit come-back-later hint
            stats.record_shed()
            raise ShedError(
                "shedding load: %d rows queued (+%d over the %d watermark)"
                % (batcher.queue_depth_rows(), X.shape[0], shed_rows),
                retry_after_s=self.config.tpu_serve_shed_retry_after_s)
        stats.record_request(X.shape[0])
        t0 = time.perf_counter()
        with obs_tracing.span("serve/request", "serve", rows=X.shape[0],
                              model=name):
            try:
                out = batcher.submit(X, timeout_ms=timeout_ms)
            except QueueFullError:
                # graceful degradation: saturated queue + small request ->
                # serve it on the host walk RIGHT NOW on this thread; the
                # host path never waits on compilation, so overflow traffic
                # degrades to reference-speed instead of erroring
                if not (self.config.serve_host_fallback
                        and X.shape[0] <= self.config.serve_fallback_max_rows):
                    raise
                entry = self.registry.get(name)
                with self.profiler.phase("serve/host_fallback"):
                    out = entry.booster._gbdt.predict(X, device=False)
                stats.record_fallback()
                stats.record_batch(X.shape[0], device=False)
        stats.record_latency((time.perf_counter() - t0) * 1e3)
        return np.asarray(out)

    # -- observability ------------------------------------------------- #
    def stats_snapshot(self) -> Dict:
        with self._lock:
            stats = dict(self._stats)
            batchers = dict(self._batchers)
            breakers = {n: b.snapshot() for n, b in self._breakers.items()}
            tick = self._trend_tick = self._trend_tick + 1
        if self.series is not None:
            # sample BEFORE the alert tick so a trend rule evaluating
            # this tick sees the newest point (the store has its own
            # lock; only the tick counter needs ours)
            self.series.sample_registry(self.metrics, tick,
                                        include=self._trend_include)
        if self.alerts is not None:
            try:
                # each stats tick is an alert-engine tick: sustained and
                # burn-rate rules need a steady cadence to converge
                self.alerts.evaluate()
            except Exception as exc:  # noqa: BLE001 — never break /stats
                log.warning("alert evaluation failed (%s); disabling "
                            "serving alerts", exc)
                with self._lock:
                    self.alerts = None
        return {
            "uptime_s": round(time.time() - self._start_t, 3),
            "draining": self._draining,
            "models": {name: dict(s.snapshot(),
                                  queue_depth=batchers[name]
                                  .queue_depth_rows()
                                  if name in batchers else 0,
                                  breaker=breakers.get(name))
                       for name, s in stats.items()},
            "registry": self.registry.info(),
            "fleet": (self.fleet.snapshot()
                      if self.fleet is not None else None),
            "quota": (self._quota.snapshot()
                      if self._quota is not None else None),
            "phases": self.profiler.snapshot(),
            "alerts": (self.alerts.active()
                       if self.alerts is not None else None),
        }

    def metrics_text(self) -> str:
        """The registry in Prometheus text exposition format 0.0.4
        (GET /metrics)."""
        return self.metrics.render_prometheus()

    def trends_snapshot(self) -> Dict:
        """GET /trends: windowed summaries (slope / EWMA / quantiles)
        of every sampled series (obs/timeseries.py)."""
        if self.series is None:
            return {}
        return {"tick": self._trend_tick,
                "window": self._trend_window,
                "series": self.series.snapshot(self._trend_window)}

    # -- HTTP frontend ------------------------------------------------- #
    def serve_http(self, host: Optional[str] = None,
                   port: Optional[int] = None,
                   block: bool = True) -> ThreadingHTTPServer:
        host = host if host is not None else self.config.serve_host
        port = port if port is not None else self.config.serve_port
        httpd = ThreadingHTTPServer((host, port), _make_handler(self))
        httpd.daemon_threads = True
        with self._lock:
            self._httpd = httpd
        bound = httpd.server_address
        log.info("serving on http://%s:%d (POST /predict, GET /stats, "
                 "GET /metrics)", bound[0], bound[1])
        if block:
            try:
                httpd.serve_forever()
            except KeyboardInterrupt:
                log.info("interrupt: shutting down server")
            finally:
                self.shutdown()
        else:
            thread = threading.Thread(
                target=httpd.serve_forever, daemon=True,
                name="lgbm-serve-http")
            with self._lock:
                self._http_thread = thread
            thread.start()
        return httpd

    @property
    def http_port(self) -> Optional[int]:
        return self._httpd.server_address[1] if self._httpd else None

    # -- readiness + graceful drain ------------------------------------ #
    def is_ready(self) -> bool:
        """Readiness (GET /readyz): serving traffic is welcome — not
        draining and at least one model loaded.  Liveness (/livez) is
        unconditional: a draining server is alive, just not ready."""
        return not self._draining and bool(self.registry.names())

    def begin_drain(self) -> None:
        """Flip to draining: /readyz goes 503 (so load balancers stop
        sending), new predicts get DrainingError, queued + in-flight
        requests keep going."""
        with self._lock:
            if self._draining:
                return
            self._draining = True
            batchers = list(self._batchers.values())
        for b in batchers:
            b.begin_drain()
        log.info("serving: draining — no new work admitted, %d batcher(s) "
                 "finishing in-flight requests", len(batchers))

    def drain_and_shutdown(self, timeout_s: Optional[float] = None) -> bool:
        """Graceful termination: drain every batcher within `timeout_s`
        (Config.tpu_serve_drain_timeout_s by default), then shut the
        HTTP frontend and workers down.  Returns True when every
        admitted request completed before the deadline."""
        if timeout_s is None:
            timeout_s = self.config.tpu_serve_drain_timeout_s
        self.begin_drain()
        deadline = time.perf_counter() + max(float(timeout_s), 0.0)
        with self._lock:
            batchers = list(self._batchers.values())
        clean = True
        for b in batchers:
            clean &= b.drain(max(deadline - time.perf_counter(), 0.0))
        if not clean:
            log.warning("serving: drain timed out after %.1fs; remaining "
                        "requests get BatcherStoppedError", timeout_s)
        self.shutdown()
        return clean

    def install_signal_handlers(self) -> bool:
        """SIGTERM -> drain_and_shutdown in a background thread (the
        handler itself must return immediately so serve_forever's accept
        loop keeps answering in-flight connections).  Returns False when
        not on the main thread (signals unavailable)."""
        import signal as signal_mod

        def on_term(signum, _frame):
            log.warning("serving: signal %d — starting graceful drain "
                        "(timeout %.1fs)", signum,
                        self.config.tpu_serve_drain_timeout_s)
            threading.Thread(target=self.drain_and_shutdown,
                             name="lgbm-serve-drain", daemon=True).start()

        try:
            signal_mod.signal(signal_mod.SIGTERM, on_term)
        except ValueError:
            return False
        return True

    def shutdown(self) -> None:
        with self._lock:
            levers, self._policy_levers = self._policy_levers, None
        if levers:
            from ..control import default_actuator
            act = default_actuator()
            for lever_name, fn in levers:
                act.unbind(lever_name, fn)
        for name in self.registry.names():
            rset = self.registry.replica_set(name)
            if rset is not None:
                rset.stop()
        with self._lock:
            supervisor, self._supervisor = self._supervisor, None
        if supervisor is not None:
            try:
                supervisor.stop()
            except Exception as exc:  # noqa: BLE001 — teardown never raises
                log.warning("supervisor stop failed: %s", exc)
        with self._lock:
            httpd, self._httpd = self._httpd, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        with self._lock:
            batchers = list(self._batchers.values())
            self._batchers.clear()
            shadows = list(self._shadows.values())
            self._shadows.clear()
        for b in batchers:
            b.stop()
        for s in shadows:
            s.stop()
        if self.fleet is not None:
            self.fleet.stop()
        with self._lock:
            tracing, self._tracing = self._tracing, False
        if tracing:
            try:
                path = obs_tracing.get_tracer().flush()
                if path:
                    log.info("trace: span timeline written to %s", path)
            except Exception as exc:  # noqa: BLE001 — teardown never raises
                log.warning("trace flush failed: %s", exc)


def _make_handler(server: Server):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # route through our logger
            log.debug("http: " + fmt, *args)

        def _reply(self, code: int, payload: Dict,
                   headers: Optional[Dict[str, str]] = None) -> None:
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def _read_json(self) -> Dict:
            length = int(self.headers.get("Content-Length") or 0)
            if length <= 0:
                return {}
            return json.loads(self.rfile.read(length).decode() or "{}")

        def _reply_text(self, code: int, body: str, content_type: str) -> None:
            data = body.encode("utf-8")
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):
            path = self.path.split("?", 1)[0]
            if path == "/metrics":
                self._reply_text(200, server.metrics_text(),
                                 "text/plain; version=0.0.4; charset=utf-8")
            elif path == "/stats":
                self._reply(200, server.stats_snapshot())
            elif path == "/models":
                self._reply(200, {"models": server.registry.info()})
            elif path in ("/healthz", "/health", "/livez"):
                # liveness: the process is up and answering — even while
                # draining (kill a live-but-draining pod and you abandon
                # its in-flight requests)
                self._reply(200, {"status": "ok",
                                  "models": server.registry.names()})
            elif path == "/supervisor":
                sup = server._supervisor
                if sup is None:
                    self._reply(404, {"error": "no supervisor attached"})
                else:
                    self._reply(200, sup.snapshot())
            elif path == "/fleet":
                if server.fleet is None:
                    self._reply(404, {"error": "no fleet residency manager "
                                      "(set tpu_fleet_hbm_budget_mb)"})
                else:
                    self._reply(200, server.fleet.snapshot())
            elif path == "/alerts":
                if server.alerts is None:
                    self._reply(404, {"error": "alerting disabled "
                                      "(set tpu_alert)"})
                else:
                    self._reply(200, server.alerts.snapshot())
            elif path == "/trends":
                if server.series is None:
                    self._reply(404, {"error": "trend store disabled "
                                      "(set tpu_trend)"})
                else:
                    self._reply(200, server.trends_snapshot())
            elif path == "/cluster":
                from ..obs import federation as _federation
                self._reply(200,
                            _federation.cluster_snapshot(server.metrics))
            elif path == "/readyz":
                # readiness: route traffic here?  503 while draining or
                # model-less so load balancers rotate this replica out
                if server.is_ready():
                    self._reply(200, {"status": "ready",
                                      "models": server.registry.names()})
                else:
                    self._reply(503, {
                        "status": ("draining" if server._draining
                                   else "no models loaded")})
            else:
                self._reply(404, {"error": "unknown path %s" % path})

        def do_POST(self):
            path = self.path.split("?", 1)[0]
            try:
                payload = self._read_json()
            except (ValueError, json.JSONDecodeError) as e:
                self._reply(400, {"error": "bad JSON: %s" % e})
                return
            try:
                if path == "/predict":
                    self._predict(payload)
                elif path == "/ingest":
                    self._ingest(payload)
                elif path == "/models/load":
                    self._load(payload)
                elif path == "/models/evict":
                    name = payload.get("name") or ""
                    self._reply(200 if server.evict_model(name) else 404,
                                {"name": name})
                else:
                    self._reply(404, {"error": "unknown path %s" % path})
            except ModelNotFoundError as e:
                self._reply(404, {"error": "unknown model %s" % e})
            except ShedError as e:
                self._reply(429, {"error": str(e)},
                            headers={"Retry-After": "%d" % max(
                                1, int(round(e.retry_after_s)))})
            except QueueFullError as e:
                self._reply(429, {"error": str(e)},
                            headers={"Retry-After": "%d" % max(1, int(round(
                                server.config.tpu_serve_shed_retry_after_s)))})
            except RequestTimeoutError as e:
                self._reply(504, {"error": str(e)})
            except (BatcherStoppedError, DrainingError) as e:
                self._reply(503, {"error": str(e)})
            except (ValueError, TypeError, log.LightGBMError) as e:
                self._reply(400, {"error": str(e)})

        def _predict(self, payload: Dict) -> None:
            rows = payload.get("rows")
            if rows is None and "row" in payload:
                rows = [payload["row"]]
            if rows is None:
                raise ValueError('payload needs "rows" ([[...], ...]) '
                                 'or "row" ([...])')
            name = payload.get("model") or server.config.serve_model_name
            out = server.predict(rows, model=name,
                                 timeout_ms=payload.get("timeout_ms"))
            version = server.registry.get(name).version
            self._reply(200, {"model": name, "version": version,
                              "predictions": np.asarray(out).tolist()})

        def _ingest(self, payload: Dict) -> None:
            sup = server._supervisor
            if sup is None:
                self._reply(404, {"error": "no supervisor attached"})
                return
            rows = payload.get("rows")
            if rows is None:
                raise ValueError('payload needs "rows" ([[...], ...])')
            accepted, shed = sup.ingest(rows, payload.get("labels"),
                                        payload.get("weights"))
            self._reply(200, {"accepted": accepted, "shed": shed})

        def _load(self, payload: Dict) -> None:
            name = payload.get("name") or server.config.serve_model_name
            entry = server.load_model(
                name, model_str=payload.get("model_str"),
                model_file=payload.get("model_file"))
            self._reply(200, {"model": name, "version": entry.version,
                              "info": entry.info()})

    return Handler
