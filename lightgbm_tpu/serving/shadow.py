"""Shadow-mode traffic mirror for candidate models.

A `ShadowMirror` sits beside one served model: every batch the server
predicts is *offered* to the mirror AFTER the live output is final, and
a daemon worker replays it on the CANDIDATE booster to accumulate
paired-prediction divergence stats.  Three properties make it safe to
attach to production traffic:

- the serving thread only copies the batch and enqueues it — the live
  output array is never handed to the worker, so the served response is
  bitwise what it would be with no mirror attached;
- the queue is bounded and `observe` drops (with a counter) when the
  candidate can't keep up — shadow scoring sheds, serving never does;
- the worker predicts on the HOST walk, so a cold candidate never
  triggers an XLA compile on the serving box's device.

The quality verdict itself (held-out metric window) lives in
`resilience/supervisor.py`; the mirror answers the cheaper streaming
question "how far apart are live and candidate on real traffic".
"""
from __future__ import annotations

import queue
import threading
from typing import Dict, Optional

import numpy as np

from ..obs import default_registry
from ..utils import log


class ShadowMirror:
    """Paired live-vs-candidate predictions on mirrored traffic."""

    def __init__(self, name: str, booster, max_queue_batches: int = 64):
        self.name = name
        self.booster = booster
        # materialize any deferred trees NOW, on this thread: after this
        # the worker's predicts are pure reads, safe to run concurrently
        # with whoever else holds the candidate (supervisor, registry)
        booster._gbdt._sync_model()
        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, max_queue_batches))
        self._lock = threading.Lock()
        self._count = 0            # rows scored
        self._sum_abs = 0.0
        self._max_abs = 0.0
        self._dropped = 0          # rows shed off the full queue
        self._errors = 0
        self._offered = 0          # batches enqueued
        self._done = 0             # batches fully processed
        self._stopped = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="shadow-%s" % name, daemon=True)
        self._thread.start()

    # -- serving side --------------------------------------------------- #
    def observe(self, X: np.ndarray, live_out: np.ndarray) -> None:
        """Offer one served batch to the mirror.  Non-blocking, never
        raises, never mutates or retains the caller's arrays."""
        if self._stopped.is_set():
            return
        try:
            self._q.put_nowait((np.array(X, copy=True),
                                np.array(live_out, copy=True)))
            with self._lock:
                self._offered += 1
        except queue.Full:
            with self._lock:
                self._dropped += int(X.shape[0])
            default_registry().counter(
                "lgbm_shadow_dropped_total",
                help="Mirrored rows shed because the shadow queue was full",
                model=self.name).inc(int(X.shape[0]))

    # -- worker side ---------------------------------------------------- #
    def _run(self) -> None:
        while True:
            try:
                item = self._q.get(timeout=0.1)
            except queue.Empty:
                if self._stopped.is_set():
                    return
                continue
            if item is None:
                return
            X, live = item
            try:
                cand = self.booster._gbdt.predict(X, device=False)
                delta = np.abs(np.asarray(cand, np.float64).reshape(-1)
                               - np.asarray(live, np.float64).reshape(-1))
                with self._lock:
                    self._count += int(X.shape[0])
                    self._sum_abs += float(delta.sum())
                    self._max_abs = max(self._max_abs, float(delta.max()))
            except Exception as exc:   # noqa: BLE001 — shadow never escapes
                with self._lock:
                    self._errors += 1
                log.debug("shadow %s: scoring batch failed: %s",
                          self.name, exc)
            finally:
                with self._lock:
                    self._done += 1

    # -- lifecycle / stats ---------------------------------------------- #
    def snapshot(self) -> Dict:
        with self._lock:
            mean = self._sum_abs / self._count if self._count else 0.0
            return {
                "model": self.name,
                "rows": self._count,
                "mean_abs_delta": mean,
                "max_abs_delta": self._max_abs,
                "dropped_rows": self._dropped,
                "errors": self._errors,
                "pending_batches": self._q.qsize(),
            }

    def drain(self, timeout_s: float = 5.0) -> bool:
        """Best-effort wait until every offered batch is PROCESSED (not
        merely dequeued) — tests and the supervisor's shadow verdict
        read snapshot() right after this."""
        import time
        deadline = time.monotonic() + timeout_s

        def _settled() -> bool:
            with self._lock:
                return self._done >= self._offered
        while time.monotonic() < deadline:
            if _settled():
                return True
            time.sleep(0.01)
        return _settled()

    def stop(self, timeout_s: Optional[float] = 5.0) -> None:
        if self._stopped.is_set():
            return
        self._stopped.set()
        try:
            self._q.put_nowait(None)
        except queue.Full:
            pass
        self._thread.join(timeout=timeout_s)
