"""scikit-learn API wrappers.

Mirror of python-package/lightgbm/sklearn.py (868 LoC): LGBMModel base +
LGBMRegressor / LGBMClassifier / LGBMRanker, with custom-objective closures
over (y_true, y_pred [, weight, group]) and eval-metric wrappers returning
(name, value, is_higher_better) — same calling conventions so user code
moves over unchanged.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

from . import basic, engine
from .utils import log

try:
    from sklearn.base import BaseEstimator, ClassifierMixin, RegressorMixin
    from sklearn.exceptions import NotFittedError
    from sklearn.preprocessing import LabelEncoder
    from sklearn.utils.validation import check_array
    _SKLEARN = True
except ImportError:  # pragma: no cover
    BaseEstimator = object

    class ClassifierMixin:
        pass

    class RegressorMixin:
        pass

    class NotFittedError(ValueError):
        pass
    LabelEncoder = None
    check_array = None
    _SKLEARN = False


class LGBMNotFittedError(NotFittedError):
    """Raised on predict-before-fit: a NotFittedError subclass so
    sklearn tooling (check_is_fitted, pipelines) recognizes it
    (reference compat.py LGBMNotFittedError)."""


def _check_X(X, estimator=None):
    """Input validation shared by fit/predict: rejects complex and empty
    inputs with sklearn's messages, accepts CSR/CSC sparse (the Dataset
    layer bins sparse columns natively) and preserves NaN (missing
    values are first-class in GBDTs)."""
    if _SKLEARN:
        return check_array(X, accept_sparse=["csr", "csc"],
                           dtype=np.float64, ensure_all_finite=False,
                           estimator=estimator)
    return np.asarray(X, np.float64)


def _call_with_dataset(func: Callable, preds, dataset, what: str):
    """Dispatch a user callback taking (y_true, y_pred[, weight[, group]]).

    The arity is taken from inspect.signature so functools.partial and
    bound methods work; errors raised inside the callback propagate
    unchanged (the reference wrappers, sklearn.py:24-214)."""
    import inspect

    labels = dataset.get_label()
    argsets = {2: (labels, preds),
               3: (labels, preds, dataset.get_weight()),
               4: (labels, preds, dataset.get_weight(), dataset.get_group())}
    try:
        params = inspect.signature(func).parameters.values()
        if any(p.kind == inspect.Parameter.VAR_POSITIONAL for p in params):
            argc = 4
        else:
            argc = sum(p.kind in (inspect.Parameter.POSITIONAL_ONLY,
                                  inspect.Parameter.POSITIONAL_OR_KEYWORD)
                       for p in params)
    except (TypeError, ValueError):
        argc = 2
    if argc not in argsets:
        raise TypeError("Self-defined %s should have 2-4 arguments" % what)
    return func(*argsets[argc])


def _objective_from_callable(func: Callable):
    """Wrap sklearn-style fobj(y_true, y_pred[, weight[, group]]) into the
    engine's fobj(preds, dataset) (sklearn.py:24-118 _ObjectiveFunctionWrapper)."""
    def wrapped(preds, dataset):
        grad, hess = _call_with_dataset(func, preds, dataset, "objective")
        return grad, hess
    return wrapped


def _eval_from_callable(func: Callable):
    """sklearn-style feval(y_true, y_pred[, weight[, group]]) ->
    engine feval(preds, dataset) (sklearn.py:120-214)."""
    def wrapped(preds, dataset):
        return _call_with_dataset(func, preds, dataset, "eval function")
    return wrapped


def _apply_class_weight(class_weight, y, sample_weight):
    """dict / 'balanced' class_weight -> per-sample weights folded into
    sample_weight (reference _LGBMComputeSampleWeight usage,
    python-package/lightgbm/sklearn.py:488-493).  Returns sample_weight
    unchanged when class_weight is None."""
    if class_weight is None:
        return sample_weight
    if _SKLEARN:
        from sklearn.utils.class_weight import compute_sample_weight
        cw = compute_sample_weight(class_weight, y)
    else:
        y = np.asarray(y)
        classes, counts = np.unique(y, return_counts=True)
        if class_weight == "balanced":
            wmap = {c: len(y) / (len(classes) * cnt)
                    for c, cnt in zip(classes, counts)}
        elif isinstance(class_weight, dict):
            wmap = {c: class_weight.get(c, 1.0) for c in classes}
        else:
            raise ValueError("class_weight must be 'balanced' or a dict")
        cw = np.array([wmap[v] for v in y], np.float64)
    if sample_weight is None or len(sample_weight) == 0:
        return cw
    return np.multiply(np.asarray(sample_weight, np.float64), cw)


class LGBMModel(BaseEstimator):
    """Base sklearn estimator (sklearn.py:216-617)."""

    def __init__(self, boosting_type="gbdt", num_leaves=31, max_depth=-1,
                 learning_rate=0.1, n_estimators=100,
                 subsample_for_bin=200000, objective=None, class_weight=None,
                 min_split_gain=0.0, min_child_weight=1e-3, min_child_samples=20,
                 subsample=1.0, subsample_freq=0, colsample_bytree=1.0,
                 reg_alpha=0.0, reg_lambda=0.0, random_state=None,
                 n_jobs=-1, silent=True, importance_type="split", **kwargs):
        self.boosting_type = boosting_type
        self.num_leaves = num_leaves
        self.max_depth = max_depth
        self.learning_rate = learning_rate
        self.n_estimators = n_estimators
        self.subsample_for_bin = subsample_for_bin
        self.objective = objective
        self.class_weight = class_weight
        self.min_split_gain = min_split_gain
        self.min_child_weight = min_child_weight
        self.min_child_samples = min_child_samples
        self.subsample = subsample
        self.subsample_freq = subsample_freq
        self.colsample_bytree = colsample_bytree
        self.reg_alpha = reg_alpha
        self.reg_lambda = reg_lambda
        self.random_state = random_state
        self.n_jobs = n_jobs
        self.silent = silent
        self.importance_type = importance_type
        self._other_params = dict(kwargs)
        self._Booster: Optional[basic.Booster] = None
        self._evals_result = None
        self._best_iteration = -1
        self._best_score = {}
        self._n_features = None
        self._classes = None
        self._n_classes = None
        self.set_params(**kwargs)

    # -- sklearn plumbing --------------------------------------------------
    def get_params(self, deep=True):
        params = super().get_params(deep=deep) if _SKLEARN else {}
        params.update(self._other_params)
        return params

    def set_params(self, **params):
        for key, value in params.items():
            setattr(self, key, value)
            if hasattr(self, "_other_params"):
                self._other_params[key] = value
        return self

    def _process_params(self) -> Dict[str, Any]:
        params = self.get_params()
        params.pop("silent", None)
        params.pop("importance_type", None)
        params.pop("n_estimators", None)
        params.pop("class_weight", None)
        # sklearn-alias -> native names (sklearn.py:296-318)
        ren = {"boosting_type": "boosting", "min_split_gain": "min_gain_to_split",
               "min_child_weight": "min_sum_hessian_in_leaf",
               "min_child_samples": "min_data_in_leaf",
               "subsample": "bagging_fraction", "subsample_freq": "bagging_freq",
               "colsample_bytree": "feature_fraction",
               "reg_alpha": "lambda_l1", "reg_lambda": "lambda_l2",
               "random_state": "seed", "subsample_for_bin": "bin_construct_sample_cnt",
               "n_jobs": "num_threads"}
        for old, new in ren.items():
            if old in params:
                v = params.pop(old)
                if v is not None:
                    params[new] = v
        if params.get("seed") is None:
            params.pop("seed", None)
        if self.silent:
            params.setdefault("verbose", -1)
        obj = (self.objective if self.objective is not None
               else getattr(self, "_objective_resolved", None))
        if callable(obj):
            self._fobj = _objective_from_callable(obj)
            params["objective"] = "none"
        else:
            self._fobj = None
            if obj is not None:
                params["objective"] = obj
        # per-fit overrides (num_class etc.) — kept out of the constructor
        # params so refitting on different data re-derives them (sklearn
        # estimators must not mutate __init__ params in fit)
        params.update(getattr(self, "_fit_param_overrides", {}))
        return params

    # -- fit ---------------------------------------------------------------
    def fit(self, X, y, sample_weight=None, init_score=None, group=None,
            eval_set=None, eval_names=None, eval_sample_weight=None,
            eval_class_weight=None, eval_init_score=None, eval_group=None,
            eval_metric=None, early_stopping_rounds=None, verbose=True,
            feature_name="auto", categorical_feature="auto", callbacks=None):
        params = self._process_params()
        if eval_metric is not None and not callable(eval_metric):
            params["metric"] = eval_metric
        feval = _eval_from_callable(eval_metric) if callable(eval_metric) else None

        # class_weight -> per-sample weights multiplied into sample_weight
        # (reference fit path, python-package/lightgbm/sklearn.py:488-493).
        # LGBMClassifier folds it in on the ORIGINAL labels before
        # encoding (_cw_folded); this base path covers direct LGBMModel
        # users
        if not getattr(self, "_cw_folded", False):
            sample_weight = _apply_class_weight(self.class_weight, y,
                                                sample_weight)

        if y is None:
            raise ValueError(
                "requires y to be passed, but the target y is None")
        X = _check_X(X, estimator=self)
        if _SKLEARN:
            from sklearn.utils.validation import (check_consistent_length,
                                                  column_or_1d)
            if not callable(getattr(self, "objective", None)):
                # finite-label validation + 2d-column ravel with the
                # standard DataConversionWarning; custom objectives may
                # use unconventional label encodings, leave those alone
                y = column_or_1d(y, warn=True)
                y = check_array(y, ensure_2d=False, dtype=np.float64,
                                input_name="y")
            check_consistent_length(X, y)
        self._n_features = X.shape[1]
        # sklearn-protocol fitted marker (trailing underscore, set in
        # fit): check_is_fitted / pipelines key off it
        self.n_features_in_ = X.shape[1]
        train_set = basic.Dataset(X, label=y, weight=sample_weight,
                                  group=group, init_score=init_score,
                                  feature_name=feature_name,
                                  categorical_feature=categorical_feature)
        valid_sets: List[basic.Dataset] = []
        valid_names: List[str] = []
        if eval_set is not None:
            if isinstance(eval_set, tuple):
                eval_set = [eval_set]
            for i, (vx, vy) in enumerate(eval_set):
                vw = eval_sample_weight[i] if eval_sample_weight else None
                if eval_class_weight is not None and i < len(eval_class_weight):
                    vw = _apply_class_weight(eval_class_weight[i], vy, vw)
                vg = eval_group[i] if eval_group else None
                vi = eval_init_score[i] if eval_init_score else None
                valid_sets.append(basic.Dataset(
                    np.asarray(vx, np.float64), label=vy, weight=vw, group=vg,
                    init_score=vi, reference=train_set))
                valid_names.append(eval_names[i] if eval_names
                                   else "valid_%d" % i)

        evals_result: Dict[str, Any] = {}
        self._Booster = engine.train(
            params, train_set, num_boost_round=self.n_estimators,
            valid_sets=valid_sets or None, valid_names=valid_names or None,
            fobj=self._fobj, feval=feval,
            early_stopping_rounds=early_stopping_rounds,
            evals_result=evals_result, verbose_eval=verbose,
            callbacks=callbacks)
        self._evals_result = evals_result
        self._best_iteration = self._Booster.best_iteration
        self._best_score = self._Booster.best_score
        return self

    def predict(self, X, raw_score=False, num_iteration=-1,
                pred_leaf=False, pred_contrib=False, **kwargs):
        if self._Booster is None:
            raise LGBMNotFittedError(
                "Estimator not fitted, call fit before exploiting the model.")
        X = _check_X(X, estimator=self)
        if X.shape[1] != self._n_features:
            # sklearn's standard consistency error message
            raise ValueError(
                "X has %d features, but %s is expecting %d features "
                "as input." % (X.shape[1], type(self).__name__,
                               self._n_features))
        return self._Booster.predict(X, raw_score=raw_score,
                                     num_iteration=num_iteration,
                                     pred_leaf=pred_leaf,
                                     pred_contrib=pred_contrib)

    # -- attributes --------------------------------------------------------
    @property
    def n_features_(self):
        return self._n_features

    @property
    def booster_(self) -> basic.Booster:
        if self._Booster is None:
            raise LGBMNotFittedError(
                "No booster found. Need to call fit first.")
        return self._Booster

    def __sklearn_tags__(self):
        tags = super().__sklearn_tags__()
        tags.input_tags.sparse = True      # Dataset bins CSR/CSC natively
        tags.input_tags.allow_nan = True   # missing values are first-class
        return tags

    @property
    def best_iteration_(self):
        return self._best_iteration

    @property
    def best_score_(self):
        return self._best_score

    @property
    def evals_result_(self):
        return self._evals_result

    @property
    def feature_importances_(self) -> np.ndarray:
        return self.booster_.feature_importance(
            importance_type=self.importance_type)


class LGBMRegressor(RegressorMixin, LGBMModel):
    """sklearn.py:619-658."""

    def fit(self, X, y, **kwargs):
        self._objective_resolved = "regression"
        self._fit_param_overrides = {}
        return super().fit(X, y, **kwargs)


class LGBMClassifier(ClassifierMixin, LGBMModel):
    """sklearn.py:660-789."""

    def fit(self, X, y, **kwargs):
        if y is None:
            raise ValueError(
                "requires y to be passed, but the target y is None")
        y = np.asarray(y)
        if _SKLEARN:
            from sklearn.utils.multiclass import check_classification_targets
            from sklearn.utils.validation import column_or_1d
            if y.ndim > 1:
                y = column_or_1d(y, warn=True)
            if y.dtype.kind == "f" and not np.isfinite(y).all():
                raise ValueError(
                    "Input y contains NaN or infinity")
            # rejects continuous targets with the standard
            # "Unknown label type: continuous" error
            check_classification_targets(y)
        if LabelEncoder is not None:
            self._le = LabelEncoder().fit(y)
            y_enc = self._le.transform(y)
            self._classes = self._le.classes_
        else:
            self._classes = np.unique(y)
            y_enc = np.searchsorted(self._classes, y)
        self._n_classes = len(self._classes)
        self._objective_resolved = ("binary" if self._n_classes <= 2
                                    else "multiclass")
        self._fit_param_overrides = (
            {"num_class": self._n_classes} if self._n_classes > 2 else {})
        # dict class_weight keys refer to ORIGINAL labels: fold the
        # weights in here, before label encoding, so {label: w} works for
        # any label set (the v2.2.4 reference applies it to the encoded
        # labels — a landmine later LightGBM fixed; 'balanced' and
        # 0..k-1 integer dicts are unaffected either way)
        if self.class_weight is not None:
            kwargs["sample_weight"] = _apply_class_weight(
                self.class_weight, y, kwargs.get("sample_weight"))
        self._cw_folded = True
        try:
            return super().fit(X, y_enc, **kwargs)
        finally:
            self._cw_folded = False

    def predict(self, X, raw_score=False, num_iteration=-1,
                pred_leaf=False, pred_contrib=False, **kwargs):
        result = self.predict_proba(X, raw_score, num_iteration,
                                    pred_leaf, pred_contrib, **kwargs)
        if raw_score or pred_leaf or pred_contrib:
            return result
        if result.ndim > 1:
            idx = np.argmax(result, axis=1)
        else:
            idx = (result > 0.5).astype(int)
        return np.asarray(self._classes)[idx]

    def predict_proba(self, X, raw_score=False, num_iteration=-1,
                      pred_leaf=False, pred_contrib=False, **kwargs):
        result = super().predict(X, raw_score, num_iteration,
                                 pred_leaf, pred_contrib, **kwargs)
        if raw_score or pred_leaf or pred_contrib:
            return result
        if self._n_classes <= 2 and result.ndim == 1:
            return np.vstack([1.0 - result, result]).T
        return result

    @property
    def classes_(self):
        return self._classes

    @property
    def n_classes_(self):
        return self._n_classes


class LGBMRanker(LGBMModel):
    """sklearn.py:791-868."""

    def fit(self, X, y, group=None, eval_group=None, eval_at=(1, 2, 3, 4, 5),
            **kwargs):
        if group is None:
            raise ValueError("Should set group for ranking task")
        if kwargs.get("eval_set") is not None and eval_group is None:
            raise ValueError("Eval_group cannot be None when eval_set is not None")
        self._objective_resolved = "lambdarank"
        self._fit_param_overrides = {"ndcg_eval_at": list(eval_at)}
        self.eval_at = list(eval_at)
        return super().fit(X, y, group=group, eval_group=eval_group, **kwargs)
