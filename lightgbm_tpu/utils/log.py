"""Logging utilities.

TPU-native analogue of the reference logger (include/LightGBM/utils/log.h:20-103):
four levels (Fatal/Warning/Info/Debug), a registerable callback so host
applications (Python bindings, CLI) can reroute output, and CHECK helpers.
"""
from __future__ import annotations

import sys
from typing import Callable, Optional

FATAL = -1
WARNING = 0
INFO = 1
DEBUG = 2

_level = INFO
_callback: Optional[Callable[[str], None]] = None


class LightGBMError(RuntimeError):
    """Raised where the reference calls Log::Fatal (utils/log.h:70)."""


def set_level(level: int) -> None:
    global _level
    _level = level


def get_level() -> int:
    return _level


def set_callback(cb: Optional[Callable[[str], None]]) -> None:
    global _callback
    _callback = cb


def _write(level_str: str, msg: str) -> None:
    line = "[LightGBM-TPU] [%s] %s\n" % (level_str, msg)
    if _callback is not None:
        _callback(line)
    else:
        sys.stdout.write(line)
        sys.stdout.flush()


def debug(msg: str, *args) -> None:
    if _level >= DEBUG:
        _write("Debug", msg % args if args else msg)


def info(msg: str, *args) -> None:
    if _level >= INFO:
        _write("Info", msg % args if args else msg)


def warning(msg: str, *args) -> None:
    if _level >= WARNING:
        _write("Warning", msg % args if args else msg)


def fatal(msg: str, *args) -> None:
    text = msg % args if args else msg
    _write("Fatal", text)
    raise LightGBMError(text)


def check(condition: bool, msg: str = "Check failed") -> None:
    if not condition:
        fatal(msg)
