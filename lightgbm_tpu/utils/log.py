"""Logging utilities.

TPU-native analogue of the reference logger (include/LightGBM/utils/log.h:20-103):
four levels (Fatal/Warning/Info/Debug), a registerable callback so host
applications (Python bindings, CLI) can reroute output, and CHECK helpers.

Routing: Info/Debug go to stdout, Warning/Fatal to stderr — a piped CLI
run (`task=predict ... > preds.tsv`) must not have warnings corrupting
its output stream.  An opt-in structured mode (set_json_mode) emits one
JSON object per line with bound context fields (bind_context: rank,
model, iteration, ...) for log aggregators; the registered callback, when
set, receives the formatted line for either mode.
"""
from __future__ import annotations

import json
import sys
import time
from typing import Any, Callable, Dict, Optional

FATAL = -1
WARNING = 0
INFO = 1
DEBUG = 2

_LEVELS_BY_NAME = {
    "fatal": FATAL,
    "warning": WARNING, "warn": WARNING,
    "info": INFO,
    "debug": DEBUG,
}

_level = INFO
_callback: Optional[Callable[[str], None]] = None
_json_mode = False
_context: Dict[str, Any] = {}


class LightGBMError(RuntimeError):
    """Raised where the reference calls Log::Fatal (utils/log.h:70)."""


def set_level(level: int) -> None:
    global _level
    _level = level


def get_level() -> int:
    return _level


def set_level_by_name(name: str) -> None:
    """Set the level from its name ("debug" | "info" | "warning" |
    "fatal", case-insensitive; "warn" accepted)."""
    level = _LEVELS_BY_NAME.get(str(name).strip().lower())
    if level is None:
        fatal("Unknown log level %r (expected one of %s)"
              % (name, ", ".join(sorted(set(_LEVELS_BY_NAME)))))
    set_level(level)


def set_callback(cb: Optional[Callable[[str], None]]) -> None:
    global _callback
    _callback = cb


def set_json_mode(enabled: bool = True) -> None:
    """Structured mode: every line becomes one JSON object with ts /
    level / msg plus any bound context fields."""
    global _json_mode
    _json_mode = bool(enabled)


def get_json_mode() -> bool:
    return _json_mode


def bind_context(**fields) -> None:
    """Attach fields (rank, model, iteration, ...) to every subsequent
    JSON-mode line; a None value unbinds that field."""
    for k, v in fields.items():
        if v is None:
            _context.pop(k, None)
        else:
            _context[k] = v


def clear_context() -> None:
    _context.clear()


def _write(level_str: str, msg: str) -> None:
    if _json_mode:
        rec: Dict[str, Any] = {"ts": round(time.time(), 3),
                               "level": level_str.lower(), "msg": msg}
        rec.update(_context)
        line = json.dumps(rec, default=str) + "\n"
    else:
        line = "[LightGBM-TPU] [%s] %s\n" % (level_str, msg)
    if _callback is not None:
        _callback(line)
    else:
        stream = sys.stderr if level_str in ("Warning", "Fatal") else sys.stdout
        stream.write(line)
        stream.flush()


def debug(msg: str, *args) -> None:
    if _level >= DEBUG:
        _write("Debug", msg % args if args else msg)


def info(msg: str, *args) -> None:
    if _level >= INFO:
        _write("Info", msg % args if args else msg)


def warning(msg: str, *args) -> None:
    if _level >= WARNING:
        _write("Warning", msg % args if args else msg)


def fatal(msg: str, *args) -> None:
    text = msg % args if args else msg
    _write("Fatal", text)
    raise LightGBMError(text)


def check(condition: bool, msg: str = "Check failed") -> None:
    if not condition:
        fatal(msg)
