"""Per-phase timing — the TIMETAG analogue.

The reference accumulates per-phase std::chrono durations in the tree
learner and prints them at destruction (serial_tree_learner.cpp:15-42)
plus per-iteration wall clock in GBDT::Train (gbdt.cpp:251-254).  On TPU
the compute phases live inside ONE compiled lax.while_loop, so in-graph
phase attribution is impossible from the host; the subsystem therefore
has two halves:

- this module: host-side phase accumulators around every dispatch the
  driver makes (gradients / grow / drain / score / eval), with an
  optional per-phase device sync so the numbers mean device time and
  not dispatch time.  Enabled via Config.tpu_profile; report printed at
  booster teardown (GBDT.__del__) or on demand via profile_report().
- tools/phase_bench.py: standalone microbenchmarks of the device
  kernels (partition / segment-histogram / split-scan / label recovery)
  at real workload shapes — the in-loop attribution the host cannot see.

jax.profiler traces: set Config.tpu_profile_trace_dir to wrap training
in start_trace/stop_trace for TensorBoard-level analysis.
"""
from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Dict, Optional

from . import log
from ..obs import tracing


class Profiler:
    """Named wall-clock accumulators with optional device sync.

    sync_fn, when provided, is called at phase exit before the clock
    stops (a scalar device fetch), so asynchronously dispatched work is
    charged to the phase that launched it.  Without it, phases measure
    dispatch time only — still useful for host-overhead attribution.

    Accumulation is lock-guarded: the serving request path updates one
    shared Profiler from many HTTP worker threads.
    """

    def __init__(self, enabled: bool = False, sync_fn=None):
        self.enabled = enabled
        self.sync_fn = sync_fn
        self.totals: Dict[str, float] = {}
        self.counts: Dict[str, int] = {}
        self.mins: Dict[str, float] = {}
        self.maxs: Dict[str, float] = {}
        self._lock = threading.Lock()
        self._t0 = time.perf_counter()

    @contextmanager
    def phase(self, name: str):
        # every phase site doubles as a span site: the tracer records a
        # nested span for this phase even when the accumulators are off,
        # so tpu_trace_path alone yields a full timeline.  The span
        # closes AFTER sync_fn, so it covers device time like the clock.
        tracer = tracing.get_tracer()
        span = tracer.span(name, "phase") if tracer.enabled else None
        if not self.enabled and span is None:
            yield
            return
        if span is not None:
            span.__enter__()
        start = time.perf_counter()
        try:
            yield
        finally:
            if self.sync_fn is not None:
                try:
                    self.sync_fn()
                except Exception as exc:  # noqa: BLE001 — must not kill train
                    log.debug("profiler sync failed: %s", exc)
            if span is not None:
                try:
                    span.__exit__(None, None, None)
                except Exception as exc:  # noqa: BLE001
                    log.debug("profiler span exit failed: %s", exc)
            dt = time.perf_counter() - start
            if not self.enabled:
                return
            with self._lock:
                self.totals[name] = self.totals.get(name, 0.0) + dt
                self.counts[name] = self.counts.get(name, 0) + 1
                if dt < self.mins.get(name, float("inf")):
                    self.mins[name] = dt
                if dt > self.maxs.get(name, float("-inf")):
                    self.maxs[name] = dt

    def reset(self) -> None:
        """Zero every accumulator and restart the wall clock — serving
        /stats and long-running boosters can re-baseline instead of
        accumulating unboundedly stale totals."""
        with self._lock:
            self.totals.clear()
            self.counts.clear()
            self.mins.clear()
            self.maxs.clear()
            self._t0 = time.perf_counter()

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        """Machine-readable view of the accumulators (the /stats wire
        format of the serving subsystem): {phase: {total_s, calls,
        ms_per_call, min_ms, max_ms}}."""
        with self._lock:
            return {
                name: {
                    "total_s": round(total, 6),
                    "calls": self.counts[name],
                    "ms_per_call": round(
                        1e3 * total / max(self.counts[name], 1), 3),
                    "min_ms": round(1e3 * self.mins[name], 3),
                    "max_ms": round(1e3 * self.maxs[name], 3),
                }
                for name, total in self.totals.items()
            }

    def report(self, header: str = "profile") -> Optional[str]:
        if not self.enabled or not self.totals:
            return None
        wall = time.perf_counter() - self._t0
        tracked = sum(self.totals.values())
        lines = ["[%s] wall %.3fs, tracked %.3fs" % (header, wall, tracked)]
        for name, total in sorted(self.totals.items(), key=lambda kv: -kv[1]):
            c = self.counts[name]
            lines.append("  %-24s %8.3fs  (%6d calls, %7.2f ms/call)"
                         % (name, total, c, 1e3 * total / max(c, 1)))
        text = "\n".join(lines)
        log.info(text)
        return text


class TraceSession:
    """jax.profiler trace wrapper keyed off Config.tpu_profile_trace_dir."""

    def __init__(self, trace_dir: Optional[str]):
        self.trace_dir = trace_dir or None
        self._live = False

    def start(self):
        if not self.trace_dir or self._live:
            return
        import jax
        try:
            jax.profiler.start_trace(self.trace_dir)
        except RuntimeError as exc:
            # another profiler session is already live (e.g. two boosters
            # sharing one process) — don't claim ownership of it, and
            # don't let a double start_trace kill training
            log.warning("[profile] start_trace skipped: %s", exc)
            return
        self._live = True

    def stop(self):
        """Idempotent; callers run this in a `finally` (engine.train /
        GBDT.finish_telemetry) so a raising training loop cannot leak a
        live profiler session."""
        if not self._live:
            return
        self._live = False
        import jax
        try:
            jax.profiler.stop_trace()
        except Exception as exc:  # noqa: BLE001 — teardown must not raise
            log.warning("[profile] stop_trace failed: %s", exc)
            return
        log.info("[profile] jax trace written to %s", self.trace_dir)
