// Native text-data parser for lightgbm_tpu.
//
// The TPU framework's analogue of the reference's C++ Parser
// (src/io/parser.hpp:1-129, src/io/parser.cpp: CSVParser/TSVParser/
// LibSVMParser with format sniffing): one streaming pass over the file
// with a local strtod-style float scanner, multithreaded by row chunks.
// Exposed as a plain C ABI for ctypes (no pybind11 dependency).
//
// Build: g++ -O3 -march=native -shared -fPIC -o libtpugbdt_parser.so
//            fast_parser.cpp -lpthread
#include <algorithm>
#include <cctype>
#include <charconv>
#include <cstdio>
#include <functional>
#include <cstdint>
#include <cstdlib>
#include <cmath>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

namespace {

// from_chars leaves *out unmodified on result_out_of_range; recover the
// strtod/Python-float() result (+-inf on overflow, +-0 on underflow) from
// the token's decimal exponent — any out-of-range token is far beyond the
// +-308 boundary, so the sign of the estimate decides.
inline double out_of_range_value(const char* first, const char* last) {
  bool neg = (first < last && *first == '-');
  if (first < last && (*first == '-' || *first == '+')) ++first;
  long intdig = 0, fraczeros = 0;
  bool seen_nonzero = false;
  const char* p = first;
  while (p < last && *p >= '0' && *p <= '9') {
    if (*p != '0' || seen_nonzero) { seen_nonzero = true; ++intdig; }
    ++p;
  }
  if (p < last && *p == '.') {
    ++p;
    while (p < last && *p >= '0' && *p <= '9') {
      if (!seen_nonzero) {
        if (*p == '0') ++fraczeros; else seen_nonzero = true;
      }
      ++p;
    }
  }
  long ex = 0;
  if (p < last && (*p == 'e' || *p == 'E')) {
    ++p;
    bool eneg = false;
    if (p < last && (*p == '-' || *p == '+')) { eneg = (*p == '-'); ++p; }
    while (p < last && *p >= '0' && *p <= '9' && ex < 1000000)
      ex = ex * 10 + (*p - '0');
    if (eneg) ex = -ex;
  }
  long dec = ex + (intdig > 0 ? intdig : -fraczeros);
  double v = dec > 0 ? HUGE_VAL : 0.0;
  return neg ? -v : v;
}

// locale-independent, correctly-rounded double parse: strtod obeys
// LC_NUMERIC (a host app's setlocale(LC_NUMERIC, "de_DE") would silently
// stop every "3.14" at the '.'), std::from_chars never does, and it
// matches Python float() bit-for-bit.  Accepts inf/nan (general fmt).
// Returns the end of the consumed token, or `first` on failure.
inline const char* parse_double(const char* first, const char* last,
                                double* out) {
  auto res = std::from_chars(first, last, *out);
  if (res.ec == std::errc::result_out_of_range) {
    *out = out_of_range_value(first, res.ptr);
    return res.ptr;
  }
  if (res.ec != std::errc())
    return first;
  return res.ptr;
}

// fast float parse: short integers on the fast path; anything with a
// fraction, exponent, or >15 digits goes through from_chars so the
// result is bit-identical to the Python fallback's float() (binning is
// boundary-sensitive, so the two parse paths must agree exactly, not to
// within a few ULP).  `lend` bounds the scan (line end; the file buffer
// is not NUL-terminated).
inline const char* fast_atof(const char* p, const char* lend, double* out) {
  while (p < lend && *p == ' ') ++p;
  bool neg = false;
  if (p < lend && (*p == '-' || *p == '+')) {
    neg = (*p == '-');
    ++p;
  }
  if (p < lend &&
      (std::isdigit(static_cast<unsigned char>(*p)) || *p == '.')) {
    const char* digs = p;   // from_chars takes '-' but not '+': re-sign
    double v = 0.0;
    int digits = 0;
    while (p < lend && std::isdigit(static_cast<unsigned char>(*p))) {
      v = v * 10.0 + (*p - '0');
      ++digits;
      ++p;
    }
    // >15 digits: v*10+d double-rounds past 2^53; from_chars rounds once
    if ((p < lend && (*p == '.' || *p == 'e' || *p == 'E'))
        || digits > 15) {
      double d = 0.0;
      const char* q = parse_double(digs, lend, &d);
      if (q == digs) {
        *out = std::nan("");
        return p;
      }
      *out = neg ? -d : d;
      return q;
    }
    *out = neg ? -v : v;
    return p;
  }
  // nan / inf / NA / empty field: from_chars handles nan/inf; anything it
  // cannot consume (NA, empty before a separator) becomes NaN so missing
  // values match the pandas fallback (NaN), not silently 0.0
  double d = 0.0;
  const char* q = parse_double(p, lend, &d);
  if (q == p) {
    *out = std::nan("");
    return p;
  }
  *out = neg ? -d : d;
  return q;
}

struct Lines {
  const char* data;
  std::vector<size_t> offsets;  // start of each line
  std::vector<size_t> ends;
};

void split_lines(const char* buf, size_t len, Lines* out) {
  out->data = buf;
  size_t i = 0;
  while (i < len) {
    size_t start = i;
    while (i < len && buf[i] != '\n') ++i;
    size_t end = i;
    if (end > start && buf[end - 1] == '\r') --end;
    // skip blank lines and '#' comment lines (pandas fallback: comment='#')
    if (end > start && buf[start] != '#') {
      out->offsets.push_back(start);
      out->ends.push_back(end);
    }
    ++i;
  }
}

int count_columns(const char* p, const char* end, char sep) {
  int n = 1;
  for (; p < end; ++p)
    if (*p == sep) ++n;
  return n;
}

void parse_rows_delim(const Lines& lines, size_t row0, size_t row1,
                      char sep, int ncol, double* out) {
  for (size_t r = row0; r < row1; ++r) {
    const char* p = lines.data + lines.offsets[r];
    const char* end = lines.data + lines.ends[r];
    double* dst = out + r * ncol;
    for (int c = 0; c < ncol; ++c) {
      if (p >= end) {
        // short row: trailing fields are missing -> NaN (pandas parity)
        dst[c] = std::nan("");
        continue;
      }
      double v = 0.0;
      p = fast_atof(p, end, &v);
      dst[c] = v;
      while (p < end && *p != sep) ++p;
      if (p < end) ++p;  // skip separator
    }
  }
}

void parse_rows_libsvm(const Lines& lines, size_t row0, size_t row1,
                       int ncol, double* out, double* labels) {
  for (size_t r = row0; r < row1; ++r) {
    const char* p = lines.data + lines.offsets[r];
    const char* end = lines.data + lines.ends[r];
    double* dst = out + r * ncol;
    std::memset(dst, 0, sizeof(double) * ncol);
    double lab = 0.0;
    p = fast_atof(p, end, &lab);
    labels[r] = lab;
    while (p < end) {
      while (p < end && *p == ' ') ++p;
      if (p >= end || *p == '#') break;
      double idx = 0.0;
      p = fast_atof(p, end, &idx);
      if (p < end && *p == ':') {
        ++p;
        double v = 0.0;
        p = fast_atof(p, end, &v);
        // bound BEFORE the cast: double->int of an out-of-range value
        // (huge index, inf, nan) is undefined behavior
        if (idx >= 0.0 && idx < 2147483647.0) {
          int i = static_cast<int>(idx);
          if (i < ncol) dst[i] = v;
        }
      } else {
        while (p < end && *p != ' ') ++p;
      }
    }
  }
}

int libsvm_max_index(const Lines& lines, size_t row0, size_t row1) {
  int mx = -1;
  for (size_t r = row0; r < row1; ++r) {
    const char* p = lines.data + lines.offsets[r];
    const char* end = lines.data + lines.ends[r];
    double lab;
    p = fast_atof(p, end, &lab);
    while (p < end) {
      while (p < end && *p == ' ') ++p;
      if (p >= end || *p == '#') break;
      double idx = 0.0;
      p = fast_atof(p, end, &idx);
      if (p < end && *p == ':') {
        ++p;
        double v;
        p = fast_atof(p, end, &v);
        if (idx >= 0.0 && idx < 2147483647.0 && static_cast<int>(idx) > mx)
          mx = static_cast<int>(idx);
      } else {
        while (p < end && *p != ' ') ++p;
      }
    }
  }
  return mx;
}

void parallel_for(size_t n, int threads,
                  const std::function<void(size_t, size_t)>& fn) {
  if (threads <= 1 || n < 4096) {
    fn(0, n);
    return;
  }
  std::vector<std::thread> pool;
  size_t chunk = (n + threads - 1) / threads;
  for (int t = 0; t < threads; ++t) {
    size_t a = t * chunk, b = std::min(n, a + chunk);
    if (a >= b) break;
    pool.emplace_back(fn, a, b);
  }
  for (auto& th : pool) th.join();
}

}  // namespace

extern "C" {

// Parses `path`.  Returns 0 on success.
//   format out: 0 csv, 1 tsv, 2 libsvm
//   data out:   row-major [rows, cols] doubles (malloc'd)
//   labels out: [rows] doubles (malloc'd), only for libsvm, else null
// The caller frees both with tpugbdt_free.
int tpugbdt_parse_file(const char* path, int skip_header, int num_threads,
                       int num_features_hint,
                       int64_t* out_rows, int64_t* out_cols,
                       double** out_data, double** out_labels,
                       int* out_format) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return 1;
  std::fseek(f, 0, SEEK_END);
  long size = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  std::vector<char> buf(static_cast<size_t>(size));
  if (size > 0 && std::fread(buf.data(), 1, size, f) != (size_t)size) {
    std::fclose(f);
    return 2;
  }
  std::fclose(f);

  Lines lines;
  split_lines(buf.data(), buf.size(), &lines);
  size_t first = skip_header ? 1 : 0;
  if (lines.offsets.size() <= first) return 3;
  size_t nrows = lines.offsets.size() - first;
  Lines body;
  body.data = lines.data;
  body.offsets.assign(lines.offsets.begin() + first, lines.offsets.end());
  body.ends.assign(lines.ends.begin() + first, lines.ends.end());

  // format sniff: colon takes precedence over the delimiters (reference
  // parser.cpp:136; parser.py detect_format implements the same rule), so
  // both parse paths agree no matter which one ran.  A colon inside the
  // first token (the label) is ignored, lines are stripped of surrounding
  // whitespace first, and separator-less lines (featureless libsvm rows)
  // are inconclusive — look at the next line, up to 32 like _read_head.
  bool has_tab = false, has_comma = false, has_colon = false;
  for (size_t r = 0; r < nrows && r < 32; ++r) {
    const char* q0 = body.data + body.offsets[r];
    const char* qe = body.data + body.ends[r];
    while (q0 < qe && (*q0 == ' ' || *q0 == '\t')) ++q0;   // strip, like
    while (qe > q0 && (qe[-1] == ' ' || qe[-1] == '\t')) --qe;  // .strip()
    bool tab = false, comma = false, colon = false, past_first = false;
    for (const char* q = q0; q < qe; ++q) {
      if (*q == '\t') { tab = true; past_first = true; }
      else if (*q == ',') { comma = true; past_first = true; }
      else if (*q == ' ') { past_first = true; }
      else if (*q == ':' && past_first) { colon = true; }
    }
    if (!past_first) continue;   // single token: inconclusive
    has_tab = tab; has_comma = comma; has_colon = colon;
    break;
  }
  const char* p = body.data + body.offsets[0];
  const char* end = body.data + body.ends[0];
  int threads = num_threads > 0
      ? num_threads
      : static_cast<int>(std::thread::hardware_concurrency());

  if (has_colon) {
    // libsvm
    std::vector<int> maxes(threads > 0 ? threads : 1, -1);
    {
      int T = threads > 0 ? threads : 1;
      std::vector<std::thread> pool;
      size_t chunk = (nrows + T - 1) / T;
      for (int t = 0; t < T; ++t) {
        size_t a = t * chunk, b = std::min(nrows, a + chunk);
        if (a >= b) break;
        pool.emplace_back([&, t, a, b]() {
          maxes[t] = libsvm_max_index(body, a, b);
        });
      }
      for (auto& th : pool) th.join();
    }
    int mx = num_features_hint - 1;
    for (int m : maxes)
      if (m > mx) mx = m;
    int ncol = mx + 1;
    double* data =
        static_cast<double*>(std::malloc(sizeof(double) * nrows * ncol));
    double* labels = static_cast<double*>(std::malloc(sizeof(double) * nrows));
    if (!data || !labels) {
      std::free(data);
      std::free(labels);
      return 4;
    }
    parallel_for(nrows, threads, [&](size_t a, size_t b) {
      parse_rows_libsvm(body, a, b, ncol, data, labels);
    });
    *out_rows = static_cast<int64_t>(nrows);
    *out_cols = ncol;
    *out_data = data;
    *out_labels = labels;
    *out_format = 2;
    return 0;
  }

  char sep = has_tab ? '\t' : (has_comma ? ',' : '\t');
  int ncol = count_columns(p, end, sep);
  double* data =
      static_cast<double*>(std::malloc(sizeof(double) * nrows * ncol));
  if (!data) return 4;
  parallel_for(nrows, threads, [&](size_t a, size_t b) {
    parse_rows_delim(body, a, b, sep, ncol, data);
  });
  *out_rows = static_cast<int64_t>(nrows);
  *out_cols = ncol;
  *out_data = data;
  *out_labels = nullptr;
  *out_format = has_tab ? 1 : 0;
  return 0;
}

void tpugbdt_free(void* p) { std::free(p); }

}  // extern "C"
