# Training callbacks (the reference's R-package/R/callback.R factories;
# each returns function(env) where env carries booster / iteration /
# eval records — same CallbackEnv idiom as the Python package).

#' env fields: booster, iteration, begin_iteration, end_iteration,
#' eval_list (records from lgb.Booster.eval), met_early_stop (set by
#' cb.early.stop to end the loop).
CB_ENV_FIELDS <- c("booster", "iteration", "begin_iteration",
                   "end_iteration", "eval_list", "met_early_stop")

cb.print.evaluation <- function(period = 1L) {
  function(env) {
    if (period <= 0L || (env$iteration %% period) != 0L) return(invisible())
    msgs <- vapply(env$eval_list, function(r) {
      sprintf("%s's %s:%g", r$data_name, r$name, r$value)
    }, character(1))
    cat(sprintf("[%d]\t%s\n", env$iteration, paste(msgs, collapse = "\t")))
  }
}

cb.record.evaluation <- function() {
  function(env) {
    bst <- env$booster
    for (r in env$eval_list) {
      d <- r$data_name
      m <- r$name
      if (is.null(bst$record_evals[[d]])) bst$record_evals[[d]] <- list()
      if (is.null(bst$record_evals[[d]][[m]])) {
        bst$record_evals[[d]][[m]] <- list(eval = list())
      }
      k <- length(bst$record_evals[[d]][[m]]$eval) + 1L
      bst$record_evals[[d]][[m]]$eval[[k]] <- r$value
    }
  }
}

#' Reset parameters on a schedule: values are either a vector (one per
#' iteration) or function(iteration, total) -> value.  Marked
#' pre-iteration: lgb.train runs it BEFORE every boosting update so the
#' schedule applies to the iteration about to train (the reference's
#' before_iteration callback ordering).
cb.reset.parameter <- function(new_params) {
  stopifnot(is.list(new_params))
  cb <- function(env) {
    i <- env$iteration - env$begin_iteration + 1L
    total <- env$end_iteration - env$begin_iteration + 1L
    resolved <- lapply(new_params, function(spec) {
      if (is.function(spec)) spec(i, total) else spec[[min(i, length(spec))]]
    })
    lgb.Booster.reset_parameter(env$booster, resolved)
  }
  attr(cb, "is_pre_iteration") <- TRUE
  cb
}

#' Stop when the first validation metric stops improving for
#' stopping_rounds iterations; stores best_iter/best_score on the
#' booster and rolls back to it (reference cb.early.stop).
cb.early.stop <- function(stopping_rounds, verbose = TRUE) {
  best <- new.env(parent = emptyenv())
  best$score <- NA_real_
  best$iter <- -1L
  best$since <- 0L
  function(env) {
    recs <- Filter(function(r) r$data_name != "train", env$eval_list)
    if (length(recs) == 0L) return(invisible())
    r <- recs[[1L]]
    better <- if (is.na(best$score)) TRUE
              else if (r$higher_better) r$value > best$score
              else r$value < best$score
    if (better) {
      best$score <- r$value
      best$iter <- env$iteration
      best$since <- 0L
    } else {
      best$since <- best$since + 1L
      if (best$since >= stopping_rounds) {
        env$booster$best_iter <- best$iter
        env$booster$best_score <- best$score
        env$met_early_stop <- TRUE
        if (verbose) {
          cat(sprintf("Early stopping, best iteration: [%d] %s: %g\n",
                      best$iter, r$name, best$score))
        }
      }
    }
    env$booster$best_iter <- best$iter
    env$booster$best_score <- best$score
  }
}
