# lgb.Booster: the training/prediction handle over the C ABI (the
# reference's R-package/R/lgb.Booster.R + lgb.Predictor.R roles on
# plain environments; .Call glue in src/lightgbm_tpu_R.c).

#' Internal constructor: exactly one of train_set / modelfile /
#' model_str must be given (mirrors the reference Booster$initialize).
Booster <- function(params = list(), train_set = NULL, modelfile = NULL,
                    model_str = NULL) {
  lgb.load_lib()
  env <- new.env(parent = emptyenv())
  env$params <- params
  env$valid_sets <- list()
  env$valid_names <- character(0)
  env$record_evals <- list()
  env$best_iter <- -1L
  env$best_score <- NA_real_
  if (!is.null(train_set)) {
    stopifnot(lgb.is.Dataset(train_set))
    lgb.Dataset.construct(train_set)
    env$train_set <- train_set
    env$handle <- .Call("LGBMR_BoosterCreate", train_set$handle,
                        lgb.params2str(params))
  } else if (!is.null(modelfile)) {
    env$handle <- .Call("LGBMR_BoosterCreateFromModelfile", modelfile)
  } else if (!is.null(model_str)) {
    env$handle <- .Call("LGBMR_BoosterLoadModelFromString", model_str)
  } else {
    stop("Booster needs train_set, modelfile or model_str")
  }
  class(env) <- "lgb.Booster"
  env
}

lgb.Booster.add_valid <- function(booster, data, name) {
  stopifnot(lgb.is.Booster(booster), lgb.is.Dataset(data))
  lgb.Dataset.construct(data)
  .Call("LGBMR_BoosterAddValidData", booster$handle, data$handle)
  booster$valid_sets[[length(booster$valid_sets) + 1L]] <- data
  booster$valid_names <- c(booster$valid_names, name)
  invisible(booster)
}

#' One boosting iteration; fobj(preds, train_set) -> list(grad, hess)
#' switches to the custom-objective path (UpdateOneIterCustom).
lgb.Booster.update <- function(booster, fobj = NULL) {
  if (is.null(fobj)) {
    finished <- .Call("LGBMR_BoosterUpdateOneIter", booster$handle)
  } else {
    preds <- lgb.Booster.inner_predict(booster, 0L)
    gh <- fobj(preds, booster$train_set)
    if (!is.list(gh) || is.null(gh$grad) || is.null(gh$hess)) {
      stop("fobj must return list(grad = ..., hess = ...)")
    }
    finished <- .Call("LGBMR_BoosterUpdateOneIterCustom", booster$handle,
                      as.double(gh$grad), as.double(gh$hess))
  }
  invisible(finished)
}

lgb.Booster.rollback_one_iter <- function(booster) {
  .Call("LGBMR_BoosterRollbackOneIter", booster$handle)
  invisible(booster)
}

lgb.Booster.current_iter <- function(booster) {
  .Call("LGBMR_BoosterGetCurrentIteration", booster$handle)
}

#' Raw scores on the train (data_idx = 0) or a valid set (1-based after
#' that) — the Booster::GetPredict path used by custom fobj/feval:
#' reads the engine's incrementally-maintained scores, no re-binning or
#' ensemble re-walk (the reference's __inner_predict).
lgb.Booster.inner_predict <- function(booster, data_idx = 0L) {
  .Call("LGBMR_BoosterGetPredict", booster$handle, as.integer(data_idx))
}

#' Evaluate on train + every added valid set; returns a list of
#' records: list(data_name, name, value, higher_better).  A custom
#' feval(preds, dataset) -> list(name, value, higher_better) runs on
#' EVERY set (train raw scores + each valid's raw scores via
#' GetPredict), like the reference's per-valid feval loop.
lgb.Booster.eval <- function(booster, feval = NULL) {
  names_ <- .Call("LGBMR_BoosterGetEvalNames", booster$handle)
  sets <- c("train", booster$valid_names)
  datasets <- c(list(booster$train_set), booster$valid_sets)
  out <- list()
  for (idx in seq_along(sets) - 1L) {
    vals <- .Call("LGBMR_BoosterGetEval", booster$handle, idx)
    for (j in seq_along(vals)) {
      out[[length(out) + 1L]] <- list(
        data_name = sets[idx + 1L], name = names_[j], value = vals[j],
        higher_better = lgb.metric.higher_better(names_[j]))
    }
    if (!is.null(feval)) {
      preds <- lgb.Booster.inner_predict(booster, idx)
      fr <- feval(preds, datasets[[idx + 1L]])
      out[[length(out) + 1L]] <- list(
        data_name = sets[idx + 1L], name = fr[[1L]], value = fr[[2L]],
        higher_better = isTRUE(fr[[3L]]))
    }
  }
  out
}

#' Predict on a new matrix.
#' @param rawscore,predleaf,predcontrib select the output type
#'   (margin / leaf indices / per-feature SHAP contributions)
predict.lgb.Booster <- function(object, data, num_iteration = -1L,
                                rawscore = FALSE, predleaf = FALSE,
                                predcontrib = FALSE, header = FALSE,
                                reshape = TRUE, params = "", ...) {
  if (is.data.frame(data)) data <- as.matrix(data)
  if (!is.double(data)) storage.mode(data) <- "double"
  ptype <- 0L
  if (rawscore) ptype <- 1L
  if (predleaf) ptype <- 2L
  if (predcontrib) ptype <- 3L
  out <- .Call("LGBMR_BoosterPredictForMat", object$handle, data, ptype,
               as.integer(num_iteration), params)
  n <- nrow(data)
  if (reshape && length(out) > n && length(out) %% n == 0L) {
    # multiclass / leaf / contrib outputs come back row-major
    out <- matrix(out, nrow = n, byrow = TRUE)
  }
  out
}

lgb.Booster.save_model <- function(booster, filename,
                                   num_iteration = -1L) {
  .Call("LGBMR_BoosterSaveModel", booster$handle,
        as.integer(num_iteration), filename)
  invisible(booster)
}

lgb.Booster.to_string <- function(booster, num_iteration = -1L) {
  .Call("LGBMR_BoosterSaveModelToString", booster$handle,
        as.integer(num_iteration))
}

lgb.Booster.dump_model <- function(booster, num_iteration = -1L) {
  .Call("LGBMR_BoosterDumpModel", booster$handle,
        as.integer(num_iteration))
}

lgb.Booster.reset_parameter <- function(booster, params) {
  .Call("LGBMR_BoosterResetParameter", booster$handle,
        lgb.params2str(params))
  booster$params <- utils::modifyList(booster$params, params)
  invisible(booster)
}

#' Load a model from a text file written by save_model (also reads
#' models written by the reference implementation — the two speak the
#' same format, gbdt_model_text.cpp:244,343).
lgb.load <- function(filename = NULL, model_str = NULL) {
  if (!is.null(filename)) return(Booster(modelfile = filename))
  if (!is.null(model_str)) return(Booster(model_str = model_str))
  stop("either filename or model_str is required")
}

lgb.save <- function(booster, filename, num_iteration = -1L) {
  lgb.Booster.save_model(booster, filename, num_iteration)
}

#' RDS round-trip: embed the model text so standard R serialization
#' works on the otherwise-external handle (the reference's
#' saveRDS.lgb.Booster / readRDS.lgb.Booster pair).
saveRDS.lgb.Booster <- function(object, file, num_iteration = -1L, ...) {
  raw_model <- lgb.Booster.to_string(object, num_iteration)
  saveRDS(list(class = "lgb.Booster.raw", model_str = raw_model,
               params = object$params, best_iter = object$best_iter,
               record_evals = object$record_evals), file = file, ...)
}

readRDS.lgb.Booster <- function(file, ...) {
  blob <- readRDS(file, ...)
  stopifnot(identical(blob$class, "lgb.Booster.raw"))
  booster <- Booster(model_str = blob$model_str)
  booster$params <- blob$params
  booster$best_iter <- blob$best_iter
  booster$record_evals <- blob$record_evals
  booster
}

#' Eval results recorded by lgb.train(record = TRUE).
lgb.get.eval.result <- function(booster, data_name, eval_name,
                                iters = NULL, is_err = FALSE) {
  rec <- booster$record_evals[[data_name]][[eval_name]]
  if (is.null(rec)) {
    stop("no recorded results for ", data_name, "/", eval_name)
  }
  out <- unlist(rec$eval)
  if (!is.null(iters)) out <- out[iters]
  out
}
