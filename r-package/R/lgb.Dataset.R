# lgb.Dataset: lazy-constructed training data over the C ABI (the
# reference's R-package/R/lgb.Dataset.R role, rebuilt on plain
# environments instead of R6 so the package has no hard dependencies).
#
# The object is an environment of fields + a NULL handle; construction
# (binning) happens on first use, and a valid set constructed against a
# reference shares its bin mappers through the ABI's reference argument
# (c_api.h LGBM_DatasetCreateFromMat reference parameter).

#' Create a lightgbm_tpu Dataset (not yet constructed/binned).
#'
#' @param data numeric matrix (column-major, as R stores it) or a path
#'   to a text file (CSV/TSV/LibSVM) for the file loader
#' @param label,weight,init_score numeric vectors, nrow(data) long
#' @param group integer vector of per-query document counts (ranking)
#' @param params named list of dataset parameters (max_bin, ...)
#' @param reference an lgb.Dataset whose bin mappers this set must share
#'   (validation sets); see lgb.Dataset.create.valid
#' @param colnames feature names; defaults to colnames(data)
#' @param categorical_feature names or 1-based indices of categoricals
#' @param free_raw_data drop the raw matrix after construction
lgb.Dataset <- function(data, label = NULL, weight = NULL, group = NULL,
                        init_score = NULL, params = list(),
                        reference = NULL, colnames = NULL,
                        categorical_feature = NULL,
                        free_raw_data = TRUE) {
  if (!is.null(reference) && !lgb.is.Dataset(reference)) {
    stop("reference must be an lgb.Dataset")
  }
  if (is.matrix(data) && !is.double(data)) storage.mode(data) <- "double"
  env <- new.env(parent = emptyenv())
  env$raw_data <- data
  env$label <- label
  env$weight <- weight
  env$group <- group
  env$init_score <- init_score
  env$params <- params
  env$reference <- reference
  env$colnames <- if (!is.null(colnames)) colnames
                  else if (is.matrix(data)) base::colnames(data)
  env$categorical_feature <- categorical_feature
  env$free_raw_data <- isTRUE(free_raw_data)
  env$handle <- NULL
  class(env) <- "lgb.Dataset"
  env
}

#' Materialize the Dataset through the C ABI (idempotent).
lgb.Dataset.construct <- function(dataset) {
  stopifnot(lgb.is.Dataset(dataset))
  if (!is.null(dataset$handle)) return(invisible(dataset))
  lgb.load_lib()
  params <- lgb.prep.categorical(dataset$params,
                                 dataset$categorical_feature,
                                 dataset$colnames)
  pstr <- lgb.params2str(params)
  ref_handle <- NULL
  if (!is.null(dataset$reference)) {
    lgb.Dataset.construct(dataset$reference)
    ref_handle <- dataset$reference$handle
  }
  if (is.character(dataset$raw_data)) {
    dataset$handle <- .Call("LGBMR_DatasetCreateFromFile",
                            dataset$raw_data, pstr, ref_handle)
  } else {
    dataset$handle <- .Call("LGBMR_DatasetCreateFromMat",
                            dataset$raw_data, pstr, ref_handle)
  }
  if (!is.null(dataset$label)) {
    .Call("LGBMR_DatasetSetField", dataset$handle, "label",
          as.double(dataset$label))
  }
  if (!is.null(dataset$weight)) {
    .Call("LGBMR_DatasetSetField", dataset$handle, "weight",
          as.double(dataset$weight))
  }
  if (!is.null(dataset$group)) {
    .Call("LGBMR_DatasetSetField", dataset$handle, "group",
          as.integer(dataset$group))
  }
  if (!is.null(dataset$init_score)) {
    .Call("LGBMR_DatasetSetField", dataset$handle, "init_score",
          as.double(dataset$init_score))
  }
  if (!is.null(dataset$colnames)) {
    .Call("LGBMR_DatasetSetFeatureNames", dataset$handle,
          as.character(dataset$colnames))
  }
  if (dataset$free_raw_data && !is.character(dataset$raw_data)) {
    dataset$raw_data <- NULL
  }
  invisible(dataset)
}

#' A validation set binned with the same mappers as `dataset`
#' (Dataset::CreateValid, the reference's lgb.Dataset.create.valid).
lgb.Dataset.create.valid <- function(dataset, data, label = NULL,
                                     weight = NULL, group = NULL,
                                     init_score = NULL, params = list()) {
  stopifnot(lgb.is.Dataset(dataset))
  lgb.Dataset(data, label = label, weight = weight, group = group,
              init_score = init_score, params = params,
              reference = dataset)
}

#' Save the constructed Dataset in the fast binary format.
lgb.Dataset.save <- function(dataset, fname) {
  lgb.Dataset.construct(dataset)
  .Call("LGBMR_DatasetSaveBinary", dataset$handle, fname)
  invisible(dataset)
}

#' Update dataset parameters before construction.
lgb.Dataset.set.reference <- function(dataset, reference) {
  stopifnot(lgb.is.Dataset(dataset), lgb.is.Dataset(reference))
  if (!is.null(dataset$handle)) {
    stop("cannot set reference after the Dataset is constructed")
  }
  dataset$reference <- reference
  invisible(dataset)
}

dim.lgb.Dataset <- function(x) {
  if (!is.null(x$handle)) {
    c(.Call("LGBMR_DatasetGetNumData", x$handle),
      .Call("LGBMR_DatasetGetNumFeature", x$handle))
  } else if (is.matrix(x$raw_data)) {
    dim(x$raw_data)
  } else {
    stop("constructed handle or raw matrix required for dim()")
  }
}

dimnames.lgb.Dataset <- function(x) list(NULL, x$colnames)

#' getinfo / setinfo mirror the reference's S3 generics.
getinfo <- function(dataset, ...) UseMethod("getinfo")
getinfo.lgb.Dataset <- function(dataset, name, ...) {
  lgb.Dataset.construct(dataset)
  out <- .Call("LGBMR_DatasetGetField", dataset$handle, name)
  if (name %in% c("group", "query")) {
    # the ABI returns cumulative query boundaries; give back counts
    out <- diff(as.integer(out))
  }
  out
}

setinfo <- function(dataset, ...) UseMethod("setinfo")
setinfo.lgb.Dataset <- function(dataset, name, info, ...) {
  if (is.null(dataset$handle)) {
    # pre-construction: stash so construct() applies it
    slot <- c(label = "label", weight = "weight", group = "group",
              init_score = "init_score")[[name]]
    assign(slot, info, envir = dataset)
  } else if (name %in% c("group", "query")) {
    .Call("LGBMR_DatasetSetField", dataset$handle, "group",
          as.integer(info))
  } else {
    .Call("LGBMR_DatasetSetField", dataset$handle, name, as.double(info))
  }
  invisible(dataset)
}
