# Minimal R API over the lightgbm_tpu C ABI (.Call glue in src/) —
# the lgb.Dataset / lgb.train / predict idiom of the reference
# R-package, reduced to the training/predict core.

.lgb_loaded <- FALSE

lgb.load_lib <- function(so_path = NULL) {
  if (.lgb_loaded) return(invisible(TRUE))
  if (is.null(so_path)) {
    # documented flow runs from <repo>/r-package (cd r-package &&
    # Rscript smoke.R), so the repo root is one dirname up
    so_path <- file.path(dirname(getwd()), "native",
                         "liblightgbm_tpu.so")
  }
  dyn.load(so_path, local = FALSE)   # LGBM_* must be global for the glue
  dyn.load(file.path("src", "lightgbm_tpu_R.so"))
  .lgb_loaded <<- TRUE
  invisible(TRUE)
}

lgb.Dataset <- function(data, label = NULL, params = "") {
  stopifnot(is.matrix(data))
  .Call("LGBMR_DatasetCreateFromMat", data, nrow(data), ncol(data),
        params, if (is.null(label)) NULL else as.double(label))
}

lgb.train <- function(params, data, nrounds = 10) {
  bst <- .Call("LGBMR_BoosterCreate", data, params)
  for (i in seq_len(nrounds)) {
    .Call("LGBMR_BoosterUpdateOneIter", bst)
  }
  bst
}

predict.lgb <- function(bst, data) {
  .Call("LGBMR_BoosterPredictForMat", bst, data, nrow(data), ncol(data))
}

lgb.save <- function(bst, filename) {
  invisible(.Call("LGBMR_BoosterSaveModel", bst, filename))
}

lgb.load <- function(filename) {
  .Call("LGBMR_BoosterCreateFromModelfile", filename)
}
