# lgb.cv: k-fold cross-validation (reference R-package/R/lgb.cv.R),
# training one booster per fold and aggregating per-iteration metric
# mean/sd across folds.

#' Stratified or plain fold assignment, or caller-provided folds
#' (list of test-index vectors).
lgb.make.folds <- function(label, nfold, stratified, seed) {
  set.seed(seed)
  n <- length(label)
  if (stratified && length(unique(label)) <= max(32L, nfold)) {
    # per-class round-robin like the reference/sklearn stratified KFold
    fold_of <- integer(n)
    for (cls in unique(label)) {
      idx <- sample(which(label == cls))
      fold_of[idx] <- rep_len(seq_len(nfold), length(idx))
    }
  } else {
    fold_of <- rep_len(seq_len(nfold), n)[sample.int(n)]
  }
  lapply(seq_len(nfold), function(k) which(fold_of == k))
}

#' Cross validation.
#' @return list(record_evals = per-iteration mean/sd per metric,
#'   best_iter, boosters = the per-fold lgb.Booster list)
lgb.cv <- function(params = list(), data, nrounds = 100L, nfold = 3L,
                   label = NULL, folds = NULL, stratified = TRUE,
                   obj = NULL, eval = NULL, verbose = 1L,
                   eval_freq = 1L, early_stopping_rounds = NULL,
                   seed = 0L, ...) {
  if (!lgb.is.Dataset(data)) {
    data <- lgb.Dataset(data, label = label)
  }
  if (is.null(data$raw_data) || is.character(data$raw_data)) {
    stop("lgb.cv needs an unconstructed matrix-backed Dataset ",
         "(folds re-bin per training split)")
  }
  X <- data$raw_data
  y <- data$label
  group <- data$group
  if (!is.null(group) && !is.null(folds)) {
    stop("grouped (ranking) data folds by query internally; ",
         "caller-provided row folds would split queries — drop `folds`")
  }
  if (is.null(folds) && is.null(group)) {
    folds <- lgb.make.folds(y, nfold, stratified, seed)
  }

  test_groups <- train_groups <- NULL
  if (!is.null(group)) {
    # ranking data folds by QUERY (splitting inside a query corrupts
    # the list structure — the reference group-folds the same way):
    # fold assignment is over queries, row indices derive from the
    # per-query boundaries
    nq <- length(group)
    set.seed(seed)
    qfold <- rep_len(seq_len(nfold), nq)[sample.int(nq)]
    bounds <- c(0L, cumsum(group))
    rows_of_query <- lapply(seq_len(nq),
                            function(qi) (bounds[qi] + 1L):bounds[qi + 1L])
    folds <- lapply(seq_len(nfold), function(k) {
      unlist(rows_of_query[qfold == k], use.names = FALSE)
    })
    test_groups <- lapply(seq_len(nfold), function(k) group[qfold == k])
    train_groups <- lapply(seq_len(nfold), function(k) group[qfold != k])
  }

  boosters <- list()
  per_iter <- list()   # [[iter]][[metric]] -> numeric vector over folds
  for (k in seq_along(folds)) {
    test_idx <- folds[[k]]
    dtrain <- lgb.Dataset(X[-test_idx, , drop = FALSE], label = y[-test_idx],
                          weight = if (!is.null(data$weight))
                            data$weight[-test_idx],
                          init_score = if (!is.null(data$init_score))
                            data$init_score[-test_idx],
                          group = if (!is.null(group)) train_groups[[k]],
                          params = data$params,
                          categorical_feature = data$categorical_feature)
    dtest <- lgb.Dataset.create.valid(
      dtrain, X[test_idx, , drop = FALSE], label = y[test_idx],
      weight = if (!is.null(data$weight)) data$weight[test_idx],
      init_score = if (!is.null(data$init_score))
        data$init_score[test_idx],
      group = if (!is.null(group)) test_groups[[k]])
    bst <- lgb.train(params = params, data = dtrain, nrounds = nrounds,
                     valids = list(test = dtest), obj = obj, eval = eval,
                     verbose = 0L, record = TRUE, eval_freq = eval_freq,
                     early_stopping_rounds = early_stopping_rounds, ...)
    boosters[[k]] <- bst
    for (m in names(bst$record_evals[["test"]])) {
      vals <- unlist(bst$record_evals[["test"]][[m]]$eval)
      for (i in seq_along(vals)) {
        key <- sprintf("%d", i)
        if (is.null(per_iter[[key]])) per_iter[[key]] <- list()
        per_iter[[key]][[m]] <- c(per_iter[[key]][[m]], vals[i])
      }
    }
  }

  record <- list()
  niter <- length(per_iter)
  metrics <- if (niter > 0L) names(per_iter[["1"]]) else character(0)
  for (m in metrics) {
    means <- vapply(seq_len(niter),
                    function(i) mean(per_iter[[sprintf("%d", i)]][[m]]),
                    numeric(1))
    sds <- vapply(seq_len(niter),
                  function(i) stats::sd(per_iter[[sprintf("%d", i)]][[m]]),
                  numeric(1))
    record[[paste0("test.", m, ".mean")]] <- means
    record[[paste0("test.", m, ".sd")]] <- sds
    if (verbose > 0L) {
      cat(sprintf("[cv] %s final: %g+%g\n", m, means[niter], sds[niter]))
    }
  }
  best_iter <- -1L
  if (length(metrics) > 0L) {
    m1 <- metrics[[1L]]
    means <- record[[paste0("test.", m1, ".mean")]]
    best_iter <- if (lgb.metric.higher_better(m1)) which.max(means)
                 else which.min(means)
  }
  list(record_evals = record, best_iter = as.integer(best_iter),
       boosters = boosters)
}
