# Feature importance + per-prediction interpretation (the reference's
# lgb.importance.R / lgb.interprete.R / lgb.plot.importance.R trio).

#' Feature importance from the trained model.
#' @param percentage normalize Gain/Cover/Frequency to fractions
#' @return data.frame(Feature, Gain, Frequency) sorted by Gain
lgb.importance <- function(model, percentage = TRUE) {
  stopifnot(lgb.is.Booster(model))
  names_ <- .Call("LGBMR_BoosterGetFeatureNames", model$handle)
  split_ <- .Call("LGBMR_BoosterFeatureImportance", model$handle, -1L,
                  0L)  # C_API_FEATURE_IMPORTANCE_SPLIT
  gain_ <- .Call("LGBMR_BoosterFeatureImportance", model$handle, -1L,
                 1L)   # C_API_FEATURE_IMPORTANCE_GAIN
  if (percentage) {
    if (sum(gain_) > 0) gain_ <- gain_ / sum(gain_)
    if (sum(split_) > 0) split_ <- split_ / sum(split_)
  }
  out <- data.frame(Feature = names_, Gain = gain_, Frequency = split_,
                    stringsAsFactors = FALSE)
  out[order(-out$Gain), , drop = FALSE]
}

#' Per-prediction feature contributions for chosen rows, via TreeSHAP
#' (predcontrib) — same additive-contribution semantics as the
#' reference's lgb.interprete tree walk, computed by the device SHAP
#' path instead.
#' @param idxset 1-based row indices of `data` to explain
#' @return list of data.frame(Feature, Contribution), one per index,
#'   sorted by |Contribution|; the "BIAS" row is the expected value
lgb.interprete <- function(model, data, idxset) {
  stopifnot(lgb.is.Booster(model))
  if (!is.matrix(data)) data <- as.matrix(data)
  rows <- data[idxset, , drop = FALSE]
  contrib <- predict(model, rows, predcontrib = TRUE, reshape = TRUE)
  if (is.null(dim(contrib))) contrib <- matrix(contrib, nrow = 1L)
  names_ <- c(.Call("LGBMR_BoosterGetFeatureNames", model$handle), "BIAS")
  lapply(seq_along(idxset), function(i) {
    row <- contrib[i, ]
    # multiclass: contributions come back (F+1) per class; fold classes
    if (length(row) > length(names_)) {
      row <- rowSums(matrix(row, nrow = length(names_)))
    }
    df <- data.frame(Feature = names_, Contribution = row,
                     stringsAsFactors = FALSE)
    df[order(-abs(df$Contribution)), , drop = FALSE]
  })
}

#' Barplot of lgb.importance output (base graphics; the reference uses
#' ggplot-free base plotting here too).
lgb.plot.importance <- function(tree_imp, top_n = 10L,
                                measure = "Gain", ...) {
  top <- utils::head(tree_imp[order(-tree_imp[[measure]]), ], top_n)
  graphics::barplot(rev(top[[measure]]), names.arg = rev(top$Feature),
                    horiz = TRUE, las = 1,
                    main = paste("Feature importance by", measure), ...)
  invisible(top)
}

#' Barplot of one lgb.interprete record.
lgb.plot.interpretation <- function(tree_interpretation, top_n = 10L, ...) {
  top <- utils::head(tree_interpretation, top_n)
  graphics::barplot(rev(top$Contribution), names.arg = rev(top$Feature),
                    horiz = TRUE, las = 1,
                    main = "Feature contribution", ...)
  invisible(top)
}
