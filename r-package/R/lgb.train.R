# lgb.train: the main R training entry (reference R-package/R/lgb.train.R),
# driving the Booster iteration loop with valids, metric recording,
# callbacks and early stopping.

#' Train a gbdt model.
#'
#' @param params named list of parameters (see docs/Parameters.md)
#' @param data an lgb.Dataset
#' @param nrounds boosting iterations
#' @param valids named list of lgb.Dataset validation sets
#' @param obj custom objective function(preds, dataset) ->
#'   list(grad, hess); NULL uses params$objective
#' @param eval custom metric function(preds, dataset) ->
#'   list(name, value, higher_better)
#' @param verbose <= 0 silences the per-eval_freq metric printing
#' @param record keep eval results on booster$record_evals
#' @param eval_freq evaluate every this many iterations
#' @param init_model path or lgb.Booster to continue training from
#' @param early_stopping_rounds stop when the first valid metric has
#'   not improved this many rounds
#' @param callbacks extra function(env) callbacks
#' @return an lgb.Booster
lgb.train <- function(params = list(), data, nrounds = 100L,
                      valids = list(), obj = NULL, eval = NULL,
                      verbose = 1L, record = TRUE, eval_freq = 1L,
                      init_model = NULL, early_stopping_rounds = NULL,
                      callbacks = list(), ...) {
  stopifnot(lgb.is.Dataset(data))
  extra <- list(...)
  params <- utils::modifyList(params, extra)
  if (!is.null(obj)) params$objective <- "none"

  lgb.Dataset.construct(data)
  booster <- Booster(params = params, train_set = data)
  if (!is.null(init_model)) {
    # continued training: merge the warm model's trees into the fresh
    # booster (LGBM_BoosterMerge rebuilds train/valid scores, so the
    # following updates boost on top of the warm ensemble)
    warm <- if (lgb.is.Booster(init_model)) init_model
            else Booster(modelfile = init_model)
    .Call("LGBMR_BoosterMerge", booster$handle, warm$handle)
  }
  for (nm in names(valids)) {
    lgb.Booster.add_valid(booster, valids[[nm]], nm)
  }

  cbs <- c(callbacks, list(if (record) cb.record.evaluation()),
           list(if (verbose > 0L) cb.print.evaluation(eval_freq)),
           list(if (!is.null(early_stopping_rounds) &&
                    length(valids) > 0L)
                  cb.early.stop(early_stopping_rounds,
                                verbose = verbose > 0L)))
  cbs <- Filter(Negate(is.null), cbs)
  # pre-iteration callbacks (parameter schedules) run before EVERY
  # update; the rest run after evaluation on eval_freq boundaries
  pre_cbs <- Filter(function(cb) isTRUE(attr(cb, "is_pre_iteration")), cbs)
  post_cbs <- Filter(function(cb) !isTRUE(attr(cb, "is_pre_iteration")),
                     cbs)

  env <- new.env()
  env$booster <- booster
  env$begin_iteration <- 1L
  env$end_iteration <- as.integer(nrounds)
  env$met_early_stop <- FALSE
  for (i in seq_len(nrounds)) {
    env$iteration <- i
    for (cb in pre_cbs) cb(env)
    lgb.Booster.update(booster, fobj = obj)
    if ((i %% eval_freq) == 0L || i == nrounds) {
      env$eval_list <- lgb.Booster.eval(booster, feval = eval)
      for (cb in post_cbs) cb(env)
      if (isTRUE(env$met_early_stop)) break
    }
  }
  if (booster$best_iter > 0L) {
    # roll the model back so predict() uses the best iteration
    while (lgb.Booster.current_iter(booster) > booster$best_iter) {
      lgb.Booster.rollback_one_iter(booster)
    }
  }
  booster
}

#' The simple one-call interface (reference R-package/R/lightgbm.R):
#' data/label in, trained booster out.
lightgbm <- function(data, label = NULL, weight = NULL,
                     params = list(), nrounds = 100L, verbose = 1L,
                     objective = "regression", ...) {
  if (!lgb.is.Dataset(data)) {
    data <- lgb.Dataset(data, label = label, weight = weight)
  }
  params$objective <- params$objective %||% objective
  lgb.train(params = params, data = data, nrounds = nrounds,
            verbose = verbose, ...)
}

`%||%` <- function(a, b) if (is.null(a)) b else a
