# Internal helpers shared across the package (the lgb.params2str /
# lgb.check.params role of the reference's R-package/R/utils.R, written
# for this package's .Call glue).

.lgb_env <- new.env(parent = emptyenv())
.lgb_env$loaded <- FALSE

#' Load the native libraries (the C ABI .so + the .Call glue).
#' Called lazily by every entry point; safe to call repeatedly.
lgb.load_lib <- function(lib_dir = NULL, glue_so = NULL) {
  if (isTRUE(.lgb_env$loaded)) return(invisible(TRUE))
  if (is.null(lib_dir)) {
    lib_dir <- Sys.getenv("LIGHTGBM_TPU_LIB",
                          file.path(dirname(getwd()), "native"))
  }
  dyn.load(file.path(lib_dir, "liblightgbm_tpu.so"), local = FALSE)
  if (is.null(glue_so)) {
    glue_so <- file.path("src", "lightgbm_tpu_R.so")
    if (!file.exists(glue_so)) {
      glue_so <- system.file("libs", "lightgbm_tpu_R.so",
                             package = "lightgbmtpu")
    }
  }
  dyn.load(glue_so)
  .lgb_env$loaded <- TRUE
  invisible(TRUE)
}

#' list(k = v) -> "k=v k2=v2,v3" parameter string for the C ABI
#' (Config::Str2Map splits on spaces/newlines; vector values join with
#' commas like the reference's lgb.params2str).
lgb.params2str <- function(params) {
  if (length(params) == 0L) return("")
  stopifnot(is.list(params))
  keys <- names(params)
  if (is.null(keys) || any(!nzchar(keys))) {
    stop("every parameter must be named")
  }
  one <- function(k) {
    v <- params[[k]]
    if (is.logical(v)) v <- tolower(as.character(v))
    paste0(k, "=", paste(v, collapse = ","))
  }
  paste(vapply(keys, one, character(1)), collapse = " ")
}

#' Merge categorical_feature (1-based names or indices) into params as
#' the 0-based categorical_feature list the config layer expects.
lgb.prep.categorical <- function(params, categorical_feature, colnames) {
  if (is.null(categorical_feature) || length(categorical_feature) == 0L) {
    return(params)
  }
  if (is.character(categorical_feature)) {
    idx <- match(categorical_feature, colnames)
    if (anyNA(idx)) {
      stop("categorical_feature names not in colnames: ",
           paste(categorical_feature[is.na(idx)], collapse = ", "))
    }
  } else {
    idx <- as.integer(categorical_feature)
  }
  params[["categorical_feature"]] <- paste(idx - 1L, collapse = ",")
  params
}

lgb.is.Dataset <- function(x) inherits(x, "lgb.Dataset")
lgb.is.Booster <- function(x) inherits(x, "lgb.Booster")

#' Higher-is-better flag per metric name (metric.hpp max_metric lists)
lgb.metric.higher_better <- function(name) {
  grepl("^(auc|ndcg|map)", name)
}
