# End-to-end R smoke over the real C ABI (.so): train, predict, save,
# reload, compare.  Run from r-package/ after building the glue:
#   R CMD SHLIB src/lightgbm_tpu_R.c -L../native -llightgbm_tpu \
#       -Wl,-rpath,$(realpath ../native)
#   PYTHONPATH=.. Rscript smoke.R
source("R/lgb.R")
lgb.load_lib()

set.seed(7)
n <- 2000; f <- 5
X <- matrix(rnorm(n * f), n, f)
y <- as.double(X[, 1] > 0)

ds <- lgb.Dataset(X, label = y, params = "max_bin=63")
bst <- lgb.train("objective=binary verbose=-1 num_leaves=15", ds,
                 nrounds = 6)
p <- predict.lgb(bst, X)
sep <- mean(p[y > 0.5]) - mean(p[y < 0.5])
cat(sprintf("separation: %.3f\n", sep))
stopifnot(sep > 0.2)

lgb.save(bst, "model_r.txt")
bst2 <- lgb.load("model_r.txt")
p2 <- predict.lgb(bst2, X)
stopifnot(max(abs(p - p2)) < 1e-6)
cat("R ABI SMOKE OK\n")
