/* .Call glue over the LGBM_* C ABI exported by
 * native/liblightgbm_tpu.so — the same thin argument-shuffle role as
 * the reference's R-package/src/lightgbm_R.cpp (1-625), written from
 * scratch against this framework's trampoline ABI (the extern
 * signatures below are structurally checked against
 * lightgbm_tpu/capi_abi.py by tests/test_r_package.py).
 *
 * Build with:
 *   R CMD SHLIB lightgbm_tpu_R.c -L../../native -llightgbm_tpu
 * (needs an R toolchain; see ../README.md for the validation story).
 *
 * Conventions: handles ride R external pointers with finalizers;
 * R matrices are column-major doubles (is_row_major = 0, float64
 * data_type = 1); label/weight fields convert to float32 (type 0),
 * init_score stays float64 (type 1), group converts to int32 (type 2)
 * — the reference R glue makes the same conversions.
 */
#include <R.h>
#include <Rinternals.h>
#include <R_ext/Rdynload.h>
#include <stdint.h>
#include <string.h>

typedef void *DatasetHandle;
typedef void *BoosterHandle;

/* ---- extern ABI (subset used by the R package) ---------------------- */
extern const char *LGBM_GetLastError(void);
extern int LGBM_DatasetCreateFromMat(const void *, int, int32_t, int32_t,
                                     int, const char *, const DatasetHandle,
                                     DatasetHandle *);
extern int LGBM_DatasetCreateFromFile(const char *, const char *,
                                      const DatasetHandle, DatasetHandle *);
extern int LGBM_DatasetGetNumData(DatasetHandle, int32_t *);
extern int LGBM_DatasetGetNumFeature(DatasetHandle, int32_t *);
extern int LGBM_DatasetSetField(DatasetHandle, const char *, const void *,
                                int32_t, int);
extern int LGBM_DatasetGetField(DatasetHandle, const char *, int32_t *,
                                const void **, int32_t *);
extern int LGBM_DatasetSaveBinary(DatasetHandle, const char *);
extern int LGBM_DatasetSetFeatureNames(DatasetHandle, const char **, int);
extern int LGBM_DatasetGetFeatureNames(DatasetHandle, char **, int32_t *);
extern int LGBM_DatasetUpdateParam(DatasetHandle, const char *);
extern int LGBM_DatasetFree(DatasetHandle);
extern int LGBM_BoosterCreate(const DatasetHandle, const char *,
                              BoosterHandle *);
extern int LGBM_BoosterCreateFromModelfile(const char *, int32_t *,
                                           BoosterHandle *);
extern int LGBM_BoosterLoadModelFromString(const char *, int32_t *,
                                           BoosterHandle *);
extern int LGBM_BoosterAddValidData(BoosterHandle, const DatasetHandle);
extern int LGBM_BoosterUpdateOneIter(BoosterHandle, int32_t *);
extern int LGBM_BoosterUpdateOneIterCustom(BoosterHandle, const float *,
                                           const float *, int32_t *);
extern int LGBM_BoosterRollbackOneIter(BoosterHandle);
extern int LGBM_BoosterGetCurrentIteration(BoosterHandle, int32_t *);
extern int LGBM_BoosterGetNumClasses(BoosterHandle, int32_t *);
extern int LGBM_BoosterGetNumFeature(BoosterHandle, int32_t *);
extern int LGBM_BoosterGetEvalCounts(BoosterHandle, int32_t *);
extern int LGBM_BoosterGetEvalNames(BoosterHandle, int32_t *, char **);
extern int LGBM_BoosterGetFeatureNames(BoosterHandle, int32_t *, char **);
extern int LGBM_BoosterGetEval(BoosterHandle, int, int32_t *, double *);
extern int LGBM_BoosterGetNumPredict(BoosterHandle, int, int64_t *);
extern int LGBM_BoosterGetPredict(BoosterHandle, int, int64_t *, double *);
extern int LGBM_BoosterCalcNumPredict(BoosterHandle, int, int, int,
                                      int64_t *);
extern int LGBM_BoosterPredictForMat(BoosterHandle, const void *, int,
                                     int32_t, int32_t, int, int, int,
                                     const char *, int64_t *, double *);
extern int LGBM_BoosterSaveModel(BoosterHandle, int, int, const char *);
extern int LGBM_BoosterSaveModelToString(BoosterHandle, int, int, int64_t,
                                         int64_t *, char *);
extern int LGBM_BoosterDumpModel(BoosterHandle, int, int, int64_t,
                                 int64_t *, char *);
extern int LGBM_BoosterFeatureImportance(BoosterHandle, int, int, double *);
extern int LGBM_BoosterResetParameter(BoosterHandle, const char *);
extern int LGBM_BoosterMerge(BoosterHandle, BoosterHandle);
extern int LGBM_BoosterFree(BoosterHandle);

#define CHECK_CALL(x) \
  if ((x) != 0) Rf_error("lightgbm_tpu: %s", LGBM_GetLastError())

/* per-name buffer size: the v2 char** ABI carries no length, 256 bytes
 * per name is the documented limit (reference basic.py uses 255) */
#define NAME_LEN 256

/* ---- handle plumbing ------------------------------------------------ */
static void dataset_finalizer(SEXP ext) {
  DatasetHandle h = R_ExternalPtrAddr(ext);
  if (h != NULL) { LGBM_DatasetFree(h); R_ClearExternalPtr(ext); }
}

static void booster_finalizer(SEXP ext) {
  BoosterHandle h = R_ExternalPtrAddr(ext);
  if (h != NULL) { LGBM_BoosterFree(h); R_ClearExternalPtr(ext); }
}

static SEXP wrap_dataset(DatasetHandle h) {
  SEXP ext = PROTECT(R_MakeExternalPtr(h, R_NilValue, R_NilValue));
  R_RegisterCFinalizerEx(ext, dataset_finalizer, TRUE);
  UNPROTECT(1);
  return ext;
}

static SEXP wrap_booster(BoosterHandle h) {
  SEXP ext = PROTECT(R_MakeExternalPtr(h, R_NilValue, R_NilValue));
  R_RegisterCFinalizerEx(ext, booster_finalizer, TRUE);
  UNPROTECT(1);
  return ext;
}

static void *checked_ptr(SEXP ext) {
  void *h = R_ExternalPtrAddr(ext);
  if (h == NULL) Rf_error("lightgbm_tpu: handle is NULL (already freed?)");
  return h;
}

/* names buffer for the unsized char** convention of the v2 ABI — the
 * slot count MUST come from the matching count query (GetNumFeature /
 * GetEvalCounts) or the callee writes past the array */
static char **alloc_name_array(int n) {
  if (n <= 0) n = 1;
  char **arr = (char **)R_alloc(n, sizeof(char *));
  char *blob = (char *)R_alloc((size_t)n * NAME_LEN, 1);
  for (int i = 0; i < n; i++) arr[i] = blob + (size_t)i * NAME_LEN;
  return arr;
}

static SEXP names_to_charvec(char **arr, int n) {
  SEXP out = PROTECT(Rf_allocVector(STRSXP, n));
  for (int i = 0; i < n; i++) SET_STRING_ELT(out, i, Rf_mkChar(arr[i]));
  UNPROTECT(1);
  return out;
}

/* ---- Dataset -------------------------------------------------------- */
SEXP LGBMR_DatasetCreateFromMat(SEXP mat, SEXP params, SEXP ref) {
  DatasetHandle h = NULL;
  SEXP dims = Rf_getAttrib(mat, R_DimSymbol);
  if (Rf_isNull(dims) || Rf_length(dims) != 2)
    Rf_error("lightgbm_tpu: data must be a numeric matrix");
  int nr = INTEGER(dims)[0], nc = INTEGER(dims)[1];
  DatasetHandle refh = Rf_isNull(ref) ? NULL : checked_ptr(ref);
  /* R matrices are column-major: is_row_major = 0, float64 = 1 */
  CHECK_CALL(LGBM_DatasetCreateFromMat(REAL(mat), 1, nr, nc, 0,
                                       CHAR(Rf_asChar(params)), refh, &h));
  return wrap_dataset(h);
}

SEXP LGBMR_DatasetCreateFromFile(SEXP filename, SEXP params, SEXP ref) {
  DatasetHandle h = NULL;
  DatasetHandle refh = Rf_isNull(ref) ? NULL : checked_ptr(ref);
  CHECK_CALL(LGBM_DatasetCreateFromFile(CHAR(Rf_asChar(filename)),
                                        CHAR(Rf_asChar(params)), refh, &h));
  return wrap_dataset(h);
}

SEXP LGBMR_DatasetGetNumData(SEXP ds) {
  int32_t n = 0;
  CHECK_CALL(LGBM_DatasetGetNumData(checked_ptr(ds), &n));
  return Rf_ScalarInteger(n);
}

SEXP LGBMR_DatasetGetNumFeature(SEXP ds) {
  int32_t n = 0;
  CHECK_CALL(LGBM_DatasetGetNumFeature(checked_ptr(ds), &n));
  return Rf_ScalarInteger(n);
}

SEXP LGBMR_DatasetSetField(SEXP ds, SEXP name, SEXP vec) {
  const char *field = CHAR(Rf_asChar(name));
  int n = Rf_length(vec);
  DatasetHandle h = checked_ptr(ds);
  if (strcmp(field, "group") == 0 || strcmp(field, "query") == 0) {
    int32_t *buf = (int32_t *)R_alloc(n, sizeof(int32_t));
    if (TYPEOF(vec) == INTSXP) {
      memcpy(buf, INTEGER(vec), (size_t)n * sizeof(int32_t));
    } else {
      double *src = REAL(vec);
      for (int i = 0; i < n; i++) buf[i] = (int32_t)src[i];
    }
    CHECK_CALL(LGBM_DatasetSetField(h, field, buf, n, /*int32*/ 2));
  } else if (strcmp(field, "init_score") == 0) {
    /* init_score is the one float64 field (metadata.cpp SetInitScore) */
    SEXP dvec = PROTECT(Rf_coerceVector(vec, REALSXP));
    CHECK_CALL(LGBM_DatasetSetField(h, field, REAL(dvec), n, /*f64*/ 1));
    UNPROTECT(1);
  } else {
    float *buf = (float *)R_alloc(n, sizeof(float));
    SEXP dvec = PROTECT(Rf_coerceVector(vec, REALSXP));
    double *src = REAL(dvec);
    for (int i = 0; i < n; i++) buf[i] = (float)src[i];
    CHECK_CALL(LGBM_DatasetSetField(h, field, buf, n, /*f32*/ 0));
    UNPROTECT(1);
  }
  return R_NilValue;
}

SEXP LGBMR_DatasetGetField(SEXP ds, SEXP name) {
  const char *field = CHAR(Rf_asChar(name));
  int32_t out_len = 0, out_type = 0;
  const void *ptr = NULL;
  CHECK_CALL(LGBM_DatasetGetField(checked_ptr(ds), field, &out_len, &ptr,
                                  &out_type));
  if (out_len <= 0 || ptr == NULL) return Rf_allocVector(REALSXP, 0);
  SEXP out = PROTECT(Rf_allocVector(REALSXP, out_len));
  double *dst = REAL(out);
  if (out_type == 0) {          /* float32 */
    const float *src = (const float *)ptr;
    for (int i = 0; i < out_len; i++) dst[i] = (double)src[i];
  } else if (out_type == 1) {   /* float64 */
    memcpy(dst, ptr, (size_t)out_len * sizeof(double));
  } else {                      /* int32 */
    const int32_t *src = (const int32_t *)ptr;
    for (int i = 0; i < out_len; i++) dst[i] = (double)src[i];
  }
  UNPROTECT(1);
  return out;
}

SEXP LGBMR_DatasetSaveBinary(SEXP ds, SEXP filename) {
  CHECK_CALL(LGBM_DatasetSaveBinary(checked_ptr(ds),
                                    CHAR(Rf_asChar(filename))));
  return R_NilValue;
}

SEXP LGBMR_DatasetSetFeatureNames(SEXP ds, SEXP names) {
  int n = Rf_length(names);
  const char **arr = (const char **)R_alloc(n, sizeof(char *));
  for (int i = 0; i < n; i++) arr[i] = CHAR(STRING_ELT(names, i));
  CHECK_CALL(LGBM_DatasetSetFeatureNames(checked_ptr(ds), arr, n));
  return R_NilValue;
}

SEXP LGBMR_DatasetGetFeatureNames(SEXP ds) {
  DatasetHandle h = checked_ptr(ds);
  int32_t nf = 0;
  CHECK_CALL(LGBM_DatasetGetNumFeature(h, &nf));
  char **arr = alloc_name_array(nf);
  int32_t n = 0;
  CHECK_CALL(LGBM_DatasetGetFeatureNames(h, arr, &n));
  if (n > nf) Rf_error("lightgbm_tpu: feature-name count grew mid-call");
  return names_to_charvec(arr, n);
}

SEXP LGBMR_DatasetUpdateParam(SEXP ds, SEXP params) {
  CHECK_CALL(LGBM_DatasetUpdateParam(checked_ptr(ds),
                                     CHAR(Rf_asChar(params))));
  return R_NilValue;
}

/* ---- Booster -------------------------------------------------------- */
SEXP LGBMR_BoosterCreate(SEXP ds, SEXP params) {
  BoosterHandle h = NULL;
  CHECK_CALL(LGBM_BoosterCreate(checked_ptr(ds), CHAR(Rf_asChar(params)),
                                &h));
  return wrap_booster(h);
}

SEXP LGBMR_BoosterCreateFromModelfile(SEXP filename) {
  BoosterHandle h = NULL;
  int32_t iters = 0;
  CHECK_CALL(LGBM_BoosterCreateFromModelfile(CHAR(Rf_asChar(filename)),
                                             &iters, &h));
  return wrap_booster(h);
}

SEXP LGBMR_BoosterLoadModelFromString(SEXP model_str) {
  BoosterHandle h = NULL;
  int32_t iters = 0;
  CHECK_CALL(LGBM_BoosterLoadModelFromString(CHAR(Rf_asChar(model_str)),
                                             &iters, &h));
  return wrap_booster(h);
}

SEXP LGBMR_BoosterAddValidData(SEXP bst, SEXP ds) {
  CHECK_CALL(LGBM_BoosterAddValidData(checked_ptr(bst), checked_ptr(ds)));
  return R_NilValue;
}

SEXP LGBMR_BoosterUpdateOneIter(SEXP bst) {
  int32_t finished = 0;
  CHECK_CALL(LGBM_BoosterUpdateOneIter(checked_ptr(bst), &finished));
  return Rf_ScalarLogical(finished);
}

SEXP LGBMR_BoosterUpdateOneIterCustom(SEXP bst, SEXP grad, SEXP hess) {
  int n = Rf_length(grad);
  if (Rf_length(hess) != n)
    Rf_error("lightgbm_tpu: grad/hess length mismatch");
  float *g = (float *)R_alloc(n, sizeof(float));
  float *hs = (float *)R_alloc(n, sizeof(float));
  double *gs = REAL(grad), *hsrc = REAL(hess);
  for (int i = 0; i < n; i++) { g[i] = (float)gs[i]; hs[i] = (float)hsrc[i]; }
  int32_t finished = 0;
  CHECK_CALL(LGBM_BoosterUpdateOneIterCustom(checked_ptr(bst), g, hs,
                                             &finished));
  return Rf_ScalarLogical(finished);
}

SEXP LGBMR_BoosterRollbackOneIter(SEXP bst) {
  CHECK_CALL(LGBM_BoosterRollbackOneIter(checked_ptr(bst)));
  return R_NilValue;
}

SEXP LGBMR_BoosterGetCurrentIteration(SEXP bst) {
  int32_t it = 0;
  CHECK_CALL(LGBM_BoosterGetCurrentIteration(checked_ptr(bst), &it));
  return Rf_ScalarInteger(it);
}

SEXP LGBMR_BoosterGetNumClasses(SEXP bst) {
  int32_t n = 0;
  CHECK_CALL(LGBM_BoosterGetNumClasses(checked_ptr(bst), &n));
  return Rf_ScalarInteger(n);
}

SEXP LGBMR_BoosterGetNumFeature(SEXP bst) {
  int32_t n = 0;
  CHECK_CALL(LGBM_BoosterGetNumFeature(checked_ptr(bst), &n));
  return Rf_ScalarInteger(n);
}

SEXP LGBMR_BoosterGetEvalNames(SEXP bst) {
  BoosterHandle h = checked_ptr(bst);
  int32_t cnt = 0;
  CHECK_CALL(LGBM_BoosterGetEvalCounts(h, &cnt));
  char **arr = alloc_name_array(cnt);
  int32_t n = 0;
  CHECK_CALL(LGBM_BoosterGetEvalNames(h, &n, arr));
  if (n > cnt) Rf_error("lightgbm_tpu: eval-name count grew mid-call");
  return names_to_charvec(arr, n);
}

SEXP LGBMR_BoosterGetFeatureNames(SEXP bst) {
  BoosterHandle h = checked_ptr(bst);
  int32_t nf = 0;
  CHECK_CALL(LGBM_BoosterGetNumFeature(h, &nf));
  char **arr = alloc_name_array(nf);
  int32_t n = 0;
  CHECK_CALL(LGBM_BoosterGetFeatureNames(h, &n, arr));
  if (n > nf) Rf_error("lightgbm_tpu: feature-name count grew mid-call");
  return names_to_charvec(arr, n);
}

SEXP LGBMR_BoosterGetEval(SEXP bst, SEXP data_idx) {
  int32_t cnt = 0;
  BoosterHandle h = checked_ptr(bst);
  CHECK_CALL(LGBM_BoosterGetEvalCounts(h, &cnt));
  if (cnt <= 0) return Rf_allocVector(REALSXP, 0);
  SEXP out = PROTECT(Rf_allocVector(REALSXP, cnt));
  int32_t out_len = 0;
  CHECK_CALL(LGBM_BoosterGetEval(h, Rf_asInteger(data_idx), &out_len,
                                 REAL(out)));
  if (out_len != cnt) Rf_error("lightgbm_tpu: eval count mismatch");
  UNPROTECT(1);
  return out;
}

/* raw training-state scores (data_idx 0 = train, then valids in add
 * order) — the fast path for custom objectives: no re-binning, no
 * re-walking the ensemble */
SEXP LGBMR_BoosterGetPredict(SEXP bst, SEXP data_idx) {
  BoosterHandle h = checked_ptr(bst);
  int idx = Rf_asInteger(data_idx);
  int64_t want = 0;
  CHECK_CALL(LGBM_BoosterGetNumPredict(h, idx, &want));
  SEXP out = PROTECT(Rf_allocVector(REALSXP, (R_xlen_t)want));
  int64_t got = 0;
  CHECK_CALL(LGBM_BoosterGetPredict(h, idx, &got, REAL(out)));
  if (got != want) Rf_error("lightgbm_tpu: predict length mismatch");
  UNPROTECT(1);
  return out;
}

SEXP LGBMR_BoosterPredictForMat(SEXP bst, SEXP mat, SEXP predict_type,
                                SEXP num_iteration, SEXP params) {
  SEXP dims = Rf_getAttrib(mat, R_DimSymbol);
  if (Rf_isNull(dims) || Rf_length(dims) != 2)
    Rf_error("lightgbm_tpu: data must be a numeric matrix");
  int nr = INTEGER(dims)[0], nc = INTEGER(dims)[1];
  int pt = Rf_asInteger(predict_type), ni = Rf_asInteger(num_iteration);
  BoosterHandle h = checked_ptr(bst);
  int64_t want = 0;
  CHECK_CALL(LGBM_BoosterCalcNumPredict(h, nr, pt, ni, &want));
  SEXP out = PROTECT(Rf_allocVector(REALSXP, (R_xlen_t)want));
  int64_t got = 0;
  CHECK_CALL(LGBM_BoosterPredictForMat(h, REAL(mat), 1, nr, nc, 0, pt, ni,
                                       CHAR(Rf_asChar(params)), &got,
                                       REAL(out)));
  if (got != want) Rf_error("lightgbm_tpu: prediction length mismatch");
  UNPROTECT(1);
  return out;
}

SEXP LGBMR_BoosterSaveModel(SEXP bst, SEXP num_iteration, SEXP filename) {
  CHECK_CALL(LGBM_BoosterSaveModel(checked_ptr(bst), 0,
                                   Rf_asInteger(num_iteration),
                                   CHAR(Rf_asChar(filename))));
  return R_NilValue;
}

/* two-call buffer pattern shared by SaveModelToString / DumpModel */
static SEXP string_from_two_call(int (*fn)(BoosterHandle, int, int, int64_t,
                                           int64_t *, char *),
                                 BoosterHandle h, int ni) {
  int64_t need = 0;
  CHECK_CALL(fn(h, 0, ni, 0, &need, NULL));
  char *buf = (char *)R_alloc((size_t)need + 1, 1);
  int64_t got = 0;
  CHECK_CALL(fn(h, 0, ni, need + 1, &got, buf));
  return Rf_mkString(buf);
}

SEXP LGBMR_BoosterSaveModelToString(SEXP bst, SEXP num_iteration) {
  return string_from_two_call(LGBM_BoosterSaveModelToString,
                              checked_ptr(bst), Rf_asInteger(num_iteration));
}

SEXP LGBMR_BoosterDumpModel(SEXP bst, SEXP num_iteration) {
  return string_from_two_call(LGBM_BoosterDumpModel, checked_ptr(bst),
                              Rf_asInteger(num_iteration));
}

SEXP LGBMR_BoosterFeatureImportance(SEXP bst, SEXP num_iteration,
                                    SEXP importance_type) {
  BoosterHandle h = checked_ptr(bst);
  int32_t nf = 0;
  CHECK_CALL(LGBM_BoosterGetNumFeature(h, &nf));
  SEXP out = PROTECT(Rf_allocVector(REALSXP, nf));
  CHECK_CALL(LGBM_BoosterFeatureImportance(h, Rf_asInteger(num_iteration),
                                           Rf_asInteger(importance_type),
                                           REAL(out)));
  UNPROTECT(1);
  return out;
}

SEXP LGBMR_BoosterResetParameter(SEXP bst, SEXP params) {
  CHECK_CALL(LGBM_BoosterResetParameter(checked_ptr(bst),
                                        CHAR(Rf_asChar(params))));
  return R_NilValue;
}

SEXP LGBMR_BoosterMerge(SEXP bst, SEXP other) {
  CHECK_CALL(LGBM_BoosterMerge(checked_ptr(bst), checked_ptr(other)));
  return R_NilValue;
}

/* ---- registration --------------------------------------------------- */
#define CALLDEF(name, n) {#name, (DL_FUNC)&name, n}
static const R_CallMethodDef call_methods[] = {
    CALLDEF(LGBMR_DatasetCreateFromMat, 3),
    CALLDEF(LGBMR_DatasetCreateFromFile, 3),
    CALLDEF(LGBMR_DatasetGetNumData, 1),
    CALLDEF(LGBMR_DatasetGetNumFeature, 1),
    CALLDEF(LGBMR_DatasetSetField, 3),
    CALLDEF(LGBMR_DatasetGetField, 2),
    CALLDEF(LGBMR_DatasetSaveBinary, 2),
    CALLDEF(LGBMR_DatasetSetFeatureNames, 2),
    CALLDEF(LGBMR_DatasetGetFeatureNames, 1),
    CALLDEF(LGBMR_DatasetUpdateParam, 2),
    CALLDEF(LGBMR_BoosterCreate, 2),
    CALLDEF(LGBMR_BoosterCreateFromModelfile, 1),
    CALLDEF(LGBMR_BoosterLoadModelFromString, 1),
    CALLDEF(LGBMR_BoosterAddValidData, 2),
    CALLDEF(LGBMR_BoosterUpdateOneIter, 1),
    CALLDEF(LGBMR_BoosterUpdateOneIterCustom, 3),
    CALLDEF(LGBMR_BoosterRollbackOneIter, 1),
    CALLDEF(LGBMR_BoosterGetCurrentIteration, 1),
    CALLDEF(LGBMR_BoosterGetNumClasses, 1),
    CALLDEF(LGBMR_BoosterGetNumFeature, 1),
    CALLDEF(LGBMR_BoosterGetEvalNames, 1),
    CALLDEF(LGBMR_BoosterGetFeatureNames, 1),
    CALLDEF(LGBMR_BoosterGetEval, 2),
    CALLDEF(LGBMR_BoosterGetPredict, 2),
    CALLDEF(LGBMR_BoosterPredictForMat, 5),
    CALLDEF(LGBMR_BoosterSaveModel, 3),
    CALLDEF(LGBMR_BoosterSaveModelToString, 2),
    CALLDEF(LGBMR_BoosterDumpModel, 2),
    CALLDEF(LGBMR_BoosterFeatureImportance, 3),
    CALLDEF(LGBMR_BoosterResetParameter, 2),
    CALLDEF(LGBMR_BoosterMerge, 2),
    {NULL, NULL, 0}};

void R_init_lightgbm_tpu_R(DllInfo *dll) {
  R_registerRoutines(dll, NULL, call_methods, NULL, NULL);
  R_useDynamicSymbols(dll, FALSE);
}
