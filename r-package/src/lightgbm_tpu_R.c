/* .Call glue over the LGBM_* C ABI exported by
 * native/liblightgbm_tpu.so — the same thin argument-shuffle role as
 * the reference's R-package/src/lightgbm_R.cpp (1-625), written
 * against this framework's trampoline.  Build with:
 *   R CMD SHLIB lightgbm_tpu_R.c -L../../native -llightgbm_tpu
 * (needs an R toolchain; see ../README.md for the validation story).
 */
#include <R.h>
#include <Rinternals.h>
#include <stdint.h>
#include <string.h>

typedef void *DatasetHandle;
typedef void *BoosterHandle;

extern const char *LGBM_GetLastError(void);
extern int LGBM_DatasetCreateFromMat(const void *, int, int32_t, int32_t,
                                     int, const char *, const DatasetHandle,
                                     DatasetHandle *);
extern int LGBM_DatasetSetField(DatasetHandle, const char *, const void *,
                                int32_t, int);
extern int LGBM_DatasetFree(DatasetHandle);
extern int LGBM_BoosterCreate(const DatasetHandle, const char *,
                              BoosterHandle *);
extern int LGBM_BoosterUpdateOneIter(BoosterHandle, int *);
extern int LGBM_BoosterPredictForMat(BoosterHandle, const void *, int,
                                     int32_t, int32_t, int, int, int,
                                     const char *, int64_t *, double *);
extern int LGBM_BoosterSaveModel(BoosterHandle, int, int, const char *);
extern int LGBM_BoosterCreateFromModelfile(const char *, int *,
                                           BoosterHandle *);
extern int LGBM_BoosterFree(BoosterHandle);

#define CHECK_CALL(x) \
  if ((x) != 0) Rf_error("lightgbm_tpu: %s", LGBM_GetLastError())

static void dataset_finalizer(SEXP ext) {
  DatasetHandle h = R_ExternalPtrAddr(ext);
  if (h != NULL) { LGBM_DatasetFree(h); R_ClearExternalPtr(ext); }
}

static void booster_finalizer(SEXP ext) {
  BoosterHandle h = R_ExternalPtrAddr(ext);
  if (h != NULL) { LGBM_BoosterFree(h); R_ClearExternalPtr(ext); }
}

SEXP LGBMR_DatasetCreateFromMat(SEXP mat, SEXP nrow, SEXP ncol,
                                SEXP params, SEXP label) {
  DatasetHandle h = NULL;
  int nr = Rf_asInteger(nrow), nc = Rf_asInteger(ncol);
  /* R matrices are column-major: is_row_major = 0 */
  CHECK_CALL(LGBM_DatasetCreateFromMat(REAL(mat), /*float64*/ 1, nr, nc, 0,
                                       CHAR(Rf_asChar(params)), NULL, &h));
  if (!Rf_isNull(label)) {
    int n = Rf_length(label);
    float *buf = (float *)R_alloc(n, sizeof(float));
    double *src = REAL(label);
    for (int i = 0; i < n; i++) buf[i] = (float)src[i];
    CHECK_CALL(LGBM_DatasetSetField(h, "label", buf, n, /*float32*/ 0));
  }
  SEXP ext = PROTECT(R_MakeExternalPtr(h, R_NilValue, R_NilValue));
  R_RegisterCFinalizerEx(ext, dataset_finalizer, TRUE);
  UNPROTECT(1);
  return ext;
}

SEXP LGBMR_BoosterCreate(SEXP ds, SEXP params) {
  BoosterHandle h = NULL;
  CHECK_CALL(LGBM_BoosterCreate(R_ExternalPtrAddr(ds),
                                CHAR(Rf_asChar(params)), &h));
  SEXP ext = PROTECT(R_MakeExternalPtr(h, R_NilValue, R_NilValue));
  R_RegisterCFinalizerEx(ext, booster_finalizer, TRUE);
  UNPROTECT(1);
  return ext;
}

SEXP LGBMR_BoosterUpdateOneIter(SEXP bst) {
  int finished = 0;
  CHECK_CALL(LGBM_BoosterUpdateOneIter(R_ExternalPtrAddr(bst), &finished));
  return Rf_ScalarLogical(finished);
}

SEXP LGBMR_BoosterPredictForMat(SEXP bst, SEXP mat, SEXP nrow, SEXP ncol) {
  int nr = Rf_asInteger(nrow), nc = Rf_asInteger(ncol);
  SEXP out = PROTECT(Rf_allocVector(REALSXP, nr));
  int64_t out_len = 0;
  CHECK_CALL(LGBM_BoosterPredictForMat(
      R_ExternalPtrAddr(bst), REAL(mat), 1, nr, nc, 0,
      /*normal*/ 0, /*all iters*/ -1, "", &out_len, REAL(out)));
  if (out_len != nr) Rf_error("prediction length mismatch");
  UNPROTECT(1);
  return out;
}

SEXP LGBMR_BoosterSaveModel(SEXP bst, SEXP filename) {
  CHECK_CALL(LGBM_BoosterSaveModel(R_ExternalPtrAddr(bst), 0, -1,
                                   CHAR(Rf_asChar(filename))));
  return R_NilValue;
}

SEXP LGBMR_BoosterCreateFromModelfile(SEXP filename) {
  BoosterHandle h = NULL;
  int iters = 0;
  CHECK_CALL(LGBM_BoosterCreateFromModelfile(CHAR(Rf_asChar(filename)),
                                             &iters, &h));
  SEXP ext = PROTECT(R_MakeExternalPtr(h, R_NilValue, R_NilValue));
  R_RegisterCFinalizerEx(ext, booster_finalizer, TRUE);
  UNPROTECT(1);
  return ext;
}
