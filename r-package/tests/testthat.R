library(testthat)

# Load the package sources directly (no install step in this repo):
# the glue .so is built by `R CMD SHLIB` per ../README.md.
for (f in list.files(file.path("..", "R"), full.names = TRUE)) source(f)
lgb.load_lib(lib_dir = file.path("..", "..", "native"),
             glue_so = file.path("..", "src", "lightgbm_tpu_R.so"))

test_dir("testthat")
