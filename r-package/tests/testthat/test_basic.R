# Mirrors the reference R-package/tests/testthat/test_basic.R flow:
# train / predict / save / reload / early stop on the agaricus-like
# binary task, using the repo's committed sample data.

context("lightgbmtpu basic train/predict")

data_path <- file.path("..", "..", "..", "tests", "fixtures", "interop",
                       "binary.test")
raw <- as.matrix(read.table(data_path))
y <- raw[, 1]
X <- raw[, -1, drop = FALSE]

test_that("train and predict binary classification", {
  dtrain <- lgb.Dataset(X, label = y)
  bst <- lgb.train(params = list(objective = "binary", verbose = -1),
                   data = dtrain, nrounds = 20L, verbose = 0L)
  expect_true(lgb.is.Booster(bst))
  expect_equal(lgb.Booster.current_iter(bst), 20L)
  pred <- predict(bst, X)
  expect_equal(length(pred), nrow(X))
  expect_true(all(pred >= 0 & pred <= 1))
  auc <- local({
    r <- rank(pred)
    pos <- y > 0.5
    (sum(r[pos]) - sum(pos) * (sum(pos) + 1) / 2) /
      (sum(pos) * sum(!pos))
  })
  expect_gt(auc, 0.9)
})

test_that("save/load round trip preserves predictions", {
  dtrain <- lgb.Dataset(X, label = y)
  bst <- lgb.train(params = list(objective = "binary", verbose = -1),
                   data = dtrain, nrounds = 10L, verbose = 0L)
  pred <- predict(bst, X)
  tmp <- tempfile(fileext = ".txt")
  lgb.save(bst, tmp)
  bst2 <- lgb.load(tmp)
  expect_equal(predict(bst2, X), pred, tolerance = 1e-9)
  # string round trip
  s <- lgb.Booster.to_string(bst)
  bst3 <- lgb.load(model_str = s)
  expect_equal(predict(bst3, X), pred, tolerance = 1e-9)
})

test_that("RDS round trip via saveRDS.lgb.Booster", {
  dtrain <- lgb.Dataset(X, label = y)
  bst <- lgb.train(params = list(objective = "binary", verbose = -1),
                   data = dtrain, nrounds = 5L, verbose = 0L)
  pred <- predict(bst, X)
  tmp <- tempfile(fileext = ".rds")
  saveRDS.lgb.Booster(bst, tmp)
  back <- readRDS.lgb.Booster(tmp)
  expect_equal(predict(back, X), pred, tolerance = 1e-9)
})

test_that("validation metrics are recorded and early stopping works", {
  n <- nrow(X)
  idx <- seq_len(n %/% 2)
  dtrain <- lgb.Dataset(X[idx, ], label = y[idx])
  dvalid <- lgb.Dataset.create.valid(dtrain, X[-idx, ], label = y[-idx])
  bst <- lgb.train(params = list(objective = "binary", metric = "auc",
                                 verbose = -1),
                   data = dtrain, nrounds = 50L,
                   valids = list(valid = dvalid),
                   early_stopping_rounds = 5L, verbose = 0L)
  rec <- lgb.get.eval.result(bst, "valid", "auc")
  expect_gt(length(rec), 0L)
  expect_true(bst$best_iter > 0L)
})

test_that("feature importance and interpretation", {
  dtrain <- lgb.Dataset(X, label = y,
                        colnames = paste0("f", seq_len(ncol(X))))
  bst <- lgb.train(params = list(objective = "binary", verbose = -1),
                   data = dtrain, nrounds = 10L, verbose = 0L)
  imp <- lgb.importance(bst)
  expect_equal(nrow(imp), ncol(X))
  expect_true(all(imp$Gain >= 0))
  expect_equal(sum(imp$Gain), 1, tolerance = 1e-6)
  inter <- lgb.interprete(bst, X, idxset = c(1L, 2L))
  expect_equal(length(inter), 2L)
  # contributions + bias sum to the raw prediction
  raw1 <- predict(bst, X[1, , drop = FALSE], rawscore = TRUE)
  expect_equal(sum(inter[[1L]]$Contribution), raw1, tolerance = 1e-4)
})

test_that("continued training from init_model adds trees", {
  dtrain <- lgb.Dataset(X, label = y)
  bst <- lgb.train(params = list(objective = "binary", verbose = -1),
                   data = dtrain, nrounds = 5L, verbose = 0L)
  tmp <- tempfile(fileext = ".txt")
  lgb.save(bst, tmp)
  dtrain2 <- lgb.Dataset(X, label = y)
  bst2 <- lgb.train(params = list(objective = "binary", verbose = -1),
                    data = dtrain2, nrounds = 5L, init_model = tmp,
                    verbose = 0L)
  expect_equal(lgb.Booster.current_iter(bst2), 10L)
})
