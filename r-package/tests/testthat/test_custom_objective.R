# Mirrors reference tests/testthat/test_custom_objective.R: custom
# fobj/feval through LGBM_BoosterUpdateOneIterCustom.

context("custom objective")

data_path <- file.path("..", "..", "..", "tests", "fixtures", "interop",
                       "binary.test")
raw <- as.matrix(read.table(data_path))
y <- raw[, 1]
X <- raw[, -1, drop = FALSE]

logregobj <- function(preds, dtrain) {
  labels <- getinfo(dtrain, "label")
  p <- 1 / (1 + exp(-preds))
  list(grad = p - labels, hess = p * (1 - p))
}

evalerror <- function(preds, dtrain) {
  labels <- getinfo(dtrain, "label")
  err <- mean((preds > 0) != (labels > 0.5))
  list("error", err, FALSE)
}

test_that("custom objective trains and improves", {
  dtrain <- lgb.Dataset(X, label = y, free_raw_data = FALSE)
  bst <- lgb.train(params = list(metric = "none", verbose = -1),
                   data = dtrain, nrounds = 30L, obj = logregobj,
                   eval = evalerror, verbose = 0L)
  preds <- predict(bst, X, rawscore = TRUE)
  err <- mean((preds > 0) != (y > 0.5))
  expect_lt(err, 0.3)
})
