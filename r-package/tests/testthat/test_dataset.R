# Mirrors reference tests/testthat/test_dataset.R: field get/set,
# dims, save_binary, valid-set mapper sharing.

context("lgb.Dataset")

data_path <- file.path("..", "..", "..", "tests", "fixtures", "interop",
                       "binary.test")
raw <- as.matrix(read.table(data_path))
y <- raw[, 1]
X <- raw[, -1, drop = FALSE]

test_that("dim and colnames", {
  ds <- lgb.Dataset(X, label = y,
                    colnames = paste0("c", seq_len(ncol(X))))
  expect_equal(dim(ds), dim(X))
  lgb.Dataset.construct(ds)
  expect_equal(dim(ds)[1], nrow(X))
  expect_equal(dimnames(ds)[[2]], paste0("c", seq_len(ncol(X))))
})

test_that("getinfo/setinfo round trip", {
  ds <- lgb.Dataset(X, label = y)
  lgb.Dataset.construct(ds)
  expect_equal(getinfo(ds, "label"), as.numeric(y), tolerance = 1e-6)
  w <- runif(nrow(X))
  setinfo(ds, "weight", w)
  expect_equal(getinfo(ds, "weight"), w, tolerance = 1e-6)
})

test_that("save_binary writes a loadable file", {
  ds <- lgb.Dataset(X, label = y)
  tmp <- tempfile(fileext = ".bin")
  lgb.Dataset.save(ds, tmp)
  expect_true(file.exists(tmp))
  expect_gt(file.info(tmp)$size, 0)
})

test_that("valid set shares mappers with its reference", {
  idx <- seq_len(nrow(X) %/% 2)
  dtrain <- lgb.Dataset(X[idx, ], label = y[idx])
  dvalid <- lgb.Dataset.create.valid(dtrain, X[-idx, ], label = y[-idx])
  bst <- lgb.train(params = list(objective = "binary", metric = "auc",
                                 verbose = -1),
                   data = dtrain, nrounds = 5L,
                   valids = list(valid = dvalid), verbose = 0L)
  expect_gt(lgb.get.eval.result(bst, "valid", "auc")[1], 0.5)
})
