# Mirrors reference tests/testthat/test_parameters.R: parameter string
# handling and cb.reset.parameter scheduling.

context("parameters")

data_path <- file.path("..", "..", "..", "tests", "fixtures", "interop",
                       "binary.test")
raw <- as.matrix(read.table(data_path))
y <- raw[, 1]
X <- raw[, -1, drop = FALSE]

test_that("params2str formats scalars, vectors and logicals", {
  expect_equal(lgb.params2str(list()), "")
  expect_equal(lgb.params2str(list(a = 1, b = "x")), "a=1 b=x")
  expect_equal(lgb.params2str(list(v = c(1, 3, 5))), "v=1,3,5")
  expect_equal(lgb.params2str(list(f = TRUE)), "f=true")
  expect_error(lgb.params2str(list(1)), "named")
})

test_that("learning rate schedule via cb.reset.parameter", {
  dtrain <- lgb.Dataset(X, label = y)
  bst <- lgb.train(
    params = list(objective = "binary", verbose = -1,
                  learning_rate = 0.1),
    data = dtrain, nrounds = 6L, verbose = 0L,
    callbacks = list(cb.reset.parameter(
      list(learning_rate = function(i, total) 0.1 * 0.9^i))))
  expect_equal(lgb.Booster.current_iter(bst), 6L)
})

test_that("cv aggregates across folds", {
  cv <- lgb.cv(params = list(objective = "binary", metric = "auc",
                             verbose = -1),
               data = X, label = y, nrounds = 8L, nfold = 3L,
               verbose = 0L)
  expect_true("test.auc.mean" %in% names(cv$record_evals))
  expect_equal(length(cv$record_evals$test.auc.mean), 8L)
  expect_gt(cv$record_evals$test.auc.mean[8], 0.8)
  expect_true(cv$best_iter >= 1L)
})
