/* SWIG interface for the lightgbm_tpu C ABI (Java target).
 *
 * The counterpart of the reference's swig/lightgbmlib.i: wraps the
 * LGBM_* export surface of liblightgbm_tpu.so so JVM consumers (e.g.
 * Spark integrations) drive training/prediction through JNI.  The
 * helper typemaps below give Java callers typed carriers for the
 * out-parameters (handles, counts, score buffers) — the same pattern
 * the reference provides via carrays/cpointer helpers.
 *
 * Generate + build (needs a JDK for jni.h):
 *   swig -java -package com.lightgbm.tpu -outdir java/com/lightgbm/tpu \
 *        -o lightgbm_tpu_wrap.c lightgbm_tpu.i
 *   cc -shared -fPIC -I$JAVA_HOME/include -I$JAVA_HOME/include/linux \
 *        lightgbm_tpu_wrap.c -L../native -llightgbm_tpu \
 *        -o liblightgbm_tpu_swig.so
 *
 * The underlying ABI contract is validated without a JVM by
 * tests/test_capi_so.py (ctypes against the same .so); a CI with a JDK
 * runs tests/test_swig_java.py's generation step plus this compile.
 */
%module lightgbmtpulib

%{
#include "../native/lightgbm_tpu_c_api.h"
%}

%include "stdint.i"
%include "carrays.i"
%include "cpointer.i"

/* typed out-parameter carriers (Java: new_voidpp() -> handle cell,
 * voidpp_value() to read it back; arrays for score/data buffers) */
%pointer_functions(void *, voidpp)
%pointer_functions(int, intp)
%pointer_functions(int64_t, int64p)
%pointer_functions(double, doublep)
%array_functions(double, doubleArray)
%array_functions(float, floatArray)
%array_functions(int, intArray)
%array_functions(int64_t, int64Array)

/* string-array out-params (eval/feature names): fixed-size char buffers
 * the caller allocates; mirrors the reference's string_array helpers */
%include "cmalloc.i"
%allocators(void, voidmem)

%include "../native/lightgbm_tpu_c_api.h"
