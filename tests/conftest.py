"""Test harness configuration.

Runs the whole suite on a virtual 8-device CPU platform so the parallel tree
learners (data/feature/voting over a jax Mesh) are exercised without TPU pod
hardware — the single-process multi-rank emulation the reference only
sketches via THREAD_LOCAL network state (src/network/network.cpp:13-23).
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.RandomState(42)
