"""Test harness configuration.

Runs the whole suite on a virtual 8-device CPU platform so the parallel tree
learners (data/feature/voting over a jax Mesh) are exercised without TPU pod
hardware — the single-process multi-rank emulation the reference only
sketches via THREAD_LOCAL network state (src/network/network.cpp:13-23).

NOTE: this image pre-imports jax via sitecustomize (TPU tunnel registration),
so env vars must be FORCED (not setdefault) — backend selection is lazy, so
overriding here, before the first jax op, still works.
"""
import os

# The image caps the stack at 8 MB; a full-suite run accumulates enough
# jit state that a late XLA-CPU compile recurses past it and SEGFAULTS
# (observed twice at ~78%, inside an estimator-check fit).  The hard
# limit is unlimited, so raise the soft limit for the test process and
# every thread it spawns after this point.
import resource

_soft, _hard = resource.getrlimit(resource.RLIMIT_STACK)
if _soft != resource.RLIM_INFINITY and (_soft < 512 << 20):
    resource.setrlimit(resource.RLIMIT_STACK,
                       (512 << 20 if _hard == resource.RLIM_INFINITY
                        else min(512 << 20, _hard), _hard))
import threading

threading.stack_size(64 << 20)   # XLA worker threads get big stacks too

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# sitecustomize may have already initialized the axon TPU backend; reroute
# to the virtual CPU platform (config first, then drop cached backends).
jax.config.update("jax_platforms", "cpu")
try:
    import jax.extend.backend
    jax.extend.backend.clear_backends()
except (ImportError, AttributeError):  # fall back to the private spelling
    from jax._src import xla_bridge as _xb
    _xb._clear_backends()

# x64 on in tests: numpy-oracle comparisons need f64; library code uses
# explicit dtypes everywhere so production (x64 off) behavior is unchanged.
jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.RandomState(42)


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_per_module():
    """Accumulated jit executables eventually make a late XLA-CPU
    compile recurse past even the raised stack cap and SEGFAULT (first
    hit at ~78% in round 4, fixed by a clear before the estimator-check
    module; round 5's extra tests moved the crash to ~68%, inside
    test_review_fixes).  Clearing between modules bounds accumulation
    for good; modules recompile their own programs anyway, so the
    wall-clock cost is small."""
    yield
    jax.clear_caches()


def pytest_sessionstart(session):
    assert jax.default_backend() == "cpu", (
        "tests must run on the virtual CPU platform, got %s" % jax.default_backend())
    assert jax.device_count() == 8, (
        "expected 8 virtual CPU devices, got %d" % jax.device_count())
