"""Deliberate SPMD collective-symmetry violations — lint fixture.

Never imported; parsed by tests/test_lint.py only.
"""
import threading


def allreduce_histograms(hist):
    return hist


def _sync_wait(x):
    return x


def helper_reduce(h):
    # collective-bearing only transitively: no collective name here
    return allreduce_histograms(h)


class Comm:
    def __init__(self):
        self._lock = threading.Lock()
        self.rank = 0
        self.world = 1

    def rank_gated(self, h):
        if self.rank == 0:
            return allreduce_histograms(h)      # collective-rank-branch
        return h

    def transitive_gated(self, h):
        if self.rank == 0:
            return helper_reduce(h)     # rank-branch via the call graph
        return h

    def loop_gated(self, h):
        while self.world > 1:
            h = _sync_wait(h)           # loop bounded by world size
        return h

    def divergent(self, h):
        if self.rank == 0:
            g = allreduce_histograms(h)     # collective-divergent-sequence
            _sync_wait(g)
        else:
            g = _sync_wait(h)
        return g

    def under_lock(self, h):
        with self._lock:
            return allreduce_histograms(h)      # collective-under-lock


def shard_psum(x):
    return psum(x, "mp")        # noqa: F821 — parsed, never imported


def mesh_reduce(x):
    # the shard_map closure form: shard_psum is PASSED, never called by
    # name — the closure rule must still mark mesh_reduce bearing
    return shard_map(shard_psum, None)      # noqa: F821


class MeshComm:
    def __init__(self):
        self.rank = 0

    def mesh_gated(self, x):
        if self.rank == 0:
            return mesh_reduce(x)   # collective-rank-branch via the
        return x                    # shard_map closure rule
