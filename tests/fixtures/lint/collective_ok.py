"""Rank-symmetric collective usage — lint fixture, must stay clean.

Never imported; parsed by tests/test_lint.py only.
"""


def allgather_rows(rows):
    return rows


def broadcasted_iota(n):
    return list(range(n))


class Comm:
    def __init__(self):
        self.rank = 0
        self.dead = set()

    def symmetric(self, h, hub_rank):
        # identical collective sequence in both arms: exempt
        if self.rank == hub_rank:
            g = allgather_rows(h)
        else:
            g = allgather_rows(h)
        return g

    def static_branch(self, h, dp):
        # config branch, identical on every rank by construction
        if dp:
            return allgather_rows(h)
        return h

    def guard_raise(self, h):
        # guard-and-raise prologue: every surviving rank reaches the
        # collective below
        if self.rank in self.dead:
            raise RuntimeError("fenced")
        return allgather_rows(h)

    def over_batches(self, batches):
        out = []
        for b in batches:       # symmetric loop: same on every rank
            out.append(allgather_rows(b))
        return out

    def with_file(self, h, fh):
        with fh:                # not a lock
            return allgather_rows(h)

    def shape_op(self, n):
        return broadcasted_iota(n)      # shape op, not a collective


def shard_psum(x):
    return psum(x, "mp")        # noqa: F821 — parsed, never imported


def mesh_reduce(x):
    # bearing via the shard_map closure rule ...
    return shard_map(shard_psum, None)      # noqa: F821


def mesh_square(x):
    # ... but a lambda closure is anonymous: nothing to resolve, clean
    return shard_map(lambda v: v * v, None)     # noqa: F821


class MeshComm:
    def __init__(self):
        self.rank = 0

    def every_rank(self, x):
        # reached unconditionally on every rank: symmetric, clean
        return mesh_reduce(x)

    def gated_non_collective(self, x):
        # rank branch, but the shard_map'd closure performs no
        # collective — must NOT flag
        if self.rank == 0:
            return mesh_square(x)
        return x
