"""Deliberate buffer-donation violations — lint fixture.

Never imported (the jax import is only ever parsed); used by
tests/test_lint.py only.
"""
import functools

import jax


def _impl(a, b):
    return a


@functools.partial(jax.jit, donate_argnums=(0,))
def grow_step(arena, grads):
    return arena + grads


def use_after(arena, grads):
    out = grow_step(arena, grads)
    total = arena.sum() + out.sum()     # donation-use-after
    return total


def double_same_call(arena, grads):
    fused = jax.jit(_impl, donate_argnums=(0, 1))
    out = fused(arena, arena)           # donation-double, one call
    return out


def double_sequential(arena, grads):
    g1 = grow_step(arena, grads)
    g2 = grow_step(arena, grads)        # donation-double, no rebind
    return g1 + g2


def escape(arena, grads):
    grow_step(arena, grads)
    return arena                        # donation-escape


class Trainer:
    def __init__(self):
        self._fused = self._build()

    def _build(self):
        fn = jax.jit(_impl, donate_argnums=(0,))
        return fn

    def step(self, state):
        self._fused(state["arena"], 1)
        return state["arena"]           # donation-escape via subscript
