"""Donation-correct idioms from the real tree — lint fixture, clean.

Never imported (the jax import is only ever parsed); used by
tests/test_lint.py only.
"""
import functools

import jax


def _impl3(a, b, c):
    return a, b


@functools.partial(jax.jit, donate_argnums=(0,))
def grow_step(arena, grads):
    return arena + grads


def rebind_then_use(arena, grads):
    arena = grow_step(arena, grads)     # rebound by its own statement
    return arena


def same_statement_rebind(arena, grads):
    arena, stats = grow_step(arena, grads), None
    return arena, stats


def branch_isolated(arena, grads, flag):
    if flag:
        out = grow_step(arena, grads)   # donated in the if-arm only
    else:
        out = arena.sum()               # opposite arm: can't co-execute
    return out


def star_call(arena, bins, grads):
    fused = jax.jit(_impl3, donate_argnums=(0, 1))
    args = (arena, bins, grads)
    arena, bins = fused(*args)          # star-call through tuple literal
    return arena, bins


def dict_closure(state, grads):
    state["arena"] = grow_step(state["arena"], grads)
    return state
