"""tpulint fixture consumer for the driftproj schema."""


def run(cfg):
    x = cfg.tpu_used_knob                        # schema read: fine
    y = cfg.serve_undocumented                   # read, but not in docs
    z = getattr(cfg, "tpu_typo_knob", None)      # -> config-phantom-param
    return x, y, z
