"""tpulint fixture schema: exercises every config-drift check."""

_SCHEMA = [
    ("num_iterations", int, 100),
    ("tpu_used_knob", str, "auto"),
    ("tpu_dead_knob", bool, False),     # -> config-dead-param (unread)
    ("serve_undocumented", int, 1),     # -> config-undocumented-param
]

ALIAS_TABLE = {
    "n_iter": "num_iterations",
    "bad_alias": "nonexistent_param",   # -> config-broken-alias
}
