"""tpulint fixture: every hygiene checker must FIRE on this file."""
import socket


def bare_except(path):
    try:
        return int(open(path).read())      # resource-no-with (MEDIUM)
    except:                                # except-bare (MEDIUM)
        return 0


def swallow(fn):
    try:
        fn()
    except Exception:                      # except-swallow (MEDIUM)
        pass


def leaky_socket(host, port):
    s = socket.socket()                    # socket-no-with (LOW)
    s.connect((host, port))
    s.sendall(b"ping")
    s.close()
    return True
