"""tpulint fixture: NO hygiene checker may fire on this file."""
import contextlib
import logging
import os
import socket

log = logging.getLogger(__name__)


def managed_read(path):
    with open(path) as fh:
        return fh.read()


def narrow_except(path):
    try:
        return managed_read(path)
    except (OSError, ValueError) as exc:   # narrow: fine
        log.warning("read failed: %s", exc)
        return ""


def broad_but_handled(fn):
    try:
        fn()
    except Exception as exc:               # broad but logged: fine
        log.warning("best-effort hook failed: %s", exc)


def managed_socket(host, port):
    with socket.create_connection((host, port)) as s:
        s.sendall(b"ping")


def closing_socket():
    with contextlib.closing(socket.socket()) as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def durable_write(path, data):
    with open(path, "w") as fh:
        fh.write(data)
        fh.flush()
        os.fsync(fh.fileno())              # fsync present: fine


def handed_to_caller(path):
    return open(path, "rb")                # returned: caller manages


def suppressed_leak(path):
    fh = open(path)                        # tpulint: ok=resource-no-with
    return fh.read()
