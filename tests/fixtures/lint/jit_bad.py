"""tpulint fixture: every jit checker must FIRE on this file.

Not imported by anything — scanned as AST only (tests point the lint
suite at this directory explicitly; the repo gate never scans tests/).
"""
import numpy as np
from functools import partial

import jax
import jax.numpy as jnp


@jax.jit
def sync_item(x):
    total = jnp.sum(x)
    return total.item()            # jit-host-sync (HIGH)


@jax.jit
def sync_block(x):
    y = jnp.cumsum(x)
    y.block_until_ready()          # jit-host-sync (HIGH)
    return y


@jax.jit
def sync_numpy(x):
    host = np.asarray(x)           # jit-host-sync (HIGH): host numpy
    return jnp.asarray(host)


@jax.jit
def cast_traced(x):
    return float(x) * 2.0          # jit-host-cast (MEDIUM)


@jax.jit
def branch_traced(x):
    if x > 0:                      # jit-traced-branch (MEDIUM)
        return x
    return -x


@partial(jax.jit, static_argnames=("mode",))
def branch_partial(x, mode):
    val = x if x > 0 else -x       # jit-traced-branch (MEDIUM): IfExp on x
    if mode == "fast":             # NOT flagged: mode is static
        return val
    return val * 2


def wrapped_impl(x, n):
    while x < n:                   # jit-traced-branch: x traced (n static)
        x = x + 1
    return x


wrapped = partial(jax.jit, static_argnames=("n",))(wrapped_impl)
