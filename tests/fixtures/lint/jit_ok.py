"""tpulint fixture: NO jit checker may fire on this file."""
import numpy as np
from functools import partial

import jax
import jax.numpy as jnp


@jax.jit
def shape_branch(x):
    if x.shape[0] > 128:           # shape metadata is trace-concrete
        return jnp.sum(x)
    return jnp.mean(x)


@jax.jit
def len_and_none(x, aux=None):
    if aux is not None:            # identity test never concretizes
        x = x + aux
    n = float(len(x))              # len() is concrete; cast of it too
    return x / n


@partial(jax.jit, static_argnames=("k",))
def static_branch(x, k):
    if k > 3:                      # static param: fine
        return jnp.topk(x, k)[0] if hasattr(jnp, "topk") else x
    return x


@jax.jit
def local_python(x):
    scale = 2.0
    if scale > 1.0:                # plain python local, not a param
        x = x * scale
    return jnp.where(x > 0, x, 0.0)   # jnp.where instead of branching


@jax.jit
def allowed_sync(x):
    s = jnp.sum(x)
    return s.item()                # tpulint: ok=jit-host-sync


def host_helper(x):
    return np.asarray(x).sum()     # not jitted: host numpy is fine


def host_cast(x):
    return float(x)                # not jitted either
