"""tpulint fixture: every lock checker must FIRE on this file."""
import queue
import socket
import threading
import time


class UnguardedWrite:
    """_count is guarded in add() but mutated raw in reset()."""

    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0

    def add(self, n):
        with self._lock:
            self._count += n

    def reset(self):
        self._count = 0            # lock-unguarded-write (HIGH)


class SharedWrite:
    """No locked site for _mode, but two methods race on it."""

    def __init__(self):
        self._lock = threading.Lock()
        self._items = []
        self._mode = "idle"

    def run(self):
        self._mode = "busy"        # lock-shared-write (MEDIUM)
        with self._lock:
            self._items.append(1)

    def describe(self):
        return self._mode


class BlockingUnderLock:
    def __init__(self, sock, q):
        self._lock = threading.Lock()
        self._sock = sock
        self._q = q
        self._last = b""

    def pump(self):
        with self._lock:
            data = self._sock.recv(4096)     # lock-blocking-call (HIGH)
            item = self._q.get()             # lock-blocking-call (MEDIUM)
            time.sleep(0.5)                  # lock-blocking-call (MEDIUM)
            self._last = data
            return item


class Reentrant:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0

    def outer(self):
        with self._lock:
            with self._lock:       # lock-reentrant (HIGH)
                self._n += 1


class OrderAB:
    def __init__(self, other):
        self._lock = threading.Lock()
        self.other = other

    def cross(self):
        with self._lock:
            self.other.locked_entry()        # A -> B edge


class OrderBA:
    def __init__(self, other):
        self._lock = threading.Lock()
        self.other = other

    def locked_entry(self):
        with self._lock:
            return True

    def cross_back(self):
        with self._lock:
            self.other.cross()               # B -> A edge: cycle (HIGH)
