"""tpulint fixture: NO lock checker may fire on this file."""
import threading


class Disciplined:
    """Guarded attrs always mutated under the lock; Condition.wait on
    the lock's own condition; private helper only called from
    __init__."""

    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._queue = []
        self._stopped = False
        self._init_state()

    def _init_state(self):
        self._queue = []           # init-only helper: no lock needed
        self._stopped = False

    def put(self, item):
        with self._lock:
            self._queue.append(item)
            self._cv.notify()

    def take(self):
        with self._cv:
            while not self._queue:
                self._cv.wait()    # Condition.wait releases the lock
            return self._queue.pop(0)

    def stop(self):
        with self._lock:
            self._stopped = True

    def snapshot(self):
        with self._lock:
            return list(self._queue)

    def peek_len(self):
        return len(self._queue)    # read outside lock: not flagged


class ReentrantByDesign:
    def __init__(self):
        self._lock = threading.RLock()
        self._n = 0

    def outer(self):
        with self._lock:
            with self._lock:       # RLock: re-acquire is fine
                self._n += 1


class TimeoutsEverywhere:
    def __init__(self, q, worker):
        self._lock = threading.Lock()
        self._q = q
        self._worker = worker
        self._got = None

    def drain(self):
        with self._lock:
            self._got = self._q.get(timeout=1.0)   # timed: fine
            self._worker.join(2.0)                 # timed: fine
