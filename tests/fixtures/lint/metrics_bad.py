"""Deliberate metrics-hygiene violations (never scanned by the repo
gate — tests/ is outside DEFAULT_ROOTS)."""


class _Registry:
    def counter(self, name, help="", **labels):
        return self

    def gauge(self, name, help="", **labels):
        return self

    def histogram(self, name, bounds=(), help="", **labels):
        return self


registry = _Registry()


def bad_prefix():
    # name escapes the lgbm_ namespace: invisible to every dashboard glob
    registry.counter("serve_requests_total", help="oops")
    registry.gauge("up", help="oops")


def bad_labels(request_id, row):
    # per-request label values: unbounded cardinality
    registry.counter("lgbm_serve_requests_total",
                     request=f"req-{request_id}")
    registry.gauge("lgbm_serve_queue_depth_rows",
                   row="row-%d" % row)
    registry.histogram("lgbm_serve_latency_ms",
                       shard="{}".format(row))


def bad_dynamic(name):
    # name unauditable by the prefix check
    registry.gauge(name, help="who knows")
