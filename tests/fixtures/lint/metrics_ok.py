"""Clean metrics usage the hygiene checker must NOT flag."""

_TABLE = (
    ("lgbm_comm_bytes_sent_total", "Bytes sent"),
    ("lgbm_comm_bytes_received_total", "Bytes received"),
)


class _Registry:
    def counter(self, name, help="", **labels):
        return self

    def gauge(self, name, help="", **labels):
        return self


registry = _Registry()


def good(rank):
    registry.counter("lgbm_serve_requests_total", help="Requests",
                     model="churn")
    # bounded label through str() of a small enum-ish value is fine
    registry.gauge("lgbm_hybrid_host_up", host=str(rank))
    # table-driven family, audited in the table, exempted on the line
    for name, help_text in _TABLE:
        registry.counter(name, help=help_text)  # tpulint: ok=metrics-dynamic-name


def not_a_registry(things):
    # a receiver that is not a registry: never a metric site
    things.counter("whatever", tag=f"x-{len(things)}")
