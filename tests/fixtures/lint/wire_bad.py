"""Deliberate wire-protocol violations — lint fixture.

A miniature frame protocol: the FRAME_* module constants make this a
wire module in the checker's eyes.  Never imported; parsed by
tests/test_lint.py only.
"""

FRAME_DATA = 0
FRAME_POISON = 1
FRAME_PING = 2          # sent below, never handled -> unhandled-kind
FRAME_RETIRED = 7       # never sent nor handled -> dead-kind


def _send_frame(sock, payload, kind):
    sock.sendall(payload)


def _recv_frame(sock):
    return sock.recv(1024), 0, 0


def ping(sock):
    _send_frame(sock, b"", kind=FRAME_PING)


def drain(sock):
    # wire-unfenced-recv: no generation compare anywhere in here
    payload, gen_stamp, kind = _recv_frame(sock)
    return payload


def ctrl_loop(sock):
    # wire-blocking-handler (and unfenced): dispatches on frame kinds,
    # loops on a recv with no select/settimeout bound
    while True:
        payload, gen_stamp, kind = _recv_frame(sock)
        if kind == FRAME_POISON:
            return payload
