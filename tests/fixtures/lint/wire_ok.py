"""Well-formed miniature wire protocol — lint fixture, must be clean.

Never imported; parsed by tests/test_lint.py only.
"""
import select

FRAME_DATA = 0
FRAME_POISON = 1


def _send_frame(sock, payload, kind):
    sock.sendall(payload)


def _recv_frame(sock):
    return sock.recv(1024), 0, 0


def poison(sock):
    _send_frame(sock, b"", kind=FRAME_POISON)


class Comm:
    def __init__(self):
        self.generation = 0

    def recv_fenced(self, sock):
        payload, peer_gen, kind = _recv_frame(sock)
        if peer_gen != self.generation:
            return None
        if kind == FRAME_POISON:
            raise RuntimeError("poisoned")
        return payload

    def ctrl_loop(self, sock, stop):
        while not stop.is_set():
            ready, _, _ = select.select([sock], [], [], 0.5)
            if not ready:
                continue
            payload, peer_gen, kind = _recv_frame(sock)
            if peer_gen != self.generation:
                continue
            if kind == FRAME_POISON:
                return payload


def handshake(sock):
    # pre-formation: the generation does not exist yet on this path
    # tpulint: disable-next-line=wire-unfenced-recv
    return _recv_frame(sock)[0]
