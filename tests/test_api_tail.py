"""API-tail coverage: Booster.model_from_string (post-ctor),
Booster.get_leaf_output, Dataset.attr/set_attr round-trip, and the
reset_parameter callback routing EVERY scheduled parameter through
Booster.reset_parameter (not just learning_rate)."""
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import callback


def _fit(params=None, n=300, iters=6, seed=0, **train_kw):
    rng = np.random.RandomState(seed)
    X = rng.rand(n, 6)
    y = X[:, 0] * 2 - X[:, 1] + 0.05 * rng.randn(n)
    base = {"objective": "regression", "num_leaves": 15, "verbose": -1,
            "min_data_in_leaf": 5}
    base.update(params or {})
    return lgb.train(base, lgb.Dataset(X, label=y),
                     num_boost_round=iters, **train_kw), X


# --------------------------------------------------------------------- #
# Booster.model_from_string (post-constructor re-init)
# --------------------------------------------------------------------- #
def test_model_from_string_post_ctor():
    bst_a, X = _fit(seed=0)
    bst_b, _ = _fit({"num_leaves": 7}, iters=12, seed=1)
    ref_b = bst_b.predict(X)
    # overwrite bst_a in place with bst_b's model text
    out = bst_a.model_from_string(bst_b.model_to_string())
    assert out is bst_a                      # chainable, reference API shape
    np.testing.assert_array_equal(bst_a.predict(X), ref_b)
    assert bst_a.num_trees() == bst_b.num_trees()
    assert bst_a.best_iteration == -1        # stale state reset


def test_model_from_string_roundtrip_identity():
    bst, X = _fit(seed=2)
    ref = bst.predict(X)
    bst.model_from_string(bst.model_to_string())
    np.testing.assert_array_equal(bst.predict(X), ref)


# --------------------------------------------------------------------- #
# Booster.get_leaf_output
# --------------------------------------------------------------------- #
def test_get_leaf_output_matches_tree_and_c_api():
    from lightgbm_tpu import c_api
    import ctypes
    bst, X = _fit(seed=3)
    # same model through the C API surface for cross-checking
    niter, handle = ctypes.c_int(), ctypes.c_void_p()
    c_api.LGBM_BoosterLoadModelFromString(
        bst.model_to_string().encode(), ctypes.byref(niter),
        ctypes.byref(handle))
    try:
        g = bst._gbdt
        for tree_id in (0, len(g.models) - 1):
            tree = g.models[tree_id]
            for leaf_id in (0, tree.num_leaves - 1):
                got = bst.get_leaf_output(tree_id, leaf_id)
                assert got == float(tree.leaf_value[leaf_id])
                out = ctypes.c_double()
                c_api.LGBM_BoosterGetLeafValue(handle, tree_id, leaf_id,
                                               ctypes.byref(out))
                assert got == out.value
    finally:
        c_api.LGBM_BoosterFree(handle)


def test_leaf_outputs_sum_to_raw_prediction():
    bst, X = _fit(seed=4)
    leaves = np.asarray(bst.predict(X[:5], pred_leaf=True), int)
    raw = bst.predict(X[:5], raw_score=True)
    for i in range(5):
        total = sum(bst.get_leaf_output(t, int(leaves[i, t]))
                    for t in range(leaves.shape[1]))
        np.testing.assert_allclose(total, raw[i], rtol=1e-12)


def test_get_leaf_output_bounds_checked():
    bst, _ = _fit(seed=5)
    from lightgbm_tpu.utils import log
    with pytest.raises(log.LightGBMError):
        bst.get_leaf_output(10_000, 0)
    with pytest.raises(log.LightGBMError):
        bst.get_leaf_output(0, 10_000)


# --------------------------------------------------------------------- #
# Dataset.attr / set_attr
# --------------------------------------------------------------------- #
def test_dataset_attr_roundtrip():
    ds = lgb.Dataset(np.random.rand(20, 3), label=np.zeros(20))
    assert ds.attr("missing") is None
    out = ds.set_attr(source="unit-test", rows=20)
    assert out is ds                           # chainable
    assert ds.attr("source") == "unit-test"
    assert ds.attr("rows") == "20"             # str coercion
    ds.set_attr(source=None)                   # None deletes
    assert ds.attr("source") is None
    assert ds.attr("rows") == "20"


# --------------------------------------------------------------------- #
# reset_parameter callback: ALL scheduled params take effect
# --------------------------------------------------------------------- #
def test_reset_parameter_callback_routes_all_params():
    lam = [0.0, 0.5, 1.0, 2.0, 4.0, 8.0]
    bst, _ = _fit(iters=6, seed=6,
                  callbacks=[callback.reset_parameter(lambda_l2=lam)])
    # the schedule's FINAL value must be live on the booster, proving the
    # callback reached Booster.reset_parameter -> split params, not just
    # a mutated learning_rate
    assert bst._gbdt.split_params.lambda_l2 == lam[-1]
    assert bst.params["lambda_l2"] == lam[-1]


def test_reset_parameter_callback_learning_rate_schedule():
    lrs = [0.3, 0.2, 0.1, 0.05]
    bst, _ = _fit(iters=4, seed=7,
                  callbacks=[callback.reset_parameter(learning_rate=lrs)])
    assert bst._gbdt.shrinkage_rate == lrs[-1]
    assert bst._gbdt.config.learning_rate == lrs[-1]


def test_reset_parameter_changes_training_outcome():
    # an extreme lambda_l2 schedule must actually alter the trees; if the
    # callback silently dropped non-lr params both runs would be identical
    sched = callback.reset_parameter(
        lambda_l2=lambda it: 0.0 if it < 3 else 1e6)
    bst_a, X = _fit(iters=6, seed=8)
    bst_b, _ = _fit(iters=6, seed=8, callbacks=[sched])
    assert not np.array_equal(bst_a.predict(X), bst_b.predict(X))
    # heavy shrinkage-by-regularization: later trees are near-constant
    last = bst_b._gbdt.models[-1]
    assert np.max(np.abs(last.leaf_value[:last.num_leaves])) < 1e-3


def test_booster_reset_parameter_direct():
    bst, _ = _fit(iters=2, seed=9)
    bst.reset_parameter({"lambda_l1": 0.25, "learning_rate": 0.07})
    assert bst._gbdt.split_params.lambda_l1 == 0.25
    assert bst._gbdt.shrinkage_rate == 0.07
