import math

import numpy as np
import pytest

from lightgbm_tpu.io.bin_mapper import (
    CATEGORICAL, MISSING_NAN, MISSING_NONE, MISSING_ZERO, NUMERICAL,
    BinMapper, greedy_find_bin,
)


def make_mapper(values, total=None, max_bin=255, min_data_in_bin=3,
                min_split_data=20, bin_type=NUMERICAL, use_missing=True,
                zero_as_missing=False):
    values = np.asarray(values, dtype=np.float64)
    total = total if total is not None else len(values)
    m = BinMapper()
    m.find_bin(values, total, max_bin, min_data_in_bin, min_split_data,
               bin_type, use_missing, zero_as_missing)
    return m


def test_simple_uniform_bins():
    vals = np.arange(1.0, 1001.0)
    m = make_mapper(vals, max_bin=10)
    assert m.num_bin == 10
    assert not m.is_trivial
    # all values fall into a valid bin, monotonic mapping
    bins = m.values_to_bins(vals)
    assert bins.min() >= 0 and bins.max() == m.num_bin - 1
    assert np.all(np.diff(bins.astype(int)) >= 0)


def test_zero_gets_own_bin():
    vals = np.concatenate([np.linspace(-5, -1, 100), np.linspace(1, 5, 100)])
    total = 300  # 100 implied zeros
    m = make_mapper(vals, total=total, max_bin=16)
    zero_bin = m.value_to_bin(0.0)
    assert m.value_to_bin(1e-40) == zero_bin
    assert m.value_to_bin(-1e-40) == zero_bin
    assert m.value_to_bin(-1.0) < zero_bin < m.value_to_bin(1.0)
    assert m.default_bin == zero_bin


def test_nan_missing_gets_last_bin():
    vals = np.array([1.0, 2.0, 3.0, np.nan, np.nan] * 20)
    m = make_mapper(vals)
    assert m.missing_type == MISSING_NAN
    assert m.value_to_bin(np.nan) == m.num_bin - 1
    bins = m.values_to_bins(np.array([np.nan, 1.0]))
    assert bins[0] == m.num_bin - 1


def test_no_missing():
    m = make_mapper(np.arange(100.0) + 1.0)
    assert m.missing_type == MISSING_NONE
    # NaN at predict time maps to zero's bin
    assert m.value_to_bin(np.nan) == m.value_to_bin(0.0)


def test_use_missing_false():
    vals = np.array([1.0, 2.0, np.nan] * 30)
    m = make_mapper(vals, use_missing=False)
    assert m.missing_type == MISSING_NONE


def test_trivial_constant_feature():
    m = make_mapper(np.full(100, 7.0), total=100)
    assert m.is_trivial


def test_min_data_in_leaf_filter():
    # only 2 samples on one side of the only split -> filtered out
    vals = np.concatenate([np.full(98, 1.0), np.full(2, 5.0)])
    m = make_mapper(vals, min_split_data=20)
    assert m.is_trivial


def test_values_to_bins_matches_scalar():
    rng = np.random.RandomState(0)
    vals = rng.randn(5000) * 10
    vals[rng.rand(5000) < 0.1] = 0.0
    some_nan = vals.copy()
    some_nan[rng.rand(5000) < 0.05] = np.nan
    m = make_mapper(some_nan, max_bin=63)
    test_vals = np.concatenate([some_nan[:500], m.bin_upper_bound[:-1]])
    vec = m.values_to_bins(test_vals)
    scalar = np.array([m.value_to_bin(v) for v in test_vals])
    np.testing.assert_array_equal(vec, scalar)


def test_greedy_find_bin_few_distinct():
    bounds = greedy_find_bin([1.0, 2.0, 3.0], [10, 10, 10], 255, 30, 3)
    assert len(bounds) == 3
    assert bounds[-1] == math.inf
    assert 1.0 < bounds[0] <= 2.0 + 1e-9


def test_categorical_basic():
    vals = np.array([0.0] * 50 + [1.0] * 30 + [2.0] * 15 + [3.0] * 5)
    m = make_mapper(vals, bin_type=CATEGORICAL, min_data_in_bin=1, min_split_data=1)
    assert m.bin_type == CATEGORICAL
    assert not m.is_trivial
    # most frequent category can't be bin 0 when it is category 0
    assert m.value_to_bin(0.0) > 0
    # categories map to distinct bins, ordered by count
    bins = {c: m.value_to_bin(float(c)) for c in [0, 1, 2, 3]}
    assert len(set(bins.values())) == 4
    # unseen category falls into last bin
    assert m.value_to_bin(99.0) == m.num_bin - 1


def test_categorical_negative_is_nan():
    vals = np.array([1.0] * 50 + [2.0] * 30 + [-1.0] * 20)
    m = make_mapper(vals, bin_type=CATEGORICAL, min_data_in_bin=1, min_split_data=1)
    assert m.value_to_bin(-5.0) == m.num_bin - 1


def test_state_round_trip():
    rng = np.random.RandomState(1)
    m = make_mapper(rng.randn(1000))
    m2 = BinMapper.from_state(m.to_state())
    vals = rng.randn(100)
    np.testing.assert_array_equal(m.values_to_bins(vals), m2.values_to_bins(vals))
    cat = make_mapper(np.array([0.0, 1, 1, 2, 2, 2] * 20), bin_type=CATEGORICAL,
                      min_data_in_bin=1, min_split_data=1)
    cat2 = BinMapper.from_state(cat.to_state())
    assert cat2.categorical_2_bin == cat.categorical_2_bin


def test_max_bin_respected():
    rng = np.random.RandomState(2)
    for max_bin in (16, 63, 255):
        m = make_mapper(rng.randn(20000), max_bin=max_bin)
        assert m.num_bin <= max_bin
