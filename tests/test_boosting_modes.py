"""DART / GOSS / RF boosting modes (reference test_engine.py:51,735,752)."""
import numpy as np
import pytest

import lightgbm_tpu as lgb

REGRESSION_TRAIN = "/root/reference/examples/regression/regression.train"
REGRESSION_TEST = "/root/reference/examples/regression/regression.test"


def _load(path):
    mat = np.loadtxt(path)
    return mat[:, 1:], mat[:, 0]


@pytest.fixture(scope="module")
def data():
    X, y = _load(REGRESSION_TRAIN)
    Xt, yt = _load(REGRESSION_TEST)
    return X, y, Xt, yt


@pytest.mark.slow
def test_dart(data):
    X, y, Xt, yt = data
    train = lgb.Dataset(X, y)
    valid = train.create_valid(Xt, yt)
    evals = {}
    bst = lgb.train({"objective": "regression", "boosting": "dart",
                     "metric": "l2", "verbose": -1, "drop_rate": 0.1},
                    train, num_boost_round=40, valid_sets=[valid],
                    evals_result=evals, verbose_eval=False)
    assert evals["valid_0"]["l2"][-1] < 1.0
    assert np.isfinite(bst.predict(Xt)).all()


def test_goss(data):
    X, y, Xt, yt = data
    train = lgb.Dataset(X, y)
    valid = train.create_valid(Xt, yt)
    evals = {}
    bst = lgb.train({"objective": "regression", "boosting": "goss",
                     "metric": "l2", "verbose": -1, "learning_rate": 0.1},
                    train, num_boost_round=40, valid_sets=[valid],
                    evals_result=evals, verbose_eval=False)
    assert evals["valid_0"]["l2"][-1] < 1.0
    # GOSS warm-up ends at iteration 10 (1/lr); training still converges after
    assert evals["valid_0"]["l2"][-1] < evals["valid_0"]["l2"][5]


def test_rf(data):
    X, y, Xt, yt = data
    train = lgb.Dataset(X, y)
    valid = train.create_valid(Xt, yt)
    evals = {}
    bst = lgb.train({"objective": "regression", "boosting": "rf",
                     "metric": "l2", "verbose": -1,
                     "bagging_freq": 1, "bagging_fraction": 0.7,
                     "feature_fraction": 0.8},
                    train, num_boost_round=30, valid_sets=[valid],
                    evals_result=evals, verbose_eval=False)
    # averaged-forest validation error beats predicting the mean
    base = np.mean((yt - y.mean()) ** 2)
    assert evals["valid_0"]["l2"][-1] < base
    pred = bst.predict(Xt)
    # predictions are averaged, not summed
    assert pred.min() > y.min() - 1 and pred.max() < y.max() + 1


def test_rf_requires_bagging(data):
    X, y, _, _ = data
    with pytest.raises(Exception):
        lgb.train({"objective": "regression", "boosting": "rf", "verbose": -1},
                  lgb.Dataset(X, y), num_boost_round=2)


def test_bagging(data):
    X, y, Xt, yt = data
    train = lgb.Dataset(X, y)
    valid = train.create_valid(Xt, yt)
    evals = {}
    lgb.train({"objective": "regression", "metric": "l2", "verbose": -1,
               "bagging_freq": 2, "bagging_fraction": 0.5},
              train, num_boost_round=30, valid_sets=[valid],
              evals_result=evals, verbose_eval=False)
    assert evals["valid_0"]["l2"][-1] < 1.0


def test_feature_fraction(data):
    X, y, Xt, yt = data
    train = lgb.Dataset(X, y)
    bst = lgb.train({"objective": "regression", "verbose": -1,
                     "feature_fraction": 0.5}, train, num_boost_round=10)
    assert np.isfinite(bst.predict(Xt)).all()


def test_shap_sums_to_prediction(data):
    X, y, Xt, _ = data
    train = lgb.Dataset(X, y)
    bst = lgb.train({"objective": "regression", "verbose": -1},
                    train, num_boost_round=5)
    sub = Xt[:20]
    contrib = bst.predict(sub, pred_contrib=True)
    raw = bst.predict(sub, raw_score=True)
    np.testing.assert_allclose(contrib.sum(axis=1), raw, rtol=1e-6)


def test_goss_device_sampling_semantics(rng):
    """_goss_sample: top rows kept unamplified, exactly other_k of the
    rest amplified by (n-top_k)/other_k, mask covers only selected rows."""
    import jax
    import jax.numpy as jnp
    from lightgbm_tpu.models.goss import _goss_sample
    n, top_k, other_k = 1000, 200, 100
    g = jnp.asarray(rng.randn(2, n), jnp.float32)
    h = jnp.asarray(np.abs(rng.randn(2, n)) + 0.1, jnp.float32)
    mult = (n - top_k) / other_k
    g2, h2, mask = _goss_sample(g, h, jax.random.PRNGKey(0),
                                jnp.float32(mult), top_k=top_k,
                                other_k=other_k)
    score = np.abs(np.asarray(g) * np.asarray(h)).sum(axis=0)
    thr = np.partition(score, n - top_k)[n - top_k]
    is_top = score >= thr
    mask = np.asarray(mask)
    amp = np.asarray(g2)[0] / np.asarray(g)[0]
    # top rows: kept, not amplified
    assert (mask[is_top] == 0).all()
    np.testing.assert_allclose(amp[is_top], 1.0, rtol=1e-6)
    # sampled others: amplified by mult and in the bag
    sampled = (~is_top) & (mask == 0)
    assert sampled.sum() == other_k
    np.testing.assert_allclose(amp[sampled], mult, rtol=1e-5)
    # dropped rows: out of bag
    assert (mask[(~is_top) & ~sampled] == -1).all()


def test_l1_renew_device_matches_host(rng):
    """renew_leaf_percentiles vs the per-leaf numpy oracle, weighted and
    unweighted, several alphas."""
    import jax.numpy as jnp
    from lightgbm_tpu.objective import percentile, weighted_percentile
    from lightgbm_tpu.ops.quantile import renew_leaf_percentiles
    n, L = 3000, 12
    residual = rng.randn(n)
    lids = rng.randint(-1, L, n)     # -1 = out of bag
    weights = rng.rand(n) + 0.05
    for alpha in (0.5, 0.1, 0.9):
        dev = np.asarray(renew_leaf_percentiles(
            jnp.asarray(residual), jnp.asarray(lids, jnp.int32),
            jnp.asarray(alpha), L=L))
        devw = np.asarray(renew_leaf_percentiles(
            jnp.asarray(residual), jnp.asarray(lids, jnp.int32),
            jnp.asarray(alpha), L=L, weights=jnp.asarray(weights)))
        for leaf in range(L):
            rows = np.flatnonzero(lids == leaf)
            if len(rows) == 0:
                continue
            np.testing.assert_allclose(
                dev[leaf], percentile(residual[rows], alpha),
                rtol=1e-5, atol=1e-7)
            np.testing.assert_allclose(
                devw[leaf], weighted_percentile(residual[rows],
                                                weights[rows], alpha),
                rtol=1e-5, atol=1e-7)
