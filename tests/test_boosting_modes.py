"""DART / GOSS / RF boosting modes (reference test_engine.py:51,735,752)."""
import numpy as np
import pytest

import lightgbm_tpu as lgb

REGRESSION_TRAIN = "/root/reference/examples/regression/regression.train"
REGRESSION_TEST = "/root/reference/examples/regression/regression.test"


def _load(path):
    mat = np.loadtxt(path)
    return mat[:, 1:], mat[:, 0]


@pytest.fixture(scope="module")
def data():
    X, y = _load(REGRESSION_TRAIN)
    Xt, yt = _load(REGRESSION_TEST)
    return X, y, Xt, yt


def test_dart(data):
    X, y, Xt, yt = data
    train = lgb.Dataset(X, y)
    valid = train.create_valid(Xt, yt)
    evals = {}
    bst = lgb.train({"objective": "regression", "boosting": "dart",
                     "metric": "l2", "verbose": -1, "drop_rate": 0.1},
                    train, num_boost_round=40, valid_sets=[valid],
                    evals_result=evals, verbose_eval=False)
    assert evals["valid_0"]["l2"][-1] < 1.0
    assert np.isfinite(bst.predict(Xt)).all()


def test_goss(data):
    X, y, Xt, yt = data
    train = lgb.Dataset(X, y)
    valid = train.create_valid(Xt, yt)
    evals = {}
    bst = lgb.train({"objective": "regression", "boosting": "goss",
                     "metric": "l2", "verbose": -1, "learning_rate": 0.1},
                    train, num_boost_round=40, valid_sets=[valid],
                    evals_result=evals, verbose_eval=False)
    assert evals["valid_0"]["l2"][-1] < 1.0
    # GOSS warm-up ends at iteration 10 (1/lr); training still converges after
    assert evals["valid_0"]["l2"][-1] < evals["valid_0"]["l2"][5]


def test_rf(data):
    X, y, Xt, yt = data
    train = lgb.Dataset(X, y)
    valid = train.create_valid(Xt, yt)
    evals = {}
    bst = lgb.train({"objective": "regression", "boosting": "rf",
                     "metric": "l2", "verbose": -1,
                     "bagging_freq": 1, "bagging_fraction": 0.7,
                     "feature_fraction": 0.8},
                    train, num_boost_round=30, valid_sets=[valid],
                    evals_result=evals, verbose_eval=False)
    # averaged-forest validation error beats predicting the mean
    base = np.mean((yt - y.mean()) ** 2)
    assert evals["valid_0"]["l2"][-1] < base
    pred = bst.predict(Xt)
    # predictions are averaged, not summed
    assert pred.min() > y.min() - 1 and pred.max() < y.max() + 1


def test_rf_requires_bagging(data):
    X, y, _, _ = data
    with pytest.raises(Exception):
        lgb.train({"objective": "regression", "boosting": "rf", "verbose": -1},
                  lgb.Dataset(X, y), num_boost_round=2)


def test_bagging(data):
    X, y, Xt, yt = data
    train = lgb.Dataset(X, y)
    valid = train.create_valid(Xt, yt)
    evals = {}
    lgb.train({"objective": "regression", "metric": "l2", "verbose": -1,
               "bagging_freq": 2, "bagging_fraction": 0.5},
              train, num_boost_round=30, valid_sets=[valid],
              evals_result=evals, verbose_eval=False)
    assert evals["valid_0"]["l2"][-1] < 1.0


def test_feature_fraction(data):
    X, y, Xt, yt = data
    train = lgb.Dataset(X, y)
    bst = lgb.train({"objective": "regression", "verbose": -1,
                     "feature_fraction": 0.5}, train, num_boost_round=10)
    assert np.isfinite(bst.predict(Xt)).all()


def test_shap_sums_to_prediction(data):
    X, y, Xt, _ = data
    train = lgb.Dataset(X, y)
    bst = lgb.train({"objective": "regression", "verbose": -1},
                    train, num_boost_round=5)
    sub = Xt[:20]
    contrib = bst.predict(sub, pred_contrib=True)
    raw = bst.predict(sub, raw_score=True)
    np.testing.assert_allclose(contrib.sum(axis=1), raw, rtol=1e-6)
