"""C API shim tests — the reference's tests/c_api_test/test_.py flow
driven against lightgbm_tpu.c_api as the LIB."""
import ctypes
import os

import numpy as np
import pytest

import lightgbm_tpu.c_api as LIB

BINARY_TRAIN = "/root/reference/examples/binary_classification/binary.train"
BINARY_TEST = "/root/reference/examples/binary_classification/binary.test"


def c_array(ctype, values):
    return (ctype * len(values))(*values)


def c_str(string):
    return ctypes.c_char_p(string.encode("ascii"))


def _load_from_file(filename, reference):
    handle = ctypes.c_void_p()
    rc = LIB.LGBM_DatasetCreateFromFile(
        c_str(filename), c_str("max_bin=15"), reference,
        ctypes.byref(handle))
    assert rc == 0, LIB.LGBM_GetLastError()
    return handle


def _read_mat(filename):
    data, label = [], []
    with open(filename) as inp:
        for line in inp.readlines():
            data.append([float(x) for x in line.split("\t")[1:]])
            label.append(float(line.split("\t")[0]))
    return np.array(data), np.array(label, dtype=np.float32)


def _load_from_mat(filename, reference):
    mat, label = _read_mat(filename)
    flat = np.array(mat.reshape(mat.size), copy=False)
    handle = ctypes.c_void_p()
    rc = LIB.LGBM_DatasetCreateFromMat(
        flat.ctypes.data_as(ctypes.POINTER(ctypes.c_void_p)),
        LIB.C_API_DTYPE_FLOAT64, mat.shape[0], mat.shape[1], 1,
        c_str("max_bin=15"), reference, ctypes.byref(handle))
    assert rc == 0, LIB.LGBM_GetLastError()
    rc = LIB.LGBM_DatasetSetField(handle, c_str("label"),
                                  c_array(ctypes.c_float, label),
                                  len(label), 0)
    assert rc == 0, LIB.LGBM_GetLastError()
    return handle


def test_dataset_roundtrip(tmp_path):
    from scipy import sparse
    train = _load_from_file(BINARY_TRAIN, None)
    num_data = ctypes.c_long()
    assert LIB.LGBM_DatasetGetNumData(train, ctypes.byref(num_data)) == 0
    assert num_data.value == 7000
    num_feature = ctypes.c_long()
    assert LIB.LGBM_DatasetGetNumFeature(train,
                                         ctypes.byref(num_feature)) == 0
    assert num_feature.value == 28

    # mat / CSR / CSC against the train reference
    test = _load_from_mat(BINARY_TEST, train)
    LIB.LGBM_DatasetFree(test)
    mat, label = _read_mat(BINARY_TEST)
    for maker, args in (("CSR", sparse.csr_matrix(mat)),
                        ("CSC", sparse.csc_matrix(mat))):
        m = args
        handle = ctypes.c_void_p()
        if maker == "CSR":
            rc = LIB.LGBM_DatasetCreateFromCSR(
                c_array(ctypes.c_int, m.indptr), LIB.C_API_DTYPE_INT32,
                c_array(ctypes.c_int, m.indices),
                m.data.ctypes.data_as(ctypes.POINTER(ctypes.c_void_p)),
                LIB.C_API_DTYPE_FLOAT64, len(m.indptr), len(m.data),
                m.shape[1], c_str("max_bin=15"), train,
                ctypes.byref(handle))
        else:
            rc = LIB.LGBM_DatasetCreateFromCSC(
                c_array(ctypes.c_int, m.indptr), LIB.C_API_DTYPE_INT32,
                c_array(ctypes.c_int, m.indices),
                m.data.ctypes.data_as(ctypes.POINTER(ctypes.c_void_p)),
                LIB.C_API_DTYPE_FLOAT64, len(m.indptr), len(m.data),
                m.shape[0], c_str("max_bin=15"), train,
                ctypes.byref(handle))
        assert rc == 0, (maker, LIB.LGBM_GetLastError())
        rc = LIB.LGBM_DatasetSetField(handle, c_str("label"),
                                      c_array(ctypes.c_float, label),
                                      len(label), 0)
        assert rc == 0
        nd = ctypes.c_long()
        LIB.LGBM_DatasetGetNumData(handle, ctypes.byref(nd))
        assert nd.value == 500
        LIB.LGBM_DatasetFree(handle)

    # save-binary round trip (auto-detected on load, dataset_loader.cpp:267)
    binpath = str(tmp_path / "train.binary.bin")
    assert LIB.LGBM_DatasetSaveBinary(train, c_str(binpath)) == 0
    LIB.LGBM_DatasetFree(train)
    train2 = _load_from_file(binpath, None)
    nd = ctypes.c_long()
    LIB.LGBM_DatasetGetNumData(train2, ctypes.byref(nd))
    assert nd.value == 7000
    LIB.LGBM_DatasetFree(train2)


def test_booster_train_eval_save_predict(tmp_path):
    train = _load_from_mat(BINARY_TRAIN, None)
    test = _load_from_mat(BINARY_TEST, train)
    booster = ctypes.c_void_p()
    rc = LIB.LGBM_BoosterCreate(
        train, c_str("app=binary metric=auc num_leaves=31 verbose=-1"),
        ctypes.byref(booster))
    assert rc == 0, LIB.LGBM_GetLastError()
    assert LIB.LGBM_BoosterAddValidData(booster, test) == 0
    is_finished = ctypes.c_int(0)
    aucs = []
    for i in range(1, 31):
        assert LIB.LGBM_BoosterUpdateOneIter(
            booster, ctypes.byref(is_finished)) == 0
        result = np.array([0.0], dtype=np.float64)
        out_len = ctypes.c_ulong(0)
        rc = LIB.LGBM_BoosterGetEval(
            booster, 1, ctypes.byref(out_len),
            result.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
        assert rc == 0 and out_len.value == 1
        aucs.append(result[0])
    # valid-set AUC with max_bin=15 (reference oracle: ~0.83 test AUC)
    assert aucs[-1] > 0.78 and aucs[-1] > aucs[0]

    model_path = str(tmp_path / "model.txt")
    assert LIB.LGBM_BoosterSaveModel(booster, 0, -1, c_str(model_path)) == 0
    LIB.LGBM_BoosterFree(booster)
    LIB.LGBM_DatasetFree(train)
    LIB.LGBM_DatasetFree(test)

    booster2 = ctypes.c_void_p()
    num_total_model = ctypes.c_long()
    rc = LIB.LGBM_BoosterCreateFromModelfile(
        c_str(model_path), ctypes.byref(num_total_model),
        ctypes.byref(booster2))
    assert rc == 0 and num_total_model.value == 30

    mat, label = _read_mat(BINARY_TEST)
    flat = np.array(mat.reshape(mat.size), copy=False)
    preb = np.zeros(mat.shape[0], dtype=np.float64)
    num_preb = ctypes.c_long()
    rc = LIB.LGBM_BoosterPredictForMat(
        booster2, flat.ctypes.data_as(ctypes.POINTER(ctypes.c_void_p)),
        LIB.C_API_DTYPE_FLOAT64, mat.shape[0], mat.shape[1], 1,
        LIB.C_API_PREDICT_RAW_SCORE, 25, c_str(""),
        ctypes.byref(num_preb),
        preb.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
    assert rc == 0 and num_preb.value == mat.shape[0]
    assert np.abs(preb).max() > 0

    out_file = str(tmp_path / "preb.txt")
    rc = LIB.LGBM_BoosterPredictForFile(
        booster2, c_str(BINARY_TEST), 0, 0, 25, c_str(""), c_str(out_file))
    assert rc == 0
    vals = np.loadtxt(out_file)
    assert vals.shape == (500,)
    assert ((vals >= 0) & (vals <= 1)).all()     # normal = probabilities
    from sklearn.metrics import roc_auc_score
    assert roc_auc_score(label, vals) > 0.78
    LIB.LGBM_BoosterFree(booster2)


def _mat_dataset(rng, n=400, f=6, label=True, params="max_bin=31"):
    X = rng.rand(n, f)
    h = ctypes.c_void_p()
    flat = np.ascontiguousarray(X.reshape(-1))
    assert LIB.LGBM_DatasetCreateFromMat(
        flat.ctypes.data_as(ctypes.POINTER(ctypes.c_void_p)), 1, n, f, 1,
        c_str(params), None, ctypes.byref(h)) == 0
    if label:
        y = (X[:, 0] > 0.5).astype(np.float32)
        assert LIB.LGBM_DatasetSetField(
            h, c_str("label"), c_array(ctypes.c_float, y), n, 0) == 0
    return h, X


def test_streaming_push_rows(rng):
    n, f = 300, 5
    h = ctypes.c_void_p()
    assert LIB.LGBM_DatasetCreateFromSampledColumn(
        None, None, f, None, 50, n, c_str("max_bin=15"),
        ctypes.byref(h)) == 0
    X = rng.rand(n, f)
    half = n // 2
    for start, block in ((0, X[:half]), (half, X[half:])):
        flat = np.ascontiguousarray(block.reshape(-1))
        assert LIB.LGBM_DatasetPushRows(
            h, flat.ctypes.data_as(ctypes.POINTER(ctypes.c_void_p)), 1,
            len(block), f, start) == 0
    y = (X[:, 0] > 0.5).astype(np.float32)
    assert LIB.LGBM_DatasetSetField(
        h, c_str("label"), c_array(ctypes.c_float, y), n, 0) == 0
    nd = ctypes.c_long()
    assert LIB.LGBM_DatasetGetNumData(h, ctypes.byref(nd)) == 0
    assert nd.value == n
    # pushed rows must train
    bst = ctypes.c_void_p()
    assert LIB.LGBM_BoosterCreate(
        h, c_str("objective=binary verbose=-1 min_data_in_leaf=5"),
        ctypes.byref(bst)) == 0
    fin = ctypes.c_int()
    assert LIB.LGBM_BoosterUpdateOneIter(bst, ctypes.byref(fin)) == 0


def test_push_rows_by_csr(rng):
    """Streaming ingest: mappers fitted from the sampled columns, pushed
    rows binned incrementally — the binned result must match a dataset
    constructed from the same rows with mappers from the same sample."""
    from scipy import sparse
    n, f, s = 200, 6, 50
    X = (rng.rand(n, f) * (rng.rand(n, f) > 0.5)).astype(np.float64)
    csr = sparse.csr_matrix(X)
    h = ctypes.c_void_p()
    # dense per-column sample of the first s rows (the reference's
    # sampled-column format: values + row indices per column)
    col_vals = [np.ascontiguousarray(X[:s, j]) for j in range(f)]
    col_idx = [np.arange(s, dtype=np.int32) for _ in range(f)]
    vp = (ctypes.c_void_p * f)(*[v.ctypes.data_as(ctypes.c_void_p).value
                                 for v in col_vals])
    ip = (ctypes.c_void_p * f)(*[v.ctypes.data_as(ctypes.c_void_p).value
                                 for v in col_idx])
    npc = (ctypes.c_int32 * f)(*([s] * f))
    assert LIB.LGBM_DatasetCreateFromSampledColumn(
        vp, ip, f, npc, s, n, c_str("max_bin=15"),
        ctypes.byref(h)) == 0
    assert LIB.LGBM_DatasetPushRowsByCSR(
        h, c_array(ctypes.c_int, csr.indptr), 2,
        c_array(ctypes.c_int, csr.indices),
        csr.data.ctypes.data_as(ctypes.POINTER(ctypes.c_void_p)), 1,
        len(csr.indptr), len(csr.data), f, 0) == 0
    ds = LIB._resolve(h)
    # no O(n*f) float staging: the raw matrix must NOT exist
    assert ds.data is None
    ds.construct()
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.io.dataset import BinnedDataset
    oracle_m = BinnedDataset.construct(X[:s], Config({"max_bin": 15}),
                                       bin_rows=False)
    np.testing.assert_array_equal(np.asarray(ds._binned.bins),
                                  oracle_m.bin_block(X))


def test_subset_and_feature_names(rng):
    h, X = _mat_dataset(rng)
    names = (ctypes.c_char_p * 6)(*[("f%d" % i).encode() for i in range(6)])
    assert LIB.LGBM_DatasetSetFeatureNames(h, names, 6) == 0
    idx = np.arange(0, 100, dtype=np.int32)
    sub = ctypes.c_void_p()
    assert LIB.LGBM_DatasetGetSubset(
        h, c_array(ctypes.c_int32, idx), len(idx), c_str(""),
        ctypes.byref(sub)) == 0
    nd = ctypes.c_long()
    assert LIB.LGBM_DatasetGetNumData(sub, ctypes.byref(nd)) == 0
    assert nd.value == 100
    bufs = [ctypes.create_string_buffer(64) for _ in range(6)]
    arr = (ctypes.c_char_p * 6)(*[ctypes.cast(b, ctypes.c_char_p)
                                  for b in bufs])
    out_len = ctypes.c_int()
    assert LIB.LGBM_DatasetGetFeatureNames(
        h, arr, ctypes.byref(out_len)) == 0
    assert out_len.value == 6 and bufs[0].value == b"f0"


def test_booster_breadth(rng):
    h, X = _mat_dataset(rng)
    bst = ctypes.c_void_p()
    assert LIB.LGBM_BoosterCreate(
        h, c_str("objective=binary verbose=-1 min_data_in_leaf=5"),
        ctypes.byref(bst)) == 0
    fin = ctypes.c_int()
    for _ in range(3):
        assert LIB.LGBM_BoosterUpdateOneIter(bst, ctypes.byref(fin)) == 0
    # custom-gradient update
    n = len(X)
    pred = np.zeros(n, np.float64)
    grad = np.asarray(pred - (X[:, 0] > 0.5), np.float32)
    hess = np.full(n, 0.25, np.float32)
    assert LIB.LGBM_BoosterUpdateOneIterCustom(
        bst, c_array(ctypes.c_float, grad), c_array(ctypes.c_float, hess),
        ctypes.byref(fin)) == 0
    # counters
    out = ctypes.c_long()
    assert LIB.LGBM_BoosterNumberOfTotalModel(bst, ctypes.byref(out)) == 0
    assert out.value == 4
    assert LIB.LGBM_BoosterNumModelPerIteration(bst, ctypes.byref(out)) == 0
    assert out.value == 1
    assert LIB.LGBM_BoosterGetNumFeature(bst, ctypes.byref(out)) == 0
    assert out.value == 6
    # leaf get/set round trip
    lv = ctypes.c_double()
    assert LIB.LGBM_BoosterGetLeafValue(bst, 0, 0, ctypes.byref(lv)) == 0
    assert LIB.LGBM_BoosterSetLeafValue(bst, 0, 0, lv.value * 2.0) == 0
    lv2 = ctypes.c_double()
    assert LIB.LGBM_BoosterGetLeafValue(bst, 0, 0, ctypes.byref(lv2)) == 0
    assert abs(lv2.value - lv.value * 2.0) < 1e-12
    # importance
    imp = np.zeros(6, np.float64)
    assert LIB.LGBM_BoosterFeatureImportance(
        bst, -1, 0, imp.ctypes.data_as(ctypes.POINTER(ctypes.c_double))) == 0
    assert imp.sum() > 0
    # dump model JSON
    out_len = ctypes.c_long()
    buf = ctypes.create_string_buffer(1 << 20)
    assert LIB.LGBM_BoosterDumpModel(
        bst, 0, -1, len(buf.raw), ctypes.byref(out_len), buf) == 0
    import json
    d = json.loads(buf.value.decode())
    assert d["tree_info"]
    # calc num predict
    assert LIB.LGBM_BoosterCalcNumPredict(
        bst, 10, 0, -1, ctypes.byref(out_len)) == 0
    assert out_len.value == 10
    # predict for mats (array of row pointers)
    rows = [np.ascontiguousarray(X[i]) for i in range(4)]
    ptrs = (ctypes.POINTER(ctypes.c_double) * 4)(
        *[r.ctypes.data_as(ctypes.POINTER(ctypes.c_double)) for r in rows])
    res = np.zeros(4, np.float64)
    assert LIB.LGBM_BoosterPredictForMats(
        bst, ptrs, 1, 4, 6, 0, -1, c_str(""), ctypes.byref(out_len),
        res.ctypes.data_as(ctypes.POINTER(ctypes.c_double))) == 0
    assert out_len.value == 4
    # reset parameter
    assert LIB.LGBM_BoosterResetParameter(
        bst, c_str("learning_rate=0.05")) == 0
    assert abs(LIB._resolve(bst)._gbdt.shrinkage_rate - 0.05) < 1e-12
    # refit with leaf preds
    lp = np.zeros((n, 4), np.int32)
    assert LIB.LGBM_BoosterRefit(
        bst, lp.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), n, 4) == 0
    # merge
    bst2 = ctypes.c_void_p()
    assert LIB.LGBM_BoosterCreate(
        h, c_str("objective=binary verbose=-1 min_data_in_leaf=5"),
        ctypes.byref(bst2)) == 0
    assert LIB.LGBM_BoosterUpdateOneIter(bst2, ctypes.byref(fin)) == 0
    assert LIB.LGBM_BoosterMerge(bst, bst2) == 0
    assert LIB.LGBM_BoosterNumberOfTotalModel(bst, ctypes.byref(out)) == 0
    assert out.value == 5


def test_dataset_dump_text(rng, tmp_path):
    h, _ = _mat_dataset(rng, n=50)
    p = tmp_path / "dump.txt"
    assert LIB.LGBM_DatasetDumpText(h, c_str(str(p))) == 0
    text = p.read_text()
    assert text.startswith("num_data: 50")


def test_set_last_error():
    assert LIB.LGBM_SetLastError(b"custom boom") == 0
    assert LIB.LGBM_GetLastError() == b"custom boom"


def test_eval_counts_names_values_align_for_multivalue_metrics(rng):
    """GetEvalCounts == len(GetEvalNames) == len(GetEval results) even
    for metrics that expand to one value per position (ndcg@k / map@k)
    — the reference sums Metric::GetName() sizes (metric.hpp), and a
    mismatch overflows fixed-size caller buffers (the R glue sizes its
    output from GetEvalCounts)."""
    n, q = 600, 6
    X = rng.rand(n, 5)
    h = ctypes.c_void_p()
    flat = np.ascontiguousarray(X.reshape(-1))
    assert LIB.LGBM_DatasetCreateFromMat(
        flat.ctypes.data_as(ctypes.POINTER(ctypes.c_void_p)), 1, n, 5, 1,
        c_str("max_bin=31"), None, ctypes.byref(h)) == 0
    y = rng.randint(0, 3, n).astype(np.float32)
    assert LIB.LGBM_DatasetSetField(
        h, c_str("label"), c_array(ctypes.c_float, y), n, 0) == 0
    grp = np.full(q, n // q, np.int32)
    assert LIB.LGBM_DatasetSetField(
        h, c_str("group"),
        grp.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), q, 2) == 0
    bst = ctypes.c_void_p()
    assert LIB.LGBM_BoosterCreate(
        h, c_str("objective=lambdarank metric=ndcg,map verbose=-1"),
        ctypes.byref(bst)) == 0
    fin = ctypes.c_int(0)
    assert LIB.LGBM_BoosterUpdateOneIter(bst, ctypes.byref(fin)) == 0

    cnt = ctypes.c_int(0)
    assert LIB.LGBM_BoosterGetEvalCounts(bst, ctypes.byref(cnt)) == 0
    assert cnt.value == 10  # ndcg@1..5 + map@1..5
    bufs = [ctypes.create_string_buffer(256) for _ in range(cnt.value)]
    arr = (ctypes.c_char_p * cnt.value)(
        *[ctypes.addressof(b) for b in bufs])
    nn = ctypes.c_int(0)
    assert LIB.LGBM_BoosterGetEvalNames(bst, ctypes.byref(nn), arr) == 0
    names = [bufs[i].value.decode() for i in range(nn.value)]
    assert names[:5] == ["ndcg@%d" % k for k in range(1, 6)]
    vals = (ctypes.c_double * cnt.value)()
    vn = ctypes.c_int(0)
    assert LIB.LGBM_BoosterGetEval(bst, 0, ctypes.byref(vn), vals) == 0
    assert vn.value == nn.value == cnt.value
