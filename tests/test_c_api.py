"""C API shim tests — the reference's tests/c_api_test/test_.py flow
driven against lightgbm_tpu.c_api as the LIB."""
import ctypes
import os

import numpy as np
import pytest

import lightgbm_tpu.c_api as LIB

BINARY_TRAIN = "/root/reference/examples/binary_classification/binary.train"
BINARY_TEST = "/root/reference/examples/binary_classification/binary.test"


def c_array(ctype, values):
    return (ctype * len(values))(*values)


def c_str(string):
    return ctypes.c_char_p(string.encode("ascii"))


def _load_from_file(filename, reference):
    handle = ctypes.c_void_p()
    rc = LIB.LGBM_DatasetCreateFromFile(
        c_str(filename), c_str("max_bin=15"), reference,
        ctypes.byref(handle))
    assert rc == 0, LIB.LGBM_GetLastError()
    return handle


def _read_mat(filename):
    data, label = [], []
    with open(filename) as inp:
        for line in inp.readlines():
            data.append([float(x) for x in line.split("\t")[1:]])
            label.append(float(line.split("\t")[0]))
    return np.array(data), np.array(label, dtype=np.float32)


def _load_from_mat(filename, reference):
    mat, label = _read_mat(filename)
    flat = np.array(mat.reshape(mat.size), copy=False)
    handle = ctypes.c_void_p()
    rc = LIB.LGBM_DatasetCreateFromMat(
        flat.ctypes.data_as(ctypes.POINTER(ctypes.c_void_p)),
        LIB.C_API_DTYPE_FLOAT64, mat.shape[0], mat.shape[1], 1,
        c_str("max_bin=15"), reference, ctypes.byref(handle))
    assert rc == 0, LIB.LGBM_GetLastError()
    rc = LIB.LGBM_DatasetSetField(handle, c_str("label"),
                                  c_array(ctypes.c_float, label),
                                  len(label), 0)
    assert rc == 0, LIB.LGBM_GetLastError()
    return handle


def test_dataset_roundtrip(tmp_path):
    from scipy import sparse
    train = _load_from_file(BINARY_TRAIN, None)
    num_data = ctypes.c_long()
    assert LIB.LGBM_DatasetGetNumData(train, ctypes.byref(num_data)) == 0
    assert num_data.value == 7000
    num_feature = ctypes.c_long()
    assert LIB.LGBM_DatasetGetNumFeature(train,
                                         ctypes.byref(num_feature)) == 0
    assert num_feature.value == 28

    # mat / CSR / CSC against the train reference
    test = _load_from_mat(BINARY_TEST, train)
    LIB.LGBM_DatasetFree(test)
    mat, label = _read_mat(BINARY_TEST)
    for maker, args in (("CSR", sparse.csr_matrix(mat)),
                        ("CSC", sparse.csc_matrix(mat))):
        m = args
        handle = ctypes.c_void_p()
        if maker == "CSR":
            rc = LIB.LGBM_DatasetCreateFromCSR(
                c_array(ctypes.c_int, m.indptr), LIB.C_API_DTYPE_INT32,
                c_array(ctypes.c_int, m.indices),
                m.data.ctypes.data_as(ctypes.POINTER(ctypes.c_void_p)),
                LIB.C_API_DTYPE_FLOAT64, len(m.indptr), len(m.data),
                m.shape[1], c_str("max_bin=15"), train,
                ctypes.byref(handle))
        else:
            rc = LIB.LGBM_DatasetCreateFromCSC(
                c_array(ctypes.c_int, m.indptr), LIB.C_API_DTYPE_INT32,
                c_array(ctypes.c_int, m.indices),
                m.data.ctypes.data_as(ctypes.POINTER(ctypes.c_void_p)),
                LIB.C_API_DTYPE_FLOAT64, len(m.indptr), len(m.data),
                m.shape[0], c_str("max_bin=15"), train,
                ctypes.byref(handle))
        assert rc == 0, (maker, LIB.LGBM_GetLastError())
        rc = LIB.LGBM_DatasetSetField(handle, c_str("label"),
                                      c_array(ctypes.c_float, label),
                                      len(label), 0)
        assert rc == 0
        nd = ctypes.c_long()
        LIB.LGBM_DatasetGetNumData(handle, ctypes.byref(nd))
        assert nd.value == 500
        LIB.LGBM_DatasetFree(handle)

    # save-binary round trip (auto-detected on load, dataset_loader.cpp:267)
    binpath = str(tmp_path / "train.binary.bin")
    assert LIB.LGBM_DatasetSaveBinary(train, c_str(binpath)) == 0
    LIB.LGBM_DatasetFree(train)
    train2 = _load_from_file(binpath, None)
    nd = ctypes.c_long()
    LIB.LGBM_DatasetGetNumData(train2, ctypes.byref(nd))
    assert nd.value == 7000
    LIB.LGBM_DatasetFree(train2)


def test_booster_train_eval_save_predict(tmp_path):
    train = _load_from_mat(BINARY_TRAIN, None)
    test = _load_from_mat(BINARY_TEST, train)
    booster = ctypes.c_void_p()
    rc = LIB.LGBM_BoosterCreate(
        train, c_str("app=binary metric=auc num_leaves=31 verbose=-1"),
        ctypes.byref(booster))
    assert rc == 0, LIB.LGBM_GetLastError()
    assert LIB.LGBM_BoosterAddValidData(booster, test) == 0
    is_finished = ctypes.c_int(0)
    aucs = []
    for i in range(1, 31):
        assert LIB.LGBM_BoosterUpdateOneIter(
            booster, ctypes.byref(is_finished)) == 0
        result = np.array([0.0], dtype=np.float64)
        out_len = ctypes.c_ulong(0)
        rc = LIB.LGBM_BoosterGetEval(
            booster, 1, ctypes.byref(out_len),
            result.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
        assert rc == 0 and out_len.value == 1
        aucs.append(result[0])
    # valid-set AUC with max_bin=15 (reference oracle: ~0.83 test AUC)
    assert aucs[-1] > 0.78 and aucs[-1] > aucs[0]

    model_path = str(tmp_path / "model.txt")
    assert LIB.LGBM_BoosterSaveModel(booster, 0, -1, c_str(model_path)) == 0
    LIB.LGBM_BoosterFree(booster)
    LIB.LGBM_DatasetFree(train)
    LIB.LGBM_DatasetFree(test)

    booster2 = ctypes.c_void_p()
    num_total_model = ctypes.c_long()
    rc = LIB.LGBM_BoosterCreateFromModelfile(
        c_str(model_path), ctypes.byref(num_total_model),
        ctypes.byref(booster2))
    assert rc == 0 and num_total_model.value == 30

    mat, label = _read_mat(BINARY_TEST)
    flat = np.array(mat.reshape(mat.size), copy=False)
    preb = np.zeros(mat.shape[0], dtype=np.float64)
    num_preb = ctypes.c_long()
    rc = LIB.LGBM_BoosterPredictForMat(
        booster2, flat.ctypes.data_as(ctypes.POINTER(ctypes.c_void_p)),
        LIB.C_API_DTYPE_FLOAT64, mat.shape[0], mat.shape[1], 1,
        LIB.C_API_PREDICT_RAW_SCORE, 25, c_str(""),
        ctypes.byref(num_preb),
        preb.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
    assert rc == 0 and num_preb.value == mat.shape[0]
    assert np.abs(preb).max() > 0

    out_file = str(tmp_path / "preb.txt")
    rc = LIB.LGBM_BoosterPredictForFile(
        booster2, c_str(BINARY_TEST), 0, 0, 25, c_str(""), c_str(out_file))
    assert rc == 0
    vals = np.loadtxt(out_file)
    assert vals.shape == (500,)
    assert ((vals >= 0) & (vals <= 1)).all()     # normal = probabilities
    from sklearn.metrics import roc_auc_score
    assert roc_auc_score(label, vals) > 0.78
    LIB.LGBM_BoosterFree(booster2)
