"""Run the REFERENCE's own ctypes C-API test file against the shim.

tests/c_api_test/test_.py from /root/reference drives the raw LGBM_*
ABI (dataset create from file/mat/CSR/CSC, binary round trip, booster
train/eval/save/reload/predict).  The only modification is the library
load: `LIB = LoadDll()` is swapped for the in-process shim — everything
else runs verbatim, which is the cross-implementation oracle the
reference itself uses (SURVEY §4.2).
"""
import ctypes
import os

import pytest

# interpret-mode Pallas dominates these — excluded from the
# fast tier (pytest -m 'not slow'); run the full suite before
# committing engine changes
pytestmark = pytest.mark.slow

REF_TEST = "/root/reference/tests/c_api_test/test_.py"


class _ShimLib:
    """Stands in for the ctypes CDLL: attribute lookup returns the shim
    function (plain Python callables tolerate .restype assignment)."""

    def __getattr__(self, name):
        from lightgbm_tpu import c_api
        return getattr(c_api, name)


@pytest.fixture()
def ref_module(tmp_path, monkeypatch):
    source = open(REF_TEST).read()
    patched = source.replace("LIB = LoadDll()", "LIB = __SHIM_LIB__")
    assert patched != source, "reference test layout changed"
    monkeypatch.chdir(tmp_path)   # the flow writes model.txt etc to cwd
    ns = {"__SHIM_LIB__": _ShimLib(), "__file__": REF_TEST,
          "__name__": "ref_c_api_test"}
    exec(compile(patched, REF_TEST, "exec"), ns)
    return ns


def test_reference_dataset_flow(ref_module):
    ref_module["test_dataset"]()


def test_reference_booster_flow(ref_module):
    ref_module["test_booster"]()
    # the flow leaves model.txt + preb.txt behind; sanity-check them
    assert os.path.exists("model.txt")
    preds = [float(x) for x in open("preb.txt").read().split()]
    assert len(preds) == 500   # binary.test rows
