"""The REAL shared-object C ABI: build native/liblightgbm_tpu.so (the
embedded-CPython trampoline over lightgbm_tpu/c_api.py) and drive the
train/predict/save/reload flow through a ctypes.CDLL load — the binary
contract R/.Call and SWIG/JNI consume (reference include/LightGBM/c_api.h,
R-package/src/lightgbm_R.cpp)."""
import ctypes
import os
import shutil
import subprocess
import sysconfig

import numpy as np
import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SO = os.path.join(ROOT, "native", "liblightgbm_tpu.so")
SRC = os.path.join(ROOT, "native", "lightgbm_tpu_capi.c")


def _build():
    if os.path.exists(SO) and (os.path.getmtime(SO) >=
                               os.path.getmtime(SRC)):
        return True
    cc = shutil.which("cc") or shutil.which("gcc")
    if cc is None:
        return False
    inc = sysconfig.get_paths()["include"]
    libdir = sysconfig.get_config_var("LIBDIR")
    ver = sysconfig.get_config_var("LDVERSION") or "3.12"
    cmd = [cc, "-O2", "-fPIC", "-Wall", "-shared", "-o", SO, SRC,
           "-I" + inc, "-L" + libdir, "-lpython" + ver]
    return subprocess.run(cmd, capture_output=True).returncode == 0


@pytest.fixture(scope="module")
def lib():
    if not _build():
        pytest.skip("no C toolchain / libpython to build the trampoline")
    lib = ctypes.CDLL(SO)
    lib.LGBM_GetLastError.restype = ctypes.c_char_p
    return lib


def test_abi_symbols_exported(lib):
    # the full reference surface must resolve from the binary
    from lightgbm_tpu import capi_abi
    for name in capi_abi.SIGS:
        assert getattr(lib, name) is not None, name


def test_abi_train_predict_roundtrip(lib, rng):
    n, f = 2000, 5
    X = np.ascontiguousarray(rng.randn(n, f), np.float64)
    y = np.ascontiguousarray((X[:, 0] > 0), np.float32)
    h = ctypes.c_void_p()
    assert lib.LGBM_DatasetCreateFromMat(
        X.ctypes.data_as(ctypes.c_void_p), 1, n, f, 1,
        b"max_bin=63", None, ctypes.byref(h)) == 0, lib.LGBM_GetLastError()
    assert lib.LGBM_DatasetSetField(
        h, b"label", y.ctypes.data_as(ctypes.c_void_p), n, 0) == 0
    nd = ctypes.c_int(0)
    assert lib.LGBM_DatasetGetNumData(h, ctypes.byref(nd)) == 0
    assert nd.value == n

    bh = ctypes.c_void_p()
    assert lib.LGBM_BoosterCreate(
        h, b"objective=binary verbose=-1 num_leaves=15",
        ctypes.byref(bh)) == 0, lib.LGBM_GetLastError()
    fin = ctypes.c_int(0)
    for _ in range(6):
        assert lib.LGBM_BoosterUpdateOneIter(bh, ctypes.byref(fin)) == 0

    out = np.zeros(n, np.float64)
    nout = ctypes.c_int64(0)
    assert lib.LGBM_BoosterPredictForMat(
        bh, X.ctypes.data_as(ctypes.c_void_p), 1, n, f, 1, 0, -1, b"",
        ctypes.byref(nout),
        out.ctypes.data_as(ctypes.c_void_p)) == 0
    assert nout.value == n
    assert out[y > 0.5].mean() - out[y < 0.5].mean() > 0.2

    buf = ctypes.create_string_buffer(1 << 21)
    olen = ctypes.c_int64(0)
    assert lib.LGBM_BoosterSaveModelToString(
        bh, 0, -1, ctypes.c_int64(len(buf)), ctypes.byref(olen), buf) == 0
    assert olen.value > 100
    bh2 = ctypes.c_void_p()
    niters = ctypes.c_int(0)
    assert lib.LGBM_BoosterLoadModelFromString(
        buf.value, ctypes.byref(niters), ctypes.byref(bh2)) == 0
    assert niters.value == 6
    out2 = np.zeros(n, np.float64)
    assert lib.LGBM_BoosterPredictForMat(
        bh2, X.ctypes.data_as(ctypes.c_void_p), 1, n, f, 1, 0, -1, b"",
        ctypes.byref(nout), out2.ctypes.data_as(ctypes.c_void_p)) == 0
    np.testing.assert_allclose(out2, out, rtol=1e-6, atol=1e-7)
    assert lib.LGBM_BoosterFree(bh) == 0
    assert lib.LGBM_BoosterFree(bh2) == 0
    assert lib.LGBM_DatasetFree(h) == 0


def test_abi_error_protocol(lib):
    bad = ctypes.c_void_p(0xDEAD)
    nd = ctypes.c_int(0)
    assert lib.LGBM_DatasetGetNumData(bad, ctypes.byref(nd)) == -1
    assert b"invalid handle" in lib.LGBM_GetLastError()
