"""Carried-arena fast path: scores/labels ride the arena as residue
planes, so the per-tree rowid sort disappears from the training loop
(see gbdt._run_fused_iter_carried / partition_pallas.compact_carry).
These tests pin its engagement conditions and its equivalence to the
label engine."""
import numpy as np
import pytest

import lightgbm_tpu as lgb

pytestmark = pytest.mark.slow


def _data(rng, n=3000, F=8):
    X = rng.randn(n, F).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] * X[:, 2]
         + 0.3 * rng.randn(n) > 0).astype(np.float32)
    return X, y


def test_carried_engages_and_matches_label_engine(rng):
    X, y = _data(rng)
    preds = {}
    for eng in ("partition", "label"):
        params = {"objective": "binary", "num_leaves": 31, "verbose": -1,
                  "min_data_in_leaf": 5, "tpu_tree_engine": eng}
        bst = lgb.train(params, lgb.Dataset(X, y), num_boost_round=12)
        if eng == "partition":
            assert getattr(bst._gbdt, "_carried_active", False) is True
        preds[eng] = bst.predict(X)
    # f32 reassociation noise only (the GPU-parity band)
    np.testing.assert_allclose(preds["partition"], preds["label"],
                               rtol=1e-3, atol=1e-5)


def test_carried_regression_objective(rng):
    X, _ = _data(rng)
    yr = (X[:, 0] * 2 + np.sin(X[:, 1]) + 0.1 * rng.randn(len(X))
          ).astype(np.float32)
    params = {"objective": "regression", "num_leaves": 31, "verbose": -1,
              "tpu_tree_engine": "partition"}
    bst = lgb.train(params, lgb.Dataset(X, yr), num_boost_round=10)
    assert getattr(bst._gbdt, "_carried_active", False) is True
    mse = float(np.mean((bst.predict(X) - yr) ** 2))
    assert mse < 0.5 * float(np.var(yr)), mse


def test_carried_subclassed_objective_opts_out(rng):
    """huber overrides _raw_gradients but not the carry pair — it must
    NOT engage the carried path (it would train with L2 math)."""
    X, _ = _data(rng)
    yr = (X[:, 0] + 0.1 * rng.randn(len(X))).astype(np.float32)
    params = {"objective": "huber", "num_leaves": 15, "verbose": -1,
              "tpu_tree_engine": "partition"}
    bst = lgb.train(params, lgb.Dataset(X, yr), num_boost_round=5)
    assert getattr(bst._gbdt, "_carried_active", True) is False


def test_carried_demotes_on_external_score_write(rng):
    """rollback writes train scores; the next iteration must demote the
    carried path (stale planes) and keep training correctly."""
    X, y = _data(rng)
    params = {"objective": "binary", "num_leaves": 31, "verbose": -1,
              "min_data_in_leaf": 5, "tpu_tree_engine": "partition"}
    ds = lgb.Dataset(X, y)
    bst = lgb.Booster(params=params, train_set=ds)
    for _ in range(6):
        bst.update()
    g = bst._gbdt
    assert getattr(g, "_carried_active", False) is True
    bst.rollback_one_iter()
    bst.update()
    assert g._carried_active is False     # demoted, not broken
    assert bst.num_trees() == 6
    # and the model still predicts sanely after the mode switch
    from sklearn.metrics import roc_auc_score
    assert roc_auc_score(y, bst.predict(X)) > 0.9


def test_carried_lazy_score_materializes(rng):
    """Reading the training score mid-run reconstructs the row order
    exactly (the materializer sort), matching eval-time expectations."""
    X, y = _data(rng)
    params = {"objective": "binary", "num_leaves": 31, "verbose": -1,
              "min_data_in_leaf": 5, "tpu_tree_engine": "partition"}
    ds = lgb.Dataset(X, y)
    bst = lgb.Booster(params=params, train_set=ds)
    for _ in range(5):
        bst.update()
    g = bst._gbdt
    assert g._carried_active
    score = np.asarray(g.train_state.score)[0]
    # raw-score predict over the same 5 trees must agree with the
    # training-state score (deferred pipeline drains on predict)
    raw = bst.predict(X, raw_score=True)
    np.testing.assert_allclose(score, raw, rtol=1e-3, atol=1e-5)


def test_carried_with_forced_splits(rng, tmp_path):
    """Forced splits inject cache rows before the grow loop — the
    carried root must serve them identically to the pristine path."""
    import json
    X, y = _data(rng)
    fs = {"feature": 0, "threshold": 0.0,
          "left": {"feature": 1, "threshold": 0.0}}
    p = tmp_path / "forced.json"
    p.write_text(json.dumps(fs))
    preds = {}
    for eng in ("partition", "label"):
        params = {"objective": "binary", "num_leaves": 15, "verbose": -1,
                  "min_data_in_leaf": 5, "tpu_tree_engine": eng,
                  "forcedsplits_filename": str(p)}
        bst = lgb.train(params, lgb.Dataset(X, y), num_boost_round=6)
        model = bst._gbdt.models[0]
        assert int(model.split_feature[0]) == 0       # root forced
        preds[eng] = bst.predict(X)
    np.testing.assert_allclose(preds["partition"], preds["label"],
                               rtol=1e-3, atol=1e-5)


def test_carried_with_efb_bundles(rng):
    """EFB-bundled group columns ride the carried arena: bins_t holds
    GROUP columns and the carry planes sit after the group block."""
    n = 4000
    num = rng.randn(n, 3).astype(np.float32)
    cats = rng.randint(0, 3, (n, 6))
    onehot = np.zeros((n, 18), np.float32)
    onehot[np.arange(n)[:, None], cats + np.arange(6) * 3] = 1.0
    X = np.column_stack([num, onehot])
    y = (num[:, 0] + (cats[:, 0] == 1) + 0.3 * rng.randn(n) > 0.5
         ).astype(np.float32)
    preds = {}
    for eng in ("partition", "label"):
        params = {"objective": "binary", "num_leaves": 31, "verbose": -1,
                  "min_data_in_leaf": 5, "tpu_tree_engine": eng,
                  "enable_bundle": True}
        bst = lgb.train(params, lgb.Dataset(X, y), num_boost_round=8)
        if eng == "partition":
            assert getattr(bst._gbdt, "_carried_active", False) is True
            assert bst._gbdt.train_state.bundle is not None
        preds[eng] = bst.predict(X)
    np.testing.assert_allclose(preds["partition"], preds["label"],
                               rtol=1e-3, atol=1e-5)
