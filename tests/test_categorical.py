"""Categorical optimal-split tests.

Oracle: a direct numpy transliteration of FindBestThresholdCategorical
(src/treelearner/feature_histogram.hpp:110-271) checked against the
vectorized device scan, plus end-to-end quality/round-trip tests.
"""
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.ops.split import (K_EPSILON, SplitParams,
                                    best_split_categorical_per_feature,
                                    calculate_splitted_leaf_output,
                                    leaf_split_gain,
                                    leaf_split_gain_given_output)

MISSING_NONE = 0


def _gain(lg, lh, rg, rh, l1, l2, mds):
    lo = calculate_splitted_leaf_output(lg, lh, l1, l2, mds)
    ro = calculate_splitted_leaf_output(rg, rh, l1, l2, mds)
    return float(leaf_split_gain_given_output(lg, lh, l1, l2, lo)
                 + leaf_split_gain_given_output(rg, rh, l1, l2, ro))


def oracle_categorical(hist, sum_g, sum_h, n_data, num_bin, missing_type,
                       p: SplitParams, max_cat_threshold=32):
    """hpp:110-271 for one feature; returns (gain_rel, left_bins or None)."""
    sum_h = sum_h + 2 * K_EPSILON
    l2n = p.lambda_l2
    gain_shift = float(leaf_split_gain(sum_g, sum_h, p.lambda_l1, l2n,
                                       p.max_delta_step))
    min_gain_shift = gain_shift + p.min_gain_to_split
    used_bin = num_bin - 1 + (missing_type == MISSING_NONE)
    use_onehot = num_bin <= p.max_cat_to_onehot
    l2 = l2n + p.cat_l2
    best_gain, best_left = -np.inf, None
    if use_onehot:
        for t in range(used_bin):
            g, h, c = hist[t]
            c = int(round(c))
            if c < p.min_data_in_leaf or h < p.min_sum_hessian_in_leaf:
                continue
            oc = n_data - c
            if oc < p.min_data_in_leaf:
                continue
            oh = sum_h - h - K_EPSILON
            if oh < p.min_sum_hessian_in_leaf:
                continue
            og = sum_g - g
            cur = _gain(og, oh, g, h + K_EPSILON, p.lambda_l1, l2,
                        p.max_delta_step)
            if cur <= min_gain_shift:
                continue
            if cur > best_gain:
                best_gain, best_left = cur, [t]
    else:
        sorted_idx = [i for i in range(used_bin)
                      if round(hist[i, 2]) >= p.cat_smooth]
        ub = len(sorted_idx)
        sorted_idx.sort(key=lambda i: hist[i, 0] / (hist[i, 1] + p.cat_smooth))
        max_num_cat = min(max_cat_threshold, (ub + 1) // 2)
        for dir_, start in ((1, 0), (-1, ub - 1)):
            pos = start
            grp = 0
            lg, lh, lc = 0.0, K_EPSILON, 0
            for i in range(min(ub, max_num_cat)):
                t = sorted_idx[pos]
                pos += dir_
                lg += hist[t, 0]
                lh += hist[t, 1]
                lc += int(round(hist[t, 2]))
                grp += int(round(hist[t, 2]))
                if lc < p.min_data_in_leaf or lh < p.min_sum_hessian_in_leaf:
                    continue
                rc = n_data - lc
                if rc < p.min_data_in_leaf or rc < p.min_data_per_group:
                    break
                rh = sum_h - lh
                if rh < p.min_sum_hessian_in_leaf:
                    break
                if grp < p.min_data_per_group:
                    continue
                grp = 0
                cur = _gain(lg, lh, sum_g - lg, rh, p.lambda_l1, l2,
                            p.max_delta_step)
                if cur <= min_gain_shift:
                    continue
                if cur > best_gain:
                    best_gain = cur
                    if dir_ == 1:
                        best_left = sorted_idx[:i + 1]
                    else:
                        best_left = sorted_idx[ub - 1 - i:]
    if best_left is None:
        return -np.inf, None
    return best_gain - min_gain_shift, sorted(best_left)


@pytest.mark.parametrize("mode_params", [
    dict(max_cat_to_onehot=32),                      # one-hot mode
    dict(max_cat_to_onehot=1, cat_smooth=2.0,
         min_data_per_group=5),                      # sorted mode
    dict(max_cat_to_onehot=1, cat_smooth=10.0,
         min_data_per_group=50, cat_l2=3.0),         # sorted, heavier reg
])
@pytest.mark.slow
def test_cat_scan_vs_oracle(rng, mode_params):
    import jax.numpy as jnp
    F, B = 6, 16
    params = SplitParams(min_data_in_leaf=5, **mode_params)
    for trial in range(5):
        counts = rng.randint(0, 60, (F, B)).astype(np.float64)
        g = rng.randn(F, B) * np.sqrt(counts)
        h = np.abs(rng.randn(F, B)) * counts * 0.1 + counts * 0.05
        hist = np.stack([g, h, counts], axis=-1)
        num_bins = rng.randint(4, B + 1, F).astype(np.int32)
        for f in range(F):
            hist[f, num_bins[f]:] = 0.0
        missing = np.zeros(F, np.int32)
        sum_g = hist[..., 0].sum(1)
        sum_h = hist[..., 1].sum(1)
        n_data = hist[..., 2].sum(1).astype(np.int32)

        # scan whole leaf per feature (vectorized call takes one leaf's sums;
        # use per-feature totals by evaluating features one at a time)
        for f in range(F):
            pf = best_split_categorical_per_feature(
                jnp.asarray(hist[f:f + 1]), sum_g[f], sum_h[f], n_data[f],
                jnp.asarray(num_bins[f:f + 1]), jnp.asarray(missing[f:f + 1]),
                params, max_cat_threshold=8)
            og, oleft = oracle_categorical(hist[f], sum_g[f], sum_h[f],
                                           int(n_data[f]), int(num_bins[f]),
                                           0, params, max_cat_threshold=8)
            got = float(pf.gain[0])
            if oleft is None:
                assert got == -np.inf, (trial, f, got)
            else:
                assert got > -np.inf, (trial, f, og)
                np.testing.assert_allclose(got, og, rtol=1e-4, atol=1e-7)
                left = sorted(int(v) for v in
                              np.flatnonzero(np.asarray(pf.cat_mask[0])))
                # near-tied asc/desc scans can pick the same partition of
                # eligible bins with sides swapped (the reference breaks the
                # tie on ~1e-9 float noise); accept either side assignment
                eligible = sorted(
                    i for i in range(int(num_bins[f]))
                    if round(hist[f, i, 2]) >= params.cat_smooth)
                complement = sorted(set(eligible) - set(oleft))
                assert left in (oleft, complement), (trial, f, left, oleft)


def _cat_data(rng, n=2000):
    cat = rng.randint(0, 8, n)
    Xnum = rng.randn(n, 3)
    y = ((cat % 3 == 0).astype(float) * 2.0 + Xnum[:, 0] * 0.3
         + 0.1 * rng.randn(n) > 1.0).astype(float)
    X = np.column_stack([cat.astype(float), Xnum])
    return X, y


@pytest.mark.parametrize("onehot", [1, 32])
def test_cat_end_to_end(rng, onehot):
    X, y = _cat_data(rng)
    params = {"objective": "binary", "num_leaves": 15, "learning_rate": 0.2,
              "verbose": -1, "min_data_in_leaf": 20, "cat_smooth": 1.0,
              "min_data_per_group": 10, "max_cat_to_onehot": onehot}
    b = lgb.train(params, lgb.Dataset(X, y, categorical_feature=[0]),
                  num_boost_round=20)
    p = b.predict(X)
    assert np.mean((p > 0.5) == y) > 0.95
    # round-trip: bitsets survive the v2 text format
    b2 = lgb.Booster(model_str=b.model_to_string())
    np.testing.assert_allclose(b2.predict(X), p, rtol=1e-5, atol=1e-6)
    assert b.num_trees() == 20


def test_cat_unseen_category_goes_right(rng):
    X, y = _cat_data(rng)
    params = {"objective": "binary", "num_leaves": 15, "learning_rate": 0.2,
              "verbose": -1, "min_data_in_leaf": 20, "cat_smooth": 1.0,
              "min_data_per_group": 10, "max_cat_to_onehot": 1}
    b = lgb.train(params, lgb.Dataset(X, y, categorical_feature=[0]),
                  num_boost_round=10)
    Xq = X[:4].copy()
    Xq[:, 0] = 999.0          # unseen category
    Xq2 = X[:4].copy()
    Xq2[:, 0] = np.nan        # missing
    # both must route deterministically (right path) without crashing
    assert np.isfinite(b.predict(Xq)).all()
    assert np.isfinite(b.predict(Xq2)).all()


@pytest.mark.slow
@pytest.mark.parametrize("mode", ["data", "feature", "voting"])
def test_cat_parallel_matches_serial(rng, mode):
    X, y = _cat_data(rng)
    params = {"objective": "binary", "num_leaves": 15, "learning_rate": 0.2,
              "verbose": -1, "min_data_in_leaf": 20, "cat_smooth": 1.0,
              "min_data_per_group": 10, "max_cat_to_onehot": 4,
              "num_machines": 8}
    serial = lgb.train(dict(params, tree_learner="serial"),
                       lgb.Dataset(X, y, categorical_feature=[0]),
                       num_boost_round=5)
    par = lgb.train(dict(params, tree_learner=mode),
                    lgb.Dataset(X, y, categorical_feature=[0]),
                    num_boost_round=5)
    ps, pp = serial.predict(X), par.predict(X)
    # the sorted-ctr category order is tie-sensitive to psum accumulation
    # order, so individual splits may pick equivalent near-tied partitions;
    # assert tight drift + quality parity instead of tree identity
    assert np.mean(np.abs(ps - pp)) < 0.01
    assert np.mean((pp > 0.5) == y) > 0.95
