"""Compile-and-compare oracle for convert_model codegen: train a small
model, emit C++ via the task=convert_model CLI path, compile it with the
system compiler (skip cleanly when none), and assert the compiled
predictions match the interpreter — the tests/cpp_test oracle of the
reference CI (.ci/test.sh:52-58)."""
import shutil
import subprocess
import sys

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.app import Application

_MAIN = r"""
#include <cstdio>
#include <cstdlib>

void PredictRaw(const double* arr, double* output);
void Predict(const double* arr, double* output);
int NumPredictOutputs();

int main() {
  int n, nf;
  if (std::scanf("%d %d", &n, &nf) != 2) return 1;
  int k = NumPredictOutputs();
  std::vector<double> row(nf), out(k);
  for (int i = 0; i < n; ++i) {
    for (int j = 0; j < nf; ++j)
      if (std::scanf("%lf", &row[j]) != 1) return 1;
    PredictRaw(row.data(), out.data());
    for (int c = 0; c < k; ++c) std::printf("%.17g ", out[c]);
    Predict(row.data(), out.data());
    for (int c = 0; c < k; ++c) std::printf("%.17g ", out[c]);
    std::printf("\n");
  }
  return 0;
}
"""


def _compiler():
    for name in ("g++", "c++", "clang++"):
        path = shutil.which(name)
        if path:
            return path
    return None


def _compile_and_run(tmp_path, booster, X):
    """convert_model CLI -> append main() -> compile -> run over X.
    Returns (raw, transformed) arrays of shape [n, k]."""
    cxx = _compiler()
    if cxx is None:
        pytest.skip("no C++ compiler on PATH")
    model_path = tmp_path / "model.txt"
    cpp_path = tmp_path / "model.cpp"
    booster.save_model(str(model_path))
    Application(["task=convert_model", "input_model=%s" % model_path,
                 "convert_model=%s" % cpp_path]).run()
    code = cpp_path.read_text()
    assert "PredictRaw" in code and "NumPredictOutputs" in code
    cpp_path.write_text(code + _MAIN)
    exe = tmp_path / "model_bin"
    subprocess.run([cxx, "-O1", "-o", str(exe), str(cpp_path)], check=True,
                   capture_output=True, timeout=300)
    n, nf = X.shape
    feed = ["%d %d" % (n, nf)]
    for row in X:
        feed.append(" ".join("nan" if np.isnan(v) else "%.17g" % v
                             for v in row))
    proc = subprocess.run([str(exe)], input="\n".join(feed),
                          capture_output=True, text=True, check=True,
                          timeout=120)
    vals = np.array([[float(t) for t in line.split()]
                     for line in proc.stdout.strip().splitlines()])
    k = vals.shape[1] // 2
    return vals[:, :k], vals[:, k:]


def _data(seed, n=300, nf=6):
    rng = np.random.RandomState(seed)
    X = rng.rand(n, nf)
    X[:, 3] = rng.randint(0, 8, n)           # categorical-ish column
    return X, rng


def test_compiled_regression_matches_interpreter(tmp_path):
    X, rng = _data(0)
    y = 3.0 * X[:, 0] + np.sin(4 * X[:, 1]) + 0.1 * rng.randn(len(X))
    bst = lgb.train({"objective": "regression", "num_leaves": 15,
                     "verbose": -1, "min_data_in_leaf": 5},
                    lgb.Dataset(X, label=y,
                                categorical_feature=[3]),
                    num_boost_round=10)
    Xt = _data(1, n=64)[0]
    Xt[::7, 1] = np.nan                       # exercise missing handling
    c_raw, c_pred = _compile_and_run(tmp_path, bst, Xt)
    py_raw = bst.predict(Xt, raw_score=True)
    np.testing.assert_allclose(c_raw[:, 0], py_raw, rtol=0, atol=1e-12)
    np.testing.assert_allclose(c_pred[:, 0], bst.predict(Xt),
                               rtol=1e-10, atol=1e-12)


def test_compiled_binary_matches_interpreter(tmp_path):
    X, rng = _data(2)
    y = (X[:, 0] + 0.3 * rng.randn(len(X)) > 0.5).astype(float)
    bst = lgb.train({"objective": "binary", "num_leaves": 7, "verbose": -1,
                     "min_data_in_leaf": 5},
                    lgb.Dataset(X, label=y), num_boost_round=8)
    Xt = _data(3, n=50)[0]
    c_raw, c_pred = _compile_and_run(tmp_path, bst, Xt)
    np.testing.assert_allclose(c_raw[:, 0], bst.predict(Xt, raw_score=True),
                               rtol=0, atol=1e-12)
    probs = bst.predict(Xt)
    np.testing.assert_allclose(c_pred[:, 0], probs, rtol=1e-10, atol=1e-12)
    assert np.all((c_pred[:, 0] > 0) & (c_pred[:, 0] < 1))


def test_compiled_multiclass_matches_interpreter(tmp_path):
    X, rng = _data(4)
    y = np.digitize(X[:, 0] + 0.1 * rng.randn(len(X)), [0.33, 0.66])
    bst = lgb.train({"objective": "multiclass", "num_class": 3,
                     "num_leaves": 7, "verbose": -1, "min_data_in_leaf": 5},
                    lgb.Dataset(X, label=y), num_boost_round=5)
    Xt = _data(5, n=40)[0]
    c_raw, c_pred = _compile_and_run(tmp_path, bst, Xt)
    assert c_raw.shape == (40, 3)
    np.testing.assert_allclose(c_raw, bst.predict(Xt, raw_score=True),
                               rtol=0, atol=1e-12)
    np.testing.assert_allclose(c_pred, bst.predict(Xt),
                               rtol=1e-10, atol=1e-12)
    np.testing.assert_allclose(c_pred.sum(axis=1), 1.0, rtol=1e-12)


if __name__ == "__main__":
    sys.exit(pytest.main([__file__, "-v"]))
