import pytest

from lightgbm_tpu.config import Config, alias_transform, param_dict_to_str, str2map
from lightgbm_tpu.utils.log import LightGBMError


def test_defaults():
    c = Config()
    assert c.num_leaves == 31
    assert c.learning_rate == 0.1
    assert c.max_bin == 255
    assert c.objective == "regression"
    assert c.eval_at == [1, 2, 3, 4, 5]


def test_alias_resolution():
    c = Config({"n_estimators": 50, "eta": "0.3", "num_leaf": 7})
    assert c.num_iterations == 50
    assert c.learning_rate == 0.3
    assert c.num_leaves == 7


def test_alias_priority_longest_wins():
    out = alias_transform({"num_tree": "10", "num_boost_round": "20"})
    assert out["num_iterations"] == "20"


def test_canonical_beats_alias():
    out = alias_transform({"num_iterations": "5", "n_estimators": "99"})
    assert out["num_iterations"] == "5"


def test_type_coercion():
    c = Config({"bagging_fraction": "0.5", "header": "true", "eval_at": "1,3,5"})
    assert c.bagging_fraction == 0.5
    assert c.header is True
    assert c.eval_at == [1, 3, 5]


def test_str2map():
    m = str2map("task=train data=a.txt  num_leaves=7 # comment")
    assert m == {"task": "train", "data": "a.txt", "num_leaves": "7"}


def test_param_dict_to_str():
    s = param_dict_to_str({"metric": ["auc", "binary_logloss"], "verbose": -1, "header": True})
    assert "metric=auc,binary_logloss" in s
    assert "header=true" in s


def test_conflict_checks():
    with pytest.raises(LightGBMError):
        Config({"num_leaves": 1})
    with pytest.raises(LightGBMError):
        Config({"bagging_fraction": 0.0})
    with pytest.raises(LightGBMError):
        Config({"boosting": "goss", "top_rate": 0.9, "other_rate": 0.5})


def test_inert_params_warn_once(capsys):
    """Accepted-but-inert knobs must warn, not silently no-op."""
    import lightgbm_tpu.config as config_mod
    config_mod._INERT_WARNED.clear()
    # two_round and histogram_pool_size act now; only the storage
    # knobs remain inert
    Config({"sparse_threshold": 0.5, "is_enable_sparse": False})
    # warnings go to stderr (utils/log routes Warning/Fatal there)
    err = capsys.readouterr().err
    assert "sparse_threshold" in err and "is_enable_sparse" in err
    # once per process only
    Config({"sparse_threshold": 0.5})
    assert "sparse_threshold" not in capsys.readouterr().err
    # default values stay silent
    config_mod._INERT_WARNED.clear()
    Config({"sparse_threshold": 0.8})
    assert "sparse_threshold" not in capsys.readouterr().err


def test_initscore_file_loading(tmp_path):
    import numpy as np
    from lightgbm_tpu.io.loader import load_init_score_file
    d = tmp_path / "data.csv"
    d.write_text("1,2\n0,3\n")
    # side-file fallback <data>.init (metadata.cpp:391-397)
    (tmp_path / "data.csv.init").write_text("0.5\n-0.25\n")
    s = load_init_score_file(str(d))
    np.testing.assert_allclose(s, [0.5, -0.25])
    # explicit file, multiclass columns -> class-major flatten
    f = tmp_path / "scores.tsv"
    f.write_text("1\t10\n2\t20\n3\t30\n")
    s = load_init_score_file(str(d), str(f))
    np.testing.assert_allclose(s, [1, 2, 3, 10, 20, 30])
    # absent side file -> None
    d2 = tmp_path / "other.csv"
    d2.write_text("1,2\n")
    assert load_init_score_file(str(d2)) is None


def test_init_score_size_mismatch_fatal():
    import numpy as np
    import pytest
    from lightgbm_tpu.io.metadata import Metadata
    from lightgbm_tpu.utils.log import LightGBMError
    meta = Metadata(5)
    meta.set_label(np.zeros(5))
    with pytest.raises(LightGBMError):
        meta.set_init_score(np.arange(3.0))
    meta.set_init_score(np.arange(10.0))  # k=2 blocks: fine
