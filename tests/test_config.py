import pytest

from lightgbm_tpu.config import Config, alias_transform, param_dict_to_str, str2map
from lightgbm_tpu.utils.log import LightGBMError


def test_defaults():
    c = Config()
    assert c.num_leaves == 31
    assert c.learning_rate == 0.1
    assert c.max_bin == 255
    assert c.objective == "regression"
    assert c.eval_at == [1, 2, 3, 4, 5]


def test_alias_resolution():
    c = Config({"n_estimators": 50, "eta": "0.3", "num_leaf": 7})
    assert c.num_iterations == 50
    assert c.learning_rate == 0.3
    assert c.num_leaves == 7


def test_alias_priority_longest_wins():
    out = alias_transform({"num_tree": "10", "num_boost_round": "20"})
    assert out["num_iterations"] == "20"


def test_canonical_beats_alias():
    out = alias_transform({"num_iterations": "5", "n_estimators": "99"})
    assert out["num_iterations"] == "5"


def test_type_coercion():
    c = Config({"bagging_fraction": "0.5", "header": "true", "eval_at": "1,3,5"})
    assert c.bagging_fraction == 0.5
    assert c.header is True
    assert c.eval_at == [1, 3, 5]


def test_str2map():
    m = str2map("task=train data=a.txt  num_leaves=7 # comment")
    assert m == {"task": "train", "data": "a.txt", "num_leaves": "7"}


def test_param_dict_to_str():
    s = param_dict_to_str({"metric": ["auc", "binary_logloss"], "verbose": -1, "header": True})
    assert "metric=auc,binary_logloss" in s
    assert "header=true" in s


def test_conflict_checks():
    with pytest.raises(LightGBMError):
        Config({"num_leaves": 1})
    with pytest.raises(LightGBMError):
        Config({"bagging_fraction": 0.0})
    with pytest.raises(LightGBMError):
        Config({"boosting": "goss", "top_rate": 0.9, "other_rate": 0.5})
