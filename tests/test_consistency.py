"""Cross-stack consistency oracle (reference tests/python_package_test/
test_consistency.py:41-60): each reference example's train.conf is run
through the CLI (app.py) AND through the Python API on the same data;
predictions must agree to 5 decimals.  Also checks file-loaded vs
in-memory Dataset equivalence."""
import os

import numpy as np
import pytest

# interpret-mode Pallas dominates these — excluded from the
# fast tier (pytest -m 'not slow'); run the full suite before
# committing engine changes
pytestmark = pytest.mark.slow

import lightgbm_tpu as lgb
from lightgbm_tpu.app import Application
from lightgbm_tpu.io.parser import load_text_file

EXAMPLES = "/root/reference/examples"


class FileLoader:
    def __init__(self, directory, prefix, tmp_path, config_file="train.conf"):
        self.directory = os.path.join(EXAMPLES, directory)
        self.prefix = prefix
        self.tmp = str(tmp_path)
        self.params = {}
        with open(os.path.join(self.directory, config_file)) as f:
            for line in f.readlines():
                line = line.split("#", 1)[0].strip()
                if line and "=" in line:
                    k, v = [t.strip() for t in line.split("=", 1)]
                    if "early_stopping" in k or k in ("data", "valid_data",
                                                     "task", "output_model"):
                        continue
                    self.params[k] = v
        # keep runtime sane: the oracle is about PARITY, not 100 rounds
        self.params["num_trees"] = "20"
        self.params["verbose"] = "-1"

    def path(self, suffix):
        return os.path.join(self.directory, self.prefix + suffix)

    def load_dataset(self, suffix):
        X, libsvm_y, _ = load_text_file(self.path(suffix))
        if libsvm_y is not None:
            return X, libsvm_y
        return X[:, 1:], X[:, 0]

    def train_cli(self):
        model_path = os.path.join(self.tmp, "cli_model.txt")
        argv = ["data=" + self.path(".train"),
                "output_model=" + model_path,
                "task=train", "config=/dev/null"]
        argv += ["%s=%s" % (k, v) for k, v in self.params.items()]
        Application(argv).run()
        return lgb.Booster(model_file=model_path)

    def _side_fields(self):
        """weight / group / init_score side files, like the reference's
        explicit load_field calls (test_consistency.py:73,95,108)."""
        kwargs = {}
        qf = self.path(".train.query")
        if os.path.exists(qf):
            kwargs["group"] = np.loadtxt(qf, dtype=int)
        wf = self.path(".train.weight")
        if os.path.exists(wf):
            kwargs["weight"] = np.loadtxt(wf)
        inf = self.path(".train.init")
        if os.path.exists(inf):
            kwargs["init_score"] = np.loadtxt(inf)
        return kwargs

    def train_python(self):
        X, y = self.load_dataset(".train")
        ds = lgb.Dataset(X, label=y, params=dict(self.params),
                         **self._side_fields())
        return lgb.train(dict(self.params), ds)

    def check(self, decimal=5):
        cli = self.train_cli()
        py = self.train_python()
        X_test, _ = self.load_dataset(".test")
        p_cli = cli.predict(X_test)
        p_py = py.predict(X_test)
        np.testing.assert_array_almost_equal(p_cli, p_py, decimal=decimal)
        return cli, py, X_test

    def file_load_check(self):
        """File-loaded vs in-memory Dataset equivalence
        (test_consistency.py:48-60)."""
        X, y = self.load_dataset(".train")
        mem = lgb.Dataset(X, label=y, params=dict(self.params),
                          **self._side_fields()).construct()
        from lightgbm_tpu.config import Config
        from lightgbm_tpu.io import loader as loader_mod
        cfg = Config(dict(self.params))
        d = loader_mod.load_data_file(cfg, self.path(".train"),
                                      initscore_filename=cfg.initscore_filename)
        filed = lgb.Dataset(d.X, label=d.label, weight=d.weight,
                            group=d.group, init_score=d.init_score,
                            params=dict(self.params)).construct()
        assert mem.num_data() == filed.num_data()
        assert mem.num_feature() == filed.num_feature()
        np.testing.assert_array_almost_equal(mem.get_label(),
                                             filed.get_label())
        a, b = mem.get_group(), filed.get_group()
        if a is not None or b is not None:
            np.testing.assert_array_equal(a, b)
        np.testing.assert_array_equal(mem._binned.bins, filed._binned.bins)


def test_binary_consistency(tmp_path):
    fd = FileLoader("binary_classification", "binary", tmp_path)
    cli, py, X_test = fd.check()
    # CLI predict task must reproduce the in-process prediction
    out = os.path.join(str(tmp_path), "preds.txt")
    model = os.path.join(str(tmp_path), "cli_model.txt")
    cli.save_model(model)
    Application(["task=predict", "data=" + fd.path(".test"),
                 "input_model=" + model, "output_result=" + out,
                 "config=/dev/null", "verbose=-1"]).run()
    file_pred = np.loadtxt(out)
    np.testing.assert_array_almost_equal(file_pred, cli.predict(X_test),
                                         decimal=5)
    fd.file_load_check()


def test_regression_consistency(tmp_path):
    # regression example ships .init side files: both stacks must load them
    fd = FileLoader("regression", "regression", tmp_path)
    fd.check()
    fd.file_load_check()


def test_multiclass_consistency(tmp_path):
    fd = FileLoader("multiclass_classification", "multiclass", tmp_path)
    fd.check()
    fd.file_load_check()


def test_lambdarank_consistency(tmp_path):
    fd = FileLoader("lambdarank", "rank", tmp_path)
    fd.check()
    fd.file_load_check()
